#!/bin/bash
# SLURM batch script — parity with the reference's dragg/batch.sh:10-14,
# minus the redis-server boot (state is in-process).  Submit with:
#   sbatch deploy/batch.sh

#SBATCH --time=04:00:00
#SBATCH --nodes=1
#SBATCH --ntasks=1
#SBATCH --job-name="dragg-tpu"

module purge
# Activate whatever environment provides jax (TPU or CPU):
#   source activate dragg-tpu

cd "${SLURM_SUBMIT_DIR:-$(dirname "$0")/..}"
python -u -m dragg_tpu run --outputs-dir "${OUTPUT_DIR:-outputs}"
