#!/usr/bin/env bash
# TPU pod-slice launch for dragg_tpu — the TPU-native replacement for the
# reference's HPC story (dragg/batch.sh:10-14 boots redis-server + main.py on
# one SLURM node; here there is no Redis and the "cluster" is a TPU slice).
#
# Creates a TPU VM slice, installs the framework on every host, and runs the
# simulation as one multi-host JAX program: jax.distributed.initialize()
# enumerates all hosts' chips into a single mesh, and the home axis shards
# over ICI/DCN automatically (dragg_tpu/parallel/mesh.py).
#
# Usage:
#   ./deploy/launch_tpu_pod.sh <tpu-name> [accelerator-type] [zone] [-- run args]
# Example:
#   ./deploy/launch_tpu_pod.sh dragg-v4-8 v4-8 us-central2-b -- \
#       --config config.toml --outputs-dir outputs
set -euo pipefail

TPU_NAME="${1:?usage: launch_tpu_pod.sh <tpu-name> [accel-type] [zone] [-- run args]}"
shift
# Optional positionals up to the "--" separator; everything after it is
# passed to `python -m dragg_tpu run` verbatim.
POS=()
while [ $# -gt 0 ] && [ "$1" != "--" ]; do POS+=("$1"); shift; done
[ "${1:-}" = "--" ] && shift
if [ "${#POS[@]}" -gt 2 ]; then
    echo "error: unexpected positional args '${POS[*]:2}' — put run args after '--'" >&2
    exit 2
fi
ACCEL="${POS[0]:-v4-8}"
ZONE="${POS[1]:-us-central2-b}"
RUN_ARGS=("$@")

REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
VERSION="tpu-ubuntu2204-base"

echo ">> creating TPU slice ${TPU_NAME} (${ACCEL}) in ${ZONE}"
gcloud compute tpus tpu-vm create "${TPU_NAME}" \
    --zone="${ZONE}" --accelerator-type="${ACCEL}" --version="${VERSION}"

echo ">> installing dragg_tpu on all hosts"
gcloud compute tpus tpu-vm scp --recurse --worker=all --zone="${ZONE}" \
    "${REPO_DIR}" "${TPU_NAME}:~/dragg_tpu_repo"
gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --worker=all --zone="${ZONE}" \
    --command='pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
               && pip install -e ~/dragg_tpu_repo --no-deps && pip install flax pandas matplotlib'

echo ">> launching the run on every host (one multi-host JAX program)"
# DRAGG_DISTRIBUTED=1 makes the run entry call jax.distributed.initialize()
# IN-PROCESS before building the mesh (dragg_tpu/__main__.py), so every
# worker's command joins a single JAX program spanning all hosts' chips.
gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --worker=all --zone="${ZONE}" \
    --command="cd ~/dragg_tpu_repo && DRAGG_DISTRIBUTED=1 python -m dragg_tpu run ${RUN_ARGS[*]:-}"

echo ">> done.  Delete the slice with:"
echo "   gcloud compute tpus tpu-vm delete ${TPU_NAME} --zone=${ZONE}"
