#!/usr/bin/env bash
# TPU pod-slice launch for dragg_tpu — the TPU-native replacement for the
# reference's HPC story (dragg/batch.sh:10-14 boots redis-server + main.py on
# one SLURM node; here there is no Redis and the "cluster" is a TPU slice).
#
# Creates a TPU VM slice, installs the framework on every host, and runs the
# simulation as one multi-host JAX program: jax.distributed.initialize()
# enumerates all hosts' chips into a single mesh, and the home axis shards
# over ICI/DCN automatically (dragg_tpu/parallel/mesh.py).
#
# Usage:
#   ./deploy/launch_tpu_pod.sh <tpu-name> [accelerator-type] [zone] [-- run args]
# Example:
#   ./deploy/launch_tpu_pod.sh dragg-v4-8 v4-8 us-central2-b -- \
#       --config config.toml --outputs-dir outputs
set -euo pipefail

TPU_NAME="${1:?usage: launch_tpu_pod.sh <tpu-name> [accel-type] [zone] [-- run args]}"
ACCEL="${2:-v4-8}"
ZONE="${3:-us-central2-b}"
shift $(( $# >= 3 ? 3 : $# ))
[ "${1:-}" = "--" ] && shift
RUN_ARGS=("$@")

REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
VERSION="tpu-ubuntu2204-base"

echo ">> creating TPU slice ${TPU_NAME} (${ACCEL}) in ${ZONE}"
gcloud compute tpus tpu-vm create "${TPU_NAME}" \
    --zone="${ZONE}" --accelerator-type="${ACCEL}" --version="${VERSION}"

echo ">> installing dragg_tpu on all hosts"
gcloud compute tpus tpu-vm scp --recurse --worker=all --zone="${ZONE}" \
    "${REPO_DIR}" "${TPU_NAME}:~/dragg_tpu_repo"
gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --worker=all --zone="${ZONE}" \
    --command='pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
               && pip install -e ~/dragg_tpu_repo --no-deps && pip install flax pandas matplotlib'

echo ">> launching the run on every host (one multi-host JAX program)"
# jax.distributed.initialize() is a no-op on a single host and wires DCN on
# pods; the same command runs on every worker.
gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --worker=all --zone="${ZONE}" \
    --command="cd ~/dragg_tpu_repo && python -c 'import jax; jax.distributed.initialize()' \
               && python -m dragg_tpu run ${RUN_ARGS[*]:-}"

echo ">> done.  Delete the slice with:"
echo "   gcloud compute tpus tpu-vm delete ${TPU_NAME} --zone=${ZONE}"
