"""Post-processing / analysis — capability parity with the reference's
``Reformat`` (dragg/reformat.py:20-509).

Same responsibilities, rebuilt cleanly:

* **Run discovery** by parameter permutation over the reference's output
  layout ``outputs/<start>_<end>/<params>/version-<V>/<case>/results.json``
  (dragg/reformat.py:101-171) — our Aggregator writes the identical layout,
  so either framework's outputs are discoverable;
* **Daily statistics** (daily max/min/range/avg/std, composite typical day,
  dragg/reformat.py:429-473) as pure numpy functions plus a dependency-free
  text table (the reference used PrettyTable);
* **Figures** — aggregate-load comparison, typical-day profile, per-home
  traces with thermal bounds, reward-price histograms
  (dragg/reformat.py:257-505) — via matplotlib (always available in this
  image); ``fig.savefig`` replaces plotly's ``write_image``.
"""

from __future__ import annotations

import itertools as it
import json
import os
from datetime import datetime, timedelta

import numpy as np

from dragg_tpu.config import configured_solver, load_config
from dragg_tpu.logger import Logger


# --------------------------------------------------------------------------
# Pure statistics (dragg/reformat.py:429-473 inner computations)
# --------------------------------------------------------------------------

def _legend(ax, size):
    if ax.get_legend_handles_labels()[0]:
        ax.legend(fontsize=size)


def daily_stats(loads: np.ndarray, steps_per_day: int) -> dict:
    """Daily aggregate-load statistics over whole days.

    Returns {} when fewer than one whole day of data exists (the reference
    warns "Not enough data collected", dragg/reformat.py:470-471).
    """
    loads = np.asarray(loads, dtype=float)
    n_days = len(loads) // steps_per_day
    if n_days < 1:
        return {}
    days = loads[: n_days * steps_per_day].reshape(n_days, steps_per_day)
    daily_max = days.max(axis=1)
    daily_min = days.min(axis=1)
    return {
        "daily_max": daily_max,
        "daily_min": daily_min,
        "daily_range": daily_max - daily_min,
        "daily_avg": days.mean(axis=1),
        "daily_std": days.std(axis=1),
        "composite_day": days.mean(axis=0),
        "avg_daily_max": float(daily_max.mean()),
        "std_daily_max": float(daily_max.std()),
        "overall_max": float(daily_max.max()),
        "avg_daily_range": float((daily_max - daily_min).mean()),
    }


def stats_table(rows: list[tuple[str, dict]]) -> str:
    """Dependency-free fixed-width table of per-run daily stats — the
    PrettyTable at dragg/reformat.py:430,469-472."""
    headers = ["run name", "avg daily max", "std daily max", "overall max", "avg daily range"]
    body = []
    for name, st in rows:
        if not st:
            body.append([name, "-", "-", "-", "-"])
        else:
            body.append([
                name,
                f"{st['avg_daily_max']:.3f}", f"{st['std_daily_max']:.3f}",
                f"{st['overall_max']:.3f}", f"{st['avg_daily_range']:.3f}",
            ])
    widths = [max(len(str(r[i])) for r in [headers] + body) for i in range(len(headers))]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    fmt = lambda r: "| " + " | ".join(str(v).ljust(w) for v, w in zip(r, widths)) + " |"
    return "\n".join([sep, fmt(headers), sep] + [fmt(r) for r in body] + [sep])


# --------------------------------------------------------------------------
# Run discovery
# --------------------------------------------------------------------------

class Reformat:
    """Discover finished runs and build comparison figures
    (dragg/reformat.py:20-47).

    Parameters default to the reference's env-var resolution
    (``DATA_DIR``/``OUTPUT_DIR``/``CONFIG_FILE``, dragg/reformat.py:24-29);
    a config dict or path can be passed directly.
    """

    def __init__(self, config=None, outputs_dir: str | None = None):
        self.log = Logger("reformat")
        self.outputs_dir = os.path.expanduser(
            outputs_dir if outputs_dir is not None else os.environ.get("OUTPUT_DIR", "outputs")
        )
        if not os.path.isdir(self.outputs_dir):
            raise FileNotFoundError(f"No outputs directory found: {self.outputs_dir}")
        if isinstance(config, dict):
            self.config = config
        else:
            self.config = load_config(config)

        self.date_ranges = self._date_ranges()
        self.mpc_params = self._mpc_params()
        self.versions = {self.config["simulation"].get("named_version", "test")}
        self.date_folders = self.set_date_folders()
        self.mpc_folders = self.set_mpc_folders()
        self.files = self.set_files()
        self.sample_home: str | None = None
        self._results_cache: dict = {}
        self.save_path = os.path.join(
            self.outputs_dir, "images", datetime.now().strftime("%m%dT%H%M%S")  # dragg: disable=DT014, presentation-only image dir stamp
        )

    # -------------------------------------------------- parameter spaces
    def _date_ranges(self) -> dict:
        """Single-config permutation seed (dragg/reformat.py:80-84); callers
        can add more values to the sets before re-running discovery."""
        sim = self.config["simulation"]
        return {
            "start_datetime": {datetime.strptime(sim["start_datetime"], "%Y-%m-%d %H")},
            "end_datetime": {datetime.strptime(sim["end_datetime"], "%Y-%m-%d %H")},
        }

    def _mpc_params(self) -> dict:
        """(dragg/reformat.py:86-99)."""
        cfg = self.config
        return {
            "n_houses": {cfg["community"]["total_number_homes"]},
            "mpc_prediction_horizons": {cfg["home"]["hems"]["prediction_horizon"]},
            "mpc_hourly_steps": {cfg["home"]["hems"]["sub_subhourly_steps"]},
            "check_type": {cfg["simulation"]["check_type"]},
            "agg_interval": {cfg["agg"]["subhourly_steps"]},
            "solver": {configured_solver(cfg)},
        }

    def _load(self, path: str) -> dict:
        """Memoized results.json loader — each plot method iterates the same
        files; parse each (potentially huge) JSON once per Reformat."""
        if path not in self._results_cache:
            with open(path) as f:
                self._results_cache[path] = json.load(f)
        return self._results_cache[path]

    @staticmethod
    def _permute(space: dict) -> list[dict]:
        keys, values = zip(*space.items())
        return [dict(zip(keys, v)) for v in it.product(*values)]

    # ---------------------------------------------------------- discovery
    def set_date_folders(self) -> list[dict]:
        """(dragg/reformat.py:101-123)."""
        found = []
        perms = sorted(self._permute(self.date_ranges),
                       key=lambda i: i["end_datetime"], reverse=True)
        for p in perms:
            folder = os.path.join(
                self.outputs_dir,
                f"{p['start_datetime'].strftime('%Y-%m-%dT%H')}_"
                f"{p['end_datetime'].strftime('%Y-%m-%dT%H')}",
            )
            if os.path.isdir(folder):
                hours = int((p["end_datetime"] - p["start_datetime"]).total_seconds() / 3600)
                found.append({"folder": folder, "hours": hours, "start_dt": p["start_datetime"]})
        if not found:
            self.log.logger.error("No files found for the date ranges specified.")
        return found

    def set_mpc_folders(self) -> list[dict]:
        """(dragg/reformat.py:125-142)."""
        from dragg_tpu.utils import run_dir_name

        found = []
        for j in self.date_folders:
            for p in self._permute(self.mpc_params):
                folder = os.path.join(
                    j["folder"],
                    run_dir_name(
                        p["check_type"], p["n_houses"],
                        p["mpc_prediction_horizons"], p["agg_interval"],
                        p["mpc_hourly_steps"], p["solver"],
                    ),
                )
                if os.path.isdir(folder):
                    timesteps = j["hours"] * p["agg_interval"]
                    minutes = 60 // p["agg_interval"]
                    x_lims = [j["start_dt"] + timedelta(minutes=minutes * x) for x in range(timesteps)]
                    entry = {"path": folder, "agg_dt": p["agg_interval"], "ts": timesteps, "x_lims": x_lims}
                    if entry["path"] not in [e["path"] for e in found]:
                        found.append(entry)
        return found

    def set_files(self) -> list[dict]:
        """Collect every case's results.json under each version dir
        (dragg/reformat.py:144-171)."""
        files = []
        for j in self.mpc_folders:
            for version in self.versions:
                vdir = os.path.join(j["path"], f"version-{version}")
                if not os.path.isdir(vdir):
                    continue
                for case_dir in sorted(os.listdir(vdir)):
                    path = os.path.join(vdir, case_dir, "results.json")
                    if os.path.isfile(path):
                        entry = {
                            "results": path,
                            "name": f"{case_dir}, v = {version}",
                            "case": case_dir,
                            "parent": j,
                        }
                        agent = os.path.join(vdir, case_dir, "utility_agent-results.json")
                        if os.path.isfile(agent):
                            entry["q_results"] = agent
                        files.append(entry)
                        self.log.logger.info(f"Adding results file at {path}")
        return files

    def get_type_list(self, home_type: str) -> set:
        """Home names of a given type present in EVERY discovered run
        (dragg/reformat.py:173-194)."""
        type_list: set | None = None
        for file in self.files:
            data = self._load(file["results"])
            # Skip Summary-only runs (e.g. the simplified-response case has
            # no per-home blocks) — they would empty the intersection.
            if not any(isinstance(h, dict) and "type" in h for n, h in data.items()
                       if n != "Summary"):
                continue
            names = {
                n for n, h in data.items()
                if isinstance(h, dict) and h.get("type") == home_type
            }
            type_list = names if type_list is None else type_list & names
        return type_list or set()

    # ------------------------------------------------------------- figures
    def _new_fig(self):
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(12, 7))
        return fig, ax

    def plot_baseline(self, ax=None):
        """Aggregate + cumulative community load per run
        (dragg/reformat.py:311-320)."""
        fig = None
        if ax is None:
            fig, ax = self._new_fig()
        for file in self.files:
            data = self._load(file["results"])
            loads = np.asarray(data["Summary"]["p_grid_aggregate"], dtype=float)
            x = file["parent"]["x_lims"][: len(loads)]
            ax.step(x, loads[: len(x)], where="post", label=f"Agg Load - {file['name']}")
        ax.set_xlabel("Time")
        ax.set_ylabel("Agg. Demand (kW)")
        _legend(ax, 8)
        if fig is not None:
            fig.suptitle("Aggregate Load Comparison")
        return fig

    def plot_typ_day(self, ax=None):
        """Composite (average) daily load profile per run
        (dragg/reformat.py:322-376)."""
        fig = None
        if ax is None:
            fig, ax = self._new_fig()
        for file in self.files:
            data = self._load(file["results"])
            spd = 24 * file["parent"]["agg_dt"]
            st = daily_stats(data["Summary"]["p_grid_aggregate"], spd)
            if not st:
                self.log.logger.warning(
                    "Not enough data collected to have daily stats, try running the aggregator for longer."
                )
                continue
            ax.plot(np.arange(spd) / file["parent"]["agg_dt"], st["composite_day"],
                    alpha=0.6, label=file["name"])
        ax.set_title("Avg Daily Load Profile")
        ax.set_xlabel("Time of Day")
        ax.set_ylabel("Agg. Demand (kW)")
        _legend(ax, 8)
        return fig

    def _file_daily_series(self, file):
        """Per-run (x, loads, daily stats, setpoint) shared by the parametric
        and max/12hr-avg figures.  Daily stats cover whole days only, so the
        returned ``xd`` is the x prefix they align to."""
        data = self._load(file["results"])
        spd = 24 * file["parent"]["agg_dt"]
        loads = np.asarray(data["Summary"]["p_grid_aggregate"], dtype=float)
        st = daily_stats(loads, spd)
        x = file["parent"]["x_lims"][: len(loads)]
        sp = np.asarray(data["Summary"].get("p_grid_setpoint", []), dtype=float)
        xd = x[: len(st["daily_max"]) * spd] if st else []
        per_step = lambda a: np.repeat(a, spd)[: len(xd)]
        return x, st, sp, xd, per_step

    def plot_parametric(self, ax=None):
        """Setpoint + daily max/min/range/avg/std traces per run, and the
        daily stats table printed to the log (dragg/reformat.py:429-473)."""
        fig = None
        if ax is None:
            fig, ax = self._new_fig()
        table_rows = []
        for file in self.files:
            x, st, sp, xd, per_step = self._file_daily_series(file)
            table_rows.append((file["name"], st))
            if not st:
                continue
            if sp.size:
                ax.plot(x[: sp.size], sp[: len(x)], alpha=0.5,
                        label=f"{file['name']} - setpoint")
            ax.step(xd, per_step(st["daily_max"]), where="post", alpha=0.5,
                    linestyle=":", label=f"{file['name']} - daily max")
            ax.step(xd, per_step(st["daily_min"]), where="post", alpha=0.5,
                    linestyle="--", label=f"{file['name']} - daily min")
        self.table = stats_table(table_rows)
        print(self.table)
        ax.set_ylabel("Agg. Demand (kW)")
        _legend(ax, 7)
        return fig

    def rl2baseline(self, ax=None):
        """Baseline-vs-RL comparison (dragg/reformat.py:475-486)."""
        fig = None
        if ax is None:
            fig, ax = self._new_fig()
        if not self.files:
            self.log.logger.warning("No aggregator runs found for analysis.")
            return fig
        self.plot_baseline(ax)
        self.plot_parametric(ax)
        ax.set_title("RL Baseline Comparison")
        return fig

    def plot_environmental_values(self, ax, file, name: str | None = None):
        """OAT/GHI traces plus TOU price on a secondary axis, and the comfort
        bands for ``name`` (dragg/reformat.py:206-211).

        Returns the secondary (price) axis so callers can stack more price
        traces on it.
        """
        data = self._load(file["results"])
        summary = data["Summary"]
        x = file["parent"]["x_lims"]
        oat = np.asarray(summary.get("OAT", []), dtype=float)
        ghi = np.asarray(summary.get("GHI", []), dtype=float)
        tou = np.asarray(summary.get("TOU", []), dtype=float)
        if oat.size:
            n = min(len(x), oat.size)
            ax.plot(x[:n], oat[:n], color="gray", alpha=0.6, label="OAT (C)")
        if ghi.size:
            n = min(len(x), ghi.size)
            # GHI is hundreds of W/m^2; scale onto the temperature axis the
            # way the reference relies on legend-toggling instead.
            ax.plot(x[:n], ghi[:n] / 100.0, color="goldenrod", alpha=0.5,
                    label="GHI (x100 W/m2)")
        pax = ax.twinx()
        pax.set_ylabel("Price ($/kWh)")
        if tou.size:
            n = min(len(x), tou.size)
            pax.step(x[:n], tou[:n], where="post", color="green", alpha=0.6,
                     label="TOU Price ($/kWh)")
        if name is not None:
            self._thermal_bounds(ax, x, name)
        return pax

    def plot_single_home(self, name: str | None = None, ax=None,
                         plot_price: bool = True):
        """Per-home temperature traces with thermal bounds, environmental
        overlay, and the price signal; PV/battery series when the home has
        them (dragg/reformat.py:257-296; price + env overlay
        dragg/reformat.py:206-211,229-244)."""
        fig = None
        if ax is None:
            fig, ax = self._new_fig()
        if name is None:
            name = self.sample_home
        if name is None:
            candidates = sorted(self.get_type_list("base"))
            if not candidates:
                self.log.logger.error("No homes found to plot.")
                return fig
            name = candidates[0]
            self.log.logger.info(f'Proceeding with home: "{name}"')
        self.sample_home = name

        pax = None
        for file in self.files:
            comm = self._load(file["results"])
            if name not in comm:
                self.log.logger.error(f"No home with name: {name}")
                continue
            data = comm[name]
            x = file["parent"]["x_lims"]
            nts = min(len(x), len(data["temp_in_opt"]))
            ax.plot(x[:nts], data["temp_in_opt"][:nts], label=f"Tin - {file['name']}")
            ax.plot(x[:nts], data["temp_wh_opt"][:nts], label=f"Twh - {file['name']}")
            if pax is None:
                pax = self.plot_environmental_values(ax, file, name)
            if plot_price:
                rp = np.asarray(comm["Summary"].get("RP", []), dtype=float)
                if rp.size:
                    n = min(len(x), rp.size)
                    pax.step(x[:n], rp[:n], where="post", alpha=0.5,
                             linestyle="--", label=f"RP - {file['name']}")
            if "pv" in data["type"]:
                ax.step(x[:nts], data["p_pv_opt"][:nts], where="post", alpha=0.5,
                        label=f"Ppv (kW) - {file['name']}")
            if "batt" in data["type"]:
                nb = min(len(x), len(data["e_batt_opt"]))
                ax.step(x[:nb], data["e_batt_opt"][:nb], where="post", alpha=0.5,
                        label=f"SOC (kWh) - {file['name']}")
            ax.set_title(f"{name} - {data['type']} type")
        ax.set_xlabel("Time of Day (hour)")
        ax.set_ylabel("Temperature (deg C)")
        _legend(ax, 7)
        if pax is not None:
            _legend(pax, 7)
        return fig

    def plot_all_homes(self, names=None, save: bool = False):
        """One single-home figure per home — the reference iterates a home
        list and rebuilds the single-home figure for each
        (dragg/reformat.py:298-309).  Defaults to every home present in all
        runs; returns the list of (home-name, figure) pairs.
        """
        if names is None:
            names = sorted(set().union(
                *(self.get_type_list(t) for t in
                  ("base", "pv_only", "battery_only", "pv_battery"))
            ))
        figs = []
        for home in names:
            self.sample_home = home
            fig = self.plot_single_home(home)
            figs.append((home, fig))
        if save:
            import matplotlib.pyplot as plt

            self.save_images(figs)
            # One figure per home can be the whole community — release them
            # from pyplot's registry once they are on disk.
            for _, fig in figs:
                if fig is not None:
                    plt.close(fig)
        return figs

    def plot_max_and_12hravg(self, ax=None):
        """Daily-max load plus the utility's trailing-average setpoint ("12 Hr
        Avg") per run (dragg/reformat.py:378-427)."""
        fig = None
        if ax is None:
            fig, ax = self._new_fig()
        for file in self.files:
            x, st, sp, xd, per_step = self._file_daily_series(file)
            if sp.size:
                ax.plot(x[: sp.size], sp[: len(x)], alpha=0.5,
                        label=f"{file['name']} - 12 Hr Avg")
            if not st:
                continue
            ax.step(xd, per_step(st["daily_max"]), where="post",
                    label=f"{file['name']} - Daily Max")
        ax.set_title("12 Hour Avg and Daily Max")
        ax.set_ylabel("Agg. Demand (kW)")
        _legend(ax, 8)
        return fig

    def _thermal_bounds(self, ax, x, name) -> None:
        """Comfort-band shading from the cached population file
        (dragg/reformat.py:213-227)."""
        path = os.path.join(
            self.outputs_dir,
            f"all_homes-{self.config['community']['total_number_homes']}-config.json",
        )
        if not os.path.isfile(path):
            return
        with open(path) as f:
            homes = json.load(f)
        home = next((h for h in homes if h["name"] == name), None)
        if home is None:
            return
        ax.fill_between(x, home["hvac"]["temp_in_min"], home["hvac"]["temp_in_max"],
                        color="lightsteelblue", alpha=0.3, label="Tin bounds")
        ax.fill_between(x, home["wh"]["temp_wh_min"], home["wh"]["temp_wh_max"],
                        color="pink", alpha=0.3, label="Twh bounds")

    def all_rps(self, ax=None):
        """Reward-price histograms per run, with the μ−RP residual histogram
        when agent telemetry exists (dragg/reformat.py:488-505)."""
        fig = None
        if ax is None:
            fig, ax = self._new_fig()
        for file in self.files:
            data = self._load(file["results"])
            rps = np.asarray(data["Summary"].get("RP", []), dtype=float)
            if rps.size:
                ax.hist(rps, bins=30, alpha=0.5, label=file["name"])
            if "q_results" in file:
                with open(file["q_results"]) as f:
                    agent = json.load(f)
                mu = np.asarray(agent.get("mu", []), dtype=float)
                if mu.size == rps.size and rps.size:
                    ax.hist(mu - rps, bins=30, alpha=0.3,
                            label=f"mu - RP - {file['name']}")
        ax.set_xlabel("Reward price ($/kWh)")
        _legend(ax, 8)
        return fig

    # ----------------------------------------------------------------- main
    def main(self, save: bool = True) -> list:
        """Default figure set (dragg/reformat.py:41-47): RL-vs-baseline and a
        sample home; saves PNGs under outputs/images/<timestamp>/."""
        figs = [("rl2baseline", self.rl2baseline()),
                ("single_home", self.plot_single_home()),
                ("typical_day", self.plot_typ_day()),
                ("max_and_12hravg", self.plot_max_and_12hravg()),
                ("all_rps", self.all_rps())]
        self.images = [f for _, f in figs if f is not None]
        if save:
            self.save_images(figs)
        return self.images

    def save_images(self, figs=None) -> None:
        """(dragg/reformat.py:69-78)."""
        os.makedirs(self.save_path, exist_ok=True)
        if figs is None:
            figs = [(f"figure_{i}", f) for i, f in enumerate(self.images)]
        for title, fig in figs:
            if fig is None:
                continue
            path = os.path.join(self.save_path, f"{title}.png")
            self.log.logger.info(f"Saving image to {path}.")
            fig.savefig(path, dpi=100, bbox_inches="tight")
