"""Environment diagnosis: ``python -m dragg_tpu doctor``.

Answers "why isn't this working" in one screen: backend reachability
(checked in a SUBPROCESS with a hard timeout, so a wedged TPU tunnel can
never hang the diagnosis — the failure mode that motivated this tool),
device inventory, Pallas kernel availability, the native C++ runtime,
data-file resolution, and output-directory writability.

Exit code 0 when every check passes or degrades gracefully (CPU fallback
counts as degraded-ok); 1 when something is broken outright.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

OK, WARN, FAIL = "ok", "warn", "FAIL"


def _check_backend(timeout_s: float = 60.0) -> dict:
    """Probe jax backend init via the SHARED subprocess probe
    (dragg_tpu/utils/probe.py) so doctor and bench.py cannot disagree
    about tunnel liveness."""
    from dragg_tpu.utils.probe import probe_backend

    r = probe_backend(timeout_s)
    if r.pop("ok"):
        r.pop("elapsed_s", None)
        return {"status": OK, **r}
    return {"status": FAIL, "error": r["error"]}


def _check_cpu_fallback(timeout_s: float) -> dict:
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp\n"
        "assert float(jnp.sum(jnp.ones(8))) == 8.0\n"
        "print('cpu-ok')\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        ok = proc.returncode == 0 and "cpu-ok" in proc.stdout
        return {"status": OK if ok else FAIL,
                **({} if ok else {"error": (proc.stderr or "")[-300:]})}
    except subprocess.TimeoutExpired:
        return {"status": FAIL, "error": "CPU backend init hung"}


def _check_native() -> dict:
    try:
        from dragg_tpu.native import StateBus

        bus = StateBus()
        bus.hset("doctor", "k", "v")
        ok = bus.hget("doctor", "k") == "v"
        return {"status": OK if ok else FAIL,
                "native_extension": bool(bus.native),
                **({} if bus.native else
                   {"note": "pure-Python fallback active (g++ build unavailable)"})}
    except Exception as e:
        return {"status": FAIL, "error": repr(e)}


def _check_data(cfg: dict | None) -> dict:
    data_dir = os.environ.get("DATA_DIR")
    if not data_dir:
        from dragg_tpu.data import bundled_data_dir

        bundled = bundled_data_dir()
        if bundled is not None:
            # Round 5: no DATA_DIR resolves to the repo's bundled assets
            # (reference-default file-ingestion path), not synthetic.
            data_dir = bundled
        else:
            return {"status": OK,
                    "note": "no DATA_DIR and no bundled data/ — synthetic "
                            "weather/draws/prices"}
    # The exact file names the runtime resolves (dragg_tpu/data.py), env
    # overrides included.
    wanted = [os.environ.get("SOLAR_TEMPERATURE_DATA_FILE", "nsrdb.csv")]
    if cfg is not None:
        wanted.append(cfg["home"]["wh"].get("waterdraw_file",
                                            "waterdraw_profiles.csv"))
        if cfg["agg"].get("spp_enabled", False):
            wanted.append(os.environ.get("SPP_DATA_FILE", "spp_data.csv"))
    missing = [f for f in wanted
               if not os.path.isfile(os.path.join(data_dir, f))]
    return {"status": WARN if missing else OK, "data_dir": data_dir,
            **({"missing": missing,
                "note": "missing files substitute SYNTHETIC data (loudly)"}
               if missing else {})}


def _check_telemetry() -> dict:
    """Unified-telemetry plumbing: registry loads, a throwaway bus round-
    trips one event (dragg_tpu/telemetry).  Reports the shared stream
    when ``$DRAGG_TELEMETRY_DIR`` routes this process's events."""
    try:
        from dragg_tpu import telemetry

        r = telemetry.selftest()
        stream = os.environ.get(telemetry.ENV_DIR)
        return {"status": OK if r["ok"] else FAIL,
                "registered": f"{r['events']} events / {r['metrics']} metrics",
                **({"stream": stream} if stream else {})}
    except Exception as e:
        return {"status": FAIL, "error": repr(e)}


def _check_trace_plane(timeout_s: float = 30.0) -> dict:
    """Trace-plane selftest (``--telemetry``, ISSUE 20): a SUBPROCESS
    runs a tiny traced bus session — root + child spans, a skew record,
    a sub-second live-flush cadence — then this process asserts the
    stream assembles to one COMPLETE causal tree (>=1 root, zero
    orphans), that metrics.json existed BEFORE close (the crash-loss
    fix), and that the rollup folds with Prometheus exposition.  A
    subprocess so the probe never perturbs this process's own bus or
    trace context."""
    code = (
        "import json, os, tempfile, time\n"
        "from dragg_tpu import telemetry\n"
        "from dragg_tpu.telemetry import trace\n"
        "d = tempfile.mkdtemp(prefix='dragg_traceck_')\n"
        "trace.enable()\n"
        "telemetry.init_run(d, flush_s=0.05)\n"
        "telemetry.emit('run.start', config_label='doctor', platform='cpu')\n"
        "telemetry.inc('wire.dedup', 1)\n"
        "telemetry.emit('chunk.done', t0=0, t1=2, device_s=0.01,\n"
        "               **trace.child_fields())\n"
        "telemetry.emit('trace.skew', shard=0, offset_s=0.0, rtt_s=0.001)\n"
        "time.sleep(0.1)\n"
        "telemetry.emit('run.end', ok=True)\n"
        "live = os.path.exists(os.path.join(d, telemetry.METRICS_FILE))\n"
        "telemetry.close_run(write_metrics=True)\n"
        "print('TRACECK ' + json.dumps({'dir': d, 'live_flush': live}))\n")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        line = next((l for l in (proc.stdout or "").splitlines()
                     if l.startswith("TRACECK ")), None)
        if proc.returncode != 0 or line is None:
            return {"status": FAIL, "error": (proc.stderr or "")[-300:]}
        child = json.loads(line[len("TRACECK "):])
        from dragg_tpu.telemetry import rollup, traces

        rep = traces.trace_report(child["dir"])
        roll = rollup.fold_rollup(child["dir"])
        prom = rollup.prometheus_text(roll)
        import shutil

        shutil.rmtree(child["dir"], ignore_errors=True)
        problems = traces.completeness_problems(rep)
        if not child["live_flush"]:
            problems.append("no metrics.json before close "
                            "(live flush did not fire)")
        if "dragg_" not in prom:
            problems.append("prometheus exposition empty")
        return {"status": OK if not problems else FAIL,
                "traces": len(rep["traces"]),
                "live_flush": child["live_flush"],
                "rollup_streams": len(roll.get("streams", {})),
                **({"problems": problems} if problems else {})}
    except subprocess.TimeoutExpired:
        return {"status": FAIL,
                "error": f"trace selftest hung >{timeout_s:.0f}s"}
    except Exception as e:
        return {"status": FAIL, "error": repr(e)}


def _check_staged_compile(timeout_s: float) -> dict:
    """Opt-in (``--compile-check``): a tiny engine's chunk compile run
    through the STAGED path (telemetry/compile_obs: lower → compile →
    first-execute, persistent-cache verdict) in a hard-timeouted
    subprocess — proves the stage-attribution machinery works in this
    environment and reports where compile time goes.  A hang here names
    the stuck stage instead of wedging doctor."""
    code = ("import json\n"
            "from dragg_tpu.telemetry.compile_obs import selftest\n"
            "print('STAGED ' + json.dumps(selftest()))\n")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        line = next((l for l in (proc.stdout or "").splitlines()
                     if l.startswith("STAGED ")), None)
        if proc.returncode != 0 or line is None:
            return {"status": FAIL, "error": (proc.stderr or "")[-300:]}
        rep = json.loads(line[len("STAGED "):])
        return {"status": OK if rep.get("ok") else FAIL,
                "stages": rep.get("stages"), "cache": rep.get("cache")}
    except subprocess.TimeoutExpired:
        return {"status": FAIL,
                "error": f"staged compile hung >{timeout_s:.0f}s"}


def _check_serve_journal() -> dict:
    """Serving-substrate plumbing (dragg_tpu/serve): a throwaway journal
    round-trips the accepted→done lifecycle, refuses a double answer,
    and replays a torn tail without losing the durable record — the
    crash-safety contract the daemon's zero-lost-requests guarantee
    stands on.  Pure stdlib; never launches a worker."""
    import tempfile

    try:
        from dragg_tpu.serve.journal import Journal, replay

        with tempfile.TemporaryDirectory(prefix="dragg_serve_") as d:
            path = os.path.join(d, "journal.jsonl")
            j = Journal(path)
            j.accepted("probe", {"id": "probe", "home": 0})
            j.accepted("torn", {"id": "torn", "home": 1})
            ok = j.done("probe", {"p_grid": 1.0})
            ok &= not j.done("probe", {"p_grid": 2.0})  # exactly-once
            j.close()
            with open(path, "ab") as f:
                f.write(b'{"state": "done", "id": "torn", "resp')  # torn
            rep = replay(path)
            ok &= set(rep.pending) == {"torn"}      # torn line dropped,
            ok &= set(rep.terminal) == {"probe"}    # durable kept
            ok &= rep.dropped_lines == 1
        return {"status": OK if ok else FAIL,
                **({} if ok else {"error": "journal selftest mismatch"})}
    except Exception as e:
        return {"status": FAIL, "error": repr(e)}


def _check_shard_journal() -> dict:
    """Shard-coordinator crash-safety selftest (``--shard-check``),
    mirroring the round-11 serve_journal check for the round-18 shard
    substrate (dragg_tpu/shard/journal.py): the epoch → plan → chunk
    lifecycle round-trips, a DUPLICATE epoch token is refused (reusing a
    dead coordinator's token would re-admit the orphan workers the spool
    EPOCH fence exists to stop), and replay survives truncation at EVERY
    byte boundary — the frontier only ever walks backward to a prefix,
    never corrupts.  Pure stdlib; never launches a worker."""
    import tempfile

    try:
        from dragg_tpu.shard.journal import Journal, replay

        with tempfile.TemporaryDirectory(prefix="dragg_shard_") as d:
            path = os.path.join(d, "shard_journal.jsonl")
            j = Journal(path)
            j.epoch("probe-epoch")
            j.plan(4, 2, [(0, 2), (2, 4)], steps=8, chunk_steps=2)
            j.launch(0, 1, "cpu", 0, 2)
            j.chunk(0, 0, 0, 2)
            j.chunk(0, 1, 2, 4)
            j.chunk(1, 0, 0, 2)
            ok = True
            try:
                j.epoch("probe-epoch")  # duplicate must be refused
                ok = False
            except ValueError:
                pass
            j.close()
            # A successor instance must refuse the duplicate too (the
            # claim set survives via replay, not process memory).
            j2 = Journal(path)
            try:
                j2.epoch("probe-epoch")
                ok = False
            except ValueError:
                pass
            j2.epoch("probe-epoch-2")
            j2.close()
            rep_full = replay(path)
            ok &= rep_full.frontier == {0: 2, 1: 1}
            ok &= rep_full.epochs == ["probe-epoch", "probe-epoch-2"]
            # Torn-tail truncation at every byte boundary: replay never
            # raises, the frontier is monotone non-increasing toward the
            # head, and a torn final line drops silently (serve journal
            # property-test precedent).
            with open(path, "rb") as f:
                raw = f.read()
            prev_total = None
            for cut in range(len(raw), -1, -1):
                with open(path, "wb") as f:
                    f.write(raw[:cut])
                rep = replay(path)
                total = sum(rep.frontier.values())
                ok &= rep.dropped_lines <= 1
                if prev_total is not None:
                    ok &= total <= prev_total
                prev_total = total
        return {"status": OK if ok else FAIL,
                "note": f"torn-tail sweep over {len(raw) + 1} boundaries",
                **({} if ok else {"error": "shard journal selftest "
                                           "mismatch"})}
    except Exception as e:
        return {"status": FAIL, "error": repr(e)}


def _check_shard_wire() -> dict:
    """Networked shard transport selftest (``--shard-check``), run
    against a LIVE loopback chunk-ingest server (shard/transport.py):

    * a pushed chunk frame is journal-acked BEFORE the 200 and its
      retained spool file matches the payload byte-for-byte;
    * a TORN frame at EVERY byte boundary is discarded whole (400,
      no state change) — the wire analog of the journal torn-tail sweep;
    * a duplicate ``(epoch, shard, chunk)`` token is refused (acked
      without re-merge) ACROSS a transport restart — the dedup set is
      re-seeded from the journal + spool, not process memory;
    * a fenced (stale-epoch) push is refused with 409 naming the stale
      token.

    Pure stdlib + loopback TCP; never launches a worker."""
    import tempfile

    try:
        from http.client import HTTPConnection

        from dragg_tpu.serve import spool as sp
        from dragg_tpu.shard import journal as sj
        from dragg_tpu.shard import wire
        from dragg_tpu.shard.transport import (ChunkIngestServer,
                                               EpochFenced, WireClient)

        with tempfile.TemporaryDirectory(prefix="dragg_wire_") as d:
            spool_dir = os.path.join(d, "spool")
            jpath = os.path.join(d, "shard_journal.jsonl")
            journal = sj.Journal(jpath)
            journal.epoch("probe-epoch")
            sp.write_epoch(spool_dir, "probe-epoch")
            payload = {"shard": 0, "gen": 1, "seq": 0, "t0": 0, "t1": 2,
                       "series": {"agg_load": [[1.0], [2.0]]}}
            srv = ChunkIngestServer(spool_dir, journal, "probe-epoch")
            srv.start()
            ok = True
            try:
                client = WireClient(srv.endpoint, "probe-epoch", 0,
                                    spool_dir, retry_s=5.0)
                ok &= client.push_chunk(0, payload) == "acked"
                ok &= sj.replay(jpath).acked == {0: [0]}  # ack before 200
                ok &= sp.read_json(
                    sp.chunk_path(spool_dir, 0, 0)) == payload
                ok &= client.push_chunk(0, payload) == "dup"
                # Torn frame at EVERY byte boundary: 400, no state change.
                frame = wire.encode_frame(
                    {"kind": "chunk", "epoch": "probe-epoch", "shard": 0,
                     "seq": 1, "payload": {**payload, "seq": 1}})
                host, port = srv.endpoint.rsplit(":", 1)
                for cut in range(len(frame)):
                    conn = HTTPConnection(host, int(port), timeout=10.0)
                    try:
                        conn.request(
                            "POST", "/chunk", body=frame[:cut],
                            headers={"Content-Type":
                                     "application/octet-stream"})
                        r = conn.getresponse()
                        r.read()
                        ok &= r.status == 400
                    finally:
                        conn.close()
                ok &= sp.read_json(
                    sp.chunk_path(spool_dir, 0, 1)) is None
                ok &= sj.replay(jpath).acked == {0: [0]}
            finally:
                srv.stop()
            # Transport restart: dedup token survives (seeded from the
            # journal + retained spool files, not process memory).
            srv2 = ChunkIngestServer(spool_dir, journal, "probe-epoch")
            srv2.start()
            try:
                client2 = WireClient(srv2.endpoint, "probe-epoch", 0,
                                     spool_dir, retry_s=5.0)
                ok &= client2.push_chunk(0, payload) == "dup"
                ok &= sj.replay(jpath).acked == {0: [0]}  # no re-journal
                # Fenced-epoch push: refused, stale token named.
                stale = WireClient(srv2.endpoint, "dead-epoch", 0,
                                   spool_dir, retry_s=5.0)
                try:
                    stale.push_chunk(2, {**payload, "seq": 2})
                    ok = False
                except EpochFenced as e:
                    ok &= wire.chunk_token("dead-epoch", 0, 2) in str(e)
            finally:
                srv2.stop()
            journal.close()
        return {"status": OK if ok else FAIL,
                "note": f"torn-frame sweep over {len(frame)} boundaries, "
                        f"dedup across restart, fence named",
                **({} if ok else {"error": "shard wire selftest "
                                           "mismatch"})}
    except Exception as e:
        return {"status": FAIL, "error": repr(e)}


def _check_outputs(outputs_dir: str) -> dict:
    try:
        os.makedirs(outputs_dir, exist_ok=True)
        with tempfile.NamedTemporaryFile(dir=outputs_dir, delete=True):
            pass
        return {"status": OK, "outputs_dir": os.path.abspath(outputs_dir)}
    except OSError as e:
        return {"status": FAIL, "error": repr(e)}


def _check_config() -> tuple[dict, dict | None]:
    try:
        from dragg_tpu.config import configured_solver, load_config

        # Report what load_config actually resolves: the default path only
        # loads when the file exists (config.py load_config).
        path = os.path.join(os.path.expanduser(os.environ.get("DATA_DIR", "data")),
                            os.environ.get("CONFIG_FILE", "config.toml"))
        source = f"file:{path}" if os.path.exists(path) else "defaults"
        cfg = load_config(None)
        return {"status": OK, "source": source,
                "homes": cfg["community"]["total_number_homes"],
                "solver": configured_solver(cfg)}, cfg
    except Exception as e:
        return {"status": FAIL, "error": repr(e)}, None


def run_classify(backend_timeout: float = 60.0, stream=None) -> int:
    """``python -m dragg_tpu doctor --classify``: one classified liveness
    verdict as a JSON line — NAMES the failure (resilience taxonomy:
    TUNNEL_DOWN / WEDGED / alive) instead of printing raw probe output,
    so operators and the runbook branch on a word, not a stderr tail.
    Exit 0 = a TPU backend is up; 1 = it is not (kind says why)."""
    from dragg_tpu.resilience.liveness import check_liveness

    stream = stream or sys.stdout
    r = check_liveness(backend_timeout)
    print(json.dumps(r._asdict()), file=stream)
    return 0 if r.alive else 1


def run_doctor(outputs_dir: str = "outputs", backend_timeout: float = 60.0,
               stream=None, compile_check: bool = False,
               shard_check: bool = False,
               telemetry_check: bool = False) -> int:
    stream = stream or sys.stdout
    config_res, cfg = _check_config()
    backend_res = _check_backend(backend_timeout)
    checks = {
        "config": config_res,
        "backend": backend_res,
        # The backend probe succeeding on "cpu" already proves CPU init.
        "cpu_fallback": ({"status": OK, "note": "backend probe ran on cpu"}
                         if backend_res.get("backend") == "cpu"
                         else _check_cpu_fallback(max(backend_timeout, 120.0))),
        "native_runtime": _check_native(),
        "data_files": _check_data(cfg),
        "outputs_writable": _check_outputs(outputs_dir),
        "telemetry": _check_telemetry(),
        "serve_journal": _check_serve_journal(),
    }
    if compile_check:
        checks["staged_compile"] = _check_staged_compile(
            max(backend_timeout, 300.0))
    if shard_check:
        checks["shard_journal"] = _check_shard_journal()
        checks["shard_wire"] = _check_shard_wire()
    if telemetry_check:
        checks["trace_plane"] = _check_trace_plane()
    # Pallas only matters when a TPU backend is up — and its self-test
    # compiles a kernel, so it runs in a SUBPROCESS with the same hard
    # timeout as the backend probe (a tunnel can wedge between probes).
    if checks["backend"].get("backend") == "tpu":
        code = ("from dragg_tpu.ops import pallas_band\n"
                "print('PALLAS', pallas_band.available())\n")
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=max(backend_timeout, 120.0))
            up = "PALLAS True" in proc.stdout
            checks["pallas_kernels"] = {
                "status": OK if up else WARN,
                **({} if up else
                   {"note": "self-test failed — XLA scan fallback active"}),
            }
        except subprocess.TimeoutExpired:
            checks["pallas_kernels"] = {
                "status": WARN, "note": "kernel self-test hung; scan fallback"}

    hard_fail = False
    for name, res in checks.items():
        status = res["status"]
        # An unreachable accelerator with a healthy CPU fallback is
        # degraded-ok: every entry point still works on CPU.
        if status == FAIL and name == "backend" \
                and checks["cpu_fallback"]["status"] == OK:
            status = WARN
            res = {**res, "note": "accelerator unreachable; CPU fallback healthy"}
        hard_fail |= status == FAIL
        detail = {k: v for k, v in res.items() if k != "status"}
        print(f"  {name:18s} [{status:4s}] "
              f"{json.dumps(detail) if detail else ''}", file=stream)
    print(("DOCTOR: FAIL — see [FAIL] lines above" if hard_fail else
           "DOCTOR: environment usable"), file=stream)
    return 1 if hard_fail else 0
