"""Scenario subsystem: EV / heat-pump home types as data-driven specs, and
community event timelines (tariff shocks, DR curtailment, outage
islanding) compiled into the engine step as per-step gathers.

ROADMAP item 4 / docs/architecture.md §15 / docs/scenarios.md.  The home
types themselves live where home types live (homes.HOME_TYPES +
ops/qp.TYPE_SPECS); this package owns the DECLARATIVE layer — pack files,
mix expansion, and the event timeline the engine closes over.
"""

from dragg_tpu.scenarios.packs import (  # noqa: F401 — re-exported API
    MIX_KEYS,
    apply_scenarios,
    load_pack,
    pack_path,
    packs_dir,
)
from dragg_tpu.scenarios.timeline import (  # noqa: F401 — re-exported API
    EVENT_KINDS,
    EventTimeline,
    ScenarioError,
    build_timeline,
    describe_timeline,
    empty_timeline,
    timeline_digest,
    timeline_for,
)
