"""Scenario-pack loader: TOML packs expand into home mixes + event lists.

A pack (``data/packs/<name>.toml`` — authoring guide: docs/scenarios.md)
declares a home-type mix and a community event schedule; the ``[scenarios]``
config table names one (``scenarios.pack``) and/or carries inline
``[[scenarios.events]]`` entries.  :func:`apply_scenarios` is the ONE
entry point that mutates a config from its pack (mix fractions → per-type
``community.homes_*`` counts; pack events merged into
``scenarios.events``), so home synthesis, the engine, bench, and
validate_scale all see the same expansion — ``tests/test_fuzz_configs.py``
fuzzes the whole matrix through it.
"""

from __future__ import annotations

import copy
import os

try:
    import tomllib
except ImportError:  # Python < 3.11: same API from the tomli backport
    import tomli as tomllib

from dragg_tpu.scenarios.timeline import EVENT_KINDS, ScenarioError

# [mix] keys a pack may set, and the community count key each expands to.
MIX_KEYS = {
    "pv_only": "homes_pv",
    "battery_only": "homes_battery",
    "pv_battery": "homes_pv_battery",
    "ev": "homes_ev",
    "heat_pump": "homes_heat_pump",
}
_EXPANDED_FLAG = "_pack_expanded"


def packs_dir(data_dir: str | None = None) -> str | None:
    """Directory pack names resolve under: ``<data_dir>/packs`` when a data
    dir is configured, else the bundled ``data/packs``."""
    if data_dir:
        return os.path.join(data_dir, "packs")
    from dragg_tpu.data import bundled_data_dir

    bundled = bundled_data_dir()
    return os.path.join(bundled, "packs") if bundled else None


def pack_path(name: str, data_dir: str | None = None) -> str:
    """Resolve a pack name to a file path: a literal ``.toml`` path wins,
    else ``<packs_dir>/<name>.toml``."""
    if name.endswith(".toml") and os.path.isfile(name):
        return name
    base = packs_dir(data_dir)
    candidate = os.path.join(base, f"{name}.toml") if base else None
    if candidate and os.path.isfile(candidate):
        return candidate
    raise ScenarioError(
        f"scenario pack {name!r} not found (looked for {candidate!r}; "
        f"packs live under data/packs/ — docs/scenarios.md)")


def load_pack(path: str) -> dict:
    """Load + schema-check one pack file."""
    with open(path, "rb") as f:
        pack = tomllib.load(f)
    mix = pack.get("mix", {})
    unknown = set(mix) - set(MIX_KEYS)
    if unknown:
        raise ScenarioError(
            f"pack {path}: unknown [mix] home types {sorted(unknown)} "
            f"(known: {sorted(MIX_KEYS)})")
    total = 0.0
    for t, frac in mix.items():
        if not 0.0 <= float(frac) <= 1.0:
            raise ScenarioError(
                f"pack {path}: mix.{t} must be a fraction in [0, 1], "
                f"got {frac}")
        total += float(frac)
    if total > 1.0 + 1e-9:
        raise ScenarioError(
            f"pack {path}: mix fractions sum to {total:.3f} > 1")
    for ev in pack.get("events", []):
        if ev.get("kind") not in EVENT_KINDS:
            raise ScenarioError(
                f"pack {path}: event kind {ev.get('kind')!r} not in "
                f"{EVENT_KINDS}")
    return pack


def apply_scenarios(config: dict, data_dir: str | None = None) -> dict:
    """Expand ``[scenarios]`` declaratively into the config: the named
    pack's ``[mix]`` fractions become per-type ``community.homes_*``
    counts (of ``total_number_homes`` — PER community, like every other
    count) and its events merge after the inline ones.  Returns a new
    config; idempotent (a second application is a no-op), and a config
    with no ``[scenarios]`` table comes back unchanged."""
    scn = config.get("scenarios", {}) or {}
    if not scn or scn.get(_EXPANDED_FLAG):
        return config
    name = scn.get("pack", "")
    events = list(scn.get("events", []) or [])
    if not name and not events:
        return config
    cfg = copy.deepcopy(config)
    if name:
        pack = load_pack(pack_path(name, data_dir))
        n = int(cfg["community"]["total_number_homes"])
        mix = pack.get("mix", {})
        for t, count_key in MIX_KEYS.items():
            if t in mix:
                cfg["community"][count_key] = int(float(mix[t]) * n)
        total = sum(int(cfg["community"].get(k, 0))
                    for k in MIX_KEYS.values())
        if total > n:
            raise ScenarioError(
                f"pack {name!r}: expanded mix counts ({total}) exceed "
                f"total_number_homes ({n})")
        events += list(pack.get("events", []))
    cfg.setdefault("scenarios", {})
    cfg["scenarios"]["events"] = events
    cfg["scenarios"][_EXPANDED_FLAG] = True
    return cfg
