"""Community event timelines — scenario events compiled into the step as data.

ROADMAP item 4 / docs/architecture.md §15: DR curtailment windows, grid
outage islanding, and TOU/real-time tariff shocks are DATA, not code.  A
timeline is four dense per-community series over the full environment
span (the same resolution as OAT/GHI/TOU), keyed per community so the
fleet axis runs heterogeneous event schedules under ONE compiled pattern
set; the engine gathers an (n_homes, H) window per step exactly like the
weather windows (``Engine._prepare``).

An all-default timeline (no events) is represented as ``None`` end to
end, so event-free runs trace the pre-scenario program byte-for-byte —
the acceptance invariant ``tests/test_scenarios.py`` pins.
"""

from __future__ import annotations

import warnings
from typing import Any, NamedTuple

import numpy as np

EVENT_KINDS = ("tariff_shock", "dr", "outage")


class ScenarioError(ValueError):
    """Raised for malformed scenario events / pack files."""


class EventTimeline(NamedTuple):
    """Dense per-community event series.  Shapes are (C, T) with C the
    fleet size and T the environment-series length (weather resolution),
    so step-t windows are plain dynamic slices.

    * ``price``  — additive $/kWh tariff shock (0 default);
    * ``cap``    — per-home grid-power upper bound, kW (+inf default;
      DR curtailment tightens it, outage pins it to 0);
    * ``floor``  — per-home grid-power lower bound, kW (−inf default;
      outage islanding pins it to 0: no import AND no export);
    * ``relax``  — indoor comfort-band widening, degC (0 default; DR and
      outage windows grant relief so tightened grid caps trade against
      comfort instead of infeasibility).
    """

    price: np.ndarray   # (C, T) f32
    cap: np.ndarray     # (C, T) f32
    floor: np.ndarray   # (C, T) f32
    relax: np.ndarray   # (C, T) f32

    @property
    def n_communities(self) -> int:
        return int(self.price.shape[0])

    @property
    def has_price(self) -> bool:
        return bool(np.any(self.price != 0.0))

    @property
    def has_grid(self) -> bool:
        return bool(np.any(np.isfinite(self.cap))
                    or np.any(np.isfinite(self.floor)))

    @property
    def has_relax(self) -> bool:
        return bool(np.any(self.relax != 0.0))

    @property
    def inert(self) -> bool:
        """True when the timeline changes nothing — the engine must then
        behave byte-identically to one built with no timeline at all."""
        return not (self.has_price or self.has_grid or self.has_relax)


def empty_timeline(n_communities: int, n_steps: int) -> EventTimeline:
    return EventTimeline(
        price=np.zeros((n_communities, n_steps), np.float32),
        cap=np.full((n_communities, n_steps), np.inf, np.float32),
        floor=np.full((n_communities, n_steps), -np.inf, np.float32),
        relax=np.zeros((n_communities, n_steps), np.float32),
    )


def _event_windows(ev: dict, t_env: int, dt: int, start_index: int):
    """Series index ranges [a, b) covered by one event, clipped to the
    environment span (windows crossing either edge clip, never error —
    the fuzz suite exercises horizon-edge events)."""
    start_h = float(ev.get("start_hour", 0.0))
    dur_h = float(ev.get("duration_hours", 0.0))
    if dur_h <= 0:
        raise ScenarioError(
            f"event {ev.get('kind')!r} needs duration_hours > 0, got {dur_h}")
    rep_h = float(ev.get("repeat_hours", 0.0))
    if rep_h < 0:
        raise ScenarioError(f"repeat_hours must be >= 0, got {rep_h}")
    if 0 < rep_h <= dur_h:
        raise ScenarioError(
            f"repeat_hours ({rep_h}) must exceed duration_hours ({dur_h}) "
            f"— overlapping repeats of one event are a schedule bug")
    a0 = start_index + int(round(start_h * dt))
    width = max(1, int(round(dur_h * dt)))
    stride = int(round(rep_h * dt))
    out = []
    a = a0
    while a < t_env:
        b = min(a + width, t_env)
        if b > max(a, 0):
            out.append((max(a, 0), b))
        if stride <= 0:
            break
        a += stride
    return out


def _event_communities(ev: dict, n_communities: int) -> list[int]:
    comms = ev.get("communities", [])
    if not comms:
        return list(range(n_communities))
    bad = [c for c in comms if not 0 <= int(c) < n_communities]
    if bad:
        raise ScenarioError(
            f"event {ev.get('kind')!r} names communities {bad} but the "
            f"fleet has {n_communities}")
    return [int(c) for c in comms]


def build_timeline(events: list[dict], n_communities: int, t_env: int,
                   dt: int, start_index: int) -> EventTimeline | None:
    """Expand declarative event dicts (docs/scenarios.md schema) into the
    dense :class:`EventTimeline`.  Returns ``None`` for an empty / inert
    schedule so callers keep the no-events fast path.

    ``start_hour`` is SIM-relative (hours from the simulation start, which
    sits at ``start_index`` in the environment series); ``repeat_hours``
    re-applies the window periodically (e.g. 24 = daily DR call)."""
    if not events:
        return None
    tl = empty_timeline(n_communities, t_env)
    for ev in events:
        kind = ev.get("kind")
        if kind not in EVENT_KINDS:
            raise ScenarioError(
                f"unknown event kind {kind!r} (expected one of "
                f"{'|'.join(EVENT_KINDS)})")
        comms = _event_communities(ev, n_communities)
        relax = float(ev.get("comfort_relax_degc", 0.0))
        for a, b in _event_windows(ev, t_env, dt, start_index):
            for c in comms:
                if kind == "tariff_shock":
                    tl.price[c, a:b] += np.float32(ev["price_delta"])
                elif kind == "dr":
                    # Overlapping DR windows compose as the TIGHTEST cap.
                    tl.cap[c, a:b] = np.minimum(
                        tl.cap[c, a:b], np.float32(ev["p_cap_kw"]))
                    tl.relax[c, a:b] = np.maximum(tl.relax[c, a:b], relax)
                else:  # outage: islanded — no import, no export
                    tl.cap[c, a:b] = 0.0
                    tl.floor[c, a:b] = 0.0
                    tl.relax[c, a:b] = np.maximum(tl.relax[c, a:b], relax)
    return None if tl.inert else tl


def timeline_for(config: dict, n_communities: int, t_env: int, dt: int,
                 start_index: int, data_dir: str | None = None
                 ) -> EventTimeline | None:
    """The resolved event timeline of a config's ``[scenarios]`` table —
    the ``events`` list, which after :func:`packs.apply_scenarios` also
    carries the named pack's events.  ``None`` when the config schedules
    nothing.

    A pack that was NEVER expanded is ignored WITH A WARNING rather than
    half-applied: resolving its events here while its ``[mix]`` never
    reached home synthesis would run the pack's schedule against a
    population it did not declare (``apply_scenarios`` is the one
    expansion point — packs.py).

    Tariff shocks compose with the TOU ladder — and were designed against
    the FIXED ladder (``tpu.fix_tou_peak = true``): under the default
    bug-parity ladder the peak price the shock was calibrated against
    never applies (dragg/aggregator.py:214-215 — docs/config.md), so a
    shock schedule running on the bug-parity path warns loudly."""
    from dragg_tpu.scenarios.packs import _EXPANDED_FLAG

    del data_dir  # packs resolve only through apply_scenarios
    scn = config.get("scenarios", {}) or {}
    events = list(scn.get("events", []) or [])
    if scn.get("pack") and not scn.get(_EXPANDED_FLAG):
        warnings.warn(
            f"scenarios.pack = {scn['pack']!r} is set but was never "
            f"expanded — call dragg_tpu.scenarios.apply_scenarios(config) "
            f"BEFORE synthesizing homes / building the engine (the "
            f"Aggregator, bench, validate_scale, and the serve worker all "
            f"do).  Ignoring the pack here: applying only its events "
            f"against a population missing its [mix] would run a schedule "
            f"the pack did not declare.",
            stacklevel=2)
    if not events:
        return None
    if any(e.get("kind") == "tariff_shock" for e in events) \
            and not config.get("tpu", {}).get("fix_tou_peak", False):
        warnings.warn(
            "scenario tariff shocks are composing with the BUG-PARITY TOU "
            "ladder (tpu.fix_tou_peak = false): the reference's peak price "
            "is silently overwritten by the shoulder assignment "
            "(dragg/aggregator.py:214-215), so shock deltas stack on a "
            "ladder whose peak tier never applies.  Set "
            "tpu.fix_tou_peak = true for the intended tiering.",
            stacklevel=2)
    return build_timeline(events, n_communities, t_env, dt, start_index)


def timeline_digest(tl: EventTimeline | None) -> str | None:
    """Content hash of the dense timeline series — the checkpoint
    `run_shape` key, so ANY schedule edit (a cap magnitude, a price
    delta, a community retarget) invalidates a resume even when the
    step-count summary is unchanged (the arrays are deterministic
    functions of the config, so the digest is stable across runs)."""
    if tl is None:
        return None
    import hashlib

    h = hashlib.sha256()
    for a in (tl.price, tl.cap, tl.floor, tl.relax):
        h.update(np.ascontiguousarray(a, dtype=np.float32).tobytes())
    return h.hexdigest()[:16]


def describe_timeline(tl: EventTimeline | None) -> dict[str, Any]:
    """Small JSON-able summary for logs / bench artifacts."""
    if tl is None:
        return {"events": False}
    return {
        "events": True,
        "communities": tl.n_communities,
        "shock_steps": int(np.sum(np.any(tl.price != 0, axis=0))),
        "dr_steps": int(np.sum(np.any(
            np.isfinite(tl.cap) & (tl.cap > 0), axis=0))),
        "outage_steps": int(np.sum(np.any(tl.cap == 0, axis=0))),
    }
