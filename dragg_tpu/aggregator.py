"""Community aggregator — host-side orchestration around the device engine.

Capability parity with the reference ``Aggregator`` (dragg/aggregator.py:29-970):
config + weather + price ingestion, seeded home synthesis (with the
``all_homes-<N>-config.json`` cache), the simulation loop, per-home data
collection, the RL utility setpoint, and results.json checkpoints in the
reference's directory layout — so the reference's ``Reformat`` post-processing
consumes our outputs unchanged.

Architectural inversion (SURVEY.md §7): the reference's hot loop fans one
process per home out over a pathos pool and moves every datum through Redis
(dragg/aggregator.py:711-755); here the community is a batched tensor program
(:mod:`dragg_tpu.engine`) and the host loop only touches the device at
checkpoint boundaries — one ``lax.scan`` chunk per checkpoint interval.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from dragg_tpu import telemetry
from dragg_tpu.config import configured_solver, load_config
from dragg_tpu.data import EnvironmentData, load_environment, load_waterdraw_profiles, parse_dt
from dragg_tpu.engine import Engine, StepOutputs, make_engine
from dragg_tpu.homes import check_home_configs
from dragg_tpu.logger import Logger
from dragg_tpu.scenarios import describe_timeline, timeline_digest

# Per-home series appended each timestep, in the reference's result-hash
# vocabulary (dragg/aggregator.py:741-745) → StepOutputs field name.
_BASE_KEYS = {
    "p_grid_opt": "p_grid",
    "forecast_p_grid_opt": "forecast_p_grid",
    "p_load_opt": "p_load",
    "temp_in_opt": "temp_in",
    "temp_wh_opt": "temp_wh",
    "hvac_cool_on_opt": "hvac_cool_on",
    "hvac_heat_on_opt": "hvac_heat_on",
    "wh_heat_on_opt": "wh_heat_on",
    "cost_opt": "cost",
    "waterdraws": "waterdraws",
    "correct_solve": "correct_solve",
}
_PV_KEYS = {"p_pv_opt": "p_pv", "u_pv_curt_opt": "u_pv_curt"}
_BATT_KEYS = {"e_batt_opt": "e_batt", "p_batt_ch": "p_batt_ch", "p_batt_disch": "p_batt_disch"}
_EV_KEYS = {"p_ev_ch_opt": "p_ev_ch", "e_ev_opt": "e_ev"}

# Observatory (round 9): per-bucket conv-iters metric literals, the
# bench.phase.solve_<type>_s precedent — absent buckets never observe.
_CONV_ITERS_METRICS = {
    "pv_battery": "solver.conv_iters_pv_battery",
    "pv_only": "solver.conv_iters_pv_only",
    "battery_only": "solver.conv_iters_battery_only",
    "base": "solver.conv_iters_base",
    "ev": "solver.conv_iters_ev",
    "heat_pump": "solver.conv_iters_heat_pump",
    "superset": "solver.conv_iters_superset",
}


def _is_ready(a) -> bool:
    """Whether a dispatched jax array's computation has completed — the
    pipeline's overlap-credit probe.  Conservative on any backend that
    cannot answer (old jax, non-addressable pod arrays): report ready, so
    ``overlap_hidden_s`` stays a LOWER bound and never over-credits."""
    try:
        return bool(a.is_ready())
    except Exception:
        return True


class Aggregator:
    """Drop-in analog of the reference Aggregator (dragg/aggregator.py:29).

    Parameters
    ----------
    config : dict | str | None
        A validated config dict, a path to a TOML file, or None to resolve
        via ``$DATA_DIR/$CONFIG_FILE`` with synthetic-data fallback.
    data_dir : str | None
        Where to look for nsrdb.csv / waterdraw profiles; defaults to
        ``$DATA_DIR`` (reference: dragg/aggregator.py:31-37).
    outputs_dir : str
        Root of the run-directory tree (reference: dragg/aggregator.py:32).
    """

    def __init__(self, config=None, data_dir=None, outputs_dir="outputs"):
        self.log = Logger("aggregator")
        # Distinguish "user configured a data dir" (arg or $DATA_DIR — missing
        # files there warn loudly, round-1 verdict weak #7) from "nothing
        # configured and the default ./data doesn't exist" (intentional
        # synthetic-data run; stay quiet by resolving to None).
        resolved = data_dir if data_dir is not None else os.path.expanduser(
            os.environ.get("DATA_DIR", "data")
        )
        explicit = data_dir is not None or "DATA_DIR" in os.environ
        self.data_dir = resolved if (explicit or os.path.isdir(resolved)) else None
        self.outputs_dir = outputs_dir
        os.makedirs(self.outputs_dir, exist_ok=True)

        if isinstance(config, dict):
            self.config = config
        else:
            self.config = load_config(config)
        # Scenario packs expand declaratively BEFORE anything reads the
        # community mix: [mix] fractions become community.homes_* counts
        # and pack events merge into scenarios.events (idempotent —
        # dragg_tpu/scenarios, docs/scenarios.md).
        from dragg_tpu.scenarios import apply_scenarios

        self.config = apply_scenarios(self.config, self.data_dir)
        self.check_type = self.config["simulation"]["check_type"]
        self.case = "baseline"

        # Fleet resolution ([fleet] — round 12, architecture.md §14):
        # C > 1 folds C independent communities (own seeds / weather
        # offsets) into one batched engine; community.total_number_homes
        # stays PER COMMUNITY.
        from dragg_tpu.homes import fleet_community_base, fleet_config

        (self.n_communities, self._fleet_seed_stride,
         self._fleet_weather_off_h) = fleet_config(self.config)
        # Shard workers (architecture.md §19) run a community RANGE of a
        # larger fleet: community_base shifts seeds/names/weather to the
        # global identities, so coverage must extend past the LAST global
        # community's offset, not the local count's.
        self._fleet_comm_base = fleet_community_base(self.config)

        # Simulation window (dragg/aggregator.py:111-127).
        self.start_dt = parse_dt(self.config["simulation"]["start_datetime"])
        self.end_dt = parse_dt(self.config["simulation"]["end_datetime"])
        self.hours = int((self.end_dt - self.start_dt).total_seconds() / 3600)
        self.dt = int(self.config["agg"]["subhourly_steps"])
        self.dt_interval = 60 // self.dt
        self.num_timesteps = int(np.ceil(self.hours * self.dt))

        # Environment series (weather + TOU price).  A fleet with weather
        # offsets shifts community c's windows c*offset hours forward, so
        # coverage must extend past the horizon by the largest offset.
        self.env: EnvironmentData = load_environment(self.config, data_dir=self.data_dir)
        horizon_hours = int(self.config["home"]["hems"]["prediction_horizon"])
        self.env.check_coverage(
            self.start_dt, self.end_dt,
            horizon_hours
            + (self._fleet_comm_base + self.n_communities - 1)
            * self._fleet_weather_off_h)
        self.start_index = self.env.start_index(self.start_dt)

        self.all_homes: list[dict] | None = None
        self.engine: Engine | None = None
        self._state = None
        self.timestep = 0
        self.baseline_agg_load_list: list[float] = []
        self.all_rps = np.zeros(self.num_timesteps)
        self.all_sps = np.zeros(self.num_timesteps)
        self.agg_load = 0.0
        self.agg_cost = 0.0
        self.forecast_load = 0.0
        self.reward_price = np.zeros(
            int(self.config["agg"].get("rl", {}).get("action_horizon", 1)) * self.dt
        )
        self.start_time = None
        self.end_time = None
        self.extra_summary: dict = {}  # case-specific Summary additions
        self.resumed_from: str | None = None  # checkpoint dir a run resumed from
        self.collector = None  # SeriesCollector, built by reset_collected_data
        self._home_static: dict = {}
        self.summary_only_case = False  # simplified case: no per-home blocks
        # Stop after N scan chunks (None = run to completion).  Each chunk
        # ends at a checkpoint boundary, so stopping here is equivalent to
        # the process being killed right after a checkpoint — the hook the
        # resume tests (and operators doing staged runs) use.
        self.stop_after_chunks: int | None = None
        self.version = self.config["simulation"].get("named_version", "test")
        self.run_dir = None
        self._solve_iters: list[int] = []
        # Whether THIS aggregator opened the telemetry bus (run() →
        # _telemetry_open: config-enabled AND process 0).  The engine
        # emits below gate on this flag, NOT on telemetry.active(): the
        # bus auto-joins $DRAGG_TELEMETRY_DIR lazily, and without the
        # flag every non-zero rank of a pod run would duplicate
        # chunk.done onto the shared stream (and telemetry.enabled=false
        # would be overridden by a supervising parent's env export).
        self._telemetry_on = False
        # Opt-in worst-k forensic dumps (telemetry.forensics — resolved
        # with the rest of the [telemetry] config in _telemetry_open).
        self._forensics_on = False
        # Persistent XLA compilation cache: a re-run of the same config
        # skips the 20-40 s cold compile entirely (docs/perf_notes.md).
        from dragg_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache(self.config)

    # ----------------------------------------------------------- population
    @property
    def total_homes(self) -> int:
        """Homes across the whole fleet (= per-community count × C)."""
        return (int(self.config["community"]["total_number_homes"])
                * self.n_communities)

    def _homes_cache_file(self) -> str:
        """Population cache path.  C=1 keeps the reference's exact
        ``all_homes-<N>-config.json`` name; a fleet's name carries the
        community axis too — a 2×500 fleet and a 1×1000 community have
        the same total and (at equal mix ratios) the same per-type
        counts, so a total-only key would silently cross-reuse their
        cached populations (review round 12)."""
        n = self.total_homes
        tag = (f"{n}" if self.n_communities == 1
               else f"{n}-{self.n_communities}comm")
        return os.path.join(self.outputs_dir, f"all_homes-{tag}-config.json")

    def get_homes(self) -> None:
        """Create or reload the home population (dragg/aggregator.py:263-271):
        reuse ``all_homes-<N>-config.json`` unless overwrite_existing.
        With ``fleet.communities > 1`` the population is C communities
        drawn with their own seeds, stored community-major in one flat
        list (homes.create_fleet_homes) under a fleet-tagged cache name
        (:meth:`_homes_cache_file`)."""
        from dragg_tpu.homes import create_fleet_homes

        homes_file = self._homes_cache_file()
        if not self.config["community"].get("overwrite_existing", True) and os.path.isfile(homes_file):
            with open(homes_file) as f:
                self.all_homes = json.load(f)
        else:
            waterdraw = load_waterdraw_profiles(
                self._waterdraw_path(), seed=int(self.config["simulation"]["random_seed"])
            )
            self.all_homes = create_fleet_homes(
                self.config, self.num_timesteps, self.dt, waterdraw)
        if self.n_communities == 1:
            check_home_configs(self.all_homes, self.config)
        else:
            # Per-community blocks each satisfy the (per-community) config
            # counts; the fleet structure itself is validated again when
            # the spec is derived (homes.fleet_spec_for).
            B = len(self.all_homes) // self.n_communities
            for c in range(self.n_communities):
                check_home_configs(self.all_homes[c * B:(c + 1) * B],
                                   self.config)
        self.write_home_configs()

    def _waterdraw_path(self) -> str | None:
        from dragg_tpu.data import waterdraw_path

        return waterdraw_path(self.config, self.data_dir)

    def write_home_configs(self) -> None:
        """Persist the population (dragg/aggregator.py:846-854)."""
        with open(self._homes_cache_file(), "w") as f:
            json.dump(self.all_homes, f, indent=4)

    def _build_engine(self) -> None:
        from dragg_tpu.homes import build_fleet_batch

        hems = self.config["home"]["hems"]
        horizon = max(1, int(hems["prediction_horizon"]) * self.dt)
        # Fleet batches are TYPE-MAJOR (all communities' homes of one type
        # contiguous) so the bucketed engine compiles ONE pattern per type
        # regardless of C; fleet.global_idx / engine.real_home_cols map
        # the merged outputs back to this aggregator's community-major
        # all_homes order.  C=1 reduces to build_home_batch exactly.
        batch, fleet = build_fleet_batch(
            self.all_homes, self.config, horizon, self.dt,
            int(hems["sub_subhourly_steps"]))
        self.batch = batch
        # Multi-device processes (a TPU pod slice launched via
        # deploy/launch_tpu_pod.sh, or any host with >1 visible device)
        # shard the home axis over the mesh automatically; ``tpu.sharded``
        # forces either behavior.  The sharded engine pads the home count
        # to a multiple of the mesh — per-home outputs are sliced back to
        # the true population in _collect_chunk.
        sharded = self.config.get("tpu", {}).get("sharded", "auto")
        if sharded not in ("auto", True, False):
            raise ValueError(
                f"tpu.sharded must be 'auto', true, or false, got {sharded!r}")
        if sharded == "auto":
            # Device enumeration initializes the backend — route through
            # the sanctioned helper (this process is committed to the
            # device anyway: the engine build below puts arrays on it),
            # never a bare jax.devices() (lint-enforced; CLAUDE.md).
            from dragg_tpu.resilience.devices import device_count

            use_sharded = device_count() > 1
        else:
            use_sharded = bool(sharded)
        if use_sharded:
            from dragg_tpu.parallel import make_sharded_engine

            self.engine = make_sharded_engine(
                batch, self.env, self.config, self.start_index, fleet=fleet,
                data_dir=self.data_dir)
            self.log.logger.info(
                f"sharded engine: {self.engine.mesh.devices.size} devices, "
                f"{self.engine.n_homes} home slots "
                f"({self.engine.true_n_homes} real)")
        else:
            self.engine = make_engine(batch, self.env, self.config,
                                      self.start_index, fleet=fleet,
                                      data_dir=self.data_dir)
        if fleet is not None:
            self.log.logger.info(
                f"fleet engine: {fleet.n_communities} communities × "
                f"{fleet.homes_per_community} homes "
                f"(seeds {fleet.seeds[0]}..{fleet.seeds[-1]}, weather "
                f"offset {self._fleet_weather_off_h} h/community)")
        if self.engine.bucketed:
            self.log.logger.info(
                "type-bucketed engine: " + ", ".join(
                    f"{b['name']}×{b['n_real']} (m={b['m_eq']}, n={b['n_var']})"
                    for b in self.engine.bucket_info()))
        evts = describe_timeline(getattr(self.engine, "_events", None))
        if evts.get("events"):
            self.log.logger.info(f"scenario event timeline: {evts}")

    # ------------------------------------------------------------- data mgmt
    def _home_selected(self, home: dict) -> bool:
        """check_type selection (dragg/aggregator.py:767-770)."""
        return self.check_type == "all" or home["type"] == self.check_type

    def _home_keys(self, home: dict) -> list[str]:
        keys = list(_BASE_KEYS)
        if "pv" in home["type"]:
            keys += list(_PV_KEYS)
        if "battery" in home["type"]:
            keys += list(_BATT_KEYS)
        if home["type"] == "ev":
            keys += list(_EV_KEYS)
        return keys

    def reset_collected_data(self) -> None:
        """Initialize the per-home series store (dragg/aggregator.py:589-615).

        Series live in a :class:`~dragg_tpu.native.SeriesCollector` (C++
        when the native library builds, pure-Python otherwise — identical
        API), which is the single source of truth for per-home time series;
        static per-home fields stay in ``self._home_static``."""
        from dragg_tpu.native import SeriesCollector

        self.timestep = 0
        self.baseline_agg_load_list = []
        self._solve_iters = []
        # Per-case Summary additions must not leak across cases (e.g. a
        # baseline shape error surfacing in a clean rl_agg Summary).
        self.extra_summary = {}
        # Wall-clock phase attribution (device scan vs host collect),
        # surfaced as Summary.phase_times.  Pipeline accounting (round
        # 12): ``overlap_hidden_s`` is the portion of host collect/
        # checkpoint wall that provably ran WHILE the next chunk executed
        # on device (a lower bound — host windows during which the device
        # finished are not credited), and ``state_snapshot`` the donated-
        # carry host-copy cost the pipeline pays per chunk.
        self._phase_times = {"device_chunks": 0.0, "collect": 0.0,
                             "overlap_hidden_s": 0.0, "state_snapshot": 0.0}
        if getattr(self, "collector", None) is not None:
            self.collector.close()
        n = len(self.all_homes)
        self.collector = SeriesCollector(n)
        self._home_static = {}
        temp_in_init = np.zeros((1, n))
        temp_wh_init = np.zeros((1, n))
        e_batt_init = np.zeros((1, n))
        for i, home in enumerate(self.all_homes):
            self._home_static[home["name"]] = {
                "type": home["type"],
                "temp_in_sp": home["hvac"]["temp_in_sp"],
                "temp_wh_sp": home["wh"]["temp_wh_sp"],
            }
            temp_in_init[0, i] = home["hvac"]["temp_in_init"]
            temp_wh_init[0, i] = home["wh"]["temp_wh_init"]
            if "battery" in home["type"]:
                e_batt_init[0, i] = home["battery"]["e_batt_init"]
        # Leading initial elements (dragg/aggregator.py:600-603,612).
        self.collector.add_chunk("temp_in_opt", temp_in_init)
        self.collector.add_chunk("temp_wh_opt", temp_wh_init)
        self.collector.add_chunk("e_batt_opt", e_batt_init)

    def _collect_chunk(self, outs: StepOutputs, track_setpoints: bool = True,
                       device_s: float | None = None) -> None:
        """Append a chunk of stacked step outputs to the series store — the
        analog of per-step ``collect_data`` Redis reads
        (dragg/aggregator.py:728-755), amortized over the whole chunk: one
        native append per (series, chunk) instead of per-home Python loops.

        ``track_setpoints=False`` skips the host-side ``gen_setpoint`` loop:
        the RL-aggregator scan already tracks the setpoint on device and
        overwrites ``all_sps`` with the authoritative values.

        ``device_s`` (the caller's measured device wall time for this
        chunk) feeds the per-chunk step-latency telemetry; the solver
        telemetry (iterations, residual maxima, solve rate) rides the
        SAME host transfer as the collected series — StepOutputs carries
        it, so telemetry adds no extra device→host syncs."""
        from dragg_tpu.checkpoint import to_host
        from dragg_tpu.engine import OBS_FIELDS

        n_true = getattr(self.engine, "true_n_homes", None) or self.engine.n_homes
        # Sharded engines pad the home axis (whole-batch padding at the
        # end, or per-bucket padding at bucket boundaries when the engine
        # is type-bucketed); real_home_cols maps slot order back to the
        # true community order either way.
        cols = getattr(self.engine, "real_home_cols", None)
        if cols is None:
            cols = np.arange(n_true)
        host = {}
        for f in StepOutputs._fields:
            # to_host all-gathers leaves that span processes (multi-host
            # pods) — it is a collective, so it runs on every process even
            # though only process 0 writes files.
            a = to_host(getattr(outs, f))
            # Replica homes are masked out of aggregates on device and
            # dropped from per-home series here.  Observatory leaves are
            # per-BUCKET folds (histograms / worst-k), not per-home —
            # their trailing axis is not the home axis, so they skip the
            # real-home column slicing.
            host[f] = a[:, cols] if a.ndim == 2 and f not in OBS_FIELDS \
                else a
        n_steps = host["p_grid"].shape[0]
        for out_key, field in (*_BASE_KEYS.items(), *_PV_KEYS.items(),
                               *_BATT_KEYS.items(), *_EV_KEYS.items()):
            self.collector.add_chunk(out_key, host[field])
        agg_loads = host["agg_load"]
        self.baseline_agg_load_list.extend(float(v) for v in agg_loads)
        self._solve_iters.extend(int(v) for v in host["admm_iters"])
        # VERBOSE solver telemetry — the reference's per-solve CVXPY
        # verbosity toggle (dragg/mpc_calc.py:81-86), batched per chunk.
        if os.environ.get("VERBOSE"):
            rate = float(host["correct_solve"].mean())
            self.log.logger.progress(
                f"chunk t={self.timestep}..{self.timestep + n_steps}: "
                f"solve_rate={rate:.4f}, "
                f"mean ADMM iters={host['admm_iters'].mean():.0f}, "
                f"agg_load range=[{agg_loads.min():.1f}, {agg_loads.max():.1f}] kW"
            )
        # Integer-repair coverage: homes whose pinned re-solve failed keep
        # the relaxed fractional action (engine._integerize_first_action).
        # Measured 99.9 % coverage on CPU (docs/perf_notes.md round 4);
        # surface any regression so on-chip configs can detect it (ADVICE
        # round 4).
        n_repair_failed = float(np.sum(host["repair_failed"]))
        if self._telemetry_on:
            # One typed record per chunk on the run's unified stream —
            # what the dashboard's /live view and the forensic artifacts
            # tail (docs/telemetry.md).
            rate = float(host["correct_solve"].mean())
            mean_iters = float(host["admm_iters"].mean())
            rpm = float(host["r_prim_max"].max())
            rdm = float(host["r_dual_max"].max())
            fields = dict(t0=self.timestep, t1=self.timestep + n_steps,
                          n_steps=n_steps, solve_rate=round(rate, 4),
                          solver_iters=round(mean_iters, 1),
                          r_prim_max=rpm, r_dual_max=rdm,
                          repair_failed=int(n_repair_failed))
            if device_s is not None:
                fields["device_s"] = round(device_s, 3)
                fields["steps_per_s"] = round(
                    n_steps / max(device_s, 1e-9), 3)
                telemetry.observe("engine.chunk_device_s", device_s)
                telemetry.observe("engine.chunk_steps_per_s",
                                  fields["steps_per_s"])
            telemetry.emit("chunk.done", **fields)
            telemetry.observe("engine.solve_iters", mean_iters)
            telemetry.set_gauge("engine.solve_rate", rate)
            telemetry.set_gauge("engine.r_prim_max", rpm)
            telemetry.set_gauge("engine.r_dual_max", rdm)
            telemetry.set_gauge("sim.timestep", self.timestep + n_steps)
            if n_repair_failed:
                telemetry.inc("engine.repair_failed", n_repair_failed)
            self._emit_observatory(host, n_steps)
        if n_repair_failed > 0:
            self.log.logger.progress(
                f"chunk t={self.timestep}..{self.timestep + n_steps}: "
                f"{int(n_repair_failed)} pinned re-solves failed "
                f"(homes kept the relaxed fractional action)")
        self._log_home_failures(host["correct_solve"])
        # Per-step setpoint tracking.  Ordering parity: the reference
        # increments the timestep in run_iteration BEFORE collect_data calls
        # gen_setpoint (dragg/aggregator.py:726,755), and the setpoint
        # computed after collecting step t is recorded at step t+1 by the
        # next redis_set_current_values (dragg/aggregator.py:671-673).
        for k in range(n_steps):
            self.agg_load = float(agg_loads[k])
            self.forecast_load = float(host["forecast_load"][k])
            self.agg_cost = float(host["agg_cost"][k])
            self.timestep += 1
            if track_setpoints:
                self.agg_setpoint = self.gen_setpoint()
                if self.timestep < self.num_timesteps:
                    self.all_sps[self.timestep] = self.agg_setpoint

    def _emit_observatory(self, host: dict, n_steps: int) -> None:
        """Observatory emits for one chunk (round 9): fold the device-side
        per-bucket histograms / worst-k capture (engine._per_home_obs —
        riding the SAME host transfer as the series above) into
        ``solver.convergence`` / ``solver.worst`` / ``solver.diverged``
        events and the per-bucket conv-iters metrics, plus the opt-in
        forensic dump (``telemetry.forensics``)."""
        if not getattr(self.engine, "obs_enabled", False):
            return
        ch = np.asarray(host["conv_hist"])            # (T, nb, RBINS)
        if ch.size == 0:
            return
        t0, t1 = self.timestep, self.timestep + n_steps
        binfo = self.engine.bucket_info()
        isum = np.asarray(host["iters_sum"])          # (T, nb)
        dc = np.asarray(host["diverged_count"])       # (T, nb)
        ih = np.asarray(host["iters_hist"])
        for bi, b in enumerate(binfo):
            rhist = ch[:, bi, :].sum(axis=0)
            n_obs = float(rhist.sum())
            mean_iters = float(isum[:, bi].sum()) / max(n_obs, 1.0)
            telemetry.emit(
                "solver.convergence", t0=t0, t1=t1, bucket=b["name"],
                n_homes=b["n_real"],
                rprim_hist=[int(v) for v in rhist],
                iters_hist=[int(v) for v in ih[:, bi, :].sum(axis=0)],
                mean_iters=round(mean_iters, 2),
                diverged=int(dc[:, bi].sum()))
            telemetry.observe(_CONV_ITERS_METRICS[b["name"]], mean_iters)  # dragg: disable=DT007, per-bucket literal from _CONV_ITERS_METRICS, each registered
        total_div = float(dc.sum())
        if total_div:
            telemetry.inc("solver.diverged_homes", total_div)
            telemetry.emit(
                "solver.diverged", t0=t0, t1=t1, total=int(total_div),
                by_bucket={b["name"]: int(dc[:, bi].sum())
                           for bi, b in enumerate(binfo)
                           if dc[:, bi].sum() > 0})
        # Global worst-k across the chunk, from the per-(step, bucket)
        # device captures (idx −1 = an under-filled bucket slot).
        wi = np.asarray(host["worst_idx"])            # (T, nb·k)
        wrp = np.asarray(host["worst_rp"])
        wrd = np.asarray(host["worst_rd"])
        wit = np.asarray(host["worst_iters"])
        wb = np.asarray(host["worst_bucket"])
        ti, si = np.nonzero(wi >= 0)
        if ti.size == 0:
            return
        k = int(self.engine.params.obs_worst_k)
        # The device fold reports non-finite residuals as the finite
        # f32-max sentinel (engine._per_home_obs, r_prim_max convention),
        # so ranking and the JSON emits below stay NaN-free; the where is
        # a belt-and-braces guard for hand-constructed outputs —
        # np.argsort would sort a NaN LAST regardless of sign, dropping
        # exactly the diverged homes this capture exists to surface.
        rank = wrp[ti, si]
        rank = np.where(np.isfinite(rank), rank, np.float32(3.4e38))
        order = np.argsort(-rank, kind="stable")
        # Dedup by home, keeping each home's worst step: the device
        # captures per (step, bucket), so one home diverging all chunk
        # would otherwise fill every slot and hide the k−1 next-worst
        # homes the event (and the forensic dump) exist to name.
        entries, seen = [], set()
        for t, s in zip(ti[order], si[order]):
            home = int(wi[t, s])
            if home in seen:
                continue
            seen.add(home)
            entries.append(
                dict(home=home,
                     bucket=binfo[int(wb[t, s])]["name"],
                     t=t0 + int(t),
                     r_prim=float(wrp[t, s]), r_dual=float(wrd[t, s]),
                     iters=int(wit[t, s])))
            if len(entries) >= k:
                break
        telemetry.emit("solver.worst", t0=t0, t1=t1, homes=entries)
        telemetry.set_gauge("solver.worst_rprim", entries[0]["r_prim"])
        if self._forensics_on:
            self._write_forensics(t0, t1, entries)

    def _write_forensics(self, t0: int, t1: int, entries: list[dict]) -> None:
        """Opt-in (``telemetry.forensics``) per-chunk dump of everything an
        offline HiGHS cross-check (tools/milp_gap.py pattern) needs to
        rebuild the worst-k homes' exact QPs WITHOUT a full-community
        re-run: the home's full synthesis config, its scalar carried state
        at chunk START (engine.state_slice), the worst step's t, and the
        chunk's reward prices.  Reconstruction = re-run ≤ one checkpoint
        interval for ONE home from the snapshot, not 10k homes from t=0."""
        if self.run_dir is None:
            return
        state0 = getattr(self, "_chunk_state0", None)
        dump = {
            "t0": t0, "t1": t1, "case": self.case,
            "start_index": int(self.engine.params.start_index),
            "solver": self.engine.params.solver,
            "horizon": int(self.engine.params.horizon),
            "integer_first_action": bool(
                self.engine.params.integer_first_action),
            "integer_repair": self.engine.params.integer_repair,
            "buckets": self.engine.bucket_info(),
            "reward_prices": [float(v) for v in self.all_rps[t0:t1]],
            "note": ("state_at_chunk_start is the scan carry at t0; "
                     "replaying t0..t for one home reproduces the exact "
                     "(t, state, QP coefficients) of the worst step"),
            "homes": [
                {**e,
                 "name": self.all_homes[e["home"]]["name"],
                 "type": self.all_homes[e["home"]]["type"],
                 "state_at_chunk_start": (
                     self.engine.state_slice(state0, e["home"])
                     if state0 is not None else None),
                 "config": self.all_homes[e["home"]]}
                for e in entries
            ],
        }
        fdir = os.path.join(self.run_dir, "forensics")
        try:
            os.makedirs(fdir, exist_ok=True)
            path = os.path.join(fdir, f"chunk_t{t0:08d}.json")
            with open(path + ".tmp", "w") as f:
                json.dump(dump, f, indent=1, default=str)
            os.replace(path + ".tmp", path)
        except OSError:
            pass  # forensics must never kill the run

    def _log_home_failures(self, correct_solve: np.ndarray) -> None:
        """Per-home failure logs — the analog of the reference's per-home
        WARN-level worker log files (home_logs/<name>.log,
        dragg/mpc_calc.py:655-658).  There is no per-home process here, so
        the batched ``correct_solve`` mask drives the same artifact: one log
        file per home that ever fell back, appended lazily (a healthy
        100k-home run creates zero files)."""
        failed = np.argwhere(np.asarray(correct_solve) == 0.0)
        if failed.size == 0 or self.run_dir is None:
            return
        log_dir = os.path.join(self.run_dir, "home_logs")
        os.makedirs(log_dir, exist_ok=True)
        base_t = self.timestep
        by_home: dict[int, list[int]] = {}
        for k, i in failed:
            by_home.setdefault(int(i), []).append(base_t + int(k))
        for i, steps in by_home.items():
            name = self.all_homes[i]["name"]
            with open(os.path.join(log_dir, f"{name}.log"), "a") as f:
                for t in steps:
                    f.write(
                        f"WARNING - {name} - timestep {t}: MPC solve failed "
                        f"tolerance; fallback controller engaged\n"
                    )

    def reset_seed(self, new_seed: int) -> None:
        """Reset the population seed (dragg/aggregator.py:255-261); takes
        effect on the next ``get_homes()``/``create_homes()``."""
        self.config["simulation"]["random_seed"] = int(new_seed)

    # ----------------------------------------------------------- RL setpoint
    def gen_setpoint(self) -> float:
        """RL utility setpoint: trailing average of community load
        (dragg/aggregator.py:677-696)."""
        prev_n = int(self.config["agg"].get("rl", {}).get("prev_timesteps", 12))
        if self.timestep < 2:
            max_poss = self._max_possible_load()
            self.tracked_loads = [0.5 * max_poss] * prev_n
            self.max_load = -float("inf")
            self.min_load = float("inf")
        else:
            self.tracked_loads[:-1] = self.tracked_loads[1:]
            self.tracked_loads[-1] = self.agg_load
        self.avg_load = float(np.average(self.tracked_loads))
        if self.agg_load > self.max_load or self.timestep % 24 == 0:
            self.max_load = self.agg_load
        if self.agg_load < self.min_load or self.timestep % 24 == 0:
            self.min_load = self.agg_load
        return self.avg_load

    def _max_possible_load(self) -> float:
        """Sum of each home's max simultaneous load (dragg/mpc_calc.py:191)."""
        return float(self._max_possible_load_per_community().sum())

    def _max_possible_load_per_community(self) -> np.ndarray:
        """(C,) per-community max possible load — the fleet RL
        observation normalizers (communities are distinct seeded
        populations, so their normalizers differ; dragg_tpu/rl/fleet),
        and the ONE home of the per-home expression
        (dragg/mpc_calc.py:191) that :meth:`_max_possible_load` sums.
        ``all_homes`` is community-major, so community c is the c-th
        block of B homes."""
        C = self.n_communities
        B = len(self.all_homes) // C
        out = np.zeros(C)
        for c in range(C):
            out[c] = sum(
                max(float(h["hvac"]["p_c"]), float(h["hvac"]["p_h"]))
                + float(h["wh"]["p"])
                for h in self.all_homes[c * B:(c + 1) * B])
        return out

    # ------------------------------------------------------------ checkpoint
    def _checkpoint_root(self) -> str:
        return os.path.join(self.run_dir, self.case, "checkpoint")

    def save_checkpoint(self, state, extra_json: dict | None = None) -> None:
        """Persist the scan carry + host bookkeeping so the run can resume
        mid-simulation (capability the reference lacks — its checkpoints are
        write-only outputs, dragg/aggregator.py:776-778).

        Atomicity: each checkpoint is a self-contained versioned directory
        (state.npz + progress.json + collected.json [+ extras]) staged under
        a ``.tmp`` name and renamed into place, after which the ``LATEST``
        pointer is atomically replaced.  A kill at any instant leaves either
        the previous complete checkpoint or the new complete one — never a
        torn mix.  results.json stays a user-facing output; resume never
        reads it.

        Multi-host (``jax.process_count() > 1``): every process dumps its
        OWN addressable shard blocks (no gather collective, no shared-FS
        assumption) — see :meth:`_save_checkpoint_multiprocess`."""
        import shutil

        import jax
        from dragg_tpu.checkpoint import save_progress, save_pytree, to_host

        if jax.process_count() > 1:
            self._save_checkpoint_multiprocess(state, extra_json)
            return
        state = jax.tree_util.tree_map(to_host, state)
        root = self._checkpoint_root()
        os.makedirs(root, exist_ok=True)
        name = f"ckpt_t{self.timestep:08d}"
        tmp = os.path.join(root, name + ".tmp")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        save_pytree(os.path.join(tmp, "state.npz"), state)
        self.collector.write_json(os.path.join(tmp, "collected.json"),
                                  self._results_plan(None))
        for fname, obj in (extra_json or {}).items():
            save_progress(os.path.join(tmp, fname), obj)
        save_progress(os.path.join(tmp, "progress.json"), self._progress_dict())
        final = os.path.join(root, name)
        # A previous run killed between this rename and the LATEST replace
        # leaves a complete ckpt dir at `final` while LATEST still points at
        # the older checkpoint; the resumed run reaches this timestep again
        # and os.rename onto a non-empty dir raises.  Clear it first — the
        # staged tmp dir is the authoritative new checkpoint.
        shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)
        latest_tmp = os.path.join(root, "LATEST.tmp")
        with open(latest_tmp, "w") as f:
            f.write(name)
        os.replace(latest_tmp, os.path.join(root, "LATEST"))
        # Prune superseded checkpoints.
        for entry in os.listdir(root):
            if entry.startswith("ckpt_") and entry != name:
                shutil.rmtree(os.path.join(root, entry), ignore_errors=True)

    def _progress_dict(self) -> dict:
        return {
            "run_shape": self._run_shape(),
            "timestep": self.timestep,
            "elapsed": time.time() - self.start_time,  # dragg: disable=DT014, wall-clock elapsed for results/progress telemetry, not simulation state
            "baseline_agg_load_list": self.baseline_agg_load_list,
            "all_rps": self.all_rps.tolist(),
            "all_sps": self.all_sps.tolist(),
            "solve_iters": self._solve_iters,
            "tracked_loads": getattr(self, "tracked_loads", None),
            "max_load": getattr(self, "max_load", None),
            "min_load": getattr(self, "min_load", None),
        }

    def _save_checkpoint_multiprocess(self, state, extra_json) -> None:
        """Multi-host checkpoint: per-process shard dumps + barrier-gated
        publish, so a pod whose workers have SEPARATE local disks can still
        resume (round-2 open item, docs/round2_summary.md).

        Protocol (every process runs it against its own filesystem):
        1. each process atomically writes ``state.procXXXXX-of-YYYYY.npz``
           with only ITS addressable blocks (checkpoint.save_pytree_local —
           collective-free); process 0 also writes progress/collected/extras;
        2. global barrier — no LATEST anywhere until every shard is durable;
        3. every process atomically replaces its LATEST pointer (identical
           bytes, so the racing writes on a shared FS are benign);
        4. barrier, then prune superseded checkpoint dirs.
        A crash between 2 and 3 tears LATEST across workers; resume detects
        that via the broadcast decision + per-shard timestep check and
        starts fresh instead of deadlocking (:meth:`try_resume`)."""
        import shutil

        import jax
        from jax.experimental import multihost_utils

        from dragg_tpu.checkpoint import (save_progress, save_pytree_local,
                                          shard_file_name)

        root = self._checkpoint_root()
        name = f"ckpt_t{self.timestep:08d}"
        final = os.path.join(root, name)
        # Any write failure (disk full, permissions) is allgathered as a
        # go/no-go flag BEFORE the barrier — a rank that raised inside the
        # write block would otherwise leave every other rank blocked in
        # sync_global_devices forever (ADVICE round 3).  On no-go, no rank
        # publishes LATEST: the previous checkpoint stays authoritative and
        # the run continues.
        ok = True
        try:
            os.makedirs(final, exist_ok=True)
            save_pytree_local(
                os.path.join(final, shard_file_name(jax.process_index(),
                                                    jax.process_count())),
                state, self.timestep)
            if jax.process_index() == 0:
                self.collector.write_json(
                    os.path.join(final, "collected.json"),
                    self._results_plan(None))
                for fname, obj in (extra_json or {}).items():
                    save_progress(os.path.join(final, fname), obj)
                save_progress(os.path.join(final, "progress.json"),
                              self._progress_dict())
        except Exception:
            self.log.logger.exception(
                f"checkpoint write failed on process {jax.process_index()}; "
                f"skipping publish of {name} (previous checkpoint remains "
                f"authoritative)")
            ok = False
        all_ok = bool(np.all(multihost_utils.process_allgather(
            np.asarray([ok]))))
        if not all_ok:
            if ok:
                self.log.logger.warning(
                    f"checkpoint {name} aborted: another process failed its "
                    f"write; no LATEST update")
            return
        multihost_utils.sync_global_devices(f"dragg_ckpt_files_{name}")
        latest_tmp = os.path.join(root, f"LATEST.tmp{jax.process_index()}")
        with open(latest_tmp, "w") as f:
            f.write(name)
        os.replace(latest_tmp, os.path.join(root, "LATEST"))
        multihost_utils.sync_global_devices(f"dragg_ckpt_latest_{name}")
        for entry in os.listdir(root):
            if entry.startswith("ckpt_") and entry != name:
                shutil.rmtree(os.path.join(root, entry), ignore_errors=True)

    def clear_checkpoint(self) -> None:
        """Drop the resume checkpoint once a run completes, so a later
        invocation with ``resume=true`` starts fresh instead of re-running
        the final chunk over completed results."""
        import shutil

        shutil.rmtree(self._checkpoint_root(), ignore_errors=True)

    def _latest_checkpoint_dir(self) -> str | None:
        root = self._checkpoint_root()
        pointer = os.path.join(root, "LATEST")
        if not os.path.isfile(pointer):
            return None
        with open(pointer) as f:
            name = f.read().strip()
        d = os.path.join(root, name)
        return d if os.path.isdir(d) else None

    def _run_shape(self) -> dict:
        """Dimensions a checkpoint is only valid for: restored bookkeeping
        arrays (all_rps/all_sps) and the scan carry are sized by these, so a
        config change between runs must invalidate the checkpoint instead of
        surfacing later as an obscure broadcast/index error."""
        return {
            "num_timesteps": self.num_timesteps,
            "n_homes": len(self.all_homes) if self.all_homes else
                       self.total_homes,
            # The fleet's community axis (round 12): the carry leaves are
            # sized by the WHOLE fleet and the per-home bookkeeping by its
            # community-major order, so a checkpoint written at a
            # different C (or a pre-fleet one) must start fresh, not
            # misattribute homes across communities.
            "communities": self.n_communities,
            # Solver family (config.resolve_solver_family): warm_rho is a
            # continuous per-home rho under admm but a bank-snapped value
            # under reluqp, and the two families' warm carries are not
            # interchangeable semantics even at identical leaf shapes — a
            # checkpoint written under one family must start fresh under
            # the other, not silently cross-seed it (round 10).
            "solver": (self.engine.params.solver
                       if self.engine is not None else None),
            # Hot-loop matmul policy (ISSUE 11): warm iterates written
            # under bf16x3 sit at a different fixed-point accuracy than
            # f32 ones even at identical leaf shapes/dtypes (the carry
            # itself stays f32 by the ops/precision discipline), and a
            # mid-run policy flip would silently mix the two trajectories
            # — invalidate, don't cross-seed.
            "precision": (self.engine.params.precision
                          if self.engine is not None else None),
            # Sharded engines pad the home axis, so the carry leaves are
            # sized by the SLOT count — a checkpoint from a different
            # device count / sharding mode must start fresh, not crash in
            # load_pytree's leaf-shape check.
            "n_home_slots": self.engine.n_homes if self.engine is not None
                            else None,
            # The warm-start carry is zero-width unless a solver consumes
            # it (engine.init_state), so a checkpoint written under
            # solver=admm (or ipm_warm=true) has differently-shaped
            # warm_x/warm_y_box leaves than the ipm default — another
            # "invalidate, don't crash" dimension (advisor finding, r4).
            "warm_cols": (self.engine.warm_cols
                          if self.engine is not None else None),
            # Type-bucketed state is a per-bucket tuple whose leaf shapes
            # depend on the bucket partition — a checkpoint from a
            # different tpu.bucketed resolution (or home mix) must start
            # fresh, not crash in the leaf-count/shape check.
            "buckets": ([[b["name"], b["n_slots"]]
                         for b in self.engine.bucket_info()]
                        if self.engine is not None and self.engine.bucketed
                        else None),
            "horizon": int(self.config["home"]["hems"]["prediction_horizon"]),
            # Scenario dimension (docs/architecture.md §15): the carry
            # gained the e_ev leaf (state_rev bump — pre-scenario
            # checkpoints have fewer leaves and must start fresh, not
            # crash load_pytree's leaf-count check), and an event
            # timeline changes step semantics (grid caps / shocks) even
            # at identical leaf shapes — keyed by a CONTENT digest of
            # the dense series, so magnitude-only schedule edits (cap
            # 3 kW → 1 kW) invalidate a resume too, not just window
            # count changes.
            "state_rev": 2,
            "events": (timeline_digest(getattr(self.engine, "_events",
                                               None))
                       if self.engine is not None else None),
            # Fleet RL agent-carry layout (ROADMAP item 1): the batched
            # carry's leaf structure depends on the policy layout
            # (shared vs per-community), the core (linear vs ddpg), and
            # the learner batch — a checkpoint written under one must
            # start fresh under another, not crash load_pytree's
            # leaf-count/shape check.
            "rl_fleet": self._rl_fleet_shape(),
            # Shard files are per-process; a checkpoint from a different
            # process topology must start fresh, not mis-assemble.
            "process_count": __import__("jax").process_count(),
        }

    def _rl_fleet_shape(self) -> list | None:
        """The fleet-RL checkpoint-shape key (None when no fleet RL case
        can run — single community, or RL cases disabled).  Besides the
        policy layout it carries every hyperparameter that SIZES a carry
        leaf: the DDPG MLP width (network/Adam pytrees), the linear
        core's critic count (θ_q columns), and the setpoint-tracker
        window (EnvCarry.tracker) — an edit to any of these must start
        fresh, not crash load_pytree's leaf-shape check."""
        sim = self.config["simulation"]
        if self.n_communities == 1 or not (
                sim.get("run_rl_agg", False)
                or sim.get("run_rl_simplified", False)):
            return None
        from dragg_tpu.rl.fleet import fleet_params_from_config

        fp = fleet_params_from_config(self.config, self.n_communities)
        p = self.config["rl"]["parameters"]
        kind = str(p.get("agent", "linear"))
        core_shape = (int(self.config.get("tpu", {}).get("ddpg_hidden", 64))
                      if kind == "ddpg"
                      else (2 if p.get("twin_q", True) else 1))
        prev_n = int(self.config["agg"].get("rl", {})
                     .get("prev_timesteps", 12))
        return [fp.policy, kind, fp.learner_batch, fp.gradient,
                bool(fp.event_features), core_shape, prev_n]

    def try_resume(self, template_state):
        """Restore (state, t) from the latest complete checkpoint if one
        exists and ``simulation.resume`` is enabled; else (template_state, 0).
        Sets ``self.resumed_from`` to the checkpoint directory so callers can
        restore their own extras (e.g. RL agent telemetry).

        Multi-host: process 0 decides (it owns progress.json) and the
        decision is BROADCAST so every process takes the same branch — a
        local filesystem check on each process would deadlock the next
        collective the first time the workers disagreed (advisor finding,
        ADVICE round 2).  Each process then loads its own shard file;
        per-shard validity is allgathered into one global go/no-go."""
        import jax

        from dragg_tpu.checkpoint import load_progress, load_pytree

        self.resumed_from = None
        if not self.config["simulation"].get("resume", False):
            return template_state, 0
        if jax.process_count() > 1:
            return self._try_resume_multiprocess(template_state)
        d = self._latest_checkpoint_dir()
        if d is None:
            return template_state, 0
        prog = load_progress(os.path.join(d, "progress.json"))
        want = self._run_shape()
        got = prog.get("run_shape")
        if got != want:
            self.log.logger.warning(
                f"Checkpoint {d} was written for run shape {got}, current "
                f"config is {want}; ignoring it and starting fresh."
            )
            return template_state, 0
        state = load_pytree(os.path.join(d, "state.npz"), template_state)
        self._restore_from_progress(d, prog)
        self.timestep = int(prog["timestep"])
        self.resumed_from = d
        self.log.logger.info(f"Resuming {self.case} from timestep {self.timestep}.")
        return state, self.timestep

    def _restore_from_progress(self, d: str, prog: dict,
                               include_tracker: bool = True) -> None:
        """Rank-0 host bookkeeping restore from a checkpoint dir — ONE body
        shared by the single- and multi-process resume paths so a new
        progress.json field cannot silently desynchronize them.
        ``include_tracker=False`` skips the setpoint-tracker fields (the
        multi-process path restores those on every rank via broadcast)."""
        from dragg_tpu.checkpoint import load_progress

        collected = load_progress(os.path.join(d, "collected.json"))
        for i, home in enumerate(self.all_homes):
            series = collected.get(home["name"])
            if not series or not self._home_selected(home):
                continue
            for key, values in series.items():
                if isinstance(values, list):
                    self.collector.import_series(key, i, values)
        self.baseline_agg_load_list = list(prog["baseline_agg_load_list"])
        self.all_rps = np.asarray(prog["all_rps"], dtype=np.float64)
        self.all_sps = np.asarray(prog["all_sps"], dtype=np.float64)
        self._solve_iters = list(prog["solve_iters"])
        if include_tracker and prog.get("tracked_loads") is not None:
            self.tracked_loads = list(prog["tracked_loads"])
            self.max_load = prog["max_load"]
            self.min_load = prog["min_load"]
        # Keep cumulative solve_time meaningful across the restart.
        self.start_time = time.time() - float(prog.get("elapsed", 0.0))  # dragg: disable=DT014, resume restores wall-clock elapsed accounting, not simulation state

    def _try_resume_multiprocess(self, template_state):
        """Deadlock-free multi-host resume over per-process shard files.

        Decision flow (all of it collective, so every process branches the
        same way): process 0 validates progress.json + run_shape and
        broadcasts the candidate timestep (−1 = start fresh); each process
        then checks ITS shard file (existence + stored timestep, catching a
        checkpoint torn by a mid-publish crash) and the verdicts are
        allgathered — any bad shard sends every process back to t=0."""
        import jax
        from jax.experimental import multihost_utils

        from dragg_tpu.checkpoint import (load_progress, load_pytree_local,
                                          shard_file_name)

        t_resume = -1
        prog = None
        if jax.process_index() == 0:
            d = self._latest_checkpoint_dir()
            if d is not None:
                try:
                    prog = load_progress(os.path.join(d, "progress.json"))
                    if prog.get("run_shape") == self._run_shape():
                        t_resume = int(prog["timestep"])
                    else:
                        self.log.logger.warning(
                            f"Checkpoint {d} run shape {prog.get('run_shape')} "
                            f"!= current {self._run_shape()}; starting fresh.")
                        prog = None
                except Exception as e:
                    self.log.logger.warning(
                        f"Checkpoint {d} unreadable ({e!r}); starting fresh.")
                    prog = None
        t_resume = int(multihost_utils.broadcast_one_to_all(
            np.asarray(t_resume, np.int32)))
        if t_resume < 0:
            return template_state, 0
        name = f"ckpt_t{t_resume:08d}"
        shard = os.path.join(self._checkpoint_root(), name,
                             shard_file_name(jax.process_index(),
                                             jax.process_count()))
        local_ok = False
        if os.path.isfile(shard):
            try:
                with np.load(shard) as data:
                    local_ok = int(data["__timestep__"]) == t_resume
            except Exception:
                local_ok = False
        all_ok = bool(np.all(multihost_utils.process_allgather(
            np.asarray(local_ok))))
        if not all_ok:
            self.log.logger.warning(
                f"Checkpoint {name}: shard missing/torn on some process "
                f"(local ok={local_ok}); all processes starting fresh.")
            return template_state, 0
        state = load_pytree_local(shard, template_state,
                                  expect_timestep=t_resume)
        # Host bookkeeping that every process needs to step identically
        # (reward prices feed the device chunks) travels by broadcast from
        # process 0; output-only fields (collector series, baseline list)
        # stay rank-0 — only rank 0 writes results.
        if prog is not None:
            rps = np.asarray(prog["all_rps"], dtype=np.float64)
            sps = np.asarray(prog["all_sps"], dtype=np.float64)
        else:
            rps = np.zeros(self.num_timesteps)
            sps = np.zeros(self.num_timesteps)
        self.all_rps = np.asarray(
            multihost_utils.broadcast_one_to_all(rps), dtype=np.float64)
        self.all_sps = np.asarray(
            multihost_utils.broadcast_one_to_all(sps), dtype=np.float64)
        # The setpoint tracker advances on EVERY process (gen_setpoint runs
        # inside _collect_chunk everywhere), so its host state must resume
        # consistently too: [present_flag, max_load, min_load, *tracked].
        prev_n = int(self.config["agg"].get("rl", {}).get("prev_timesteps", 12))
        tl = np.zeros(prev_n + 3)
        if prog is not None and prog.get("tracked_loads") is not None:
            tracked = list(prog["tracked_loads"])[:prev_n]
            tl[0] = 1.0
            tl[1] = float(prog["max_load"])
            tl[2] = float(prog["min_load"])
            tl[3:3 + len(tracked)] = tracked
        tl = np.asarray(multihost_utils.broadcast_one_to_all(tl))
        if tl[0] > 0:
            self.max_load = float(tl[1])
            self.min_load = float(tl[2])
            self.tracked_loads = [float(v) for v in tl[3:]]
        if jax.process_index() == 0 and prog is not None:
            self._restore_from_progress(
                os.path.join(self._checkpoint_root(), name), prog,
                include_tracker=False)
        self.timestep = t_resume
        self.resumed_from = os.path.join(self._checkpoint_root(), name)
        self.log.logger.info(
            f"Resuming {self.case} from timestep {t_resume} "
            f"(process {jax.process_index()}/{jax.process_count()}).")
        return state, self.timestep

    # ------------------------------------------------------------------ runs
    def run_baseline(self) -> None:
        """The baseline community simulation (dragg/aggregator.py:757-778):
        chunked device scans with checkpoint writes between chunks.

        Double-buffered host pipeline (round 12, ``fleet.pipeline`` —
        architecture.md §14): once chunk N's device scan completes, chunk
        N+1 is DISPATCHED (jax async dispatch) *before* chunk N's outputs
        are materialized, so all of chunk N's host work — numpy collect,
        observatory fold, checkpoint, telemetry — runs while the device
        executes N+1 instead of sitting on its critical path.  On
        accelerator backends the re-dispatch DONATES the carry, host-
        snapshotted first (checkpoint.host_snapshot — the snapshot
        doubles as the checkpoint payload and the forensics chunk-start
        state); CPU and multi-host runs keep non-donated carries (see the
        ``donate`` resolution below).  ``fleet.pipeline = false``
        restores the synchronous order (host work before the next
        dispatch) for overlap A/Bs."""
        horizon_h = self.config["home"]["hems"]["prediction_horizon"]
        self.log.logger.info(f"Performing baseline run for horizon: {horizon_h}")
        self.start_time = time.time()  # dragg: disable=DT014, wall-clock elapsed accounting for progress telemetry
        state, t = self.try_resume(self.engine.init_state())
        H = self.engine.params.horizon
        import jax

        from dragg_tpu.checkpoint import host_snapshot
        # Supervised-run instrumentation (dragg_tpu/resilience): progress
        # beats let the supervisor's stall detector distinguish a hung
        # device chunk from a slow one, and the fault site lets chaos
        # tests kill/hang this child deterministically mid-run.
        from dragg_tpu.resilience.faults import fault_hook
        from dragg_tpu.resilience.heartbeat import beat

        pipelined = bool(self.config.get("fleet", {}).get("pipeline", True))
        # Donation is an accelerator-HBM optimization ONLY: XLA:CPU runs
        # donated computations SYNCHRONOUSLY inside the dispatch call
        # (measured round 12: warm donated dispatch 2.1 s = the whole
        # chunk, vs 0.05 s async without donation — docs/perf_notes.md),
        # which would serialize the very overlap this pipeline exists
        # for; host RAM is not the constrained resource there.  Multi-
        # host runs also skip it (per-process checkpoint shards read the
        # device state's addressable blocks).
        from dragg_tpu.resilience.devices import default_platform

        donate = (pipelined and jax.process_count() == 1
                  and default_platform() != "cpu")

        def process(pend, after_state, overlapping):
            """Host work for one finished chunk: collect + telemetry +
            (mid-run) checkpoint of ``after_state``.  Under the pipeline
            this runs while the NEXT chunk executes on device; the
            overlap credit is a lower bound — granted only when that
            chunk is still provably running as the host window closes.
            The same probe stamps ``overlapping``'s earliest OBSERVED
            completion so its device span isn't inflated by THIS host
            window (see the loop-top device_s accounting)."""
            p_t0, p_ns, p_outs, device_s = (pend["t0"], pend["n_steps"],
                                            pend["outs"], pend["device_s"])
            p_start = pend["start_state"]
            self._phase_times["device_chunks"] += device_s
            self._chunk_state0 = p_start
            host_t0 = time.perf_counter()
            self._collect_chunk(p_outs, device_s=device_s)
            # "collect" keeps its pre-round-12 meaning (_collect_chunk
            # only) and is booked BEFORE write_outputs so a mid-run
            # results.json Summary already includes this chunk's value;
            # the overlap credit below covers the WHOLE host window
            # (collect + results + checkpoint).
            collect_s = time.perf_counter() - host_t0
            self._phase_times["collect"] += collect_s
            if self._telemetry_on:
                telemetry.observe("engine.collect_s", collect_s)
            # Mid-window completion probe: the checkpoint/results writes
            # below can dwarf the collect, so observing completion here
            # keeps the next chunk's device_s bound tight when the device
            # finished early (device_s is dispatch → earliest OBSERVED
            # completion — an upper bound at probe granularity).
            if overlapping is not None and overlapping["ready_at"] is None \
                    and _is_ready(overlapping["outs"].agg_load):
                overlapping["ready_at"] = time.perf_counter()
            end_t = p_t0 + p_ns
            beat({"timestep": end_t})
            if end_t < self.num_timesteps:
                self.log.logger.info("Creating a checkpoint file.")
                self.write_outputs()
                self.save_checkpoint(after_state)
            host_s = time.perf_counter() - host_t0
            if overlapping is not None:
                if overlapping["ready_at"] is not None:
                    pass  # completed mid-window; earliest stamp kept
                elif _is_ready(overlapping["outs"].agg_load):
                    # Completed during this host window — stamp the bound
                    # for its device_s; no overlap credit (lower bound).
                    overlapping["ready_at"] = time.perf_counter()
                else:
                    self._phase_times["overlap_hidden_s"] += host_s
                    if self._telemetry_on:
                        telemetry.observe("engine.overlap_hidden_s",
                                          host_s)

        chunks = 0
        # The chunk in flight (dict): t0/n_steps/outs/dispatched (the
        # dispatch stamp)/start_state (forensics)/device_s/ready_at (the
        # earliest time the chunk was OBSERVED complete — the overlap
        # probe stamps it so device_s is not inflated by host work that
        # ran after the device already finished).
        pending = None
        beat({"timestep": t})
        while True:
            dispatch = t < self.num_timesteps and (
                self.stop_after_chunks is None
                or chunks < self.stop_after_chunks)
            if not dispatch and pending is None:
                break
            if pending is not None:
                # Wait for the in-flight chunk BEFORE dispatching the
                # next: keeps the per-chunk device span honest (dispatch→
                # ready with an idle queue) and is required by donation
                # (the snapshot below must copy computed buffers).
                # device_s = dispatch → earliest OBSERVED completion: the
                # block-return time, unless the previous chunk's overlap
                # probe already saw this chunk finished DURING that host
                # window — then its (earlier) probe stamp is the bound,
                # so host work never pads the device span (review round
                # 12: on host-bound runs the raw dispatch→block wall
                # conflated the two and device_chunks + collect could
                # exceed total wall).
                jax.block_until_ready(pending["outs"].agg_load)
                done_t = pending["ready_at"] or time.perf_counter()
                pending["device_s"] = done_t - pending["dispatched"]
            # ``state`` is the carry AFTER the pending chunk — the
            # checkpoint payload once that chunk's host work runs.
            after_state = state
            if not pipelined and pending is not None:
                # Synchronous order (the pre-round-12 loop, kept for
                # overlap A/Bs): host work BEFORE the next dispatch.
                process(pending, after_state, overlapping=None)
                pending = None
            nxt = None
            if dispatch:
                n_steps = min(self.checkpoint_interval,
                              self.num_timesteps - t)
                rps = np.zeros((n_steps, H), dtype=np.float32)
                fault_hook("sim_chunk")
                if donate:
                    # Owning host copy of the carry — it must outlive the
                    # donated re-dispatch below (checkpoint payload +
                    # next chunk's forensics start state).
                    t_sn = time.perf_counter()
                    after_state = host_snapshot(state)
                    self._phase_times["state_snapshot"] += \
                        time.perf_counter() - t_sn
                # Stage-named beat BEFORE the chunk: the first chunk is
                # where the scan program compiles, so a supervised run
                # that stalls there is attributed to the compile, not a
                # slow simulation (the supervisor surfaces the last
                # payload on failure.*).
                beat({"stage": ("first_chunk(compile+execute)" if chunks == 0
                                else "chunk_execute"), "timestep": t})
                d0 = time.perf_counter()
                with self._maybe_profile(chunks):
                    state, outs = self.engine.run_chunk(state, t, rps,
                                                        donate=donate)
                    if self._profiling_chunk(chunks):
                        # Keep the traced chunk's execution inside the
                        # trace context (serializes this one chunk).
                        jax.block_until_ready(outs.agg_load)
                nxt = {"t0": t, "n_steps": n_steps, "outs": outs,
                       "dispatched": d0,
                       "start_state":
                           after_state if self._forensics_on else None,
                       "device_s": 0.0, "ready_at": None}
                t += n_steps
                chunks += 1
            if pending is not None:
                # Pipelined: the finished chunk's host work overlaps the
                # device execution of the chunk dispatched above.
                process(pending, after_state, overlapping=nxt)
            pending = nxt
        self._state = state
        if self.stop_after_chunks is not None and t < self.num_timesteps:
            self.log.logger.info(f"Stopping early after {chunks} chunks.")

    def _profile_dir(self) -> str:
        """The ONE resolution of the trace destination (env overrides
        config) — both the trace decision and the writer read it here so
        they can never disagree."""
        return os.environ.get(
            "JAX_PROFILE_DIR", self.config.get("tpu", {}).get("profile_dir", "")
        )

    def _profiling_chunk(self, chunk_idx: int) -> bool:
        """Whether ``_maybe_profile`` traces this chunk — the pipeline
        serializes exactly that chunk so its execution stays inside the
        trace context."""
        return bool(self._profile_dir()) and chunk_idx == 1

    def _maybe_profile(self, chunk_idx: int):
        """Profiler trace around one device chunk (SURVEY §5.1: the
        reference's only tracing is wall-clock solve_time;
        dragg/aggregator.py:765,788-799).  When ``tpu.profile_dir`` (or
        ``JAX_PROFILE_DIR``) is set, the SECOND chunk — the first is the
        compile — is traced for TensorBoard/xprof."""
        import contextlib

        if not self._profiling_chunk(chunk_idx):
            return contextlib.nullcontext()
        profile_dir = self._profile_dir()
        import jax

        self.log.logger.info(f"Writing profiler trace to {profile_dir}")
        return jax.profiler.trace(profile_dir)

    def check_baseline_vals(self) -> list[str]:
        """Result-shape check over the check_type-selected homes
        (dragg/aggregator.py:698-709).  The reference only logs failures;
        here they are also surfaced in ``Summary.check_errors`` so a shape
        bug at the end of a multi-hour run can't pass silently (round-1
        verdict, weak #8)."""
        errors: list[str] = []
        for i, home in enumerate(self.all_homes):
            if not self._home_selected(home):
                continue
            for k in self._home_keys(home):
                want = self.num_timesteps + 1 if k in ("temp_in_opt", "temp_wh_opt", "e_batt_opt") else self.num_timesteps
                got = self.collector.length(k, i)
                if got != want:
                    msg = f"Incorrect number of hours. {home['name']}: {k} {got}"
                    self.log.logger.error(msg)
                    errors.append(msg)
        if errors:
            self.extra_summary["check_errors"] = errors
        return errors

    # --------------------------------------------------------------- outputs
    def set_run_dir(self) -> None:
        """Reference directory layout (dragg/aggregator.py:818-829) via the
        shared name builder (dragg_tpu.utils.layout) that Reformat's
        discovery also uses."""
        from dragg_tpu.utils import date_folder_name, run_dir_name

        cfg = self.config
        self.run_dir = os.path.join(
            self.outputs_dir,
            date_folder_name(self.start_dt, self.end_dt),
            run_dir_name(
                self.check_type,
                cfg["community"]["total_number_homes"],
                cfg["home"]["hems"]["prediction_horizon"],
                self.dt,
                int(cfg["home"]["hems"]["sub_subhourly_steps"]),
                configured_solver(cfg),
            ),
            f"version-{self.version}",
        )
        os.makedirs(self.run_dir, exist_ok=True)

    def summarize_baseline(self) -> dict:
        """Build the Summary block (dragg/aggregator.py:783-816)."""
        self.end_time = time.time()  # dragg: disable=DT014, wall-clock elapsed accounting for progress telemetry
        t_diff = self.end_time - self.start_time
        cfg = self.config
        sim_slice = slice(self.start_index, self.start_index + self.num_timesteps)
        self.max_agg_load = max(self.baseline_agg_load_list) if self.baseline_agg_load_list else 0.0
        summary = {
            "case": self.case,
            "start_datetime": self.start_dt.strftime("%Y-%m-%d %H"),
            "end_datetime": self.end_dt.strftime("%Y-%m-%d %H"),
            "solve_time": t_diff,
            "horizon": cfg["home"]["hems"]["prediction_horizon"],
            "num_homes": cfg["community"]["total_number_homes"],
            "p_max_aggregate": self.max_agg_load,
            "p_grid_aggregate": list(self.baseline_agg_load_list),
            "OAT": self.env.oat[sim_slice].tolist(),
            "GHI": self.env.ghi[sim_slice].tolist(),
            "RP": self.all_rps.tolist(),
            "p_grid_setpoint": self.all_sps.tolist(),
            # dragg_tpu extras (additive; Reformat ignores unknown keys).
            "solver_iterations": list(self._solve_iters),
            "phase_times": {k: round(v, 3) for k, v in
                            getattr(self, "_phase_times", {}).items()},
        }
        if self.n_communities > 1:
            summary["fleet"] = {
                "communities": self.n_communities,
                "homes_per_community":
                    int(cfg["community"]["total_number_homes"]),
                "homes_total": self.total_homes,
                "seed_stride": self._fleet_seed_stride,
                "weather_offset_hours": self._fleet_weather_off_h,
            }
            summary["num_homes"] = self.total_homes
        # The reference wraps the price series in a 1-tuple — a trailing-comma
        # bug (dragg/aggregator.py:814-816) we do NOT reproduce.
        summary["TOU"] = self.env.tou[sim_slice].tolist()
        summary.update(self.extra_summary)
        return summary

    def _results_plan(self, summary: dict | None) -> list[tuple]:
        """Build the streaming write plan for results.json: raw JSON
        fragments for structure/static fields, series references for the
        hot numeric arrays (expanded by the native writer)."""
        plan: list[tuple] = [("raw", "{")]
        first = True
        if self.all_homes:
            for i, home in enumerate(self.all_homes):
                if not first:
                    plan.append(("raw", ", "))
                first = False
                statics = self._home_static[home["name"]]
                frag = json.dumps(home["name"]) + ": {"
                frag += ", ".join(
                    f"{json.dumps(k)}: {json.dumps(v)}" for k, v in statics.items()
                )
                plan.append(("raw", frag))
                selected = self._home_selected(home)
                for key in self._home_keys(home):
                    plan.append(("raw", f", {json.dumps(key)}: "))
                    if selected:
                        plan.append(("series", key, i))
                    elif key == "temp_in_opt":
                        plan.append(("raw", json.dumps([home["hvac"]["temp_in_init"]])))
                    elif key == "temp_wh_opt":
                        plan.append(("raw", json.dumps([home["wh"]["temp_wh_init"]])))
                    elif key == "e_batt_opt":
                        plan.append(("raw", json.dumps([home["battery"]["e_batt_init"]])))
                    else:
                        plan.append(("raw", "[]"))
                plan.append(("raw", "}"))
        if summary is not None:
            if not first:
                plan.append(("raw", ", "))
            plan.append(("raw", '"Summary": ' + json.dumps(summary)))
        plan.append(("raw", "}"))
        return plan

    def write_outputs(self) -> None:
        """Serialize per-home series + Summary → <run_dir>/<case>/results.json
        (dragg/aggregator.py:831-844), streamed by the native writer.
        Multi-host: every process holds identical collected series (the
        chunk gathers are collectives); only process 0 writes."""
        import jax

        if jax.process_index() != 0:
            return
        summary = self.summarize_baseline()
        case_dir = os.path.join(self.run_dir, self.case)
        os.makedirs(case_dir, exist_ok=True)
        path = os.path.join(case_dir, "results.json")
        include_homes = self.all_homes is not None and not self.summary_only_case
        if include_homes:
            self.collector.write_json(path, self._results_plan(summary))
        else:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"Summary": summary}, f, indent=4)
            os.replace(tmp, path)

    # ------------------------------------------------------------------- run
    def _checkpoint_steps(self) -> int:
        """hourly/daily/weekly → timesteps (dragg/aggregator.py:949-955)."""
        interval = self.config["simulation"].get("checkpoint_interval", "daily")
        return {
            "hourly": self.dt,
            "daily": self.dt * 24,
            "weekly": self.dt * 24 * 7,
        }.get(interval, 500)

    def _telemetry_open(self) -> bool:
        """Open the run-scoped telemetry bus (``<run_dir>/events.jsonl``
        + in-memory metrics — dragg_tpu/telemetry) on process 0.  The
        destination resolves config ``telemetry.dir`` →
        ``$DRAGG_TELEMETRY_DIR`` (a supervising parent exports it so the
        child's events land on the SAME stream as the supervisor's) →
        the run directory."""
        from dragg_tpu.config import default_config

        tcfg = {**default_config()["telemetry"],
                **self.config.get("telemetry", {})}
        import jax

        if not tcfg["enabled"] or jax.process_index() != 0:
            return False
        self._forensics_on = bool(tcfg.get("forensics", False))
        tdir = tcfg["dir"] or os.environ.get(telemetry.ENV_DIR) \
            or self.run_dir
        telemetry.init_run(tdir)
        cfg = self.config
        telemetry.emit(
            "run.start",
            case=self.case,
            homes=cfg["community"]["total_number_homes"],
            horizon=cfg["home"]["hems"]["prediction_horizon"],
            solver=configured_solver(cfg),
            run_dir=self.run_dir,
        )
        return True

    def _telemetry_close(self, t0: float) -> None:
        telemetry.emit(
            "run.end",
            timestep=self.timestep,
            num_timesteps=self.num_timesteps,
            elapsed_s=round(time.time() - t0, 3),  # dragg: disable=DT014, wall-clock elapsed for the run summary, not simulation state
            completed=self.timestep >= self.num_timesteps,
        )
        telemetry.write_snapshot()
        telemetry.close_run()

    def run(self) -> None:
        """Entry point (dragg/aggregator.py:941-970)."""
        self.log.logger.info("Made it to Aggregator Run")
        self.checkpoint_interval = self._checkpoint_steps()
        self.version = self.config["simulation"].get("named_version", "test")
        self.set_run_dir()
        self._telemetry_on = self._telemetry_open()
        t_run0 = time.time()  # dragg: disable=DT014, wall-clock elapsed for the run summary, not simulation state
        try:
            self._run_cases()
        finally:
            if self._telemetry_on:
                self._telemetry_close(t_run0)
                self._telemetry_on = False

    def _run_cases(self) -> None:
        """The enabled simulation cases, in reference order.

        Fleet RL (ROADMAP item 1, shipped): ``fleet.communities > 1``
        with an RL case enabled routes through the vectorized fleet
        trainer (dragg_tpu/rl/fleet) — each community's agent stream
        announces its OWN reward price and sees its OWN per-community
        aggregate (never a silently-merged fleet total); the rl/runner
        entry points dispatch on ``n_communities``."""
        if self.config["simulation"].get("run_rbo_mpc", True):
            self.case = "baseline"
            self.get_homes()
            self._build_engine()
            self.reset_collected_data()
            self.run_baseline()
            if self.timestep >= self.num_timesteps:
                self.check_baseline_vals()
                self.write_outputs()
                self.clear_checkpoint()
            else:
                # Stopped early at a checkpoint boundary — results.json and
                # the resume checkpoint were already written there.  Behave
                # like a kill: do not fall through to the RL cases.
                return
        if self.config["simulation"].get("run_rl_agg", False):
            from dragg_tpu.rl.runner import run_rl_agg

            run_rl_agg(self)
            if self.timestep < self.num_timesteps:
                return  # halted at a checkpoint boundary (see above)
        if self.config["simulation"].get("run_rl_simplified", False):
            from dragg_tpu.rl.runner import run_rl_simplified

            run_rl_simplified(self)
