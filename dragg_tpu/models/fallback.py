"""Vectorized rule-based fallback controller.

Capability parity with the reference's infeasibility recovery
(dragg/mpc_calc.py:527-596): when a home's MPC solve fails, (i) replay the
last feasible plan shifted by ``solve_counter`` and patch it bang-bang where
the simulated temperatures would violate bounds, else (ii) pure bang-bang
keyed on the current thermal state.  This controller doubles as the
horizon-0 "no-MPC" mode.

Implemented as a branch-free batched function (every home evaluates both
paths; ``jnp.where`` selects), so it composes with ``vmap``/``pjit`` and
runs inside the jitted engine step — the reference handles this per-home
imperatively (SURVEY.md §5.3).

Unit note: duties here are raw counts in [0, s].  The reference's replay
path reads back the *stored* (duty/s) value and multiplies by the per-step
power P/s, under-heating replayed steps by a factor of s
(dragg/mpc_calc.py:537-547 vs :342); we use consistent raw-duty units
throughout instead of replicating that inconsistency.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from dragg_tpu.models.thermal import hvac_step, wh_step


class FallbackResult(NamedTuple):
    cool_on: jnp.ndarray   # raw duty [0, s]
    heat_on: jnp.ndarray
    wh_on: jnp.ndarray
    temp_in: jnp.ndarray   # simulated next indoor temp
    temp_wh: jnp.ndarray   # simulated next WH temp
    counter: jnp.ndarray   # updated solve_counter


def fallback_control(
    counter,            # (n,) previous solve_counter (int) — already for this failure: counter_prev + 1
    timestep,           # scalar int
    horizon: int,
    replay_cool,        # (n,) raw-duty plan value at index `counter` of the last feasible plan
    replay_heat,
    replay_wh,
    temp_in_init,       # (n,)
    temp_wh_init,       # (n,) (after draw mixing)
    oat1,               # scalar or (n,) OAT at step t+1
    hvac_r, hvac_c, hvac_p_c, hvac_p_h,
    wh_r, wh_c, wh_p,
    temp_in_min, temp_in_max, temp_wh_min, temp_wh_max,
    cool_max, heat_max, wh_max,  # (n,) seasonal duty caps (0 or s)
    dt: int,
) -> FallbackResult:
    """Compute fallback duties + simulated temps for every home.

    The caller increments ``counter`` before the call (reference increments
    at dragg/mpc_calc.py:529) and applies the result only where the solve
    failed.
    """
    zero = jnp.zeros_like(temp_in_init)

    # --- Path A: replay last feasible plan, shifted (dragg/mpc_calc.py:533-557).
    replay_ok = (counter < horizon) & (timestep > 0)
    a_cool, a_heat, a_wh = replay_cool, replay_heat, replay_wh
    t_in_a = hvac_step(temp_in_init, oat1, hvac_r, hvac_c, dt, a_cool, a_heat, hvac_p_c, hvac_p_h)
    t_wh_a = wh_step(temp_wh_init, t_in_a, wh_r, wh_c, dt, a_wh, wh_p)
    too_hot = t_in_a > temp_in_max
    too_cold = t_in_a < temp_in_min
    a_heat = jnp.where(too_hot, zero, jnp.where(too_cold, heat_max, a_heat))
    a_cool = jnp.where(too_hot, cool_max, jnp.where(too_cold, zero, a_cool))
    a_wh = jnp.where(t_wh_a < temp_wh_min, wh_max, a_wh)

    # --- Path B: pure bang-bang on current state (dragg/mpc_calc.py:559-574).
    hot0 = temp_in_init > temp_in_max
    cold0 = temp_in_init < temp_in_min
    b_heat = jnp.where(cold0, heat_max, zero)
    b_cool = jnp.where(hot0, cool_max, zero)
    b_wh = jnp.where(temp_wh_init < temp_wh_min, wh_max, zero)
    counter_b = jnp.maximum(counter, horizon)

    cool = jnp.where(replay_ok, a_cool, b_cool)
    heat = jnp.where(replay_ok, a_heat, b_heat)
    wh = jnp.where(replay_ok, a_wh, b_wh)
    new_counter = jnp.where(replay_ok, counter, counter_b)

    # Final forward simulation with the chosen duties (dragg/mpc_calc.py:576-582).
    new_temp_in = hvac_step(temp_in_init, oat1, hvac_r, hvac_c, dt, cool, heat, hvac_p_c, hvac_p_h)
    new_temp_wh = wh_step(temp_wh_init, new_temp_in, wh_r, wh_c, dt, wh, wh_p)

    return FallbackResult(
        cool_on=cool, heat_on=heat, wh_on=wh,
        temp_in=new_temp_in, temp_wh=new_temp_wh, counter=new_counter,
    )
