"""Battery state-of-charge dynamics (dragg/mpc_calc.py:363-372)."""

from __future__ import annotations


def battery_step(e_batt, p_ch, p_disch, ch_eff, disch_eff, dt):
    """E' = E + (eta_ch * p_ch + p_disch / eta_disch) / dt.

    ``p_disch`` is non-positive by convention (dragg/mpc_calc.py:369-370).
    """
    return e_batt + (ch_eff * p_ch + p_disch / disch_eff) / dt
