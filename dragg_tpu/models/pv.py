"""PV generation model (dragg/mpc_calc.py:380-385)."""

from __future__ import annotations


def pv_power(ghi, area, eff, u_curt):
    """p_pv = area * eff * GHI * (1 - u_curt) / 1000  [kW], GHI in W/m2."""
    return area * eff * ghi * (1.0 - u_curt) / 1000.0
