"""RC thermal dynamics for HVAC and water heater — pure JAX, batchable.

These are the update equations of the reference MPC constraints
(dragg/mpc_calc.py:313-342) and of its fallback simulator
(dragg/mpc_calc.py:541-582), written once as vectorized functions so the QP
builder, the fallback controller, and the unit tests all share them.

Units follow the reference: R in degC/kW, C in kJ/degC (the home dict's
``c`` × 1000), powers in kW per sub-subhourly step (total power / s), dt in
steps-per-hour, duties are raw counts in [0, s].
"""

from __future__ import annotations

import jax.numpy as jnp


def hvac_step(temp_in, oat_next, hvac_r, hvac_c, dt, cool_on, heat_on, p_c, p_h):
    """One indoor-temperature RC step (dragg/mpc_calc.py:313-317).

    T' = T + 3600 * ((OAT - T)/R - cool*Pc + heat*Ph) / (C * dt)
    """
    return temp_in + 3600.0 * (
        (oat_next - temp_in) / hvac_r - cool_on * p_c + heat_on * p_h
    ) / (hvac_c * dt)


def wh_mix(temp_wh, draw, tank_size, tap_temp=15.0):
    """Water-draw mixing (dragg/mpc_calc.py:271,281):
    T' = (T*(size - draw) + tap*draw) / size.  tap_temp=15 degC as in the
    reference (dragg/mpc_calc.py:181)."""
    return (temp_wh * (tank_size - draw) + tap_temp * draw) / tank_size


def wh_step(temp_wh, temp_in_next, wh_r, wh_c, dt, wh_on, wh_p):
    """One water-heater RC step (dragg/mpc_calc.py:336-338):
    T' = T + 3600 * ((Tin - T)/Rwh + wh*Pwh) / (Cwh * dt)
    """
    return temp_wh + 3600.0 * (
        (temp_in_next - temp_wh) / wh_r + wh_on * wh_p
    ) / (wh_c * dt)


def wh_traj_step(temp_wh, temp_in_next, frac, wh_r, wh_c, dt, wh_on, wh_p, tap_temp=15.0):
    """One step of the *trajectory* WH constraint with in-step draw mixing
    (dragg/mpc_calc.py:330-332): the mixed temperature
    M = (1-frac)*T + frac*tap replaces T in the RC update."""
    mixed = (1.0 - frac) * temp_wh + frac * tap_temp
    return mixed + 3600.0 * ((temp_in_next - mixed) / wh_r + wh_on * wh_p) / (wh_c * dt)


def expand_draws(window_hourly, dt: int, horizon: int):
    """Expand an hourly draw window to the subhourly horizon grid.

    Reproduces the reference's ``water_draws`` (dragg/mpc_calc.py:193-201):
    the hourly window (length horizon//dt + 1) is repeated dt times and
    divided by dt; the first dt entries are used as-is and entries at index
    i >= dt are the mean of raw[i-1 : i+2] (a shorter window at the array
    end).  Returns draw sizes of length horizon + 1.

    ``window_hourly`` may be batched with leading dims; expansion applies to
    the last axis.
    """
    raw = jnp.repeat(window_hourly, dt, axis=-1) / dt  # (..., horizon + dt)
    n_raw = raw.shape[-1]
    h_plus = horizon + 1
    idx = jnp.arange(h_plus)
    # Rolling mean of raw[i-1:i+2] with edge truncation, matching
    # np.average over a python slice.
    prev_ok = (idx - 1 >= 0).astype(raw.dtype)
    next_ok = (idx + 1 < n_raw).astype(raw.dtype)
    take = lambda off: jnp.take(raw, jnp.clip(idx + off, 0, n_raw - 1), axis=-1)
    rolled = (take(-1) * prev_ok + take(0) + take(1) * next_ok) / (prev_ok + 1.0 + next_ok)
    direct = jnp.take(raw, jnp.minimum(idx, n_raw - 1), axis=-1)
    return jnp.where(idx < dt, direct, rolled)
