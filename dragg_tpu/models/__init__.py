from dragg_tpu.models.thermal import hvac_step, wh_mix, wh_step, expand_draws  # noqa: F401
from dragg_tpu.models.battery import battery_step  # noqa: F401
from dragg_tpu.models.pv import pv_power  # noqa: F401
from dragg_tpu.models.fallback import fallback_control, FallbackResult  # noqa: F401
