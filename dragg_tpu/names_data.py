"""First-name pool for home naming.

The reference names homes ``names.get_first_name() + '-' + 5charsuffix``
(dragg/aggregator.py:396-397) via the third-party ``names`` package.  We
embed a small name pool instead; names are decorative identifiers, and the
seeded *parameter* streams (numpy) are what determine behavioral parity.
"""

FIRST_NAMES = [
    "Alice", "Alvin", "Amara", "Andre", "Anita", "Anthony", "April", "Arjun",
    "Astrid", "Avery", "Bianca", "Boris", "Brandon", "Bridget", "Bruno",
    "Camille", "Carlos", "Carmen", "Cedric", "Celia", "Chidi", "Clara",
    "Cormac", "Crystal", "Dahlia", "Damon", "Daniela", "Darius", "Dawn",
    "Declan", "Delia", "Dennis", "Dorothy", "Edgar", "Elena", "Elias",
    "Elsa", "Emeka", "Emil", "Erin", "Esme", "Ethan", "Farah", "Felix",
    "Fiona", "Floyd", "Freya", "Gary", "Gemma", "Gideon", "Gloria", "Grant",
    "Greta", "Hana", "Harvey", "Hazel", "Hector", "Helga", "Hugo", "Ian",
    "Ida", "Igor", "Imani", "Ingrid", "Irene", "Isaac", "Ivan", "Jada",
    "Jason", "Javier", "Jerome", "Joan", "Jonah", "Joyce", "Juan", "Judith",
    "Kai", "Kara", "Keiko", "Kelvin", "Kendra", "Kofi", "Kurt", "Laila",
    "Lars", "Laura", "Leif", "Lena", "Leo", "Lillie", "Linus", "Lorenzo",
    "Lucia", "Luther", "Mabel", "Magnus", "Maeve", "Marcus", "Margot",
    "Mariana", "Marvin", "Matilda", "Maya", "Mehmet", "Mei", "Milan",
    "Milo", "Mina", "Miriam", "Mohammed", "Myles", "Nadia", "Naomi",
    "Nathan", "Nelly", "Nestor", "Nia", "Nikolai", "Nina", "Noel", "Nora",
    "Odessa", "Olaf", "Olive", "Omar", "Oscar", "Otis", "Paige", "Pablo",
    "Pearl", "Pedro", "Petra", "Philip", "Priya", "Quentin", "Quinn",
    "Rafael", "Ramona", "Randall", "Raquel", "Ravi", "Regina", "Rhea",
    "Robert", "Rocco", "Rosa", "Rowan", "Ruby", "Rufus", "Sadie", "Salma",
    "Samuel", "Sanjay", "Saoirse", "Sasha", "Selene", "Serena", "Seth",
    "Shirley", "Silas", "Simone", "Sofia", "Soren", "Stella", "Sven",
    "Tamar", "Tariq", "Tessa", "Theo", "Thora", "Tobias", "Trudy", "Uma",
    "Ursula", "Valerie", "Vera", "Victor", "Vikram", "Viola", "Wade",
    "Walter", "Wanda", "Wendell", "Willa", "Xander", "Ximena", "Yara",
    "Yusuf", "Yvette", "Zainab", "Zelda", "Zora",
]
