"""Coordinator CLI: ``python -m dragg_tpu.shard --run-dir D --steps T``.

Runs (or RESUMES — the run dir is the durable state) a sharded fleet
baseline and prints the merged result as one JSON line.  This parent is
jax-free by contract; all device work happens in the supervised shard
workers.  Kill it with -9 and run the same command again: the journal
replays to the exact chunk frontier (tests/test_shard.py pins it).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dragg_tpu.shard")
    ap.add_argument("--config", default=None,
                    help="TOML config path (default: defaults + flags)")
    ap.add_argument("--run-dir", required=True,
                    help="journal + spool directory (durable; calling "
                         "again resumes)")
    ap.add_argument("--steps", type=int, required=True)
    ap.add_argument("--chunk", type=int, default=None,
                    help="shard.chunk_steps override")
    ap.add_argument("--workers", type=int, default=None,
                    help="shard.workers override")
    ap.add_argument("--communities", type=int, default=None,
                    help="fleet.communities override")
    ap.add_argument("--homes", type=int, default=None,
                    help="community.total_number_homes override")
    ap.add_argument("--stop-t", type=int, default=None,
                    help="quiesce every shard at this chunk boundary "
                         "(the reshard barrier); resume without it to "
                         "finish")
    ap.add_argument("--platform", choices=["auto", "tpu", "cpu"],
                    default="auto")
    ap.add_argument("--data-dir", default=None)
    args = ap.parse_args(argv)

    from dragg_tpu.config import load_config
    from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax
    from dragg_tpu.shard.coordinator import run_sharded

    assert_parent_has_no_jax()
    config = load_config(args.config)
    if args.communities is not None:
        config.setdefault("fleet", {})["communities"] = args.communities
    if args.homes is not None:
        config["community"]["total_number_homes"] = args.homes
    result = run_sharded(
        config, run_dir=args.run_dir, steps=args.steps,
        workers=args.workers, chunk_steps=args.chunk,
        platform=args.platform, data_dir=args.data_dir,
        stop_t=args.stop_t,
        log=lambda m: print(f"[shard] {m}", file=sys.stderr, flush=True))
    print(json.dumps(result))
    return 0 if result["ok"] or result["stopped_early"] else 1


if __name__ == "__main__":
    sys.exit(main())
