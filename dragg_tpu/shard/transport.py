"""Networked shard transport: crash-safe chunk exchange over TCP.

``shard.transport = "tcp"`` (docs/config.md ``[shard]``) replaces the
shared-disk chunk exchange with a wire, keeping every durability
invariant the spool already proves (architecture.md §20):

* the coordinator runs :class:`ChunkIngestServer` — a jax-free HTTP
  server whose ``POST /chunk`` handler persists the pushed payload to
  the SAME retained spool outbox file the shared-disk path uses, then
  fsync's the chunk ack into the coordinator's journal
  (shard/journal.py) **before** the 200 — the serve daemon's
  journal-before-ack discipline, so once a worker sees the ack the
  payload of record is durable on the coordinator's disk and the worker
  needs no local copy;
* workers push length-prefixed, checksummed frames (shard/wire.py) with
  **at-least-once delivery**: :class:`WireClient` retries through a
  bounded exponential backoff (resilience.liveness.backoff_delays
  schedule) with a per-operation deadline on every socket op
  (resilience.net discipline), and the server dedups by the
  ``(epoch, shard, chunk)`` token — a duplicate is acked without
  re-merge or re-journal, so a lost ack never double-merges;
* **epoch fencing over the wire**: a push carrying a stale epoch token
  is refused with 409 naming the stale token (mirroring the round-18
  spool EPOCH fence) — :class:`EpochFenced` makes the orphan worker
  exit at the chunk boundary exactly like the file fence does;
* **graceful degradation**: when both ends share a disk and the wire
  stays down past ``shard.transport_retry_s``, the client falls back to
  writing the spool outbox file directly (first-write-wins, exactly the
  round-18 path) and stays degraded — the coordinator's drain loop
  merges spool files and wire-ingested files identically;
* **params flow the other way** on the same wire: ``GET /params`` is a
  long-poll the worker drains at each chunk boundary
  (:meth:`ChunkIngestServer.publish_params` → ``stop_t`` today; the
  learner broadcast of ROADMAP item 3 rides this channel).

Chaos sites (``$DRAGG_FAULT_INJECT`` — resilience/faults.py SITES):
``wire_send`` (torn = truncated frame), ``wire_partition`` (cut =
connection severed mid-frame), ``wire_ack`` (drop = ack lost after
merge+journal).  All three are deterministic and covered by
tests/test_shard.py; ``doctor --shard-check`` additionally sweeps a
torn frame at every byte boundary against a live server.

Stdlib only; never imports jax (the coordinator side runs inside the
jax-free parent — resilience.supervisor contract).
"""

from __future__ import annotations

import json
import threading
import time
from http.client import HTTPConnection, HTTPException
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from dragg_tpu import telemetry
from dragg_tpu.resilience.faults import WireFault, fault_hook
from dragg_tpu.resilience.liveness import backoff_delays
from dragg_tpu.resilience.net import connect_deadline, parse_endpoint
from dragg_tpu.serve import spool as sp
from dragg_tpu.shard import wire

# Per-connection deadline on every server-side socket op (the handler's
# reads/writes inherit it — BaseHTTPRequestHandler.timeout).
SERVER_OP_TIMEOUT_S = 30.0
CLIENT_OP_TIMEOUT_S = 10.0


class EpochFenced(RuntimeError):
    """The server refused a push from a fenced (stale-epoch) orphan."""

    def __init__(self, stale: str, current: str, shard: int, seq: int):
        super().__init__(
            f"chunk push fenced: stale epoch token "
            f"{wire.chunk_token(stale, shard, seq)!r} — the run is owned "
            f"by epoch {current!r} (orphan of a dead coordinator; exit at "
            f"the chunk boundary, spool-fence semantics)")
        self.stale = stale
        self.current = current


class _Handler(BaseHTTPRequestHandler):
    server_version = "dragg-wire/1"
    protocol_version = "HTTP/1.1"
    timeout = SERVER_OP_TIMEOUT_S

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # the telemetry stream is the log of record

    def _reply(self, status: int, doc: dict) -> None:
        body = json.dumps(doc).encode("utf-8")
        self._reply_bytes(status, "application/json", body)

    def _reply_bytes(self, status: int, ctype: str, body: bytes) -> None:
        try:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            # The peer vanished mid-reply (a severed connection is a
            # chaos-site behavior, not a server fault) — the client's
            # at-least-once retry is the recovery path, not this write.
            self.close_connection = True

    # ------------------------------------------------------------ chunk push
    def do_POST(self) -> None:
        owner: ChunkIngestServer = self.server.owner
        if self.path != "/chunk":
            self._reply(404, {"error": f"no such endpoint {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = -1
        if length <= 0 or length > wire.MAX_FRAME_BYTES:
            self._reply(400, {"error": f"bad Content-Length {length}"})
            return
        try:
            data = self.rfile.read(length)
        except OSError:
            # Partition mid-body: nothing decoded, nothing changed.
            self.close_connection = True
            return
        try:
            doc = wire.decode_frame(data)
            shard = int(doc["shard"])
            seq = int(doc["seq"])
            epoch = str(doc["epoch"])
            payload = doc["payload"]
            if not isinstance(payload, dict) \
                    or int(payload.get("seq", -1)) != seq:
                raise wire.TornFrame("payload/seq mismatch")
        except (wire.TornFrame, KeyError, TypeError, ValueError) as e:
            # A torn/foreign frame is DISCARDED whole (the wire analog of
            # the spool's atomic rename): no state changed, the client's
            # at-least-once retry re-sends the complete frame.
            telemetry.emit("wire.reject", reason=str(e), bytes=len(data))
            self._reply(400, {"error": "torn frame", "detail": str(e)})
            return
        if epoch != owner.epoch:
            telemetry.emit("wire.fence", shard=shard, seq=seq,
                           got=epoch, want=owner.epoch)
            self._reply(409, {
                "error": "stale epoch",
                "token": wire.chunk_token(epoch, shard, seq),
                "got": epoch, "want": owner.epoch})
            return
        dup = owner.ingest(shard, seq, payload)
        # Server-side ingest span, parented on the chunk span that rode
        # the frame body (worker.py) — the wire hop stays one causal
        # chain.  No fields when the coordinator isn't tracing.
        telemetry.emit("wire.ingest", shard=shard, seq=seq, dup=dup,
                       bytes=length,
                       **telemetry.trace.child_fields(
                           parent=payload.get("trace_span")))
        try:
            fault_hook("wire_ack")
        except WireFault:
            # Ack lost AFTER merge+journal: sever without responding.
            # The client's retry hits the dedup token and is acked
            # without re-merge — the invariant this site exists to test.
            self.close_connection = True
            return
        self._reply(200, {"ok": True, "dup": dup})

    # --------------------------------------------------------- params pull
    def do_GET(self) -> None:
        owner: ChunkIngestServer = self.server.owner
        url = urlparse(self.path)
        if url.path == "/ping":
            self._reply(200, {"ok": True, "epoch": owner.epoch})
            return
        if url.path == "/clock":
            # Clock-skew handshake (ISSUE 20): the wire client brackets
            # this call and derives its wall-clock offset against the
            # coordinator — merged ordering's honesty correction for
            # the multi-host future.
            self._reply(200, {"ok": True, "t": time.time(),  # dragg: disable=DT014, the handshake MEASURES wall clocks — that is the payload
                              "epoch": owner.epoch})
            return
        if url.path in ("/rollup.json", "/metrics"):
            from dragg_tpu.telemetry import rollup as rollup_mod

            run_dir = owner.run_dir or telemetry.run_dir()
            if not run_dir:
                self._reply(404, {"error": "no telemetry run dir"})
                return
            roll = rollup_mod.fold_rollup(run_dir)
            if url.path == "/rollup.json":
                self._reply(200, roll)
            else:
                self._reply_bytes(
                    200, "text/plain; version=0.0.4",
                    rollup_mod.prometheus_text(roll).encode("utf-8"))
            return
        if url.path != "/params":
            self._reply(404, {"error": f"no such endpoint {url.path}"})
            return
        q = parse_qs(url.query)
        try:
            shard = int(q.get("shard", ["0"])[0])
            have = int(q.get("have", ["0"])[0])
            wait_s = min(float(q.get("wait", ["0"])[0]),
                         SERVER_OP_TIMEOUT_S / 2)
        except ValueError:
            self._reply(400, {"error": "bad query"})
            return
        version, params = owner.wait_params(shard, have, wait_s)
        self._reply(200, {"version": version, "params": params})


class ChunkIngestServer:
    """Coordinator-side chunk ingest + params broadcast (one per run).

    Construction seeds the dedup token set from the journal's acked
    frontier AND the retained spool chunk files, so the at-least-once
    token survives a transport restart: a duplicate ``(epoch, shard,
    chunk)`` push after the server process bounced is still acked as a
    duplicate, never re-merged (``doctor --shard-check`` pins this)."""

    def __init__(self, spool_dir: str, journal, epoch: str, *,
                 listen: str = "127.0.0.1:0", run_dir: str | None = None,
                 log=None):
        self.spool_dir = spool_dir
        self.journal = journal
        self.epoch = epoch
        # Telemetry run dir backing /rollup.json + /metrics (falls back
        # to the process bus's dir at request time when None).
        self.run_dir = run_dir
        self.log = log
        self._lock = threading.Lock()
        self._params_cv = threading.Condition(self._lock)
        self._params: dict[int, tuple[int, dict]] = {}
        self._seen: set[tuple[int, int]] = set()   # payload durable
        self._acked: set[tuple[int, int]] = set()  # journaled at ingest
        # Transport-restart dedup seed: journal acks + retained files.
        from dragg_tpu.shard import journal as sj

        rep = sj.replay(journal.path)
        for k, seqs in rep.acked.items():
            self._seen.update((int(k), int(s)) for s in seqs)
        for k, _dir in _shard_outboxes(spool_dir):
            for seq, _path in sp.list_chunks(_dir):
                self._seen.add((k, seq))
        host, port = parse_endpoint(listen)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.owner = self
        self.endpoint = (f"{self._httpd.server_address[0]}"
                         f":{self._httpd.server_address[1]}")
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.1),
            name="dragg-wire-ingest", daemon=True)
        self._thread.start()
        if self.log:
            self.log(f"wire: chunk-ingest server on {self.endpoint} "
                     f"(epoch {self.epoch})")

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -------------------------------------------------------------- ingest
    def ingest(self, shard: int, seq: int, payload: dict) -> bool:
        """Persist + journal-ack one pushed chunk; returns True when it
        was a duplicate (acked without re-merge).  Journal-before-ack:
        the spool file write (fsync'd atomic rename) and the journal
        chunk ack both complete BEFORE the handler sends the 200."""
        with self._lock:
            if (shard, seq) in self._seen:
                telemetry.inc("wire.dedup", 1)
                return True
            sp.ensure_shard_dirs(self.spool_dir, shard)
            path = sp.chunk_path(self.spool_dir, shard, seq)
            # FIRST WRITE WINS (worker outbox contract): a degraded-path
            # file that landed on the shared disk first stays the
            # payload of record.
            if sp.read_json(path) is None:
                sp.atomic_write_json(path, payload)
            self.journal.chunk(shard, seq, int(payload["t0"]),
                               int(payload["t1"]))
            self._seen.add((shard, seq))
            self._acked.add((shard, seq))
            return False

    def was_acked(self, shard: int, seq: int) -> bool:
        """True when THIS server journaled the ack at ingest — the
        coordinator's drain loop skips re-journaling those."""
        with self._lock:
            return (shard, seq) in self._acked

    # -------------------------------------------------------------- params
    def publish_params(self, shard: int, params: dict) -> int:
        """Broadcast a params document to one shard (long-poll wakeup);
        returns the new version number."""
        with self._params_cv:
            version = self._params.get(shard, (0, None))[0] + 1
            self._params[shard] = (version, params)
            self._params_cv.notify_all()
        return version

    def wait_params(self, shard: int, have: int,
                    wait_s: float) -> tuple[int, dict | None]:
        """Current ``(version, params)`` for ``shard``, blocking up to
        ``wait_s`` for a version newer than ``have`` (long-poll)."""
        deadline = time.monotonic() + max(0.0, wait_s)
        with self._params_cv:
            while True:
                version, params = self._params.get(shard, (0, None))
                remaining = deadline - time.monotonic()
                if version > have or remaining <= 0:
                    return version, params
                self._params_cv.wait(timeout=remaining)


def _shard_outboxes(spool_dir: str):
    """(shard, outbox_dir) pairs present on disk."""
    import os

    try:
        names = os.listdir(spool_dir)
    except OSError:
        return
    for name in sorted(names):
        if name.startswith("s") and name[1:].isdigit():
            yield int(name[1:]), sp.shard_outbox_dir(spool_dir,
                                                     int(name[1:]))


class WireClient:
    """Worker-side push client: at-least-once chunk delivery with
    bounded retry/backoff, per-op socket deadlines, and sticky
    degradation to the shared spool past ``retry_s``."""

    def __init__(self, endpoint: str, epoch: str, shard: int,
                 spool_dir: str, *, retry_s: float = 10.0,
                 op_timeout_s: float = CLIENT_OP_TIMEOUT_S, log=None):
        self.host, self.port = parse_endpoint(endpoint)
        self.epoch = epoch
        self.shard = shard
        self.spool_dir = spool_dir
        self.retry_s = float(retry_s)
        self.op_timeout_s = float(op_timeout_s)
        self.log = log
        self.degraded = False
        if telemetry.trace.enabled():
            self._clock_handshake()

    def _clock_handshake(self) -> None:
        """Bracket a ``GET /clock`` to measure this process's wall-clock
        offset against the coordinator (offset = server − midpoint, the
        classic NTP-lite estimate).  Emitted as ``trace.skew`` so the
        merged tailer and the trace assembler can order cross-process
        records honestly (ISSUE 20).  Best-effort: a dead wire just
        means no correction record, never a stalled worker."""
        conn = HTTPConnection(self.host, self.port,
                              timeout=self.op_timeout_s)
        try:
            t0 = time.time()  # dragg: disable=DT014, bracketing wall clocks IS the skew measurement
            conn.request("GET", "/clock")
            r = conn.getresponse()
            body = r.read()
            t1 = time.time()  # dragg: disable=DT014, bracketing wall clocks IS the skew measurement
            if r.status != 200:
                return
            doc = json.loads(body)
            offset = float(doc["t"]) - (t0 + t1) / 2.0
            telemetry.emit("trace.skew", shard=self.shard,
                           offset_s=round(offset, 6),
                           rtt_s=round(t1 - t0, 6))
        except (OSError, ValueError, KeyError, HTTPException):
            pass
        finally:
            conn.close()

    # ------------------------------------------------------------- pushing
    def push_chunk(self, seq: int, payload: dict) -> str:
        """Deliver one chunk payload; returns ``"acked"`` (first
        delivery), ``"dup"`` (the server already had it — a lost ack's
        retry), or ``"spool"`` (wire down past the budget, payload
        written to the shared spool instead).  Raises
        :class:`EpochFenced` when a successor coordinator owns the run.
        Only returns once the payload is DURABLE on the coordinator's
        side (journal-before-ack) or on the shared disk — the caller's
        outbox-before-checkpoint ordering stands either way."""
        if self.degraded:
            return self._spool_write(seq, payload)
        frame = wire.encode_frame({
            "kind": "chunk", "epoch": self.epoch, "shard": self.shard,
            "seq": seq, "payload": payload})
        t_start = time.monotonic()
        attempts = 0
        # Wire-scale backoff: the liveness layer's schedule shape
        # (exponential, capped) at socket timescales.
        delays = backoff_delays(64, base_s=0.05, cap_s=0.5)
        while True:
            attempts += 1
            status, resp = self._attempt(frame)
            if status == 200:
                dup = bool((resp or {}).get("dup"))
                push_s = time.monotonic() - t_start
                # Trace-only extras (span + ``s`` duration for the
                # critical-path "wire" bucket): the off-mode stream
                # stays byte-identical to round 19.
                extra = telemetry.trace.child_fields(
                    parent=payload.get("trace_span"))
                if extra:
                    extra["s"] = round(push_s, 6)
                telemetry.emit("wire.push", shard=self.shard, seq=seq,
                               dup=dup, attempts=attempts, **extra)
                telemetry.observe("wire.push_s", push_s)
                return "dup" if dup else "acked"
            if status == 409:
                raise EpochFenced(self.epoch,
                                  str((resp or {}).get("want", "?")),
                                  self.shard, seq)
            telemetry.inc("wire.retries", 1)
            if time.monotonic() - t_start >= self.retry_s:
                return self._degrade(seq, payload, attempts, t_start)
            time.sleep(delays[min(attempts - 1, len(delays) - 1)])

    def _attempt(self, frame: bytes) -> tuple[int | None, dict | None]:
        """One delivery attempt; (status, response doc) or (None, None)
        on any transport-level failure (connect/send/recv error or an
        injected wire fault)."""
        try:
            fault_hook("wire_send")
            fault_hook("wire_partition")
        except WireFault as wf:
            self._corrupt_send(frame, wf.action)
            return None, None
        conn = HTTPConnection(self.host, self.port,
                              timeout=self.op_timeout_s)
        try:
            conn.request("POST", "/chunk", body=frame,
                         headers={"Content-Type":
                                  "application/octet-stream"})
            r = conn.getresponse()
            body = r.read()
            try:
                doc = json.loads(body) if body else {}
            except ValueError:
                doc = {}
            return r.status, doc
        except (OSError, HTTPException):
            return None, None
        finally:
            conn.close()

    def _corrupt_send(self, frame: bytes, action: str) -> None:
        """Deterministic network misbehavior for the chaos sites: a
        ``torn`` frame (truncated body, honest Content-Length — the
        server must discard it whole) or a ``cut`` connection (full
        length claimed, half the body sent, then severed — partition
        mid-chunk).  Either way this attempt fails and the at-least-once
        retry delivers the complete frame."""
        cut = max(1, len(frame) // 2)
        claim = cut if action == "torn" else len(frame)
        head = (f"POST /chunk HTTP/1.1\r\nHost: {self.host}\r\n"
                f"Content-Type: application/octet-stream\r\n"
                f"Content-Length: {claim}\r\nConnection: close\r\n\r\n"
                ).encode("ascii")
        try:
            sock = connect_deadline(self.host, self.port,
                                    self.op_timeout_s)
            try:
                sock.sendall(head + frame[:cut])
            finally:
                sock.close()  # sever before (torn) / instead of any ack
        except OSError:
            pass  # the wire being down IS the injected condition

    def _degrade(self, seq: int, payload: dict, attempts: int,
                 t_start: float) -> str:
        """Sticky fallback to the shared-disk spool (round-18 path) once
        the wire stayed down past the retry budget."""
        self.degraded = True
        after_s = time.monotonic() - t_start
        telemetry.emit("wire.degrade", shard=self.shard,
                       after_s=round(after_s, 3), attempts=attempts)
        if self.log:
            self.log(f"wire: degrading to spool after {attempts} "
                     f"attempts ({after_s:.1f}s > retry budget "
                     f"{self.retry_s:.1f}s)")
        return self._spool_write(seq, payload)

    def _spool_write(self, seq: int, payload: dict) -> str:
        """The round-18 outbox write, verbatim (first write wins)."""
        out_path = sp.chunk_path(self.spool_dir, self.shard, seq)
        if sp.read_json(out_path) is None:
            sp.atomic_write_json(out_path, payload)
        return "spool"

    # -------------------------------------------------------------- params
    def poll_params(self, have: int = 0,
                    wait_s: float = 0.0) -> tuple[int, dict] | None:
        """One params pull (long-poll when ``wait_s`` > 0); ``(version,
        params)`` when something newer than ``have`` is published, else
        None.  Errors report None — params are advisory, never worth
        stalling the chunk loop over."""
        conn = HTTPConnection(self.host, self.port,
                              timeout=max(self.op_timeout_s,
                                          wait_s + 5.0))
        try:
            conn.request("GET", f"/params?shard={self.shard}&have={have}"
                                f"&wait={wait_s}")
            r = conn.getresponse()
            body = r.read()
            if r.status != 200:
                return None
            doc = json.loads(body)
            version = int(doc.get("version", 0))
            if version > have and doc.get("params") is not None:
                return version, doc["params"]
            return None
        except (OSError, ValueError, HTTPException):
            return None
        finally:
            conn.close()
