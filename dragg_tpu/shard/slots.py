"""Parent-side shard slots — stdlib only, never imports jax.

A :class:`ShardSlot` owns one long-lived shard worker child
(shard/worker.py) and the supervision state the coordinator's loop
reads every tick: process liveness, heartbeat age (the round-4 stall
detector), and the classified post-mortem verdict — the non-blocking
shape of serve/pool.WorkerSlot, with one shard-specific addition:

**per-shard telemetry streams.**  ``resilience/supervisor.py`` exports
ONE ``$DRAGG_TELEMETRY_DIR`` to every child, which is right for a
single supervised child but interleaves N concurrent shard workers'
events into one bus file.  Each slot therefore exports
``<stream>/shard<k>`` to its child — its own ``events.jsonl`` —
and ``telemetry.tail_events_dir`` / the dashboard's ``/live`` merge the
sub-streams back into one ordered view (docs/telemetry.md).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

from dragg_tpu import telemetry
from dragg_tpu.resilience import heartbeat as hb
from dragg_tpu.resilience.supervisor import kill_group, read_tail
from dragg_tpu.resilience.taxonomy import classify_child
from dragg_tpu.serve import spool


def shard_stream_dir(base_dir: str, shard: int) -> str:
    """Shard ``k``'s telemetry sub-stream directory under the
    coordinator's stream dir — the ONE naming rule the slot export, the
    merged tailer, and the dashboard all share."""
    return os.path.join(base_dir, f"shard{shard}")


class ShardSlot:
    """One shard: launch/poll/kill a generation-counted worker child."""

    def __init__(self, spool_dir: str, shard: int, *, epoch: str = "",
                 log=None):
        self.spool_dir = spool_dir
        self.shard = shard
        self.epoch = epoch
        self.log = log
        self.gen = 0
        self.proc: subprocess.Popen | None = None
        self.platform: str | None = None
        self.hb_path: str | None = None
        self.err_path: str | None = None
        self.out_path: str | None = None
        self.launched_at: float | None = None
        spool.ensure_shard_dirs(spool_dir, shard)

    def launch(self, platform: str, env_base: dict | None = None) -> None:
        """Start generation ``gen+1``.  ``platform`` "cpu" pins the CPU
        backend AND drops the axon plugin registration (runner.cpu_env —
        the wedge-proof child environment); anything else inherits the
        caller's backend resolution."""
        from dragg_tpu.resilience.runner import cpu_env

        assert self.proc is None or self.proc.poll() is not None
        self.gen += 1
        self.platform = platform
        sdir = spool.shard_dir(self.spool_dir, self.shard)
        fd, self.hb_path = tempfile.mkstemp(prefix=f"hb-{self.gen}-",
                                            dir=sdir)
        os.close(fd)
        import json

        with open(self.hb_path, "w") as f:
            json.dump({"t": time.time()}, f)  # dragg: disable=DT014, heartbeat seed — the stall-kill protocol is wall-clock
        env = cpu_env(env_base) if platform == "cpu" else dict(
            os.environ if env_base is None else env_base)
        env[hb.ENV] = self.hb_path
        # The child runs ``-m dragg_tpu.shard.worker`` from whatever cwd
        # the coordinator has — make the package importable even when
        # the parent found it via sys.path (tools/ entry points).
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        # Per-shard telemetry sub-stream (module docstring): N concurrent
        # children must not interleave into the coordinator's bus file.
        stream = telemetry.run_dir() or env.get(telemetry.ENV_DIR)
        if stream:
            env[telemetry.ENV_DIR] = shard_stream_dir(stream, self.shard)
        # Causal trace context + live-rollup flush cadence travel the
        # same way as the stream dir (ISSUE 20): exported only when the
        # coordinator traces/flushes, so untraced runs stay byte-
        # identical.
        trace_ctx = telemetry.trace.env_value()
        if trace_ctx:
            env[telemetry.trace.ENV_CTX] = trace_ctx
        flush_s = os.environ.get(telemetry.ENV_FLUSH)
        if flush_s:
            env.setdefault(telemetry.ENV_FLUSH, flush_s)
        argv = [sys.executable, "-m", "dragg_tpu.shard.worker",
                "--spool", self.spool_dir, "--shard", str(self.shard),
                "--gen", str(self.gen)]
        if self.epoch:
            argv += ["--epoch", self.epoch]
        self.out_path = os.path.join(sdir, f"out-{self.gen}.log")
        self.err_path = os.path.join(sdir, f"err-{self.gen}.log")
        with open(self.out_path, "wb") as out_f, \
                open(self.err_path, "wb") as err_f:
            self.proc = subprocess.Popen(argv, env=env, stdout=out_f,
                                         stderr=err_f,
                                         start_new_session=True)
        self.launched_at = time.monotonic()
        telemetry.emit("shard.launch", shard=self.shard, gen=self.gen,
                       pid=self.proc.pid, platform=platform)
        telemetry.inc("shard.restarts", 1 if self.gen > 1 else 0)
        if self.log:
            self.log(f"shard s{self.shard} gen={self.gen} "
                     f"pid={self.proc.pid} platform={platform}")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def heartbeat_age(self) -> float | None:
        if self.hb_path is None:
            return None
        age, _ = hb.read(self.hb_path)
        return age

    def elapsed(self) -> float:
        return (time.monotonic() - self.launched_at
                if self.launched_at is not None else 0.0)

    def kill(self, grace_s: float = 5.0) -> None:
        if self.proc is not None and self.proc.poll() is None:
            kill_group(self.proc, grace_s)

    def verdict(self, *, timed_out: bool = False,
                stalled: bool = False) -> str:
        """Taxonomy kind for the (dead) current generation."""
        rc = self.proc.poll() if self.proc is not None else None
        tail = read_tail(self.err_path, 4000) if self.err_path else ""
        kind = classify_child(rc, timed_out, stalled, tail)
        return kind or "CHILD_CRASH"

    def stderr_tail(self, limit: int = 2000) -> str:
        return read_tail(self.err_path, limit) if self.err_path else ""
