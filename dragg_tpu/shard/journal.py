"""The coordinator's crash-safe shard journal — stdlib only, fsync'd.

The round-11 serve journal's durability contract, applied to chunk
ownership: every record the coordinator relies on after a restart is one
fsync'd JSONL line, so a kill -9 at ANY instruction leaves a replayable
file.  A restarted coordinator folds the journal to the exact per-shard
chunk frontier (acked chunks are merged from their retained spool files,
everything after the frontier is recomputed by the resumed workers —
re-work bounded by one chunk per the worker's outbox-then-checkpoint
ordering).

Record grammar (one JSON object per line, ``state`` discriminates)::

    {"state": "epoch", "token": ...}                  ownership claim
    {"state": "plan", "communities": C, "workers": N,
     "ranges": [[c0, c1], ...], "steps": T,
     "chunk_steps": k}                                run geometry
    {"state": "launch", "shard": k, "gen": g,
     "platform": p, "c0": ..., "c1": ...}             worker generation
    {"state": "chunk", "shard": k, "seq": n,
     "t0": ..., "t1": ...}                            merge ack (frontier)
    {"state": "exit", "shard": k, "gen": g, "rc": ...,
     "failure": kind}                                 classified death
    {"state": "transition", "shard": k, "from": p,
     "to": p2, "failure": kind}                       degradation mark
    {"state": "done", "shard": k, "chunks": n}        shard completed

Crash consistency is by construction (serve/journal.py precedent): a
torn final line parses as garbage and is dropped by :func:`replay` —
the write that tore never returned, so nothing observable is lost.

Duplicate-epoch refusal: an epoch token may be claimed ONCE per journal.
The token is what fences orphan workers out of the spool
(serve/spool.py EPOCH file); a successor that re-used a dead
coordinator's token would re-admit exactly the orphans the fence exists
to stop, so :meth:`Journal.epoch` raises instead of appending.
``python -m dragg_tpu doctor --shard-check`` self-tests both properties
(torn-tail truncation at every byte boundary, duplicate refusal).
"""

from __future__ import annotations

import json
import os
import threading
from typing import NamedTuple

EPOCH = "epoch"
PLAN = "plan"
LAUNCH = "launch"
CHUNK = "chunk"
EXIT = "exit"
TRANSITION = "transition"
DONE = "done"


class ReplayState(NamedTuple):
    """The fold of one shard journal.

    ``epochs``     — claimed ownership tokens, oldest first;
    ``plan``       — the run-geometry record (None before the first run);
    ``frontier``   — shard -> next unacked chunk seq (0 = nothing acked);
    ``acked``      — shard -> sorted list of acked chunk seqs;
    ``platforms``  — shard -> platform of the newest launch/transition;
    ``gens``       — shard -> highest launched generation (a successor
                     coordinator CONTINUES the numbering, so per-gen
                     logs/payloads stay distinct across restarts);
    ``restarts``   — shard -> launches beyond the first generation
                     (across every coordinator lifetime);
    ``done``       — shards whose completion was journaled;
    ``dropped_lines`` — unparseable lines skipped (a torn tail is 0 or
                     1; more means outside interference — surfaced, not
                     fatal).
    """

    epochs: list
    plan: dict | None
    frontier: dict
    acked: dict
    platforms: dict
    gens: dict
    restarts: dict
    done: set
    dropped_lines: int


class Journal:
    """Append side.  One instance owns the file handle; every append is
    fsync'd before returning."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self._fsync = fsync
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        rep = replay(path)
        self._epochs = set(rep.epochs)
        self._fh = open(path, "a", encoding="utf-8")
        # The tcp transport's chunk-ingest server journal-acks from its
        # handler threads while the coordinator loop appends lifecycle
        # records — appends must serialize (whole lines, fsync'd in
        # order).  RLock: ``epoch`` holds it across its check-then-append.
        self._lock = threading.RLock()

    def _append(self, rec: dict) -> None:
        with self._lock:
            self._fh.write(json.dumps(rec, separators=(",", ":"),
                                      default=str) + "\n")
            self._fh.flush()
            if self._fsync:
                os.fsync(self._fh.fileno())

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass

    # ----------------------------------------------------------- lifecycle
    def epoch(self, token: str) -> None:
        """Claim the run for one coordinator instance.  Raises on a
        duplicate token — reusing a dead coordinator's token would
        re-admit the orphan workers the spool EPOCH fence exists to
        stop."""
        with self._lock:
            if token in self._epochs:
                raise ValueError(
                    f"epoch token {token!r} already claimed in {self.path} "
                    f"— a successor coordinator must mint a fresh token")
            self._epochs.add(token)
            self._append({"state": EPOCH, "token": token})

    def plan(self, communities: int, workers: int,
             ranges: list[tuple[int, int]], steps: int,
             chunk_steps: int) -> None:
        self._append({"state": PLAN, "communities": communities,
                      "workers": workers,
                      "ranges": [[int(a), int(b)] for a, b in ranges],
                      "steps": steps, "chunk_steps": chunk_steps})

    def launch(self, shard: int, gen: int, platform: str,
               c0: int, c1: int) -> None:
        self._append({"state": LAUNCH, "shard": shard, "gen": gen,
                      "platform": platform, "c0": c0, "c1": c1})

    def chunk(self, shard: int, seq: int, t0: int, t1: int) -> None:
        """Ack one merged chunk — the durable frontier record.  The chunk
        PAYLOAD stays in the retained spool outbox file; the ack is what
        tells a restarted coordinator the file is merged-and-owned."""
        self._append({"state": CHUNK, "shard": shard, "seq": seq,
                      "t0": t0, "t1": t1})

    def exit(self, shard: int, gen: int, rc: int | None,
             failure: str | None) -> None:
        self._append({"state": EXIT, "shard": shard, "gen": gen,
                      "rc": rc, "failure": failure})

    def transition(self, shard: int, from_platform: str, to_platform: str,
                   failure: str | None) -> None:
        self._append({"state": TRANSITION, "shard": shard,
                      "from": from_platform, "to": to_platform,
                      "failure": failure})

    def done(self, shard: int, chunks: int) -> None:
        self._append({"state": DONE, "shard": shard, "chunks": chunks})


def replay(path: str) -> ReplayState:
    """Fold a journal file into :class:`ReplayState`.  Never raises on
    file content: torn/garbage lines are counted and skipped, unknown
    states ignored (forward compatibility)."""
    epochs: list = []
    plan: dict | None = None
    acked: dict = {}
    platforms: dict = {}
    gens: dict = {}
    restarts: dict = {}
    done: set = set()
    dropped = 0
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.read().split("\n")
    except OSError:
        return ReplayState([], None, {}, {}, {}, {}, {}, set(), 0)
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            dropped += 1
            continue
        if not isinstance(rec, dict):
            dropped += 1
            continue
        state = rec.get("state")
        if state == EPOCH and "token" in rec:
            if rec["token"] not in epochs:
                epochs.append(rec["token"])
        elif state == PLAN:
            plan = rec  # newest wins (there should only ever be one)
        elif state == CHUNK and "shard" in rec:
            acked.setdefault(int(rec["shard"]), set()).add(int(rec["seq"]))
        elif state == LAUNCH and "shard" in rec:
            k = int(rec["shard"])
            platforms[k] = rec.get("platform")
            gen = int(rec.get("gen", 1))
            gens[k] = max(gens.get(k, 0), gen)
            if gen > 1:
                restarts[k] = restarts.get(k, 0) + 1
        elif state == TRANSITION and "shard" in rec:
            platforms[int(rec["shard"])] = rec.get("to")
        elif state == DONE and "shard" in rec:
            done.add(int(rec["shard"]))
    # The frontier is the first GAP in each shard's acked seqs: acks past
    # a gap (out-of-order merge after a restart race) are re-merged from
    # their retained spool files rather than trusted blindly.
    frontier = {}
    sorted_acks = {}
    for k, seqs in acked.items():
        n = 0
        while n in seqs:
            n += 1
        frontier[k] = n
        sorted_acks[k] = sorted(seqs)
    return ReplayState(epochs, plan, frontier, sorted_acks, platforms,
                       gens, restarts, done, dropped)
