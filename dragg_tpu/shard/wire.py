"""Wire frame codec for the networked shard transport — stdlib only.

One chunk exchanged over TCP (shard/transport.py, architecture.md §20)
is one **frame**: a fixed header followed by a JSON document body
serialized through the SAME codec the spool files use
(serve/spool.dumps_doc), so a payload round-trips byte-identically
whether it travelled the shared disk or the wire.

Frame layout (big-endian)::

    MAGIC   4 bytes  b"DRGW"
    VERSION 1 byte   0x01
    LENGTH  4 bytes  u32 body length
    CRC32   4 bytes  u32 zlib.crc32 of the body
    BODY    LENGTH bytes of UTF-8 JSON (spool.dumps_doc)

Every defect an unreliable wire can produce — truncation at ANY byte
boundary, a flipped bit, a foreign protocol speaking to our port, an
absurd length claim — decodes to :class:`TornFrame`, never to a partial
document (the atomic-rename guarantee of the spool, re-proven for a
byte stream).  ``doctor --shard-check`` sweeps truncation at every byte
boundary against a live ingest server.

Dedup identity: :func:`chunk_token` names one pushed chunk as
``(epoch, shard, seq)`` — the at-least-once delivery token the ingest
server acks duplicates by (and the name a fenced orphan's refusal
quotes back).
"""

from __future__ import annotations

import struct
import zlib

from dragg_tpu.serve.spool import dumps_doc, loads_doc

MAGIC = b"DRGW"
VERSION = 1
_HEADER = struct.Struct(">4sBII")
HEADER_BYTES = _HEADER.size

# Refuse absurd length claims before allocating: the largest legitimate
# frame is one chunk's per-community float64 series — megabytes at the
# extreme fleet shapes, nowhere near this.
MAX_FRAME_BYTES = 256 * 1024 * 1024


class TornFrame(ValueError):
    """The bytes do not decode to exactly one complete, checksummed
    frame — truncated, corrupted, or not ours."""


def chunk_token(epoch: str, shard: int, seq: int) -> str:
    """The ``(epoch, shard, chunk)`` delivery token, as one string."""
    return f"{epoch}/s{shard}/c{seq}"


def encode_frame(doc: dict) -> bytes:
    """One document -> one complete frame."""
    body = dumps_doc(doc).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame body {len(body)} bytes exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    return _HEADER.pack(MAGIC, VERSION, len(body),
                        zlib.crc32(body) & 0xFFFFFFFF) + body


def decode_frame(data: bytes) -> dict:
    """Exactly one complete frame -> its document; :class:`TornFrame`
    on anything else (short, long, bad magic/version/length/crc, body
    that is not one JSON object)."""
    if len(data) < HEADER_BYTES:
        raise TornFrame(f"short frame: {len(data)} < header "
                        f"{HEADER_BYTES} bytes")
    magic, version, length, crc = _HEADER.unpack_from(data)
    if magic != MAGIC:
        raise TornFrame(f"bad magic {magic!r}")
    if version != VERSION:
        raise TornFrame(f"unknown frame version {version}")
    if length > MAX_FRAME_BYTES:
        raise TornFrame(f"length claim {length} exceeds MAX_FRAME_BYTES")
    body = data[HEADER_BYTES:]
    if len(body) != length:
        raise TornFrame(f"torn body: {len(body)} of {length} bytes")
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise TornFrame("crc mismatch")
    try:
        return loads_doc(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise TornFrame(f"body is not one JSON document: {e}") from e
