"""Cross-process fleet sharding (ROADMAP item 4, architecture.md §19).

A jax-free COORDINATOR process partitions ``fleet.communities`` into
``shard.workers`` contiguous community ranges and runs each range in its
own supervised worker process.  Workers own their own mesh/backend and
exchange only per-chunk per-community aggregate series over the spool —
never raw state — so the coordinator's merge is ``real_home_pairs``-
ordered and bit-identical to the in-process fleet (tests/test_shard.py).

Layers (each its own module, parent side strictly jax-free):

* :mod:`partition` — community-range math, shard configs, and the ONE
  per-community fold both sides of every parity comparison share;
* :mod:`journal`   — the coordinator's fsync'd crash-safety record
  (chunk-frontier replay, duplicate-epoch refusal);
* :mod:`worker`    — the jax child (``python -m dragg_tpu.shard.worker``);
* :mod:`slots`     — non-blocking per-shard supervision handles;
* :mod:`coordinator` — the run loop: launch, merge, requeue, degrade,
  resume.
"""

from dragg_tpu.shard.partition import (  # noqa: F401
    fold_community_series,
    merge_shard_series,
    shard_config,
    shard_ranges,
)
