"""Shard worker — the jax child owning one contiguous community range.

``python -m dragg_tpu.shard.worker --spool S --shard K --gen G --epoch T``

Reads its range spec from ``<spool>/s<K>/spec.json`` (written by the
coordinator), builds a fleet engine for global communities
``[c0, c1)`` via ``fleet.community_base`` (homes.fleet_community_base —
global seeds / names / weather offsets, so this shard's per-community
trajectories are bit-identical to the in-process fleet's), and runs the
chunk loop:

1. **epoch fence** — read the spool EPOCH file; a mismatch means a
   successor coordinator owns the run and this process is an orphan of a
   killed one: exit between chunks (serve/spool.py precedent);
2. ``fault_hook("shard_chunk")`` — the chaos suite's per-shard site
   (``shard_build`` guards the engine build);
3. run one device chunk, fold the per-home outputs into per-community
   aggregate series (shard/partition.fold_outputs — the ONE fold parity
   comparisons share) and write the outbox chunk file ATOMICALLY;
4. checkpoint the scan carry (checkpoint.save_checkpoint_dir).

The outbox-THEN-checkpoint order bounds crash re-work at one chunk: a
kill between the two resumes at the previous frontier and recomputes a
chunk whose (deterministic, bit-identical) outbox file it simply
rewrites; a kill before the outbox write recomputes the same chunk.  A
relaunched generation resumes from ``LATEST`` after validating the
run-shape guard (aggregator._run_shape precedent — a reshard or config
edit must start the shard fresh, not mis-assemble).

``stop_t`` in the spec is the elastic-reshard quiesce barrier: every
shard exits exactly at that chunk boundary, leaving equal-frontier
checkpoints ``tools/reshard_checkpoint.py`` can regroup.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _run_shape(spec: dict, cfg: dict, engine) -> dict:
    """What a shard checkpoint is only valid for (the aggregator's
    run-shape guard, scoped to one shard): community range + geometry +
    every config dimension that sizes or re-interprets a carry leaf."""
    return {
        "c0": int(spec["c0"]), "c1": int(spec["c1"]),
        "homes_per_community": int(cfg["community"]["total_number_homes"]),
        "steps": int(spec["steps"]),
        "chunk_steps": int(spec["chunk_steps"]),
        "horizon": int(cfg["home"]["hems"]["prediction_horizon"]),
        "solver": engine.params.solver,
        "precision": engine.params.precision,
        "warm_cols": engine.warm_cols,
        "buckets": ([[b["name"], b["n_slots"]] for b in engine.bucket_info()]
                    if engine.bucketed else None),
        "n_home_slots": engine.n_homes,
        "state_rev": 2,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--spool", required=True)
    ap.add_argument("--shard", type=int, required=True)
    ap.add_argument("--gen", type=int, default=1)
    ap.add_argument("--epoch", default="")
    args = ap.parse_args()

    from dragg_tpu.serve import spool as sp

    spec = sp.read_json(sp.shard_spec_path(args.spool, args.shard))
    if spec is None:
        print(f"shard {args.shard}: no spec at "
              f"{sp.shard_spec_path(args.spool, args.shard)}",
              file=sys.stderr)
        sys.exit(2)

    import jax
    import numpy as np

    from dragg_tpu import telemetry
    from dragg_tpu.checkpoint import (latest_checkpoint_dir, load_progress,
                                      load_pytree, save_checkpoint_dir)
    from dragg_tpu.data import (load_environment, load_waterdraw_profiles,
                                waterdraw_path)
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_fleet_batch, create_fleet_homes
    from dragg_tpu.resilience.faults import fault_hook
    from dragg_tpu.resilience.heartbeat import beat
    from dragg_tpu.shard.partition import (fold_outputs, series_to_lists,
                                           shard_config)

    c0, c1 = int(spec["c0"]), int(spec["c1"])
    steps = int(spec["steps"])
    chunk_steps = int(spec["chunk_steps"])
    stop_t = spec.get("stop_t")
    stop_t = steps if stop_t is None else min(int(stop_t), steps)

    cfg = shard_config(spec["config"], c0, c1)
    data_dir = spec.get("data_dir")

    # Networked transport (shard/transport.py): push chunks over the
    # wire with at-least-once delivery instead of writing the outbox
    # file ourselves; params (stop_t today) flow back on the same wire.
    # Spool mode (no transport key) keeps the round-18 path byte-for-
    # byte.
    client = None
    if spec.get("transport") == "tcp" and spec.get("endpoint"):
        from dragg_tpu.shard.transport import EpochFenced, WireClient

        client = WireClient(
            str(spec["endpoint"]), args.epoch, args.shard, args.spool,
            retry_s=float(spec.get("transport_retry_s", 10.0)))
    params_ver = 0

    beat({"stage": "shard_build", "shard": args.shard})
    fault_hook("shard_build")
    env = load_environment(cfg, data_dir=data_dir)
    dt = int(cfg["agg"]["subhourly_steps"])
    # The waterdraw profile pool is seeded by the BASE simulation seed
    # (shared by every community of the fleet — aggregator.get_homes);
    # per-community identity rides fleet.community_base inside
    # create_fleet_homes.
    wd = load_waterdraw_profiles(
        waterdraw_path(cfg, data_dir),
        seed=int(cfg["simulation"]["random_seed"]))
    homes = create_fleet_homes(cfg, steps, dt, wd)
    hems = cfg["home"]["hems"]
    horizon = max(1, int(hems["prediction_horizon"]) * dt)
    batch, fleet = build_fleet_batch(homes, cfg, horizon, dt,
                                     int(hems["sub_subhourly_steps"]))
    # ``tpu.sharded`` resolves exactly like the aggregator's engine
    # build: "auto" shards this shard's home axis when the worker sees
    # >1 device (each worker owns its OWN mesh — that is the point of
    # the process split), true/false force either path.  NOTE: sharded
    # checkpoints carry slot-padded leaves; reshard them only at the
    # same resolution (the run-shape guard refuses a mismatch loudly).
    sharded = cfg.get("tpu", {}).get("sharded", "auto")
    if sharded == "auto":
        from dragg_tpu.resilience.devices import device_count

        use_sharded = device_count() > 1
    else:
        use_sharded = bool(sharded)
    start_index = int(spec.get("start_index", 0))
    if use_sharded:
        from dragg_tpu.parallel import make_sharded_engine

        engine = make_sharded_engine(batch, env, cfg, start_index,
                                     fleet=fleet, data_dir=data_dir)
    else:
        engine = make_engine(batch, env, cfg, start_index, fleet=fleet,
                             data_dir=data_dir)
    C_local = c1 - c0
    pairs = np.asarray(engine.real_home_pairs)
    cols = np.asarray(engine.real_home_cols)
    platform = jax.devices()[0].platform  # dragg: disable=DT004, supervised shard child — committed to its backend

    # Comfort-band bounds in community-major order (validate_scale
    # convention), with the scenario relaxation headroom.
    order = (np.argsort(np.asarray(fleet.global_idx)) if fleet is not None
             else np.arange(batch.n_homes))
    tin_min = np.asarray(batch.temp_in_min)[order]
    tin_max = np.asarray(batch.temp_in_max)[order]
    twh_min = np.asarray(batch.temp_wh_min)[order]
    twh_max = np.asarray(batch.temp_wh_max)[order]
    band_tol = 0.05
    evts = getattr(engine, "_events", None)
    if evts is not None:
        band_tol += float(np.max(evts.relax))

    # Resume from the latest complete checkpoint whose run shape matches.
    ckpt_root = sp.shard_ckpt_root(args.spool, args.shard)
    shape = _run_shape(spec, cfg, engine)
    state, t = engine.init_state(), 0
    d = latest_checkpoint_dir(ckpt_root)
    if d is not None:
        try:
            prog = load_progress(os.path.join(d, "progress.json"))
        except (OSError, ValueError):
            prog = None
        if prog is not None and prog.get("run_shape") == shape:
            state = load_pytree(os.path.join(d, "state.npz"), state)
            t = int(prog["timestep"])
            print(f"shard {args.shard}: resuming from t={t} ({d})",
                  file=sys.stderr, flush=True)
        elif prog is not None:
            print(f"shard {args.shard}: checkpoint {d} run shape mismatch; "
                  f"starting fresh", file=sys.stderr, flush=True)

    sp.atomic_write_json(
        os.path.join(sp.shard_dir(args.spool, args.shard),
                     f"ready-{args.gen}.json"),
        {"shard": args.shard, "gen": args.gen, "platform": platform,
         "t_resume": t, "communities": [c0, c1]})
    beat({"stage": "shard_ready", "timestep": t})

    H = engine.params.horizon
    while t < stop_t:
        if args.epoch and sp.read_epoch(args.spool) != args.epoch:
            # A successor coordinator fenced this generation out.
            print(f"shard {args.shard}: epoch token changed — exiting "
                  f"(orphan fence)", file=sys.stderr, flush=True)
            sys.exit(0)
        if client is not None:
            # Params pull on the wire (long-poll channel, drained
            # non-blocking at each chunk boundary): a published stop_t
            # tightens the quiesce barrier mid-run.
            got = client.poll_params(have=params_ver)
            if got is not None:
                params_ver, params = got
                if params.get("stop_t") is not None:
                    stop_t = min(stop_t, max(t, int(params["stop_t"])))
        fault_hook("shard_chunk")
        # One causal span per chunk (ISSUE 20): the same span id rides
        # chunk.done, the outbox/wire payload, and the coordinator's
        # merge record, so the assembler links worker chunk -> wire
        # push -> merge into one rooted chain.  Empty when tracing off.
        chunk_span = telemetry.trace.child_fields()
        k = min(chunk_steps, stop_t - t)
        rps = np.zeros((k, H), dtype=np.float32)
        t0 = time.perf_counter()
        state, outs = engine.run_chunk(state, t, rps)
        jax.block_until_ready(outs.agg_load)
        device_s = time.perf_counter() - t0
        series = fold_outputs(outs, pairs, C_local)
        solved = np.asarray(outs.correct_solve)[:, cols]
        tin = np.asarray(outs.temp_in)[:, cols]
        twh = np.asarray(outs.temp_wh)[:, cols]
        vi = np.where(solved > 0,
                      np.maximum(tin_min[None] - tin, tin - tin_max[None]),
                      -1.0)
        vw = np.where(solved > 0,
                      np.maximum(twh_min[None] - twh, twh - twh_max[None]),
                      -1.0)
        seq = t // chunk_steps
        payload = {
            "shard": args.shard, "gen": args.gen, "seq": seq,
            "t0": t, "t1": t + k, "platform": platform,
            "series": series_to_lists(series),
            "solve_rate": float(solved.mean()),
            "viol_max": float(max(vi.max(), vw.max())),
            "band_tol": band_tol,
            "device_s": round(device_s, 4),
        }
        if chunk_span:
            # The span crosses the process boundary inside the payload
            # (spool file or DRGW frame body — no codec change); absent
            # entirely when tracing is off, keeping outbox files
            # byte-identical to round 19.
            payload["trace_span"] = chunk_span["span"]
        # Outbox BEFORE checkpoint (module docstring): a crash between
        # the two re-computes one deterministic chunk, never loses one.
        # FIRST WRITE WINS: a relaunched generation re-covering the
        # ≤1-chunk re-work window must not overwrite a retained file the
        # coordinator may already have acked — after a cross-platform
        # degrade the recompute is only tolerance-equal, and a later
        # coordinator restart re-merges the FILE, which must stay the
        # payload of record.  (Torn files read as None and are rewritten.)
        if client is not None:
            # Wire delivery: push_chunk only returns once the payload is
            # durable on the coordinator's side (journal-before-ack) or
            # on the shared spool (degraded path) — the outbox-before-
            # checkpoint ordering stands either way.  No local copy is
            # kept: the ack IS the durability receipt.
            try:
                client.push_chunk(seq, payload)
            except EpochFenced as e:
                print(f"shard {args.shard}: {e}", file=sys.stderr,
                      flush=True)
                sys.exit(0)
        else:
            out_path = sp.chunk_path(args.spool, args.shard, seq)
            if sp.read_json(out_path) is None:
                sp.atomic_write_json(out_path, payload)
        t += k
        save_checkpoint_dir(ckpt_root, t, state, {"run_shape": shape})
        beat({"timestep": t})
        telemetry.emit("chunk.done", t0=t - k, t1=t, n_steps=k,
                       solve_rate=round(payload["solve_rate"], 4),
                       device_s=round(device_s, 3),
                       steps_per_s=round(k / max(device_s, 1e-9), 3),
                       **chunk_span)
        # Flush-on-crash metrics (ISSUE 20 satellite): with the rollup
        # flush armed, persist this shard's in-progress snapshot every
        # chunk — a kill -9 loses at most one chunk of metric deltas
        # and the coordinator's post-mortem/rollup sees the last
        # interval.  Unarmed runs write nothing mid-run (round 19).
        if os.environ.get(telemetry.ENV_FLUSH):
            telemetry.set_gauge("sim.timestep", t)
            telemetry.observe("engine.chunk_device_s", device_s)
            telemetry.write_snapshot()
    sys.exit(0)


if __name__ == "__main__":
    main()
