"""Community-range partition math + the shared per-community fold —
stdlib + numpy only (the coordinator imports this and must stay
jax-free).

The cross-shard coupling is deliberately LOW-DIMENSIONAL (the
heterogeneous-aggregation template, PAPERS.md arxiv 2605.30763): a shard
worker ships per-chunk per-community aggregate series — the
``community_fold_arrays()`` reduction of its per-home outputs — and
nothing else.  Bit-identity of the merged result with the in-process
fleet then rests on two facts this module pins:

* every community's per-home trajectory is composition-invariant (the
  fleet parity contract, tests/test_fleet.py): a shard engine running
  communities ``[c0, c0+k)`` with ``fleet.community_base = c0``
  reproduces those communities' rows of the full fleet exactly;
* both sides of every comparison fold per-home values through ONE
  implementation — :func:`fold_community_series`, summing each
  community's homes in community-major (``real_home_pairs``) order with
  float64 accumulation, so the reduction order is identical no matter
  which process ran the homes.
"""

from __future__ import annotations

import copy

import numpy as np

# The per-home StepOutputs fields a shard worker folds per community and
# ships over the spool (out-field name -> merged-series name).  The fold
# of ``p_grid`` is each community's ``agg_load``-style sum; ``cost`` its
# aggregate cost; ``correct_solve`` its solved-home count.
FOLD_FIELDS = {
    "p_grid": "agg_load",
    "cost": "agg_cost",
    "correct_solve": "solved",
}


def shard_ranges(communities: int, workers: int) -> list[tuple[int, int]]:
    """Balanced CONTIGUOUS community ranges ``[(c0, c1), ...]`` — the
    first ``communities % workers`` shards carry one extra community.
    Contiguity is load-bearing: it keeps every shard a plain
    ``community_base`` + count fleet config, and checkpoint resharding a
    pure community-column regrouping."""
    if workers < 1:
        raise ValueError(f"shard.workers must be >= 1, got {workers}")
    if communities < workers:
        raise ValueError(
            f"cannot split {communities} communities over {workers} shard "
            f"workers — every shard needs at least one community")
    base, extra = divmod(communities, workers)
    ranges, c0 = [], 0
    for k in range(workers):
        n = base + (1 if k < extra else 0)
        ranges.append((c0, c0 + n))
        c0 += n
    return ranges


def shard_config(config: dict, c0: int, c1: int) -> dict:
    """The shard worker's config for global communities ``[c0, c1)``:
    ``fleet.communities`` becomes the range size and
    ``fleet.community_base`` the range start (on top of any base the
    parent config already carried), so seeds / name prefixes / weather
    offsets keep their GLOBAL identities (homes.fleet_community_base).

    Scenario event targeting is remapped too: an event naming explicit
    global ``communities`` keeps only this shard's members, re-indexed
    shard-local (the timeline builder sizes its (C, T) series by the
    engine's local community count); events without the key apply
    everywhere and pass through unchanged.  An event whose targets all
    live on other shards is dropped here — it still fires there."""
    cfg = copy.deepcopy(config)
    fleet = cfg.setdefault("fleet", {})
    parent_base = int(fleet.get("community_base", 0))
    fleet["communities"] = int(c1 - c0)
    fleet["community_base"] = parent_base + int(c0)
    events = cfg.get("scenarios", {}).get("events", [])
    if events:
        kept = []
        for ev in events:
            ev = copy.deepcopy(ev)
            targets = ev.get("communities")
            if targets is not None:
                local = [int(c) - c0 for c in targets if c0 <= int(c) < c1]
                if not local:
                    continue
                ev["communities"] = local
            kept.append(ev)
        cfg["scenarios"]["events"] = kept
    return cfg


def fold_community_series(values: np.ndarray, pairs: np.ndarray,
                          n_communities: int) -> np.ndarray:
    """(T, C) float64 per-community sums of one per-home (T, cols) array.

    ``pairs`` is ``engine.real_home_pairs`` — ``(community, output
    column)`` per home in community-major order.  Each community's homes
    are summed as one contiguous float64 block in that order (numpy's
    pairwise reduction over an identically-shaped, identically-ordered
    block), so a shard folding its local range and the in-process fleet
    folding the same communities produce BIT-identical values — the
    ground the merged-output parity tests stand on."""
    pairs = np.asarray(pairs)
    values = np.asarray(values, dtype=np.float64)
    out = np.zeros((values.shape[0], n_communities), dtype=np.float64)
    for c in range(n_communities):
        cols = pairs[pairs[:, 0] == c, 1]
        if cols.size:
            out[:, c] = values[:, cols].sum(axis=1)
    return out


def fold_outputs(outs, pairs: np.ndarray, n_communities: int,
                 fields: dict | None = None) -> dict[str, np.ndarray]:
    """Fold one chunk's StepOutputs into the shipped per-community
    series — the worker's wire payload AND the in-process reference the
    parity tests compare against (one fold, two callers, zero drift)."""
    out = {}
    for field, name in (fields or FOLD_FIELDS).items():
        out[name] = fold_community_series(
            np.asarray(getattr(outs, field)), pairs, n_communities)
    return out


def merge_shard_series(per_shard: dict[int, np.ndarray],
                       ranges: list[tuple[int, int]]) -> np.ndarray:
    """Assemble per-shard (T, C_shard) blocks into the (T, C) fleet
    series, community-major (shard k owns columns ``ranges[k]``)."""
    T = next(iter(per_shard.values())).shape[0]
    C = ranges[-1][1]
    out = np.zeros((T, C), dtype=np.float64)
    for k, (c0, c1) in enumerate(ranges):
        out[:, c0:c1] = per_shard[k]
    return out


def series_to_lists(series: dict[str, np.ndarray]) -> dict[str, list]:
    """JSON-safe nested lists.  Python floats are doubles and
    ``json.dumps`` emits ``repr`` round-trippable values, so the
    spool/merge path preserves every bit of the float64 fold."""
    return {k: np.asarray(v, dtype=np.float64).tolist()
            for k, v in series.items()}
