"""The shard coordinator — jax-free, crash-safe, restartable.

``run_sharded`` partitions ``fleet.communities`` into ``shard.workers``
contiguous ranges, runs each range in a supervised worker process
(shard/slots.py), and merges the per-chunk per-community aggregate
series the workers ship over the spool.  The parent NEVER initializes a
jax backend (resilience.supervisor contract — a wedged tunnel must not
hang the one process that classifies and survives it).

Durability model (the round-11/16 serving machinery, applied to chunk
ownership):

* the **spool** (serve/spool.py) holds the wire state: per-shard specs,
  outbox chunk files (atomic renames, RETAINED until the run completes),
  per-generation logs/checkpoints, and the EPOCH ownership token that
  fences orphan workers of a killed coordinator;
* the **journal** (shard/journal.py, fsync'd) holds the decisions: the
  run plan, every launch/exit/transition, and one ``chunk`` ack per
  merged chunk — a restarted coordinator replays it to the exact
  per-shard chunk frontier, re-reads the acked chunks' retained spool
  files, and resumes the loop; nothing is re-solved behind the frontier
  and at most ONE chunk per shard is recomputed ahead of it (the
  worker's outbox-then-checkpoint ordering);
* each shard **degrades TPU→CPU independently**: after
  ``shard.degrade_after`` consecutive failures (and
  ``resilience.degrade_to_cpu``) the relaunch pins the wedge-proof CPU
  environment, with the taxonomy kind journaled and the transition on
  the telemetry stream — the other shards keep their platform.
"""

from __future__ import annotations

import math
import os
import time
import uuid

import numpy as np

from dragg_tpu import telemetry
from dragg_tpu.serve import spool as sp
from dragg_tpu.shard import journal as sj
from dragg_tpu.shard.partition import merge_shard_series, shard_ranges
from dragg_tpu.shard.slots import ShardSlot

JOURNAL_FILE = "shard_journal.jsonl"
MERGED_FILE = "merged.json"


def shard_settings(config: dict) -> dict:
    """The ``[shard]`` config section with defaults applied."""
    from dragg_tpu.config import default_config

    merged = dict(default_config()["shard"])
    merged.update((config or {}).get("shard", {}))
    return merged


class _Shard:
    """Coordinator-side state for one shard: slot + frontier + merge."""

    def __init__(self, slot: ShardSlot, c0: int, c1: int):
        self.slot = slot
        self.c0, self.c1 = c0, c1
        self.frontier = 0          # next unacked chunk seq
        self.payloads: dict = {}   # seq -> merged chunk payload
        self.failures = 0          # consecutive failures since last ack
        self.restarts = 0
        # Progress clock for the deadline: re-armed on every chunk ack,
        # so ``shard.deadline_s`` bounds the time WITHOUT progress, not
        # a whole (legitimately multi-hour) shard run.
        self.progress_at = time.monotonic()
        self.done_journaled = False

    def stalled_for(self) -> float:
        return time.monotonic() - max(
            self.progress_at,
            self.slot.launched_at if self.slot.launched_at is not None
            else self.progress_at)


def run_sharded(config: dict, *, run_dir: str, steps: int,
                workers: int | None = None, chunk_steps: int | None = None,
                platform: str = "auto", data_dir: str | None = None,
                stop_t: int | None = None, start_index: int = 0,
                log=None) -> dict:
    """Run ``steps`` baseline timesteps of the config's fleet across
    shard worker processes; return the merged result dict (also written
    to ``<run_dir>/merged.json``).

    ``run_dir`` is the durable state (journal + spool): calling again
    with the same directory RESUMES — after a coordinator kill, a
    partial ``stop_t`` run, or a checkpoint reshard
    (tools/reshard_checkpoint.py) — refusing a changed plan loudly.
    ``stop_t`` stops every shard exactly at that chunk boundary (the
    reshard quiesce barrier); resume with ``stop_t=None`` to finish.
    ``platform`` "cpu" pins every worker to the wedge-proof CPU env;
    "auto"/"tpu" inherit the caller's backend resolution, degrading
    per shard on classified failures.
    """
    from dragg_tpu.homes import fleet_config

    scfg = shard_settings(config)
    from dragg_tpu.resilience.runner import resilience_config

    rcfg = resilience_config(config)
    n_workers = int(workers if workers is not None else scfg["workers"])
    k_chunk = int(chunk_steps if chunk_steps is not None
                  else scfg["chunk_steps"])
    if k_chunk < 1:
        raise ValueError(f"shard.chunk_steps must be >= 1, got {k_chunk}")
    # ``deadline_s`` is a PROGRESS deadline: the clock re-arms on every
    # merged chunk (and on relaunch), so a healthy shard acking chunks
    # for hours is never killed — only one that stops producing.
    deadline_s = float(scfg["deadline_s"]) or float(rcfg["deadline_s"])
    stall_s = float(scfg["stall_s"]) or None
    max_restarts = int(scfg["restarts"])
    degrade_after = int(scfg["degrade_after"])
    poll_s = float(scfg["poll_s"])
    degrade_to_cpu = bool(rcfg.get("degrade_to_cpu", True))

    C = fleet_config(config)[0]
    ranges = shard_ranges(C, n_workers)
    target_t = steps if stop_t is None else min(int(stop_t), steps)
    if target_t % k_chunk and target_t != steps:
        raise ValueError(
            f"stop_t={target_t} is not a chunk boundary (chunk_steps="
            f"{k_chunk}) — shards must quiesce at equal frontiers")
    n_chunks_target = math.ceil(target_t / k_chunk)

    os.makedirs(run_dir, exist_ok=True)
    spool_dir = os.path.join(run_dir, "spool")
    transport = str(scfg.get("transport", "spool"))
    if transport not in ("spool", "tcp"):
        raise ValueError(f"shard.transport must be 'spool' or 'tcp', "
                         f"got {transport!r}")
    server = None
    opened_bus = False
    tcfg = config.get("telemetry", {})
    # Trace plane (ISSUE 20): ``telemetry.trace = true`` makes this
    # coordinator the trace root — every slot launch exports the context
    # so worker/supervisor records land in one causal tree.  The flush
    # cadence rides the env the same way the slot export reads it.
    flush_cfg = float(tcfg.get("flush_interval_s", 0.0) or 0.0)
    if flush_cfg and not os.environ.get(telemetry.ENV_FLUSH):
        os.environ[telemetry.ENV_FLUSH] = str(flush_cfg)
    if tcfg.get("trace") and not telemetry.trace.enabled():
        telemetry.trace.enable()
    if tcfg.get("enabled", True) and not telemetry.active():
        telemetry.init_run(run_dir)
        opened_bus = True
    journal = sj.Journal(os.path.join(run_dir, JOURNAL_FILE))
    shards: dict[int, _Shard] = {}
    t_run0 = time.monotonic()
    try:
        rep = sj.replay(journal.path)
        plan = {"communities": C, "workers": n_workers,
                "ranges": [[a, b] for a, b in ranges], "steps": int(steps),
                "chunk_steps": k_chunk}
        if rep.plan is not None:
            got = {k: rep.plan.get(k) for k in plan}
            if got != plan:
                raise ValueError(
                    f"shard run {run_dir} was journaled for plan {got}, "
                    f"asked to run {plan} — reshard the checkpoints "
                    f"(tools/reshard_checkpoint.py) instead of mutating a "
                    f"run in place")
        else:
            journal.plan(C, n_workers, ranges, int(steps), k_chunk)
        # Fresh ownership token: orphan workers of a dead predecessor
        # exit at their next chunk boundary (spool EPOCH fence).
        token = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
        journal.epoch(token)
        sp.write_epoch(spool_dir, token)
        if transport == "tcp":
            # Chunk ingest over the wire (shard/transport.py,
            # architecture.md §20): workers push checksummed frames to
            # this server, which journal-acks BEFORE the 200.  The spool
            # stays the durable store — the server persists into the
            # same outbox files — so every resume/reshard/fence path
            # below is transport-agnostic.
            from dragg_tpu.shard.transport import ChunkIngestServer

            server = ChunkIngestServer(
                spool_dir, journal, token,
                listen=str(scfg.get("listen", "127.0.0.1:0")),
                run_dir=run_dir, log=log)
            server.start()
        telemetry.emit("shard.plan", communities=C, workers=n_workers,
                       ranges=[[a, b] for a, b in ranges], steps=steps,
                       chunk_steps=k_chunk, target_t=target_t,
                       resumed=rep.plan is not None)
        if log:
            log(f"plan: {C} communities over {n_workers} shards "
                f"{ranges}, steps={steps}, chunk={k_chunk}"
                + (f", resume frontier {dict(rep.frontier)}"
                   if rep.plan is not None else ""))

        for k, (c0, c1) in enumerate(ranges):
            sh = _Shard(ShardSlot(spool_dir, k, epoch=token, log=log),
                        c0, c1)
            sh.restarts = rep.restarts.get(k, 0)
            shards[k] = sh
            spec = {"config": config, "data_dir": data_dir, "c0": c0,
                    "c1": c1, "steps": int(steps), "chunk_steps": k_chunk,
                    "stop_t": target_t if target_t < steps else None,
                    "start_index": start_index}
            if server is not None:
                # tcp-only keys: the spool-mode spec stays byte-identical
                # to round 18.
                spec["transport"] = "tcp"
                spec["endpoint"] = server.endpoint
                spec["transport_retry_s"] = float(
                    scfg.get("transport_retry_s", 10.0))
            sp.atomic_write_json(sp.shard_spec_path(spool_dir, k), spec)
            # A successor CONTINUES the generation numbering so per-gen
            # logs and payload ``gen`` tags stay distinct across
            # coordinator restarts (the steady-rate filter in _merge
            # treats each generation's first chunk as its compile).
            sh.slot.gen = rep.gens.get(k, 0)
            # Replay the journaled frontier from the retained spool files
            # — the payloads of record for every acked chunk.  Capped at
            # THIS run's target: a resume with a smaller stop_t must not
            # merge (or emit) chunks past the quiesce barrier.
            for seq in range(min(rep.frontier.get(k, 0), n_chunks_target)):
                payload = sp.read_json(sp.chunk_path(spool_dir, k, seq))
                if payload is None:
                    raise ValueError(
                        f"journal acks shard {k} chunk {seq} but its spool "
                        f"file is missing/torn — the run dir is corrupt")
                sh.payloads[seq] = payload
                sh.frontier = seq + 1

        # Launch platform: "cpu" pins the wedge-proof CPU env, "tpu" and
        # "auto" inherit the caller's backend resolution ("inherit" in
        # the journal/logs).  A shard the journal says already degraded
        # stays on its degraded platform (provenance respected across
        # coordinator restarts).
        base_platform = "cpu" if platform == "cpu" else (
            "tpu" if platform == "tpu" else "inherit")
        for k, sh in shards.items():
            if sh.frontier >= n_chunks_target:
                continue
            p = rep.platforms.get(k, base_platform)
            sh.slot.launch("cpu" if p == "cpu" else base_platform)
            journal.launch(k, sh.slot.gen, sh.slot.platform, sh.c0, sh.c1)

        def _drain(sh: _Shard, k: int) -> None:
            """Merge every consecutive ready chunk at the frontier."""
            while sh.frontier < n_chunks_target:
                seq = sh.frontier
                t_m0 = time.monotonic()
                payload = sp.read_json(sp.chunk_path(spool_dir, k, seq))
                if payload is None or int(payload.get("seq", -1)) != seq:
                    return
                sh.payloads[seq] = payload
                sh.frontier = seq + 1
                sh.failures = 0
                sh.progress_at = time.monotonic()  # re-arm the deadline
                # A wire-ingested chunk was journal-acked BEFORE the 200
                # (journal-before-ack) — re-journaling here would record
                # a double merge.  Degraded-to-spool files (and every
                # spool-transport chunk) still get their ack from this
                # loop.
                if not (server is not None and server.was_acked(k, seq)):
                    journal.chunk(k, seq, int(payload["t0"]),
                                  int(payload["t1"]))
                # Trace-only extras: a merge span parented on the chunk
                # span that rode the payload, plus the merge duration
                # the critical-path "merge" bucket attributes.  Nothing
                # when tracing is off (round-19 byte identity).
                extra = telemetry.trace.child_fields(
                    parent=payload.get("trace_span"))
                if extra:
                    extra["s"] = round(time.monotonic() - t_m0, 6)
                telemetry.emit("shard.chunk", shard=k, seq=seq,
                               t0=payload["t0"], t1=payload["t1"],
                               solve_rate=payload.get("solve_rate"),
                               device_s=payload.get("device_s"),
                               **extra)
                if payload.get("device_s") is not None:
                    telemetry.observe("shard.chunk_s",
                                      float(payload["device_s"]))

        while True:
            for k, sh in shards.items():
                _drain(sh, k)
                if sh.frontier >= n_chunks_target:
                    if not sh.done_journaled:
                        sh.done_journaled = True
                        journal.done(k, sh.frontier)
                        telemetry.emit("shard.done", shard=k,
                                       chunks=sh.frontier)
                        if log:
                            log(f"shard s{k} complete "
                                f"({sh.frontier} chunks)")
                    if sh.slot.alive() and sh.slot.elapsed() > 30.0:
                        sh.slot.kill()  # lingering past its natural exit
                    continue
                if not sh.slot.alive() and sh.slot.proc is not None:
                    # Late outbox harvest first: the worker may have died
                    # AFTER writing its final chunk.
                    _drain(sh, k)
                    if sh.frontier >= n_chunks_target:
                        continue
                    kind = sh.slot.verdict()
                    _record_failure(journal, sh, k, kind)
                    _relaunch(journal, sh, k, base_platform,
                              degrade_after, degrade_to_cpu,
                              max_restarts, log)
                    continue
                if sh.slot.alive():
                    killed = None
                    age = sh.slot.heartbeat_age()
                    if stall_s is not None and age is not None \
                            and age > stall_s:
                        killed = dict(stalled=True)
                    elif sh.stalled_for() > deadline_s:
                        killed = dict(timed_out=True)
                    if killed:
                        sh.slot.kill()
                        kind = sh.slot.verdict(**killed)
                        _record_failure(journal, sh, k, kind)
                        _relaunch(journal, sh, k, base_platform,
                                  degrade_after, degrade_to_cpu,
                                  max_restarts, log)
            if all(sh.frontier >= n_chunks_target
                   for sh in shards.values()):
                break
            time.sleep(poll_s)

        result = _merge(shards, ranges, config, C, k_chunk, target_t,
                        steps, time.monotonic() - t_run0)
        sp.atomic_write_json(os.path.join(run_dir, MERGED_FILE), result)
        telemetry.emit("shard.merge", communities=C, workers=n_workers,
                       steps=target_t, solve_rate=result["solve_rate"],
                       restarts=result["restarts"],
                       elapsed_s=result["elapsed_s"])
        return result
    finally:
        for sh in shards.values():
            sh.slot.kill(grace_s=2.0)
        if server is not None:
            server.stop()
        journal.close()
        if opened_bus:
            telemetry.close_run(write_metrics=True)


def _record_failure(journal: sj.Journal, sh: _Shard, k: int,
                    kind: str) -> None:
    rc = sh.slot.proc.poll() if sh.slot.proc is not None else None
    sh.failures += 1
    journal.exit(k, sh.slot.gen, rc, kind)
    telemetry.emit("shard.exit", shard=k, gen=sh.slot.gen, rc=rc,
                   failure=kind)
    telemetry.emit("failure." + kind,  # dragg: disable=DT007, kind from taxonomy.FAILURE_KINDS, each registered literally
                   source="shard", label=f"s{k}", rc=rc)


def _relaunch(journal: sj.Journal, sh: _Shard, k: int, base_platform: str,
              degrade_after: int, degrade_to_cpu: bool, max_restarts: int,
              log) -> None:
    if sh.restarts >= max_restarts:
        raise RuntimeError(
            f"shard {k} failed {sh.restarts + 1} times (restart budget "
            f"{max_restarts}) — giving up; the journal and checkpoints "
            f"hold the frontier for a later resume")
    sh.restarts += 1
    platform = sh.slot.platform or base_platform
    if (degrade_to_cpu and platform != "cpu"
            and sh.failures >= degrade_after):
        journal.transition(k, platform, "cpu", None)
        telemetry.emit("shard.transition", shard=k, from_platform=platform,
                       to_platform="cpu")
        telemetry.emit("degrade.transition", from_platform=platform,
                       to_platform="cpu", failure=None)
        platform = "cpu"
        if log:
            log(f"shard s{k} degrading to cpu after {sh.failures} "
                f"consecutive failures")
    sh.slot.launch(platform)
    journal.launch(k, sh.slot.gen, sh.slot.platform, sh.c0, sh.c1)


def _merge(shards: dict[int, _Shard], ranges, config: dict, C: int,
           k_chunk: int, target_t: int, steps: int,
           elapsed_s: float) -> dict:
    """Assemble the merged result: per-community (T, C) series in
    community-major (``real_home_pairs``) order, fleet totals, and the
    run provenance."""
    series_names = sorted(next(iter(shards[0].payloads.values()))
                          ["series"]) if shards[0].payloads else []
    series: dict[str, np.ndarray] = {}
    for name in series_names:
        per_shard = {}
        for k, sh in shards.items():
            blocks = [np.asarray(sh.payloads[seq]["series"][name],
                                 dtype=np.float64)
                      for seq in range(sh.frontier)]
            per_shard[k] = (np.concatenate(blocks, axis=0) if blocks
                            else np.zeros((0, sh.c1 - sh.c0)))
        series[name] = merge_shard_series(per_shard, ranges)
    B = int(config["community"]["total_number_homes"])
    solved = series.get("solved")
    T = solved.shape[0] if solved is not None else 0
    solve_rate = (float(solved.sum()) / max(T * C * B, 1)
                  if solved is not None else None)
    viol_max = max((sh.payloads[seq].get("viol_max", 0.0)
                    for sh in shards.values()
                    for seq in range(sh.frontier)), default=0.0)
    band_tol = max((sh.payloads[seq].get("band_tol", 0.05)
                    for sh in shards.values()
                    for seq in range(sh.frontier)), default=0.05)
    platforms = sorted({sh.payloads[seq].get("platform", "?")
                        for sh in shards.values()
                        for seq in range(sh.frontier)})
    # Steady-state device rate: per-chunk device seconds EXCLUDING each
    # generation's first chunk (it carries the compile) — the honest
    # home-steps/s the N-shard vs in-process A/B compares
    # (docs/perf_notes.md).
    steady_s, steady_steps = 0.0, 0
    for sh in shards.values():
        seen_gen = set()
        for seq in range(sh.frontier):
            p = sh.payloads[seq]
            gen = p.get("gen", 1)
            if gen not in seen_gen:
                seen_gen.add(gen)  # first chunk of this gen = compile
                continue
            if p.get("device_s") is None:
                continue  # resharded history carries no device wall
            steady_s += float(p["device_s"])
            steady_steps += int(p["t1"]) - int(p["t0"])
    return {
        "ok": bool(viol_max <= band_tol),
        "communities": C,
        "homes_per_community": B,
        "homes_total": C * B,
        "workers": len(shards),
        "ranges": [[a, b] for a, b in ranges],
        "steps": target_t,
        "stopped_early": target_t < steps,
        "chunk_steps": k_chunk,
        "series": {k: v.tolist() for k, v in series.items()},
        "totals": {k: v.sum(axis=1).tolist() for k, v in series.items()},
        "solve_rate": (round(solve_rate, 4)
                       if solve_rate is not None else None),
        "viol_max": round(float(viol_max), 5),
        "platforms": platforms,
        "restarts": {k: sh.restarts for k, sh in shards.items()
                     if sh.restarts},
        "elapsed_s": round(elapsed_s, 2),
        "home_steps_per_s": round(C * B * target_t / max(elapsed_s, 1e-9),
                                  1),
        "steady_home_steps_per_s": (
            round(C * B * steady_steps / steady_s, 1)
            if steady_s > 0 and steady_steps > 0 else None),
    }
