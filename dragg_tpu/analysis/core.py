"""dragglint core — single-pass AST dispatch, findings, suppressions,
baseline (ISSUE 14).

The framework that replaced ``tools/lint.py``'s seven ad-hoc checks:

* every rule declares the AST node types it wants (``node_types``) and
  one shared recursive walk dispatches each node to every interested
  rule, maintaining the lexical scope stack (function / lambda / class)
  the JAX rules need — ONE walk per file regardless of rule count (the
  perf guard in tests/test_analysis.py pins the full-repo run);
* stable rule IDs (``DT0xx``), per-rule severity (``error`` fails the
  run, ``warn`` is reported only) and per-rule scope globs (fnmatch
  against the repo-relative posix path; ``*`` crosses ``/``);
* ONE suppression syntax — ``# dragg: disable=DT0xx[,DT0yy][, reason]``
  on the offending line, or ``# dragg: disable-file=DT0xx[, reason]``
  anywhere in the file — with the legacy per-check markers
  (``# device-call-ok:`` etc.) grandfathered: still honored, but each
  run warns once so downstream callers migrate;
* a committed baseline (``.dragglint-baseline.json`` at the repo root):
  entries ``{rule, path, count, reason}`` absorb up to ``count``
  findings of ``rule`` in ``path``, so a new rule can land warn-first
  against existing debt and ratchet — findings beyond the count stay
  live errors, and a shrunk count is reported as a stale entry to
  tighten.

This module must stay importable with NO third-party dependencies (in
particular: no jax) — the analyzer is exactly the tool you reach for
when the axon tunnel is wedged and ``import jax`` would hang
(CLAUDE.md gotchas).
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from dataclasses import dataclass, field

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BASELINE_NAME = ".dragglint-baseline.json"
SKIP_DIRS = {".git", "__pycache__", ".cache", "outputs", "native/_build",
             ".pytest_cache", ".claude"}

SEVERITIES = ("error", "warn")

# The canonical ID registry (rules.RULE_IDS re-exports it; the catalog
# test pins docs/analysis.md + fixture coverage against it).  Lives here
# so suppression parsing can validate IDs without importing the rules.
KNOWN_RULE_IDS = ("DT001", "DT002", "DT003", "DT004", "DT005", "DT006",
                  "DT007", "DT008", "DT009", "DT010", "DT011", "DT012",
                  "DT013", "DT014", "DT015", "DT016")

# Legacy per-check markers (rounds 6-14) — grandfathered so downstream
# docs/snippets keep working, mapped onto the rule IDs that replaced
# them.  ``# noqa`` keeps its historical meaning on import lines.
LEGACY_MARKERS = {
    "# device-call-ok:": "DT004",
    "# accept-timeout-ok:": "DT006",
    "# telemetry-name-ok:": "DT007",
    "# precision-ok:": "DT008",
    "# kkt-inv-ok:": "DT009",
}
_DISABLE = "# dragg: disable="
_DISABLE_FILE = "# dragg: disable-file="


@dataclass
class Finding:
    """One analyzer finding.  ``suppressed`` names the mechanism that
    silenced it (None = live); live error-severity findings fail the
    run."""

    rule: str
    severity: str
    path: str          # repo-relative, posix separators
    line: int
    message: str
    suppressed: str | None = None   # None | inline | file | legacy | baseline
    reason: str = ""

    @property
    def live(self) -> bool:
        return self.suppressed is None

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "severity": self.severity, "path": self.path,
             "line": self.line, "message": self.message}
        if self.suppressed:
            d["suppressed"] = self.suppressed
            if self.reason:
                d["reason"] = self.reason
        return d

    def render(self) -> str:
        tag = f" [{self.suppressed}]" if self.suppressed else ""
        return (f"{self.path}:{self.line}: {self.rule} "
                f"{self.severity}{tag}: {self.message}")


class Rule:
    """Base class for per-file rules.

    Subclasses set ``id``/``name``/``severity``/``scope`` (and optionally
    ``exclude``) and implement some of:

    * ``visit(node, ctx)`` for each node whose type is in ``node_types``
      (the shared walk calls it exactly once per node);
    * ``begin_file(ctx)`` / ``end_file(ctx)`` around each file's walk
      (per-file state lives on the rule instance — one analyzer run owns
      one instance set, built fresh by :func:`make_rules`);
    * ``on_lines(ctx)`` for purely textual checks (no AST needed).
    """

    id: str = "DT000"
    name: str = "unnamed"
    severity: str = "error"
    scope: tuple[str, ...] = ("*",)
    exclude: tuple[str, ...] = ()
    node_types: tuple[type, ...] = ()

    def applies(self, rel: str) -> bool:
        return (any(fnmatch.fnmatchcase(rel, g) for g in self.scope)
                and not any(fnmatch.fnmatchcase(rel, g) for g in self.exclude))

    def configure(self, root: str) -> None:
        """Called once by :func:`analyze` with the repo root under
        analysis — rules that read repo files outside the walked set
        (the telemetry registry) re-anchor here instead of silently
        using the installation's own tree."""

    def begin_file(self, ctx: FileContext) -> None:
        pass

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        pass

    def end_file(self, ctx: FileContext) -> None:
        pass

    def on_lines(self, ctx: FileContext) -> None:
        pass


class ProjectRule(Rule):
    """Repo-level rule (cross-file consistency — home-type registry,
    config docs).  Runs once per analysis, after the per-file walks."""

    def run_project(self, root: str) -> list[Finding]:
        return []


@dataclass
class FileContext:
    """Everything a rule may need about the file under analysis."""

    rel: str                       # repo-relative posix path
    src: str
    lines: list[str]
    tree: ast.AST | None
    findings: list[Finding] = field(default_factory=list)
    scope_stack: list[ast.AST] = field(default_factory=list)

    def line_text(self, lineno: int) -> str:
        return self.lines[lineno - 1] if 0 < lineno <= len(self.lines) else ""

    def report(self, rule: Rule, lineno: int, message: str) -> None:
        self.findings.append(Finding(rule.id, rule.severity, self.rel,
                                     lineno, message))

    def enclosing_functions(self) -> list[ast.AST]:
        """Innermost-last function/lambda scopes around the current node."""
        return [n for n in self.scope_stack
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))]


@dataclass
class Result:
    """One analysis run: findings plus run-level notes (legacy-marker
    warnings, stale baseline entries)."""

    findings: list[Finding]
    notes: list[str]
    files: int

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings
                if f.live and f.severity == "error"]

    @property
    def exit_code(self) -> int:
        return 1 if self.errors else 0

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "files": self.files,
            "findings": [f.to_dict() for f in self.findings],
            "notes": list(self.notes),
            "summary": {
                "errors": len(self.errors),
                "warns": len([f for f in self.findings
                              if f.live and f.severity == "warn"]),
                "suppressed": len([f for f in self.findings
                                   if f.suppressed not in (None, "baseline")]),
                "baselined": len([f for f in self.findings
                                  if f.suppressed == "baseline"]),
            },
        }


# --------------------------------------------------------------- suppression
def parse_disable(comment_tail: str) -> tuple[set[str], str]:
    """``DT004,DT005, reason text`` -> ({'DT004','DT005'}, 'reason text').
    Tokens from the front that look like rule IDs are IDs; the remainder
    is the free-form reason."""
    ids: set[str] = set()
    parts = comment_tail.split(",")
    i = 0
    while i < len(parts):
        tok = parts[i].strip()
        if len(tok) == 5 and tok[:2] == "DT" and tok[2:].isdigit():
            ids.add(tok)
            i += 1
        else:
            break
    reason = ",".join(parts[i:]).strip()
    return ids, reason


@dataclass
class Suppressions:
    """Per-file suppression state parsed once from the source lines."""

    by_line: dict[int, set[str]]        # inline disables
    reasons: dict[int, str]
    file_wide: set[str]                 # disable-file IDs
    file_reasons: dict[str, str]
    legacy_by_line: dict[int, str]      # lineno -> rule id (legacy marker)
    malformed: list[tuple[int, str]]    # (lineno, detail) — DT016 feed

    @classmethod
    def parse(cls, lines: list[str]) -> Suppressions:
        by_line: dict[int, set[str]] = {}
        reasons: dict[int, str] = {}
        file_wide: set[str] = set()
        file_reasons: dict[str, str] = {}
        legacy: dict[int, str] = {}
        malformed: list[tuple[int, str]] = []
        known = set(KNOWN_RULE_IDS)

        def vet(ids: set[str], tail: str, lineno: int) -> set[str]:
            """Drop unknown IDs and record malformed/unknown suppressions
            (a typo'd ID is a silent no-op otherwise — the author thinks
            the site is covered when it is not, DT016)."""
            head = tail.split(",")[0].strip().lower()
            if not ids:
                # Not malformed: documentation DESCRIBING the syntax —
                # the "DT0xx" placeholder (possibly "DT0xx[,DT0yy]…"),
                # or a marker that ENDS a string literal (the parser's
                # own constants, fixtures built by concatenation).
                if head.startswith("dt0xx") or \
                        tail.strip()[:1] in ("'", '"', "`"):
                    return ids
            # Scan EVERY comma token for id-like-but-invalid entries —
            # a typo'd ID after a valid one ("DT004,DT05, reason") would
            # otherwise fold silently into the reason text.
            bad = [t.strip() for t in tail.split(",")
                   if re.fullmatch(r"(?i)dt\d+", t.strip())
                   and t.strip() not in known]
            for t in bad:
                malformed.append(
                    (lineno, f"unknown or malformed rule ID {t}"))
            if not ids and not bad:
                malformed.append(
                    (lineno, "suppression names no valid rule ID"))
            return ids & known

        for i, line in enumerate(lines, 1):
            if _DISABLE_FILE in line:
                tail = line.split(_DISABLE_FILE, 1)[1]
                ids, reason = parse_disable(tail)
                ids = vet(ids, tail, i)
                file_wide |= ids
                for rid in ids:
                    file_reasons[rid] = reason
            elif _DISABLE in line:
                tail = line.split(_DISABLE, 1)[1]
                ids, reason = parse_disable(tail)
                by_line[i] = vet(ids, tail, i)
                reasons[i] = reason
            for marker, rid in LEGACY_MARKERS.items():
                if marker in line:
                    legacy[i] = rid
        return cls(by_line, reasons, file_wide, file_reasons, legacy,
                   malformed)

    def apply(self, finding: Finding, line_text: str) -> str | None:
        """Mark ``finding`` suppressed in place when a marker covers it;
        returns 'legacy' when a legacy marker did (caller counts those
        for the one-time migration warning)."""
        rid = finding.rule
        if rid in self.by_line.get(finding.line, ()):  # inline
            finding.suppressed = "inline"
            finding.reason = self.reasons.get(finding.line, "")
        elif rid in self.file_wide:
            finding.suppressed = "file"
            finding.reason = self.file_reasons.get(rid, "")
        elif self.legacy_by_line.get(finding.line) == rid:
            finding.suppressed = "legacy"
        elif rid == "DT002" and "noqa" in line_text:
            # ``# noqa`` is NOT a legacy dragglint marker — it keeps its
            # permanent flake8 meaning (the hosted CI runs flake8 on the
            # same files), so it suppresses DT002 without the migration
            # warning.
            finding.suppressed = "noqa"
        return finding.suppressed


# ------------------------------------------------------------------ baseline
def load_baseline(path: str) -> list[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    return list(data.get("entries", []))


def apply_baseline(findings: list[Finding], entries: list[dict],
                   notes: list[str],
                   analyzed: set[str] | None = None) -> None:
    """Suppress up to ``count`` live findings per (rule, path) entry.
    Findings beyond the count stay live (the ratchet); a count larger
    than the live finding tally is reported stale so it gets tightened
    — but ONLY when the entry's path was actually analyzed this run
    (``analyzed``; None = everything): a --changed or subtree run that
    skipped the file must not tell the developer to ratchet to zero."""
    for e in entries:
        rule, path = e.get("rule", ""), e.get("path", "")
        try:
            count = int(e.get("count", 0))
        except (TypeError, ValueError):
            notes.append(f"malformed baseline entry {rule} {path}: count "
                         f"{e.get('count')!r} is not an integer — entry "
                         f"ignored")
            continue
        reason = e.get("reason", "")
        if not reason:
            notes.append(f"baseline entry {rule} {path}: missing reason "
                         f"(every baselined debt needs one)")
        matched = 0
        for f in findings:
            if matched >= count:
                break
            if f.live and f.rule == rule and f.path == path:
                f.suppressed = "baseline"
                f.reason = reason
                matched += 1
        if matched < count and (analyzed is None or path in analyzed):
            notes.append(
                f"stale baseline entry: {rule} {path} allows {count} but "
                f"only {matched} found — ratchet the count down")


# ---------------------------------------------------------------- the walk
def _dispatch_walk(tree: ast.AST, rules: list[Rule], ctx: FileContext) -> None:
    """ONE recursive traversal dispatching each node to every interested
    rule, maintaining ``ctx.scope_stack`` (class/function/lambda nesting)
    so rules can ask about their lexical context."""
    interest: dict[type, list[Rule]] = {}
    for r in rules:
        for t in r.node_types:
            interest.setdefault(t, []).append(r)
    if not interest:
        return
    scope_types = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
    stack = ctx.scope_stack

    def visit(node: ast.AST) -> None:
        for r in interest.get(type(node), ()):
            r.visit(node, ctx)
        scoped = isinstance(node, scope_types)
        if scoped:
            stack.append(node)
        for child in ast.iter_child_nodes(node):
            visit(child)
        if scoped:
            stack.pop()

    visit(tree)


def check_source(src: str, rel: str, rules: list[Rule]) -> list[Finding]:
    """Run the per-file pipeline on one source string (the test fixtures'
    entry point — ``rel`` decides which scope globs apply).  Inline /
    file-level / legacy suppressions are applied; baseline is not."""
    applicable = [r for r in rules
                  if not isinstance(r, ProjectRule) and r.applies(rel)]
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [Finding("DT001", "error", rel, e.lineno or 1,
                        f"syntax error: {e.msg}")]
    ctx = FileContext(rel=rel, src=src, lines=lines, tree=tree)
    for r in applicable:
        r.begin_file(ctx)
        r.on_lines(ctx)
    _dispatch_walk(tree, applicable, ctx)
    for r in applicable:
        r.end_file(ctx)
    sup = Suppressions.parse(lines)
    for lineno, detail in sup.malformed:
        ctx.findings.append(Finding(
            "DT016", "error", rel, lineno,
            f"{detail} — a broken suppression is a silent no-op; fix "
            f"the ID list (# dragg: disable=DT0xx[, reason])"))
    for f in ctx.findings:
        sup.apply(f, ctx.line_text(f.line))
    ctx.findings.sort(key=lambda f: (f.line, f.rule))
    return ctx.findings


def iter_py_files(root: str):
    for base, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs
                   if d not in SKIP_DIRS and not d.startswith(".")]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(base, f)


def analyze(root: str = ROOT, paths: list[str] | None = None,
            rules: list[Rule] | None = None,
            baseline_path: str | None = None,
            use_baseline: bool = True) -> Result:
    """Analyze ``paths`` (default: every .py under ``root``) and the
    project-level rules; apply the committed baseline unless disabled."""
    from dragg_tpu.analysis.rules import make_rules

    rules = make_rules() if rules is None else rules
    for r in rules:
        r.configure(root)
    notes: list[str] = []
    findings: list[Finding] = []
    legacy_seen: list[str] = []
    analyzed: set[str] = set()
    files = 0
    for path in (paths if paths is not None else iter_py_files(root)):
        rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
        analyzed.add(rel)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            notes.append(f"unreadable: {rel}: {e}")
            continue
        files += 1
        file_findings = check_source(src, rel, rules)
        for f in file_findings:
            if f.suppressed == "legacy":
                legacy_seen.append(f"{f.path}:{f.line}")
        findings.extend(file_findings)
    for r in rules:
        if isinstance(r, ProjectRule):
            findings.extend(r.run_project(root))
    if legacy_seen:
        notes.append(
            f"legacy suppression markers honored at {len(legacy_seen)} "
            f"site(s) (first: {legacy_seen[0]}) — migrate to "
            f"'# dragg: disable=DT0xx, reason' (docs/analysis.md)")
    if use_baseline:
        bp = baseline_path or os.path.join(root, BASELINE_NAME)
        apply_baseline(findings, load_baseline(bp), notes, analyzed)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return Result(findings=findings, notes=notes, files=files)
