"""dragglint CLI — ``python -m dragg_tpu.analysis`` (ISSUE 14).

Exit 0 iff no live error-severity findings.  ``tools/lint.py`` shims
here so CI, the pre-commit habit, and muscle memory all keep working.

    python -m dragg_tpu.analysis                 # whole repo + project rules
    python -m dragg_tpu.analysis dragg_tpu/ops   # a subtree
    python -m dragg_tpu.analysis --changed       # git-diff'd files only
    python -m dragg_tpu.analysis --json out.json # findings artifact (CI)
    python -m dragg_tpu.analysis --list-rules    # the DT0xx catalog
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from dragg_tpu.analysis.core import (
    BASELINE_NAME,
    ROOT,
    analyze,
    iter_py_files,
)
from dragg_tpu.analysis.rules import catalog, make_rules


def changed_py_files(root: str) -> list[str]:
    """Working-tree .py files that differ from HEAD (staged, unstaged,
    or untracked) — the fast pre-commit scope.  Deleted files drop out
    naturally (they no longer exist to analyze)."""
    proc = subprocess.run(
        ["git", "-C", root, "status", "--porcelain"],
        capture_output=True, text=True, timeout=30)
    if proc.returncode != 0:
        raise RuntimeError(f"git status failed: {proc.stderr.strip()}")
    out = []
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:          # rename: analyze the new side
            path = path.split(" -> ", 1)[1]
        path = path.strip('"')
        full = os.path.join(root, path)
        if path.endswith(".py") and os.path.isfile(full):
            out.append(full)
    return sorted(set(out))


def expand_paths(root: str, args_paths: list[str]) -> list[str] | None:
    """Positional paths -> concrete .py files (dirs recurse); None means
    the full default walk."""
    if not args_paths:
        return None
    out: list[str] = []
    for p in args_paths:
        full = os.path.abspath(p)
        if os.path.isdir(full):
            out.extend(iter_py_files(full))
        else:
            out.append(full)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dragg_tpu.analysis",
        description="dragglint: rule-based static analysis for JAX/"
                    "device/journal discipline (docs/analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: whole repo)")
    ap.add_argument("--root", default=ROOT,
                    help="repo root (default: autodetected)")
    ap.add_argument("--changed", action="store_true",
                    help="analyze only git-changed .py files (fast "
                         "pre-commit mode; project rules still run)")
    ap.add_argument("--json", metavar="PATH", dest="json_out",
                    help="write the findings document to PATH ('-' for "
                         "stdout) — the CI artifact")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the committed baseline (show all debt)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for row in catalog():
            print(f"{row['id']}  {row['severity']:<5}  {row['name']:<20} "
                  f"scope={','.join(row['scope'])}")
        return 0

    if args.changed:
        if args.paths:
            ap.error("--changed and explicit paths are mutually "
                     "exclusive — naming paths under --changed would "
                     "silently skip the unchanged ones")
        paths = changed_py_files(args.root)
    else:
        paths = expand_paths(args.root, args.paths)

    res = analyze(root=args.root, paths=paths, rules=make_rules(),
                  baseline_path=args.baseline,
                  use_baseline=not args.no_baseline)

    doc = res.to_dict()
    if args.json_out == "-":
        print(json.dumps(doc, indent=1))
    else:
        for f in res.findings:
            if f.live:
                print(f.render())
        if args.json_out:
            with open(args.json_out, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1)
    for note in res.notes:
        print(f"dragglint: note: {note}", file=sys.stderr)
    s = doc["summary"]
    print(f"dragglint: {res.files} files, {s['errors']} error(s), "
          f"{s['warns']} warn(s), {s['baselined']} baselined, "
          f"{s['suppressed']} suppressed", file=sys.stderr)
    return res.exit_code


if __name__ == "__main__":
    sys.exit(main())
