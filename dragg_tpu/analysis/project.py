"""dragglint project rules — repo-level consistency checks that span
files (ISSUE 14 satellite: migrated from tools/lint.py's home-type
check and tests/test_homes_data.py's config-doc check; the tests now
assert them through ``analysis.run_rules``).

DT010 home-type co-registration: every ``homes.HOME_TYPES`` entry must
      carry an ``ops/qp.TYPE_SPECS`` block spec, appear (quoted) in a
      parity-bearing test file, and be documented in docs/config.md —
      a scenario home type cannot ship half-wired (ISSUE 10).
DT011 config-key documentation: docs/config.md documents every leaf
      key of ``config.default_config`` within its own section (the
      CLAUDE.md convention: "config keys must be documented — a test
      enforces it"; the test now routes through this rule).

Both rules read literal tables via ast where possible; DT011 imports
``dragg_tpu.config`` (stdlib-only by construction) for the live default
tree — still no jax anywhere on the analyzer's import path.
"""

from __future__ import annotations

import ast
import os

from dragg_tpu.analysis.core import Finding, ProjectRule


def literal_names(path: str, var: str) -> list[str] | None:
    """String members of a top-level tuple/dict literal assigned to
    ``var`` in ``path`` (tuple -> elements, dict -> keys); None on parse
    failure so the rule degrades quietly instead of crashing the run."""
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        for t in targets:
            if not (isinstance(t, ast.Name) and t.id == var):
                continue
            v = node.value
            if isinstance(v, ast.Tuple):
                return [e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
            if isinstance(v, ast.Dict):
                return [k.value for k in v.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
    return None


class HomeTypeRule(ProjectRule):
    """DT010 (docstring above)."""

    id = "DT010"
    name = "home-type-registry"
    scope = ("dragg_tpu/homes.py", "dragg_tpu/ops/qp.py")

    def run_project(self, root: str) -> list[Finding]:
        home_types = literal_names(
            os.path.join(root, "dragg_tpu", "homes.py"), "HOME_TYPES")
        specs = literal_names(
            os.path.join(root, "dragg_tpu", "ops", "qp.py"), "TYPE_SPECS")
        if home_types is None or specs is None:
            return []  # parse problems are reported per-file (DT001)
        try:
            with open(os.path.join(root, "docs", "config.md"),
                      encoding="utf-8") as f:
                doc = f.read()
        except OSError:
            doc = ""
        # Parity evidence: the quoted type name appears in a test file
        # whose source mentions parity (the test_qp_parity /
        # test_bucketed / test_scenarios convention).
        parity_src = ""
        tests_dir = os.path.join(root, "tests")
        try:
            test_files = sorted(os.listdir(tests_dir))
        except OSError:
            test_files = []
        for fn in test_files:
            if not fn.endswith(".py"):
                continue
            try:
                with open(os.path.join(tests_dir, fn),
                          encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                continue
            if "parity" in src.lower():
                parity_src += src
        out = []

        def report(path, msg):
            out.append(Finding(self.id, self.severity, path, 1, msg))

        for t in home_types:
            if t not in specs:
                report("dragg_tpu/homes.py",
                       f"HOME_TYPES entry {t!r} has no ops/qp.TYPE_SPECS "
                       f"block spec — the bucketed engine cannot "
                       f"shape-specialize it")
            if f"`{t}`" not in doc and f"homes_{t}" not in doc:
                report("docs/config.md",
                       f"HOME_TYPES entry {t!r} undocumented — mention "
                       f"`{t}` (or its homes_{t} count key)")
            if f'"{t}"' not in parity_src and f"'{t}'" not in parity_src:
                report("tests",
                       f"HOME_TYPES entry {t!r} appears in no parity-"
                       f"bearing test file — add objective-parity "
                       f"coverage (tests/test_qp_parity.py pattern)")
        return out


class ConfigDocRule(ProjectRule):
    """DT011 (docstring above).  ``config`` is injectable so the
    negative self-test can run against a synthetic tree without
    doctoring the live package."""

    id = "DT011"
    name = "config-doc"
    scope = ("dragg_tpu/config.py", "docs/config.md")

    # Distribution keys are documented as a family, not per key.
    FAMILIES = ("home.hvac.", "home.wh.", "home.battery.", "home.pv.",
                "home.ev.", "home.heat_pump.")

    def __init__(self, config: dict | None = None):
        self._config = config

    def run_project(self, root: str) -> list[Finding]:
        if self._config is None:
            # Lazy: dragg_tpu.config is stdlib-only (tomllib + copy) —
            # safe on the analyzer's jax-free import path.
            from dragg_tpu.config import default_config

            config = default_config()
        else:
            config = self._config
        doc_path = os.path.join(root, "docs", "config.md")
        try:
            with open(doc_path, encoding="utf-8") as f:
                doc = f.read()
        except OSError:
            return [Finding(self.id, self.severity, "docs/config.md", 1,
                            "docs/config.md missing — every config key "
                            "must be documented there")]

        def leaves(d, pre=""):
            for k, v in d.items():
                if isinstance(v, dict):
                    yield from leaves(v, pre + k + ".")
                else:
                    yield pre + k, k

        # Match within the key's own section so a leaf name shared with
        # an already-documented key elsewhere can't satisfy the check.
        sections = {}
        for block in doc.split("\n## ")[1:]:
            title, _, body = block.partition("\n")
            sections[title.strip().split()[0].strip("[]")] = body

        def section_bodies(path):
            top = path.split(".")[0]
            for name, body in sections.items():
                if name == top or name.startswith(top):
                    yield body

        out = []
        for path, key in leaves(config):
            if path.startswith(self.FAMILIES):
                continue
            if not any(f"`{key}`" in body for body in section_bodies(path)):
                out.append(Finding(
                    self.id, self.severity, "docs/config.md", 1,
                    f"config key '{path}' undocumented — document "
                    f"`{key}` in its [{path.split('.')[0]}] section"))
        return out
