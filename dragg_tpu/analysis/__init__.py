"""dragglint — the repo's rule-based static analyzer (ISSUE 14).

Every invariant the repo learned the hard way — no bare
``jax.devices()`` (a wedged axon tunnel hangs backend init), deadlines
on every subprocess, dense matmuls through ``mxu_einsum``, fsync'd
journal records, no host syncs inside the jitted hot loop — enforced as
a catalog of DT0xx rules over the whole package instead of folklore in
entry-point whitelists.  ``python -m dragg_tpu.analysis`` runs it;
``tools/lint.py`` is a thin shim over the same engine.  Rule catalog
and suppression/baseline workflow: docs/analysis.md.

This package (and everything it imports) is stdlib-only: the analyzer
must run exactly when ``import jax`` would hang.
"""

from __future__ import annotations

from dragg_tpu.analysis.core import (  # noqa: F401
    BASELINE_NAME,
    FileContext,
    Finding,
    ProjectRule,
    Result,
    Rule,
    Suppressions,
    analyze,
    check_source,
    parse_disable,
)
from dragg_tpu.analysis.rules import RULE_IDS, catalog, make_rules  # noqa: F401


def run_rules(root: str | None = None, paths: list[str] | None = None,
              select: set[str] | None = None,
              use_baseline: bool = True) -> list["Finding"]:
    """The thin wrapper the test-suite asserts through (ISSUE 14
    satellite): run the analyzer (optionally a rule subset) and return
    LIVE findings — suppressed/baselined ones are already absorbed.

    ``select`` filters by rule ID ({'DT011'} runs just the config-doc
    rule).  Tests typically assert ``run_rules(select={...}) == []``.
    """
    from dragg_tpu.analysis.core import ROOT

    rules = make_rules()
    if select is not None:
        rules = [r for r in rules if r.id in select]
        if paths is None and all(isinstance(r, ProjectRule) for r in rules):
            # Project-rules-only selection: skip the per-file walk
            # entirely (it would parse ~140 files to discard every
            # finding) — the tests that assert DT010/DT011 take this.
            paths = []
    res = analyze(root=root or ROOT, paths=paths, rules=rules,
                  use_baseline=use_baseline)
    return [f for f in res.findings if f.live]
