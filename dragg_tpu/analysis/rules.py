"""dragglint rules — every invariant the repo learned the hard way, as
one catalog of DT0xx rules (ISSUE 14; full rationale per rule in
docs/analysis.md).

DT001 parse            every file parses (check-ast parity)
DT002 unused-import    autoflake parity (``# noqa`` grandfathered)
DT003 whitespace       no tabs in indent / trailing ws / missing EOF \\n
DT004 device-call      no bare jax.devices()/local_devices()/
                       default_backend() — a wedged axon tunnel hangs
                       backend init (CLAUDE.md gotchas, rounds 2-4)
DT005 subprocess-deadline  subprocess.run/check_* need timeout=; raw
                       sockets need a deadline in scope (settimeout /
                       create_connection timeout= — round-19 shard wire)
DT006 accept-loop      serve_forever() needs poll_interval=; raw
                       socket.accept() needs a suppression (ISSUE 7)
DT007 telemetry-name   emits name central-registry literals (round 7)
DT008 precision        dense contractions route through mxu_einsum in
                       the dense-family solver files (ISSUE 11/round 14)
DT009 kkt-inverse      no generic linalg.inv outside ops/ (round 10)
DT012 traced-host-sync no .item()/float()/bool()/np.asarray/device_get
                       in functions reachable from jit/scan roots — a
                       host sync inside the fused step serializes the
                       MXU hot loop (observatory zero-extra-syncs
                       invariant, arxiv 2311.18056 MXU-nativeness)
DT013 donation         jitted entry points carrying large state should
                       donate the carry (round-12 HBM halving; the CPU
                       sync caveat is the documented suppression)
DT014 determinism      no wall-clock / global-stream randomness in the
                       framework — seeds flow from config (fleet
                       seed-stride contract, round 12/15)
DT015 journal-fsync    record-writing paths in the serve journal and
                       checkpoint spool fsync before acknowledging
                       (the round-11 durability contract)

Project rules DT010 (home-type co-registration) and DT011 (config-key
documentation) live in dragg_tpu/analysis/project.py.

No third-party imports here (core.py docstring: the analyzer must run
while jax would hang).
"""

from __future__ import annotations

import ast
import os

from dragg_tpu.analysis.core import KNOWN_RULE_IDS, FileContext, Rule

# Scope shorthands (fnmatch globs against repo-relative posix paths;
# ``*`` crosses ``/``).  The framework-wide scope is the ISSUE-14
# widening: tools/ + bench.py entry points AND the whole package.
FRAMEWORK = ("dragg_tpu/*", "tools/*", "bench.py")


class UnusedImportRule(Rule):
    """DT002: a bound import never referenced (autoflake parity).  Names
    quoted anywhere in the file (``__all__`` / getattr re-export idioms)
    count as used; ``# noqa`` on the import line is grandfathered."""

    id = "DT002"
    name = "unused-import"
    node_types = (ast.Import, ast.ImportFrom, ast.Name)

    def begin_file(self, ctx: FileContext) -> None:
        self._imported: dict[str, int] = {}
        self._used: set[str] = set()

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                self._imported[a.asname or a.name.split(".")[0]] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    self._imported[a.asname or a.name] = node.lineno
        else:
            self._used.add(node.id)

    def end_file(self, ctx: FileContext) -> None:
        for name, lineno in sorted(self._imported.items(),
                                   key=lambda kv: kv[1]):
            if name in self._used or name == "annotations":
                continue
            if f'"{name}"' in ctx.src or f"'{name}'" in ctx.src:
                continue
            ctx.report(self, lineno, f"unused import '{name}'")


class WhitespaceRule(Rule):
    """DT003: trailing whitespace, tabs in indentation, newline at EOF."""

    id = "DT003"
    name = "whitespace"

    def on_lines(self, ctx: FileContext) -> None:
        for i, line in enumerate(ctx.lines, 1):
            if line != line.rstrip():
                ctx.report(self, i, "trailing whitespace")
            if line[:len(line) - len(line.lstrip())].count("\t"):
                ctx.report(self, i, "tab in indentation")
        if ctx.src and not ctx.src.endswith("\n"):
            ctx.report(self, len(ctx.lines), "no newline at end of file")


class DeviceCallRule(Rule):
    """DT004: bare jax.devices()/local_devices()/default_backend().  A
    wedged axon tunnel makes backend init HANG (CLAUDE.md; rounds 2-4
    outages) — device touches run in supervised/probed children, or
    through the one sanctioned helper (resilience.devices)."""

    id = "DT004"
    name = "device-call"
    scope = FRAMEWORK
    node_types = (ast.Call,)
    _CALLS = {"devices", "local_devices", "default_backend"}

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
                and fn.value.id == "jax" and fn.attr in self._CALLS):
            ctx.report(self, node.lineno,
                       f"bare jax.{fn.attr}() — probe/supervise it "
                       f"(dragg_tpu/resilience) or route through "
                       f"resilience.devices, the sanctioned helper")


class SubprocessDeadlineRule(Rule):
    """DT005: deadline discipline on anything that can block forever —
    subprocess.run/check_output/check_call/call without timeout=, and
    (round 19, the shard wire) a raw socket created without a deadline
    in scope: ``socket.socket(...)`` with no later ``.settimeout(...)``
    on the bound name in the same function, or
    ``socket.create_connection(...)`` without a timeout argument.  An
    un-deadlined child or socket op can hang forever, defeating the
    supervision layer (CLAUDE.md; the round-4 wedge burned hours).
    ``resilience.net.connect_deadline`` is the sanctioned socket
    helper."""

    id = "DT005"
    name = "subprocess-deadline"
    scope = FRAMEWORK
    node_types = (ast.Call, ast.Assign, ast.With)
    _FNS = {"run", "check_output", "check_call", "call"}
    _MODULE = "<module>"

    def begin_file(self, ctx: FileContext) -> None:
        # (holder, varname) -> creation lineno for sockets still waiting
        # for a settimeout in the same scope.
        self._socks: dict[tuple[object, str], int] = {}
        self._claimed: set[int] = set()   # creation Call node ids already
        # handled via their Assign/With binding (the walk visits parents
        # first, so the binding claims the inner Call before visit sees
        # it bare).

    @staticmethod
    def _creation(call: ast.AST) -> str | None:
        """"socket" | "create_connection" when ``call`` constructs a raw
        socket via the socket module, else None."""
        fn = call.func
        if (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "socket"
                and fn.attr in ("socket", "create_connection")):
            return fn.attr
        return None

    @staticmethod
    def _has_deadline(call: ast.AST, kind: str) -> bool:
        if kind != "create_connection":
            return False   # socket.socket() cannot take one at creation
        return (len(call.args) >= 2
                or any(kw.arg == "timeout" for kw in call.keywords))

    def _holder(self, ctx: FileContext) -> object:
        fns = ctx.enclosing_functions()
        return fns[-1] if fns else self._MODULE

    def _track_binding(self, call: ast.AST, name: str | None,
                       ctx: FileContext) -> None:
        kind = self._creation(call)
        if kind is None:
            return
        self._claimed.add(id(call))
        if self._has_deadline(call, kind):
            return
        if name is None:
            ctx.report(self, call.lineno,
                       f"socket.{kind}() without a deadline — every raw "
                       f"socket op needs a timeout (settimeout/timeout=; "
                       f"resilience.net.connect_deadline is the "
                       f"sanctioned helper)")
        else:
            self._socks[(self._holder(ctx), name)] = call.lineno

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call):
                name = (node.targets[0].id
                        if len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name) else None)
                self._track_binding(node.value, name, ctx)
            return
        if isinstance(node, ast.With):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    name = (item.optional_vars.id
                            if isinstance(item.optional_vars, ast.Name)
                            else None)
                    self._track_binding(item.context_expr, name, ctx)
            return
        fn = node.func
        if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
                and fn.value.id == "subprocess" and fn.attr in self._FNS
                and not any(kw.arg == "timeout" for kw in node.keywords)):
            ctx.report(self, node.lineno,
                       f"subprocess.{fn.attr}() without timeout= — an "
                       f"un-deadlined child can hang forever (use "
                       f"resilience.supervisor or pass a timeout)")
            return
        if (isinstance(fn, ast.Attribute) and fn.attr == "settimeout"
                and isinstance(fn.value, ast.Name)):
            self._socks.pop((self._holder(ctx), fn.value.id), None)
            return
        if id(node) not in self._claimed:
            # A creation consumed inline (passed straight to a helper,
            # returned, ...) — nothing to watch for a settimeout on.
            self._track_binding(node, None, ctx)

    def end_file(self, ctx: FileContext) -> None:
        for (_holder, name), lineno in sorted(self._socks.items(),
                                              key=lambda kv: kv[1]):
            ctx.report(self, lineno,
                       f"socket '{name}' created without a deadline in "
                       f"scope — call {name}.settimeout(...) (or pass "
                       f"timeout= to create_connection); "
                       f"resilience.net.connect_deadline is the "
                       f"sanctioned helper")


class AcceptLoopRule(Rule):
    """DT006: the serving daemon must stay interruptible —
    serve_forever() needs an explicit poll_interval= and raw
    socket.accept() loops need a socket timeout (ISSUE 7 drain
    budget)."""

    id = "DT006"
    name = "accept-loop"
    scope = FRAMEWORK
    node_types = (ast.Call,)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        if fn.attr == "serve_forever":
            if not any(kw.arg == "poll_interval" for kw in node.keywords):
                ctx.report(self, node.lineno,
                           "serve_forever() without poll_interval= — a "
                           "quiet socket must not outlive the drain "
                           "budget")
        elif fn.attr == "accept" and not node.args and not node.keywords:
            ctx.report(self, node.lineno,
                       "raw socket accept() — an un-timeouted accept "
                       "loop cannot drain; set a socket timeout and "
                       "suppress with the reason")


class TelemetryNameRule(Rule):
    """DT007: telemetry.emit/span/observe/inc/set_gauge must name a
    central-registry entry as a string literal (round 7 — free strings
    fragment the unified stream)."""

    id = "DT007"
    name = "telemetry-name"
    scope = FRAMEWORK
    node_types = (ast.Call,)
    _FNS = {"emit": "EVENTS", "span": "METRICS", "observe": "METRICS",
            "inc": "METRICS", "set_gauge": "METRICS"}

    def __init__(self, registry_path: str | None = None):
        self._explicit_path = registry_path
        self._registry_path = registry_path or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "dragg_tpu", "telemetry", "registry.py")
        self._registry: dict | None = None
        self._loaded = False

    def configure(self, root: str) -> None:
        """Validate names against the ANALYZED tree's registry, not this
        installation's (`--root` may point at another checkout); an
        explicit constructor path still wins."""
        if self._explicit_path is None:
            self._registry_path = os.path.join(
                root, "dragg_tpu", "telemetry", "registry.py")
            self._loaded = False
            self._registry = None

    def _load_registry(self) -> dict | None:
        """{'EVENTS': set, 'METRICS': set} parsed from the registry
        module's literal tables via ast (no import — the analyzer stays
        dependency-free)."""
        if self._loaded:
            return self._registry
        self._loaded = True
        try:
            with open(self._registry_path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None
        names: dict = {"EVENTS": set(), "METRICS": set()}
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Name) and t.id in names
                        and isinstance(node.value, ast.Dict)):
                    names[t.id] |= {k.value for k in node.value.keys
                                    if isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)}
        self._registry = names
        return names

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "telemetry" and fn.attr in self._FNS):
            return
        reg = self._load_registry()
        if reg is None:
            return
        table = self._FNS[fn.attr]
        arg = node.args[0] if node.args else None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in reg[table]:
                ctx.report(self, node.lineno,
                           f"telemetry.{fn.attr}({arg.value!r}) names "
                           f"nothing in registry.{table} — register it "
                           f"in dragg_tpu/telemetry/registry.py (and "
                           f"docs/telemetry.md)")
        else:
            ctx.report(self, node.lineno,
                       f"telemetry.{fn.attr}() with a computed name — "
                       f"pass a registry literal, or suppress with the "
                       f"reason if every runtime value is registered")


class PrecisionRule(Rule):
    """DT008: dense contractions in the solver families route through
    ops/precision.mxu_einsum, which owns the f32/bf16x3 cast discipline
    (ISSUE 11/round 14; rounds 2+9 measured hand-rolled dtypes
    diverging).  Non-matmul einsums (a trace) get a reasoned
    suppression."""

    id = "DT008"
    name = "precision"
    scope = ("dragg_tpu/ops/*",)
    exclude = ("dragg_tpu/ops/precision.py",)
    node_types = (ast.Call,)
    _CONTRACTIONS = {"einsum", "dot", "matmul", "tensordot", "dot_general"}

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute)
                and fn.attr in self._CONTRACTIONS):
            ctx.report(self, node.lineno,
                       f"bare dense contraction ({fn.attr}) — route it "
                       f"through ops/precision.mxu_einsum (which owns "
                       f"the f32/bf16x3 cast policy), or suppress with "
                       f"the reason if it is outside the dense-family "
                       f"policy")


class KktInverseRule(Rule):
    """DT009: no direct linalg.inv outside dragg_tpu/ops/ — KKT-sized
    operators go through the equilibrated, condition-checked route
    (ops.reluqp.equilibrated_spd_inverse; round 10: a generic LU inverse
    silently amplifies f32 conditioning error into the hot loop)."""

    id = "DT009"
    name = "kkt-inverse"
    scope = FRAMEWORK
    exclude = ("dragg_tpu/ops/*",)
    node_types = (ast.Call,)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "inv"
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "linalg"):
            ctx.report(self, node.lineno,
                       "direct linalg.inv outside ops/ — KKT-sized "
                       "inverses must go through "
                       "ops.reluqp.equilibrated_spd_inverse; suppress "
                       "with the reason if the operand is provably not "
                       "KKT-sized")


def _jit_target(node: ast.Call):
    """The function reference a ``jax.jit(...)``/``jit(...)`` call wraps
    (first positional arg), or None."""
    fn = node.func
    is_jit = (isinstance(fn, ast.Name) and fn.id == "jit") or (
        isinstance(fn, ast.Attribute) and fn.attr == "jit"
        and isinstance(fn.value, ast.Name) and fn.value.id == "jax")
    return node.args[0] if is_jit and node.args else None


def _is_jit_ref(node: ast.AST) -> bool:
    """Whether ``node`` is a reference to jax.jit / jit (for partial)."""
    return ((isinstance(node, ast.Name) and node.id == "jit")
            or (isinstance(node, ast.Attribute) and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax"))


_TRACE_FNS = {"scan": 1, "while_loop": 2, "fori_loop": 1, "cond": 2,
              "map": 1, "associative_scan": 1}
# fn-name -> how many leading callable args to treat as traced roots
# (while_loop/cond take (cond_fn, body_fn) / (true_fn, false_fn);
# fori_loop's body is its THIRD arg — special-cased below).


def _traced_fn_args(node: ast.Call) -> list[ast.AST]:
    """Function-valued args of a lax.scan/while_loop/fori_loop/cond/map
    call — every one of them is traced when the call executes."""
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _TRACE_FNS):
        return []
    base = fn.value
    if not ((isinstance(base, ast.Name) and base.id in ("lax", "jax"))
            or (isinstance(base, ast.Attribute) and base.attr == "lax")):
        return []
    if fn.attr == "fori_loop":
        return list(node.args[2:3])
    if fn.attr == "while_loop":
        return list(node.args[:2])
    if fn.attr == "cond":
        return list(node.args[1:3])
    return list(node.args[:1])


class TracedHostSyncRule(Rule):
    """DT012: no host syncs in traced code.  ``.item()``, ``jax.
    device_get``, ``np.asarray`` and ``float()``/``bool()``/``int()`` of
    a traced value inside any function reachable from a ``jax.jit`` /
    ``lax.scan``-family root either fail the trace or (worse, via
    callbacks/weak typing) silently force a device→host round trip per
    step — exactly what the observatory's zero-extra-syncs invariant and
    the fused fleet RL step (one jitted step, arxiv 2402.15932) forbid.

    Reachability is per-file and name-level: jit/scan roots plus the
    closure of same-file calls (``f(...)`` and ``self.f(...)``).
    ``float()``/``bool()``/``int()`` are only flagged when the argument
    names a PARAMETER of a reachable function (parameters of traced
    functions are traced; config attributes like ``self.params.dt`` are
    static and stay legal).  The rule is ``static_argnames``-aware:
    names listed in any ``jax.jit(..., static_argnames=...)`` in the
    file (directly or via a module-level tuple like the solvers'
    ``_STATIC``) are Python values at trace time, so host reads of them
    (``int(bank)``, ``np.asarray(pat.rows)``) are setup, not syncs."""

    id = "DT012"
    name = "traced-host-sync"
    scope = ("dragg_tpu/engine.py", "dragg_tpu/ops/*", "dragg_tpu/rl/*")
    node_types = (ast.Call, ast.FunctionDef, ast.AsyncFunctionDef,
                  ast.Assign)

    def begin_file(self, ctx: FileContext) -> None:
        self._defs: dict[str, list[ast.AST]] = {}
        self._roots: set[ast.AST] = set()
        self._root_names: set[str] = set()
        self._edges: list[tuple[ast.AST | None, str]] = []
        self._candidates: list[tuple[ast.AST, list[ast.AST], str]] = []
        self._static_names: set[str] = set()
        self._module_tuples: dict[str, set[str]] = {}

    def _record_static(self, call: ast.Call) -> None:
        """Union the names in a ``static_argnames=`` kwarg (literal
        tuple/str, or a module-level tuple constant by name)."""
        for kw in call.keywords:
            # static_argnums deliberately NOT accepted: its values are
            # positional indices, which a name-keyed filter cannot map to
            # parameters — claiming to honor it would silently not.
            if kw.arg != "static_argnames":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                self._static_names.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                self._static_names |= {e.value for e in v.elts
                                       if isinstance(e, ast.Constant)
                                       and isinstance(e.value, str)}
            elif isinstance(v, ast.Name):
                self._static_names |= self._module_tuples.get(v.id, set())

    @staticmethod
    def _base_name(node: ast.AST) -> str | None:
        while isinstance(node, ast.Attribute):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    # ---------------------------------------------------------- collection
    def _enclosing(self, ctx: FileContext) -> ast.AST | None:
        fns = ctx.enclosing_functions()
        return fns[-1] if fns else None

    def _mark_root_ref(self, ref: ast.AST) -> None:
        if isinstance(ref, ast.Name):
            self._root_names.add(ref.id)
        elif isinstance(ref, ast.Attribute):      # jax.jit(self._chunk_entry)
            self._root_names.add(ref.attr)
        elif isinstance(ref, ast.Lambda):
            self._roots.add(ref)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, ast.Assign):
            # Module-level tuple-of-str constants (the solvers' _STATIC).
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                names = {e.value for e in node.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)}
                if names:
                    self._module_tuples[node.targets[0].id] = names
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._defs.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                if _is_jit_ref(dec) or (
                        isinstance(dec, ast.Call)
                        and (any(_is_jit_ref(a) for a in dec.args)
                             or _is_jit_ref(dec.func))):
                    self._roots.add(node)
                if isinstance(dec, ast.Call):
                    self._record_static(dec)
            return
        # ast.Call
        if _is_jit_ref(node.func) or any(_is_jit_ref(a) for a in node.args):
            self._record_static(node)
        target = _jit_target(node)
        if target is not None:
            self._mark_root_ref(target)
        for ref in _traced_fn_args(node):
            self._mark_root_ref(ref)
        enclosing = self._enclosing(ctx)
        fn = node.func
        if isinstance(fn, ast.Name):
            self._edges.append((enclosing, fn.id))
        elif isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
                and fn.value.id == "self":
            self._edges.append((enclosing, fn.attr))
        # Host-sync candidates (scope stack copied: flagged iff any
        # enclosing function ends up reachable).
        stack = list(ctx.enclosing_functions())
        if isinstance(fn, ast.Attribute):
            if fn.attr == "item" and not node.args:
                self._candidates.append((node, stack, ".item()"))
            elif fn.attr == "device_get" and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "jax":
                self._candidates.append((node, stack, "jax.device_get"))
            elif fn.attr == "asarray" and isinstance(fn.value, ast.Name) \
                    and fn.value.id in ("np", "numpy", "onp") and node.args:
                self._candidates.append(
                    (node, stack, f"{fn.value.id}.asarray"))
        elif isinstance(fn, ast.Name) and fn.id in ("float", "bool", "int") \
                and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Name):
            self._candidates.append((node, stack, f"{fn.id}()"))

    # ---------------------------------------------------------- resolution
    @staticmethod
    def _params(fn_node: ast.AST) -> set[str]:
        a = fn_node.args
        names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        return set(names)

    def end_file(self, ctx: FileContext) -> None:
        reachable: set[ast.AST] = set(self._roots)
        pending = set(self._root_names)
        resolved: set[str] = set()
        while True:
            for name in pending - resolved:
                resolved.add(name)
                reachable.update(self._defs.get(name, ()))
            new_names = {name for enc, name in self._edges
                         if enc in reachable} - resolved
            if not new_names:
                break
            pending |= new_names
        for node, stack, kind in self._candidates:
            hit = [s for s in stack if s in reachable]
            if not hit:
                continue
            if kind.endswith("()") and kind != ".item()":
                # float()/bool()/int(): only traced when the argument is
                # a parameter of a reachable enclosing function.
                argname = node.args[0].id
                if not any(argname in self._params(s) for s in hit):
                    continue
            # static_argnames values are Python at trace time — reading
            # them on the host is setup, not a sync.
            base = self._base_name(node.args[0]) if node.args else None
            if base is not None and base in self._static_names \
                    and kind != ".item()":
                continue
            ctx.report(self, node.lineno,
                       f"{kind} on a value inside jit/scan-reachable "
                       f"code — a host sync here serializes the fused "
                       f"step (move it outside the traced region, or "
                       f"suppress with the reason if the value is "
                       f"provably static)")


def _carries_state(params: set[str]) -> str | None:
    for p in params:
        low = p.lower()
        if low in ("state", "carry", "cstate", "community_state") or \
                low.endswith("_state") or low.endswith("_carry"):
            return p
    return None


class DonationRule(Rule):
    """DT013: a jitted step entry point whose signature carries large
    state (a ``state``/``carry`` parameter) without ``donate_argnums`` /
    ``donate_argnames`` re-allocates the carry every dispatch — donation
    halves the carry HBM at the 100k-home target (round 12).  The
    documented counter-case IS the suppression example: XLA:CPU executes
    donated computations synchronously (round-12 caveat, engine.
    run_chunk docstring), so CPU-path entries suppress with that
    reason."""

    id = "DT013"
    name = "donation"
    scope = ("dragg_tpu/*",)
    node_types = (ast.Call, ast.FunctionDef, ast.AsyncFunctionDef)

    def begin_file(self, ctx: FileContext) -> None:
        # name -> [(def node, its enclosing-function stack)] — the stack
        # disambiguates same-named nested defs (engine.py has two
        # distinct `wrapped`s; resolving by bare name would cross-talk).
        self._defs: dict[str, list[tuple[ast.AST, tuple[ast.AST, ...]]]] = {}
        self._deferred: list[tuple[ast.Call, str, tuple[ast.AST, ...]]] = []

    @staticmethod
    def _donates(call: ast.Call) -> bool:
        return any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in call.keywords)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._defs.setdefault(node.name, []).append(
                (node, tuple(ctx.enclosing_functions())))
            for dec in node.decorator_list:
                donated = isinstance(dec, ast.Call) and self._donates(dec)
                if (_is_jit_ref(dec) or (isinstance(dec, ast.Call) and (
                        _is_jit_ref(dec.func)
                        or any(_is_jit_ref(a) for a in dec.args)))) \
                        and not donated:
                    p = _carries_state(TracedHostSyncRule._params(node))
                    if p:
                        ctx.report(self, node.lineno, self._msg(node.name, p))
            return
        target = _jit_target(node)
        if target is None or self._donates(node):
            return
        stack = tuple(ctx.enclosing_functions())
        if isinstance(target, ast.Name):
            self._deferred.append((node, target.id, stack))
        elif isinstance(target, ast.Attribute):
            self._deferred.append((node, target.attr, stack))

    def _msg(self, fn_name: str, param: str) -> str:
        return (f"jit of '{fn_name}' carries state parameter '{param}' "
                f"without donate_argnums — donation halves the carry "
                f"HBM (round 12); suppress with the reason when the "
                f"non-donated entry is deliberate (e.g. the XLA:CPU "
                f"synchronous-donation caveat, engine.run_chunk)")

    def end_file(self, ctx: FileContext) -> None:
        for call, name, stack in self._deferred:
            cands = self._defs.get(name, ())
            if not cands:
                continue
            # Resolve to the lexically NEAREST def: the one sharing the
            # longest enclosing-function prefix with the call site.
            def shared(dstack):
                n = 0
                for a, b in zip(stack, dstack):
                    if a is not b:
                        break
                    n += 1
                return n
            d, _ = max(cands, key=lambda c: shared(c[1]))
            p = _carries_state(TracedHostSyncRule._params(d))
            if p:
                ctx.report(self, call.lineno, self._msg(name, p))


class DeterminismRule(Rule):
    """DT014: wall-clock and global-stream randomness in the framework
    break run reproducibility — seeds must flow from config (community c
    seeds ``random_seed + c*seed_stride``; fleet/RL runs are pinned
    deterministic by tests).  Seeded constructors (``random.Random(s)``,
    ``np.random.RandomState(s)``, ``default_rng``) and ``jax.random.*``
    are the sanctioned routes.  Wall-clock protocol sites (heartbeats,
    progress telemetry) suppress with the reason; ``time.monotonic`` is
    always fine (elapsed measurement is not identity)."""

    id = "DT014"
    name = "determinism"
    scope = ("dragg_tpu/*",)
    exclude = ("dragg_tpu/telemetry/*", "dragg_tpu/analysis/*")
    node_types = (ast.Call,)
    _SEEDED = {"Random", "SystemRandom", "RandomState", "default_rng",
               "Generator", "PCG64"}

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        base = fn.value
        # time.time / time.time_ns
        if isinstance(base, ast.Name) and base.id == "time" \
                and fn.attr in ("time", "time_ns"):
            ctx.report(self, node.lineno,
                       f"time.{fn.attr}() in framework code — wall "
                       f"clock is nondeterministic state; thread times "
                       f"from config/telemetry or suppress with the "
                       f"reason (heartbeat/progress protocol sites)")
        # datetime.now / datetime.utcnow (datetime.X or datetime.datetime.X)
        elif fn.attr in ("now", "utcnow", "today") and (
                (isinstance(base, ast.Name) and base.id == "datetime")
                or (isinstance(base, ast.Attribute)
                    and base.attr == "datetime")):
            ctx.report(self, node.lineno,
                       f"datetime.{fn.attr}() in framework code — wall "
                       f"clock is nondeterministic state; suppress with "
                       f"the reason if this is presentation-only")
        # random.X (module-level global stream)
        elif isinstance(base, ast.Name) and base.id == "random" \
                and fn.attr not in self._SEEDED:
            ctx.report(self, node.lineno,
                       f"random.{fn.attr}() uses the process-global "
                       f"stream — seed an explicit random.Random(seed) "
                       f"from config (fleet seed-stride contract)")
        # np.random.X / numpy.random.X (module-level global stream)
        elif isinstance(base, ast.Attribute) and base.attr == "random" \
                and isinstance(base.value, ast.Name) \
                and base.value.id in ("np", "numpy") \
                and fn.attr not in self._SEEDED:
            ctx.report(self, node.lineno,
                       f"np.random.{fn.attr}() uses the process-global "
                       f"stream — use np.random.RandomState(seed)/"
                       f"default_rng(seed) seeded from config")


class JournalFsyncRule(Rule):
    """DT015: the serve journal's durability contract (an acknowledged
    request survives ANY process death) and the checkpoint spool's
    resume contract both hinge on write+flush+fsync BEFORE the caller
    proceeds — a rename without fsync can publish an empty file after
    power loss.  Every function in the journal/spool scope that writes
    records must fsync in the same function."""

    id = "DT015"
    name = "journal-fsync"
    scope = ("dragg_tpu/serve/journal.py", "dragg_tpu/serve/spool.py",
             "dragg_tpu/checkpoint.py", "dragg_tpu/shard/journal.py")
    node_types = (ast.Call,)
    _WRITERS = {"write", "writelines", "savez", "savez_compressed"}

    _MODULE = "<module>"   # holder for writes outside any function —
    # module-init code in the durability files is held to the same
    # contract (a blind spot here would let an un-fsync'd publish back)

    def begin_file(self, ctx: FileContext) -> None:
        self._writes: dict[object, int] = {}    # holder -> first lineno
        self._fsyncs: set[object] = set()

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        fn = node.func
        is_write = (isinstance(fn, ast.Attribute)
                    and fn.attr in self._WRITERS) or (
            isinstance(fn, ast.Attribute) and fn.attr == "dump"
            and isinstance(fn.value, ast.Name)
            and fn.value.id in ("json", "pickle"))
        is_fsync = isinstance(fn, ast.Attribute) and fn.attr == "fsync"
        if not (is_write or is_fsync):
            return
        fns = ctx.enclosing_functions()
        holder = fns[-1] if fns else self._MODULE
        if is_fsync:
            self._fsyncs.add(holder)
        else:
            self._writes.setdefault(holder, node.lineno)

    def end_file(self, ctx: FileContext) -> None:
        for holder, lineno in self._writes.items():
            if holder not in self._fsyncs:
                where = (holder if holder is self._MODULE
                         else f"'{getattr(holder, 'name', '<lambda>')}'")
                ctx.report(self, lineno,
                           f"record write in {where} without os.fsync "
                           f"before returning — a crash can lose an "
                           f"acknowledged record (journal/checkpoint "
                           f"durability contract)")


def make_rules() -> list[Rule]:
    """Fresh rule instances for one analysis run (rules hold per-file
    state).  Project rules are appended so ``analyze`` runs them after
    the per-file walks."""
    from dragg_tpu.analysis.project import ConfigDocRule, HomeTypeRule

    return [
        UnusedImportRule(),
        WhitespaceRule(),
        DeviceCallRule(),
        SubprocessDeadlineRule(),
        AcceptLoopRule(),
        TelemetryNameRule(),
        PrecisionRule(),
        KktInverseRule(),
        TracedHostSyncRule(),
        DonationRule(),
        DeterminismRule(),
        JournalFsyncRule(),
        HomeTypeRule(),
        ConfigDocRule(),
    ]


RULE_IDS = KNOWN_RULE_IDS


def catalog() -> list[dict]:
    """[{id, name, severity, scope}] for --list-rules and the docs
    test (docs/analysis.md must document every registered rule).
    DT001 (parse) and DT016 (bad-suppression) are framework-level —
    emitted by core.check_source, not rule instances."""
    rows = [{"id": "DT001", "name": "parse", "severity": "error",
             "scope": ("*",)},
            {"id": "DT016", "name": "bad-suppression", "severity": "error",
             "scope": ("*",)}]
    for r in make_rules():
        rows.append({"id": r.id, "name": r.name, "severity": r.severity,
                     "scope": r.scope})
    rows.sort(key=lambda r: r["id"])
    return rows
