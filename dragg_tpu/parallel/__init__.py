"""Multi-chip parallelism over the home axis.

The reference's only parallelism strategy is an embarrassingly-parallel
process-pool fan-out over homes with Redis as the communication backend
(dragg/aggregator.py:723-724, dragg/redis_client.py:13-25).  The TPU-native
equivalent (SURVEY.md §2.3) shards the home axis of the batched community
program over a ``jax.sharding.Mesh``: every per-home array is placed with
``NamedSharding(mesh, P("homes"))``, the engine step is jitted over the mesh,
and XLA's SPMD partitioner inserts the collectives — the community's one
reduction (``agg_load = Σ p_grid``, dragg/aggregator.py:751) becomes a single
``psum`` riding ICI.  No KV store, no pickling, no host round-trips in the
hot loop.
"""

from dragg_tpu.parallel.mesh import (
    ShardedEngine,
    make_mesh,
    make_sharded_engine,
    pad_batch,
    shard_state,
)

__all__ = [
    "ShardedEngine",
    "make_mesh",
    "make_sharded_engine",
    "pad_batch",
    "shard_state",
]
