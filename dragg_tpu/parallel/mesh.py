"""Mesh construction, batch padding, and the sharded community engine.

Design (SURVEY.md §2.3, §7 step 4): the community is data-parallel over the
home axis — the reference fans one process per home over a pathos pool
(dragg/aggregator.py:723-724); here the axis is sharded over the TPU mesh and
XLA inserts the collectives.  Environment series (OAT/GHI/TOU) are replicated
— they are the analog of the reference pushing full series into Redis once
(dragg/aggregator.py:653-662) — while every per-home tensor (state, QP
coefficients, water-draw schedules) is sharded on mesh axis ``"homes"``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dragg_tpu.engine import CommunityState, Engine, EngineParams
from dragg_tpu.homes import pad_batch  # noqa: F401 — re-exported API

HOMES_AXIS = "homes"


def make_mesh(n_devices: int | None = None, axis_name: str = HOMES_AXIS,
              devices=None) -> Mesh:
    """A 1-D device mesh over the home axis.

    Homes are independent problems, so a single mesh axis is the whole
    parallelism taxonomy for this workload (SURVEY.md §2.3: TP/PP/SP/EP are
    structurally absent in the reference; DP-over-homes is the core
    strategy).  Multi-host pod slices extend the same axis over DCN —
    the device enumeration already spans all processes.

    Device enumeration routes through the sanctioned helper
    (resilience.devices — never a bare ``jax.devices()``, CLAUDE.md):
    mesh construction only runs on device-committed paths (supervised
    children, engine builds), which is exactly that helper's contract.
    """
    if devices is None:
        from dragg_tpu.resilience.devices import device_list

        devices = device_list()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def shard_state(state, mesh: Mesh, axis_name: str = HOMES_AXIS):
    """Place a CommunityState on the mesh: per-home leaves sharded on dim 0,
    the PRNG key replicated.  A type-bucketed engine's state is a TUPLE of
    per-bucket CommunityStates (each bucket shard-padded independently);
    each is placed the same way."""
    if isinstance(state, tuple) and not isinstance(state, CommunityState):
        return tuple(shard_state(s, mesh, axis_name) for s in state)
    sharded = NamedSharding(mesh, P(axis_name))
    replicated = NamedSharding(mesh, P())
    return CommunityState(*[
        jax.device_put(v, replicated if name == "key" else sharded)
        for name, v in zip(CommunityState._fields, state)
    ])


class ShardedEngine(Engine):
    """An :class:`~dragg_tpu.engine.Engine` whose home axis is sharded over a
    device mesh.

    The step function itself is unchanged — sharding is expressed purely
    through data placement: per-home constants (QP coefficients, draw
    schedules, the check mask) and the threaded state are committed with
    ``NamedSharding(mesh, P("homes"))``; XLA's SPMD partitioner propagates
    the sharding through the batched program and lowers the aggregate-load
    sum to a cross-device all-reduce.  This is the "annotate shardings, let
    XLA insert collectives" recipe — the opposite of the reference's
    explicit Redis message-passing (dragg/redis_client.py, SURVEY.md §5.8).

    The home count is padded to a multiple of the mesh size with masked-out
    replica homes; callers index real homes as ``[:true_n_homes]``.
    """

    def __init__(self, params: EngineParams, batch, env_oat, env_ghi, env_tou,
                 check_mask=None, mesh: Mesh | None = None,
                 axis_name: str = HOMES_AXIS, fleet=None, events=None,
                 hour0: int = 0):
        if mesh is None:
            mesh = make_mesh(axis_name=axis_name)
        self.mesh = mesh
        self.axis_name = axis_name
        self.true_n_homes = batch.n_homes
        n_shards = mesh.devices.size
        # Engine.__init__ resolves the "auto" solve backend against the
        # PER-SHARD memory budget — tell it the mesh size first.
        self._mesh_shards = n_shards
        if check_mask is None:
            check_mask = np.ones(batch.n_homes)
        # Type-bucketed engines pad PER BUCKET (Engine._build_buckets, so
        # every bucket slice divides the mesh evenly) — the plan must be
        # resolved on the UNPADDED batch here, before the whole-batch
        # padding would append edge-replica homes whose type codes could
        # flip an "auto" decision.
        from dragg_tpu.engine import resolve_bucket_plan

        self._bucket_ranges = resolve_bucket_plan(params.bucketed,
                                                  batch.type_code)
        if self._bucket_ranges is None:
            batch, pad_mask = pad_batch(batch, n_shards)
            check_mask = np.pad(np.asarray(check_mask, dtype=np.float64),
                                (0, batch.n_homes - self.true_n_homes)) * pad_mask
        super().__init__(params, batch, env_oat, env_ghi, env_tou,
                         check_mask=check_mask, fleet=fleet, events=events,
                         hour0=hour0)

        shard = NamedSharding(mesh, P(axis_name))
        rep = NamedSharding(mesh, P())
        put_s = lambda a: jax.device_put(jnp.asarray(np.asarray(a)), shard)
        put_r = lambda a: jax.device_put(jnp.asarray(np.asarray(a)), rep)

        # Replicated environment series (+ the event-timeline series,
        # which are per-community, not per-home — every shard reads its
        # homes' community rows).
        self._oat = put_r(self._oat)
        self._ghi = put_r(self._ghi)
        self._tou = put_r(self._tou)
        self._evt = {k: put_r(v) for k, v in self._evt.items()}
        if self._bucketed:
            # Per-home constants live in the bucket contexts (each bucket
            # padded to a mesh multiple); commit each bucket's arrays with
            # the homes sharding.  The engine-level superset copies stay
            # unsharded — the bucketed trace never reads them, and jit
            # drops unused inputs at compile.
            from dragg_tpu.engine import _TypeBucket

            for c in self._buckets:
                st = c.static
                # _replace keeps the host-side index members (sparsity,
                # per-step band positions) intact while committing the
                # per-home coefficient arrays with the homes sharding.
                c.static = st._replace(
                    vals=put_s(st.vals), a_in=put_s(st.a_in),
                    a_wh=put_s(st.a_wh), kin=put_s(st.kin),
                    kwh=put_s(st.kwh), awr=put_s(st.awr),
                )
                c.batch = type(c.batch)(*[put_s(f) for f in c.batch])
                # Every per-home bucket constant (draws/tank/check_mask +
                # the fleet identity arrays) gets the homes sharding —
                # iterated from ARRAY_ATTRS so a new per-home constant
                # cannot silently stay replicated.
                for attr in _TypeBucket.ARRAY_ATTRS:
                    setattr(c, attr, put_s(getattr(c, attr)))
            return
        # Sharded per-home device constants (superset batch).
        self._draws = put_s(self._draws)
        self._tank = put_s(self._tank)
        self._check_mask = put_s(self._check_mask)
        self._home_idx = put_s(self._home_idx)
        self._noise_idx = put_s(self._noise_idx)
        self._home_key = put_s(self._home_key)
        self._env_off = put_s(self._env_off)
        self._comm_idx = put_s(self._comm_idx)
        # QP static: shared sparsity indices (and per-step band positions)
        # stay host-side numpy constants; per-home coefficient arrays are
        # sharded.
        st = self.static
        self.static = st._replace(
            vals=put_s(st.vals), a_in=put_s(st.a_in), a_wh=put_s(st.a_wh),
            kin=put_s(st.kin), kwh=put_s(st.kwh), awr=put_s(st.awr),
        )
        # HomeBatch fields re-committed as sharded device arrays so the
        # ``jnp.asarray(...)`` closures in the traced step pick up the
        # sharding instead of baking replicated host constants.
        self.batch = type(batch)(*[put_s(f) for f in batch])

    def init_state(self):
        return shard_state(super().init_state(), self.mesh, self.axis_name)


def make_sharded_engine(batch, env, config, start_index: int,
                        mesh: Mesh | None = None,
                        fleet=None, events=None,
                        data_dir=None) -> ShardedEngine:
    """Sharded counterpart of :func:`dragg_tpu.engine.make_engine`."""
    from dragg_tpu.engine import (check_mask_for, engine_params, env_hour0,
                                  resolve_engine_events)

    axis = config.get("tpu", {}).get("mesh_axis", HOMES_AXIS)
    params = engine_params(config, start_index)
    if events is None:
        events = resolve_engine_events(config, env, params, fleet=fleet,
                                       data_dir=data_dir)
    return ShardedEngine(
        params, batch, env.oat, env.ghi, env.tou,
        check_mask=check_mask_for(batch, config), mesh=mesh, axis_name=axis,
        fleet=fleet, events=events, hour0=env_hour0(env),
    )
