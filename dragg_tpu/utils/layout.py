"""Output-directory layout — the single source of truth for the run-folder
names shared by the writer (Aggregator.set_run_dir) and discovery
(Reformat.set_date_folders/set_mpc_folders).

Format parity with the reference layout (dragg/aggregator.py:818-829,
discovered back at dragg/reformat.py:101-142):
``outputs/<start>_<end>/<type>-homes_<N>-horizon_<H>-interval_<X>-<Y>-solver_<S>/version-<V>``.
"""

from __future__ import annotations

from datetime import datetime


def date_folder_name(start_dt: datetime, end_dt: datetime) -> str:
    return f"{start_dt.strftime('%Y-%m-%dT%H')}_{end_dt.strftime('%Y-%m-%dT%H')}"


def run_dir_name(check_type: str, n_homes: int, horizon_hours: int,
                 agg_subhourly_steps: int, sub_subhourly_steps: int,
                 solver: str) -> str:
    dt_interval = 60 // int(agg_subhourly_steps)
    return (
        f"{check_type}-homes_{n_homes}"
        f"-horizon_{horizon_hours}"
        f"-interval_{dt_interval}-{dt_interval // int(sub_subhourly_steps)}"
        f"-solver_{solver}"
    )
