"""Precision filter for XLA:CPU's spurious AOT feature-mismatch errors.

Root cause (measured, docs/perf_notes.md round 5): XLA:CPU embeds LLVM
*tuning preferences* (``+prefer-no-gather``/``+prefer-no-scatter``,
chosen from the CPU *model* at compile time) in the serialized AOT
result's target-machine feature list, but ``cpu_aot_loader.cc``'s
load-time check compares that list against the detected host *ISA*
features — which never contain tuning preferences.  Result: every warm
persistent-cache load logs "Machine type ... doesn't match ... could
lead to execution errors such as SIGILL" **on the very machine that
compiled the entry**.  A minimal two-process repro (jit a matmul with a
cache dir, run twice) shows the full feature diff is exactly
``{prefer-no-gather, prefer-no-scatter}``; ``--xla_cpu_max_isa`` does
not remove it.  The round-4 host-CPU-fingerprint cache keying
(compile_cache.py) targets *cross-host* loads and cannot help — compile
host == load host here.

The loader emits one line per missing feature and names it ("Target
machine feature +X is not  supported"), so per-line classification is
exact: a line is benign iff the named feature is a tuning preference
(``prefer-*`` — LLVM subtarget tuning, not an instruction-set bit; a
missing tuning pref cannot SIGILL).  Lines naming a *real* ISA feature
(the genuine cross-host hazard the fingerprint guards) pass through
untouched, as does every other byte of stderr.

Install only in CLI/bench entry processes (never under pytest — the
fd-2 dup would fight pytest's capture machinery).

Subprocess caveat (ADVICE r5 #3): children spawned AFTER install inherit
fd 2 = the filter pipe's write end.  At parent exit :func:`drain`
restores the real fd 2 and joins the pump with a bounded timeout — a
still-running child keeps the pipe's write side open, so the pump never
sees EOF, the join expires, and the child's remaining stderr dies with
the parent.  Spawners in a filtered process should therefore pass
``stderr=real_stderr_fd()`` (or a file, as bench.py's supervisor does)
so the child bypasses the parent-lifetime pipe entirely.
"""

from __future__ import annotations

import os
import re
import threading

_INSTALLED = False
_REAL_ERR_FD: int | None = None


def real_stderr_fd() -> int | None:
    """The saved UNFILTERED stderr fd while the filter is installed
    (None = filter not installed; use plain fd 2 / None).  Pass as the
    ``stderr=`` of subprocess spawns from a filtered process — see the
    module docstring's subprocess caveat.  The fd stays valid for the
    process lifetime (drain() restores fd 2 FROM it, never closes it)."""
    return _REAL_ERR_FD

# One loader line names one feature; benign iff it is an LLVM tuning
# preference.  Keep the match tight: file tag + exact phrase + pref name.
_BENIGN = re.compile(
    rb"cpu_aot_loader\.cc.*Target machine feature \+prefer-[a-z0-9-]+ is"
    rb" not +supported on the host machine"
)


def line_is_benign_aot_mismatch(line: bytes) -> bool:
    """True iff ``line`` is the known-spurious tuning-preference variant
    of the AOT mismatch error (unit-tested separately from the fd pump)."""
    return _BENIGN.search(line) is not None


def install_aot_mismatch_filter() -> bool:
    """Idempotently interpose a pump thread on fd 2 that drops benign
    tuning-preference AOT-mismatch lines and passes everything else
    through byte-exact.  Returns True when (newly or already) installed.

    Opt-out: ``DRAGG_STDERR_FILTER=0``.
    """
    global _INSTALLED
    if _INSTALLED:
        return True
    if os.environ.get("DRAGG_STDERR_FILTER", "1") == "0":
        return False
    # Enforce the never-under-pytest invariant HERE, not at call sites:
    # in-tree tests drive the CLI main() in-process, and a dup2 on fd 2
    # inside the pytest session races its capture machinery (round-5
    # review finding).  Both conditions: subprocesses spawned BY a test
    # inherit PYTEST_CURRENT_TEST via env but are not themselves pytest
    # (they must still install — the e2e filter test depends on it), so
    # the guard additionally requires pytest imported in THIS process.
    import sys

    if "PYTEST_CURRENT_TEST" in os.environ and "pytest" in sys.modules:
        return False
    try:
        real_err = os.dup(2)
        os.set_inheritable(real_err, True)  # usable as a child's stderr=
        rd, wr = os.pipe()
        os.dup2(wr, 2)
        os.close(wr)
    except OSError:
        return False
    global _REAL_ERR_FD
    _REAL_ERR_FD = real_err

    def pump() -> None:
        buf = b""
        while True:
            try:
                chunk = os.read(rd, 65536)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            # Pass complete lines; hold the partial tail (the loader's
            # lines are long — the two full feature lists — so the tail
            # can span many reads).
            *lines, buf = buf.split(b"\n")
            for line in lines:
                if not line_is_benign_aot_mismatch(line):
                    os.write(real_err, line + b"\n")
        if buf:
            os.write(real_err, buf)

    t = threading.Thread(target=pump, name="dragg-stderr-filter",
                         daemon=True)
    t.start()

    def drain() -> None:
        # Exit-time drain: restore the real fd 2 and close the pipe's
        # last write end so the pump sees EOF, then join it — without
        # this, a crash traceback written just before exit can die with
        # the daemon thread (round-5 review finding; bench.py's child
        # stderr_tail diagnostics depend on the final bytes).
        try:
            os.dup2(real_err, 2)  # also closes the pipe writer at fd 2
        except OSError:
            pass
        t.join(timeout=2.0)

    import atexit

    atexit.register(drain)
    _INSTALLED = True
    return True
