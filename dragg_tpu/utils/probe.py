"""Subprocess jax-backend probe with a hard timeout — ONE implementation.

CLAUDE.md gotcha: a wedged axon tunnel hangs ANY in-process jax backend
init (the plugin registers at interpreter start), and the local proxy
accepting TCP is not liveness — so the only safe probe runs jax.devices()
in a SUBPROCESS under a hard timeout.  This module is the single home for
that pattern; ``dragg_tpu doctor``, ``bench.py``'s tunnel-aware ladder,
and ``tools/tpu_probe.py`` (the probe CLI / outage recorder) all call it
so their liveness verdicts cannot drift apart (advisor finding, round 4).
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

_PROBE_CODE = (
    "import json, jax\n"
    "ds = jax.devices()\n"
    "print(json.dumps({'backend': jax.default_backend(),"
    " 'devices': [str(d) for d in ds],"
    " 'kind': getattr(ds[0], 'device_kind', '')}))\n"
)


def probe_backend(timeout_s: float = 60.0) -> dict:
    """Probe default-backend init in a subprocess.

    Returns ``{'ok': True, 'backend', 'devices', 'kind', 'elapsed_s'}`` on
    success, else ``{'ok': False, 'error', 'elapsed_s', 'timeout': bool}``.
    """
    t0 = time.monotonic()
    try:
        proc = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        elapsed = round(time.monotonic() - t0, 1)
        if proc.returncode == 0:
            info = json.loads(proc.stdout.strip().splitlines()[-1])
            return {"ok": True, "elapsed_s": elapsed, **info}
        return {"ok": False, "elapsed_s": elapsed, "timeout": False,
                "error": (proc.stderr or "")[-500:]}
    except subprocess.TimeoutExpired:
        return {"ok": False, "elapsed_s": round(time.monotonic() - t0, 1),
                "timeout": True,
                "error": f"backend init hung >{timeout_s:.0f}s (wedged "
                         "accelerator tunnel? try JAX_PLATFORMS=cpu)"}
    except Exception as e:
        # Probe PLUMBING failure (fork OSError, rc-0 child with garbled
        # stdout, ...): callers guarantee one-JSON-line contracts
        # (bench.py) — a broken probe must classify, never traceback.
        return {"ok": False, "elapsed_s": round(time.monotonic() - t0, 1),
                "timeout": False, "error": f"probe plumbing failed: {e!r}"}


def _wedge_signature() -> str:
    """One-word-per-endpoint HTTP corroboration for a HUNG probe (the
    round-4 wedge signature: proxy answers 403 in ms while the remote-
    compile helper port stops listening — CLAUDE.md; round 3 separately
    saw the proxy ACCEPT and then hang, which gets its own "hang" label).
    Diagnostic color only; the jax probe stays authoritative.  The peek
    itself lives in dragg_tpu/resilience/liveness.py (the structured,
    classified API) — this keeps the legacy one-line format."""
    from dragg_tpu.resilience.liveness import read_wedge_signature

    proxy, helper = read_wedge_signature()
    return f"[proxy:{proxy} compile:{helper}]"


def probe_tpu(timeout_s: float = 60.0) -> tuple[bool, str]:
    """(tpu_alive, one-line detail) — alive only when the default backend
    actually resolves to a TPU within the timeout."""
    r = probe_backend(timeout_s)
    if r["ok"]:
        alive = r.get("backend") == "tpu"
        return alive, (f"{r.get('backend')} {r.get('kind', '')} "
                       f"({r['elapsed_s']}s)").strip()
    # The HTTP corroboration only means something for a HUNG backend init
    # (the wedge); a fast failure (ImportError, CPU-only env) gets none.
    sig = f" {_wedge_signature()}" if r.get("timeout") else ""
    return False, (f"{r['error'][:160]} ({r['elapsed_s']}s){sig}"
                   ).replace("\n", " ").strip()


def append_probe_log(path: str, alive: bool, detail: str) -> str:
    """Append one timestamped verdict line to the probe transcript (the
    committed outage/uptime record round 3 lacked); returns the line."""
    stamp = datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S")  # dragg: disable=DT014, outage transcript wall-clock stamp (presentation-only)
    line = f"{stamp} {'LIVE' if alive else 'DOWN'} {detail}"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(line + "\n")
    return line
