"""Persistent XLA compilation cache wiring.

Cold-start compiles are pure latency on every run — 20.9 s for the 10k-home
chunk on chip, and even the 50-home smoke bench pays ~20 s (docs/
perf_notes.md).  JAX can persist compiled executables keyed by (HLO,
backend, flags) so the SECOND process-level run of the same config skips
XLA entirely.  The reference has no analog (CVXPY re-canonicalizes every
process; GLPK has no compile step) — this is a TPU-stack-specific cost and
win.

Enabled by default (``tpu.compile_cache = true``); the directory resolves
from ``tpu.compile_cache_dir`` → ``$DRAGG_COMPILE_CACHE_DIR`` →
``$JAX_COMPILATION_CACHE_DIR`` → ``~/.cache/dragg_tpu/xla``, ALWAYS with
a per-host CPU fingerprint subdir appended (a cache written on a
differently-featured host must not be loaded — observed XLA:CPU AOT
SIGILL hazard; see :func:`_host_fingerprint`).

Note the fingerprint does NOT silence the ``cpu_aot_loader`` mismatch
ERRORs on warm caches: those are structural same-host noise (XLA embeds
LLVM tuning prefs the host-feature check never contains — root-caused
round 5, docs/perf_notes.md) and are handled by the precision filter in
:mod:`dragg_tpu.utils.stderr_filter`.
"""

from __future__ import annotations

import logging
import os

_log = logging.getLogger("dragg_tpu.compile_cache")
_ENABLED_DIR: str | None = None


def _host_fingerprint() -> str:
    """Short best-effort id of this host's CPU (see cache-dir segregation
    below).  Hashes the cpuinfo feature line (x86 ``flags`` / ARM
    ``Features``) AND the model-name line — the observed AOT mismatch was
    on ``+prefer-no-gather``, an LLVM tuning feature derived from the CPU
    MODEL that never appears in the flags line, so the model must be part
    of the key.  Falls back to the machine arch when cpuinfo is
    unreadable; best-effort, not a guarantee (two hosts with identical
    model + features strings share a subdir — which is also when sharing
    is safe)."""
    import hashlib
    import platform

    parts = []
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip().lower()
                if key in ("flags", "features", "model name", "cpu part",
                           "cpu implementer"):
                    parts.append(line.strip())
                    if len(parts) >= 3:
                        break
    except OSError:
        pass
    if parts:
        return hashlib.sha256("|".join(sorted(parts)).encode()).hexdigest()[:12]
    return platform.machine() or "unknown"


def enabled_cache_dir() -> str | None:
    """The directory a prior :func:`enable_compile_cache` activated in
    this process (None = persistent cache off) — the staged-compile
    hit/miss heuristic reads entry counts here
    (dragg_tpu/telemetry/compile_obs.py)."""
    return _ENABLED_DIR


def solver_cache_scope(config: dict | None) -> str:
    """Cache-directory scope token for the configured solver family —
    part of the cache key (round 10).

    XLA's own entry key hashes the serialized HLO, so ipm/admm/reluqp
    executables for the SAME bucket pattern can never alias byte-wise —
    but they all land in one flat directory, where (a) the 2 GiB LRU
    evicts one family's entries while sweeping another, and (b) the
    staged-compile hit/miss heuristic (compile_obs._cache_entries counts
    directory entries) attributes one family's writes to another's
    compile.  Scoping the directory by solver family — and, for reluqp,
    by the rho-bank size, which changes every solver executable's shape —
    keeps both honest.

    Configs naming a reference solver resolve through the same registry
    as the engine (``config.resolve_solver_family``), so GLPK_MI shares
    the ipm scope.  Callers with no config (or an unresolvable solver)
    get the "shared" scope — still fingerprint-segregated, just not
    family-split."""
    if not config:
        return "shared"
    try:
        from dragg_tpu.config import resolve_solver_family

        fam = resolve_solver_family(config)
    except Exception:
        return "shared"
    tpu_cfg = config.get("tpu") or {}
    if fam == "reluqp":
        # Same clamp as engine_params — the scope token must name the bank
        # size actually compiled, not the raw config value.
        bank = max(1, int(tpu_cfg.get("reluqp_bank", 5)))
        token = f"reluqp-bank{bank}"
    else:
        token = fam
    # Mixed-precision policy (ISSUE 11): a non-default precision changes
    # every dense-family executable (the hot-loop matmuls lower to
    # different programs), so bf16x3 sweeps must not LRU-churn or
    # hit/miss-confuse the f32 history.  The ipm ignores the policy —
    # its scope stays unsuffixed, and so does the f32 default (existing
    # cache dirs keep their names).
    prec = str(tpu_cfg.get("precision", "f32"))
    if fam in ("admm", "reluqp") and prec != "f32":
        token += f"-{prec}"
    return token


def _resolve_cache_dir(config: dict | None = None) -> tuple[str, str, bool]:
    """(base_dir, cache_dir, dragg_owned) for a config — the pure path
    logic of :func:`enable_compile_cache`, split out so the regression
    test can assert the solver scoping without touching the process-global
    jax cache config."""
    tpu_cfg = (config or {}).get("tpu", {})
    base_dir = (
        str(tpu_cfg.get("compile_cache_dir") or "")
        or os.environ.get("DRAGG_COMPILE_CACHE_DIR", "")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR", "")
        or os.path.join(os.path.expanduser("~"), ".cache", "dragg_tpu", "xla")
    )
    # Dragg owns the dir only when it came from a dragg-specific source;
    # $JAX_COMPILATION_CACHE_DIR is a standard JAX env var plausibly shared
    # with other JAX programs on this host, and sweeping there would delete
    # cache entries dragg did not create (ADVICE round 4).
    dragg_owned = bool(
        str(tpu_cfg.get("compile_cache_dir") or "")
        or os.environ.get("DRAGG_COMPILE_CACHE_DIR", "")
        or not os.environ.get("JAX_COMPILATION_CACHE_DIR", "")
    )
    # Segregate by host CPU fingerprint: the cache directory lives in the
    # home volume and SURVIVES across differently-featured hosts (observed:
    # XLA:CPU loading an AOT result compiled with +prefer-no-gather on a
    # host without it, warning "could lead to execution errors such as
    # SIGILL").  A per-fingerprint subdir prevents cross-machine loads
    # (best-effort — see _host_fingerprint) while keeping the warm-cache
    # win on a stable host.  Below the fingerprint, a per-solver-family
    # scope (see solver_cache_scope).
    cache_dir = os.path.join(base_dir, _host_fingerprint(),
                             solver_cache_scope(config))
    return base_dir, cache_dir, dragg_owned


def enable_compile_cache(config: dict | None = None) -> str | None:
    """Idempotently enable JAX's persistent compilation cache; returns the
    cache directory, or None when disabled (``tpu.compile_cache = false``)
    or unavailable.  Safe to call before or after backend initialization —
    the cache config is read at compile time."""
    global _ENABLED_DIR
    tpu_cfg = (config or {}).get("tpu", {})
    if not tpu_cfg.get("compile_cache", True):
        if _ENABLED_DIR is not None:
            # The process-global JAX cache config cannot be un-set per
            # Aggregator: a prior enable stays in effect (ADVICE round 3).
            _log.warning(
                "compile_cache=false requested but the persistent cache was "
                "already enabled at %s earlier in this process; it stays "
                "enabled (jax.config is process-global)", _ENABLED_DIR)
        return None
    base_dir, cache_dir, dragg_owned = _resolve_cache_dir(config)
    if _ENABLED_DIR is not None:
        if cache_dir != _ENABLED_DIR:
            _log.warning(
                "persistent compilation cache already enabled at %s; "
                "ignoring later request for %s (jax.config is "
                "process-global — first enable wins)",
                _ENABLED_DIR, cache_dir)
        return _ENABLED_DIR
    # Pre-fingerprint entries at the base level — and pre-solver-scope
    # entries at the fingerprint level (rounds ≤9 wrote entries there) —
    # are dead weight no code path reads anymore (JAX's 2 GiB LRU only
    # manages the active subdir) — sweep plain files, leave
    # subdirectories (other hosts' / other solver families' caches).
    # Only in dragg-owned dirs, and only once per process (we are past the
    # _ENABLED_DIR short-circuit here), never in a shared
    # $JAX_COMPILATION_CACHE_DIR (ADVICE round 4).
    if dragg_owned:
        for sweep_dir in (base_dir, os.path.dirname(cache_dir)):
            try:
                for entry in os.listdir(sweep_dir):
                    p = os.path.join(sweep_dir, entry)
                    if os.path.isfile(p):
                        os.remove(p)
            except OSError:
                pass
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Persist everything that took any real compile time; the default
        # 1 s floor would skip most of the small per-phase programs whose
        # compiles still add up across a sweep.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # Bound the directory: JAX never evicts without a cap, and every
        # distinct (homes, horizon, solver) combination persists entries —
        # sweeps would grow it monotonically.  2 GiB holds hundreds of
        # full-size community programs; LRU eviction handles the rest.
        jax.config.update("jax_compilation_cache_max_size",
                          2 * 1024 * 1024 * 1024)
        _ENABLED_DIR = cache_dir
        return cache_dir
    except Exception as e:  # never let cache plumbing sink a run
        _log.warning("persistent compilation cache unavailable (%r)", e)
        return None
