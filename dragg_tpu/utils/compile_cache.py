"""Persistent XLA compilation cache wiring.

Cold-start compiles are pure latency on every run — 20.9 s for the 10k-home
chunk on chip, and even the 50-home smoke bench pays ~20 s (docs/
perf_notes.md).  JAX can persist compiled executables keyed by (HLO,
backend, flags) so the SECOND process-level run of the same config skips
XLA entirely.  The reference has no analog (CVXPY re-canonicalizes every
process; GLPK has no compile step) — this is a TPU-stack-specific cost and
win.

Enabled by default (``tpu.compile_cache = true``); the directory resolves
from ``tpu.compile_cache_dir`` → ``$DRAGG_COMPILE_CACHE_DIR`` →
``$JAX_COMPILATION_CACHE_DIR`` → ``~/.cache/dragg_tpu/xla``.
"""

from __future__ import annotations

import logging
import os

_log = logging.getLogger("dragg_tpu.compile_cache")
_ENABLED_DIR: str | None = None


def enable_compile_cache(config: dict | None = None) -> str | None:
    """Idempotently enable JAX's persistent compilation cache; returns the
    cache directory, or None when disabled (``tpu.compile_cache = false``)
    or unavailable.  Safe to call before or after backend initialization —
    the cache config is read at compile time."""
    global _ENABLED_DIR
    tpu_cfg = (config or {}).get("tpu", {})
    if not tpu_cfg.get("compile_cache", True):
        if _ENABLED_DIR is not None:
            # The process-global JAX cache config cannot be un-set per
            # Aggregator: a prior enable stays in effect (ADVICE round 3).
            _log.warning(
                "compile_cache=false requested but the persistent cache was "
                "already enabled at %s earlier in this process; it stays "
                "enabled (jax.config is process-global)", _ENABLED_DIR)
        return None
    cache_dir = (
        str(tpu_cfg.get("compile_cache_dir") or "")
        or os.environ.get("DRAGG_COMPILE_CACHE_DIR", "")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR", "")
        or os.path.join(os.path.expanduser("~"), ".cache", "dragg_tpu", "xla")
    )
    if _ENABLED_DIR is not None:
        if cache_dir != _ENABLED_DIR:
            _log.warning(
                "persistent compilation cache already enabled at %s; "
                "ignoring later request for %s (jax.config is "
                "process-global — first enable wins)",
                _ENABLED_DIR, cache_dir)
        return _ENABLED_DIR
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Persist everything that took any real compile time; the default
        # 1 s floor would skip most of the small per-phase programs whose
        # compiles still add up across a sweep.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # Bound the directory: JAX never evicts without a cap, and every
        # distinct (homes, horizon, solver) combination persists entries —
        # sweeps would grow it monotonically.  2 GiB holds hundreds of
        # full-size community programs; LRU eviction handles the rest.
        jax.config.update("jax_compilation_cache_max_size",
                          2 * 1024 * 1024 * 1024)
        _ENABLED_DIR = cache_dir
        return cache_dir
    except Exception as e:  # never let cache plumbing sink a run
        _log.warning("persistent compilation cache unavailable (%r)", e)
        return None
