"""Cross-version jax compatibility shims — ONE home, so version drift
shows up here instead of in six call sites.

The repo targets current jax (``jax.shard_map``); the baked toolchain in
some build images ships pre-0.5 jax where the same primitive lives at
``jax.experimental.shard_map.shard_map`` with ``check_rep`` instead of
``check_vma``.
"""

from __future__ import annotations

from functools import partial

import jax


def shard_map_partial(mesh):
    """``partial(shard_map, mesh=mesh, <replication check off>)`` under
    whichever spelling this jax provides.  Replication checking is off in
    every repo use: pallas_call outputs carry no varying-mesh-axes
    annotation and the wrapped maps are per-shard elementwise over homes,
    so the check has nothing to verify."""
    if hasattr(jax, "shard_map"):
        return partial(jax.shard_map, mesh=mesh, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return partial(shard_map, mesh=mesh, check_rep=False)
