"""Shared small utilities."""

from dragg_tpu.utils.layout import date_folder_name, run_dir_name

__all__ = ["date_folder_name", "run_dir_name"]
