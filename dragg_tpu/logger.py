"""Logging for dragg_tpu.

Capability parity with the reference logger (dragg/logger.py:4-23): a named
logger with an optional per-name file handler and a custom ``PROG`` level 25,
level taken from the ``LOGLEVEL`` env var.  Unlike the reference we do not
unconditionally create ``<name>_logger.log`` files in the CWD — file handlers
are opt-in via ``log_dir`` — and we never call ``logging.basicConfig`` (which
mutates global state).
"""

import logging
import os

PROG = 25
logging.addLevelName(PROG, "PROG")


def _progress(self, message, *args, **kws):
    if self.isEnabledFor(PROG):
        self._log(PROG, message, args, **kws)


logging.Logger.progress = _progress  # type: ignore[attr-defined]

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"


class Logger:
    """A named logger for simulation outputs.

    Parameters
    ----------
    name : str
        Logger name (e.g. ``"aggregator"``).
    log_dir : str | None
        If given, also log to ``<log_dir>/<name>.log``.
    """

    def __init__(self, name: str, log_dir: str | None = None):
        self.name = name
        self.logger = logging.getLogger(f"dragg_tpu.{name}")
        self.logger.setLevel(os.environ.get("LOGLEVEL", "INFO"))
        if not self.logger.handlers:
            sh = logging.StreamHandler()
            sh.setFormatter(logging.Formatter(_FORMAT))
            self.logger.addHandler(sh)
            self.logger.propagate = False
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            path = os.path.join(log_dir, f"{name}.log")
            if not any(
                isinstance(h, logging.FileHandler)
                and getattr(h, "baseFilename", None) == os.path.abspath(path)
                for h in self.logger.handlers
            ):
                fh = logging.FileHandler(path)
                fh.setFormatter(logging.Formatter(_FORMAT))
                self.logger.addHandler(fh)

    def __getattr__(self, item):
        return getattr(self.logger, item)
