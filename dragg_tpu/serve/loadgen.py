"""Shared load-generation kit for the serving benchmarks — stdlib only,
jax-free (both consumers are daemon-parent processes).

One request builder + one result-envelope schema, shared by
``tools/serve_load.py`` (the SLO-gated load harness) and
``tools/serve_soak.py`` (the chaos soak) so the two tools replay the
same deterministic request distributions and emit the same JSON-line
shape (ISSUE 13 satellite; tests/test_serve_load.py pins the schema
both ways).
"""

from __future__ import annotations

import json
import random
import sys
import urllib.error
import urllib.request

# The load harness watches ``serve.done`` for daemon-side completion
# times instead of hammering ``/result`` with poll traffic; the
# incremental reader itself is neutral telemetry infrastructure (the
# daemon's streaming transport uses it too), so it lives in
# telemetry/bus.py — re-exported here for the serving tools.
from dragg_tpu.telemetry import EventFollower  # noqa: F401

# The shared JSON-line schema version both serving tools stamp; bump it
# when the envelope's required keys change.
SCHEMA = "serve_bench_v1"

# Keys every serving-tool result line must carry (the schema test
# asserts both tools conform).
REQUIRED_KEYS = ("tool", "schema", "ok", "homes", "requests", "metrics",
                 "violations")


def make_log(tool: str):
    """One stderr log format for the serving tools (stdout is reserved
    for the single JSON result line)."""
    def _log(msg: str) -> None:
        print(f"[{tool}] {msg}", file=sys.stderr, flush=True)
    return _log


def http_call(method: str, url: str, body=None, timeout: float = 30.0):
    """One JSON HTTP round-trip against the daemon — shared by both
    serving tools (the daemon always answers JSON, including on HTTP
    errors, so error bodies parse too)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def build_requests(n: int, homes: int, *, prefix: str = "r",
                   t_window: int = 3, rp_values=(0.0,), steps: int = 1,
                   pattern: str | None = None, state_every: int = 4,
                   seed: int | None = None) -> list[dict]:
    """The deterministic request stream both tools replay: ids
    ``<prefix>000…``, timesteps cycling a small window, homes cycling
    the community, a few state overrides, and (load harness) reward
    prices cycling ``rp_values`` — distinct rp values form distinct
    coalescing groups, which is exactly what the fleet-backed pool
    batches across community slots.

    Defaults reproduce the soak's historical trace byte-for-byte.
    ``seed`` perturbs the home/timestep draws reproducibly (the load
    harness's request-size/pattern distributions are seeded, never
    sampled from wall-clock state)."""
    rng = random.Random(seed) if seed is not None else None
    reqs = []
    for i in range(n):
        home = i % homes if rng is None else rng.randrange(homes)
        t = i % t_window if rng is None else rng.randrange(t_window)
        req: dict = {"id": f"{prefix}{i:03d}", "t": t, "home": home}
        rp = rp_values[i % len(rp_values)]
        if rp:
            req["rp"] = rp
        if steps > 1:
            req["steps"] = steps
        if pattern:
            req["pattern"] = pattern
        if state_every and i % state_every == 0:
            req["state"] = {"temp_in": 18.0 + (i % 5)}
        reqs.append(req)
    return reqs


def result_envelope(tool: str, *, ok: bool, homes: int, requests: int,
                    metrics: dict, violations: list, **extra) -> dict:
    """One serving-tool JSON line in the shared schema (repo bench
    convention: exactly one machine-readable line on stdout)."""
    out = {"tool": tool, "schema": SCHEMA, "ok": bool(ok),
           "homes": int(homes), "requests": int(requests),
           "metrics": metrics, "violations": list(violations)}
    out.update(extra)
    return out


def journal_anomalies(journal_path: str, ids) -> list[str]:
    """The load-harness journal QA: every submitted id that was ACCEPTED
    reaches exactly one terminal state, and no id is answered twice (the
    soak's richer invariant checker builds on the same records)."""
    from dragg_tpu.serve import journal as journal_mod

    ids = set(ids)
    accepted: set = set()
    done: dict = {}
    failed: dict = {}
    try:
        with open(journal_path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return [f"journal unreadable: {journal_path}"]
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        rid = rec.get("id")
        if rid not in ids:
            continue
        state = rec.get("state")
        if state == journal_mod.ACCEPTED:
            accepted.add(rid)
        elif state == journal_mod.DONE:
            done[rid] = done.get(rid, 0) + 1
        elif state == journal_mod.FAILED:
            failed[rid] = failed.get(rid, 0) + 1
    problems = []
    for rid in sorted(accepted):
        n = done.get(rid, 0) + failed.get(rid, 0)
        if n == 0:
            problems.append(f"{rid}: LOST — accepted but no terminal record")
        elif n > 1:
            problems.append(f"{rid}: {n} terminal records")
    for rid, k in sorted(done.items()):
        if k > 1:
            problems.append(f"{rid}: answered {k} times")
    return problems


