"""Parent-side worker slots for the serving daemon — stdlib only, never
imports jax.

A :class:`WorkerSlot` owns one long-lived worker child (serve/worker.py)
and the supervision state the daemon's loop reads every tick: process
liveness, heartbeat age (resilience.heartbeat — the round-4 stall
detector), the ready report, and the classified post-mortem verdict
(resilience.taxonomy).  Unlike ``supervisor.run_supervised`` — which
BLOCKS until its child exits, the right shape for one-shot measurement
jobs — serving needs a non-blocking handle: the daemon polls many slots
and its HTTP surface between ticks, and a worker's deadline is per-BATCH
(set when work is dispatched), not per-process.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

from dragg_tpu import telemetry
from dragg_tpu.resilience import heartbeat as hb
from dragg_tpu.resilience.supervisor import kill_group, read_tail
from dragg_tpu.resilience.taxonomy import classify_child
from dragg_tpu.serve import spool


class WorkerSlot:
    """One worker slot: launch/poll/kill a generation-counted child."""

    def __init__(self, spool_dir: str, slot: int, *,
                 cfg_path: str | None = None, stub: bool = False,
                 poll_s: float = 0.05, epoch: str = "", log=None,
                 pattern: str = "default"):
        self.spool_dir = spool_dir
        self.slot = slot
        self.cfg_path = cfg_path
        self.stub = stub
        self.poll_s = poll_s
        self.epoch = epoch
        self.log = log
        self.pattern = pattern  # the pattern lane this slot serves
        self.gen = 0
        self.proc: subprocess.Popen | None = None
        self.platform: str | None = None   # requested platform of this gen
        self.hb_path: str | None = None
        self.err_path: str | None = None
        self.out_path: str | None = None
        self.launched_at: float | None = None
        self.ready_report: dict | None = None
        spool.ensure_slot_dirs(spool_dir, slot)
        # A restarted daemon reuses the persistent spool: leftovers from
        # the previous instance must not masquerade as this one's state.
        # A stale ready-1.json would report a cold gen-1 worker warm
        # before it compiled, and a stale outbox batch-N could collide
        # with this instance's batch numbering — drop them all; the
        # journal replay re-queues whatever was unanswered (a dropped
        # stale ANSWER just re-solves: the journal's refused-once
        # terminal writes keep delivery exactly-once regardless).
        sdir = spool.slot_dir(spool_dir, slot)
        stale = [os.path.join(sdir, n) for n in os.listdir(sdir)
                 if n.startswith("ready-")]
        stale += [p for _seq, p in spool.list_batches(self.inbox())]
        stale += [p for _seq, p in spool.list_batches(self.outbox())]
        for p in stale:
            try:
                os.remove(p)
            except OSError:
                pass

    # ------------------------------------------------------------ lifecycle
    def launch(self, platform: str, env_base: dict | None = None) -> None:
        """Start generation ``gen+1`` on ``platform`` ("tpu" keeps the
        inherited backend resolution; "cpu" pins the CPU backend AND drops
        the axon plugin registration — runner.cpu_env, the wedge-proof
        child environment)."""
        from dragg_tpu.resilience.runner import cpu_env

        assert self.proc is None or self.proc.poll() is not None
        self.gen += 1
        self.platform = platform
        sdir = spool.slot_dir(self.spool_dir, self.slot)
        fd, self.hb_path = tempfile.mkstemp(prefix=f"hb-{self.gen}-", dir=sdir)
        os.close(fd)
        hb_seed = {"t": time.time()}  # dragg: disable=DT014, heartbeat seed — the worker stall-kill protocol is wall-clock
        with open(self.hb_path, "w") as f:
            import json

            json.dump(hb_seed, f)
        env = cpu_env(env_base) if platform == "cpu" else dict(
            os.environ if env_base is None else env_base)
        env[hb.ENV] = self.hb_path
        if telemetry.run_dir():
            env.setdefault(telemetry.ENV_DIR, telemetry.run_dir())
        # Trace context + flush cadence travel with the stream dir
        # (ISSUE 20): exported only when the daemon traces/flushes, so
        # untraced deployments launch byte-identical children.
        trace_ctx = telemetry.trace.env_value()
        if trace_ctx:
            env.setdefault(telemetry.trace.ENV_CTX, trace_ctx)
        flush_s = os.environ.get(telemetry.ENV_FLUSH)
        if flush_s:
            env.setdefault(telemetry.ENV_FLUSH, flush_s)
        argv = [sys.executable, "-m", "dragg_tpu.serve.worker",
                "--spool", self.spool_dir, "--slot", str(self.slot),
                "--gen", str(self.gen), "--poll-s", str(self.poll_s)]
        if self.epoch:
            argv += ["--epoch", self.epoch]
        argv += ["--stub"] if self.stub else ["--config", self.cfg_path]
        self.out_path = os.path.join(sdir, f"out-{self.gen}.log")
        self.err_path = os.path.join(sdir, f"err-{self.gen}.log")
        with open(self.out_path, "wb") as out_f, \
                open(self.err_path, "wb") as err_f:
            self.proc = subprocess.Popen(argv, env=env, stdout=out_f,
                                         stderr=err_f,
                                         start_new_session=True)
        self.launched_at = time.monotonic()
        self.ready_report = None
        telemetry.emit("serve.worker.launch", slot=self.slot, gen=self.gen,
                       pid=self.proc.pid, platform=platform,
                       stub=self.stub, pattern=self.pattern)
        telemetry.inc("serve.worker_restarts", 1 if self.gen > 1 else 0)
        if self.log:
            self.log(f"worker w{self.slot} gen={self.gen} pid={self.proc.pid} "
                     f"platform={platform}")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def ready(self) -> dict | None:
        """The current generation's ready report, once the worker has
        warmed its compiled engine (None while compiling / after death)."""
        if self.ready_report is None and self.proc is not None:
            self.ready_report = spool.read_json(
                spool.ready_path(self.spool_dir, self.slot, self.gen))
        return self.ready_report

    def heartbeat_age(self) -> float | None:
        if self.hb_path is None:
            return None
        age, _ = hb.read(self.hb_path)
        return age

    def kill(self, grace_s: float = 5.0) -> None:
        if self.proc is not None and self.proc.poll() is None:
            kill_group(self.proc, grace_s)

    def verdict(self, *, timed_out: bool = False,
                stalled: bool = False) -> str:
        """Taxonomy kind for the (dead) current generation.  Callers pass
        how it died: ``timed_out`` = the daemon killed it at a batch
        deadline, ``stalled`` = the daemon killed it on heartbeat stall;
        both False = it died on its own (CHILD_CRASH / VMEM_OOM from the
        stderr signature)."""
        rc = self.proc.poll() if self.proc is not None else None
        tail = read_tail(self.err_path, 4000) if self.err_path else ""
        kind = classify_child(rc, timed_out, stalled, tail)
        return kind or "CHILD_CRASH"

    def stderr_tail(self, limit: int = 2000) -> str:
        return read_tail(self.err_path, limit) if self.err_path else ""

    # --------------------------------------------------------------- spool
    def inbox(self) -> str:
        return spool.inbox_dir(self.spool_dir, self.slot)

    def outbox(self) -> str:
        return spool.outbox_dir(self.spool_dir, self.slot)

    def clear_inbox(self) -> None:
        """Drop undelivered batch files after a worker death — the daemon
        requeues their requests itself (retry accounting lives parent-
        side; a leftover file must not double-serve under the relaunch)."""
        for _seq, path in spool.list_batches(self.inbox()):
            try:
                os.remove(path)
            except OSError:
                pass
