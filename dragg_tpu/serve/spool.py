"""Spool-directory wire protocol between the serving daemon and its
workers — stdlib only, shared by both sides.

The daemon's parent process is jax-free by contract (a wedged tunnel
hangs ANY backend init — resilience.supervisor), so daemon↔worker
communication cannot be an in-process queue, and pipes would couple the
worker's liveness to the parent's read loop.  The spool is the same
pattern the checkpoint layer already trusts: ATOMIC single-file renames
on a local filesystem, so every message is observed whole or not at all,
and a kill -9 at any instruction leaves a recoverable directory, never a
half-parsed stream.

Layout (one subdirectory per worker slot)::

    <spool>/STOP                      global drain signal (workers exit
                                      between batches when present)
    <spool>/w<slot>/inbox/batch-<n>.json    daemon -> worker
    <spool>/w<slot>/outbox/batch-<n>.json   worker -> daemon
    <spool>/w<slot>/ready-<gen>.json        worker warm signal + compile
                                            report (staged_compile's)

Ordering contract for a batch: the worker writes the outbox response
ATOMICALLY first, then unlinks the inbox file.  A crash between the two
leaves both present — the daemon prefers the outbox answer and discards
the inbox leftover, so a request is never re-solved when its answer
already exists (half of the soak's answered-exactly-once invariant).
"""

from __future__ import annotations

import json
import os

STOP_FILE = "STOP"
EPOCH_FILE = "EPOCH"  # current daemon's ownership token (orphan fencing)


def slot_dir(spool: str, slot: int) -> str:
    return os.path.join(spool, f"w{slot}")


def inbox_dir(spool: str, slot: int) -> str:
    return os.path.join(slot_dir(spool, slot), "inbox")


def outbox_dir(spool: str, slot: int) -> str:
    return os.path.join(slot_dir(spool, slot), "outbox")


def ready_path(spool: str, slot: int, gen: int) -> str:
    return os.path.join(slot_dir(spool, slot), f"ready-{gen}.json")


def stop_path(spool: str) -> str:
    return os.path.join(spool, STOP_FILE)


def epoch_path(spool: str) -> str:
    return os.path.join(spool, EPOCH_FILE)


def write_epoch(spool: str, token: str) -> None:
    """Claim the spool for one daemon instance.  A daemon that died
    without cleanup (kill -9 of the parent) leaves its workers orphaned
    and still scanning this spool; the successor writes a fresh token and
    workers exit when the file no longer matches the token they were
    launched with."""
    os.makedirs(spool, exist_ok=True)
    tmp = f"{epoch_path(spool)}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(token)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, epoch_path(spool))


def read_epoch(spool: str) -> str | None:
    try:
        with open(epoch_path(spool), encoding="utf-8") as f:
            return f.read().strip()
    except OSError:
        return None


def ensure_slot_dirs(spool: str, slot: int) -> None:
    os.makedirs(inbox_dir(spool, slot), exist_ok=True)
    os.makedirs(outbox_dir(spool, slot), exist_ok=True)


def dumps_doc(payload: dict) -> str:
    """The ONE document codec both exchange surfaces share: spool files
    on disk and wire frame bodies (shard/wire.py) serialize through this
    exact call, so a chunk payload round-trips byte-identically whether
    it travelled the shared-disk spool or the TCP transport (float64
    repr round-trips exactly — shard/partition.series_to_lists)."""
    return json.dumps(payload, default=str)


def loads_doc(data: str | bytes) -> dict:
    """Inverse of :func:`dumps_doc`; raises ValueError on torn input."""
    doc = json.loads(data)
    if not isinstance(doc, dict):
        raise ValueError(f"spool/wire document must be an object, "
                         f"got {type(doc).__name__}")
    return doc


def atomic_write_json(path: str, payload: dict) -> None:
    """Write-then-rename so readers only ever see complete documents."""
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(dumps_doc(payload))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_json(path: str) -> dict | None:
    """One parsed document, or None when absent / mid-rename / torn."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ------------------------------------------------------- shard spool layout
#
# The shard coordinator (dragg_tpu/shard — architecture.md §19) reuses
# this module's atomic-rename discipline and EPOCH fencing with its own
# per-shard directories: ``<spool>/s<k>/`` holds shard k's spec, outbox
# chunk files, per-generation logs, and checkpoint tree.  Chunk files
# are RETAINED until the run completes (unlike serve batches) — they are
# the payload a restarted coordinator re-merges behind the journal's
# acked frontier.


def shard_dir(spool: str, shard: int) -> str:
    return os.path.join(spool, f"s{shard}")


def shard_outbox_dir(spool: str, shard: int) -> str:
    return os.path.join(shard_dir(spool, shard), "outbox")


def shard_spec_path(spool: str, shard: int) -> str:
    return os.path.join(shard_dir(spool, shard), "spec.json")


def shard_ckpt_root(spool: str, shard: int) -> str:
    return os.path.join(shard_dir(spool, shard), "checkpoint")


def ensure_shard_dirs(spool: str, shard: int) -> None:
    os.makedirs(shard_outbox_dir(spool, shard), exist_ok=True)


def chunk_name(seq: int) -> str:
    return f"chunk-{seq}.json"


def chunk_seq(name: str) -> int | None:
    if not (name.startswith("chunk-") and name.endswith(".json")):
        return None
    try:
        return int(name[len("chunk-"):-len(".json")])
    except ValueError:
        return None


def chunk_path(spool: str, shard: int, seq: int) -> str:
    return os.path.join(shard_outbox_dir(spool, shard), chunk_name(seq))


def list_chunks(directory: str) -> list[tuple[int, str]]:
    """(seq, path) pairs of shard chunk files, oldest seq first."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        seq = chunk_seq(name)
        if seq is not None:
            out.append((seq, os.path.join(directory, name)))
    return sorted(out)


def batch_name(seq: int) -> str:
    return f"batch-{seq}.json"


def batch_seq(name: str) -> int | None:
    if not (name.startswith("batch-") and name.endswith(".json")):
        return None
    try:
        return int(name[len("batch-"):-len(".json")])
    except ValueError:
        return None


def list_batches(directory: str) -> list[tuple[int, str]]:
    """(seq, path) pairs of complete batch files, oldest seq first."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        seq = batch_seq(name)
        if seq is not None:
            out.append((seq, os.path.join(directory, name)))
    return sorted(out)
