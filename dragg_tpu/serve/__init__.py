"""Fault-tolerant, fleet-backed MPC serving (ISSUE 7 + ISSUE 13 /
ROADMAP items 2-3).

``python -m dragg_tpu serve`` — a long-lived service whose jax-free
parent owns a crash-safe fsync'd request journal, pattern-routed
supervised worker lanes holding warm compiled FLEET engines (C community
slots per worker — one warm solve coalesces up to C request groups),
probe-gated admission with checkpointed TPU→CPU degradation, streaming
multi-chunk results, and an HTTP surface (/solve /result /healthz
/readyz /metrics.json).  See :mod:`dragg_tpu.serve.daemon` for the
architecture and ``docs/serving.md`` for operator documentation +
capacity planning.
"""

from dragg_tpu.serve.daemon import (PatternLane, ServeDaemon, run_serve,
                                    serve_config)

__all__ = ["PatternLane", "ServeDaemon", "run_serve", "serve_config"]
