"""Fault-tolerant MPC serving daemon (ISSUE 7 / ROADMAP open item 2).

``python -m dragg_tpu serve`` — a long-lived service whose jax-free
parent owns a crash-safe fsync'd request journal, a supervised worker
pool holding the compiled engine warm, probe-gated admission with
checkpointed TPU→CPU degradation, and an HTTP surface
(/solve /result /healthz /readyz /metrics.json).  See
:mod:`dragg_tpu.serve.daemon` for the architecture and
``docs/serving.md`` for operator documentation.
"""

from dragg_tpu.serve.daemon import ServeDaemon, run_serve, serve_config

__all__ = ["ServeDaemon", "run_serve", "serve_config"]
