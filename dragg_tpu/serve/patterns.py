"""Pattern lanes for multi-pattern serving admission — stdlib+numpy only
(the daemon parent is jax-free by contract; everything here is pure
config math).

A *bucket-pattern signature* names the set of compiled executables a
worker engine holds: the home-type mix (which type buckets exist and at
what per-community size), the MPC horizon, and the fleet slot count C
(type buckets hold ``C·B_type`` homes — round 12, architecture.md §14).
Two requests with the same signature can share a warm worker; two
requests with different signatures cannot (different compiled shapes).

The serving daemon routes every request to a :class:`LaneSpec` at
admission:

* the **default lane** is the daemon's own config (``serve.fleet_slots``
  community slots per worker);
* **configured lanes** come from ``serve.patterns`` — each entry warms
  its own worker(s) at boot;
* **spill lanes** are created on demand for requests carrying an inline
  pattern spec whose signature no existing lane serves, bounded by
  ``serve.spill_patterns`` (a compile-on-demand lane is a cold compile —
  the bound keeps an adversarial request stream from turning the pool
  into a compile farm).  Every lane creation is journaled
  (``pattern`` record — serve/journal.py) so a restarted daemon can
  rebuild the lane a replayed request needs.

Lane spec fields (all optional except ``name`` for configured lanes):

``horizon_hours``   MPC prediction horizon override
``homes``           community-mix overrides: ``{"total": n, "pv": k,
                    "battery": k, "pv_battery": k, "ev": k,
                    "heat_pump": k}`` (absent keys keep the daemon
                    config's counts)
``fleet_slots``     community slots C per worker (default
                    ``serve.fleet_slots``)
``workers``         worker slots for this lane (default 1; the default
                    lane uses ``serve.workers``)
"""

from __future__ import annotations

import copy
import json

# homes override key -> community config key
_HOMES_KEYS = {
    "total": "total_number_homes",
    "pv": "homes_pv",
    "battery": "homes_battery",
    "pv_battery": "homes_pv_battery",
    "ev": "homes_ev",
    "heat_pump": "homes_heat_pump",
}
_SPEC_KEYS = ("name", "horizon_hours", "homes", "fleet_slots", "workers")

# Admission ceilings for INLINE specs (network-supplied): the spill
# bound caps how MANY cold compiles a request stream can trigger; these
# cap how BIG one can be (a single admitted 1M-home/16-worker spec
# would defeat the bound).  Operator config and journal replay are
# trusted and uncapped.
_INLINE_MAX = {"horizon_hours": 168, "fleet_slots": 256, "workers": 8}
_INLINE_HOMES_MAX = 4096


class PatternError(ValueError):
    """A malformed pattern spec — answered 400 at admission, never
    journaled."""


def normalize_spec(spec: dict, scfg: dict, *, inline: bool = False) -> dict:
    """Validate one pattern spec (a ``serve.patterns`` entry or an inline
    request spec) into its canonical dict form.  Raises
    :class:`PatternError` with a client-presentable message.
    ``inline=True`` (request-supplied specs) additionally enforces the
    ``_INLINE_MAX`` / ``_INLINE_HOMES_MAX`` size ceilings."""
    if not isinstance(spec, dict):
        raise PatternError("pattern spec must be an object")
    unknown = set(spec) - set(_SPEC_KEYS)
    if unknown:
        raise PatternError(f"unknown pattern spec keys {sorted(unknown)} "
                           f"(allowed: {list(_SPEC_KEYS)})")
    out: dict = {}
    if spec.get("name") is not None:
        name = str(spec["name"])
        if not name or "/" in name or len(name) > 64:
            raise PatternError(f"bad pattern name {name!r}")
        out["name"] = name
    for key, lo in (("horizon_hours", 1), ("fleet_slots", 1),
                    ("workers", 1)):
        if spec.get(key) is None:
            continue
        try:
            v = int(spec[key])
        except (TypeError, ValueError):
            raise PatternError(f"pattern {key} must be an integer, "
                               f"got {spec[key]!r}")
        if v < lo:
            raise PatternError(f"pattern {key} must be >= {lo}, got {v}")
        if inline and v > _INLINE_MAX[key]:
            raise PatternError(f"pattern {key} must be <= "
                               f"{_INLINE_MAX[key]} for inline specs, "
                               f"got {v}")
        out[key] = v
    homes = spec.get("homes")
    if homes is not None:
        if not isinstance(homes, dict):
            raise PatternError("pattern homes must be an object of counts")
        bad = set(homes) - set(_HOMES_KEYS)
        if bad:
            raise PatternError(f"unknown pattern homes keys {sorted(bad)} "
                               f"(allowed: {sorted(_HOMES_KEYS)})")
        counts = {}
        for k, v in homes.items():
            try:
                counts[k] = int(v)
            except (TypeError, ValueError):
                raise PatternError(f"pattern homes.{k} must be an integer, "
                                   f"got {v!r}")
            if counts[k] < 0:
                raise PatternError(f"pattern homes.{k} must be >= 0")
            if inline and counts[k] > _INLINE_HOMES_MAX:
                raise PatternError(f"pattern homes.{k} must be <= "
                                   f"{_INLINE_HOMES_MAX} for inline "
                                   f"specs, got {counts[k]}")
        out["homes"] = counts
    out.setdefault("fleet_slots", max(1, int(scfg.get("fleet_slots", 1))))
    return out


def lane_config(base_config: dict, spec: dict) -> dict:
    """The engine config a lane's workers build: the daemon config with
    the spec's horizon/mix overrides applied and the fleet axis turned
    into C IDENTICAL community slots (``seed_stride = 0``,
    ``weather_offset_hours = 0`` — every slot is a copy of the serving
    community, so any request can land in any slot).  The ``[fleet]``
    table is ALWAYS pinned to the lane's geometry — a base config
    reused from fleet training (``fleet.communities = 8``, seed-strided
    DISTINCT communities) must not leak into a serving engine whose
    lane believes C = ``fleet_slots``; ``communities = 1`` with zero
    stride/offset is the engine's single-community default path, so the
    C = 1 program stays byte-identical to the round-11 engine
    (round-12 pin, tests/test_serve_fleet.py)."""
    cfg = copy.deepcopy(base_config)
    if spec.get("horizon_hours"):
        cfg["home"]["hems"]["prediction_horizon"] = int(spec["horizon_hours"])
    for k, v in (spec.get("homes") or {}).items():
        cfg["community"][_HOMES_KEYS[k]] = int(v)
    slots = int(spec.get("fleet_slots", 1))
    cfg["fleet"] = dict(cfg.get("fleet") or {})
    cfg["fleet"]["communities"] = slots
    cfg["fleet"]["seed_stride"] = 0
    cfg["fleet"]["weather_offset_hours"] = 0
    return cfg


def expanded(config: dict) -> dict:
    """The scenario-expanded copy of one lane config (packs rewrite the
    mix counts — the engine build applies the same expansion,
    dragg_tpu/scenarios).  :func:`signature` and :func:`community_size`
    accept the result via ``pre_expanded=True`` so admission pays ONE
    deepcopy + expansion per inline spec, not one per derived value
    (both run under the daemon lock)."""
    from dragg_tpu.scenarios import apply_scenarios

    return apply_scenarios(copy.deepcopy(config))


def signature(config: dict, *, pre_expanded: bool = False) -> str:
    """The bucket-pattern signature of one lane config: home-type mix ×
    horizon × fleet slots.  Scenario packs are expanded FIRST (see
    :func:`expanded`), so the signature names what actually compiles.

    Deterministic and pure — admission computes it without touching jax
    or synthesizing homes."""
    cfg = config if pre_expanded else expanded(config)
    comm = cfg["community"]
    n = int(comm["total_number_homes"])
    counts = {
        "pv_battery": int(comm.get("homes_pv_battery", 0)),
        "pv_only": int(comm.get("homes_pv", 0)),
        "battery_only": int(comm.get("homes_battery", 0)),
        "ev": int(comm.get("homes_ev", 0)),
        "heat_pump": int(comm.get("homes_heat_pump", 0)),
    }
    counts["base"] = n - sum(counts.values())
    horizon = int(cfg["home"]["hems"]["prediction_horizon"])
    slots = int(cfg.get("fleet", {}).get("communities", 1))
    mix = ",".join(f"{t}:{c}" for t, c in sorted(counts.items()) if c > 0)
    return f"h{horizon}[{mix}]xC{slots}"


def community_size(config: dict, *, pre_expanded: bool = False) -> int:
    """The per-slot serving community size of one lane config (scenario
    packs expanded — a pack's mix rewrites counts but never the total)."""
    cfg = config if pre_expanded else expanded(config)
    return int(cfg["community"]["total_number_homes"])


def spec_digest(spec: dict) -> str:
    """Canonical JSON of a normalized spec — the admission fast-path
    cache key: a repeat inline spec resolves to its lane without
    re-deriving lane config / signature (daemon ``_resolve_lane``).

    The client-chosen ``name`` is EXCLUDED: it never affects routing
    (identical geometries share a lane through the signature lookup
    regardless of name), and keying on it would let a name-cycling
    client miss the cache into a full-config deepcopy + scenario
    expansion under the daemon lock on every POST."""
    return json.dumps({k: v for k, v in spec.items() if k != "name"},
                      sort_keys=True, separators=(",", ":"))
