"""Serving worker child: ``python -m dragg_tpu.serve.worker``.

The ONLY process in a serving deployment that initializes a jax backend
(daemon-parent contract: resilience.supervisor).  Lifecycle:

1. enable the persistent compile cache, build the serving community's
   engine from the staged JSON config, and compile its one-step chunk
   program through :func:`telemetry.compile_obs.staged_compile` — so a
   hang names its stage on the heartbeat, and the cache hit/miss verdict
   lands in the ready report (the soak's warm-restart invariant reads
   exactly this);
2. write the ready file (``spool.ready_path``) carrying the compile
   report and the actual backend platform;
3. loop: claim inbox batches, solve them against the warm compiled
   runner, write outbox responses atomically (response BEFORE inbox
   unlink — spool module ordering contract), beating the heartbeat at
   every progress boundary so the daemon's stall detector only fires on
   a genuine hang;
4. exit 0 when the spool's STOP file appears (graceful drain — the
   in-flight batch finishes first).

``--stub`` runs the same protocol with a deterministic arithmetic
responder and NO jax import at all — the fast-tier daemon tests drive
every parent-side code path in milliseconds with it.

Chaos sites (``$DRAGG_FAULT_INJECT`` — resilience.faults): ``serve_boot``
fires before the engine build, ``serve_batch`` before each batch solve,
plus the ``compile_<stage>`` sites staged_compile already instruments.
"""

from __future__ import annotations

import argparse
import json
import os
import time

from dragg_tpu.resilience.faults import fault_hook
from dragg_tpu.resilience.heartbeat import beat
from dragg_tpu.serve import spool


class StubRunner:
    """Deterministic jax-free responder: the protocol without the MPC.
    Response fields mirror the engine runner's so parent-side consumers
    cannot tell them apart structurally."""

    platform = "stub"
    n_homes = 1 << 20  # accept any home index the daemon admits

    def solve(self, t: int, requests: list[dict]) -> dict:
        out = {}
        for req in requests:
            home = int(req.get("home", 0))
            st = req.get("state") or {}
            out[req["id"]] = {
                "p_grid": round(1.0 + 0.25 * home + 0.01 * t, 6),
                "temp_in": float(st.get("temp_in", 20.0)),
                "temp_wh": float(st.get("temp_wh", 46.0)),
                "e_batt": float(st.get("e_batt", 0.0)),
                "hvac_cool_on": 0.0, "hvac_heat_on": 0.5, "wh_heat_on": 0.5,
                "cost": round(0.07 * (1.0 + 0.25 * home), 6),
                "correct_solve": 1.0,
            }
        return out


class EngineRunner:
    """The real thing: a warm compiled one-step engine at the serving
    community's shape, with per-request scalar-state overrides.

    Requests are "batched into the existing bucket-pattern shapes"
    literally: the engine solves its whole fixed community batch every
    step (that IS the compiled shape), requested homes get their carried
    scalars (temp_in / temp_wh / e_batt) overridden to the caller's
    values, and only the requested homes' outputs are returned.  Engine
    state ordering is community order for both the superset and the
    bucketed path (bucket ranges are contiguous — engine.state_slice
    precedent)."""

    def __init__(self, config: dict):
        import numpy as np

        from dragg_tpu.data import load_environment, load_waterdraw_profiles
        from dragg_tpu.engine import make_engine
        from dragg_tpu.homes import build_home_batch, create_homes
        from dragg_tpu.telemetry.compile_obs import staged_compile
        from dragg_tpu.utils.compile_cache import enable_compile_cache

        self._np = np
        enable_compile_cache(config)
        beat({"stage": "serve:build"})
        # Scenario packs expand BEFORE home synthesis (mix counts) — the
        # same one-entry-point rule as the Aggregator (dragg_tpu/scenarios;
        # a pack's events reach the engine only through this expansion).
        from dragg_tpu.scenarios import apply_scenarios

        config = apply_scenarios(config)
        seed = int(config["simulation"]["random_seed"])
        env = load_environment(config)
        dt = env.dt
        hems = config["home"]["hems"]
        waterdraw = load_waterdraw_profiles(None, seed=seed)
        homes = create_homes(config, 24 * dt, dt, waterdraw)
        batch = build_home_batch(homes, int(hems["prediction_horizon"]) * dt,
                                 dt, int(hems["sub_subhourly_steps"]))
        self.engine = make_engine(batch, env, config,
                                  env.start_index(env.data_start))
        self.n_homes = self.engine.true_n_homes
        rps0 = np.zeros((1, self.engine.params.horizon), np.float32)
        self._runner, _state, _outs, self.compile_report = staged_compile(
            self.engine, self.engine.init_state(), 0, rps0, label="serve")
        self._rps0 = rps0
        # Host-side template of the initial carried state, plus the
        # community-order ranges of each state leaf-tuple element (one
        # range for the superset engine, one per bucket otherwise).
        self._template = self.engine.init_state()
        self._ranges = self._state_ranges()
        import jax

        self.platform = jax.default_backend()  # device-call-ok: serving worker is the supervised jax child

    def _state_ranges(self) -> list[tuple[int, int]]:
        if getattr(self.engine, "_bucketed", False):
            return [(c.comm_start, c.n_real) for c in self.engine._buckets]
        return [(0, self.n_homes)]

    def _with_overrides(self, requests: list[dict]):
        """The template state with each request's scalar overrides applied
        at its home's slot (field missing from the request = keep the
        engine's initial condition for that scalar)."""
        import jax.numpy as jnp

        np = self._np
        # Bucketed engines carry a tuple of per-bucket CommunityStates;
        # the superset engine carries ONE (itself a NamedTuple, so a bare
        # isinstance-tuple check would shred it into its field arrays).
        bucketed = getattr(self.engine, "_bucketed", False)
        states = list(self._template) if bucketed else [self._template]
        overridden = []
        for (start, n_real), st in zip(self._ranges, states):
            edits: dict[str, list] = {}
            for req in requests:
                home = int(req["home"])
                if not start <= home < start + n_real:
                    continue
                for field in ("temp_in", "temp_wh", "e_batt"):
                    val = (req.get("state") or {}).get(field)
                    if val is not None:
                        edits.setdefault(field, []).append(
                            (home - start, float(val)))
            if edits:
                repl = {}
                for field, pairs in edits.items():
                    arr = np.asarray(getattr(st, field)).copy()
                    for local, val in pairs:
                        arr[local] = val
                    repl[field] = jnp.asarray(arr, dtype=jnp.float32)
                st = st._replace(**repl)
            overridden.append(st)
        return tuple(overridden) if bucketed else overridden[0]

    def solve(self, t: int, requests: list[dict]) -> dict:
        np = self._np
        state = self._with_overrides(requests)
        rp = float(requests[0].get("rp", 0.0)) if requests else 0.0
        rps = self._rps0 + np.float32(rp)
        _state_out, outs = self._runner(state, t, rps)
        fields = {f: np.asarray(getattr(outs, f))[0]
                  for f in ("p_grid", "temp_in", "temp_wh", "e_batt",
                            "hvac_cool_on", "hvac_heat_on", "wh_heat_on",
                            "cost", "correct_solve")}
        return {req["id"]: {f: round(float(v[int(req["home"])]), 6)
                            for f, v in fields.items()}
                for req in requests}


def serve_loop(runner, spool_dir: str, slot: int, gen: int,
               poll_s: float, beat_every_s: float = 1.0,
               epoch: str = "") -> int:
    inbox = spool.inbox_dir(spool_dir, slot)
    outbox = spool.outbox_dir(spool_dir, slot)
    stop = spool.stop_path(spool_dir)
    last_beat = 0.0
    while True:
        # Orphan fencing: a daemon that died abruptly leaves this worker
        # running; the successor claims the spool with a fresh EPOCH
        # token, and a worker whose launch token no longer matches must
        # stand down instead of racing the new generation for batches.
        if epoch and spool.read_epoch(spool_dir) != epoch:
            beat({"stage": "serve:fenced", "gen": gen})
            return 0
        batches = spool.list_batches(inbox)
        if not batches:
            if os.path.exists(stop):
                beat({"stage": "serve:drained", "gen": gen})
                return 0
            now = time.monotonic()
            if now - last_beat >= beat_every_s:
                beat({"stage": "serve:idle", "gen": gen})
                last_beat = now
            time.sleep(poll_s)
            continue
        for seq, path in batches:
            payload = spool.read_json(path)
            if payload is None:  # mid-rename; retry next scan
                continue
            beat({"stage": "serve:batch", "batch": seq, "gen": gen})
            fault_hook("serve_batch")
            t0 = time.perf_counter()
            responses = runner.solve(int(payload.get("t", 0)),
                                     payload.get("requests", []))
            resp = {"batch": seq, "platform": runner.platform, "gen": gen,
                    "elapsed_s": round(time.perf_counter() - t0, 4),
                    "responses": responses}
            # Response BEFORE inbox unlink (spool ordering contract): a
            # crash between the two must leave the answer, not the work.
            spool.atomic_write_json(
                os.path.join(outbox, spool.batch_name(seq)), resp)
            try:
                os.remove(path)
            except OSError:
                pass
            beat({"stage": "serve:batch_done", "batch": seq, "gen": gen})
            last_beat = time.monotonic()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--spool", required=True)
    ap.add_argument("--slot", type=int, default=0)
    ap.add_argument("--gen", type=int, default=1)
    ap.add_argument("--config", default=None, help="JSON config path")
    ap.add_argument("--poll-s", type=float, default=0.05)
    ap.add_argument("--epoch", default="",
                    help="daemon ownership token; exit when the spool's "
                         "EPOCH file stops matching (orphan fencing)")
    ap.add_argument("--stub", action="store_true",
                    help="deterministic jax-free responder (protocol tests)")
    args = ap.parse_args()

    beat({"stage": "serve:boot", "slot": args.slot, "gen": args.gen})
    fault_hook("serve_boot")
    t0 = time.perf_counter()
    if args.stub:
        runner = StubRunner()
        report = {"stub": True}
    else:
        with open(args.config) as f:
            config = json.load(f)
        runner = EngineRunner(config)
        report = runner.compile_report
    spool.ensure_slot_dirs(args.spool, args.slot)
    spool.atomic_write_json(
        spool.ready_path(args.spool, args.slot, args.gen),
        {"slot": args.slot, "gen": args.gen, "platform": runner.platform,
         "warmup_s": round(time.perf_counter() - t0, 3),
         "n_homes": runner.n_homes, "compile": report})
    beat({"stage": "serve:ready", "slot": args.slot, "gen": args.gen})
    return serve_loop(runner, args.spool, args.slot, args.gen, args.poll_s,
                      epoch=args.epoch)


if __name__ == "__main__":
    raise SystemExit(main())
