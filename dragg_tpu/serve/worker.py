"""Serving worker child: ``python -m dragg_tpu.serve.worker``.

The ONLY process in a serving deployment that initializes a jax backend
(daemon-parent contract: resilience.supervisor).  Lifecycle:

1. enable the persistent compile cache, build the serving pattern's
   engine from the staged JSON config, and compile its one-step chunk
   program through :func:`telemetry.compile_obs.staged_compile` — so a
   hang names its stage on the heartbeat, and the cache hit/miss verdict
   lands in the ready report (the soak's warm-restart invariant reads
   exactly this);
2. write the ready file (``spool.ready_path``) carrying the compile
   report, the actual backend platform, and the fleet-slot geometry;
3. loop: claim inbox batches, solve them against the warm compiled
   runner, write outbox responses atomically (response BEFORE inbox
   unlink — spool module ordering contract), beating the heartbeat at
   every progress boundary so the daemon's stall detector only fires on
   a genuine hang;
4. exit 0 when the spool's STOP file appears (graceful drain — the
   in-flight batch finishes first).

**Fleet-backed batches** (ISSUE 13): with ``serve.fleet_slots = C > 1``
the worker's engine is a C-community FLEET of identical copies of the
serving community (serve/patterns.lane_config: ``seed_stride = 0``), so
ONE warm compiled solve serves up to C coalesced request *groups* — each
group owns a community slot and its own reward price through the
engine's per-community ``(C, H)`` rp path.  Per-request outputs
de-interleave from the merged batch via ``engine.real_home_cols``
(community-major global index ``cslot·B + home`` → merged column).
``C = 1`` keeps the round-11 single-community program byte-identical.

**Multi-chunk requests** stream: a group's ``steps = N > 1`` re-runs the
warm one-step program N times, carrying state, and emits one
``serve.chunk`` telemetry event per request per step — the daemon's
``/result?stream=1`` tail serves them incrementally, so first-chunk
latency decouples from run length.

``--stub`` runs the same protocol with a deterministic arithmetic
responder and NO jax import at all — the fast-tier daemon tests drive
every parent-side code path in milliseconds with it.

Chaos sites (``$DRAGG_FAULT_INJECT`` — resilience.faults): ``serve_boot``
fires before the engine build, ``serve_batch`` before each batch solve,
plus the ``compile_<stage>`` sites staged_compile already instruments.
"""

from __future__ import annotations

import argparse
import json
import os
import time

from dragg_tpu import telemetry
from dragg_tpu.resilience.faults import fault_hook
from dragg_tpu.resilience.heartbeat import beat
from dragg_tpu.serve import spool

# The per-home StepOutputs fields a response carries (first MPC action +
# provenance scalars) — shared by both runners so parent-side consumers
# cannot tell them apart structurally.
RESPONSE_FIELDS = ("p_grid", "temp_in", "temp_wh", "e_batt",
                   "hvac_cool_on", "hvac_heat_on", "wh_heat_on",
                   "cost", "correct_solve")


def _as_groups(payload: dict) -> list[dict]:
    """The batch's request groups.  Modern batches carry ``groups``
    (coalesced fleet dispatch); a legacy/hand-crafted ``requests`` list
    degrades to one group at community slot 0."""
    groups = payload.get("groups")
    if groups is None:
        reqs = payload.get("requests", [])
        rp = float(reqs[0].get("rp", 0.0)) if reqs else 0.0
        groups = [{"cslot": 0, "rp": rp, "requests": reqs}]
    return groups


class StubRunner:
    """Deterministic jax-free responder: the protocol without the MPC.
    Response fields mirror the engine runner's so parent-side consumers
    cannot tell them apart structurally; multi-step groups emit the same
    ``serve.chunk`` stream the engine runner does, so streaming is
    testable in milliseconds."""

    platform = "stub"
    n_homes = 1 << 20  # accept any home index the daemon admits
    fleet_slots = 1 << 10  # and any community slot

    def _fields(self, t: int, req: dict) -> dict:
        home = int(req.get("home", 0))
        st = req.get("state") or {}
        return {
            "p_grid": round(1.0 + 0.25 * home + 0.01 * t, 6),
            "temp_in": float(st.get("temp_in", 20.0)),
            "temp_wh": float(st.get("temp_wh", 46.0)),
            "e_batt": float(st.get("e_batt", 0.0)),
            "hvac_cool_on": 0.0, "hvac_heat_on": 0.5, "wh_heat_on": 0.5,
            "cost": round(0.07 * (1.0 + 0.25 * home), 6),
            "correct_solve": 1.0,
        }

    def solve(self, t: int, groups: list[dict], steps: int = 1,
              span: str | None = None) -> dict:
        out = {}
        for g in groups:
            cslot = int(g.get("cslot", 0))
            for req in g["requests"]:
                for k in range(steps):
                    fields = self._fields(t + k, req)
                    if steps > 1:
                        telemetry.emit("serve.chunk", id=req["id"], step=k,
                                       steps=steps, timestep=t + k, **fields,
                                       **telemetry.trace.child_fields(
                                           parent=span))
                out[req["id"]] = {**fields, "cslot": cslot, "steps": steps}
        return out


class EngineRunner:
    """The real thing: a warm compiled one-step engine at the serving
    pattern's shape, with per-request scalar-state overrides.

    Requests are "batched into the existing bucket-pattern shapes"
    literally: the engine solves its whole fixed batch every step (that
    IS the compiled shape).  With ``fleet_slots = C > 1`` the batch is a
    C-slot fleet of identical communities — each coalesced group lands
    in a community slot (its reward price in that slot's row of the
    ``(C, H)`` rp array, its state overrides at its homes' state rows) —
    and only the requested homes' outputs are returned, de-interleaved
    through ``engine.real_home_cols``.  ``C = 1`` is the round-11
    single-community path, byte-identical (``[fleet]`` untouched).

    Engine state row mapping is derived from the engine's own fleet
    rows (``global_idx`` inverse), so the superset, type-bucketed, and
    mesh-sharded variants all de-interleave through one code path
    (parity: tests/test_serve_fleet.py)."""

    def __init__(self, config: dict):
        import numpy as np

        from dragg_tpu.data import load_environment, load_waterdraw_profiles
        from dragg_tpu.homes import build_fleet_batch, create_fleet_homes
        from dragg_tpu.telemetry.compile_obs import staged_compile
        from dragg_tpu.utils.compile_cache import enable_compile_cache

        self._np = np
        enable_compile_cache(config)
        beat({"stage": "serve:build"})
        # Scenario packs expand BEFORE home synthesis (mix counts) — the
        # same one-entry-point rule as the Aggregator (dragg_tpu/scenarios;
        # a pack's events reach the engine only through this expansion).
        from dragg_tpu.scenarios import apply_scenarios

        config = apply_scenarios(config)
        seed = int(config["simulation"]["random_seed"])
        env = load_environment(config)
        dt = env.dt
        hems = config["home"]["hems"]
        waterdraw = load_waterdraw_profiles(None, seed=seed)
        homes = create_fleet_homes(config, 24 * dt, dt, waterdraw)
        batch, fleet = build_fleet_batch(
            homes, config, int(hems["prediction_horizon"]) * dt, dt,
            int(hems["sub_subhourly_steps"]))
        self.engine = self._build_engine(batch, env, config, fleet)
        self.fleet_slots = 1 if fleet is None else fleet.n_communities
        # The serving community size is PER SLOT: admission range-checks
        # request homes against one community, whichever slot they land in.
        self.n_homes = self.engine.true_n_homes // self.fleet_slots
        H = self.engine.params.horizon
        rps0 = (np.zeros((1, H), np.float32) if self.fleet_slots == 1
                else np.zeros((1, self.fleet_slots, H), np.float32))
        self._runner, _state, _outs, self.compile_report = staged_compile(
            self.engine, self.engine.init_state(), 0, rps0, label="serve")
        self._rps0 = rps0
        # Host-side template of the initial carried state, the state row
        # of each community-major global home index, and the merged
        # output column carrying it.
        self._template = self.engine.init_state()
        self._state_pos = self._state_positions()
        self._out_cols = np.asarray(self.engine.real_home_cols)
        import jax

        self.platform = jax.default_backend()  # dragg: disable=DT004, serving worker is the supervised jax child

    def _build_engine(self, batch, env, config, fleet):
        """Mirror the Aggregator's mesh decision: multi-device processes
        shard the home axis automatically (``tpu.sharded`` forces either
        way) — the de-interleave path is identical, only data placement
        changes."""
        from dragg_tpu.engine import make_engine

        sharded = config.get("tpu", {}).get("sharded", "auto")
        if sharded == "auto":
            from dragg_tpu.resilience.devices import device_count

            use_sharded = device_count() > 1
        else:
            use_sharded = bool(sharded)
        if use_sharded:
            from dragg_tpu.parallel import make_sharded_engine

            return make_sharded_engine(batch, env, config,
                                       env.start_index(env.data_start),
                                       fleet=fleet)
        return make_engine(batch, env, config,
                           env.start_index(env.data_start), fleet=fleet)

    # ------------------------------------------------------------- mapping
    def _state_positions(self) -> dict[int, tuple[int, int]]:
        """community-major global home index -> (state element, local row).
        Derived from the engine's own fleet rows: batch row ``i`` carries
        global home ``home_idx[i]``; bucketed engines slice batch rows
        ``comm_start..comm_start+n_real`` into bucket element rows
        ``0..n_real`` (shard padding appends after the real rows)."""
        eng = self.engine
        home_idx = self._np.asarray(eng._fleet_rows["home_idx"])
        pos: dict[int, tuple[int, int]] = {}
        if getattr(eng, "_bucketed", False):
            for e, c in enumerate(eng._buckets):
                for local in range(c.n_real):
                    pos[int(home_idx[c.comm_start + local])] = (e, local)
        else:
            for row in range(eng.true_n_homes):
                pos[int(home_idx[row])] = (0, row)
        return pos

    def _with_overrides(self, groups: list[dict]):
        """The template state with each request's scalar overrides applied
        at its home's state row (field missing from the request = keep the
        engine's initial condition for that scalar)."""
        import jax.numpy as jnp

        np = self._np
        # Bucketed engines carry a tuple of per-bucket CommunityStates;
        # the superset engine carries ONE (itself a NamedTuple, so a bare
        # isinstance-tuple check would shred it into its field arrays).
        bucketed = getattr(self.engine, "_bucketed", False)
        states = list(self._template) if bucketed else [self._template]
        edits: dict[tuple[int, str], list] = {}
        for g in groups:
            base = int(g.get("cslot", 0)) * self.n_homes
            for req in g["requests"]:
                elem, local = self._state_pos[base + int(req["home"])]
                for field in ("temp_in", "temp_wh", "e_batt"):
                    val = (req.get("state") or {}).get(field)
                    if val is not None:
                        edits.setdefault((elem, field), []).append(
                            (local, float(val)))
        by_elem: dict[int, dict] = {}
        for (elem, field), pairs in edits.items():
            arr = np.asarray(getattr(states[elem], field)).copy()
            for local, val in pairs:
                arr[local] = val
            by_elem.setdefault(elem, {})[field] = jnp.asarray(
                arr, dtype=jnp.float32)
        for elem, repl in by_elem.items():
            states[elem] = states[elem]._replace(**repl)
        state = tuple(states) if bucketed else states[0]
        mesh = getattr(self.engine, "mesh", None)
        if mesh is not None and by_elem:
            # Edited leaves came back as host arrays; re-commit the mesh
            # placement the compiled executable was built against.
            from dragg_tpu.parallel import shard_state

            state = shard_state(state, mesh, self.engine.axis_name)
        return state

    # --------------------------------------------------------------- solve
    def solve(self, t: int, groups: list[dict], steps: int = 1,
              span: str | None = None) -> dict:
        np = self._np
        state = self._with_overrides(groups)
        if self.fleet_slots == 1:
            rp = float(groups[0].get("rp", 0.0)) if groups else 0.0
            rps = self._rps0 + np.float32(rp)
        else:
            rp_c = np.zeros((self.fleet_slots, 1), np.float32)
            for g in groups:
                rp_c[int(g.get("cslot", 0))] = np.float32(g.get("rp") or 0.0)
            rps = self._rps0 + rp_c[None]
        want = [(req, int(g.get("cslot", 0)))
                for g in groups for req in g["requests"]]
        cols = self._out_cols
        resp: dict[str, dict] = {}
        for k in range(steps):
            state, outs = self._runner(state, t + k, rps)
            fields = {f: np.asarray(getattr(outs, f))[0]
                      for f in RESPONSE_FIELDS}
            for req, cslot in want:
                col = cols[cslot * self.n_homes + int(req["home"])]
                vals = {f: round(float(v[col]), 6)
                        for f, v in fields.items()}
                if steps > 1:
                    telemetry.emit("serve.chunk", id=req["id"], step=k,
                                   steps=steps, timestep=t + k, **vals,
                                   **telemetry.trace.child_fields(
                                       parent=span))
                resp[req["id"]] = {**vals, "cslot": cslot, "steps": steps}
            if steps > 1:
                beat({"stage": "serve:chunk", "step": k, "steps": steps})
        return resp


def serve_loop(runner, spool_dir: str, slot: int, gen: int,
               poll_s: float, beat_every_s: float = 1.0,
               epoch: str = "") -> int:
    inbox = spool.inbox_dir(spool_dir, slot)
    outbox = spool.outbox_dir(spool_dir, slot)
    stop = spool.stop_path(spool_dir)
    last_beat = 0.0
    while True:
        # Orphan fencing: a daemon that died abruptly leaves this worker
        # running; the successor claims the spool with a fresh EPOCH
        # token, and a worker whose launch token no longer matches must
        # stand down instead of racing the new generation for batches.
        if epoch and spool.read_epoch(spool_dir) != epoch:
            beat({"stage": "serve:fenced", "gen": gen})
            return 0
        batches = spool.list_batches(inbox)
        if not batches:
            if os.path.exists(stop):
                beat({"stage": "serve:drained", "gen": gen})
                return 0
            now = time.monotonic()
            if now - last_beat >= beat_every_s:
                beat({"stage": "serve:idle", "gen": gen})
                last_beat = now
            time.sleep(poll_s)
            continue
        for seq, path in batches:
            payload = spool.read_json(path)
            if payload is None:  # mid-rename; retry next scan
                continue
            beat({"stage": "serve:batch", "batch": seq, "gen": gen})
            fault_hook("serve_batch")
            groups = _as_groups(payload)
            t0 = time.perf_counter()
            # The batch span (daemon _dispatch) rides the inbox payload;
            # per-chunk serve.chunk records parent on it so the request
            # -> batch -> chunk chain crosses the process boundary.
            responses = runner.solve(int(payload.get("t", 0)), groups,
                                     steps=max(1, int(payload.get("steps", 1))),
                                     span=payload.get("span"))
            resp = {"batch": seq, "platform": runner.platform, "gen": gen,
                    "elapsed_s": round(time.perf_counter() - t0, 4),
                    "groups": len(groups), "responses": responses}
            # Response BEFORE inbox unlink (spool ordering contract): a
            # crash between the two must leave the answer, not the work.
            spool.atomic_write_json(
                os.path.join(outbox, spool.batch_name(seq)), resp)
            try:
                os.remove(path)
            except OSError:
                pass
            beat({"stage": "serve:batch_done", "batch": seq, "gen": gen})
            last_beat = time.monotonic()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--spool", required=True)
    ap.add_argument("--slot", type=int, default=0)
    ap.add_argument("--gen", type=int, default=1)
    ap.add_argument("--config", default=None, help="JSON config path")
    ap.add_argument("--poll-s", type=float, default=0.05)
    ap.add_argument("--epoch", default="",
                    help="daemon ownership token; exit when the spool's "
                         "EPOCH file stops matching (orphan fencing)")
    ap.add_argument("--stub", action="store_true",
                    help="deterministic jax-free responder (protocol tests)")
    args = ap.parse_args()

    beat({"stage": "serve:boot", "slot": args.slot, "gen": args.gen})
    fault_hook("serve_boot")
    t0 = time.perf_counter()
    if args.stub:
        runner = StubRunner()
        report = {"stub": True}
    else:
        with open(args.config) as f:
            config = json.load(f)
        runner = EngineRunner(config)
        report = runner.compile_report
    spool.ensure_slot_dirs(args.spool, args.slot)
    spool.atomic_write_json(
        spool.ready_path(args.spool, args.slot, args.gen),
        {"slot": args.slot, "gen": args.gen, "platform": runner.platform,
         "warmup_s": round(time.perf_counter() - t0, 3),
         "n_homes": runner.n_homes,
         "fleet_slots": getattr(runner, "fleet_slots", 1),
         "compile": report})
    beat({"stage": "serve:ready", "slot": args.slot, "gen": args.gen})
    return serve_loop(runner, args.spool, args.slot, args.gen, args.poll_s,
                      epoch=args.epoch)


if __name__ == "__main__":
    raise SystemExit(main())
