"""Fault-tolerant MPC serving daemon — the jax-free parent process.

``python -m dragg_tpu serve`` keeps warm compiled MPC engines behind an
HTTP surface and survives every failure kind in the resilience taxonomy
without losing a request.  The reference's lifetime model — one
pathos+Redis aggregator whose queue dies with the process
(dragg/aggregator.py:723-724) — is exactly what this daemon replaces:

* **crash-safe request journal** (serve/journal.py): a request is
  acknowledged only after its ``accepted`` record is fsync'd; on restart
  unfinished requests replay automatically and terminal records answer
  duplicates without re-solving — zero lost, zero double-answered, by
  construction;
* **supervised worker pool** (serve/pool.py + serve/worker.py): workers
  hold compiled engines warm (persistent compile cache + staged compile
  telemetry), are stall-killed on hung compiles (round-4 wedge
  prevention) and batch deadlines, and every death is classified with
  the taxonomy and retried with probe-gated backoff;
* **fleet-backed coalescing** (ISSUE 13): with ``serve.fleet_slots = C``
  each worker's engine is a C-slot FLEET of identical copies of the
  serving community (round 12: compile flat in C), and the dispatch
  loop coalesces queued requests into fleet batches under a
  latency-aware window (``serve.batch_window_ms`` — dispatch fires
  early the moment all C community slots fill).  One warm solve serves
  up to C request groups, each with its own reward price through the
  engine's per-community rp path; results map back per request via
  ``engine.real_home_cols``;
* **multi-pattern admission** (serve/patterns.py): requests route to
  worker lanes by bucket-pattern signature (home-type mix × horizon ×
  fleet slots).  ``serve.patterns`` lanes warm at boot; an inline
  request spec for an unseen signature spills to a bounded
  compile-on-demand lane (``serve.spill_patterns``), its generation
  provenance journaled so a restart can rebuild it;
* **streaming** — a multi-chunk request (``steps = N``) streams
  incremental per-chunk results over the existing events.jsonl tail:
  ``GET /result?id=…&stream=1`` answers newline-delimited JSON, one
  line per solved chunk plus the terminal record, so first-chunk
  latency decouples from run length;
* **probe-gated admission + degradation**: a dead/wedged tunnel flips
  the service to degraded-CPU serving (transition journaled, provenance
  attached to every response answered while degraded) instead of
  queueing doomed TPU work; strict ``--platform tpu`` with
  ``serve.degrade_to_cpu=false`` answers 429 + Retry-After until the
  probe goes green;
* **bounded everything**: per-request deadlines, bounded retry
  (``serve.request_retries``), queue backpressure (429 + Retry-After),
  bounded spill-lane compiles, graceful SIGTERM drain (in-flight work
  finishes; the journal carries whatever didn't).

HTTP endpoints (the dashboard's stdlib ``http.server`` idiom — its
``/live`` + ``/metrics.json`` surface, extended with serving state):

    POST /solve          accept one request (or a JSON list) -> 202/200/429/503
    GET  /result?id=...  poll one request's outcome
    GET  /result?id=...&stream=1   NDJSON chunk stream + terminal record
    GET  /healthz        process liveness (always 200 while serving)
    GET  /readyz         200 only when a warm worker can take traffic
    GET  /metrics.json   telemetry snapshot + serving counters
    GET  /events.jsonl   bounded tail of the run's telemetry stream

Request schema (POST /solve body)::

    {"id": "r1", "t": 0, "home": 3, "rp": 0.0,
     "state": {"temp_in": 20.5, "temp_wh": 46.0, "e_batt": 2.0},
     "deadline_s": 60, "steps": 1, "pattern": "default"}

``id`` is the idempotency key (generated when absent); ``home`` indexes
the serving community (whichever fleet slot the request lands in);
``state`` scalars override that home's carried initial conditions;
``steps`` > 1 makes the request multi-chunk (streamable); ``pattern``
names a lane, or carries an inline spec (serve/patterns.py).  The
response carries the home's MPC action at the final step (duty
fractions, p_grid, cost, solve verdict), the community slot it was
coalesced into, plus provenance (platform, retries, degradation record
when the service degraded).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dragg_tpu import telemetry
from dragg_tpu.resilience import liveness
from dragg_tpu.serve import journal as journal_mod
from dragg_tpu.serve import patterns as patterns_mod
from dragg_tpu.serve import spool
from dragg_tpu.serve.pool import WorkerSlot

# Failure kinds that can be transient worker trouble rather than a dead
# device path: after one of these on the TPU mode the daemon re-probes
# and only degrades when the probe agrees the tunnel is gone.
_BACKOFF_CAP_S = 60.0


def serve_config(config: dict | None) -> dict:
    """The ``[serve]`` config section with defaults applied."""
    from dragg_tpu.config import default_config

    merged = dict(default_config()["serve"])
    merged.update((config or {}).get("serve", {}))
    return merged


class PatternLane:
    """One bucket-pattern signature's worker lane: the derived engine
    config its workers build, the admission geometry (community size,
    fleet slots, per-group cap), and the worker slots serving it."""

    def __init__(self, name: str, signature: str, spec: dict, source: str,
                 cfg_path: str | None, n_homes: int, fleet_slots: int,
                 batch_max: int):
        self.name = name
        self.signature = signature
        self.spec = spec
        self.source = source  # "config" | "spill" | "replay"
        self.cfg_path = cfg_path
        self.n_homes = n_homes
        self.fleet_slots = max(1, fleet_slots)
        self.batch_max = max(1, batch_max)
        self.slots: list[WorkerSlot] = []

    def describe(self) -> dict:
        return {"signature": self.signature, "source": self.source,
                "workers": [s.slot for s in self.slots],
                "n_homes": self.n_homes, "fleet_slots": self.fleet_slots}


class ServeDaemon:
    """One serving deployment: journal + pattern lanes + worker pool +
    HTTP surface.

    Programmatic use (tests, the soak, the load harness)::

        d = ServeDaemon(config, serve_dir, platform="cpu")
        d.start()              # HTTP + dispatch threads; d.port bound
        ... POST/GET against http://127.0.0.1:{d.port} ...
        d.stop(drain=True)
    """

    def __init__(self, config: dict, serve_dir: str, *,
                 platform: str = "auto", host: str | None = None,
                 port: int | None = None, stub: bool = False,
                 log=None, sleep=time.sleep):
        self.config = json.loads(json.dumps(config))  # JSON-able contract
        self.scfg = serve_config(self.config)
        self.serve_dir = serve_dir
        self.platform_req = platform
        self.stub = stub
        self.log = log or (lambda m: None)
        self.sleep = sleep
        os.makedirs(serve_dir, exist_ok=True)
        self.spool_dir = os.path.join(serve_dir, "spool")
        os.makedirs(self.spool_dir, exist_ok=True)
        # A leftover STOP from a previous drain must not kill fresh workers.
        try:
            os.remove(spool.stop_path(self.spool_dir))
        except OSError:
            pass
        # Trace plane (ISSUE 20): ``telemetry.trace = true`` makes the
        # daemon the trace root — request spans parent on it, batch
        # spans parent on requests, and the worker env export carries
        # the context into serve.chunk records.  The live-flush cadence
        # rides the env so worker children inherit it.
        tcfg = self.config.get("telemetry", {})
        flush_cfg = float(tcfg.get("flush_interval_s", 0.0) or 0.0)
        if flush_cfg and not os.environ.get(telemetry.ENV_FLUSH):
            os.environ[telemetry.ENV_FLUSH] = str(flush_cfg)
        if tcfg.get("trace") and not telemetry.trace.enabled():
            telemetry.trace.enable()
        self._owns_bus = False
        if tcfg.get("enabled", True) and not telemetry.active():
            telemetry.init_run(os.environ.get(telemetry.ENV_DIR) or serve_dir)
            self._owns_bus = True

        # ----- journal replay BEFORE opening the append side
        jpath = os.path.join(serve_dir, "journal.jsonl")
        rep = journal_mod.replay(jpath)
        self.journal = journal_mod.Journal(
            jpath, fsync=bool(self.scfg["journal_fsync"]),
            terminal_ids=rep.terminal)
        self.lock = threading.RLock()
        self.pending: dict[str, dict] = {}    # id -> entry (queue, FIFO)
        self.assigned: dict[str, dict] = {}   # id -> entry (in a batch)
        # In-memory answer cache, BOUNDED (the journal is the unbounded
        # record): insertion-ordered dict, oldest evicted past the cap —
        # a daemon that serves for months must not hold every response
        # ever answered.  Evicted ids answer 404 on /result; duplicate
        # re-submissions of evicted ids are refused by the journal and
        # reported as terminal duplicates, never re-answered.
        self._results_cap = max(64, int(self.scfg["results_cache"]))
        self.results: dict[str, dict] = dict(
            list(rep.terminal.items())[-self._results_cap:])
        self.transition: dict | None = rep.transition
        self.in_flight: dict[int, dict] = {}  # slot -> batch record
        self._kill_ctx: dict[int, dict] = {}  # slot -> how the daemon killed it
        self.batch_seq = 0
        self.draining = False
        self._active_streams = 0  # /result?stream=1 consumers (bounded
                                  # by serve.max_streams — each holds an
                                  # HTTP thread + events-tail follower)

        # ----- worker pool: pattern lanes (serve/patterns.py)
        # Claim the spool BEFORE slot construction: orphan workers of a
        # predecessor daemon exit when the EPOCH token stops matching
        # theirs (worker fencing).
        self.epoch = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
        spool.write_epoch(self.spool_dir, self.epoch)
        self.slots: list[WorkerSlot] = []
        self.lanes: dict[str, PatternLane] = {}
        self._sig_to_lane: dict[str, str] = {}
        self._digest_to_lane: dict[str, str] = {}
        default_spec = patterns_mod.normalize_spec({}, self.scfg)
        self._add_lane("default", default_spec, "config",
                       workers=max(1, int(self.scfg["workers"])),
                       journal=False)
        for entry in self.scfg["patterns"]:
            # A malformed configured pattern is a boot error, loudly —
            # never a 400 some future request trips over.
            spec = patterns_mod.normalize_spec(entry, self.scfg)
            name = spec.get("name")
            if not name or name in self.lanes:
                raise ValueError(
                    f"serve.patterns entries need unique names "
                    f"(got {name!r})")
            self._add_lane(name, spec, "config",
                           workers=spec.get("workers", 1), journal=False)
        self.n_homes = self.lanes["default"].n_homes
        self.batch_max = self.lanes["default"].batch_max

        # ----- requeue replayed pending requests (lanes must exist first;
        # spill lanes rebuild from their journaled provenance records)
        self._replay_patterns = rep.patterns
        now = time.monotonic()
        for rid, rec in rep.pending.items():
            req = rec.get("req") or {}
            entry = self._entry(rid, req, now, replayed=True)
            lane = self._replay_lane(req)
            if lane is None:
                self._fail(entry, "pattern lane unknown at replay (no "
                                  "journaled provenance)")
                continue
            entry["lane"] = lane
            # Replay-side range check mirrors accept(): a journal from a
            # shrunk community (or a hand-edited record) must fail
            # terminally here, never reach a worker — an out-of-range
            # home KeyErrors the engine child and takes every coalesced
            # batch-mate's attempt down with it.
            try:
                home_ok = 0 <= int(req.get("home", 0)) \
                    < self.lanes[lane].n_homes
            except (TypeError, ValueError):
                home_ok = False
            if not home_ok:
                self._fail(entry,
                           f"replayed home {req.get('home')!r} outside "
                           f"lane {lane!r} community "
                           f"[0, {self.lanes[lane].n_homes})")
                continue
            self.pending[rid] = entry
        if rep.pending or rep.dropped_lines:
            telemetry.emit("serve.replay", requeued=len(self.pending),
                           terminal=len(rep.terminal),
                           dropped_lines=rep.dropped_lines)
            self.log(f"journal replay: {len(self.pending)} requeued, "
                     f"{len(rep.terminal)} terminal, "
                     f"{rep.dropped_lines} torn/dropped lines")

        # Resolved serving platform.  None = a probe verdict is owed —
        # launches park until the dispatch loop's UNLOCKED probe phase
        # applies one (the probe can block up to probe_timeout_s; it must
        # never run under the daemon lock or /healthz freezes with it).
        self.mode: str | None = "cpu" if platform == "cpu" else None
        self._probe_failure: str | None = None  # precipitating worker failure
        self.backoff_until = 0.0
        self.consec_failures = 0
        self.started_at = time.monotonic()
        self.stop_event = threading.Event()
        self._threads: list[threading.Thread] = []
        self._httpd = None
        self.host = host or str(self.scfg["host"])
        self.port = port if port is not None else int(self.scfg["port"])

    # --------------------------------------------------------------- lanes
    def _add_lane(self, name: str, spec: dict, source: str, *,
                  workers: int | None = None, journal: bool = True,
                  cfg: dict | None = None,
                  signature: str | None = None) -> PatternLane:
        """Create one pattern lane + its worker slots (caller holds the
        lock, or is the constructor).  ``journal=True`` records the
        generation provenance (spill lanes — a restart must be able to
        rebuild the lane its replayed requests name)."""
        if cfg is None:
            cfg = patterns_mod.lane_config(self.config, spec)
        if signature is None:
            signature = patterns_mod.signature(cfg)
        n_homes = patterns_mod.community_size(cfg)
        cfg_path = None
        if not self.stub:
            fd, cfg_path = tempfile.mkstemp(prefix=f"dragg_serve_{name}_",
                                            suffix=".json",
                                            dir=self.serve_dir)
            with os.fdopen(fd, "w") as f:
                json.dump(cfg, f)
        lane = PatternLane(
            name, signature, spec, source, cfg_path, n_homes,
            int(spec.get("fleet_slots", 1)),
            int(self.scfg["batch_max"]) or n_homes)
        for _ in range(max(1, int(workers or spec.get("workers", 1)))):
            slot = WorkerSlot(self.spool_dir, len(self.slots),
                              cfg_path=cfg_path, stub=self.stub,
                              poll_s=float(self.scfg["poll_s"]),
                              epoch=self.epoch, log=self.log, pattern=name)
            self.slots.append(slot)
            lane.slots.append(slot)
        self.lanes[name] = lane
        self._sig_to_lane[signature] = name
        if journal:
            self.journal.pattern(name, signature, spec, source)
        telemetry.emit("serve.pattern", name=name, signature=signature,
                       source=source, workers=len(lane.slots),
                       fleet_slots=lane.fleet_slots)
        telemetry.set_gauge("serve.patterns_active", len(self.lanes))
        if source == "spill":
            telemetry.inc("serve.spill_lanes", 1)
        self.log(f"pattern lane {name!r} [{signature}] source={source} "
                 f"workers={len(lane.slots)} C={lane.fleet_slots}")
        return lane

    def _replay_lane(self, req: dict) -> str | None:
        """Resolve a replayed request's lane, rebuilding a journaled
        spill lane when needed; None = unroutable (fails terminally)."""
        name = req.get("pattern") or "default"
        if not isinstance(name, str):
            return None
        if name in self.lanes:
            return name
        rec = self._replay_patterns.get(name)
        if rec is None:
            return None
        try:
            spec = patterns_mod.normalize_spec(rec.get("spec") or {},
                                               self.scfg)
            self._add_lane(name, spec, "replay", journal=False)
            return name
        except (patterns_mod.PatternError, ValueError, OSError):
            return None

    def _resolve_lane(self, req: dict):
        """Admission-time routing: (lane_name, None) or
        (None, (status, body)).  Inline specs for an unseen signature
        spill to a bounded compile-on-demand lane, provenance journaled
        BEFORE the request that caused it can be accepted."""
        pat = req.get("pattern")
        if pat is None or pat == "default":
            return "default", None
        if isinstance(pat, str):
            if pat in self.lanes:
                return pat, None
            return None, (400, {"error": f"unknown pattern lane {pat!r} "
                                         f"(lanes: {sorted(self.lanes)}); "
                                         f"pass an inline spec to spill"})
        try:
            spec = patterns_mod.normalize_spec(pat, self.scfg, inline=True)
        except patterns_mod.PatternError as e:
            return None, (400, {"error": str(e)})
        # Fast path for the steady state (a client stream that routinely
        # repeats the same inline spec): a known spec digest routes
        # without re-deriving lane config / signature — those cost a
        # full-config deepcopy + scenario expansion under the daemon
        # lock.
        digest = patterns_mod.spec_digest(spec)
        name = self._digest_to_lane.get(digest)
        if name is not None and name in self.lanes:
            return name, None
        cfg = patterns_mod.lane_config(self.config, spec)
        exp = patterns_mod.expanded(cfg)
        sig = patterns_mod.signature(exp, pre_expanded=True)
        name = self._sig_to_lane.get(sig)
        if name is not None:
            self._cache_digest(digest, name)
            return name, None
        # Reject an unroutable request BEFORE spending the bounded spill
        # budget on its lane (community_size is pure config math — a
        # 400-doomed request must never trigger a compile).
        n_homes = patterns_mod.community_size(exp, pre_expanded=True)
        if not 0 <= int(req.get("home", 0)) < n_homes:
            return None, (400, {"error": f"home {req.get('home')} outside "
                                         f"the serving community "
                                         f"[0, {n_homes}) of the inline "
                                         f"pattern spec"})
        n_spill = sum(1 for ln in self.lanes.values()
                      if ln.source in ("spill", "replay"))
        if n_spill >= int(self.scfg["spill_patterns"]):
            retry = float(self.scfg["retry_after_s"])
            telemetry.inc("serve.requests_rejected", 1)
            telemetry.emit("serve.reject", id=req.get("id"),
                           reason="pattern_capacity", retry_after_s=retry)
            return None, (429, {"error": "compile-on-demand pattern "
                                         "capacity exhausted "
                                         "(serve.spill_patterns)",
                                "retry_after_s": retry})
        name = spec.get("name") or f"spill{n_spill + 1}"
        if name in self.lanes:  # name collision with a different signature
            base, k = name, len(self.lanes)
            while f"{base}-{k}" in self.lanes:  # the suffix itself may
                k += 1                          # collide with a client-
            name = f"{base}-{k}"                # chosen lane name
        self._add_lane(name, spec, "spill", cfg=cfg, signature=sig)
        self._cache_digest(digest, name)
        return name, None

    def _cache_digest(self, digest: str, lane: str) -> None:
        """Remember a resolved inline-spec digest, bounded: the digest
        carries the client-chosen ``name`` field, so an adversarial
        stream could otherwise grow the map without bound (many digests
        may legitimately map to one lane)."""
        if len(self._digest_to_lane) >= 1024:
            self._digest_to_lane.pop(next(iter(self._digest_to_lane)))
        self._digest_to_lane[digest] = lane

    # ------------------------------------------------------------ admission
    def _normalize_request(self, req: dict) -> tuple[dict | None, str | None]:
        """Validate and coerce one request BEFORE the durability point —
        a malformed field must answer 400, never reach the journal (a
        poisoned 'accepted' record would crash every later replay: the
        one bad POST that bricks restarts)."""
        if not isinstance(req, dict):
            return None, "request body must be a JSON object"
        out = dict(req)
        try:
            out["home"] = int(req.get("home", 0))
        except (TypeError, ValueError):
            return None, f"home must be an integer, got {req.get('home')!r}"
        if out["home"] < 0:
            return None, f"home must be >= 0, got {out['home']}"
        for field, cast, default in (("t", int, 0), ("rp", float, 0.0)):
            raw = req.get(field)
            try:
                out[field] = default if raw is None else cast(raw)
            except (TypeError, ValueError):
                return None, f"{field} must be a number, got {raw!r}"
        if req.get("deadline_s") is not None:
            try:
                out["deadline_s"] = float(req["deadline_s"])
            except (TypeError, ValueError):
                return None, (f"deadline_s must be a number, got "
                              f"{req.get('deadline_s')!r}")
        if req.get("steps") is not None:
            try:
                steps = int(req["steps"])
            except (TypeError, ValueError):
                return None, f"steps must be an integer, got {req['steps']!r}"
            cap = max(1, int(self.scfg["max_steps"]))
            if not 1 <= steps <= cap:
                return None, (f"steps must be in [1, {cap}] "
                              f"(serve.max_steps), got {steps}")
            out["steps"] = steps
        if req.get("pattern") is not None \
                and not isinstance(req["pattern"], (str, dict)):
            return None, (f"pattern must be a lane name or an inline spec "
                          f"object, got {req['pattern']!r}")
        state = req.get("state")
        if state is not None:
            if not isinstance(state, dict):
                return None, "state must be an object of scalar overrides"
            try:
                out["state"] = {k: float(v) for k, v in state.items()
                                if v is not None}
            except (TypeError, ValueError):
                return None, f"state overrides must be numbers: {state!r}"
        return out, None

    def _entry(self, rid: str, req: dict, now: float,
               replayed: bool = False) -> dict:
        try:
            deadline_s = float(req.get("deadline_s")
                               or self.scfg["request_deadline_s"])
        except (TypeError, ValueError):
            # Replayed record from an older/hand-edited journal: serve it
            # under the default deadline rather than refuse to start.
            deadline_s = float(self.scfg["request_deadline_s"])
        try:
            steps = max(1, int(req.get("steps") or 1))
        except (TypeError, ValueError):
            steps = 1
        return {"id": rid, "req": req, "accepted_mono": now,
                "deadline_mono": now + deadline_s, "deadline_s": deadline_s,
                "retries": 0, "replayed": replayed, "last_failure": None,
                "lane": "default", "steps": steps}

    def accept(self, req: dict) -> tuple[int, dict]:
        """Admission control for one request.  Returns (http_status, body);
        202 = journaled (durable), 200 = idempotent replay of a known id,
        429 = backpressure (queue full / probe says no / spill capacity),
        503 = draining."""
        with self.lock:
            if self.draining:
                return 503, {"error": "draining", "retry_after_s": None}
            req, bad = self._normalize_request(req)
            if bad is not None:
                return 400, {"error": bad}
            # A traced CLIENT's X-Dragg-Parent rides in as a private key
            # (the HTTP handler injects it); popped before the journal's
            # durability point so the accepted record of record stays
            # canonical.  It is recorded as an INFORMATIONAL field on
            # serve.request — the request span parents on the daemon
            # root, keeping every in-stream tree rooted.
            client_parent = req.pop("_client_parent", None)
            rid = str(req.get("id") or uuid.uuid4().hex)
            known = self.results.get(rid)
            if known is not None:
                return 200, self._result_body(rid, known)
            if self.journal.is_terminal(rid):
                # Answered in a previous life / beyond the results-cache
                # window: the journal holds the answer of record — refuse
                # upfront rather than re-solve work it would refuse to
                # record.
                return 200, self._evicted_body(rid)
            if rid in self.pending or rid in self.assigned:
                return 202, {"id": rid, "status": "pending"}
            if self.mode is None and self.platform_req == "tpu" \
                    and not bool(self.scfg["degrade_to_cpu"]):
                # Strict-TPU service with a dead tunnel: admitting would
                # queue doomed work — push back with the probe cadence.
                retry = max(1.0, self.backoff_until - time.monotonic())
                telemetry.inc("serve.requests_rejected", 1)
                telemetry.emit("serve.reject", id=rid, reason="probe_down",
                               retry_after_s=round(retry, 1))
                return 429, {"error": "accelerator unavailable "
                                      "(probe-gated admission)",
                             "retry_after_s": round(retry, 1)}
            depth = len(self.pending) + len(self.assigned)
            if depth >= int(self.scfg["queue_max"]):
                # Backpressure BEFORE spill-lane resolution: a request the
                # queue refuses must never trigger a compile.
                retry = float(self.scfg["retry_after_s"])
                telemetry.inc("serve.requests_rejected", 1)
                telemetry.emit("serve.reject", id=rid, reason="queue_full",
                               retry_after_s=retry)
                return 429, {"error": "queue full",
                             "retry_after_s": retry}
            lane_name, err = self._resolve_lane(dict(req, id=rid))
            if err is not None:
                return err
            lane = self.lanes[lane_name]
            if not 0 <= req["home"] < lane.n_homes:
                return 400, {"error": f"home {req['home']} outside the "
                                      f"serving community "
                                      f"[0, {lane.n_homes}) of pattern "
                                      f"lane {lane_name!r}"}
            req = dict(req, id=rid, pattern=lane_name)
            self.journal.accepted(rid, req)       # durability point (fsync)
            entry = self._entry(rid, req, time.monotonic())
            entry["lane"] = lane_name
            self.pending[rid] = entry
            span = telemetry.trace.child_fields()
            if span:
                entry["span"] = span["span"]
                if client_parent:
                    span["client_parent"] = client_parent
            telemetry.emit("serve.request", id=rid,
                           timestep=req.get("t", 0), home=req["home"],
                           **span)
            telemetry.set_gauge("serve.queue_depth", depth + 1)
            body = {"id": rid, "status": "accepted"}
            if span:
                # The handler pops this into X-Dragg-Trace/X-Dragg-Span
                # response headers — the client's join point.
                body["_trace"] = {
                    "trace": telemetry.trace.current()["trace"],
                    "span": entry["span"]}
            return 202, body

    def _result_body(self, rid: str, rec: dict) -> dict:
        if rec.get("state") == journal_mod.DONE:
            return {"id": rid, "status": "done",
                    "response": rec.get("response")}
        return {"id": rid, "status": "failed", "reason": rec.get("reason")}

    def _evicted_body(self, rid: str) -> dict:
        # The verdict of record survives eviction: a terminally-FAILED
        # id must never be reported done just because its record left
        # the bounded cache.
        state = self.journal.terminal_state(rid) or journal_mod.DONE
        return {"id": rid, "status": state, "evicted": True,
                "note": "terminal previously; the record left the "
                        "results cache (the journal retains it)"}

    def result(self, rid: str) -> tuple[int, dict]:
        with self.lock:
            rec = self.results.get(rid)
            if rec is not None:
                return 200, self._result_body(rid, rec)
            if rid in self.pending or rid in self.assigned:
                return 200, {"id": rid, "status": "pending"}
            if self.journal.is_terminal(rid):
                return 200, self._evicted_body(rid)
            return 404, {"error": f"unknown request id {rid!r}"}

    # ----------------------------------------------------------- streaming
    def chunk_follower(self):
        """Incremental reader over the events.jsonl stream — the
        transport for ``/result?stream=1`` chunk lines.  The first poll
        reads a bounded 4 MB backlog (a chunk that scrolled past that
        window is delivered by the terminal record instead); every later
        poll costs O(new bytes), so a long stream on a busy daemon never
        re-parses the whole tail."""
        path = telemetry.events_path()
        if not path:
            return None
        return telemetry.EventFollower(path, tail_bytes=1 << 22)

    def stream_begin(self) -> bool:
        """Admit one ``/result?stream=1`` consumer under
        ``serve.max_streams``.  Every stream holds an HTTP server thread
        and its own events-tail follower for up to its whole budget, so
        streams are bounded like every other daemon resource (queue_max
        bounds requests, spill_patterns bounds lanes)."""
        with self.lock:
            if self._active_streams >= int(self.scfg["max_streams"]):
                return False
            self._active_streams += 1
            return True

    def stream_end(self) -> None:
        with self.lock:
            self._active_streams = max(0, self._active_streams - 1)

    def stream_budget_s(self, rid: str) -> float:
        """How long a streaming consumer may hold the connection: the
        request's own remaining deadline plus one batch service window
        (a completed answer is delivered even past the request
        deadline)."""
        with self.lock:
            entry = self.pending.get(rid) or self.assigned.get(rid)
            steps = entry["steps"] if entry else 1
            extra = float(self.scfg["batch_deadline_s"]) * max(1, steps)
            if entry is not None:
                return max(1.0, entry["deadline_mono"]
                           - time.monotonic()) + extra
            return extra

    def accepted_mono(self, rid: str) -> float | None:
        with self.lock:
            entry = self.pending.get(rid) or self.assigned.get(rid)
            return entry["accepted_mono"] if entry else None

    # ------------------------------------------------- platform / degrade
    def _apply_probe(self, report) -> None:
        """Fold one classified probe verdict into the serving mode.
        The probe itself ran OUTSIDE the lock (dispatch loop); only this
        fold runs under it."""
        self.log(f"probe: {'LIVE' if report.alive else report.kind} "
                 f"{report.detail}")
        failure = self._probe_failure
        self._probe_failure = None
        if report.alive:
            self.mode = "tpu"
            return
        if self.platform_req == "tpu" and not bool(self.scfg["degrade_to_cpu"]):
            self.mode = None  # stay unready; admission answers 429
            self.backoff_until = time.monotonic() + self._backoff_s()
            return
        self._degrade(failure or report.kind or "TUNNEL_DOWN")

    def _degrade(self, failure: str, batch: int | None = None) -> None:
        """Flip to degraded-CPU serving; journaled so a restarted daemon
        keeps reporting the transition's provenance."""
        if self.mode == "cpu":
            return
        self.mode = "cpu"
        self.transition = {"state": journal_mod.TRANSITION, "from": "tpu",
                           "to": "cpu", "failure": failure, "batch": batch}
        self.journal.transition("tpu", "cpu", failure, batch)
        telemetry.emit("degrade.transition", from_platform="tpu",
                       to_platform="cpu", failure=failure)
        self.log(f"DEGRADED to CPU serving (failure={failure})")

    def _provenance(self) -> dict | None:
        if self.transition is None:
            return None
        return {"from": self.transition.get("from"),
                "to": self.transition.get("to"),
                "failure": self.transition.get("failure")}

    # ------------------------------------------------------- dispatch loop
    def _tick(self) -> None:
        with self.lock:
            now = time.monotonic()
            self._expire_pending(now)
            for slot in self.slots:
                self._tick_slot(slot, now)
            for slot in self.slots:
                if (slot.alive() and slot.ready()
                        and slot.slot not in self.in_flight):
                    self._dispatch(slot, now)
            telemetry.set_gauge("serve.queue_depth",
                                len(self.pending) + len(self.assigned))
            probe_due = (self.mode is None and self.platform_req != "cpu"
                         and not self.draining and now >= self.backoff_until)
        if probe_due:
            # The probe can block up to probe_timeout_s (subprocess jax
            # backend init) — run it with the lock RELEASED so /healthz,
            # /result, and admission stay responsive while it decides.
            report = liveness.check_liveness(
                float(self.scfg["probe_timeout_s"]))
            with self.lock:
                if self.mode is None:
                    self._apply_probe(report)

    def _expire_pending(self, now: float) -> None:
        for rid in [r for r, e in self.pending.items()
                    if e["deadline_mono"] < now]:
            entry = self.pending.pop(rid)
            self._fail(entry, "request deadline expired before service")

    def _remember_result(self, rid: str, rec: dict) -> None:
        """Cache one terminal record, evicting oldest past the cap (the
        journal keeps the unbounded history; this is the /result and
        duplicate-POST lookup window)."""
        self.results[rid] = rec
        while len(self.results) > self._results_cap:
            self.results.pop(next(iter(self.results)))

    def _fail(self, entry: dict, reason: str) -> None:
        rid = entry["id"]
        if self.journal.failed(rid, reason):
            self._remember_result(rid, {"state": journal_mod.FAILED,
                                        "id": rid, "reason": reason})
            telemetry.inc("serve.requests_failed", 1)
            telemetry.emit("serve.failed", id=rid, reason=reason,
                           retries=entry["retries"])

    def _tick_slot(self, slot: WorkerSlot, now: float) -> None:
        if slot.proc is None or not slot.alive():
            if slot.proc is not None:
                self._handle_death(slot)
            self._maybe_launch(slot, now)
            return
        # Harvest answers first — also the late answers of a batch whose
        # deadline is about to land.
        self._process_outbox(slot)
        stall_s = float(self.scfg["worker_stall_s"]) or None
        fl = self.in_flight.get(slot.slot)
        age = slot.heartbeat_age()
        if fl is not None and fl["deadline_mono"] < now:
            stalled = bool(stall_s and age is not None and age > stall_s)
            self._kill_ctx[slot.slot] = {"timed_out": True,
                                         "stalled": stalled}
            slot.kill()
            self._handle_death(slot)
            return
        if stall_s and age is not None and age > stall_s:
            # Stopped making progress (hung compile / hung solve) — kill
            # before the abandoned work can wedge the tunnel (round 4).
            self._kill_ctx[slot.slot] = {"timed_out": False, "stalled": True}
            slot.kill()
            self._handle_death(slot)
            return
        report = slot.ready()
        if report is not None and slot.gen > getattr(slot, "_announced", 0):
            slot._announced = slot.gen
            compile_rep = report.get("compile") or {}
            telemetry.emit("serve.worker.ready", slot=slot.slot,
                           gen=slot.gen, platform=report.get("platform"),
                           warmup_s=report.get("warmup_s"),
                           cache=compile_rep.get("cache"))
            self.consec_failures = 0

    def _maybe_launch(self, slot: WorkerSlot, now: float) -> None:
        # mode None = a probe verdict is owed; the tick's unlocked probe
        # phase supplies it — launches park here until then.
        if self.draining or now < self.backoff_until or self.mode is None:
            return
        slot.launch(self.mode)

    def _backoff_s(self) -> float:
        base = float(self.scfg["backoff_s"])
        return min(_BACKOFF_CAP_S, base * (2 ** max(0, self.consec_failures - 1)))

    def _handle_death(self, slot: WorkerSlot) -> None:
        ctx = self._kill_ctx.pop(slot.slot, {})
        rc = slot.proc.poll() if slot.proc is not None else None
        if rc == 0 and self.draining and not ctx:
            # Clean drain exit (the worker saw STOP and finished) — not a
            # failure; harvest any final answers and retire the slot.
            self._process_outbox(slot)
            slot.proc = None
            return
        kind = slot.verdict(timed_out=ctx.get("timed_out", False),
                            stalled=ctx.get("stalled", False))
        telemetry.emit("failure." + kind,  # dragg: disable=DT007, kind from taxonomy.FAILURE_KINDS, each registered literally
                       source="serve", label=f"w{slot.slot} gen={slot.gen}",
                       rc=rc)
        telemetry.emit("serve.worker.exit", slot=slot.slot, gen=slot.gen,
                       rc=rc, failure=kind, ready=slot.ready() is not None)
        self.log(f"worker w{slot.slot} gen={slot.gen} died: {kind} (rc={rc})")
        # Late answers beat requeue: a response fsync'd before the death
        # is an answer of record, never work to redo.
        self._process_outbox(slot)
        slot.clear_inbox()
        fl = self.in_flight.pop(slot.slot, None)
        if fl:
            for rid in fl["ids"]:
                entry = self.assigned.pop(rid, None)
                if entry is None:
                    continue  # answered by the late-outbox harvest
                entry["retries"] += 1
                entry["last_failure"] = kind
                telemetry.inc("serve.request_retries", 1)
                if entry["retries"] > int(self.scfg["request_retries"]):
                    self._fail(entry,
                               f"retries exhausted (last failure: {kind})")
                else:
                    # Re-arm the queueing deadline: a steps=N batch
                    # legitimately runs batch_deadline_s·N past the
                    # request deadline (which governs QUEUED time only),
                    # so a worker death mid-service must not let
                    # _expire_pending kill the retry on the next tick —
                    # request_retries would be unreachable for exactly
                    # the long requests where a retry matters.
                    entry["deadline_mono"] = max(
                        entry["deadline_mono"],
                        time.monotonic()
                        + float(entry.get("deadline_s")
                                or self.scfg["request_deadline_s"]))
                    self.pending[entry["id"]] = entry
        slot.proc = None
        self.consec_failures += 1
        self.backoff_until = time.monotonic() + self._backoff_s()
        # Device-path failures on the TPU mode re-probe before relaunch
        # (a dead tunnel must degrade instead of relaunching into the
        # wedge) — but the probe blocks, so park the mode and let the
        # tick's unlocked probe phase deliver the verdict.
        if self.mode == "tpu":
            self.mode = None
            self._probe_failure = kind

    def _process_outbox(self, slot: WorkerSlot) -> None:
        for seq, path in spool.list_batches(slot.outbox()):
            payload = spool.read_json(path)
            if payload is None:
                continue
            responses = payload.get("responses") or {}
            platform = payload.get("platform", "?")
            if payload.get("elapsed_s") is not None:
                telemetry.observe("serve.batch_s",
                                  float(payload["elapsed_s"]))
            now = time.monotonic()
            for rid, resp in responses.items():
                entry = (self.assigned.pop(rid, None)
                         or self.pending.pop(rid, None))
                record = {"platform": platform, "batch": seq,
                          "slot": slot.slot, "gen": payload.get("gen"),
                          "pattern": slot.pattern,
                          "retries": entry["retries"] if entry else None,
                          **resp}
                degraded = self._provenance()
                if degraded is not None:
                    record["degraded"] = degraded
                if self.journal.done(rid, record):
                    self._remember_result(rid, {"state": journal_mod.DONE,
                                                "id": rid,
                                                "response": record})
                    telemetry.inc("serve.requests_done", 1)
                    # The terminal record re-uses the REQUEST span (same
                    # id): its t extent closes the span, so per-request
                    # wall time falls out of the assembled tree.
                    done_span = (telemetry.trace.span_fields(entry["span"])
                                 if entry is not None and entry.get("span")
                                 else {})
                    telemetry.emit("serve.done", id=rid, batch=seq,
                                   platform=platform,
                                   degraded=degraded is not None,
                                   **done_span)
                    if entry is not None:
                        telemetry.observe("serve.request_latency_s",
                                          now - entry["accepted_mono"])
                elif rid not in self.results:
                    # The journal refused: this id was answered in an
                    # earlier life and evicted from the cache since — a
                    # duplicate that slipped past admission.  Record a
                    # terminal marker (never the new answer: the first
                    # answer of record stands).
                    self._remember_result(
                        rid, {"state": journal_mod.FAILED, "id": rid,
                              "reason": "duplicate of an id already "
                                        "answered (evicted from the "
                                        "results cache)"})
            fl = self.in_flight.get(slot.slot)
            if fl is not None and fl["batch"] == seq:
                del self.in_flight[slot.slot]
            try:
                os.remove(path)
            except OSError:
                pass

    @staticmethod
    def _req_key(req: dict) -> tuple[int, float, int]:
        """(t, rp, home) with defensive coercion: admission normalizes
        these, but replayed records from older or hand-edited journals
        must degrade to defaults, never poison the dispatch loop."""
        def _num(v, cast, default):
            try:
                return cast(v if v is not None else default)
            except (TypeError, ValueError):
                return default
        return (_num(req.get("t"), int, 0), _num(req.get("rp"), float, 0.0),
                _num(req.get("home"), int, 0))

    def _coalesce(self, lane: PatternLane, now: float):
        """Fold this lane's queue into up to C request groups for one
        fleet batch.  One group = one (rp) at one community slot, at most
        one request per home and ``batch_max`` per group; every group in
        a batch shares (t, steps) — the compiled step takes one scalar
        timestep.  The batch waits inside ``serve.batch_window_ms`` for
        more groups (latency-aware coalescing) and dispatches EARLY the
        moment all C slots fill, on window expiry, or while draining.

        Returns (groups, t, steps, window_wait_s) or None (keep
        waiting / nothing dispatchable)."""
        anchor = None
        for e in self.pending.values():
            if e["lane"] == lane.name:
                anchor = e
                break
        if anchor is None:
            return None
        t, _rp, _home = self._req_key(anchor["req"])
        steps = anchor["steps"]
        C = lane.fleet_slots
        groups: dict[float, dict[int, dict]] = {}
        for e in self.pending.values():
            if e["lane"] != lane.name or e["steps"] != steps:
                continue
            rt, rrp, home = self._req_key(e["req"])
            if rt != t:
                continue
            g = groups.get(rrp)
            if g is None:
                if len(groups) >= C:
                    continue
                g = groups[rrp] = {}
            if home in g or len(g) >= lane.batch_max:
                continue
            g[home] = e
        if not groups:
            return None
        window_wait = now - anchor["accepted_mono"]
        window_s = float(self.scfg["batch_window_ms"]) / 1000.0
        if (len(groups) < C and window_wait < window_s
                and not self.draining):
            return None  # hold for more coalescible groups
        return list(groups.items()), t, steps, window_wait

    def _dispatch(self, slot: WorkerSlot, now: float) -> None:
        lane = self.lanes.get(slot.pattern or "default")
        if lane is None or not self.pending:
            return
        picked = self._coalesce(lane, now)
        if picked is None:
            return
        groups, t, steps, window_wait = picked
        self.batch_seq += 1
        seq = self.batch_seq
        ids: list[str] = []
        gpayload = []
        parent_span = None
        for cslot, (rp, by_home) in enumerate(groups):
            reqs = []
            for entry in by_home.values():
                rid = entry["id"]
                ids.append(rid)
                self.assigned[rid] = self.pending.pop(rid)
                reqs.append(entry["req"])
                if parent_span is None:
                    parent_span = entry.get("span")
            gpayload.append({"cslot": cslot, "rp": rp, "requests": reqs})
        batch = {"batch": seq, "t": t, "steps": steps, "groups": gpayload}
        # Batch span, parented on the first coalesced request's span; it
        # rides the inbox payload so the worker's serve.chunk records
        # parent on it (request -> batch -> chunk, one causal chain).
        # Absent entirely when tracing is off — the inbox payload stays
        # byte-identical to round 16.
        bspan = telemetry.trace.child_fields(parent=parent_span)
        if bspan:
            batch["span"] = bspan["span"]
        spool.atomic_write_json(
            os.path.join(slot.inbox(), spool.batch_name(seq)), batch)
        self.journal.assigned(ids, seq, slot.slot, slot.gen,
                              slot.platform or "?")
        self.in_flight[slot.slot] = {
            "batch": seq, "ids": ids, "t": t,
            "deadline_mono": now + float(self.scfg["batch_deadline_s"])
            * max(1, steps)}
        occupancy = len(gpayload) / max(1, lane.fleet_slots)
        telemetry.emit("serve.assign", batch=seq, slot=slot.slot,
                       gen=slot.gen, n=len(ids), groups=len(gpayload),
                       occupancy=round(occupancy, 4), timestep=t,
                       steps=steps, pattern=lane.name,
                       window_wait_s=round(window_wait, 4), **bspan)
        telemetry.observe("serve.batch_occupancy", occupancy)
        telemetry.observe("serve.coalesced_requests", float(len(ids)))
        telemetry.observe("serve.batch_window_wait_s", max(0.0, window_wait))

    # ------------------------------------------------------------- surface
    def stats(self) -> dict:
        with self.lock:
            ready = [s.slot for s in self.slots
                     if s.alive() and s.ready() is not None]
            return {
                "mode": self.mode, "draining": self.draining,
                "uptime_s": round(time.monotonic() - self.started_at, 1),
                "queue_depth": len(self.pending) + len(self.assigned),
                "pending": len(self.pending), "assigned": len(self.assigned),
                "results": len(self.results),
                "workers_ready": ready,
                "worker_gens": {s.slot: s.gen for s in self.slots},
                "patterns": {n: ln.describe()
                             for n, ln in self.lanes.items()},
                "degraded": self._provenance(),
                "batch_seq": self.batch_seq,
            }

    def ready_verdict(self) -> tuple[bool, str]:
        with self.lock:
            if self.draining:
                return False, "draining"
            if self.mode is None:
                return False, "platform unresolved (probe-gated)"
            if not any(s.alive() and s.ready() is not None
                       for s in self.slots):
                return False, "no warm worker"
            if (len(self.pending) + len(self.assigned)
                    >= int(self.scfg["queue_max"])):
                return False, "queue full"
            return True, "ok"

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Bind the HTTP surface and start the dispatch loop (threads)."""
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        http_t = threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.2),
            name="serve-http", daemon=True)
        disp_t = threading.Thread(target=self._loop, name="serve-dispatch",
                                  daemon=True)
        self._threads = [http_t, disp_t]
        for t in self._threads:
            t.start()
        self.log(f"serving on http://{self.host}:{self.port} "
                 f"(dir={self.serve_dir})")

    def _loop(self) -> None:
        tick_s = float(self.scfg["poll_s"])
        while not self.stop_event.is_set():
            try:
                self._tick()
            except Exception as e:  # the loop must survive anything
                self.log(f"tick error: {e!r}")
                telemetry.emit("serve.error", error=repr(e))
            self.sleep(tick_s)

    def drain(self, timeout_s: float | None = None) -> bool:
        """Stop accepting, let in-flight + queued work finish.  Returns
        True when the queue fully drained (False = timeout; the journal
        carries the leftovers for the next start)."""
        with self.lock:
            self.draining = True
        telemetry.emit("serve.drain", queue=len(self.pending)
                       + len(self.assigned))
        deadline = time.monotonic() + float(
            timeout_s if timeout_s is not None else self.scfg["drain_s"])
        while time.monotonic() < deadline:
            with self.lock:
                if not self.pending and not self.assigned:
                    break
            self.sleep(0.05)
        # STOP after the queue empties (or times out): workers exit
        # between batches; a mid-batch worker finishes first.
        with open(spool.stop_path(self.spool_dir), "w") as f:
            f.write("drain\n")
        stop_deadline = time.monotonic() + 10.0
        while time.monotonic() < stop_deadline:
            if not any(s.alive() for s in self.slots):
                break
            self.sleep(0.05)
        with self.lock:
            return not self.pending and not self.assigned

    def stop(self, drain: bool = True, timeout_s: float | None = None) -> bool:
        drained = self.drain(timeout_s) if drain else False
        with self.lock:
            self.draining = True
        self.stop_event.set()
        for slot in self.slots:
            slot.kill(grace_s=2.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        for t in self._threads:
            t.join(timeout=5.0)
        self.journal.close()
        for lane in self.lanes.values():
            if lane.cfg_path:
                try:
                    os.remove(lane.cfg_path)
                except OSError:
                    pass
        telemetry.write_snapshot()
        if self._owns_bus:
            # Sequential in-process daemons (the soak's scenarios) each
            # get their own stream; a bus this daemon merely joined
            # (supervised CLI, $DRAGG_TELEMETRY_DIR) stays open.
            telemetry.close_run()
        return drained


# ------------------------------------------------------------------ HTTP
def _make_handler(daemon: ServeDaemon):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass  # the daemon's own log/telemetry carry the story

        def _send(self, code: int, body: dict,
                  retry_after: float | None = None) -> None:
            # Trace join point: a traced accept tucks {"trace","span"}
            # under "_trace"; it leaves the body and answers as the
            # X-Dragg-Trace/X-Dragg-Span response headers.
            tr = body.pop("_trace", None) if isinstance(body, dict) else None
            data = json.dumps(body, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            if tr:
                self.send_header("X-Dragg-Trace", str(tr["trace"]))
                self.send_header("X-Dragg-Span", str(tr["span"]))
            if retry_after is not None:
                self.send_header("Retry-After",
                                 str(max(1, int(round(retry_after)))))
            self.end_headers()
            self.wfile.write(data)

        def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            parsed = urllib.parse.urlparse(self.path)
            if parsed.path != "/solve":
                self._send(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, OSError) as e:
                self._send(400, {"error": f"bad request body: {e!r}"})
                return
            parent_hdr = self.headers.get("X-Dragg-Parent")
            if parent_hdr:
                # Client-side trace join (tools/serve_load.py): recorded
                # as an informational field on serve.request, never as
                # the span parent (in-stream trees stay rooted).
                if isinstance(payload, dict):
                    payload.setdefault("_client_parent", parent_hdr)
                elif isinstance(payload, list):
                    for r in payload:
                        if isinstance(r, dict):
                            r.setdefault("_client_parent", parent_hdr)
            if isinstance(payload, list):
                replies = [daemon.accept(r) for r in payload]
                for _, b in replies:
                    if isinstance(b, dict):
                        b.pop("_trace", None)
                worst = max((code for code, _ in replies), default=200)
                self._send(worst if worst >= 400 else 202,
                           {"results": [b for _, b in replies]},
                           retry_after=next(
                               (b.get("retry_after_s") for c, b in replies
                                if c == 429), None))
                return
            code, body = daemon.accept(payload)
            self._send(code, body, retry_after=body.get("retry_after_s")
                       if code in (429, 503) else None)

        def _stream_result(self, rid: str) -> None:
            """NDJSON streaming: one line per serve.chunk event the
            workers emitted for this request (the events.jsonl tail is
            the transport), then the terminal record; connection close
            delimits the stream (no Content-Length).  Admission is
            bounded by ``serve.max_streams`` — each stream pins an HTTP
            thread + follower for up to its whole budget, and an
            unbounded fan-in would starve the request path's threads."""
            code, first = daemon.result(rid)
            if code == 404:
                self._send(404, first)
                return
            if not daemon.stream_begin():
                retry = float(daemon.scfg["retry_after_s"])
                telemetry.inc("serve.streams_rejected", 1)
                telemetry.emit("serve.reject", id=rid,
                               reason="stream_capacity",
                               retry_after_s=retry)
                self._send(429, {"error": "concurrent stream capacity "
                                          "exhausted (serve.max_streams)",
                                 "retry_after_s": retry},
                           retry_after=retry)
                return
            try:
                self._stream_body(rid)
            finally:
                daemon.stream_end()

        def _stream_body(self, rid: str) -> None:
            t0 = time.monotonic()
            accepted = daemon.accepted_mono(rid)
            deadline = t0 + daemon.stream_budget_s(rid)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Cache-Control", "no-store")
            self.end_headers()
            sent = {"chunks": 0, "last_step": -1, "pid": None}
            follower = daemon.chunk_follower()
            telemetry.inc("serve.streams", 1)

            def write_line(obj: dict) -> None:
                self.wfile.write(
                    (json.dumps(obj, default=str) + "\n").encode())
                self.wfile.flush()

            def poll_chunks() -> list[dict]:
                if follower is None:
                    return []
                # contains= pre-filters raw lines before JSON parsing:
                # the events stream carries EVERY telemetry event on a
                # busy daemon, and each stream has its own follower.
                # File order IS emission order (each attempt emits steps
                # ascending) — sorting by step would interleave a dead
                # attempt's chunks with its retry's.
                return [r for r in follower.poll(contains=b'"serve.chunk"')
                        if r.get("event") == "serve.chunk"
                        and r.get("id") == rid]

            def push_chunks() -> None:
                for ev in poll_chunks():
                    step = int(ev.get("step") or 0)
                    if ev.get("pid") != sent["pid"]:
                        # A new emitting process = a retry after a worker
                        # death (possibly on a degraded platform).  The
                        # chunk sequence RESTARTS so the stream stays
                        # single-provenance with the terminal answer of
                        # record — consumers keep the LAST occurrence of
                        # each step.
                        sent["pid"] = ev.get("pid")
                        sent["last_step"] = -1
                    if step <= sent["last_step"]:
                        continue
                    line = {k: v for k, v in ev.items()
                            if k not in ("event", "mono", "pid", "seq")}
                    line["kind"] = "chunk"
                    write_line(line)
                    if sent["chunks"] == 0 and accepted is not None:
                        telemetry.observe("serve.first_chunk_latency_s",
                                          time.monotonic() - accepted)
                    sent["chunks"] += 1
                    sent["last_step"] = step

            terminal = None
            try:
                while True:
                    push_chunks()
                    code, body = daemon.result(rid)
                    if body.get("status") in ("done", "failed"):
                        push_chunks()  # late chunks beat the final line
                        terminal = dict(body, kind="result")
                        write_line(terminal)
                        break
                    if time.monotonic() > deadline:
                        write_line({"id": rid, "kind": "result",
                                    "status": "timeout",
                                    "note": "stream budget exhausted; "
                                            "poll /result"})
                        break
                    time.sleep(max(0.02, float(daemon.scfg["poll_s"])))
            except OSError:
                pass  # consumer went away mid-stream; nothing to unwind
            telemetry.emit("serve.stream", id=rid, chunks=sent["chunks"],
                           terminal=(terminal or {}).get("status"),
                           elapsed_s=round(time.monotonic() - t0, 3))

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            parsed = urllib.parse.urlparse(self.path)
            q = urllib.parse.parse_qs(parsed.query)
            if parsed.path == "/result":
                rid = (q.get("id") or [""])[0]
                stream = (q.get("stream") or ["0"])[0]
                if stream not in ("", "0", "false", "no"):
                    self._stream_result(rid)
                    return
                code, body = daemon.result(rid)
                self._send(code, body)
            elif parsed.path == "/healthz":
                self._send(200, {"ok": True, "pid": os.getpid(),
                                 **daemon.stats()})
            elif parsed.path == "/readyz":
                ready, reason = daemon.ready_verdict()
                self._send(200 if ready else 503,
                           {"ready": ready, "reason": reason})
            elif parsed.path == "/metrics.json":
                self._send(200, {"serve": daemon.stats(),
                                 **telemetry.snapshot()})
            elif parsed.path == "/events.jsonl":
                limit = int((q.get("limit") or ["50"])[0])
                path = telemetry.events_path()
                events = (telemetry.tail_events(path, limit=limit)
                          if path else [])
                self._send(200, {"events": events})
            elif parsed.path in ("/rollup.json", "/metrics"):
                run_dir = telemetry.run_dir()
                if not run_dir:
                    self._send(404, {"error": "no telemetry run dir"})
                    return
                roll = telemetry.rollup.fold_rollup(run_dir)
                if parsed.path == "/rollup.json":
                    self._send(200, roll)
                    return
                text = telemetry.rollup.prometheus_text(roll).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            else:
                self._send(404, {"error": "not found"})

    return Handler


def run_serve(config: dict, serve_dir: str, *, platform: str = "auto",
              host: str | None = None, port: int | None = None,
              stub: bool = False, log=None) -> int:
    """Blocking CLI entry (``python -m dragg_tpu serve``): run until
    SIGTERM/SIGINT, then drain gracefully."""
    import signal

    daemon = ServeDaemon(config, serve_dir, platform=platform, host=host,
                         port=port, stub=stub, log=log)
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    daemon.start()
    while not stop.is_set():
        stop.wait(0.5)
    drained = daemon.stop(drain=True)
    return 0 if drained else 1
