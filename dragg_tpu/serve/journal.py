"""Crash-safe append-only request journal — stdlib only, parent-side.

The serving daemon's durability contract (ISSUE 7): a request the daemon
acknowledged (HTTP 202) must survive ANY process death — daemon crash,
worker kill -9, power loss mid-write — and be answered exactly once
after restart.  The reference kept this state in Redis hashes
(dragg/aggregator.py:723-724, one pathos+Redis aggregator whose queue
died with its process); here it is one fsync'd JSONL file, because the
journal's readers are the same forensic tools that already speak the
telemetry stream's line-JSON dialect.

Record grammar (one JSON object per line, ``state`` discriminates):

    {"state": "accepted",   "id": ..., "req": {...}}        durability point
    {"state": "assigned",   "ids": [...], "batch": n,
                            "slot": s, "gen": g, "platform": p}
    {"state": "done",       "id": ..., "response": {...}}   terminal
    {"state": "failed",     "id": ..., "reason": ...}       terminal
    {"state": "transition", "from": ..., "to": ...,
                            "failure": ..., "batch": n}     degradation mark
    {"state": "pattern",    "name": ..., "signature": ...,
                            "spec": {...}, "source": ...}   lane provenance

Crash consistency is by construction, not recovery code:

* every append is ``write + flush + fsync`` of ONE complete line before
  the caller proceeds — an acknowledged request is on disk;
* a torn final line (power loss mid-append) parses as garbage and is
  DROPPED by :func:`replay`; since the write that tore never returned to
  its caller, nothing observable is lost;
* replay folds states per id: a request whose newest record is
  ``accepted``/``assigned`` is *pending* (must be re-served); ``done``/
  ``failed`` are terminal and idempotent — a second ``done`` for an id
  is refused at append time, which is the "no request answered twice"
  half of the soak invariant (tools/serve_soak.py).

tests/test_serve.py's torn-write property test truncates a journal at
every byte boundary and asserts replay stays consistent.
"""

from __future__ import annotations

import json
import os
from typing import NamedTuple

ACCEPTED = "accepted"
ASSIGNED = "assigned"
DONE = "done"
FAILED = "failed"
TRANSITION = "transition"
PATTERN = "pattern"

TERMINAL = (DONE, FAILED)


class ReplayState(NamedTuple):
    """The fold of one journal file.

    ``pending``  — id -> accepted record (newest state not terminal;
                   re-serve these after a restart, in acceptance order);
    ``terminal`` — id -> the done/failed record (answer duplicates and
                   ``GET /result`` from here without re-solving);
    ``transition`` — the newest platform-transition record, if any (a
                   restarted daemon keeps reporting degradation
                   provenance for requests accepted before the restart);
    ``dropped_lines`` — unparseable lines skipped (a torn tail is 0 or 1;
                   more means outside interference — surfaced, not fatal);
    ``patterns`` — lane name -> newest pattern-provenance record (a
                   restarted daemon rebuilds the compile-on-demand lanes
                   its replayed pending requests were routed to —
                   serve/patterns.py).
    """

    pending: dict
    terminal: dict
    transition: dict | None
    dropped_lines: int
    patterns: dict


class Journal:
    """Append side.  One instance owns the file handle; every append is
    fsync'd before returning (the whole point — see module docstring)."""

    def __init__(self, path: str, fsync: bool = True,
                 terminal_ids: set | None = None):
        self.path = path
        self._fsync = fsync
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # Terminal-state idempotency must hold across daemon restarts (a
        # replayed "done" id refuses a second done even though this
        # process never wrote the first).  Kept as id -> state so the
        # verdict survives results-cache eviction (a FAILED id must
        # never be reported done).  Callers that already folded the file
        # (the daemon replays right before opening the append side) pass
        # the terminal mapping in instead of paying a second scan;
        # legacy set-shaped input maps to DONE-unknown.
        if terminal_ids is None:
            rep = replay(path)
            self._terminal: dict = {rid: rec.get("state", DONE)
                                    for rid, rec in rep.terminal.items()}
        elif isinstance(terminal_ids, dict):
            self._terminal = {rid: (rec.get("state", DONE)
                                    if isinstance(rec, dict) else str(rec))
                              for rid, rec in terminal_ids.items()}
        else:
            self._terminal = {rid: DONE for rid in terminal_ids}
        self._fh = open(path, "a", encoding="utf-8")

    # ------------------------------------------------------------- plumbing
    def _append(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec, separators=(",", ":"),
                                  default=str) + "\n")
        self._fh.flush()
        if self._fsync:
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass

    def is_terminal(self, req_id: str) -> bool:
        """Whether this id already has an answer of record — the FULL
        journal history, not a bounded cache.  Admission consults this so
        a duplicate of a long-evicted id is refused upfront instead of
        burning a solve the journal would refuse to record."""
        return req_id in self._terminal

    def terminal_state(self, req_id: str) -> str | None:
        """``done`` / ``failed`` / None — the verdict of record, kept so
        an id evicted from the daemon's results cache still reports WHAT
        happened, not just THAT it happened."""
        return self._terminal.get(req_id)

    # ----------------------------------------------------------- lifecycle
    def accepted(self, req_id: str, req: dict) -> None:
        self._append({"state": ACCEPTED, "id": req_id, "req": req})

    def assigned(self, ids: list[str], batch: int, slot: int, gen: int,
                 platform: str) -> None:
        self._append({"state": ASSIGNED, "ids": list(ids), "batch": batch,
                      "slot": slot, "gen": gen, "platform": platform})

    def done(self, req_id: str, response: dict) -> bool:
        """Record the answer.  Returns False (and writes nothing) when the
        id is already terminal — the caller must not deliver twice."""
        if req_id in self._terminal:
            return False
        self._terminal[req_id] = DONE
        self._append({"state": DONE, "id": req_id, "response": response})
        return True

    def failed(self, req_id: str, reason: str) -> bool:
        if req_id in self._terminal:
            return False
        self._terminal[req_id] = FAILED
        self._append({"state": FAILED, "id": req_id, "reason": reason})
        return True

    def transition(self, from_platform: str, to_platform: str,
                   failure: str | None, batch: int | None) -> None:
        self._append({"state": TRANSITION, "from": from_platform,
                      "to": to_platform, "failure": failure, "batch": batch})

    def pattern(self, name: str, signature: str, spec: dict,
                source: str) -> None:
        """Journal one pattern lane's generation provenance BEFORE any
        request is accepted into it — replay must be able to rebuild the
        lane a replayed pending request names (serve/patterns.py)."""
        self._append({"state": PATTERN, "name": name, "signature": signature,
                      "spec": spec, "source": source})


def replay(path: str) -> ReplayState:
    """Fold a journal file into :class:`ReplayState` (module docstring).
    Never raises on file content: torn/garbage lines are counted and
    skipped, unknown states ignored (forward compatibility)."""
    pending: dict = {}
    terminal: dict = {}
    transition: dict | None = None
    patterns: dict = {}
    dropped = 0
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.read().split("\n")
    except OSError:
        return ReplayState({}, {}, None, 0, {})
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            dropped += 1
            continue
        if not isinstance(rec, dict):
            dropped += 1
            continue
        state = rec.get("state")
        if state == ACCEPTED and "id" in rec:
            rid = rec["id"]
            if rid not in terminal and rid not in pending:
                pending[rid] = rec
        elif state in TERMINAL and "id" in rec:
            rid = rec["id"]
            pending.pop(rid, None)
            # First terminal record wins: a duplicate done (which Journal
            # refuses to write, but a merged/hand-edited file could carry)
            # must not change the answer of record.
            terminal.setdefault(rid, rec)
        elif state == TRANSITION:
            transition = rec
        elif state == PATTERN and "name" in rec:
            patterns[rec["name"]] = rec  # newest record wins
        elif state == ASSIGNED:
            pass  # assignment is not a durability state: accepted covers it
    return ReplayState(pending, terminal, transition, dropped, patterns)
