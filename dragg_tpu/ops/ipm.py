"""Batched primal-dual interior-point solver for the per-home MPC QPs.

A Mehrotra predictor-corrector method for

    minimize    qᵀx + (reg/2)‖x‖²
    subject to  A x = b,   l ≤ x ≤ u        (bounds may be ±inf)

run in lockstep over the home batch.  The Newton step's reduced system is
``A Θ⁻¹ Aᵀ dy = r`` with the iteration-varying diagonal
``Θ = reg + z_l/s_l + z_u/s_u`` — structurally identical to the ADMM
x-update's Schur complement, so the banded RCM factorization
(dragg_tpu/ops/banded.py, bandwidth ~4) factors it in O(B·m·bw²) per
iteration.  Each iteration: one band Cholesky + three band solves.

Why this exists (docs/perf_notes.md): splitting methods need ~450
iterations per warm MPC step at 1e-4 tolerance on these LP-like problems;
the IPM needs ~25 cold — the iteration count, not per-iteration cost, is
the TPU bottleneck.  This replaces the iteration count rather than
shaving the iteration.

Failure semantics match the ADMM path: homes whose final residuals miss
tolerance come back ``solved=False`` (primal-infeasible homes diverge in
μ and land there), and the engine routes them to the fallback controller
(dragg/mpc_calc.py:450-454 parity).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dragg_tpu.ops.admm import (
    ADMMSolution,
    _pad_gather,
    _schur_structure_for,
    ruiz_equilibrate_sparse,
)
from dragg_tpu.ops import pallas_band
from dragg_tpu.ops.banded import plan_for
from dragg_tpu.ops.qp import SparsePattern, schur_contrib

_BIG = 1e20


@partial(jax.jit, static_argnames=("pat", "iters", "tail_frac", "tail_iters",
                                   "ruiz_iters", "band_kernel", "mesh",
                                   "mesh_axis"))
def ipm_solve_qp(
    pat: SparsePattern,
    vals: jnp.ndarray,      # (B, nnz) A values
    b_eq: jnp.ndarray,      # (B, m)
    l_box: jnp.ndarray,     # (B, n)
    u_box: jnp.ndarray,     # (B, n)
    q: jnp.ndarray,         # (B, n)
    *,
    reg: float = 1e-3,
    iters: int = 30,
    tail_frac: float = 0.0,
    tail_iters: int = 0,
    # Defaults match the SHIPPED engine tolerance (tpu.ipm_eps = 2e-4 —
    # measured: half the iterations of 1e-4 at identical objective gap,
    # docs/perf_notes.md round 3), so the no-kwargs parity tests exercise
    # exactly what production runs.
    eps_abs: float = 2e-4,
    eps_rel: float = 2e-4,
    ruiz_iters: int = 10,
    band_kernel: str = "xla",
    mesh=None,
    mesh_axis: str = "homes",
    x0: jnp.ndarray | None = None,
    warm_mu: float = 1e-2,
    freeze_zmax: float = 300.0,
) -> ADMMSolution:
    """Solve the batch; returns the ADMM-compatible solution record (y_box
    carries z_u − z_l; rho is 1s — kept for interface parity)."""
    B = vals.shape[0]
    m, n = pat.m, pat.n
    dtype = vals.dtype

    schur = _schur_structure_for(pat)
    if schur is None:
        # The lru-cached helper returns None when its density HEURISTIC
        # says dense S formation is cheaper — tuned for the big dense test
        # patterns, but small type-bucketed MPC patterns (base homes at
        # H ≤ 2) can trip it while still being perfectly banded.  The IPM
        # REQUIRES the triple lists, so build them directly; genuinely
        # dense patterns still die in plan_for below (bandwidth cap).
        from dragg_tpu.ops.qp import build_schur_structure

        schur = build_schur_structure(pat)
    plan = plan_for(schur, m)
    if plan is None:
        raise ValueError("ipm_solve_qp needs a banded Schur pattern")
    bw = plan.bw
    perm_ix = jnp.asarray(plan.perm)
    invp_ix = jnp.asarray(plan.inv)

    rows = jnp.asarray(pat.rows)
    cols = jnp.asarray(pat.cols)
    row_cols = jnp.asarray(pat.row_cols)
    row_src = jnp.asarray(pat.row_src)
    col_rows = jnp.asarray(pat.col_rows)
    col_src = jnp.asarray(pat.col_src)

    # --- Fixed-variable elimination.  A barrier method needs a strict
    # interior, and the MPC boxes contain per-home FIXED variables (the
    # seasonal gate sets cool or heat bounds to [0, 0] —
    # dragg_tpu/engine.py's cool_cap/heat_cap).  Substitute them into the
    # equalities (b ← b − A·x_fix, zero their columns per home), free their
    # bounds, and restore the pinned values on exit.
    both_fin = jnp.isfinite(l_box) & jnp.isfinite(u_box)
    width = u_box - l_box
    fixed = both_fin & (width >= 0) & (width <= 1e-9 * (1.0 + jnp.abs(l_box)))
    # An inverted box (u < l) is primal-infeasible by construction — it must
    # NOT be treated as fixed (pinning to l would hide the u-violation from
    # the final box check); forcing it unsolved matches the ADMM
    # certificate's behavior.
    inverted = jnp.any(both_fin & (width < 0), axis=1)
    fixval = jnp.where(fixed, l_box, 0.0)

    def mv_raw(x):
        vpr = _pad_gather(vals, row_src)
        return jnp.sum(vpr * x[:, row_cols], axis=2)

    b_eq = b_eq - mv_raw(fixval)
    vals = jnp.where(fixed[:, cols], 0.0, vals)
    q = jnp.where(fixed, 0.0, q)
    l_box = jnp.where(fixed, -jnp.inf, l_box)
    u_box = jnp.where(fixed, jnp.inf, u_box)

    # Ruiz + cost equilibration (shared with the ADMM path).
    d, e_eq, e_box, c = ruiz_equilibrate_sparse(pat, vals, q, iters=ruiz_iters)
    vals_s = e_eq[:, rows] * vals * d[:, cols]
    vp_r = _pad_gather(vals_s, row_src)
    vp_c = _pad_gather(vals_s, col_src)
    qs = c * d * q
    bs = e_eq * b_eq
    # Bounds in the scaled variable x̂ = x/d.
    ls = jnp.where(jnp.isfinite(l_box), l_box / d, -_BIG)
    us = jnp.where(jnp.isfinite(u_box), u_box / d, _BIG)
    reg_s = c * d * d * reg  # scaled proximal diagonal (per entry)

    fin_l = jnp.isfinite(l_box)
    fin_u = jnp.isfinite(u_box)

    def mv(x):
        return jnp.sum(vp_r * x[:, row_cols], axis=2)

    def mvt(y):
        return jnp.sum(vp_c * y[:, col_rows], axis=2)

    # --- Starting point: mid-box primal, unit slacks/duals — or, when a
    # warm start is given (the engine's receding-horizon shift of the
    # previous step's plan), the warm primal pushed a safe distance into
    # the strict interior with μ-scaled duals.  Classic IPM warm-start
    # jamming is avoided by the interior margin (min 1 % of the box width)
    # and by NOT warm-starting the duals at their near-complementary
    # values: z = warm_mu/s keeps the first barrier steps well-centered.
    if x0 is not None:
        xw = jnp.where(fixed, 0.0, x0 / d)  # scaled; eliminated vars at 0
        width = jnp.where(fin_l & fin_u, us - ls, 2.0)
        margin = jnp.maximum(0.01 * width, 1e-3)
        x = jnp.clip(xw,
                     jnp.where(fin_l, ls + margin, -_BIG),
                     jnp.where(fin_u, us - margin, _BIG))
        # Floor the slacks: a box narrower than 2×margin makes the clip
        # bounds cross (lower > upper), so x − ls can come out negative —
        # a negative slack flips the barrier signs and the ratio test.
        # The r_sl/r_su residuals absorb the resulting x/s inconsistency.
        s_l = jnp.where(fin_l, jnp.maximum(x - ls, 1e-4), 1.0)
        s_u = jnp.where(fin_u, jnp.maximum(us - x, 1e-4), 1.0)
        z_l = jnp.where(fin_l, warm_mu / jnp.maximum(s_l, 1e-3), 0.0)
        z_u = jnp.where(fin_u, warm_mu / jnp.maximum(s_u, 1e-3), 0.0)
    else:
        x = jnp.where(fin_l & fin_u, 0.5 * (ls + us),
                      jnp.where(fin_l, ls + 1.0, jnp.where(fin_u, us - 1.0, 0.0)))
        s_l = jnp.where(fin_l, jnp.maximum(x - ls, 1.0), 1.0)
        s_u = jnp.where(fin_u, jnp.maximum(us - x, 1.0), 1.0)
        z_l = jnp.where(fin_l, jnp.ones_like(x), 0.0)
        z_u = jnp.where(fin_u, jnp.ones_like(x), 0.0)
    y = jnp.zeros((B, m), dtype)

    n_act = jnp.maximum(jnp.sum(fin_l, axis=1) + jnp.sum(fin_u, axis=1), 1)

    # Shared pallas/xla dispatch (ops/pallas_band.make_band_ops): pallas =
    # transposed (m, bw+1, B) storage + one fused kernel per refined solve,
    # xla = (B, m, bw+1) scans.  Same recurrences either way.
    (scatter_fn, _chol_fn, band_solve_fn, add_diag_fn,
     factor_solve_fn) = pallas_band.make_band_ops(
        plan, band_kernel, mesh=mesh, mesh_axis=mesh_axis)

    # The Mehrotra loop is built by a factory over the per-home data so it
    # runs identically on the full batch (phase 1) and on a gathered
    # straggler sub-batch (tail-compaction phase 2, see below).
    return _run_phases(
        B, m, dtype, iters, tail_frac, tail_iters, mesh,
        eps_abs, eps_rel,
        (vals_s, vp_r, vp_c, qs, bs, ls, us, reg_s, fin_l, fin_u, n_act, c * d),
        (x, y, s_l, s_u, z_l, z_u),
        dict(row_cols=row_cols, col_rows=col_rows, perm_ix=perm_ix,
             invp_ix=invp_ix, schur=schur,
             scatter_fn=scatter_fn,
             band_solve_fn=band_solve_fn, add_diag_fn=add_diag_fn,
             factor_solve_fn=factor_solve_fn,
             plan=plan, band_kernel=band_kernel, mesh_axis=mesh_axis,
             freeze_zmax=freeze_zmax),
        # final-residual extras (full-batch):
        dict(e_eq=e_eq, e_box=e_box, c=c, d=d, l_box=l_box, u_box=u_box,
             fixed=fixed, fixval=fixval, inverted=inverted),
    )


def _make_loop(data, shared, eps_abs, eps_rel):
    """(body, converged) closures over one per-home data tuple."""
    (vals_s, vp_r, vp_c, qs, bs, ls, us, reg_s, fin_l, fin_u, n_act, cd) = data
    row_cols, col_rows = shared["row_cols"], shared["col_rows"]
    perm_ix, invp_ix = shared["perm_ix"], shared["invp_ix"]
    schur = shared["schur"]
    scatter_fn = shared["scatter_fn"]
    band_solve_fn, add_diag_fn = shared["band_solve_fn"], shared["add_diag_fn"]
    factor_solve_fn = shared["factor_solve_fn"]

    def mv(x):
        return jnp.sum(vp_r * x[:, row_cols], axis=2)

    def mvt(y):
        return jnp.sum(vp_c * y[:, col_rows], axis=2)

    def solve_kkt(Lb, Sb, theta_inv, r1, r2, refine=1):
        """One reduced-KKT solve: dy from the band factor (``refine``
        refinement passes against the band S), dx by back-substitution.
        [Θ Âᵀ; Â 0][dx; dy] = [r1; r2]."""
        rhs = mv(theta_inv * r1) - r2
        dy = band_solve_fn(Lb, Sb, rhs[:, perm_ix], refine)[:, invp_ix]
        dx = theta_inv * (r1 - mvt(dy))
        return dx, dy

    def factor_solve_kkt(Sb, theta_inv, r1, r2):
        """solve_kkt with the band factor computed IN the same call —
        factor + first solve run as one fused kernel on the pallas path.
        Same rhs construction and back-substitution as solve_kkt (keep the
        two in lockstep); returns (Lb, dx, dy) so later solves against the
        same factor use solve_kkt."""
        rhs = mv(theta_inv * r1) - r2
        Lb, dy_p = factor_solve_fn(Sb, rhs[:, perm_ix], 0)
        dy = dy_p[:, invp_ix]
        dx = theta_inv * (r1 - mvt(dy))
        return Lb, dx, dy

    def residual_vecs(x, y, z_l, z_u):
        """The two gather-matvec residual vectors, computed once and
        shared by the freeze check and the Newton-step construction.
        Measured traffic-NEUTRAL (6.25 → 6.24 GB/step at 10k×H=24 — XLA
        already CSE'd the duplicated expressions across the closure
        boundary); kept because one definition replaces two copies that
        previously had to be maintained in lockstep, and CSE across
        backends is an optimization, not a guarantee."""
        r_dual = -(reg_s * x + qs + mvt(y) - z_l + z_u)     # stationarity
        r_prim = bs - mv(x)                                 # equality
        return r_dual, r_prim

    def converged_from(r_dual, r_prim, x, s_l, s_u, z_l, z_u):
        """Freeze verdict from precomputed residual vectors; |r| of the
        negated forms is bitwise identical to the pre-sharing direct
        expressions, so outcomes are unchanged."""
        rp = jnp.max(jnp.abs(r_prim), axis=1)
        rd = jnp.max(jnp.abs(r_dual) / cd, axis=1)
        gap = (jnp.sum(s_l * z_l * fin_l, axis=1)
               + jnp.sum(s_u * z_u * fin_u, axis=1)) / n_act
        gap_u = gap / jnp.maximum(jnp.abs(jnp.sum(qs * x, axis=1)), 1.0)
        ok = (rp <= eps_abs) & (rd <= 10 * eps_abs) \
            & (gap_u <= jnp.maximum(eps_rel, 1e-7))
        zmax = jnp.maximum(jnp.max(z_l * fin_l, axis=1),
                           jnp.max(z_u * fin_u, axis=1))
        diverged = (rp > 100 * jnp.maximum(eps_abs, 1e-6)) \
            & (zmax > shared["freeze_zmax"])
        return ok | diverged, rp + rd + gap_u

    def converged(x, y, s_l, s_u, z_l, z_u):
        """Per-home convergence in the scaled space (loop-internal freeze
        criterion; the authoritative check runs once at the end) plus a
        residual score used to rank stragglers for tail compaction.

        Divergence freeze: a primal-INFEASIBLE home can never reach
        rp ≤ eps — its box duals grow without bound while rp stalls
        (measured: rp stuck at ~5-12 with duals 5e3→5e4 while feasible
        homes sit at rp ≤ 5e-3, duals O(1) — docs/perf_notes.md).  Such a
        home previously burned the full iteration cap EVERY sim step and
        blocked the all-frozen early exit for the whole batch.  Freezing
        it changes nothing about its outcome (it fails the authoritative
        final residual check and routes to the fallback controller either
        way) but releases the batch.  Both conditions must hold, so a
        merely-slow feasible home (small duals) or a cold start (large
        rp, unit duals) cannot trip it.  Default threshold 300: feasible
        homes measure O(1) duals in the scaled space (~2.5 orders of
        margin).  Threshold history, all outcome-identical: 1e4->1e3 cut
        hard-chunk iterations 21-39 -> 9-16 (round 3); 1e3->300 cut
        hard-DAY iterations 15.7/19.7 -> 10.9/13.2 with BIT-identical
        solved flags / cost / aggregate load over 512 homes x 3 days
        (round 4, perf_notes).  The margin claim is CPU-measured;
        ``tpu.ipm_freeze_zmax`` exposes the threshold so on-chip regimes
        can re-tune it without a code change (ADVICE round 3)."""
        r_dual, r_prim = residual_vecs(x, y, z_l, z_u)
        return converged_from(r_dual, r_prim, x, s_l, s_u, z_l, z_u)

    def body(carry):
        i, _, x, y, s_l, s_u, z_l, z_u, cit = carry
        # Residuals FIRST (factor-independent), shared by the freeze check
        # and the Newton-step construction — one pair of gather matvecs
        # per iteration instead of two.
        r_dual, r_prim = residual_vecs(x, y, z_l, z_u)
        # Lockstep freeze: once a home converges it stops iterating — letting
        # it keep driving mu toward 0 degenerates Theta (z/s spans ~1e12)
        # and NaNs the f32 band factor while slower homes still work.
        frozen, _ = converged_from(r_dual, r_prim, x, s_l, s_u, z_l, z_u)
        theta = reg_s + jnp.where(fin_l, z_l / s_l, 0.0) + jnp.where(fin_u, z_u / s_u, 0.0)
        # f32 conditioning: cap the barrier diagonal (bounds cond(S) so the
        # band Cholesky stays meaningful at ~7 decimal digits) and Tikhonov
        # the Schur diagonal; the refined CORRECTOR solve recovers accuracy
        # for the step direction (the predictor runs unrefined — it only
        # steers sigma).
        theta = jnp.clip(theta, reg_s, 1e6)
        theta = jnp.where(frozen[:, None], 1.0, theta)  # benign factor input
        theta_inv = 1.0 / theta
        contrib = schur_contrib(schur, vals_s, theta_inv)
        Sb = add_diag_fn(scatter_fn(contrib), 1e-6)  # Tikhonov the diagonal

        r_sl = jnp.where(fin_l, x - ls - s_l, 0.0)
        r_su = jnp.where(fin_u, us - x - s_u, 0.0)
        mu = (jnp.sum(s_l * z_l * fin_l, axis=1) + jnp.sum(s_u * z_u * fin_u, axis=1)) / n_act

        # --- Affine (predictor) direction: complementarity target 0.
        rc_l = -s_l * z_l
        rc_u = -s_u * z_u
        r1 = r_dual + jnp.where(fin_l, (rc_l - z_l * r_sl) / s_l, 0.0) \
                    - jnp.where(fin_u, (rc_u - z_u * r_su) / s_u, 0.0)
        # The affine direction only steers the centering parameter σ and
        # the Mehrotra cross terms — refinement there buys nothing
        # measurable (H=24: identical convergence; H=48 engine-day: solve
        # rate 0.9927 vs 0.9901 — docs/perf_notes.md) and costs two extra
        # substitution passes + a matvec per iteration.  Factor + predictor
        # solve run as ONE fused kernel on the pallas path (the factor
        # stays in VMEM for its first consumer); the corrector below
        # re-reads the emitted factor.
        Lb, dx_a, dy_a = factor_solve_kkt(Sb, theta_inv, r1, r_prim)
        ds_l_a = jnp.where(fin_l, r_sl + dx_a, 0.0)
        ds_u_a = jnp.where(fin_u, r_su - dx_a, 0.0)
        dz_l_a = jnp.where(fin_l, (rc_l - z_l * ds_l_a) / s_l, 0.0)
        dz_u_a = jnp.where(fin_u, (rc_u - z_u * ds_u_a) / s_u, 0.0)

        def max_step(v, dv, active):
            r = jnp.where(active & (dv < 0), -v / jnp.minimum(dv, -1e-20), _BIG)
            return jnp.minimum(jnp.min(r, axis=1), 1.0)

        a_p = jnp.minimum(max_step(s_l, ds_l_a, fin_l), max_step(s_u, ds_u_a, fin_u))
        a_d = jnp.minimum(max_step(z_l, dz_l_a, fin_l), max_step(z_u, dz_u_a, fin_u))
        mu_aff = (
            jnp.sum((s_l + a_p[:, None] * ds_l_a) * (z_l + a_d[:, None] * dz_l_a) * fin_l, axis=1)
            + jnp.sum((s_u + a_p[:, None] * ds_u_a) * (z_u + a_d[:, None] * dz_u_a) * fin_u, axis=1)
        ) / n_act
        sigma = jnp.clip((mu_aff / jnp.maximum(mu, 1e-12)) ** 3, 0.0, 1.0)

        # --- Corrector: target σμ − Mehrotra cross terms.
        tgt = (sigma * mu)[:, None]
        rc_l = tgt - s_l * z_l - ds_l_a * dz_l_a
        rc_u = tgt - s_u * z_u - ds_u_a * dz_u_a
        r1 = r_dual + jnp.where(fin_l, (rc_l - z_l * r_sl) / s_l, 0.0) \
                    - jnp.where(fin_u, (rc_u - z_u * r_su) / s_u, 0.0)
        dx, dy = solve_kkt(Lb, Sb, theta_inv, r1, r_prim)
        ds_l = jnp.where(fin_l, r_sl + dx, 0.0)
        ds_u = jnp.where(fin_u, r_su - dx, 0.0)
        dz_l = jnp.where(fin_l, (rc_l - z_l * ds_l) / s_l, 0.0)
        dz_u = jnp.where(fin_u, (rc_u - z_u * ds_u) / s_u, 0.0)

        eta = 0.99
        a_p = eta * jnp.minimum(max_step(s_l, ds_l, fin_l), max_step(s_u, ds_u, fin_u))
        a_d = eta * jnp.minimum(max_step(z_l, dz_l, fin_l), max_step(z_u, dz_u, fin_u))
        a_p = jnp.where(frozen, 0.0, a_p)
        a_d = jnp.where(frozen, 0.0, a_d)
        x_n = x + a_p[:, None] * dx
        s_l_n = jnp.where(fin_l, s_l + a_p[:, None] * ds_l, s_l)
        s_u_n = jnp.where(fin_u, s_u + a_p[:, None] * ds_u, s_u)
        y_n = y + a_d[:, None] * dy
        z_l_n = jnp.where(fin_l, z_l + a_d[:, None] * dz_l, z_l)
        z_u_n = jnp.where(fin_u, z_u + a_d[:, None] * dz_u, z_u)
        # Keep the iterates strictly interior in f32.
        s_l_n = jnp.where(fin_l, jnp.maximum(s_l_n, 1e-10), 1.0)
        s_u_n = jnp.where(fin_u, jnp.maximum(s_u_n, 1e-10), 1.0)
        z_l_n = jnp.where(fin_l, jnp.maximum(z_l_n, 1e-12), 0.0)
        z_u_n = jnp.where(fin_u, jnp.maximum(z_u_n, 1e-12), 0.0)
        # NaN guard: a home whose Newton step blew up in f32 (or a
        # primal-infeasible home driving its duals to overflow) keeps its
        # last finite iterate — it will fail the final residual check and
        # route to the fallback controller.
        fin_ok = (
            jnp.all(jnp.isfinite(x_n), axis=1)
            & jnp.all(jnp.isfinite(y_n), axis=1)
            & jnp.all(jnp.isfinite(z_l_n) & jnp.isfinite(z_u_n), axis=1)
        )[:, None]
        x = jnp.where(fin_ok, x_n, x)
        y = jnp.where(fin_ok, y_n, y)
        s_l = jnp.where(fin_ok, s_l_n, s_l)
        s_u = jnp.where(fin_ok, s_u_n, s_u)
        z_l = jnp.where(fin_ok, z_l_n, z_l)
        z_u = jnp.where(fin_ok, z_u_n, z_u)
        # Per-home attribution: iterations the home was still LIVE for
        # (frozen — converged or certified-diverged — homes take zero-
        # length steps and stop accumulating).  Pre-step ``frozen`` means
        # a home frozen at iteration j reads cit = j.
        return (i + 1, jnp.all(frozen), x, y, s_l, s_u, z_l, z_u,
                cit + (~frozen).astype(cit.dtype))

    return body, converged


def _run_phases(B, m, dtype, cap, tail_frac, tail_iters, mesh,
                eps_abs, eps_rel, data, carry0, shared, fin):
    """Phase-1 full-batch Mehrotra loop, optional phase-2 tail compaction,
    final residual check.

    Tail compaction: most homes converge well before the iteration cap
    (H=48 cold: 77 % by iteration 16 while the cap runs 40 —
    docs/perf_notes.md), yet every full-batch iteration pays for all B
    homes.  With ``tail_frac`` > 0, phase 1 stops at ``iters`` and the
    worst ``ceil(B·tail_frac)`` homes are GATHERED into a compact
    sub-batch that alone runs up to ``tail_iters`` more iterations —
    straggler cost scales by tail_frac instead of 1.  Static shapes
    throughout (top_k with a static k).

    Under a mesh the same compaction runs PER SHARD inside ``shard_map``:
    each device ranks and gathers its own worst ``ceil(B_shard·tail_frac)``
    homes locally — no cross-shard all-to-all, static shapes, and the
    measured 1.5–1.6× straggler win survives on the multi-chip path
    (round-2 verdict item 4; the global gather it replaces was disabled
    there).  Shard-local ranking can pick a slightly different straggler
    set than global ranking when stragglers cluster on one shard; both
    sets cover all true stragglers whenever ``tail_frac`` is sized from
    the convergence CDF, and unconverged homes still fail the final
    residual check either way.
    """
    (vals_s, vp_r, vp_c, qs, bs, ls, us, reg_s, fin_l, fin_u, n_act, cd) = data
    x, y, s_l, s_u, z_l, z_u = carry0
    body, _ = _make_loop(data, shared, eps_abs, eps_rel)

    # Budget split lives HERE, next to the eligibility conditions, so the
    # two cannot disagree: ``cap`` is the user-facing iteration cap.  With
    # the tail eligible, phase 1 runs a shortened full-batch budget (2/5 of
    # the cap, min 10 — from the measured convergence CDF) and the tail
    # phase runs up to ``tail_iters`` (default: the cap) on the gathered
    # stragglers.  Ineligible (tiny per-shard batch / tiny cap) → the full
    # cap runs in phase 1, exactly the pre-compaction behavior.
    n_shards = int(mesh.shape[shared["mesh_axis"]]) if mesh is not None else 1
    B_shard = B // max(1, n_shards)
    do_tail = tail_frac > 0 and B_shard >= 8 and cap > 10
    if do_tail:
        iters = min(cap, max(10, cap * 2 // 5))
        tail_iters = tail_iters or cap
    else:
        iters = cap

    # Early exit once every home is frozen: frozen homes take zero-length
    # steps (a_p = a_d = 0), so stopping at that point is OUTPUT-IDENTICAL
    # to running out the fixed budget — warm steady-state batches converge
    # well before the horizon-aware cap and skip the dead iterations.
    # ``frozen`` can only grow: a frozen home does not move, so it stays
    # converged.  (all_frozen lags one iteration — it is computed from the
    # PRE-step iterate — which only costs one extra sweep, not correctness.)
    cit = jnp.zeros((B,), jnp.int32)
    i_done, _, x, y, s_l, s_u, z_l, z_u, cit = lax.while_loop(
        lambda c: (c[0] < iters) & ~c[1],
        body,
        (jnp.asarray(0), jnp.asarray(False), x, y, s_l, s_u, z_l, z_u, cit),
    )

    if do_tail:
        k = int(np.ceil(B_shard * float(tail_frac)))
        k = max(1, min(B_shard - 1, k))
        if mesh is None:
            shared_t = shared
        else:
            # Inside the shard_map region the band ops must be the PLAIN
            # per-shard kernels — the mesh-wrapped ones in ``shared`` would
            # nest shard_map.
            sc, _ch, so, ad, fs = pallas_band.make_band_ops(
                shared["plan"], shared["band_kernel"], mesh=None)
            shared_t = dict(shared, scatter_fn=sc,
                            band_solve_fn=so, add_diag_fn=ad,
                            factor_solve_fn=fs)

        def tail_phase(data_l, x, y, s_l, s_u, z_l, z_u, cit):
            """Rank, gather, and finish the worst-k stragglers of one
            (local) batch; scatter the improved iterates back."""
            _, conv2 = _make_loop(data_l, shared_t, eps_abs, eps_rel)
            frozen, score = conv2(x, y, s_l, s_u, z_l, z_u)
            # Frozen homes — converged OR certified-diverged (the
            # divergence freeze in ``converged``) — rank below any live
            # straggler: tail slots are for homes that can still improve,
            # and letting diverged homes hog them was measured as part of
            # the pre-freeze slowdown (docs/perf_notes.md).  Among live
            # stragglers the largest residuals go first.  NaN scores
            # (non-finite residuals that did NOT trip the freeze) have
            # implementation-defined top_k ordering — sanitize to +inf so
            # they rank as the worst live straggler instead of silently
            # dropping out.
            score = jnp.nan_to_num(score, nan=jnp.inf, posinf=jnp.inf)
            idx = lax.top_k(jnp.where(frozen, -1.0, score), k)[1]
            g = lambda a: a[idx]
            data2 = tuple(g(a) for a in data_l)
            body3, _ = _make_loop(data2, shared_t, eps_abs, eps_rel)
            i2, _, x2, y2, s_l2, s_u2, z_l2, z_u2, cit2 = lax.while_loop(
                lambda c: (c[0] < tail_iters) & ~c[1],
                body3,
                # Seed all-frozen from the phase-1 state: a warm
                # steady-state batch that fully converged in phase 1 skips
                # the tail loop entirely instead of paying one dead
                # zero-step iteration.
                (jnp.asarray(0), jnp.all(frozen),
                 g(x), g(y), g(s_l), g(s_u), g(z_l), g(z_u), g(cit)),
            )
            return (x.at[idx].set(x2), y.at[idx].set(y2),
                    s_l.at[idx].set(s_l2), s_u.at[idx].set(s_u2),
                    z_l.at[idx].set(z_l2), z_u.at[idx].set(z_u2),
                    cit.at[idx].set(cit2), i2)

        if mesh is None:
            x, y, s_l, s_u, z_l, z_u, cit, i2 = tail_phase(
                data, x, y, s_l, s_u, z_l, z_u, cit)
            i_done = i_done + i2
        else:
            from jax.sharding import PartitionSpec as P

            h = P(shared["mesh_axis"])  # leading home axis on every array

            def wrapped(data_l, x, y, s_l, s_u, z_l, z_u, cit):
                out = tail_phase(data_l, x, y, s_l, s_u, z_l, z_u, cit)
                return out[:7] + (out[7][None],)  # per-shard iter count

            from dragg_tpu.utils.compat import shard_map_partial

            it_specs = (h,) * 7
            x, y, s_l, s_u, z_l, z_u, cit, i2s = shard_map_partial(mesh)(
                wrapped,
                in_specs=(tuple(h for _ in data),) + it_specs,
                out_specs=it_specs + (h,),
            )(data, x, y, s_l, s_u, z_l, z_u, cit)
            i_done = i_done + jnp.max(i2s)

    # --- Final residuals in UNSCALED units (ADMM-convention norms).
    e_eq, e_box, c, d = fin["e_eq"], fin["e_box"], fin["c"], fin["d"]
    l_box, u_box = fin["l_box"], fin["u_box"]
    fixed, fixval, inverted = fin["fixed"], fin["fixval"], fin["inverted"]
    row_cols, col_rows = shared["row_cols"], shared["col_rows"]
    mv = lambda xx: jnp.sum(vp_r * xx[:, row_cols], axis=2)
    mvt = lambda yy: jnp.sum(vp_c * yy[:, col_rows], axis=2)
    mvx = mv(x)
    r_prim = jnp.max(jnp.abs((mvx - bs) / e_eq), axis=1)
    box_viol = jnp.maximum(
        jnp.where(fin_l, ls - x, 0.0), jnp.where(fin_u, x - us, 0.0)
    )
    r_prim = jnp.maximum(r_prim, jnp.max(box_viol * jnp.abs(d), axis=1))
    dual = (reg_s * x + qs + mvt(y) - z_l + z_u) / (c * d)
    r_dual = jnp.max(jnp.abs(dual), axis=1)
    gap = (jnp.sum(s_l * z_l * fin_l, axis=1) + jnp.sum(s_u * z_u * fin_u, axis=1)) / n_act
    gap_u = gap / jnp.maximum(jnp.abs(jnp.sum(qs * x, axis=1)), 1.0)
    ok = ((r_prim <= 10 * eps_abs) & (r_dual <= 10 * eps_abs)
          & (gap_u <= jnp.maximum(10 * eps_rel, 1e-6)) & ~inverted)

    # Per-home certified divergence, mirroring the loop-internal freeze
    # criterion (converged_from): scaled-space primal residual stalled
    # far above tolerance WHILE the box duals blew past the freeze
    # threshold — the primal-infeasible signature, distinct from a home
    # that is merely unconverged at the budget.
    rp_scaled = jnp.max(jnp.abs(bs - mvx), axis=1)
    zmax = jnp.maximum(jnp.max(z_l * fin_l, axis=1),
                       jnp.max(z_u * fin_u, axis=1))
    diverged = (rp_scaled > 100 * jnp.maximum(eps_abs, 1e-6)) \
        & (zmax > shared["freeze_zmax"])

    x_out = jnp.clip(d * x, l_box, u_box)
    x_out = jnp.where(fixed, fixval, x_out)
    return ADMMSolution(
        x=x_out,
        y_eq=e_eq * y / c,
        y_box=(z_u - z_l) * e_box / c,
        r_prim=r_prim,
        r_dual=r_dual,
        solved=ok,
        infeasible=jnp.zeros((B,), bool),
        iters=i_done,
        rho=jnp.ones((B,), dtype),
        conv_iters=cit,
        diverged=diverged & ~ok,
    )
