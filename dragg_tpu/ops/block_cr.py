"""Block cyclic-reduction solver for the banded Schur systems.

Why a third band backend: on TPU the sequential factor/solve recurrences
are LATENCY-bound — ``m`` dependent row steps with only (bw+1, lanes) of
work each, whether they run as an XLA scan (round-2 on-chip profile: the
scan dispatch IS the solve phase) or inside one Pallas kernel (round-3:
the in-kernel ``fori_loop`` still serializes ``m`` VPU steps, and grid
blocks execute sequentially per core).  Cyclic reduction restructures the
same SPD system as a block-tridiagonal solve with bw×bw blocks and
eliminates every other block per level: the serial chain shrinks from
``m`` steps to ``ceil(log2(m/bw))`` levels (~6 at the H=48 shapes), and
each level is a handful of batched (bw, bw) einsums — exactly the shape
XLA tiles onto the MXU.  FLOPs roughly double vs the sequential factor;
on latency-bound hardware that trade is the point.

Accuracy: the reduction is algebraically exact; in f32 the elimination
order differs from the sequential Cholesky, so results differ at rounding
level.  The IPM's iterative-refinement pass against the true band S
(ops/ipm.py solve_kkt) applies unchanged — solution quality rests on the
refined residual, not on which elimination order produced the factor.
Diagonal pivot blocks are handled via Cholesky triangular solves (every
even/odd Schur complement of an SPD matrix is SPD; no explicit inverses).

Block-tridiagonal form: with bandwidth bw, rows ks..ks+s−1 (s = bw) form
diagonal blocks D_k and the only off-diagonal coupling is to the adjacent
block (|i−j| ≤ bw spans at most one block boundary):

    U_{k−1}ᵀ x_{k−1} + D_k x_k + U_k x_{k+1} = r_k .

One reduction level eliminates the odd blocks: with A_t = U_{2t} (even t
→ odd t) and B_t = U_{2t+1} (odd t → even t+1),

    D'_t   = D_t − A_t D̂_t⁻¹ A_tᵀ − B_{t−1}ᵀ D̂_{t−1}⁻¹ B_{t−1}
    U'_t   = −A_t D̂_t⁻¹ B_t
    r'_t   = r_t − A_t D̂_t⁻¹ r̂_t − B_{t−1}ᵀ D̂_{t−1}⁻¹ r̂_{t−1}
    x̂_t    = D̂_t⁻¹ (r̂_t − A_tᵀ x'_t − B_t x'_{t+1})        (back-subst.)

(hats = odd-block quantities).  Recurse on the even half until one block
remains.  All shapes are static; the level loop is a Python loop over a
statically known depth.

Reference anchor: plays GLPK's basis-factorization role for the per-home
solves (dragg/mpc_calc.py:141-145), batched community-wide.
"""

from __future__ import annotations

# dragg: disable-file=DT008, block-CR's (bw,bw) block einsums are outside the round-14 dense-family policy (it covers the reluqp/admm iteration matmuls); repinning them to HIGHEST would change on-TPU numerics without a recorded measurement — revisit with an on-chip A/B (docs/perf_notes.md convention)

import jax
import jax.numpy as jnp


def _tri_solve(L, X, trans=False):
    """Triangular solve with a batched Cholesky factor L: L⁻¹X or L⁻ᵀX."""
    if trans:
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(L, -1, -2), X, lower=False)
    return jax.scipy.linalg.solve_triangular(L, X, lower=True)


def _spd_solve(L, X):
    """(L Lᵀ)⁻¹ X for batched blocks."""
    return _tri_solve(L, _tri_solve(L, X), trans=True)


def band_to_blocktri(Sb: jnp.ndarray, bw: int):
    """Band storage (B, m, bw+1) with ``Sb[:, i, d] = S[i, i−d]`` →
    block-tridiagonal ``(D, U)``: D (B, N, s, s) diagonal blocks,
    U (B, N−1, s, s) upper couplings, s = bw, N = ceil(m/s).  Rows beyond
    m are padded with identity (decoupled; their solution is 0/benign)."""
    B, m, _ = Sb.shape
    s = bw
    N = -(-m // s)
    mp = N * s
    padded = jnp.zeros((B, mp, bw + 1), Sb.dtype).at[:, :m, :].set(Sb)
    padded = padded.at[:, m:, 0].set(1.0)

    # D_k[a, b] = S[ks+a, ks+b]; symmetric read from the lower band.
    D = jnp.zeros((B, N, s, s), Sb.dtype)
    for a in range(s):
        for b in range(s):
            if a >= b:
                D = D.at[:, :, a, b].set(padded[:, a::s, a - b])
            else:
                D = D.at[:, :, a, b].set(padded[:, b::s, b - a])
    # U_k[a, b] = S[ks+a, (k+1)s+b] — in-band iff b ≤ a (offset s+b−a ≤ bw).
    U = jnp.zeros((B, N - 1, s, s), Sb.dtype) if N > 1 else \
        jnp.zeros((B, 0, s, s), Sb.dtype)
    for a in range(s):
        for b in range(a + 1):
            col_rows = padded[:, (s + b)::s, s + b - a]   # rows (k+1)s+b
            U = U.at[:, :, a, b].set(col_rows[:, : N - 1])
    return D, U, N, mp


def cr_factor(Sb: jnp.ndarray, bw: int):
    """Build the multilevel cyclic-reduction factor of the SPD band matrix.
    Returns an opaque pytree consumed by :func:`cr_solve`."""
    D, U, N, mp = band_to_blocktri(Sb, bw)
    levels = []
    while N > 1:
        n_odd = N // 2             # odd blocks 1, 3, …
        n_b = (N - 1) // 2         # odd blocks that have a RIGHT even
        Dod = D[:, 1::2]
        A = U[:, 0::2]                                   # (B, n_odd, s, s)
        Bc = U[:, 1::2]                                  # (B, n_b, s, s)
        Lod = jnp.linalg.cholesky(Dod)
        DinvAT = _spd_solve(Lod, jnp.swapaxes(A, -1, -2))
        DinvB = _spd_solve(Lod[:, :n_b], Bc)
        Dev = D[:, 0::2]
        # Right-neighbor correction on even t < n_odd.
        Dev = Dev.at[:, :n_odd].add(
            -jnp.einsum("bnij,bnjk->bnik", A, DinvAT))
        # Left-neighbor correction on even t = 1..n_b.
        Dev = Dev.at[:, 1:1 + n_b].add(
            -jnp.einsum("bnji,bnjk->bnik", Bc, DinvB))
        levels.append(dict(
            Lod=Lod, A=A, B=Bc,
            GA=jnp.swapaxes(DinvAT, -1, -2),     # A D̂⁻¹     (B, n_odd, s, s)
            GBT=jnp.swapaxes(DinvB, -1, -2),     # Bᵀ D̂⁻¹    (B, n_b, s, s)
        ))
        U = -jnp.einsum("bnij,bnjk->bnik", A[:, :n_b], DinvB)
        D = Dev
        N = D.shape[1]
    levels.append(jnp.linalg.cholesky(D[:, 0]))
    return dict(levels=levels, mp=mp, bw=bw)


def cr_solve(factor, r: jnp.ndarray) -> jnp.ndarray:
    """Solve S x = r with a cached CR factor; r is (B, m) in the same
    (permuted) row order as the band storage the factor was built from."""
    levels, mp, bw = factor["levels"], factor["mp"], factor["bw"]
    B, m = r.shape
    s = bw
    rb = jnp.zeros((B, mp), r.dtype).at[:, :m].set(r).reshape(B, mp // s, s)

    stack = []
    for lv in levels[:-1]:
        n_odd = lv["A"].shape[1]
        n_b = lv["B"].shape[1]
        rod = rb[:, 1::2]
        rev = rb[:, 0::2]
        rev = rev.at[:, :n_odd].add(
            -jnp.einsum("bnij,bnj->bni", lv["GA"], rod))
        rev = rev.at[:, 1:1 + n_b].add(
            -jnp.einsum("bnij,bnj->bni", lv["GBT"], rod[:, :n_b]))
        stack.append(rod)
        rb = rev

    Lroot = levels[-1]
    x = _spd_solve(Lroot, rb[:, 0, :, None])[:, :, 0][:, None]

    for lv, rod in zip(reversed(levels[:-1]), reversed(stack)):
        n_odd = lv["A"].shape[1]
        n_b = lv["B"].shape[1]
        t = rod - jnp.einsum("bnji,bnj->bni", lv["A"], x[:, :n_odd])
        t = t.at[:, :n_b].add(
            -jnp.einsum("bnij,bnj->bni", lv["B"], x[:, 1:1 + n_b]))
        xod = _spd_solve(lv["Lod"], t[..., None])[..., 0]
        N = x.shape[1] + xod.shape[1]
        out = jnp.zeros((B, N, s), x.dtype)
        out = out.at[:, 0::2].set(x)
        out = out.at[:, 1::2].set(xod)
        x = out

    return x.reshape(B, mp)[:, :m]
