from dragg_tpu.ops.qp import QPLayout, HomeQPStatic, build_qp_static, assemble_qp_step  # noqa: F401
from dragg_tpu.ops.admm import admm_solve, ADMMSolution  # noqa: F401
from dragg_tpu.ops.reluqp import reluqp_solve_qp, ReLUQPCarry  # noqa: F401
