"""Fixed-shape QP formulation of the per-home MPC.

The reference builds a CVXPY mixed-integer program per home per timestep and
canonicalizes it at runtime (dragg/mpc_calc.py:291-454).  Here the
(home-type, horizon) template is compiled once into index arrays, and each
timestep only fills a per-home coefficient vector — no runtime
canonicalization, fixed shapes, so the whole community batches on the MXU
(SURVEY.md §2.2, §7 step 2).

Relaxation: the reference's integer duty-cycle variables
(dragg/mpc_calc.py:171-173, bounded [0, sub_subhourly_steps]) are relaxed to
box-constrained continuous duty fractions.  The reference itself divides the
integer counts by ``sub_subhourly_steps`` to report duty fractions
(dragg/mpc_calc.py:497-499), so the LP/QP relaxation is the parity target
(SURVEY.md §2.2); its optimal cost lower-bounds the MILP's.  MEASURED gap
vs the true integer optimum (tools/milp_gap.py, HiGHS-MILP on these exact
matrices, 20-home community): aggregate 2.7–2.8 % at H=8 / 3.4–3.6 % at
H=6 (base-only / mixed), max 5.5 % per home — docs/perf_notes.md round 4.  First-action integerization
(pin the three k=0 duty counts to rounded values, one extra batched
re-solve) restores an implementable applied action with 0/20
comfort-infeasibility; full-horizon rounding is NOT viable (15/20
infeasible).

Problem form (OSQP convention):  minimize (1/2) x'(eps I)x + q'x subject to
l <= A x <= u, with A = [A_eq; I] — equality rows (dynamics + initial
conditions) followed by an identity box block.  Only the box block and RHS
change shape-free per timestep; A_eq has a fixed sparsity whose values are
per-home (static) except the water-draw mixing coefficients, which vary per
timestep (dragg/mpc_calc.py:330-332).

Variable vector per home (superset pv_battery shape shown; in the
superset-shaped batch base homes get zero-width battery/PV via [0,0]
bounds, while the type-bucketed engine drops the absent blocks from the
layout entirely via :class:`HomeTypeSpec`), horizon H:

    cool[H] heat[H] wh[H] p_ch[H] p_disch[H] u_curt[H]
    T_in_ev[H+1] T_wh_ev[H+1] e_batt[H+1] T_in1 T_wh1        (n = 9H + 5)

p_load / p_grid / cost of the reference are affine in these and eliminated;
the objective sum_k discount^k * price[k] * p_grid[k]
(dragg/mpc_calc.py:441-446) becomes a linear q over the controls.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

TAP_TEMP = 15.0  # assumed cold tap water temp, degC (dragg/mpc_calc.py:181)
BIG = jnp.inf


class HomeTypeSpec(NamedTuple):
    """Which optional variable/constraint blocks a home type carries.

    The reference builds a different CVXPY program per home type
    (dragg/mpc_calc.py ``manage_home`` dispatch): base homes have no
    battery or PV blocks at all.  A :class:`QPLayout` built on a spec
    drops the absent blocks from the batched program instead of padding
    them to zero-width [0, 0] boxes — the type-bucketed engine solves
    each bucket at its own (n, m) shape (docs/architecture.md §10).
    """

    has_batt: bool   # p_ch / p_disch / e_batt columns + battery dynamics rows
    has_curt: bool   # PV curtailment column (objective-only; no A_eq rows)


SUPERSET_SPEC = HomeTypeSpec(has_batt=True, has_curt=True)

# Home type name (dragg_tpu.homes.HOME_TYPES) → block spec.
TYPE_SPECS: dict[str, HomeTypeSpec] = {
    "pv_battery": SUPERSET_SPEC,
    "pv_only": HomeTypeSpec(has_batt=False, has_curt=True),
    "battery_only": HomeTypeSpec(has_batt=True, has_curt=False),
    "base": HomeTypeSpec(has_batt=False, has_curt=False),
}


class QPLayout:
    """Index bookkeeping for the per-home variable vector and equality rows.

    Default spec is the superset (pv_battery) shape, whose indices are
    identical to the historical fixed layout (n = 9H + 5, m_eq = 3H + 5).
    Under a reduced :class:`HomeTypeSpec` the absent blocks' indices are
    ``None`` so any unguarded use fails loudly instead of aliasing a live
    column."""

    def __init__(self, horizon: int, spec: HomeTypeSpec = SUPERSET_SPEC):
        H = int(horizon)
        self.H = H
        self.spec = spec
        self.has_batt = bool(spec.has_batt)
        self.has_curt = bool(spec.has_curt)
        i = 0
        self.i_cool = i; i += H          # noqa: E702 — index table reads as one block
        self.i_heat = i; i += H          # noqa: E702
        self.i_wh = i; i += H            # noqa: E702
        if self.has_batt:
            self.i_pch = i; i += H       # noqa: E702
            self.i_pd = i; i += H        # noqa: E702
        else:
            self.i_pch = self.i_pd = None
        if self.has_curt:
            self.i_curt = i; i += H      # noqa: E702
        else:
            self.i_curt = None
        self.i_tin = i; i += H + 1       # noqa: E702
        self.i_twh = i; i += H + 1       # noqa: E702
        if self.has_batt:
            self.i_eb = i; i += H + 1    # noqa: E702
        else:
            self.i_eb = None
        self.i_tin1 = i; i += 1          # noqa: E702
        self.i_twh1 = i; i += 1          # noqa: E702
        self.n = i
        # Equality rows.
        r = 0
        self.r_tin0 = r; r += 1          # noqa: E702
        self.r_tind = r; r += H          # noqa: E702  (H rows)
        self.r_twh0 = r; r += 1          # noqa: E702
        self.r_twhd = r; r += H          # noqa: E702  (H rows)
        self.r_tin1 = r; r += 1          # noqa: E702
        self.r_twh1 = r; r += 1          # noqa: E702
        if self.has_batt:
            self.r_eb0 = r; r += 1       # noqa: E702
            self.r_ebd = r; r += H       # noqa: E702  (H rows)
        else:
            self.r_eb0 = self.r_ebd = None
        self.m_eq = r
        self.m = self.m_eq + self.n


class SparsePattern(NamedTuple):
    """Static gather-padded sparsity of A_eq, shared across homes.

    The dynamics matrix has ≤``K`` nonzeros per row and ≤``Kc`` per column
    (banded RC recurrences), so both matvec directions become pure gathers +
    elementwise sums — no scatter in the hot loop, which matters on TPU.
    ``*_src`` index the flat nnz axis (-1 → empty slot, masked to 0).

    All index structures are nested int tuples, so the pattern is hashable
    and can be a ``jit`` static argument.
    """

    m: int                    # equality rows
    n: int                    # variables
    nnz: int
    rows: tuple               # (nnz,) row of each entry
    cols: tuple               # (nnz,) col of each entry
    row_cols: tuple           # (m, K) column index per row slot (0-padded)
    row_src: tuple            # (m, K) nnz index per row slot (-1-padded)
    col_rows: tuple           # (n, Kc) row index per col slot (0-padded)
    col_src: tuple            # (n, Kc) nnz index per col slot (-1-padded)


def _tt(a: np.ndarray) -> tuple:
    """ndarray → nested tuple (hashable)."""
    if a.ndim == 1:
        return tuple(int(v) for v in a)
    return tuple(tuple(int(v) for v in row) for row in a)


def _build_pattern(rows: np.ndarray, cols: np.ndarray, m: int, n: int) -> SparsePattern:
    nnz = len(rows)
    K = int(np.bincount(rows, minlength=m).max())
    Kc = int(np.bincount(cols, minlength=n).max())
    row_cols = np.zeros((m, K), dtype=np.int32)
    row_src = np.full((m, K), -1, dtype=np.int32)
    col_rows = np.zeros((n, Kc), dtype=np.int32)
    col_src = np.full((n, Kc), -1, dtype=np.int32)
    rfill = np.zeros(m, dtype=np.int64)
    cfill = np.zeros(n, dtype=np.int64)
    for e in range(nnz):
        r, c = int(rows[e]), int(cols[e])
        row_cols[r, rfill[r]] = c
        row_src[r, rfill[r]] = e
        rfill[r] += 1
        col_rows[c, cfill[c]] = r
        col_src[c, cfill[c]] = e
        cfill[c] += 1
    return SparsePattern(m=m, n=n, nnz=nnz, rows=_tt(rows), cols=_tt(cols),
                         row_cols=_tt(row_cols), row_src=_tt(row_src),
                         col_rows=_tt(col_rows), col_src=_tt(col_src))


class SchurStructure(NamedTuple):
    """Static structure for forming S = A D⁻¹ Aᵀ directly from the sparse
    values, without materializing the dense (B, m, n) A (the round-1 scale
    blocker: at 100k homes × H=48 the dense A alone was ~26 GB).

    S_ij = Σ_k Dinv_k · A_ik · A_jk — the sum runs over columns k shared by
    rows i and j.  For the banded RC pattern (≤4 nnz/row·col) the number of
    (i, j, k) triples is O(m), so S formation drops from 2Bm²n FLOPs + Bmn
    memory to a few gathers over (B, n_s, P) with n_s = nnz(S), P = max
    shared columns per (i, j).
    """

    n_s: int          # number of stored S entries (full matrix, both triangles)
    P: int            # max (e1, e2) pairs per S entry
    s_rows: tuple     # (n_s,) row of each S entry
    s_cols: tuple     # (n_s,)
    e1: tuple         # (n_s, P) first-factor nnz index (0-padded)
    e2: tuple         # (n_s, P) second-factor nnz index (0-padded)
    kcol: tuple       # (n_s, P) shared column index for the Dinv gather (0-padded)
    mask: tuple       # (n_s, P) 1/0 valid-slot mask


def build_schur_structure(pat: SparsePattern) -> SchurStructure:
    """Precompute the (i, j, k) triple lists of S = A D⁻¹ Aᵀ for a sparse
    pattern.  Cost is O(Σ_k c_k²) with c_k the column counts — tiny for the
    banded MPC pattern, and computed once per (horizon, home-type) shape."""
    from collections import defaultdict

    rows = np.asarray(pat.rows)
    cols = np.asarray(pat.cols)
    by_col: dict[int, list[int]] = defaultdict(list)
    for e in range(pat.nnz):
        by_col[int(cols[e])].append(e)
    pairs: dict[tuple[int, int], list[tuple[int, int, int]]] = defaultdict(list)
    for k, es in by_col.items():
        for a in es:
            for bb in es:
                pairs[(int(rows[a]), int(rows[bb]))].append((a, bb, k))
    n_s = len(pairs)
    P = max(len(v) for v in pairs.values())
    s_rows = np.zeros(n_s, dtype=np.int32)
    s_cols = np.zeros(n_s, dtype=np.int32)
    e1 = np.zeros((n_s, P), dtype=np.int32)
    e2 = np.zeros((n_s, P), dtype=np.int32)
    kcol = np.zeros((n_s, P), dtype=np.int32)
    mask = np.zeros((n_s, P), dtype=np.int32)
    for idx, ((i, j), lst) in enumerate(sorted(pairs.items())):
        s_rows[idx] = i
        s_cols[idx] = j
        for p, (a, bb, k) in enumerate(lst):
            e1[idx, p] = a
            e2[idx, p] = bb
            kcol[idx, p] = k
            mask[idx, p] = 1
    return SchurStructure(n_s=n_s, P=P, s_rows=_tt(s_rows), s_cols=_tt(s_cols),
                          e1=_tt(e1), e2=_tt(e2), kcol=_tt(kcol), mask=_tt(mask))


def schur_contrib(ss: SchurStructure, vals_s, Dinv) -> jnp.ndarray:
    """Per-entry values of S = Â D⁻¹ Âᵀ ((B, n_s), aligned with
    ss.s_rows/s_cols) from the precomputed triple lists."""
    e1 = jnp.asarray(ss.e1)
    e2 = jnp.asarray(ss.e2)
    kcol = jnp.asarray(ss.kcol)
    mask = jnp.asarray(ss.mask, dtype=vals_s.dtype)
    return jnp.sum(
        vals_s[:, e1] * vals_s[:, e2] * Dinv[:, kcol] * mask[None], axis=2
    )


def scatter_schur(ss: SchurStructure, m: int, contrib) -> jnp.ndarray:
    """Schur entry values (B, n_s) → dense (B, m, m)."""
    s_rows = np.asarray(ss.s_rows)
    s_cols = np.asarray(ss.s_cols)
    B = contrib.shape[0]
    return jnp.zeros((B, m, m), dtype=contrib.dtype).at[:, s_rows, s_cols].set(contrib)


def form_schur_sparse(ss: SchurStructure, m: int, vals_s, Dinv) -> jnp.ndarray:
    """Form the dense (B, m, m) S = Â D⁻¹ Âᵀ from sparse values via the
    precomputed triple lists — no dense A anywhere."""
    return scatter_schur(ss, m, schur_contrib(ss, vals_s, Dinv))


def densify_A(pat: SparsePattern, vals) -> jnp.ndarray:
    """Materialize the dense (B, m, n) A_eq from sparse values (tests,
    CPU-reference cross-checks, Schur factorization)."""
    rows = np.asarray(pat.rows)
    cols = np.asarray(pat.cols)
    return jnp.zeros((vals.shape[0], pat.m, pat.n), dtype=vals.dtype).at[
        :, rows, cols
    ].add(vals)


class HomeQPStatic(NamedTuple):
    """Per-home static pieces: the (row, col) sparsity (shared) plus the
    per-home coefficient values split into static entries and the indices of
    the timestep-varying water-mix band."""

    rows: np.ndarray          # (nnz,) shared across homes
    cols: np.ndarray          # (nnz,)
    vals: jnp.ndarray         # (n_homes, nnz) — static values; wh-mix band filled per step
    whmix_pos: np.ndarray     # (H,) positions in the nnz axis of the wh-mix coefficients
    pattern: SparsePattern    # gather-padded sparsity for the solver hot loop
    a_in: jnp.ndarray         # (n_homes,) 3600 / (C * dt)
    a_wh: jnp.ndarray         # (n_homes,) 3600 / (wh_c * dt)
    kin: jnp.ndarray          # (n_homes,) 1 - a_in / R
    kwh: jnp.ndarray          # (n_homes,) 1 - a_wh / wh_r
    awr: jnp.ndarray          # (n_homes,) a_wh / wh_r


def build_qp_static(batch, horizon: int, dt: int,
                    spec: HomeTypeSpec = SUPERSET_SPEC) -> HomeQPStatic:
    """Precompute the equality-constraint sparsity + per-home coefficients.

    ``batch`` is a HomeBatch (arrays may be numpy or jax).  Row/col index
    arrays are identical for every home; values are per-home.  ``spec``
    selects the block layout — a battery-free spec drops the SoC pin +
    dynamics rows and their nnz entirely (type-bucketed engine).
    """
    lay = QPLayout(horizon, spec)
    H = lay.H
    n_homes = batch.hvac_r.shape[0]

    a_in = 3600.0 / (np.asarray(batch.hvac_c) * dt)
    a_wh = 3600.0 / (np.asarray(batch.wh_c) * dt)
    R = np.asarray(batch.hvac_r)
    wh_r = np.asarray(batch.wh_r)
    kin = 1.0 - a_in / R
    kwh = 1.0 - a_wh / wh_r
    awr = a_wh / wh_r
    pc = np.asarray(batch.hvac_p_c)
    ph = np.asarray(batch.hvac_p_h)
    pwh = np.asarray(batch.wh_p)
    che = np.asarray(batch.batt_ch_eff)
    dse = np.asarray(batch.batt_disch_eff)

    rows, cols, vals = [], [], []
    whmix_pos = np.zeros(H, dtype=np.int64)

    def add(r, c, v):
        rows.append(r)
        cols.append(c)
        vals.append(np.broadcast_to(v, (n_homes,)).astype(np.float64))
        return len(rows) - 1

    ks = np.arange(H)
    # Indoor temp: T[0] pin + dynamics (dragg/mpc_calc.py:313-317).
    add(lay.r_tin0, lay.i_tin, 1.0)
    for k in range(H):
        add(lay.r_tind + k, lay.i_tin + k + 1, 1.0)
        add(lay.r_tind + k, lay.i_tin + k, -kin)
        add(lay.r_tind + k, lay.i_cool + k, a_in * pc)
        add(lay.r_tind + k, lay.i_heat + k, -a_in * ph)
    # WH temp: T[0] pin + dynamics with draw mixing (dragg/mpc_calc.py:329-332).
    add(lay.r_twh0, lay.i_twh, 1.0)
    for k in range(H):
        add(lay.r_twhd + k, lay.i_twh + k + 1, 1.0)
        whmix_pos[k] = add(lay.r_twhd + k, lay.i_twh + k, 0.0)  # -rem[k+1]*kwh, per step
        add(lay.r_twhd + k, lay.i_tin + k + 1, -awr)
        add(lay.r_twhd + k, lay.i_wh + k, -a_wh * pwh)
    # One-step deterministic temps (dragg/mpc_calc.py:321-324,336-338).
    add(lay.r_tin1, lay.i_tin1, 1.0)
    add(lay.r_tin1, lay.i_cool, a_in * pc)
    add(lay.r_tin1, lay.i_heat, -a_in * ph)
    add(lay.r_twh1, lay.i_twh1, 1.0)
    add(lay.r_twh1, lay.i_tin + 1, -awr)
    add(lay.r_twh1, lay.i_wh, -a_wh * pwh)
    # Battery SoC: pin + dynamics (dragg/mpc_calc.py:363-372).
    if lay.has_batt:
        add(lay.r_eb0, lay.i_eb, 1.0)
        for k in range(H):
            add(lay.r_ebd + k, lay.i_eb + k + 1, 1.0)
            add(lay.r_ebd + k, lay.i_eb + k, -1.0)
            add(lay.r_ebd + k, lay.i_pch + k, -che / dt)
            add(lay.r_ebd + k, lay.i_pd + k, -1.0 / (dse * dt))
    del ks

    rows_np = np.array(rows, dtype=np.int64)
    cols_np = np.array(cols, dtype=np.int64)
    return HomeQPStatic(
        rows=rows_np,
        cols=cols_np,
        vals=jnp.asarray(np.stack(vals, axis=1)),
        whmix_pos=whmix_pos,
        pattern=_build_pattern(rows_np, cols_np, lay.m_eq, lay.n),
        a_in=jnp.asarray(a_in),
        a_wh=jnp.asarray(a_wh),
        kin=jnp.asarray(kin),
        kwh=jnp.asarray(kwh),
        awr=jnp.asarray(awr),
    )


class QPStep(NamedTuple):
    """Everything the ADMM solver needs for one timestep, batched over homes.
    A_eq is carried sparsely (values on the shared pattern); use
    :func:`densify_A` where a dense matrix is needed."""

    vals: jnp.ndarray     # (n_homes, nnz) A_eq values on the static pattern
    b_eq: jnp.ndarray     # (n_homes, m_eq)
    l_box: jnp.ndarray    # (n_homes, n)
    u_box: jnp.ndarray    # (n_homes, n)
    q: jnp.ndarray        # (n_homes, n) unscaled (admm_solve does its own cost scaling)


def assemble_qp_step(
    static: HomeQPStatic,
    lay: QPLayout,
    batch,
    *,
    oat_window,        # (H+1,) environment slice — oat_window[k] = OAT at
                       # t+k; (n_homes, H+1) under fleet weather offsets
                       # (per-home windows, engine._prepare)
    ghi_window,        # (H+1,) GHI slice — ghi_window[k] = GHI at t+k;
                       # (n_homes, H+1) under fleet weather offsets
    price_total,       # (n_homes, H) discounting NOT applied; rp + tou
    draw_frac,         # (n_homes, H+1) draw fractions for this step (index 0 = current)
    temp_in_init,      # (n_homes,)
    temp_wh_init,      # (n_homes,) AFTER draw mixing
    e_batt_init,       # (n_homes,)
    cool_cap,          # (n_homes,) seasonal duty cap (0 or s)
    heat_cap,          # (n_homes,)
    wh_cap: float,     # s
    discount,          # scalar
) -> QPStep:
    """Fill the per-timestep QP: A_eq values (water-mix band), RHS, box
    bounds (seasonal HVAC gating, dragg/mpc_calc.py:298-309), and the linear
    objective q (discounted price on grid power, dragg/mpc_calc.py:441-446).
    """
    H = lay.H
    n_homes = static.vals.shape[0]
    dtype = jnp.float32

    rem = 1.0 - draw_frac  # remainder_frac (dragg/mpc_calc.py:202-204)
    whmix_vals = -(rem[:, 1:] * static.kwh[:, None])  # (n_homes, H)
    vals = static.vals.at[:, static.whmix_pos].set(whmix_vals).astype(dtype)

    oat = jnp.asarray(oat_window)
    # Per-home windows (fleet weather offsets) arrive 2-D; the shared
    # scalar window broadcasts through the same (., H) row writes.
    oat = oat if oat.ndim == 2 else oat[None, :]
    b = jnp.zeros((n_homes, lay.m_eq), dtype=dtype)
    b = b.at[:, lay.r_tin0].set(temp_in_init)
    b = b.at[:, lay.r_tind : lay.r_tind + H].set(
        (static.a_in[:, None] / jnp.asarray(batch.hvac_r)[:, None]) * oat[:, 1 : H + 1]
    )
    b = b.at[:, lay.r_twh0].set(temp_wh_init)
    b = b.at[:, lay.r_twhd : lay.r_twhd + H].set(draw_frac[:, 1:] * TAP_TEMP * static.kwh[:, None])
    b = b.at[:, lay.r_tin1].set(
        temp_in_init * static.kin + static.a_in / jnp.asarray(batch.hvac_r) * oat[:, 1]
    )
    b = b.at[:, lay.r_twh1].set(temp_wh_init * static.kwh)
    if lay.has_batt:
        b = b.at[:, lay.r_eb0].set(e_batt_init)
        # battery dynamics rows rhs = 0 already

    inf = jnp.full((n_homes,), BIG, dtype=dtype)
    zeros = jnp.zeros((n_homes,), dtype=dtype)
    l = jnp.zeros((n_homes, lay.n), dtype=dtype)
    u = jnp.zeros((n_homes, lay.n), dtype=dtype)

    def seg(lo, hi, i0, length):
        nonlocal l, u
        l = l.at[:, i0 : i0 + length].set(jnp.broadcast_to(lo[:, None], (n_homes, length)))
        u = u.at[:, i0 : i0 + length].set(jnp.broadcast_to(hi[:, None], (n_homes, length)))

    seg(zeros, cool_cap, lay.i_cool, H)
    seg(zeros, heat_cap, lay.i_heat, H)
    seg(zeros, jnp.full((n_homes,), wh_cap, dtype=dtype), lay.i_wh, H)
    if lay.has_batt:
        rate = jnp.asarray(batch.batt_max_rate) * jnp.asarray(batch.has_batt)
        seg(zeros, rate, lay.i_pch, H)
        seg(-rate, zeros, lay.i_pd, H)
    if lay.has_curt:
        seg(zeros, jnp.ones((n_homes,), dtype=dtype), lay.i_curt, H)
    # T_in_ev[0] is pinned by equality; bounds apply to [1:] only
    # (dragg/mpc_calc.py:318-319).
    seg(-inf, inf, lay.i_tin, 1)
    seg(jnp.asarray(batch.temp_in_min).astype(dtype), jnp.asarray(batch.temp_in_max).astype(dtype), lay.i_tin + 1, H)
    # T_wh_ev bounds apply to ALL H+1 entries including the pinned index 0
    # (dragg/mpc_calc.py:333-334) — an out-of-band initial WH temp makes the
    # problem infeasible, which routes the home to the fallback controller
    # exactly as in the reference.
    seg(jnp.asarray(batch.temp_wh_min).astype(dtype), jnp.asarray(batch.temp_wh_max).astype(dtype), lay.i_twh, H + 1)
    if lay.has_batt:
        seg(-inf, inf, lay.i_eb, 1)
        cap_min = jnp.asarray(batch.batt_cap_min).astype(dtype)
        cap_max = jnp.asarray(batch.batt_cap_max).astype(dtype)
        seg(cap_min, cap_max, lay.i_eb + 1, H)
    seg(jnp.asarray(batch.temp_in_min).astype(dtype), jnp.asarray(batch.temp_in_max).astype(dtype), lay.i_tin1, 1)
    seg(jnp.asarray(batch.temp_wh_min).astype(dtype), jnp.asarray(batch.temp_wh_max).astype(dtype), lay.i_twh1, 1)

    # Objective: sum_k w[k] * price[k] * p_grid[k], p_grid affine in controls
    # (dragg/mpc_calc.py:342,387-432,441-446).  s cancels: p_load contributes
    # s * (P/s) * duty per control unit.
    s = float(wh_cap)
    w = jnp.power(jnp.asarray(discount, dtype=dtype), jnp.arange(H, dtype=dtype))
    wp = (w[None, :] * price_total.astype(dtype))  # (n_homes, H)
    q = jnp.zeros((n_homes, lay.n), dtype=dtype)
    q = q.at[:, lay.i_cool : lay.i_cool + H].set(wp * (s * jnp.asarray(batch.hvac_p_c)[:, None]).astype(dtype))
    q = q.at[:, lay.i_heat : lay.i_heat + H].set(wp * (s * jnp.asarray(batch.hvac_p_h)[:, None]).astype(dtype))
    q = q.at[:, lay.i_wh : lay.i_wh + H].set(wp * (s * jnp.asarray(batch.wh_p)[:, None]).astype(dtype))
    if lay.has_batt:
        q = q.at[:, lay.i_pch : lay.i_pch + H].set(wp * s)
        q = q.at[:, lay.i_pd : lay.i_pd + H].set(wp * s)
    if lay.has_curt:
        # PV: p_grid -= s * pvc[k] * (1 - u_curt[k]); the constant term is
        # dropped from q (it shifts the objective, not the argmin) and the
        # u_curt coefficient is +w*price*s*pvc (dragg/mpc_calc.py:380-385,410-432).
        ghi = jnp.asarray(ghi_window).astype(dtype)
        ghi = ghi if ghi.ndim == 2 else ghi[None, :]
        pvc = (
            jnp.asarray(batch.pv_area)[:, None]
            * jnp.asarray(batch.pv_eff)[:, None]
            * jnp.asarray(batch.has_pv)[:, None]
            * ghi[:, :H]
            / 1000.0
        ).astype(dtype)
        q = q.at[:, lay.i_curt : lay.i_curt + H].set(wp * s * pvc)
    return QPStep(vals=vals, b_eq=b, l_box=l, u_box=u, q=q)


def shift_warm_start(x, lay: QPLayout):
    """Shift a stacked variable (or box-dual) vector one step along the
    horizon for warm-starting the NEXT timestep's solve: the previous plan's
    entry for time t+k+1 seeds the new problem's entry for t+k (receding
    horizon), with the final entry repeated.  Duty plans are bang-bang-like,
    so the unshifted vector mis-seeds every switching time — measured: the
    shift moves the warm-started mass-convergence point from ~200 to ~150
    ADMM iterations on a 256-home steady-state step."""
    H = lay.H

    def sh(v, i0, L):
        return v.at[:, i0 : i0 + L - 1].set(v[:, i0 + 1 : i0 + L])

    for i0 in (lay.i_cool, lay.i_heat, lay.i_wh, lay.i_pch, lay.i_pd, lay.i_curt):
        if i0 is not None:
            x = sh(x, i0, H)
    for i0, L in ((lay.i_tin, H + 1), (lay.i_twh, H + 1), (lay.i_eb, H + 1)):
        if i0 is not None:
            x = sh(x, i0, L)
    return x


class MPCSolution(NamedTuple):
    """Recovered per-home horizon series (raw duty units, kW, degC, kWh)."""

    cool: jnp.ndarray      # (n_homes, H) raw duty [0, s]
    heat: jnp.ndarray
    wh: jnp.ndarray
    p_ch: jnp.ndarray
    p_disch: jnp.ndarray
    u_curt: jnp.ndarray
    p_pv: jnp.ndarray      # (n_homes, H)
    p_load: jnp.ndarray    # (n_homes, H) total community-units load (pre /s)
    p_grid: jnp.ndarray    # (n_homes, H)
    cost: jnp.ndarray      # (n_homes, H) price * p_grid (undiscounted, parity
                           # with dragg/mpc_calc.py:444)
    temp_in_ev: jnp.ndarray  # (n_homes, H+1)
    temp_wh_ev: jnp.ndarray
    e_batt: jnp.ndarray      # (n_homes, H+1)
    temp_in1: jnp.ndarray    # (n_homes,) one-step deterministic indoor temp
    temp_wh1: jnp.ndarray


def recover_solution(x, lay: QPLayout, batch, ghi_window, price_total, s: float) -> MPCSolution:
    """Extract physical series from the stacked variable vector and rebuild
    the eliminated p_load / p_pv / p_grid / cost
    (dragg/mpc_calc.py:342,380-432,444).

    Absent blocks (a reduced :class:`HomeTypeSpec`) come back as exact
    zeros — identical to the superset solve, whose [0, 0] boxes clip the
    dead variables to 0 in the returned (box-projected) primal."""
    H = lay.H
    B = x.shape[0]
    zH = jnp.zeros((B, H), dtype=x.dtype)
    cool = x[:, lay.i_cool : lay.i_cool + H]
    heat = x[:, lay.i_heat : lay.i_heat + H]
    wh = x[:, lay.i_wh : lay.i_wh + H]
    p_ch = x[:, lay.i_pch : lay.i_pch + H] if lay.has_batt else zH
    p_disch = x[:, lay.i_pd : lay.i_pd + H] if lay.has_batt else zH
    u_curt = x[:, lay.i_curt : lay.i_curt + H] if lay.has_curt else zH
    ghi = jnp.asarray(ghi_window)
    ghi = (ghi if ghi.ndim == 2 else ghi[None, :])[:, :H]
    pvc = (
        jnp.asarray(batch.pv_area)[:, None]
        * jnp.asarray(batch.pv_eff)[:, None]
        * jnp.asarray(batch.has_pv)[:, None]
        * ghi
        / 1000.0
    )
    p_pv = pvc * (1.0 - u_curt)
    p_load = s * (
        jnp.asarray(batch.hvac_p_c)[:, None] * cool
        + jnp.asarray(batch.hvac_p_h)[:, None] * heat
        + jnp.asarray(batch.wh_p)[:, None] * wh
    )
    p_grid = p_load + s * (p_ch + p_disch) - s * p_pv
    cost = price_total * p_grid
    e_batt = (x[:, lay.i_eb : lay.i_eb + H + 1] if lay.has_batt
              else jnp.zeros((B, H + 1), dtype=x.dtype))
    return MPCSolution(
        cool=cool, heat=heat, wh=wh, p_ch=p_ch, p_disch=p_disch, u_curt=u_curt,
        p_pv=p_pv, p_load=p_load, p_grid=p_grid, cost=cost,
        temp_in_ev=x[:, lay.i_tin : lay.i_tin + H + 1],
        temp_wh_ev=x[:, lay.i_twh : lay.i_twh + H + 1],
        e_batt=e_batt,
        temp_in1=x[:, lay.i_tin1],
        temp_wh1=x[:, lay.i_twh1],
    )
