"""Fixed-shape QP formulation of the per-home MPC.

The reference builds a CVXPY mixed-integer program per home per timestep and
canonicalizes it at runtime (dragg/mpc_calc.py:291-454).  Here the
(home-type, horizon) template is compiled once into index arrays, and each
timestep only fills a per-home coefficient vector — no runtime
canonicalization, fixed shapes, so the whole community batches on the MXU
(SURVEY.md §2.2, §7 step 2).

Relaxation: the reference's integer duty-cycle variables
(dragg/mpc_calc.py:171-173, bounded [0, sub_subhourly_steps]) are relaxed to
box-constrained continuous duty fractions.  The reference itself divides the
integer counts by ``sub_subhourly_steps`` to report duty fractions
(dragg/mpc_calc.py:497-499), so the LP/QP relaxation is the parity target
(SURVEY.md §2.2); its optimal cost lower-bounds the MILP's.  MEASURED gap
vs the true integer optimum (tools/milp_gap.py, HiGHS-MILP on these exact
matrices, 20-home community): aggregate 2.7–2.8 % at H=8 / 3.4–3.6 % at
H=6 (base-only / mixed), max 5.5 % per home — docs/perf_notes.md round 4.  First-action integerization
(pin the three k=0 duty counts to rounded values, one extra batched
re-solve) restores an implementable applied action with 0/20
comfort-infeasibility; full-horizon rounding is NOT viable (15/20
infeasible).

Problem form (OSQP convention):  minimize (1/2) x'(eps I)x + q'x subject to
l <= A x <= u, with A = [A_eq; I] — equality rows (dynamics + initial
conditions) followed by an identity box block.  Only the box block and RHS
change shape-free per timestep; A_eq has a fixed sparsity whose values are
per-home (static) except the water-draw mixing coefficients, which vary per
timestep (dragg/mpc_calc.py:330-332).

Variable vector per home (superset pv_battery shape shown; in the
superset-shaped batch base homes get zero-width battery/PV via [0,0]
bounds, while the type-bucketed engine drops the absent blocks from the
layout entirely via :class:`HomeTypeSpec`), horizon H:

    cool[H] heat[H] wh[H] p_ch[H] p_disch[H] u_curt[H]
    T_in_ev[H+1] T_wh_ev[H+1] e_batt[H+1] T_in1 T_wh1        (n = 9H + 5)

p_load / p_grid / cost of the reference are affine in these and eliminated;
the objective sum_k discount^k * price[k] * p_grid[k]
(dragg/mpc_calc.py:441-446) becomes a linear q over the controls.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

TAP_TEMP = 15.0  # assumed cold tap water temp, degC (dragg/mpc_calc.py:181)
BIG = jnp.inf


class HomeTypeSpec(NamedTuple):
    """Which optional variable/constraint blocks a home type carries.

    The reference builds a different CVXPY program per home type
    (dragg/mpc_calc.py ``manage_home`` dispatch): base homes have no
    battery or PV blocks at all.  A :class:`QPLayout` built on a spec
    drops the absent blocks from the batched program instead of padding
    them to zero-width [0, 0] boxes — the type-bucketed engine solves
    each bucket at its own (n, m) shape (docs/architecture.md §10).

    Scenario blocks (docs/architecture.md §15; no reference analog —
    the reference knows only the four types above):

    * ``has_ev`` — EV charging: ``p_ev_ch`` columns + ``e_ev`` SOC
      evolution with pin/dynamics rows; departure deadlines and
      away-window availability arrive as per-step box bounds (data, not
      structure — :func:`ev_charge_bounds`).
    * ``has_hp`` — heat-pump HVAC: no layout change at all; the thermal
      coefficients of the HVAC dynamics rows become per-step values
      scaled by the OAT-dependent COP curve (:func:`hp_cops`), exactly
      like the water-mix band.
    * ``has_grid`` — explicit grid-power block for community events
      (DR curtailment caps / outage islanding): ``p_gr`` columns pinned
      to the per-step physical grid power by equality rows, so event
      windows are pure per-step box bounds on ``p_gr``.  Enabled
      engine-wide when the scenario timeline contains any grid event
      (never by a home type), so event-free runs keep the historical
      shapes bit-for-bit.
    """

    has_batt: bool          # p_ch / p_disch / e_batt columns + battery rows
    has_curt: bool          # PV curtailment column (objective-only)
    has_ev: bool = False    # EV charge column + SOC pin/dynamics rows
    has_hp: bool = False    # COP-scaled HVAC thermal coefficients (per-step)
    has_grid: bool = False  # explicit p_grid columns + defining rows


SUPERSET_SPEC = HomeTypeSpec(has_batt=True, has_curt=True)

# Home type name (dragg_tpu.homes.HOME_TYPES) → block spec.
TYPE_SPECS: dict[str, HomeTypeSpec] = {
    "pv_battery": SUPERSET_SPEC,
    "pv_only": HomeTypeSpec(has_batt=False, has_curt=True),
    "battery_only": HomeTypeSpec(has_batt=True, has_curt=False),
    "base": HomeTypeSpec(has_batt=False, has_curt=False),
    "ev": HomeTypeSpec(has_batt=False, has_curt=False, has_ev=True),
    "heat_pump": HomeTypeSpec(has_batt=False, has_curt=False, has_hp=True),
}


def superset_spec_for(type_code) -> HomeTypeSpec:
    """The shape the one-batch (unbucketed) engine pads every home to:
    the HISTORICAL superset (pv_battery — the floor, so every legacy
    population keeps its pre-scenario program byte-for-byte, dead [0, 0]
    battery/PV boxes included) unioned with the scenario blocks of the
    types actually present — EV columns appear only when some home
    carries them, and the heat-pump COP band only when some home scales
    by it."""
    from dragg_tpu.homes import HOME_TYPES

    present = {HOME_TYPES[int(c)]
               for c in np.unique(np.asarray(type_code))}
    specs = [SUPERSET_SPEC] + [TYPE_SPECS[t] for t in present]
    return HomeTypeSpec(*[any(getattr(s, f) for s in specs)
                          for f in HomeTypeSpec._fields])


# Heat-pump COP curve (docs/architecture.md §15): linear in OAT, clipped.
# Heating COP improves with warmer outdoor air; cooling COP degrades as
# the heat-rejection lift grows above HP_COOL_PIVOT.  Resistive homes are
# the COP == 1 special case (the assemble path multiplies by 1 exactly).
HP_COP_MIN = 1.0
HP_COP_MAX = 6.0
HP_COOL_PIVOT = 30.0  # degC: cooling COP = base at this OAT


def hp_cops(oat, cop_base, cop_slope):
    """(cool_cop, heat_cop) for an OAT window — broadcastable: ``oat`` is
    (H,) or (n, H), ``cop_base``/``cop_slope`` are (n,) or (n, 1)."""
    base = jnp.asarray(cop_base)
    slope = jnp.asarray(cop_slope)
    if base.ndim == 1:
        base, slope = base[:, None], slope[:, None]
    oat = jnp.asarray(oat)
    oat2 = oat if oat.ndim == 2 else oat[None, :]
    heat = jnp.clip(base + slope * oat2, HP_COP_MIN, HP_COP_MAX)
    cool = jnp.clip(base + slope * (HP_COOL_PIVOT - oat2),
                    HP_COP_MIN, HP_COP_MAX)
    return cool, heat


def ev_charge_bounds(hod_ctrl, hod_state, batch, e_ev_init, dt, eps=1e-3):
    """Per-step EV box data for one assembled timestep (shared by the
    engine's traced step and the parity fixtures, so the two cannot
    drift): ``(avail, floor)``, both (n, H).

    * ``avail[k]`` — 1 when the vehicle is home (chargeable) at control
      step k: hour-of-day outside the [away_start, away_end) window.
    * ``floor[k]`` — lower bound on ``e_ev[k+1]``: during away hours the
      SOC must hold the departure target (charging completed BEFORE
      departure — the deadline constraint), relaxed to the maximum
      physically reachable SOC (init + cumulative charge capacity along
      the availability mask, minus an fp32 slack) so a home that starts
      behind schedule charges flat-out instead of going infeasible.

    Non-EV homes read all-zero floors and all-zero availability masks
    never bind (their rate bound is already [0, 0])."""
    is_ev = jnp.asarray(batch.is_ev)[:, None]
    a_start = jnp.asarray(batch.ev_away_start)[:, None]
    a_end = jnp.asarray(batch.ev_away_end)[:, None]
    hod_c = jnp.asarray(hod_ctrl)[None, :]
    hod_s = jnp.asarray(hod_state)[None, :]
    away_c = (hod_c >= a_start) & (hod_c < a_end)
    avail = is_ev * (1.0 - away_c.astype(jnp.float32))
    rate = jnp.asarray(batch.ev_rate)[:, None]
    eff = jnp.asarray(batch.ev_ch_eff)[:, None]
    reach = jnp.asarray(e_ev_init)[:, None] + jnp.cumsum(
        avail * rate * eff / dt, axis=1)
    away_s = (hod_s >= a_start) & (hod_s < a_end)
    target = jnp.asarray(batch.ev_target_kwh)[:, None]
    floor = jnp.where(away_s & (is_ev > 0),
                      jnp.minimum(target, reach - eps), 0.0)
    return avail, jnp.maximum(floor, 0.0)


class QPLayout:
    """Index bookkeeping for the per-home variable vector and equality rows.

    Default spec is the superset (pv_battery) shape, whose indices are
    identical to the historical fixed layout (n = 9H + 5, m_eq = 3H + 5).
    Under a reduced :class:`HomeTypeSpec` the absent blocks' indices are
    ``None`` so any unguarded use fails loudly instead of aliasing a live
    column."""

    def __init__(self, horizon: int, spec: HomeTypeSpec = SUPERSET_SPEC):
        H = int(horizon)
        self.H = H
        self.spec = spec
        self.has_batt = bool(spec.has_batt)
        self.has_curt = bool(spec.has_curt)
        self.has_ev = bool(spec.has_ev)
        self.has_hp = bool(spec.has_hp)
        self.has_grid = bool(spec.has_grid)
        i = 0
        self.i_cool = i; i += H          # noqa: E702 — index table reads as one block
        self.i_heat = i; i += H          # noqa: E702
        self.i_wh = i; i += H            # noqa: E702
        if self.has_batt:
            self.i_pch = i; i += H       # noqa: E702
            self.i_pd = i; i += H        # noqa: E702
        else:
            self.i_pch = self.i_pd = None
        if self.has_ev:
            self.i_evch = i; i += H      # noqa: E702
        else:
            self.i_evch = None
        if self.has_curt:
            self.i_curt = i; i += H      # noqa: E702
        else:
            self.i_curt = None
        if self.has_grid:
            self.i_pgr = i; i += H       # noqa: E702
        else:
            self.i_pgr = None
        self.i_tin = i; i += H + 1       # noqa: E702
        self.i_twh = i; i += H + 1       # noqa: E702
        if self.has_batt:
            self.i_eb = i; i += H + 1    # noqa: E702
        else:
            self.i_eb = None
        if self.has_ev:
            self.i_eev = i; i += H + 1   # noqa: E702
        else:
            self.i_eev = None
        self.i_tin1 = i; i += 1          # noqa: E702
        self.i_twh1 = i; i += 1          # noqa: E702
        self.n = i
        # Equality rows.
        r = 0
        self.r_tin0 = r; r += 1          # noqa: E702
        self.r_tind = r; r += H          # noqa: E702  (H rows)
        self.r_twh0 = r; r += 1          # noqa: E702
        self.r_twhd = r; r += H          # noqa: E702  (H rows)
        self.r_tin1 = r; r += 1          # noqa: E702
        self.r_twh1 = r; r += 1          # noqa: E702
        if self.has_batt:
            self.r_eb0 = r; r += 1       # noqa: E702
            self.r_ebd = r; r += H       # noqa: E702  (H rows)
        else:
            self.r_eb0 = self.r_ebd = None
        if self.has_ev:
            self.r_eev0 = r; r += 1      # noqa: E702
            self.r_eevd = r; r += H      # noqa: E702  (H rows)
        else:
            self.r_eev0 = self.r_eevd = None
        if self.has_grid:
            self.r_pgr = r; r += H       # noqa: E702  (H rows)
        else:
            self.r_pgr = None
        self.m_eq = r
        self.m = self.m_eq + self.n


class SparsePattern(NamedTuple):
    """Static gather-padded sparsity of A_eq, shared across homes.

    The dynamics matrix has ≤``K`` nonzeros per row and ≤``Kc`` per column
    (banded RC recurrences), so both matvec directions become pure gathers +
    elementwise sums — no scatter in the hot loop, which matters on TPU.
    ``*_src`` index the flat nnz axis (-1 → empty slot, masked to 0).

    All index structures are nested int tuples, so the pattern is hashable
    and can be a ``jit`` static argument.
    """

    m: int                    # equality rows
    n: int                    # variables
    nnz: int
    rows: tuple               # (nnz,) row of each entry
    cols: tuple               # (nnz,) col of each entry
    row_cols: tuple           # (m, K) column index per row slot (0-padded)
    row_src: tuple            # (m, K) nnz index per row slot (-1-padded)
    col_rows: tuple           # (n, Kc) row index per col slot (0-padded)
    col_src: tuple            # (n, Kc) nnz index per col slot (-1-padded)


def _tt(a: np.ndarray) -> tuple:
    """ndarray → nested tuple (hashable)."""
    if a.ndim == 1:
        return tuple(int(v) for v in a)
    return tuple(tuple(int(v) for v in row) for row in a)


def _build_pattern(rows: np.ndarray, cols: np.ndarray, m: int, n: int) -> SparsePattern:
    nnz = len(rows)
    K = int(np.bincount(rows, minlength=m).max())
    Kc = int(np.bincount(cols, minlength=n).max())
    row_cols = np.zeros((m, K), dtype=np.int32)
    row_src = np.full((m, K), -1, dtype=np.int32)
    col_rows = np.zeros((n, Kc), dtype=np.int32)
    col_src = np.full((n, Kc), -1, dtype=np.int32)
    rfill = np.zeros(m, dtype=np.int64)
    cfill = np.zeros(n, dtype=np.int64)
    for e in range(nnz):
        r, c = int(rows[e]), int(cols[e])
        row_cols[r, rfill[r]] = c
        row_src[r, rfill[r]] = e
        rfill[r] += 1
        col_rows[c, cfill[c]] = r
        col_src[c, cfill[c]] = e
        cfill[c] += 1
    return SparsePattern(m=m, n=n, nnz=nnz, rows=_tt(rows), cols=_tt(cols),
                         row_cols=_tt(row_cols), row_src=_tt(row_src),
                         col_rows=_tt(col_rows), col_src=_tt(col_src))


class SchurStructure(NamedTuple):
    """Static structure for forming S = A D⁻¹ Aᵀ directly from the sparse
    values, without materializing the dense (B, m, n) A (the round-1 scale
    blocker: at 100k homes × H=48 the dense A alone was ~26 GB).

    S_ij = Σ_k Dinv_k · A_ik · A_jk — the sum runs over columns k shared by
    rows i and j.  For the banded RC pattern (≤4 nnz/row·col) the number of
    (i, j, k) triples is O(m), so S formation drops from 2Bm²n FLOPs + Bmn
    memory to a few gathers over (B, n_s, P) with n_s = nnz(S), P = max
    shared columns per (i, j).
    """

    n_s: int          # number of stored S entries (full matrix, both triangles)
    P: int            # max (e1, e2) pairs per S entry
    s_rows: tuple     # (n_s,) row of each S entry
    s_cols: tuple     # (n_s,)
    e1: tuple         # (n_s, P) first-factor nnz index (0-padded)
    e2: tuple         # (n_s, P) second-factor nnz index (0-padded)
    kcol: tuple       # (n_s, P) shared column index for the Dinv gather (0-padded)
    mask: tuple       # (n_s, P) 1/0 valid-slot mask


def build_schur_structure(pat: SparsePattern) -> SchurStructure:
    """Precompute the (i, j, k) triple lists of S = A D⁻¹ Aᵀ for a sparse
    pattern.  Cost is O(Σ_k c_k²) with c_k the column counts — tiny for the
    banded MPC pattern, and computed once per (horizon, home-type) shape."""
    from collections import defaultdict

    rows = np.asarray(pat.rows)
    cols = np.asarray(pat.cols)
    by_col: dict[int, list[int]] = defaultdict(list)
    for e in range(pat.nnz):
        by_col[int(cols[e])].append(e)
    pairs: dict[tuple[int, int], list[tuple[int, int, int]]] = defaultdict(list)
    for k, es in by_col.items():
        for a in es:
            for bb in es:
                pairs[(int(rows[a]), int(rows[bb]))].append((a, bb, k))
    n_s = len(pairs)
    P = max(len(v) for v in pairs.values())
    s_rows = np.zeros(n_s, dtype=np.int32)
    s_cols = np.zeros(n_s, dtype=np.int32)
    e1 = np.zeros((n_s, P), dtype=np.int32)
    e2 = np.zeros((n_s, P), dtype=np.int32)
    kcol = np.zeros((n_s, P), dtype=np.int32)
    mask = np.zeros((n_s, P), dtype=np.int32)
    for idx, ((i, j), lst) in enumerate(sorted(pairs.items())):
        s_rows[idx] = i
        s_cols[idx] = j
        for p, (a, bb, k) in enumerate(lst):
            e1[idx, p] = a
            e2[idx, p] = bb
            kcol[idx, p] = k
            mask[idx, p] = 1
    return SchurStructure(n_s=n_s, P=P, s_rows=_tt(s_rows), s_cols=_tt(s_cols),
                          e1=_tt(e1), e2=_tt(e2), kcol=_tt(kcol), mask=_tt(mask))


def schur_contrib(ss: SchurStructure, vals_s, Dinv) -> jnp.ndarray:
    """Per-entry values of S = Â D⁻¹ Âᵀ ((B, n_s), aligned with
    ss.s_rows/s_cols) from the precomputed triple lists."""
    e1 = jnp.asarray(ss.e1)
    e2 = jnp.asarray(ss.e2)
    kcol = jnp.asarray(ss.kcol)
    mask = jnp.asarray(ss.mask, dtype=vals_s.dtype)
    return jnp.sum(
        vals_s[:, e1] * vals_s[:, e2] * Dinv[:, kcol] * mask[None], axis=2
    )


def scatter_schur(ss: SchurStructure, m: int, contrib) -> jnp.ndarray:
    """Schur entry values (B, n_s) → dense (B, m, m)."""
    s_rows = np.asarray(ss.s_rows)
    s_cols = np.asarray(ss.s_cols)
    B = contrib.shape[0]
    return jnp.zeros((B, m, m), dtype=contrib.dtype).at[:, s_rows, s_cols].set(contrib)


def form_schur_sparse(ss: SchurStructure, m: int, vals_s, Dinv) -> jnp.ndarray:
    """Form the dense (B, m, m) S = Â D⁻¹ Âᵀ from sparse values via the
    precomputed triple lists — no dense A anywhere."""
    return scatter_schur(ss, m, schur_contrib(ss, vals_s, Dinv))


def densify_A(pat: SparsePattern, vals) -> jnp.ndarray:
    """Materialize the dense (B, m, n) A_eq from sparse values (tests,
    CPU-reference cross-checks, Schur factorization)."""
    rows = np.asarray(pat.rows)
    cols = np.asarray(pat.cols)
    return jnp.zeros((vals.shape[0], pat.m, pat.n), dtype=vals.dtype).at[
        :, rows, cols
    ].add(vals)


_NO_POS = np.zeros(0, dtype=np.int64)  # empty per-step-band position sentinel


class HomeQPStatic(NamedTuple):
    """Per-home static pieces: the (row, col) sparsity (shared) plus the
    per-home coefficient values split into static entries and the indices of
    the timestep-varying bands (water mix; under scenarios also the
    heat-pump COP thermal coefficients and the grid rows' PV terms)."""

    rows: np.ndarray          # (nnz,) shared across homes
    cols: np.ndarray          # (nnz,)
    vals: jnp.ndarray         # (n_homes, nnz) — static values; per-step bands filled at assemble
    whmix_pos: np.ndarray     # (H,) positions in the nnz axis of the wh-mix coefficients
    pattern: SparsePattern    # gather-padded sparsity for the solver hot loop
    a_in: jnp.ndarray         # (n_homes,) 3600 / (C * dt)
    a_wh: jnp.ndarray         # (n_homes,) 3600 / (wh_c * dt)
    kin: jnp.ndarray          # (n_homes,) 1 - a_in / R
    kwh: jnp.ndarray          # (n_homes,) 1 - a_wh / wh_r
    awr: jnp.ndarray          # (n_homes,) a_wh / wh_r
    # Heat-pump COP band (spec.has_hp): positions of the HVAC thermal
    # coefficients — entries [0:H] are rows r_tind+k (OAT at t+k+1), entry
    # [H] is the one-step r_tin1 row (OAT at t+1).  Empty when absent —
    # the assemble path compiles the band out entirely (byte-identity for
    # legacy batches).
    hp_cool_pos: np.ndarray = _NO_POS   # (H+1,) cool-duty thermal entries
    hp_heat_pos: np.ndarray = _NO_POS   # (H+1,) heat-duty thermal entries
    # Grid rows' PV terms (spec.has_grid and spec.has_curt): the u_curt
    # coefficient of each r_pgr+k row is −pvc[k] (GHI-dependent, per step).
    gridpv_pos: np.ndarray = _NO_POS    # (H,)


def build_qp_static(batch, horizon: int, dt: int,
                    spec: HomeTypeSpec = SUPERSET_SPEC) -> HomeQPStatic:
    """Precompute the equality-constraint sparsity + per-home coefficients.

    ``batch`` is a HomeBatch (arrays may be numpy or jax).  Row/col index
    arrays are identical for every home; values are per-home.  ``spec``
    selects the block layout — a battery-free spec drops the SoC pin +
    dynamics rows and their nnz entirely (type-bucketed engine).
    """
    lay = QPLayout(horizon, spec)
    H = lay.H
    n_homes = batch.hvac_r.shape[0]

    a_in = 3600.0 / (np.asarray(batch.hvac_c) * dt)
    a_wh = 3600.0 / (np.asarray(batch.wh_c) * dt)
    R = np.asarray(batch.hvac_r)
    wh_r = np.asarray(batch.wh_r)
    kin = 1.0 - a_in / R
    kwh = 1.0 - a_wh / wh_r
    awr = a_wh / wh_r
    pc = np.asarray(batch.hvac_p_c)
    ph = np.asarray(batch.hvac_p_h)
    pwh = np.asarray(batch.wh_p)
    che = np.asarray(batch.batt_ch_eff)
    dse = np.asarray(batch.batt_disch_eff)

    rows, cols, vals = [], [], []
    whmix_pos = np.zeros(H, dtype=np.int64)
    hp_cool_pos = (np.zeros(H + 1, dtype=np.int64) if lay.has_hp
                   else _NO_POS)
    hp_heat_pos = (np.zeros(H + 1, dtype=np.int64) if lay.has_hp
                   else _NO_POS)
    gridpv_pos = (np.zeros(H, dtype=np.int64)
                  if lay.has_grid and lay.has_curt else _NO_POS)

    def add(r, c, v):
        rows.append(r)
        cols.append(c)
        vals.append(np.broadcast_to(v, (n_homes,)).astype(np.float64))
        return len(rows) - 1

    ks = np.arange(H)
    # Indoor temp: T[0] pin + dynamics (dragg/mpc_calc.py:313-317).  Under
    # spec.has_hp the duty coefficients are COP-scaled per step at
    # assemble time (positions recorded); the static values seeded here
    # are the resistive COP == 1 case.
    add(lay.r_tin0, lay.i_tin, 1.0)
    for k in range(H):
        add(lay.r_tind + k, lay.i_tin + k + 1, 1.0)
        add(lay.r_tind + k, lay.i_tin + k, -kin)
        pos_c = add(lay.r_tind + k, lay.i_cool + k, a_in * pc)
        pos_h = add(lay.r_tind + k, lay.i_heat + k, -a_in * ph)
        if lay.has_hp:
            hp_cool_pos[k] = pos_c
            hp_heat_pos[k] = pos_h
    # WH temp: T[0] pin + dynamics with draw mixing (dragg/mpc_calc.py:329-332).
    add(lay.r_twh0, lay.i_twh, 1.0)
    for k in range(H):
        add(lay.r_twhd + k, lay.i_twh + k + 1, 1.0)
        whmix_pos[k] = add(lay.r_twhd + k, lay.i_twh + k, 0.0)  # -rem[k+1]*kwh, per step
        add(lay.r_twhd + k, lay.i_tin + k + 1, -awr)
        add(lay.r_twhd + k, lay.i_wh + k, -a_wh * pwh)
    # One-step deterministic temps (dragg/mpc_calc.py:321-324,336-338).
    add(lay.r_tin1, lay.i_tin1, 1.0)
    pos_c1 = add(lay.r_tin1, lay.i_cool, a_in * pc)
    pos_h1 = add(lay.r_tin1, lay.i_heat, -a_in * ph)
    if lay.has_hp:
        hp_cool_pos[H] = pos_c1
        hp_heat_pos[H] = pos_h1
    add(lay.r_twh1, lay.i_twh1, 1.0)
    add(lay.r_twh1, lay.i_tin + 1, -awr)
    add(lay.r_twh1, lay.i_wh, -a_wh * pwh)
    # Battery SoC: pin + dynamics (dragg/mpc_calc.py:363-372).
    if lay.has_batt:
        add(lay.r_eb0, lay.i_eb, 1.0)
        for k in range(H):
            add(lay.r_ebd + k, lay.i_eb + k + 1, 1.0)
            add(lay.r_ebd + k, lay.i_eb + k, -1.0)
            add(lay.r_ebd + k, lay.i_pch + k, -che / dt)
            add(lay.r_ebd + k, lay.i_pd + k, -1.0 / (dse * dt))
    # EV SOC: pin + charge-only dynamics (battery row structure minus the
    # discharge term; docs/architecture.md §15).  Deadlines / availability
    # are per-step BOX data (ev_charge_bounds), never structure.
    if lay.has_ev:
        evche = np.asarray(batch.ev_ch_eff)
        add(lay.r_eev0, lay.i_eev, 1.0)
        for k in range(H):
            add(lay.r_eevd + k, lay.i_eev + k + 1, 1.0)
            add(lay.r_eevd + k, lay.i_eev + k, -1.0)
            add(lay.r_eevd + k, lay.i_evch + k, -evche / dt)
    # Explicit grid power (community events): p_gr[k] equals the PHYSICAL
    # kW grid draw — p_gr − Σ load/storage terms − pvc[k]·u_curt = −pvc[k]
    # (the pvc entries and RHS are GHI-dependent, filled per step), so DR
    # caps and outage islanding are pure per-step box bounds on p_gr.
    if lay.has_grid:
        for k in range(H):
            add(lay.r_pgr + k, lay.i_pgr + k, 1.0)
            add(lay.r_pgr + k, lay.i_cool + k, -pc)
            add(lay.r_pgr + k, lay.i_heat + k, -ph)
            add(lay.r_pgr + k, lay.i_wh + k, -pwh)
            if lay.has_batt:
                add(lay.r_pgr + k, lay.i_pch + k, -1.0)
                add(lay.r_pgr + k, lay.i_pd + k, -1.0)
            if lay.has_ev:
                add(lay.r_pgr + k, lay.i_evch + k, -1.0)
            if lay.has_curt:
                gridpv_pos[k] = add(lay.r_pgr + k, lay.i_curt + k, 0.0)
    del ks

    rows_np = np.array(rows, dtype=np.int64)
    cols_np = np.array(cols, dtype=np.int64)
    return HomeQPStatic(
        rows=rows_np,
        cols=cols_np,
        vals=jnp.asarray(np.stack(vals, axis=1)),
        whmix_pos=whmix_pos,
        pattern=_build_pattern(rows_np, cols_np, lay.m_eq, lay.n),
        a_in=jnp.asarray(a_in),
        a_wh=jnp.asarray(a_wh),
        kin=jnp.asarray(kin),
        kwh=jnp.asarray(kwh),
        awr=jnp.asarray(awr),
        hp_cool_pos=hp_cool_pos,
        hp_heat_pos=hp_heat_pos,
        gridpv_pos=gridpv_pos,
    )


class QPStep(NamedTuple):
    """Everything the ADMM solver needs for one timestep, batched over homes.
    A_eq is carried sparsely (values on the shared pattern); use
    :func:`densify_A` where a dense matrix is needed."""

    vals: jnp.ndarray     # (n_homes, nnz) A_eq values on the static pattern
    b_eq: jnp.ndarray     # (n_homes, m_eq)
    l_box: jnp.ndarray    # (n_homes, n)
    u_box: jnp.ndarray    # (n_homes, n)
    q: jnp.ndarray        # (n_homes, n) unscaled (admm_solve does its own cost scaling)


def assemble_qp_step(
    static: HomeQPStatic,
    lay: QPLayout,
    batch,
    *,
    oat_window,        # (H+1,) environment slice — oat_window[k] = OAT at
                       # t+k; (n_homes, H+1) under fleet weather offsets
                       # (per-home windows, engine._prepare)
    ghi_window,        # (H+1,) GHI slice — ghi_window[k] = GHI at t+k;
                       # (n_homes, H+1) under fleet weather offsets
    price_total,       # (n_homes, H) discounting NOT applied; rp + tou
    draw_frac,         # (n_homes, H+1) draw fractions for this step (index 0 = current)
    temp_in_init,      # (n_homes,)
    temp_wh_init,      # (n_homes,) AFTER draw mixing
    e_batt_init,       # (n_homes,)
    cool_cap,          # (n_homes,) seasonal duty cap (0 or s)
    heat_cap,          # (n_homes,)
    wh_cap: float,     # s
    discount,          # scalar
    e_ev_init=None,    # (n_homes,) EV SOC kWh (required when lay.has_ev)
    ev_avail=None,     # (n_homes, H) 0/1 charge availability (has_ev;
                       # None = always available)
    ev_floor=None,     # (n_homes, H) e_ev[k+1] lower bound, kWh (has_ev;
                       # None = 0 — see ev_charge_bounds)
    grid_cap=None,     # (n_homes, H) p_gr upper bound, kW (has_grid;
                       # None = +inf — no event this window)
    grid_floor=None,   # (n_homes, H) p_gr lower bound, kW (has_grid;
                       # None = -inf)
    comfort_relax=None,  # (n_homes, H) degC indoor-band widening for the
                         # bounded T_in entries (DR/outage comfort relief)
) -> QPStep:
    """Fill the per-timestep QP: A_eq values (water-mix band; HP COP band
    and grid-row PV terms under scenario specs), RHS, box bounds (seasonal
    HVAC gating, dragg/mpc_calc.py:298-309; EV availability/deadline and
    event windows as per-step data), and the linear objective q (discounted
    price on grid power, dragg/mpc_calc.py:441-446).
    """
    H = lay.H
    n_homes = static.vals.shape[0]
    dtype = jnp.float32

    rem = 1.0 - draw_frac  # remainder_frac (dragg/mpc_calc.py:202-204)
    whmix_vals = -(rem[:, 1:] * static.kwh[:, None])  # (n_homes, H)
    vals64 = static.vals.at[:, static.whmix_pos].set(whmix_vals)
    oat_hp = jnp.asarray(oat_window)
    oat_hp = oat_hp if oat_hp.ndim == 2 else oat_hp[None, :]
    if len(static.hp_cool_pos):
        # Heat-pump COP band: thermal coefficients of the HVAC dynamics
        # rows scale by COP(OAT) per step.  Resistive homes in the same
        # batch multiply by exactly 1.0 — their entries are bit-identical
        # to the static seed values.
        is_hp = jnp.asarray(batch.is_hp)[:, None]
        cop_c, cop_h = hp_cops(oat_hp[:, 1:H + 1], batch.hp_cop_base,
                               batch.hp_cop_slope)
        cop_c = 1.0 + is_hp * (cop_c - 1.0)
        cop_h = 1.0 + is_hp * (cop_h - 1.0)
        # Entries [0:H] are rows r_tind+k (OAT at t+k+1); entry [H] is the
        # one-step r_tin1 row, which shares k=0's OAT (t+1).  The band
        # SCALES the seeded static coefficients (a_in·P with the right
        # signs) rather than recomputing them, so resistive homes'
        # COP == 1 entries stay bit-identical to the legacy matrices.
        cop_c_full = jnp.concatenate([cop_c, cop_c[:, :1]], axis=1)
        cop_h_full = jnp.concatenate([cop_h, cop_h[:, :1]], axis=1)
        vals64 = vals64.at[:, static.hp_cool_pos].multiply(cop_c_full)
        vals64 = vals64.at[:, static.hp_heat_pos].multiply(cop_h_full)
    # Grid rows' PV terms (−pvc[k] on u_curt; the matching RHS lands below).
    pvc_grid = None
    if lay.has_grid and lay.has_curt:
        ghi_g = jnp.asarray(ghi_window)
        ghi_g = ghi_g if ghi_g.ndim == 2 else ghi_g[None, :]
        pvc_grid = (
            jnp.asarray(batch.pv_area)[:, None]
            * jnp.asarray(batch.pv_eff)[:, None]
            * jnp.asarray(batch.has_pv)[:, None]
            * ghi_g[:, :H] / 1000.0
        )
        vals64 = vals64.at[:, static.gridpv_pos].set(-pvc_grid)
    vals = vals64.astype(dtype)

    oat = jnp.asarray(oat_window)
    # Per-home windows (fleet weather offsets) arrive 2-D; the shared
    # scalar window broadcasts through the same (., H) row writes.
    oat = oat if oat.ndim == 2 else oat[None, :]
    b = jnp.zeros((n_homes, lay.m_eq), dtype=dtype)
    b = b.at[:, lay.r_tin0].set(temp_in_init)
    b = b.at[:, lay.r_tind : lay.r_tind + H].set(
        (static.a_in[:, None] / jnp.asarray(batch.hvac_r)[:, None]) * oat[:, 1 : H + 1]
    )
    b = b.at[:, lay.r_twh0].set(temp_wh_init)
    b = b.at[:, lay.r_twhd : lay.r_twhd + H].set(draw_frac[:, 1:] * TAP_TEMP * static.kwh[:, None])
    b = b.at[:, lay.r_tin1].set(
        temp_in_init * static.kin + static.a_in / jnp.asarray(batch.hvac_r) * oat[:, 1]
    )
    b = b.at[:, lay.r_twh1].set(temp_wh_init * static.kwh)
    if lay.has_batt:
        b = b.at[:, lay.r_eb0].set(e_batt_init)
        # battery dynamics rows rhs = 0 already
    if lay.has_ev:
        ev0 = (jnp.zeros((n_homes,), dtype) if e_ev_init is None
               else jnp.asarray(e_ev_init).astype(dtype))
        b = b.at[:, lay.r_eev0].set(ev0)
    if pvc_grid is not None:
        # p_gr − (loads/storage) − pvc·u_curt = −pvc (see build_qp_static).
        b = b.at[:, lay.r_pgr:lay.r_pgr + H].set(-pvc_grid.astype(dtype))

    inf = jnp.full((n_homes,), BIG, dtype=dtype)
    zeros = jnp.zeros((n_homes,), dtype=dtype)
    l = jnp.zeros((n_homes, lay.n), dtype=dtype)
    u = jnp.zeros((n_homes, lay.n), dtype=dtype)

    def seg(lo, hi, i0, length):
        nonlocal l, u
        l = l.at[:, i0 : i0 + length].set(jnp.broadcast_to(lo[:, None], (n_homes, length)))
        u = u.at[:, i0 : i0 + length].set(jnp.broadcast_to(hi[:, None], (n_homes, length)))

    seg(zeros, cool_cap, lay.i_cool, H)
    seg(zeros, heat_cap, lay.i_heat, H)
    seg(zeros, jnp.full((n_homes,), wh_cap, dtype=dtype), lay.i_wh, H)
    if lay.has_batt:
        rate = jnp.asarray(batch.batt_max_rate) * jnp.asarray(batch.has_batt)
        seg(zeros, rate, lay.i_pch, H)
        seg(-rate, zeros, lay.i_pd, H)
    if lay.has_ev:
        ev_rate = (jnp.asarray(batch.ev_rate)
                   * jnp.asarray(batch.is_ev)).astype(dtype)[:, None]
        ev_hi = (ev_rate * jnp.asarray(ev_avail).astype(dtype)
                 if ev_avail is not None
                 else jnp.broadcast_to(ev_rate, (n_homes, H)))
        u = u.at[:, lay.i_evch:lay.i_evch + H].set(ev_hi)
        # (lower bound stays the zero init — charge-only)
    if lay.has_curt:
        seg(zeros, jnp.ones((n_homes,), dtype=dtype), lay.i_curt, H)
    if lay.has_grid:
        g_lo = (jnp.asarray(grid_floor).astype(dtype)
                if grid_floor is not None
                else jnp.full((n_homes, H), -BIG, dtype))
        g_hi = (jnp.asarray(grid_cap).astype(dtype)
                if grid_cap is not None
                else jnp.full((n_homes, H), BIG, dtype))
        l = l.at[:, lay.i_pgr:lay.i_pgr + H].set(g_lo)
        u = u.at[:, lay.i_pgr:lay.i_pgr + H].set(g_hi)
    # T_in_ev[0] is pinned by equality; bounds apply to [1:] only
    # (dragg/mpc_calc.py:318-319).  DR / outage windows widen the band by
    # the per-step comfort_relax (docs/architecture.md §15).
    seg(-inf, inf, lay.i_tin, 1)
    tin_lo = jnp.asarray(batch.temp_in_min).astype(dtype)[:, None]
    tin_hi = jnp.asarray(batch.temp_in_max).astype(dtype)[:, None]
    relax = (jnp.asarray(comfort_relax).astype(dtype)
             if comfort_relax is not None else None)
    if relax is not None:
        l = l.at[:, lay.i_tin + 1:lay.i_tin + 1 + H].set(tin_lo - relax)
        u = u.at[:, lay.i_tin + 1:lay.i_tin + 1 + H].set(tin_hi + relax)
    else:
        seg(jnp.asarray(batch.temp_in_min).astype(dtype), jnp.asarray(batch.temp_in_max).astype(dtype), lay.i_tin + 1, H)
    # T_wh_ev bounds apply to ALL H+1 entries including the pinned index 0
    # (dragg/mpc_calc.py:333-334) — an out-of-band initial WH temp makes the
    # problem infeasible, which routes the home to the fallback controller
    # exactly as in the reference.
    seg(jnp.asarray(batch.temp_wh_min).astype(dtype), jnp.asarray(batch.temp_wh_max).astype(dtype), lay.i_twh, H + 1)
    if lay.has_batt:
        seg(-inf, inf, lay.i_eb, 1)
        cap_min = jnp.asarray(batch.batt_cap_min).astype(dtype)
        cap_max = jnp.asarray(batch.batt_cap_max).astype(dtype)
        seg(cap_min, cap_max, lay.i_eb + 1, H)
    if lay.has_ev:
        seg(-inf, inf, lay.i_eev, 1)  # e_ev[0] pinned by equality
        ev_cap = (jnp.asarray(batch.ev_cap)
                  * jnp.asarray(batch.is_ev)).astype(dtype)[:, None]
        ev_lo = (jnp.asarray(ev_floor).astype(dtype)
                 if ev_floor is not None
                 else jnp.zeros((n_homes, H), dtype))
        l = l.at[:, lay.i_eev + 1:lay.i_eev + 1 + H].set(ev_lo)
        u = u.at[:, lay.i_eev + 1:lay.i_eev + 1 + H].set(
            jnp.broadcast_to(ev_cap, (n_homes, H)))
    if relax is not None:
        l = l.at[:, lay.i_tin1].set(tin_lo[:, 0] - relax[:, 0])
        u = u.at[:, lay.i_tin1].set(tin_hi[:, 0] + relax[:, 0])
    else:
        seg(jnp.asarray(batch.temp_in_min).astype(dtype), jnp.asarray(batch.temp_in_max).astype(dtype), lay.i_tin1, 1)
    seg(jnp.asarray(batch.temp_wh_min).astype(dtype), jnp.asarray(batch.temp_wh_max).astype(dtype), lay.i_twh1, 1)

    # Objective: sum_k w[k] * price[k] * p_grid[k], p_grid affine in controls
    # (dragg/mpc_calc.py:342,387-432,441-446).  s cancels: p_load contributes
    # s * (P/s) * duty per control unit.
    s = float(wh_cap)
    w = jnp.power(jnp.asarray(discount, dtype=dtype), jnp.arange(H, dtype=dtype))
    wp = (w[None, :] * price_total.astype(dtype))  # (n_homes, H)
    q = jnp.zeros((n_homes, lay.n), dtype=dtype)
    q = q.at[:, lay.i_cool : lay.i_cool + H].set(wp * (s * jnp.asarray(batch.hvac_p_c)[:, None]).astype(dtype))
    q = q.at[:, lay.i_heat : lay.i_heat + H].set(wp * (s * jnp.asarray(batch.hvac_p_h)[:, None]).astype(dtype))
    q = q.at[:, lay.i_wh : lay.i_wh + H].set(wp * (s * jnp.asarray(batch.wh_p)[:, None]).astype(dtype))
    if lay.has_batt:
        q = q.at[:, lay.i_pch : lay.i_pch + H].set(wp * s)
        q = q.at[:, lay.i_pd : lay.i_pd + H].set(wp * s)
    if lay.has_ev:
        # EV charging is paid grid energy, same convention as battery
        # charge (p_grid gains s·p_ev_ch — recover_solution).
        q = q.at[:, lay.i_evch : lay.i_evch + H].set(wp * s)
    if lay.has_curt:
        # PV: p_grid -= s * pvc[k] * (1 - u_curt[k]); the constant term is
        # dropped from q (it shifts the objective, not the argmin) and the
        # u_curt coefficient is +w*price*s*pvc (dragg/mpc_calc.py:380-385,410-432).
        ghi = jnp.asarray(ghi_window).astype(dtype)
        ghi = ghi if ghi.ndim == 2 else ghi[None, :]
        pvc = (
            jnp.asarray(batch.pv_area)[:, None]
            * jnp.asarray(batch.pv_eff)[:, None]
            * jnp.asarray(batch.has_pv)[:, None]
            * ghi[:, :H]
            / 1000.0
        ).astype(dtype)
        q = q.at[:, lay.i_curt : lay.i_curt + H].set(wp * s * pvc)
    return QPStep(vals=vals, b_eq=b, l_box=l, u_box=u, q=q)


def shift_warm_start(x, lay: QPLayout):
    """Shift a stacked variable (or box-dual) vector one step along the
    horizon for warm-starting the NEXT timestep's solve: the previous plan's
    entry for time t+k+1 seeds the new problem's entry for t+k (receding
    horizon), with the final entry repeated.  Duty plans are bang-bang-like,
    so the unshifted vector mis-seeds every switching time — measured: the
    shift moves the warm-started mass-convergence point from ~200 to ~150
    ADMM iterations on a 256-home steady-state step."""
    H = lay.H

    def sh(v, i0, L):
        return v.at[:, i0 : i0 + L - 1].set(v[:, i0 + 1 : i0 + L])

    for i0 in (lay.i_cool, lay.i_heat, lay.i_wh, lay.i_pch, lay.i_pd,
               lay.i_evch, lay.i_curt, lay.i_pgr):
        if i0 is not None:
            x = sh(x, i0, H)
    for i0, L in ((lay.i_tin, H + 1), (lay.i_twh, H + 1), (lay.i_eb, H + 1),
                  (lay.i_eev, H + 1)):
        if i0 is not None:
            x = sh(x, i0, L)
    return x


class MPCSolution(NamedTuple):
    """Recovered per-home horizon series (raw duty units, kW, degC, kWh)."""

    cool: jnp.ndarray      # (n_homes, H) raw duty [0, s]
    heat: jnp.ndarray
    wh: jnp.ndarray
    p_ch: jnp.ndarray
    p_disch: jnp.ndarray
    u_curt: jnp.ndarray
    p_pv: jnp.ndarray      # (n_homes, H)
    p_load: jnp.ndarray    # (n_homes, H) total community-units load (pre /s)
    p_grid: jnp.ndarray    # (n_homes, H)
    cost: jnp.ndarray      # (n_homes, H) price * p_grid (undiscounted, parity
                           # with dragg/mpc_calc.py:444)
    temp_in_ev: jnp.ndarray  # (n_homes, H+1)
    temp_wh_ev: jnp.ndarray
    e_batt: jnp.ndarray      # (n_homes, H+1)
    temp_in1: jnp.ndarray    # (n_homes,) one-step deterministic indoor temp
    temp_wh1: jnp.ndarray
    p_ev_ch: jnp.ndarray = None   # (n_homes, H) EV charge kW (zeros when absent)
    e_ev: jnp.ndarray = None      # (n_homes, H+1) EV SOC kWh (zeros when absent)


def recover_solution(x, lay: QPLayout, batch, ghi_window, price_total, s: float) -> MPCSolution:
    """Extract physical series from the stacked variable vector and rebuild
    the eliminated p_load / p_pv / p_grid / cost
    (dragg/mpc_calc.py:342,380-432,444).

    Absent blocks (a reduced :class:`HomeTypeSpec`) come back as exact
    zeros — identical to the superset solve, whose [0, 0] boxes clip the
    dead variables to 0 in the returned (box-projected) primal."""
    H = lay.H
    B = x.shape[0]
    zH = jnp.zeros((B, H), dtype=x.dtype)
    cool = x[:, lay.i_cool : lay.i_cool + H]
    heat = x[:, lay.i_heat : lay.i_heat + H]
    wh = x[:, lay.i_wh : lay.i_wh + H]
    p_ch = x[:, lay.i_pch : lay.i_pch + H] if lay.has_batt else zH
    p_disch = x[:, lay.i_pd : lay.i_pd + H] if lay.has_batt else zH
    u_curt = x[:, lay.i_curt : lay.i_curt + H] if lay.has_curt else zH
    ghi = jnp.asarray(ghi_window)
    ghi = (ghi if ghi.ndim == 2 else ghi[None, :])[:, :H]
    pvc = (
        jnp.asarray(batch.pv_area)[:, None]
        * jnp.asarray(batch.pv_eff)[:, None]
        * jnp.asarray(batch.has_pv)[:, None]
        * ghi
        / 1000.0
    )
    p_pv = pvc * (1.0 - u_curt)
    p_ev = x[:, lay.i_evch : lay.i_evch + H] if lay.has_ev else zH
    p_load = s * (
        jnp.asarray(batch.hvac_p_c)[:, None] * cool
        + jnp.asarray(batch.hvac_p_h)[:, None] * heat
        + jnp.asarray(batch.wh_p)[:, None] * wh
    )
    p_grid = p_load + s * (p_ch + p_disch + p_ev) - s * p_pv
    cost = price_total * p_grid
    e_batt = (x[:, lay.i_eb : lay.i_eb + H + 1] if lay.has_batt
              else jnp.zeros((B, H + 1), dtype=x.dtype))
    e_ev = (x[:, lay.i_eev : lay.i_eev + H + 1] if lay.has_ev
            else jnp.zeros((B, H + 1), dtype=x.dtype))
    return MPCSolution(
        cool=cool, heat=heat, wh=wh, p_ch=p_ch, p_disch=p_disch, u_curt=u_curt,
        p_pv=p_pv, p_load=p_load, p_grid=p_grid, cost=cost,
        temp_in_ev=x[:, lay.i_tin : lay.i_tin + H + 1],
        temp_wh_ev=x[:, lay.i_twh : lay.i_twh + H + 1],
        e_batt=e_batt,
        temp_in1=x[:, lay.i_tin1],
        temp_wh1=x[:, lay.i_twh1],
        p_ev_ch=p_ev,
        e_ev=e_ev,
    )
