"""Banded Cholesky factorization of the ADMM Schur complement.

The Schur complement S = Â D⁻¹ Âᵀ of the MPC equality block is, after a
bandwidth-reducing permutation, a banded SPD matrix with bandwidth ~5
independent of the horizon (the dynamics are first-order RC recurrences:
each temperature row couples only to its timestep neighbors —
dragg/mpc_calc.py:311-342).  The dense batched ``jnp.linalg.cholesky`` +
triangular solves used to factor S cost O(B·m³) and dominated the 10k-home
step on chip (docs/perf_notes.md); the banded factorization here is
O(B·m·bw²) — a ``lax.scan`` over the m band rows with tiny per-row work —
and the explicit inverse needed by the hot loop comes from one banded
multi-RHS forward solve plus the same GEMM as before.

The permutation is computed generically with reverse Cuthill–McKee over
S's sparsity (no layout knowledge), so any future problem template gets the
same treatment; patterns whose RCM bandwidth is large simply keep the dense
path (see ``plan_for``).
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from dragg_tpu.ops.precision import mxu_einsum

MAX_BAND = 12  # fall back to the dense factorization beyond this bandwidth


def rcm_order(rows: np.ndarray, cols: np.ndarray, m: int) -> np.ndarray:
    """Reverse Cuthill–McKee ordering of a symmetric sparsity pattern.
    Returns ``perm`` with ``perm[p] = original index placed at position p``."""
    adj: list[set] = [set() for _ in range(m)]
    for i, j in zip(rows, cols):
        if i != j:
            adj[int(i)].add(int(j))
            adj[int(j)].add(int(i))
    deg = np.asarray([len(a) for a in adj])
    nbrs = [sorted(a, key=lambda v: deg[v]) for a in adj]
    visited = np.zeros(m, dtype=bool)
    order: list[int] = []
    for start in np.argsort(deg, kind="stable"):
        if visited[start]:
            continue
        visited[start] = True
        queue = [int(start)]
        while queue:
            v = queue.pop(0)
            order.append(v)
            for u in nbrs[v]:
                if not visited[u]:
                    visited[u] = True
                    queue.append(u)
    return np.asarray(order[::-1], dtype=np.int32)


class BandPlan(NamedTuple):
    """Static plan: permutation + scatter of Schur entries into lower-band
    storage ``Sb[:, i, k] = S_perm[i, i-k]``.  All numpy; hashable via id —
    built once per (pattern) by :func:`plan_for`."""

    m: int
    bw: int
    perm: np.ndarray      # (m,) original index at permuted position
    inv: np.ndarray       # (m,) permuted position of original index
    ent_row: np.ndarray   # (n_low,) band row of each kept S entry
    ent_off: np.ndarray   # (n_low,) band offset (0 = diagonal)
    ent_src: np.ndarray   # (n_low,) index into the contrib vector


@lru_cache(maxsize=32)
def _plan_cached(s_rows: tuple, s_cols: tuple, m: int) -> BandPlan | None:
    rows = np.asarray(s_rows, dtype=np.int64)
    cols = np.asarray(s_cols, dtype=np.int64)
    perm = rcm_order(rows, cols, m)
    inv = np.empty(m, dtype=np.int32)
    inv[perm] = np.arange(m, dtype=np.int32)
    bw = int(np.max(np.abs(inv[rows] - inv[cols]))) if len(rows) else 0
    if bw > MAX_BAND:
        return None
    if bw == 0:
        # A diagonal Schur complement needs no banded machinery (and the
        # scan carries below would be zero-length) — use the dense path.
        return None
    pi = inv[rows]
    pj = inv[cols]
    keep = pi >= pj  # lower triangle (S symmetric; each pair stored once)
    return BandPlan(
        m=m, bw=bw, perm=perm, inv=inv,
        ent_row=pi[keep].astype(np.int32),
        ent_off=(pi[keep] - pj[keep]).astype(np.int32),
        ent_src=np.nonzero(keep)[0].astype(np.int32),
    )


def plan_for(ss, m: int) -> BandPlan | None:
    """Band plan for a SchurStructure over m rows, or None when the RCM
    bandwidth is too large for the banded path to pay off."""
    if ss is None or ss.n_s == 0:
        return None
    return _plan_cached(ss.s_rows, ss.s_cols, m)


def band_scatter(plan: BandPlan, contrib: jnp.ndarray) -> jnp.ndarray:
    """Schur entry values (B, n_s) → lower-band storage (B, m, bw+1)."""
    B = contrib.shape[0]
    Sb = jnp.zeros((B, plan.m, plan.bw + 1), dtype=contrib.dtype)
    return Sb.at[:, plan.ent_row, plan.ent_off].set(contrib[:, plan.ent_src])


def banded_cholesky(Sb: jnp.ndarray, bw: int) -> jnp.ndarray:
    """Batched Cholesky of band-stored SPD matrices: (B, m, bw+1) lower-band
    S → same-layout L with S = L Lᵀ.  One scan over rows; per-row work is a
    static bw² unrolled loop."""
    B = Sb.shape[0]
    dtype = Sb.dtype

    def step(prev, srow):
        # prev[d-1] = L row (i-d); srow (B, bw+1).
        row = [None] * (bw + 1)
        for k in range(bw, 0, -1):
            s = srow[:, k]
            for j in range(1, bw - k + 1):
                s = s - row[k + j] * prev[k - 1][:, j]
            row[k] = s / prev[k - 1][:, 0]
        diag = srow[:, 0]
        for j in range(1, bw + 1):
            diag = diag - row[j] * row[j]
        row[0] = jnp.sqrt(jnp.maximum(diag, 1e-20))
        row_arr = jnp.stack(row, axis=1)
        new_prev = jnp.concatenate([row_arr[None], prev[:-1]], axis=0)
        return new_prev, row_arr

    # Virtual rows above the top: unit diagonal, zero off-diagonals — the
    # zero-padded Sb entries for i<k then produce L[i,k]=0 as required.
    prev0 = jnp.zeros((bw, B, bw + 1), dtype=dtype).at[:, :, 0].set(1.0)
    _, Lrows = lax.scan(step, prev0, jnp.swapaxes(Sb, 0, 1))
    return jnp.swapaxes(Lrows, 0, 1)  # (B, m, bw+1)


def banded_forward_solve(Lb: jnp.ndarray, R: jnp.ndarray, bw: int) -> jnp.ndarray:
    """Solve L Y = R for band-stored lower-triangular L.
    R is (B, m, r); returns Y of the same shape."""
    B, m, r = R.shape

    def step(prev, inp):
        lrow, rrow = inp           # (B, bw+1), (B, r)
        acc = rrow
        for k in range(1, bw + 1):
            acc = acc - lrow[:, k, None] * prev[k - 1]
        y = acc / lrow[:, 0, None]
        new_prev = jnp.concatenate([y[None], prev[:-1]], axis=0)
        return new_prev, y

    prev0 = jnp.zeros((bw, B, r), dtype=R.dtype)
    _, Y = lax.scan(step, prev0, (jnp.swapaxes(Lb, 0, 1), jnp.swapaxes(R, 0, 1)))
    return jnp.swapaxes(Y, 0, 1)


def banded_backward_solve(Lb: jnp.ndarray, Y: jnp.ndarray, bw: int) -> jnp.ndarray:
    """Solve Lᵀ X = Y for band-stored lower-triangular L.
    Y is (B, m, r); returns X of the same shape."""
    B, m, r = Y.shape
    # Row i of Lᵀ couples x_i to x_{i+k} via L[i+k, k]: a reverse scan
    # carrying the last bw (x, L-row) pairs below the current row.
    Lrows = jnp.swapaxes(Lb, 0, 1)          # (m, B, bw+1)
    Yrows = jnp.swapaxes(Y, 0, 1)

    def rstep(carry, inp):
        lrow, yrow = inp                     # (B, bw+1), (B, r)
        xs, lrows_below = carry              # (bw, B, r), (bw, B, bw+1)
        acc = yrow
        for k in range(1, bw + 1):
            acc = acc - lrows_below[k - 1][:, k, None] * xs[k - 1]
        x = acc / lrow[:, 0, None]
        xs = jnp.concatenate([x[None], xs[:-1]], axis=0)
        lrows_below = jnp.concatenate([lrow[None], lrows_below[:-1]], axis=0)
        return (xs, lrows_below), x

    xs0 = jnp.zeros((bw, B, r), dtype=Y.dtype)
    l0 = jnp.zeros((bw, B, bw + 1), dtype=Lb.dtype).at[:, :, 0].set(1.0)
    _, X = lax.scan(rstep, (xs0, l0), (Lrows, Yrows), reverse=True)
    return jnp.swapaxes(X, 0, 1)


def band_matvec(Sb: jnp.ndarray, v: jnp.ndarray, bw: int) -> jnp.ndarray:
    """S v for lower-band-stored symmetric S: (B, m, bw+1) × (B, m)."""
    out = Sb[:, :, 0] * v
    for k in range(1, bw + 1):
        lo = Sb[:, k:, k]          # S[i, i-k] for i >= k
        out = out.at[:, k:].add(lo * v[:, :-k])   # lower-triangle term
        out = out.at[:, :-k].add(lo * v[:, k:])   # symmetric upper term
    return out


def banded_solve(Lb: jnp.ndarray, r: jnp.ndarray, bw: int) -> jnp.ndarray:
    """S⁻¹ r (band-space) via forward + backward substitution; r is (B, m)."""
    y = banded_forward_solve(Lb, r[..., None], bw)
    return banded_backward_solve(Lb, y, bw)[..., 0]


def banded_explicit_inverse(plan: BandPlan, contrib: jnp.ndarray) -> jnp.ndarray:
    """S⁻¹ (original row order, dense (B, m, m)) from Schur entry values.

    S = L Lᵀ in the permuted space; L⁻¹ comes from one banded multi-RHS
    forward solve against I, and S⁻¹ = L⁻ᵀ L⁻¹ is one batched GEMM — the
    only O(m³) step left (MXU-friendly), replacing the batched dense
    Cholesky + two triangular solves.
    """
    m, bw = plan.m, plan.bw
    B = contrib.shape[0]
    Sb = band_scatter(plan, contrib)
    Lb = banded_cholesky(Sb, bw)
    eye = jnp.broadcast_to(jnp.eye(m, dtype=contrib.dtype), (B, m, m))
    Linv = banded_forward_solve(Lb, eye, bw)           # (B, m, m), permuted
    # The one dense GEMM of the banded family routes through the policy
    # module like every MXU contraction (DT008); the f32 default is the
    # historical einsum(precision=HIGHEST) bit-for-bit.  Sinv formation
    # feeds the reluqp hot loop, so it stays pinned f32 regardless of
    # tpu.precision (the bf16x3 policy covers the ITERATION matmuls, not
    # the operator build — ops/precision.py docstring).
    Sinv_p = mxu_einsum("bkm,bkn->bmn", Linv, Linv)
    inv = plan.inv
    return Sinv_p[:, inv][:, :, inv]                   # back to original order
