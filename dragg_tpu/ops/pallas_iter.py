"""Fused Pallas solver-iteration kernel for the reluqp family (ISSUE 11).

The banked reluqp inner loop (ops/reluqp.py) runs ``check_every``
iterations between residual checks, each iteration a fixed sequence of
three batched dense matvecs plus an elementwise clamp.  Under XLA every
iteration round-trips the (B, n)/(B, m) state through HBM and each
einsum is its own fusion; this kernel runs ONE WHOLE CHECK WINDOW as a
single ``pallas_call`` — state, the per-home operators Â and S⁻¹, and
every intermediate stay VMEM-resident across all k iterations, and the
window ends with the f32 residual-max reduction (the four scalars the
convergence check consumes) computed in-kernel, so nothing but the
window-end state and four (B,) scalars ever reaches HBM.

Layout follows the round-5 band kernels (ops/pallas_band.py): the HOME
axis maps onto the TPU lanes — Â is ``(m, n, B)``, S⁻¹ ``(m, m, B)``,
vectors ``(n|m, B)`` — and each matvec runs as a fori_loop over matrix
rows with ``(n, lane_block)`` VPU elementwise-multiply + sublane
reductions per step.  Per-home operators make the contraction a batch
of independent small matvecs, which the MXU cannot tile across homes;
the lane formulation is the same trade the band kernels measured, and
like them the END-TO-END verdict belongs to the engine-level A/B
(``tools/bench_engine_kernels.py --iter-kernels``) — the ``auto``
policy resolves to the lax path until that on-chip measurement exists
(docs/perf_notes.md rule: no default without a recorded number).

Block sizing rides the round-5 scoped-VMEM auto policy scaffolding:
the budget is ``pallas_band._VMEM_BUDGET`` ($DRAGG_VMEM_BUDGET_MB), the
lane block shrinks from 512 in 128-steps until the double-buffered
per-home footprint (dominated by Â at m·n floats/home) fits half the
budget, and the full-output half bounds homes per ``pallas_call``
(``b_chunk``), chunk-parity bitwise by home independence (pinned in
tests/test_pallas_iter.py, same contract as the band kernels').

Numerics: identical operation order to ``reference_window`` below — the
pure-lax mirror of ops/reluqp.py's ``one_iter`` + ``residuals`` — and
the kernel is f32 throughout (the residual reduction MUST be f32 per
the precision discipline; ``tpu.iter_kernel="pallas"`` therefore
composes only with ``tpu.precision="f32"`` — ops/reluqp.py enforces
it).  Parity is pinned element-wise in interpreter mode by
tests/test_pallas_iter.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from dragg_tpu.ops import pallas_band
from dragg_tpu.ops.precision import mxu_einsum


def _auto_blocks(m: int, n: int, itemsize: int, B: int,
                 lane_block: int | None = None) -> tuple[int, int]:
    """(lane_block, b_chunk) from the call shape against the shared
    scoped-VMEM budget (pallas_band._VMEM_BUDGET).  Model per kernel
    program, double-buffered: Â (m·n) + S⁻¹ (m·m) + ~13 n-vectors +
    ~6 m-vectors per home; the full-output half bounds homes per call
    (3 n-vectors + 1 m-vector + 4 scalars per home).  The floor is one
    lane tile (128) — at the H=24 superset shape (m=77, n=221) even 128
    homes exceed the default 10 MiB budget, which is exactly the kind
    of verdict the on-chip A/B exists to settle (the model errs large;
    Mosaic may still fit it — and if not, the scoped-VMEM OOM is the
    recorded outcome, as in round 4)."""
    half = pallas_band._VMEM_BUDGET // 2
    per_home = 2 * (m * n + m * m + 13 * n + 6 * m) * itemsize
    if lane_block is not None:
        lb = lane_block
    else:
        lb = 512
        while lb > 128 and per_home * lb > half:
            lb -= 128
    out_per_home = (3 * n + m + 4) * itemsize
    cap = half // max(out_per_home, 1)
    cap = (cap // lb) * lb
    b_chunk = 0 if cap >= B else max(cap, lb)
    return lb, b_chunk


def _iter_kernel(a_ref, s_ref, dinv_ref, w_ref, qs_ref, bs_ref, ls_ref,
                 us_ref, rho_ref, eeq_ref, ebox_ref, cd_ref, pd_ref,
                 x_ref, z_ref, nu_ref, y_ref,
                 xo_ref, zo_ref, nuo_ref, yo_ref,
                 rp_ref, rd_ref, ps_ref, ds_ref,
                 tm_ref, tn_ref, *, m: int, n: int, k: int,
                 sigma: float, alpha: float):
    """k fused iterations + the residual-max reduction for one home
    block.  ``tm_ref`` (m, Bt) / ``tn_ref`` (n, Bt) are matvec scratch."""
    from jax.experimental import pallas as pl

    def mv(v):
        """Â v: (n, Bt) → (m, Bt), row loop over the m Â rows."""
        def row(i, _):
            arow = a_ref[pl.ds(i, 1)][0]                  # (n, Bt)
            tm_ref[pl.ds(i, 1)] = jnp.sum(arow * v, axis=0)[None]
            return 0
        lax.fori_loop(0, m, row, 0)
        return tm_ref[:]

    def smv(v):
        """S⁻¹ v: (m, Bt) → (m, Bt)."""
        def row(i, _):
            srow = s_ref[pl.ds(i, 1)][0]                  # (m, Bt)
            tm_ref[pl.ds(i, 1)] = jnp.sum(srow * v, axis=0)[None]
            return 0
        lax.fori_loop(0, m, row, 0)
        return tm_ref[:]

    def mvt(v):
        """Âᵀ v: (m, Bt) → (n, Bt), accumulated over the m rows (no
        second, transposed copy of Â in VMEM)."""
        tn_ref[:] = jnp.zeros_like(tn_ref)
        def row(i, _):
            arow = a_ref[pl.ds(i, 1)][0]                  # (n, Bt)
            vi = lax.dynamic_slice_in_dim(v, i, 1, axis=0)  # (1, Bt)
            tn_ref[:] = tn_ref[:] + arow * vi
            return 0
        lax.fori_loop(0, m, row, 0)
        return tn_ref[:]

    rho = rho_ref[:]                                       # (1, Bt)
    dinv = dinv_ref[:]
    w = w_ref[:]
    qs = qs_ref[:]
    bs = bs_ref[:]

    def one(_, carry):
        # Same operation order as ops/reluqp.py one_iter (module
        # docstring of reference_window is the normative spelling).
        x, z, nu, y = carry
        rhs = sigma * x - qs + w * (rho * z - y)
        t = mv(dinv * rhs) - bs
        nu_t = smv(t)
        x_t = dinv * (rhs - mvt(nu_t))
        z_t = w * x_t
        x_new = alpha * x_t + (1.0 - alpha) * x
        zc = alpha * z_t + (1.0 - alpha) * z
        z_new = jnp.clip(zc + y / rho, ls_ref[:], us_ref[:])
        y_new = y + rho * (zc - z_new)
        return x_new, z_new, nu_t, y_new

    x, z, nu, y = lax.fori_loop(
        0, k, one, (x_ref[:], z_ref[:], nu_ref[:], y_ref[:]))

    # Residual-max reduction (f32, ops/reluqp.py residuals parity): the
    # two matvecs the check needs run ONCE here on the VMEM-resident
    # operators instead of as fresh HBM-fed einsums outside.
    Ax = mv(x)
    At_nu = mvt(nu)
    eeq = eeq_ref[:]
    ebox = ebox_ref[:]
    cd = cd_ref[:]
    wx = w * x
    r_p_eq = jnp.max(jnp.abs((Ax - bs) / eeq), axis=0)
    r_p_box = jnp.max(jnp.abs((wx - z) / ebox), axis=0)
    dual = (pd_ref[:] * x + qs + At_nu + w * y) / cd
    p_sc = jnp.maximum(
        jnp.maximum(jnp.max(jnp.abs(Ax / eeq), axis=0),
                    jnp.max(jnp.abs(bs / eeq), axis=0)),
        jnp.maximum(jnp.max(jnp.abs(wx / ebox), axis=0),
                    jnp.max(jnp.abs(z / ebox), axis=0)))
    d_sc = jnp.maximum(
        jnp.max(jnp.abs(At_nu / cd), axis=0),
        jnp.maximum(jnp.max(jnp.abs(w * y / cd), axis=0),
                    jnp.max(jnp.abs(qs / cd), axis=0)))
    xo_ref[:] = x
    zo_ref[:] = z
    nuo_ref[:] = nu
    yo_ref[:] = y
    rp_ref[:] = jnp.maximum(r_p_eq, r_p_box)[None]
    rd_ref[:] = jnp.max(jnp.abs(dual), axis=0)[None]
    ps_ref[:] = p_sc[None]
    ds_ref[:] = d_sc[None]


@functools.partial(jax.jit, static_argnames=("k", "sigma", "alpha",
                                             "lane_block", "b_chunk"))
def _fused_window_t(A_t, Sinv_t, Dinv_t, w_t, qs_t, bs_t, ls_t, us_t,
                    rho_t, x_t, z_t, nu_t, y_t, eeq_t, ebox_t, cd_t, pd_t,
                    *, k: int, sigma: float, alpha: float,
                    lane_block: int | None = None,
                    b_chunk: int | None = None):
    """Transposed-layout core: every array home-LAST ((m,n,B), (m,m,B),
    (n|m,B), rho (1,B)).  Returns 8 home-last outputs
    (x, z, nu, y, r_prim, r_dual, p_sc, d_sc)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, n, B = A_t.shape
    dtype = A_t.dtype
    lb, ck = _auto_blocks(m, n, dtype.itemsize, B, lane_block=lane_block)
    if b_chunk is not None:
        ck = b_chunk
    if ck and B > ck:
        # Home independence makes chunked == unchunked bitwise; b_chunk=0
        # in the recursion so slices are never re-chunked (the
        # pallas_band convention).
        return pallas_band._chunked(
            lambda *arr: _fused_window_t(*arr, k=k, sigma=sigma,
                                         alpha=alpha, lane_block=lb,
                                         b_chunk=0),
            8, ck, A_t, Sinv_t, Dinv_t, w_t, qs_t, bs_t, ls_t, us_t,
            rho_t, x_t, z_t, nu_t, y_t, eeq_t, ebox_t, cd_t, pd_t)
    Bp = -(-B // lb) * lb
    if Bp != B:
        # Benign pad homes: zero Â rows, identity-ish scalings (ones),
        # zero state — the iteration stays finite and the pad columns
        # are sliced off below.
        pad_n = Bp - B
        def padz(a):
            return jnp.concatenate(
                [a, jnp.zeros(a.shape[:-1] + (pad_n,), a.dtype)], axis=-1)
        def pad1(a):
            return jnp.concatenate(
                [a, jnp.ones(a.shape[:-1] + (pad_n,), a.dtype)], axis=-1)
        A_t, Sinv_t = padz(A_t), padz(Sinv_t)
        x_t, z_t, nu_t, y_t = map(padz, (x_t, z_t, nu_t, y_t))
        qs_t, bs_t, ls_t, us_t, pd_t = map(padz, (qs_t, bs_t, ls_t, us_t,
                                                  pd_t))
        Dinv_t, w_t, rho_t, eeq_t, ebox_t, cd_t = map(
            pad1, (Dinv_t, w_t, rho_t, eeq_t, ebox_t, cd_t))
    band = lambda shape: pl.BlockSpec(shape + (lb,),
                                      lambda b: (0,) * len(shape) + (b,))
    outs = pl.pallas_call(
        functools.partial(_iter_kernel, m=m, n=n, k=k, sigma=sigma,
                          alpha=alpha),
        out_shape=(
            jax.ShapeDtypeStruct((n, Bp), dtype),   # x
            jax.ShapeDtypeStruct((n, Bp), dtype),   # z
            jax.ShapeDtypeStruct((m, Bp), dtype),   # nu
            jax.ShapeDtypeStruct((n, Bp), dtype),   # y
            jax.ShapeDtypeStruct((1, Bp), dtype),   # r_prim
            jax.ShapeDtypeStruct((1, Bp), dtype),   # r_dual
            jax.ShapeDtypeStruct((1, Bp), dtype),   # p_sc
            jax.ShapeDtypeStruct((1, Bp), dtype),   # d_sc
        ),
        grid=(Bp // lb,),
        in_specs=[
            band((m, n)), band((m, m)),                       # A, Sinv
            band((n,)), band((n,)), band((n,)), band((m,)),   # Dinv w qs bs
            band((n,)), band((n,)), band((1,)),               # ls us rho
            band((m,)), band((n,)), band((n,)), band((n,)),   # eeq ebox cd pd
            band((n,)), band((n,)), band((m,)), band((n,)),   # x z nu y
        ],
        out_specs=(band((n,)), band((n,)), band((m,)), band((n,)),
                   band((1,)), band((1,)), band((1,)), band((1,))),
        scratch_shapes=[
            pltpu.VMEM((m, lb), dtype),
            pltpu.VMEM((n, lb), dtype),
        ],
        interpret=pallas_band._interpret(),
    )(A_t, Sinv_t, Dinv_t, w_t, qs_t, bs_t, ls_t, us_t, rho_t,
      eeq_t, ebox_t, cd_t, pd_t, x_t, z_t, nu_t, y_t)
    return tuple(o[..., :B] for o in outs)


def fused_window(A, Sinv, Dinv, w, qs, bs, ls, us, rho, x, z, nu, y,
                 e_eq, e_box, cd, p_diag, *, k: int, sigma: float,
                 alpha: float, lane_block: int | None = None,
                 b_chunk: int | None = None):
    """Batch-first API the solver calls: one fused check window.

    Inputs as ops/reluqp.py holds them — Â ``(B, m, n)``, selected S⁻¹
    slab ``(B, m, m)``, vectors ``(B, n|m)``, ``rho`` ``(B,)``; ``cd``
    is the combined ``c * d`` cost/column scaling.  Returns
    ``((x, z, nu, y), (r_prim, r_dual, p_sc, d_sc))`` with the state
    batch-first and the residual maxima ``(B,)`` — exactly what the
    check window consumes (``ok`` is an elementwise comparison the
    caller owns, since the tolerances are its statics)."""
    t3 = lambda a: jnp.transpose(a, (1, 2, 0))
    tv = lambda a: jnp.swapaxes(a, 0, 1)
    outs = _fused_window_t(
        t3(A), t3(Sinv), tv(Dinv), tv(w), tv(qs), tv(bs), tv(ls), tv(us),
        rho[None, :], tv(x), tv(z), tv(nu), tv(y), tv(e_eq), tv(e_box),
        tv(cd), tv(p_diag), k=k, sigma=sigma, alpha=alpha,
        lane_block=lane_block, b_chunk=b_chunk)
    x2, z2, nu2, y2 = (tv(o) for o in outs[:4])
    rp, rd, ps, ds = (o[0] for o in outs[4:])
    return (x2, z2, nu2, y2), (rp, rd, ps, ds)


def reference_window(A, Sinv, Dinv, w, qs, bs, ls, us, rho, x, z, nu, y,
                     e_eq, e_box, cd, p_diag, *, k: int, sigma: float,
                     alpha: float):
    """Pure-lax mirror of the fused kernel — the normative spelling of
    one check window (same math and operation order as ops/reluqp.py's
    ``one_iter`` + ``residuals``, restated here so the kernel has an
    in-module reference the interpreter-mode tests pin it against).

    Contractions route through ``mxu_einsum`` like the reluqp path they
    mirror (DT008); its f32 default is the historical
    ``einsum(precision=HIGHEST)`` bit-for-bit, and the fused kernel is
    f32-only by contract (iter_kernel='pallas' rejects bf16x3), so the
    mirror stays pinned f32 too."""

    def mv(v):
        return mxu_einsum("bmn,bn->bm", A, v)

    def mvt(v):
        return mxu_einsum("bmn,bm->bn", A, v)

    rho_c = rho[:, None]

    def one(_, carry):
        x, z, nu, y = carry
        rhs = sigma * x - qs + w * (rho_c * z - y)
        t = mv(Dinv * rhs) - bs
        nu_t = mxu_einsum("bmn,bn->bm", Sinv, t)
        x_t = Dinv * (rhs - mvt(nu_t))
        z_t = w * x_t
        x_new = alpha * x_t + (1.0 - alpha) * x
        zc = alpha * z_t + (1.0 - alpha) * z
        z_new = jnp.clip(zc + y / rho_c, ls, us)
        y_new = y + rho_c * (zc - z_new)
        return x_new, z_new, nu_t, y_new

    x, z, nu, y = lax.fori_loop(0, k, one, (x, z, nu, y))
    Ax = mv(x)
    At_nu = mvt(nu)
    wx = w * x
    r_p_eq = jnp.max(jnp.abs((Ax - bs) / e_eq), axis=1)
    r_p_box = jnp.max(jnp.abs((wx - z) / e_box), axis=1)
    r_prim = jnp.maximum(r_p_eq, r_p_box)
    dual = (p_diag * x + qs + At_nu + w * y) / cd
    r_dual = jnp.max(jnp.abs(dual), axis=1)
    p_sc = jnp.maximum(
        jnp.maximum(jnp.max(jnp.abs(Ax / e_eq), axis=1),
                    jnp.max(jnp.abs(bs / e_eq), axis=1)),
        jnp.maximum(jnp.max(jnp.abs(wx / e_box), axis=1),
                    jnp.max(jnp.abs(z / e_box), axis=1)))
    d_sc = jnp.maximum(
        jnp.max(jnp.abs(At_nu / cd), axis=1),
        jnp.maximum(jnp.max(jnp.abs(w * y / cd), axis=1),
                    jnp.max(jnp.abs(qs / cd), axis=1)))
    return (x, z, nu, y), (r_prim, r_dual, p_sc, d_sc)
