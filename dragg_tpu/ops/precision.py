"""Mixed-precision policy for the dense solver hot loops (ISSUE 11).

One module owns the cast discipline so no call site hand-rolls dtypes:
every dense matmul/einsum in the MXU families (``ops/reluqp.py``'s
banked iteration, ``ops/admm.py``'s dense_inv apply path) routes through
:func:`mxu_einsum`, and the residual/convergence path declares itself
with :func:`f32_guard` — ``tools/lint.py`` rejects bare ``jnp.einsum``
in those files so the discipline cannot erode silently.

Two policies (``tpu.precision``):

* ``"f32"`` (default): BIT-IDENTICAL to the pre-policy code —
  ``jnp.einsum(..., precision=lax.Precision.HIGHEST)``, nothing cast.
* ``"bf16x3"``: each f32 operand splits into a bf16 high part and a
  bf16 low remainder (``hi = bf16(x)``, ``lo = bf16(x - f32(hi))``) and
  the contraction runs as THREE bf16-input matmuls accumulated in f32
  (``lo·hi + hi·lo + hi·hi`` — the classical 3-product scheme, dropping
  the O(2⁻¹⁶)-squared ``lo·lo`` term).  On the MXU each pass runs at
  bf16 throughput with native f32 accumulation, so the x-update costs
  ~3/6 of XLA's default HIGHEST-precision f32 emulation; the combined
  relative error is ~2⁻¹⁶ per contraction — well under the solvers'
  1e-4 tolerances when the residual path stays f32.

Why this exact shape and not plain bf16 storage: rounds 2 and 9 both
measured bf16 STORAGE diverging (docs/perf_notes.md "Matvec-precision
and refinement experiments" — bf16 Sinv with refine=0 solved 0/6; and
"Negative result: bf16 storage for the IPM's gathered A-tables" — the
primal residual floor sits above eps once A itself is rounded).  The
prescription recorded there is bf16 COMPUTE with fp32 accumulation and
an f32 residual/convergence path — which is precisely the split this
module enforces: the ITERATION may run low precision (it only has to
land near the fixed point), the residual DECIDING convergence may not.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

# The policy registry — config validation (engine.engine_params), the
# bench --precision flag, and tools/bench_engine_kernels.py all resolve
# against this tuple.
PRECISIONS = ("f32", "bf16x3")


def validate_precision(name: str) -> str:
    """Raise ValueError unless ``name`` is a registered policy."""
    if name not in PRECISIONS:
        raise ValueError(
            f"tpu.precision must be one of {'|'.join(PRECISIONS)}, "
            f"got {name!r}")
    return name


def _split_bf16(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(hi, lo) bf16 split of ``x``: hi carries the top ~8 mantissa bits,
    lo the next ~8 (computed against hi in f32).  An already-bf16 operand
    (the ADMM's opt-in bf16 Sinv storage) splits to (x, 0) — correct,
    just redundant."""
    hi = x.astype(jnp.bfloat16)
    lo = (x.astype(jnp.float32) - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def mxu_einsum(spec: str, a: jnp.ndarray, b: jnp.ndarray, *,
               precision: str = "f32", out_dtype=None) -> jnp.ndarray:
    """THE dense contraction of the solver hot paths.

    ``precision="f32"`` reproduces the historical call bit-for-bit:
    ``jnp.einsum(spec, a, b, precision=lax.Precision.HIGHEST,
    preferred_element_type=out_dtype)`` — the f32 default engine is
    therefore identical to the pre-policy engine by construction
    (pinned in tests/test_precision.py).

    ``precision="bf16x3"`` runs the 3-product bf16 split with f32
    accumulation (module docstring).  The result is f32 (cast to
    ``out_dtype`` when given); accumulation is ALWAYS f32 — there is no
    policy under which a contraction accumulates in bf16, per the
    round-2/9 negative results.
    """
    if precision == "f32":
        return jnp.einsum(spec, a, b, precision=lax.Precision.HIGHEST,
                          preferred_element_type=out_dtype)
    validate_precision(precision)
    a_hi, a_lo = _split_bf16(a)
    b_hi, b_lo = _split_bf16(b)

    def p(x, y):
        return jnp.einsum(spec, x, y, preferred_element_type=jnp.float32)

    # Small cross terms first, head term last (adds the large term into
    # an already-combined small correction — marginally better rounding).
    out = (p(a_lo, b_hi) + p(a_hi, b_lo)) + p(a_hi, b_hi)
    return out if out_dtype is None else out.astype(out_dtype)


def f32_guard(x: jnp.ndarray, what: str) -> jnp.ndarray:
    """Trace-time assertion that a residual/convergence-path tensor is
    f32.  Dtypes are static under tracing, so this costs nothing at run
    time and fails at ENGINE BUILD if a low-precision value ever leaks
    into the path that decides convergence (the round-2/9 divergence
    mode).  Returns ``x`` so call sites can wrap in place."""
    if x.dtype != jnp.float32:
        raise TypeError(
            f"precision discipline: {what} must be float32 on the "
            f"residual/convergence path, got {x.dtype} — only the "
            f"x-update matmuls may run reduced precision "
            f"(ops/precision.py, docs/architecture.md §16)")
    return x
