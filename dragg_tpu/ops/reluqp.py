"""ReLU-QP-style pre-factorized dense-matmul ADMM for the per-home MPC QPs.

Third solver family (``hems.solver = "reluqp"``), after ReLU-QP (Bishop,
Bouman, Tracy, Manchester — PAPERS.md, arxiv 2311.18056): an OSQP-style
ADMM iteration whose KKT system is factorized ONCE per (bucket pattern,
rho) into an explicit dense inverse, so every iteration of the inner loop
is a fixed sequence of batched dense matmuls plus an elementwise clamp —
exactly a ReLU-network forward pass.  No triangular solves, no
data-dependent branching, no in-loop refactorization: rho adaptation is
an INDEX SWITCH into a small geometric bank of pre-inverted Schur
operators, never a new factorization.

Differences from the existing families on the same problems:

* ``ops/admm.py`` adapts a continuous per-home rho and pays an O(Bm³)
  batched refactorization whenever any home's rho moves (gated to every
  ``rho_update_every`` check windows exactly because that cost dominated
  at B = 10⁴).  Here the factor for every admissible rho already exists,
  so the adaptation is free and can run every check window.
* The hot-loop matvecs are batched dense ``jnp.einsum`` contractions over
  an explicitly materialized (B, m, n) Â — MXU work — instead of the
  gather-padded sparse form (VPU work).  That trades ~n/K more FLOPs for
  matrix-unit throughput; on CPU the sparse form wins and the A/B in
  docs/perf_notes.md records that honestly.
* Equality elimination is retained from the ADMM (the dynamics rows are
  hard equalities; only the box block is split), so the pre-factorized
  operator is the m×m Schur complement S(ρ) = Â D(ρ)⁻¹ Âᵀ — at the
  type-bucketed shapes (m ≤ 3H+5; round 8) a full bank of R dense
  inverses is affordable where the paper's (n+m)² KKT inverse is not.

Structure of one iteration (σ, α as in OSQP; D = diag(P̂ + σ + ρŵ²)):

    rhs = σ x − q̂ + ŵ∘(ρ z − y)                     elementwise
    ν   = S(ρ)⁻¹ (Â (D⁻¹ rhs) − b̂)                  2 dense matmuls
    x⁺  = D⁻¹ (rhs − Âᵀ ν)                          1 dense matmul
    z⁺  = clip(α ŵ x⁺ + (1−α) z + y/ρ, l̂, û)        the "ReLU" clamp
    y⁺  = y + ρ (α ŵ x⁺ + (1−α) z − z⁺)             elementwise

The bank is carried across MPC timesteps in :class:`ReLUQPCarry`
(refreshed on the engine's ``admm_refactor_every`` cadence, exactly like
the ADMM's :class:`~dragg_tpu.ops.admm.FactorCarry`; between refreshes
only the water-mix band of Â drifts and the final polish refines against
the exact current S).  Homes still unconverged when the banked loop
exits get ONE fallback exact refactorization at their current rho plus a
bounded tail of iterations — the only O(Bm³) work the family can do
inside a step, reported per home in ``ADMMSolution.bank_fallback`` so
benchmarks can state whether the pre-factorized path sufficed.

Parity/failure semantics match the other families: solutions whose
residuals fail tolerance come back ``solved=False`` and the engine
routes them to the fallback controller; primal infeasibility is
certified with the OSQP §3.4 test.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dragg_tpu.ops.admm import (
    ADMMSolution,
    _pad_gather,
    _schur_structure_for,
    ruiz_equilibrate_sparse,
)
from dragg_tpu.ops.precision import f32_guard, mxu_einsum, validate_precision
from dragg_tpu.ops.qp import SparsePattern, scatter_schur, schur_contrib


class ReLUQPCarry(NamedTuple):
    """Cross-timestep cache for the reluqp family: the Ruiz/cost scalings
    plus the full pre-inverted rho bank, carried through the simulation
    scan on the same refresh cadence as the ADMM's FactorCarry.  The bank
    axis (R) is axis 1, so every leaf keeps the home batch on axis 0 and
    shards over the mesh like any other per-home tensor."""

    d: jnp.ndarray          # (B, n) column scaling
    e_eq: jnp.ndarray       # (B, m) equality-row scaling
    e_box: jnp.ndarray      # (B, n) box-row scaling
    c: jnp.ndarray          # (B, 1) cost scaling
    Sinv_bank: jnp.ndarray  # (B, R, m, m) pre-inverted Schur operators,
                            # one per bank rho (geometric schedule)


def bank_rhos(rho0: float, rho_factor: float, bank: int) -> np.ndarray:
    """The geometric rho schedule, centered on ``rho0``: bank entry r is
    ``rho0 * rho_factor**(r - bank//2)``.  Pure host-side helper so
    config docs, tests, and the solver agree on the schedule."""
    return float(rho0) * float(rho_factor) ** (
        np.arange(int(bank), dtype=np.float64) - int(bank) // 2)


def iteration_flops(m: int, n: int) -> float:
    """EXACT dense-matmul FLOPs of one reluqp iteration for one home —
    the three batched einsums of the x-update (module docstring):

        Â (D⁻¹ rhs):  m·n multiply-adds  → 2·m·n
        S⁻¹ t:        m·m multiply-adds  → 2·m²
        Âᵀ ν:         n·m multiply-adds  → 2·n·m

    Elementwise work (D⁻¹, clamp, y-update) is excluded — it is O(n) and
    not matmul FLOPs.  This is the number ``bench.py`` multiplies by the
    measured iteration count, so reluqp's ``flops_per_step`` is an exact
    count of the dense iteration rather than an analytic floor
    (tests/test_reluqp.py pins it against a hand count)."""
    return 4.0 * m * n + 2.0 * m * m


def bank_factor_flops(m: int, bank: int) -> float:
    """Dense FLOPs of (re)building the rho bank for one home: per bank
    entry one Cholesky (m³/3), one triangular solve of m RHS (m³), and
    the Gram product L⁻ᵀL⁻¹ (m³) — the same per-factor model the ADMM
    uses, times the bank size.  S formation itself runs on the sparse
    triple lists (negligible FLOPs)."""
    return float(bank) * (1.0 / 3.0 + 1.0 + 1.0) * float(m) ** 3


def equilibrated_spd_inverse(S: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Condition-checked explicit inverse of a batch of (already Ruiz-
    equilibrated) SPD matrices — the ONE sanctioned route to the dense
    rho-bank operators (``tools/lint.py`` rejects direct
    ``np.linalg.inv``/``jnp.linalg.inv`` outside ``ops/`` for exactly
    this reason: an unequilibrated, unchecked inverse of a KKT-sized
    operand silently amplifies float32 conditioning error into the hot
    loop).

    Cholesky-based (never a generic LU inverse): S = LLᵀ, S⁻¹ = L⁻ᵀL⁻¹.
    Homes whose factorization fails the finiteness check — the practical
    float32 condition test: cond(S) beyond ~1/eps makes the Cholesky
    produce non-finite or the inverse overflow — are retried once with a
    relative Tikhonov bump ``1e-6·max|S|`` on the diagonal.  Returns
    ``(Sinv, ok)`` with ``ok`` false for homes that failed even the
    bumped factorization (their rows are identity-scaled so downstream
    matmuls stay finite; the residual check then flags them unsolved)."""
    B, m, _ = S.shape
    dtype = S.dtype
    eye = jnp.eye(m, dtype=dtype)

    def try_inv(Sx):
        L = jnp.linalg.cholesky(Sx)
        Linv = lax.linalg.triangular_solve(
            L, jnp.broadcast_to(eye, Sx.shape), left_side=True, lower=True)
        # Factorization-path Gram product: pinned f32 regardless of the
        # hot-loop policy (the bank must be an accurate inverse).
        Sinv = mxu_einsum("bkm,bkn->bmn", Linv, Linv, precision="f32")
        ok = jnp.all(jnp.isfinite(Sinv), axis=(1, 2))
        return Sinv, ok

    Sinv, ok = try_inv(S)
    bump = 1e-6 * jnp.max(jnp.abs(S), axis=(1, 2))
    S2 = jnp.where(ok[:, None, None], S,
                   S + jnp.maximum(bump, 1e-12)[:, None, None] * eye)
    Sinv2, ok2 = try_inv(S2)
    out = jnp.where(ok[:, None, None], Sinv,
                    jnp.where(ok2[:, None, None], Sinv2, eye[None]))
    return out, ok | ok2


def init_reluqp_carry(B: int, pat: SparsePattern, bank: int,
                      dtype=jnp.float32) -> ReLUQPCarry:
    """Zero-filled carry for t=0 (the first step must pass refresh=True),
    shaped for ``bank`` rho entries."""
    return ReLUQPCarry(
        d=jnp.ones((B, pat.n), dtype=dtype),
        e_eq=jnp.ones((B, pat.m), dtype=dtype),
        e_box=jnp.ones((B, pat.n), dtype=dtype),
        c=jnp.ones((B, 1), dtype=dtype),
        Sinv_bank=jnp.zeros((B, bank, pat.m, pat.m), dtype=dtype),
    )


def _reluqp_impl(
    pat: SparsePattern,
    vals: jnp.ndarray,       # (B, nnz) A_eq values
    b_eq: jnp.ndarray,       # (B, m)
    l_box: jnp.ndarray,      # (B, n)
    u_box: jnp.ndarray,      # (B, n)
    q: jnp.ndarray,          # (B, n)
    *,
    rho0: float = 0.1,
    rho_factor: float = 6.0,
    bank: int = 5,
    sigma: float = 1e-6,
    alpha: float = 1.6,
    eps_abs: float = 1e-4,
    eps_rel: float = 1e-4,
    reg: float = 1e-3,
    iters: int = 2000,
    check_every: int = 25,
    ruiz_iters: int = 10,
    patience: int = 4,
    tail_iters: int = 300,   # fallback exact-refactorization tail budget
                             # for homes the banked loop left unconverged
                             # (0 disables the fallback path entirely).
                             # 300, not less: warm-started steps on a
                             # STALE bank can jam borderline homes (the
                             # stale operator biases the dual residual),
                             # and the measured rescue needs ~cold-start
                             # depth — 100 left 3/64 homes unsolved at
                             # the 64-home mixed fixture, 300 solves all
                             # (tests/test_reluqp.py equivalence suite)
    precision: str = "f32",  # hot-loop matmul policy (ops/precision.py):
                             # "bf16x3" runs the x-update einsums as
                             # 3-pass bf16 with f32 accumulation; the
                             # residual/check path below is ALWAYS f32
                             # (the round-2/9 divergence mode lives
                             # exactly in low-precision residuals)
    iter_kernel: str = "lax",  # "pallas": run each check window as ONE
                               # fused kernel (ops/pallas_iter.py —
                               # matmuls + clamp + residual-max without
                               # HBM round trips); f32-only, engine-
                               # resolved ("auto" stays lax until the
                               # on-chip A/B records a verdict)
    x0: jnp.ndarray | None = None,
    y_box0: jnp.ndarray | None = None,
    rho_warm: jnp.ndarray | None = None,  # (B,) unscaled rho hint — snapped
                                          # to the nearest bank entry
    carry_in: ReLUQPCarry | None = None,
    refresh=None,            # traced bool — recompute scalings + bank
) -> tuple[ADMMSolution, ReLUQPCarry]:
    """Solve B problems  min 1/2 x'(reg I)x + q'x  s.t. A_eq x = b_eq,
    l <= x <= u  with the pre-factorized dense iteration (module
    docstring).  Warm-startable in UNSCALED units like the ADMM."""
    B = vals.shape[0]
    m_eq, n = pat.m, pat.n
    dtype = vals.dtype
    R = int(bank)
    validate_precision(precision)
    if iter_kernel not in ("lax", "pallas"):
        raise ValueError(f"iter_kernel must be lax|pallas, got {iter_kernel!r}")
    if iter_kernel == "pallas" and precision != "f32":
        # The fused window is f32 end-to-end (its residual reduction runs
        # in-kernel); a bf16x3 hot loop composes with the lax path only.
        raise ValueError("iter_kernel='pallas' requires precision='f32'")

    rows = np.asarray(pat.rows)
    cols = np.asarray(pat.cols)
    col_rows = jnp.asarray(pat.col_rows)
    col_src = jnp.asarray(pat.col_src)
    schur = _schur_structure_for(pat)

    if carry_in is None:
        d, e_eq, e_box, c = ruiz_equilibrate_sparse(pat, vals, q,
                                                    iters=ruiz_iters)
    else:
        d, e_eq, e_box, c = lax.cond(
            refresh,
            lambda: ruiz_equilibrate_sparse(pat, vals, q, iters=ruiz_iters),
            lambda: (carry_in.d, carry_in.e_eq, carry_in.e_box, carry_in.c),
        )
    vals_s = e_eq[:, jnp.asarray(rows)] * vals * d[:, jnp.asarray(cols)]
    vp_c_raw = _pad_gather(vals, col_src)          # unscaled, certificates
    w = e_box * d
    qs = c * d * q
    bs = e_eq * b_eq
    ls = e_box * l_box
    us = e_box * u_box
    p_diag = c * d * d * reg

    # The dense scaled Â — materialized per call (it is transient; only
    # the bank persists in the carry).  Both hot-loop matvec directions
    # become batched dense einsums over it: MXU work by construction.
    # ``prec="f32"`` (the default everywhere below except the x-update)
    # is bit-identical to the historical HIGHEST-precision einsums.
    A_dense = jnp.zeros((B, m_eq, n), dtype=dtype).at[:, rows, cols].add(vals_s)

    def mv(x, prec="f32"):
        return mxu_einsum("bmn,bn->bm", A_dense, x, precision=prec)

    def mvt(y, prec="f32"):
        return mxu_einsum("bmn,bm->bn", A_dense, y, precision=prec)

    def mvt_raw(y):
        """A_eqᵀ y with UNSCALED values (infeasibility certificate —
        check-window work, not the MXU hot loop)."""
        return jnp.sum(vp_c_raw * y[:, col_rows], axis=2)

    bank_arr = (jnp.asarray(rho0, dtype)
                * jnp.asarray(rho_factor, dtype)
                ** (jnp.arange(R, dtype=dtype) - R // 2))  # (R,)

    def diag_inv(rho_b):
        return 1.0 / (p_diag + sigma + rho_b[:, None] * w * w)

    def form_S(Dinv):
        """Exact S = Â D⁻¹ Âᵀ at the CURRENT values (bank refresh, the
        fallback tail, and the final-polish refinement)."""
        if schur is not None:
            return scatter_schur(schur, m_eq,
                                 schur_contrib(schur, vals_s, Dinv))
        ADi = A_dense * Dinv[:, None, :]
        return mxu_einsum("bmn,bkn->bmk", ADi, A_dense, precision="f32")

    def build_bank():
        """The pre-factorized operator bank: one equilibrated,
        condition-checked dense inverse per bank rho.  R small dense
        factorizations ONCE per refresh — the price that buys a
        refactorization-free inner loop."""
        slabs = []
        for r in range(R):
            rho_r = jnp.full((B,), 1.0, dtype) * bank_arr[r]
            Sinv_r, _ok = equilibrated_spd_inverse(form_S(diag_inv(rho_r)))
            slabs.append(Sinv_r)
        return jnp.stack(slabs, axis=1)  # (B, R, m, m)

    if carry_in is None:
        Sinv_bank = build_bank()
    else:
        Sinv_bank = lax.cond(refresh, build_bank,
                             lambda: carry_in.Sinv_bank)

    # Warm-start boundary (unscaled → scaled), and the bank index from the
    # rho hint: idx = round(log_factor(rho_warm / rho0)) + center.
    x = jnp.zeros((B, n), dtype=dtype) if x0 is None else (x0.astype(dtype) / d)
    y_box = (jnp.zeros((B, n), dtype=dtype) if y_box0 is None
             else (c * y_box0.astype(dtype) / e_box))
    nu = jnp.zeros((B, m_eq), dtype=dtype)
    z_box = jnp.clip(w * x, ls, us)
    if rho_warm is None:
        idx = jnp.full((B,), R // 2, jnp.int32)
    else:
        lf = jnp.log(jnp.asarray(rho_factor, dtype))
        off = jnp.round(jnp.log(jnp.clip(rho_warm.astype(dtype), 1e-12, None)
                                / rho0) / lf)
        idx = jnp.clip(off.astype(jnp.int32) + R // 2, 0, R - 1)

    def select(idx):
        """(B, m, m) operator slab for each home's current bank index —
        the whole rho adaptation is this gather."""
        return jnp.take_along_axis(
            Sinv_bank, idx[:, None, None, None], axis=1)[:, 0]

    def residuals(x, z_box, nu, y_box):
        """Unscaled residuals + relative scalings (OSQP §3.4, §5.1) —
        identical math to ops/admm.py, dense matvecs.  ALWAYS f32: the
        matvecs here run at full precision whatever the hot-loop policy,
        and the guard fails the trace if a reduced-precision iterate ever
        leaks in un-upcast (ops/precision.py discipline)."""
        x = f32_guard(x, "reluqp residual iterate x")
        y_box = f32_guard(y_box, "reluqp residual dual y_box")
        Ax = mv(x)
        wx = w * x
        r_p_eq = jnp.max(jnp.abs((Ax - bs) / e_eq), axis=1)
        r_p_box = jnp.max(jnp.abs((wx - z_box) / e_box), axis=1)
        r_prim = jnp.maximum(r_p_eq, r_p_box)
        dual = (p_diag * x + qs + mvt(nu) + w * y_box) / (c * d)
        r_dual = jnp.max(jnp.abs(dual), axis=1)
        p_sc = jnp.maximum(
            jnp.maximum(jnp.max(jnp.abs(Ax / e_eq), axis=1),
                        jnp.max(jnp.abs(bs / e_eq), axis=1)),
            jnp.maximum(jnp.max(jnp.abs(wx / e_box), axis=1),
                        jnp.max(jnp.abs(z_box / e_box), axis=1)),
        )
        d_sc = jnp.maximum(
            jnp.max(jnp.abs(mvt(nu) / (c * d)), axis=1),
            jnp.maximum(jnp.max(jnp.abs(w * y_box / (c * d)), axis=1),
                        jnp.max(jnp.abs(qs / (c * d)), axis=1)),
        )
        ok = ((r_prim <= eps_abs + eps_rel * p_sc)
              & (r_dual <= eps_abs + eps_rel * d_sc))
        return r_prim, r_dual, p_sc, d_sc, ok

    def primal_infeasible(dnu, dy_box):
        """OSQP §3.4 certificate on the window's dual-change direction
        (same construction as ops/admm.py)."""
        dnu_u = e_eq * dnu / c
        dy_box_u = e_box * dy_box / c
        At_dy = mvt_raw(dnu_u) + dy_box_u
        norm_dy = jnp.maximum(jnp.max(jnp.abs(dnu_u), axis=1),
                              jnp.max(jnp.abs(dy_box_u), axis=1))
        eps_inf = 1e-4 * jnp.maximum(norm_dy, 1e-12)
        cond1 = jnp.max(jnp.abs(At_dy), axis=1) <= eps_inf
        dy_pos = jnp.maximum(dy_box_u, 0.0)
        dy_neg = jnp.minimum(dy_box_u, 0.0)
        sup = (jnp.sum(b_eq * dnu_u, axis=1)
               + jnp.sum(jnp.where(dy_pos > 0, u_box * dy_pos, 0.0), axis=1)
               + jnp.sum(jnp.where(dy_neg < 0, l_box * dy_neg, 0.0), axis=1))
        return cond1 & (sup <= -eps_inf) & (norm_dy > 1e-10)

    def one_iter(Sinv_sel, Dinv, rho_b, carry):
        """One dense iteration: 3 einsums + clamp — branch-free.  The
        three matmuls run at the configured hot-loop policy; everything
        elementwise stays f32 (the bf16x3 products re-accumulate in f32,
        so the carry never leaves f32)."""
        x, z_box, nu, y_box = carry
        rhs = sigma * x - qs + w * (rho_b[:, None] * z_box - y_box)
        t = mv(Dinv * rhs, precision) - bs
        nu_t = mxu_einsum("bmn,bn->bm", Sinv_sel, t, precision=precision)
        x_t = Dinv * (rhs - mvt(nu_t, precision))
        z_t = w * x_t
        x_new = alpha * x_t + (1.0 - alpha) * x
        v = alpha * z_t + (1.0 - alpha) * z_box + y_box / rho_b[:, None]
        z_new = jnp.clip(v, ls, us)
        y_new = y_box + rho_b[:, None] * (alpha * z_t + (1.0 - alpha) * z_box
                                          - z_new)
        return x_new, z_new, nu_t, y_new

    def window(Sinv_sel, Dinv, rho_b, state, k):
        return lax.fori_loop(
            0, k, lambda _, cc: one_iter(Sinv_sel, Dinv, rho_b, cc), state)

    def window_resid(Sinv_sel, Dinv, rho_b, state, k):
        """One check window + its residual evaluation.  Under the fused
        Pallas kernel both run in ONE launch (ops/pallas_iter.py) with
        the residual-max reduction computed in-kernel f32; the lax path
        is the historical window + residuals composition, bit-identical
        to pre-kernel code."""
        if iter_kernel == "pallas":
            from dragg_tpu.ops import pallas_iter

            st, (r_prim, r_dual, p_sc, d_sc) = pallas_iter.fused_window(
                A_dense, Sinv_sel, Dinv, w, qs, bs, ls, us, rho_b, *state,
                e_eq, e_box, c * d, p_diag, k=k, sigma=sigma, alpha=alpha)
            ok = ((r_prim <= eps_abs + eps_rel * p_sc)
                  & (r_dual <= eps_abs + eps_rel * d_sc))
            return st, (r_prim, r_dual, p_sc, d_sc, ok)
        st = window(Sinv_sel, Dinv, rho_b, state, k)
        return st, residuals(*st)

    def chunk(carry):
        (state, idx, it, _, pinf, best_done, best_r, last_improve,
         conv_it) = carry
        _, _, nu_prev, y_box_prev = state
        rho_b = bank_arr[idx]
        Dinv = diag_inv(rho_b)
        Sinv_sel = select(idx)
        state, res = window_resid(Sinv_sel, Dinv, rho_b, state, check_every)
        x, z_box, nu, y_box = state
        r_prim, r_dual, p_sc, d_sc, ok = res
        pinf = pinf | primal_infeasible(nu - nu_prev, y_box - y_box_prev)
        done = ok | pinf
        it = it + check_every
        conv_it = jnp.where((conv_it < 0) & done, it, conv_it)
        n_done = jnp.sum(done)
        r_tot = r_prim + r_dual
        descending = (r_tot < 0.99 * best_r) & ~done
        improved = (n_done > best_done) | jnp.any(descending)
        best_done = jnp.maximum(best_done, n_done)
        best_r = jnp.minimum(best_r, r_tot)
        last_improve = jnp.where(improved, it, last_improve)
        # Rho adaptation = bank-index arithmetic, EVERY window (it costs a
        # gather, not a refactorization).  Same trigger as the ADMM's
        # continuous update; the geometric grid quantizes the move.
        ratio = jnp.sqrt((r_prim / jnp.maximum(p_sc, 1e-10))
                         / jnp.maximum(r_dual / jnp.maximum(d_sc, 1e-10),
                                       1e-10))
        step = jnp.where(ratio > 5.0, 1, jnp.where(ratio < 0.2, -1, 0))
        idx = jnp.clip(idx + jnp.where(done, 0, step), 0, R - 1)
        return (state, idx, it, jnp.all(done), pinf, best_done, best_r,
                last_improve, conv_it)

    def cond(carry):
        it, all_done, last_improve = carry[2], carry[3], carry[7]
        keep = (it < iters) & (~all_done)
        if patience > 0:
            keep = keep & (it - last_improve < patience * check_every)
        return keep

    carry0 = ((x, z_box, nu, y_box), idx, jnp.asarray(0), jnp.asarray(False),
              jnp.zeros((B,), bool), jnp.asarray(-1),
              jnp.full((B,), jnp.inf, dtype=dtype), jnp.asarray(0),
              jnp.full((B,), -1, dtype=jnp.int32))
    out = lax.while_loop(cond, chunk, carry0)
    state, idx, it, _, pinf, conv_it = (out[0], out[1], out[2], out[3],
                                        out[4], out[8])
    x, z_box, nu, y_box = state
    r_prim, r_dual, _, _, ok = residuals(x, z_box, nu, y_box)

    # --- Fallback exact-refactorization tail: homes the banked loop left
    # neither converged nor certified get ONE exact factorization at
    # their CURRENT rho (fresh values, continuous — not bank-quantized
    # staleness) and a bounded extra run.  This is the only O(Bm³) work
    # the family does inside a step; ``bank_fallback`` reports who needed
    # it so artifacts can state whether the pre-factorized path sufficed.
    need_tail = ~(ok | pinf)
    fallback = jnp.zeros((B,), bool)
    if tail_iters > 0:
        def run_tail(args):
            x, z_box, nu, y_box, conv_it = args
            rho_b = bank_arr[idx]
            Dinv = diag_inv(rho_b)
            Sinv_ex, _okf = equilibrated_spd_inverse(form_S(Dinv))
            st = window(Sinv_ex, Dinv, rho_b, (x, z_box, nu, y_box),
                        tail_iters)
            x2, z2, nu2, y2 = st
            # Only the homes that NEEDED the tail adopt its iterate —
            # converged homes keep their certified solution bit-exact.
            m1 = need_tail[:, None]
            x = jnp.where(m1, x2, x)
            z_box = jnp.where(m1, z2, z_box)
            nu = jnp.where(m1, nu2, nu)
            y_box = jnp.where(m1, y2, y_box)
            conv_it = jnp.where(need_tail & (conv_it < 0), it + tail_iters,
                                conv_it)
            return x, z_box, nu, y_box, conv_it

        any_tail = jnp.any(need_tail)
        x, z_box, nu, y_box, conv_it = lax.cond(
            any_tail, run_tail, lambda a: a, (x, z_box, nu, y_box, conv_it))
        it = it + jnp.where(any_tail, tail_iters, 0)
        fallback = need_tail & any_tail
        r_prim, r_dual, _, _, ok = residuals(x, z_box, nu, y_box)

    # Final polish: D-weighted projection onto the equality manifold with
    # refinement against the EXACT current S (absorbs the bank's
    # between-refresh staleness, same role as the ADMM polish).
    rho_b = bank_arr[idx]
    Dinv = diag_inv(rho_b)
    S_ex = form_S(Dinv)
    Sinv_sel = select(idx)

    def s_solve(r):
        # Polish/refinement path: pinned f32 — it corrects the hot loop's
        # (possibly reduced-precision) iterate against the exact S.
        pinv = lambda rr: mxu_einsum("bmn,bn->bm", Sinv_sel, rr,
                                     precision="f32")
        v = pinv(r)
        for _ in range(2):
            resid = r - mxu_einsum("bmn,bn->bm", S_ex, v, precision="f32")
            v = v + pinv(resid)
        return v

    x = x - Dinv * mvt(s_solve(mv(x) - bs))

    x_out = jnp.clip(d * x, l_box, u_box)
    sol = ADMMSolution(
        x=x_out, y_eq=e_eq * nu / c, y_box=e_box * y_box / c,
        r_prim=r_prim, r_dual=r_dual, solved=ok & ~pinf, infeasible=pinf,
        iters=it, rho=bank_arr[idx],
        conv_iters=jnp.where(conv_it < 0, it, conv_it).astype(jnp.int32),
        diverged=pinf,
        bank_fallback=fallback,
    )
    return sol, ReLUQPCarry(d=d, e_eq=e_eq, e_box=e_box, c=c,
                            Sinv_bank=Sinv_bank)


# sigma/alpha are config constants and must be STATIC: the fused window
# kernel (ops/pallas_iter.py) bakes them into the compiled program — a
# traced scalar would fail the pallas_call lowering (and they never vary
# within a run anyway).
_STATIC = ("pat", "bank", "iters", "check_every", "ruiz_iters", "patience",
           "tail_iters", "precision", "iter_kernel", "sigma", "alpha")


@partial(jax.jit, static_argnames=_STATIC)
def reluqp_solve_qp(pat, vals, b_eq, l_box, u_box, q, **kwargs) -> ADMMSolution:
    """One-shot solve (scalings + bank built in-call).  See
    :func:`_reluqp_impl` for parameters."""
    sol, _ = _reluqp_impl(pat, vals, b_eq, l_box, u_box, q, **kwargs)
    return sol


@partial(jax.jit, static_argnames=_STATIC)
def reluqp_solve_qp_cached(pat, vals, b_eq, l_box, u_box, q, carry_in,
                           refresh, **kwargs) -> tuple[ADMMSolution,
                                                       ReLUQPCarry]:
    """MPC-mode solve with the cross-timestep bank cache: reuses
    ``carry_in``'s Ruiz scalings and Sinv bank unless the traced
    ``refresh`` flag fires (the engine's ``admm_refactor_every``
    cadence).  Returns the solution plus the carry for the next step."""
    return _reluqp_impl(pat, vals, b_eq, l_box, u_box, q, carry_in=carry_in,
                        refresh=refresh, **kwargs)
