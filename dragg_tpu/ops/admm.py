"""Batched OSQP-style ADMM for the per-home MPC QPs.

Replaces the reference's per-home native MILP solvers (GLPK_MI / ECOS /
GUROBI via CVXPY, dragg/mpc_calc.py:141-145,451) with one batched,
fixed-shape ADMM solve over the entire community: a single factorization +
iteration loop with all ops carrying the home batch dim, so XLA maps the
batched matmuls onto the MXU and the whole thing shards over a device mesh
along the home axis.

Algorithm (OSQP, Stellato et al. 2020) specialized to our structure — the
dynamics rows are hard equalities and every variable carries box bounds —
with **equality elimination**: only the box block goes through the ADMM
splitting, while ``A_eq x = b_eq`` is enforced exactly inside every x-update
through the KKT system

    [[D, A_eqᵀ], [A_eq, 0]] [x; ν] = [rhs; b_eq],   D = diag(P + σ + ρ w²),

solved via the Schur complement ``S = A_eq D⁻¹ A_eqᵀ`` (m_eq × m_eq, SPD).
Compared to folding the equalities into the splitting with a stiff rho
(OSQP's l==u handling), this

* removes the 1e3 rho scale whose normal equations are un-invertible in
  float32 (TPU has no fast f64),
* zeroes the equality primal residual at every iteration — convergence is
  governed by the box block alone,
* shrinks the factored matrix from n×n (9H+5) to m_eq×m_eq (3H+5).

TPU-native linear algebra: ``S⁻¹`` is formed EXPLICITLY once per
refactorization (two batched matrix-matrix triangular solves off a
Cholesky — MXU-shaped), so every iteration's KKT solve is pure batched
matmul; one iterative-refinement step against the stored ``S`` recovers
float32 accuracy.  Per-iteration triangular solves with a single RHS would
serialize on the substitution recurrence and starve the MXU.

Robustness features for 10^4–10^5 heterogeneous homes, all batched:

* modified Ruiz equilibration (per-home diagonal row/col scalings) — the box
  block stays diagonal under scaling, so its matvecs remain elementwise;
* per-home adaptive rho with periodic refactorization at chunk boundaries;
* OSQP §3.4 primal-infeasibility certificates (box ∩ dynamics = ∅ — e.g. an
  initial temperature pinned outside the comfort band).

Solutions whose residuals fail tolerance after the iteration budget are
flagged unsolved; the engine routes exactly those homes through the fallback
controller — the batched analog of the reference's try/except around
prob.solve (dragg/mpc_calc.py:450-454).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

RHO_MIN, RHO_MAX = 1e-6, 1e6


class ADMMSolution(NamedTuple):
    x: jnp.ndarray        # (B, n) primal solution (unscaled, box-projected)
    y_eq: jnp.ndarray     # (B, m_eq) duals on equality rows (UNSCALED units)
    y_box: jnp.ndarray    # (B, n) duals on box rows (UNSCALED units)
    r_prim: jnp.ndarray   # (B,) inf-norm primal residual (unscaled)
    r_dual: jnp.ndarray   # (B,) inf-norm dual residual (unscaled, cost-descaled)
    solved: jnp.ndarray   # (B,) bool
    infeasible: jnp.ndarray  # (B,) bool — certified primal-infeasible (OSQP §3.4)
    iters: jnp.ndarray    # scalar iterations executed
    rho: jnp.ndarray      # (B,) final per-home rho (for warm starting)


def _mv(A, v):
    return jnp.einsum("bmn,bn->bm", A, v, precision=lax.Precision.HIGHEST)


def _mv_t(A, v):
    return jnp.einsum("bmn,bm->bn", A, v, precision=lax.Precision.HIGHEST)


def ruiz_equilibrate(A_eq, q, iters: int = 10):
    """Modified Ruiz equilibration of the stacked constraint matrix
    [A_eq; I] plus cost normalization.

    Returns (d, e_eq, e_box, c): per-home column scaling d (n,), row
    scalings for the equality and box blocks, and the scalar cost scaling.
    The scaled matrix is diag(e)[A_eq; I]diag(d); the box block becomes
    diag(e_box * d) — still diagonal.
    """
    B, m_eq, n = A_eq.shape
    dtype = A_eq.dtype
    d = jnp.ones((B, n), dtype=dtype)
    e_eq = jnp.ones((B, m_eq), dtype=dtype)
    e_box = jnp.ones((B, n), dtype=dtype)

    def body(_, carry):
        d, e_eq, e_box = carry
        As = e_eq[:, :, None] * A_eq * d[:, None, :]
        w_box = e_box * d
        # Row inf-norms.
        r_eq = jnp.max(jnp.abs(As), axis=2)
        r_box = jnp.abs(w_box)
        e_eq = e_eq / jnp.sqrt(jnp.maximum(r_eq, 1e-8))
        e_box = e_box / jnp.sqrt(jnp.maximum(r_box, 1e-8))
        # Column inf-norms (over both blocks), using updated rows.
        As = e_eq[:, :, None] * A_eq * d[:, None, :]
        w_box = e_box * d
        c_eq = jnp.max(jnp.abs(As), axis=1)
        cn = jnp.maximum(c_eq, jnp.abs(w_box))
        d = d / jnp.sqrt(jnp.maximum(cn, 1e-8))
        return d, e_eq, e_box

    d, e_eq, e_box = lax.fori_loop(0, iters, body, (d, e_eq, e_box))
    # Cost scaling: normalize mean scaled-gradient magnitude (OSQP sec. 5.1).
    qn = jnp.max(jnp.abs(d * q), axis=1, keepdims=True)
    c = 1.0 / jnp.maximum(qn, 1e-8)
    return d, e_eq, e_box, c


@partial(jax.jit, static_argnames=("iters", "check_every", "ruiz_iters", "adaptive_rho"))
def admm_solve(
    A_eq: jnp.ndarray,       # (B, m_eq, n)
    b_eq: jnp.ndarray,       # (B, m_eq)
    l_box: jnp.ndarray,      # (B, n)
    u_box: jnp.ndarray,      # (B, n)
    q: jnp.ndarray,          # (B, n)
    *,
    rho: float = 0.1,
    sigma: float = 1e-6,
    alpha: float = 1.6,
    eps_abs: float = 1e-4,
    eps_rel: float = 1e-4,
    reg: float = 1e-8,       # quadratic regularization (P = reg I): the MPC
                             # objective is linear (SURVEY.md §7 step 2)
    iters: int = 1000,
    check_every: int = 25,
    ruiz_iters: int = 10,
    adaptive_rho: bool = True,
    x0: jnp.ndarray | None = None,
    y_box0: jnp.ndarray | None = None,
    rho0: jnp.ndarray | None = None,
) -> ADMMSolution:
    """Solve B problems  min 1/2 x'(reg I)x + q'x  s.t. A_eq x = b_eq,
    l <= x <= u  simultaneously.  Warm-startable via x0/y_box0/rho0
    (the equality dual is recomputed from the KKT solve every iteration, so
    it takes no warm start).
    All warm-start quantities are in UNSCALED (original-problem) units — the
    internal Ruiz/cost scaling is recomputed per call and applied at the
    boundary, so warm starts transfer across calls whose matrices differ
    (e.g. consecutive MPC timesteps where only the water-mix band, RHS, and
    price vector move)."""
    B, m_eq, n = A_eq.shape
    dtype = A_eq.dtype

    d, e_eq, e_box, c = ruiz_equilibrate(A_eq, q, iters=ruiz_iters)
    As = e_eq[:, :, None] * A_eq * d[:, None, :]
    w = e_box * d                      # diagonal of the scaled box block
    qs = c * d * q
    bs = e_eq * b_eq
    ls = e_box * l_box
    us = e_box * u_box
    p_diag = c * d * d * reg           # scaled P diagonal

    eye_m = jnp.eye(m_eq, dtype=dtype)

    def factor(rho_b):
        """Schur-complement factor of the equality-constrained x-update.

        Returns (Dinv, Sinv, S): D = diag(P̂+σ+ρŵ²);  S = Â D⁻¹ Âᵀ (SPD,
        m_eq×m_eq); S⁻¹ formed explicitly via Cholesky + two batched
        matrix-matrix triangular solves so the per-iteration solve is pure
        batched matmul; S kept for one refinement step.
        """
        Dinv = 1.0 / (p_diag + sigma + rho_b[:, None] * w * w)
        ADi = As * Dinv[:, None, :]
        S = jnp.einsum("bmn,bkn->bmk", ADi, As, precision=lax.Precision.HIGHEST)
        L = jnp.linalg.cholesky(S)
        Linv = lax.linalg.triangular_solve(
            L, jnp.broadcast_to(eye_m, S.shape), left_side=True, lower=True
        )
        Sinv = jnp.einsum("bkm,bkn->bmn", Linv, Linv, precision=lax.Precision.HIGHEST)
        return Dinv, Sinv, S

    def s_solve(F, r):
        """S⁻¹ r with one iterative-refinement step (recovers f32 accuracy
        of the explicit inverse; three batched matmuls, MXU-bound)."""
        _, Sinv, S = F
        v = jnp.einsum("bmn,bn->bm", Sinv, r, precision=lax.Precision.HIGHEST)
        resid = r - jnp.einsum("bmn,bn->bm", S, v, precision=lax.Precision.HIGHEST)
        return v + jnp.einsum("bmn,bn->bm", Sinv, resid, precision=lax.Precision.HIGHEST)

    def kkt_solve(F, rhs):
        """x-update KKT solve: x = D⁻¹(rhs − Âᵀν), ν = S⁻¹(Â D⁻¹ rhs − b̂).
        Equalities hold to solver accuracy at EVERY iterate."""
        Dinv = F[0]
        nu = s_solve(F, _mv(As, Dinv * rhs) - bs)
        return Dinv * (rhs - _mv_t(As, nu)), nu

    rho_b = jnp.full((B,), rho, dtype=dtype) if rho0 is None else rho0.astype(dtype)
    x = jnp.zeros((B, n), dtype=dtype) if x0 is None else (x0.astype(dtype) / d)
    # Unscaled → scaled duals: y = E ŷ / c  ⇒  ŷ = c y / e.
    nu = jnp.zeros((B, m_eq), dtype=dtype)
    y_box = jnp.zeros((B, n), dtype=dtype) if y_box0 is None else (c * y_box0.astype(dtype) / e_box)
    z_box = jnp.clip(w * x, ls, us)

    def residuals(x, z_box, nu, y_box):
        """Unscaled residuals + relative scalings (OSQP sec. 3.4, 5.1)."""
        Ax = _mv(As, x)
        wx = w * x
        r_p_eq = jnp.max(jnp.abs((Ax - bs) / e_eq), axis=1)
        r_p_box = jnp.max(jnp.abs((wx - z_box) / e_box), axis=1)
        r_prim = jnp.maximum(r_p_eq, r_p_box)
        dual = (p_diag * x + qs + _mv_t(As, nu) + w * y_box) / (c * d)
        r_dual = jnp.max(jnp.abs(dual), axis=1)
        p_sc = jnp.maximum(
            jnp.maximum(jnp.max(jnp.abs(Ax / e_eq), axis=1), jnp.max(jnp.abs(bs / e_eq), axis=1)),
            jnp.maximum(jnp.max(jnp.abs(wx / e_box), axis=1), jnp.max(jnp.abs(z_box / e_box), axis=1)),
        )
        d_sc = jnp.maximum(
            jnp.max(jnp.abs(_mv_t(As, nu) / (c * d)), axis=1),
            jnp.maximum(
                jnp.max(jnp.abs(w * y_box / (c * d)), axis=1),
                jnp.max(jnp.abs(qs / (c * d)), axis=1),
            ),
        )
        ok = (r_prim <= eps_abs + eps_rel * p_sc) & (r_dual <= eps_abs + eps_rel * d_sc)
        return r_prim, r_dual, p_sc, d_sc, ok

    def one_iter(F, rho_b, carry):
        x, z_box, nu, y_box = carry
        rhs = sigma * x - qs + w * (rho_b[:, None] * z_box - y_box)
        x_t, nu_t = kkt_solve(F, rhs)
        z_t_box = w * x_t
        x_new = alpha * x_t + (1.0 - alpha) * x
        v = alpha * z_t_box + (1.0 - alpha) * z_box + y_box / rho_b[:, None]
        z_box_new = jnp.clip(v, ls, us)
        y_box_new = y_box + rho_b[:, None] * (alpha * z_t_box + (1.0 - alpha) * z_box - z_box_new)
        return x_new, z_box_new, nu_t, y_box_new

    def primal_infeasible(dnu, dy_box):
        """OSQP primal-infeasibility certificate (Stellato et al. §3.4) on
        the dual-change direction accumulated over one check window.  An
        infeasible QP's duals diverge along a ray δy with A'δy = 0 and
        support value u'(δy)+ + l'(δy)- < 0; detecting it lets certifiably
        infeasible homes exit the iteration loop instead of burning the full
        budget (they route to the fallback controller regardless)."""
        dnu_u = e_eq * dnu / c              # unscale: y = E ŷ / c
        dy_box_u = e_box * dy_box / c
        At_dy = _mv_t(A_eq, dnu_u) + dy_box_u
        norm_dy = jnp.maximum(
            jnp.max(jnp.abs(dnu_u), axis=1), jnp.max(jnp.abs(dy_box_u), axis=1)
        )
        eps_inf = 1e-4 * jnp.maximum(norm_dy, 1e-12)
        cond1 = jnp.max(jnp.abs(At_dy), axis=1) <= eps_inf
        dy_pos = jnp.maximum(dy_box_u, 0.0)
        dy_neg = jnp.minimum(dy_box_u, 0.0)
        # inf bounds: a nonzero δy component against an infinite bound makes
        # the support value +inf, correctly blocking the certificate (the
        # non-selected inf*0 branch of the where is discarded).
        sup = (
            jnp.sum(b_eq * dnu_u, axis=1)
            + jnp.sum(jnp.where(dy_pos > 0, u_box * dy_pos, 0.0), axis=1)
            + jnp.sum(jnp.where(dy_neg < 0, l_box * dy_neg, 0.0), axis=1)
        )
        cond2 = sup <= -eps_inf
        return cond1 & cond2 & (norm_dy > 1e-10)

    def chunk(carry):
        state, rho_b, F, it, _, pinf = carry
        x0_, z0_, nu_prev, y_box_prev = state
        state = lax.fori_loop(0, check_every, lambda _, cc: one_iter(F, rho_b, cc), state)
        x, z_box, nu, y_box = state
        r_prim, r_dual, p_sc, d_sc, ok = residuals(x, z_box, nu, y_box)
        pinf = pinf | primal_infeasible(nu - nu_prev, y_box - y_box_prev)
        done = ok | pinf
        if adaptive_rho:
            ratio = jnp.sqrt(
                (r_prim / jnp.maximum(p_sc, 1e-10)) / jnp.maximum(r_dual / jnp.maximum(d_sc, 1e-10), 1e-10)
            )
            rho_new = jnp.clip(rho_b * ratio, RHO_MIN, RHO_MAX)
            update = (ratio > 5.0) | (ratio < 0.2)
            rho_next = jnp.where(update & ~done, rho_new, rho_b)
            F = lax.cond(jnp.any(rho_next != rho_b), factor, lambda _: F, rho_next)
            rho_b = rho_next
        return state, rho_b, F, it + check_every, jnp.all(done), pinf

    def cond(carry):
        _, _, _, it, all_done, _ = carry
        return (it < iters) & (~all_done)

    F = factor(rho_b)
    state = (x, z_box, nu, y_box)
    pinf0 = jnp.zeros((B,), dtype=bool)
    state, rho_b, F, it, _, pinf = lax.while_loop(
        cond, chunk, (state, rho_b, F, jnp.asarray(0), jnp.asarray(False), pinf0)
    )
    x, z_box, nu, y_box = state
    r_prim, r_dual, _, _, ok = residuals(x, z_box, nu, y_box)

    # Final polish: D-weighted projection of the iterate onto the equality
    # manifold (one extra Schur solve) — drives the dynamics-row violation to
    # solve accuracy so downstream physics sees consistent trajectories.
    Dinv = F[0]
    x = x - Dinv * _mv_t(As, s_solve(F, _mv(As, x) - bs))

    # Unscale and box-project the primal so downstream physics sees in-bound
    # values even at loose tolerance.
    x_out = jnp.clip(d * x, l_box, u_box)
    return ADMMSolution(
        x=x_out, y_eq=e_eq * nu / c, y_box=e_box * y_box / c,
        r_prim=r_prim, r_dual=r_dual, solved=ok & ~pinf, infeasible=pinf,
        iters=it, rho=rho_b,
    )
