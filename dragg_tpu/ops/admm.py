"""Batched OSQP-style ADMM for the per-home MPC QPs.

Replaces the reference's per-home native MILP solvers (GLPK_MI / ECOS /
GUROBI via CVXPY, dragg/mpc_calc.py:141-145,451) with one batched,
fixed-shape ADMM solve over the entire community: a single factorization +
iteration loop with all ops carrying the home batch dim, so XLA maps the
batched work onto the TPU vector/matrix units and the whole thing shards
over a device mesh along the home axis.

Algorithm (OSQP, Stellato et al. 2020) specialized to our structure — the
dynamics rows are hard equalities and every variable carries box bounds —
with **equality elimination**: only the box block goes through the ADMM
splitting, while ``A_eq x = b_eq`` is enforced exactly inside every x-update
through the KKT system

    [[D, A_eqᵀ], [A_eq, 0]] [x; ν] = [rhs; b_eq],   D = diag(P + σ + ρ w²),

solved via the Schur complement ``S = A_eq D⁻¹ A_eqᵀ`` (m_eq × m_eq, SPD).

**Sparse hot loop.** A_eq is the banded RC-dynamics matrix: ≤4 nonzeros per
row/column (dragg/mpc_calc.py:311-342 — each temperature couples to its
neighbor, one control, and the OAT forcing).  Dense per-home matvecs made
the solver HBM-bound (A alone is m·n·4 bytes per home per iteration); the
iteration now uses the gather-padded sparse pattern from
:class:`dragg_tpu.ops.qp.SparsePattern` — both matvec directions are pure
gathers + elementwise sums (no scatter on the TPU hot path), cutting
per-iteration A traffic and FLOPs by ~40×.  The dense m×m Schur complement
is still formed at (rare) refactorizations; its explicit inverse keeps the
per-iteration solve as one batched matmul + one refinement pass.

Proximal regularization: the MPC objective is linear, and ADMM on a pure LP
has no strong convexity — at H=24 with reg≈0, 819/1000 homes missed
tolerance in 1000 iterations.  The default ``reg=1e-3`` makes every home
solve in ~300 cold-start iterations at ≤0.35 % objective gap vs HiGHS
(measured over 64 real mixed homes at 24 h horizon) — inside the ≤1 %
parity budget (BASELINE.md).

Robustness features for 10^4–10^5 heterogeneous homes, all batched:

* modified Ruiz equilibration (per-home diagonal row/col scalings) — the box
  block stays diagonal under scaling, so its matvecs remain elementwise;
* per-home adaptive rho with periodic refactorization at chunk boundaries;
* OSQP §3.4 primal-infeasibility certificates (box ∩ dynamics = ∅ — e.g. an
  initial temperature pinned outside the comfort band);
* stagnation early-exit: in lockstep batch ADMM one pathological home would
  burn the entire iteration budget for the whole community; when no
  additional home converges or certifies for ``patience`` check windows
  (and residuals have stopped descending), the loop exits and the
  stragglers are flagged unsolved.

Solutions whose residuals fail tolerance are flagged unsolved; the engine
routes exactly those homes through the fallback controller — the batched
analog of the reference's try/except around prob.solve
(dragg/mpc_calc.py:450-454).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dragg_tpu.ops import pallas_band
from dragg_tpu.ops.banded import banded_explicit_inverse, plan_for
from dragg_tpu.ops.precision import f32_guard, mxu_einsum, validate_precision
from dragg_tpu.ops.qp import (
    SparsePattern,
    build_schur_structure,
    form_schur_sparse,
    scatter_schur,
    schur_contrib,
)

RHO_MIN, RHO_MAX = 1e-6, 1e6


class FactorCarry(NamedTuple):
    """Cross-timestep solver cache (MPC mode): the Ruiz/cost scalings and
    the explicit Schur inverse, carried through the simulation scan so
    consecutive timesteps — whose matrices differ only in the water-mix
    band (dragg_tpu/ops/qp.py:19-22) — skip the equilibration and the
    O(Bm³) refactorization.  The solve's iterative-refinement step absorbs
    the small stale-factor drift; a periodic ``refresh`` re-equilibrates
    and refactors exactly."""

    d: jnp.ndarray      # (B, n) column scaling
    e_eq: jnp.ndarray   # (B, m) equality-row scaling
    e_box: jnp.ndarray  # (B, n) box-row scaling
    c: jnp.ndarray      # (B, 1) cost scaling
    Sinv: jnp.ndarray   # the Schur factor: explicit inverse (B, m, m) in
                        # dense_inv mode, band Cholesky (B, m, bw+1) in
                        # band mode (see resolve_backend)


BAND_AUTO_BYTES = 1 << 30  # "auto": go banded when the PER-SHARD Sinv
                           # would exceed this


def resolve_backend(solve_backend: str, B: int, m: int, has_plan: bool,
                    elem_bytes: int = 4, n_shards: int = 1) -> str:
    """Resolve the in-loop solve backend (see ``_admm_impl``'s
    ``solve_backend`` parameter).  The budget is per device shard — the
    engine layer resolves "auto" with its mesh size and element width and
    passes an explicit backend down, so the factor carry is sized
    consistently; direct solver callers default to one shard."""
    if solve_backend == "band":
        if not has_plan:
            raise ValueError("solve_backend='band' needs a banded Schur pattern")
        return "band"
    if solve_backend == "dense_inv":
        return "dense_inv"
    if solve_backend != "auto":
        raise ValueError(f"unknown solve_backend {solve_backend!r}")
    if has_plan and elem_bytes * B * m * m > BAND_AUTO_BYTES * max(1, n_shards):
        return "band"
    return "dense_inv"


@lru_cache(maxsize=32)
def _schur_structure_for(pat: SparsePattern):
    """Schur triple lists for a pattern, or None when the dense einsum
    formation is cheaper (e.g. the fully-dense test pattern, where the
    triple list would be m²·n entries).  The triple count Σ_k c_k² is
    checked from the column counts BEFORE building anything, so a dense
    pattern never pays the Python enumeration."""
    col_counts = np.bincount(np.asarray(pat.cols), minlength=pat.n)
    if int(np.sum(col_counts.astype(np.int64) ** 2)) > pat.m * pat.n:
        return None
    ss = build_schur_structure(pat)
    if ss.n_s * ss.P > pat.m * pat.n:
        return None
    return ss


class ADMMSolution(NamedTuple):
    x: jnp.ndarray        # (B, n) primal solution (unscaled, box-projected)
    y_eq: jnp.ndarray     # (B, m_eq) duals on equality rows (UNSCALED units)
    y_box: jnp.ndarray    # (B, n) duals on box rows (UNSCALED units)
    r_prim: jnp.ndarray   # (B,) inf-norm primal residual (unscaled)
    r_dual: jnp.ndarray   # (B,) inf-norm dual residual (unscaled, cost-descaled)
    solved: jnp.ndarray   # (B,) bool
    infeasible: jnp.ndarray  # (B,) bool — certified primal-infeasible (OSQP §3.4)
    iters: jnp.ndarray    # scalar iterations executed
    rho: jnp.ndarray      # (B,) final per-home rho (for warm starting)
    # Observatory extras (round 9) — trailing defaults so existing
    # construction sites (tests included) stay valid.  ``conv_iters`` is
    # the iteration at which each home first satisfied the loop-internal
    # convergence check (the full budget if it never did) — the per-home
    # attribution the community-wide scalar ``iters`` cannot give;
    # ``diverged`` is the per-home certified-divergence verdict (ADMM: the
    # OSQP infeasibility certificate; IPM: the divergence freeze).
    conv_iters: jnp.ndarray | None = None  # (B,) int32
    diverged: jnp.ndarray | None = None    # (B,) bool
    # ReLU-QP extra (round 10) — which homes entered the rho bank's
    # fallback exact-refactorization tail (ops/reluqp.py; None for the
    # families without a bank).  Trailing default keeps every existing
    # construction site valid.
    bank_fallback: jnp.ndarray | None = None  # (B,) bool


def _pad_gather(vals, src):
    """(B, nnz) values → padded (B, *src.shape) with -1 slots zeroed."""
    src_ix = jnp.maximum(src, 0)
    out = vals[:, src_ix]
    return jnp.where(src[None] >= 0, out, 0.0)


def ruiz_equilibrate_sparse(pat: SparsePattern, vals, q, iters: int = 10):
    """Modified Ruiz equilibration of the stacked constraint matrix
    [A_eq; I] plus cost normalization, entirely on the sparse values.

    Returns (d, e_eq, e_box, c): per-home column scaling d (n,), row
    scalings for the equality and box blocks, and the scalar cost scaling.
    The scaled matrix is diag(e)[A_eq; I]diag(d); the box block becomes
    diag(e_box * d) — still diagonal.
    """
    B = vals.shape[0]
    dtype = vals.dtype
    rows = jnp.asarray(pat.rows)
    cols = jnp.asarray(pat.cols)
    row_src = jnp.asarray(pat.row_src)
    col_src = jnp.asarray(pat.col_src)
    d = jnp.ones((B, pat.n), dtype=dtype)
    e_eq = jnp.ones((B, pat.m), dtype=dtype)
    e_box = jnp.ones((B, pat.n), dtype=dtype)

    def scaled_abs(d, e_eq):
        return jnp.abs(e_eq[:, rows] * vals * d[:, cols])

    def body(_, carry):
        d, e_eq, e_box = carry
        a = scaled_abs(d, e_eq)
        r_eq = jnp.max(_pad_gather(a, row_src), axis=2)
        r_box = jnp.abs(e_box * d)
        # Degenerate (all-zero) rows keep their scaling: repeatedly dividing
        # by sqrt(eps) would overflow e to inf within the iteration budget
        # (zero rows arise from per-home fixed-variable elimination in the
        # IPM path — a zeroed battery block leaves its dynamics rows empty).
        e_eq = jnp.where(r_eq > 1e-8, e_eq / jnp.sqrt(jnp.maximum(r_eq, 1e-8)), e_eq)
        e_box = jnp.where(r_box > 1e-8, e_box / jnp.sqrt(jnp.maximum(r_box, 1e-8)), e_box)
        a = scaled_abs(d, e_eq)
        c_eq = jnp.max(_pad_gather(a, col_src), axis=2)
        cn = jnp.maximum(c_eq, jnp.abs(e_box * d))
        d = jnp.where(cn > 1e-8, d / jnp.sqrt(jnp.maximum(cn, 1e-8)), d)
        return d, e_eq, e_box

    d, e_eq, e_box = lax.fori_loop(0, iters, body, (d, e_eq, e_box))
    qn = jnp.max(jnp.abs(d * q), axis=1, keepdims=True)
    c = 1.0 / jnp.maximum(qn, 1e-8)
    return d, e_eq, e_box, c


def _admm_impl(
    pat: SparsePattern,      # static sparsity (hashable NamedTuple of numpy)
    vals: jnp.ndarray,       # (B, nnz) A_eq values
    b_eq: jnp.ndarray,       # (B, m_eq)
    l_box: jnp.ndarray,      # (B, n)
    u_box: jnp.ndarray,      # (B, n)
    q: jnp.ndarray,          # (B, n)
    *,
    rho: float = 0.1,
    sigma: float = 1e-6,
    alpha: float = 1.6,
    eps_abs: float = 1e-4,
    eps_rel: float = 1e-4,
    reg: float = 1e-3,       # proximal quadratic regularization (see module docstring)
    iters: int = 1000,
    check_every: int = 25,
    ruiz_iters: int = 10,
    adaptive_rho: bool = True,
    rho_update_every: int = 4,  # rho-update cadence in check windows: each
                                # in-loop rho change pays an O(Bm³) batched
                                # refactorization (at B=10⁴ that dominated
                                # the whole solve), so updates are considered
                                # every Nth residual check, not every one
    patience: int = 4,       # stagnation exit in check windows; 0 disables
    matvec_dtype: str = "f32",  # "bf16": store Sinv in bfloat16 — halves the
                                # HBM traffic of the dominant per-iteration
                                # matvec; refinement against the f32 S
                                # recovers accuracy (opt-in: effective only
                                # when cond(Ŝ) stays modest)
    precision: str = "f32",  # hot-loop matmul policy (ops/precision.py):
                             # "bf16x3" runs the dense_inv backend's
                             # per-iteration Sinv apply as 3-pass bf16
                             # with f32 accumulation; residuals,
                             # refinement, and the factorization stay
                             # f32 (round-2/9 negative results).  The
                             # band backend has no dense matmuls and
                             # ignores the policy.
    refine: int = 1,         # iterative-refinement passes per in-loop solve
    banded_factor: bool = True,  # factor S via RCM + banded Cholesky scans
                                 # (O(Bm·bw²)) instead of batched dense
                                 # Cholesky + triangular solves (O(Bm³));
                                 # automatic dense fallback when the pattern
                                 # is not banded (plan_for returns None)
    solve_backend: str = "auto",  # in-loop KKT solve:
                                  # "dense_inv": explicit (B, m, m) Sinv,
                                  #   one batched matvec per solve;
                                  # "band": banded substitution scans —
                                  #   no (B, m, m) array exists at all
                                  #   (the 100k-home × H=48 memory regime:
                                  #   Sinv would be ~2.2 GB per 25k-home
                                  #   shard, the band factor is ~36 MB);
                                  # "auto": band when the Sinv would
                                  #   exceed ~1 GB and the pattern is
                                  #   banded, else dense_inv
    band_kernel: str = "xla",  # "pallas": fused TPU kernels for the band
                               # factor/solve (ops/pallas_band.py) — the
                               # factor carry then holds TRANSPOSED
                               # (m, bw+1, B) band storage; "xla": scan path
    mesh=None,                 # sharded engines: shard_map the pallas
    mesh_axis: str = "homes",  # kernels over this mesh axis
    anderson: int = 0,       # Anderson-acceleration history depth (0 = off).
                             # Type-II AA applied once per check window on
                             # the (z, y) pair — the window map T^check_every
                             # is a fixed-point map on (z, y) since sigma~0 —
                             # with a per-home residual safeguard that
                             # reverts to the plain iterate and clears the
                             # home's history when acceleration regresses
    x0: jnp.ndarray | None = None,
    y_box0: jnp.ndarray | None = None,
    rho0: jnp.ndarray | None = None,
    carry_in: FactorCarry | None = None,
    refresh=None,            # traced bool — recompute scalings + factor
) -> tuple[ADMMSolution, FactorCarry]:
    """Solve B problems  min 1/2 x'(reg I)x + q'x  s.t. A_eq x = b_eq,
    l <= x <= u  simultaneously, with A_eq given sparsely.  Warm-startable
    via x0/y_box0/rho0 in UNSCALED units (the internal Ruiz/cost scaling is
    applied at the boundary, so warm starts transfer across calls whose
    matrices differ — e.g. consecutive MPC timesteps).  With ``carry_in``
    the scalings and Schur factor are reused unless ``refresh`` fires."""
    B = vals.shape[0]
    m_eq, n = pat.m, pat.n
    dtype = vals.dtype
    validate_precision(precision)
    store_dtype = jnp.bfloat16 if matvec_dtype == "bf16" else dtype

    rows = jnp.asarray(pat.rows)
    cols = jnp.asarray(pat.cols)
    row_cols = jnp.asarray(pat.row_cols)
    row_src = jnp.asarray(pat.row_src)
    col_rows = jnp.asarray(pat.col_rows)
    col_src = jnp.asarray(pat.col_src)
    schur = _schur_structure_for(pat)

    if carry_in is None:
        d, e_eq, e_box, c = ruiz_equilibrate_sparse(pat, vals, q, iters=ruiz_iters)
    else:
        d, e_eq, e_box, c = lax.cond(
            refresh,
            lambda: ruiz_equilibrate_sparse(pat, vals, q, iters=ruiz_iters),
            lambda: (carry_in.d, carry_in.e_eq, carry_in.e_box, carry_in.c),
        )
    vals_s = e_eq[:, rows] * vals * d[:, cols]     # scaled A values (B, nnz)
    vp_r = _pad_gather(vals_s, row_src)            # (B, m, K) row-padded
    vp_c = _pad_gather(vals_s, col_src)            # (B, n, Kc) col-padded
    vp_c_raw = _pad_gather(vals, col_src)          # unscaled, for certificates
    w = e_box * d                                  # diagonal of the scaled box block
    qs = c * d * q
    bs = e_eq * b_eq
    ls = e_box * l_box
    us = e_box * u_box
    p_diag = c * d * d * reg                       # scaled P diagonal

    def mv(x):
        """Â x via row gathers (B, n) → (B, m)."""
        return jnp.sum(vp_r * x[:, row_cols], axis=2)

    def mvt(y):
        """Âᵀ y via column gathers (B, m) → (B, n)."""
        return jnp.sum(vp_c * y[:, col_rows], axis=2)

    def mvt_raw(y):
        """A_eqᵀ y with UNSCALED values (infeasibility certificate)."""
        return jnp.sum(vp_c_raw * y[:, col_rows], axis=2)

    eye_m = jnp.eye(m_eq, dtype=dtype)

    def diag_inv(rho_b):
        """D⁻¹, D = diag(P̂ + σ + ρŵ²) — exact for the CURRENT rho."""
        return 1.0 / (p_diag + sigma + rho_b[:, None] * w * w)

    def form_S(Dinv):
        """S = Â D⁻¹ Âᵀ.  Banded patterns use the precomputed triple lists
        (no dense A anywhere — the round-1 (B, m, n) materialization was
        the 100k-home memory blocker); dense patterns fall back to the
        einsum formation."""
        if schur is not None:
            return form_schur_sparse(schur, m_eq, vals_s, Dinv)
        As_dense = jnp.zeros((B, m_eq, n), dtype=dtype).at[:, rows, cols].add(vals_s)
        ADi = As_dense * Dinv[:, None, :]
        return mxu_einsum("bmn,bkn->bmk", ADi, As_dense, precision="f32")

    band_plan = plan_for(schur, m_eq) if (banded_factor and schur is not None) else None
    backend = resolve_backend(solve_backend, B, m_eq, band_plan is not None,
                              elem_bytes=2 if matvec_dtype == "bf16" else 4)
    if backend == "band":
        perm_ix = jnp.asarray(band_plan.perm)
        invp_ix = jnp.asarray(band_plan.inv)
        # Bind the kernel family once per trace (band_kernel is static):
        # pallas uses TRANSPOSED (m, bw+1, B) band storage and one fused
        # kernel per solve, xla the (B, m, bw+1) scan path.
        scatter_fn, chol_fn, band_solve_fn, _, _ = pallas_band.make_band_ops(
            band_plan, band_kernel, mesh=mesh, mesh_axis=mesh_axis)

    def factor(rho_b):
        """Schur-complement factor of the equality-constrained x-update.

        Returns (Dinv, Sinv, S): S is SPD m_eq×m_eq; S⁻¹ formed explicitly
        so the per-iteration solve is pure batched matmul; S kept for
        refinement.  With a banded plan, the Cholesky + triangular solves
        run as O(m·bw²) band scans instead of dense O(m³) batched kernels
        (the 10k-home factor hotspot, docs/perf_notes.md).
        """
        Dinv = diag_inv(rho_b)
        if backend == "band":
            # No (B, m, m) array exists in this mode: the carry holds the
            # band Cholesky factor; refinement matvecs run on the band S.
            contrib = schur_contrib(schur, vals_s, Dinv)
            Sb = scatter_fn(contrib)
            return Dinv, chol_fn(Sb), Sb
        if band_plan is not None:
            # One contrib computation feeds both the dense S (kept for
            # refinement / stale reuse) and the banded inverse.
            contrib = schur_contrib(schur, vals_s, Dinv)
            S = scatter_schur(schur, m_eq, contrib)
            Sinv = banded_explicit_inverse(band_plan, contrib)
        else:
            S = form_S(Dinv)
            L = jnp.linalg.cholesky(S)
            Linv = lax.linalg.triangular_solve(
                L, jnp.broadcast_to(eye_m, S.shape), left_side=True, lower=True
            )
            # Factorization-path Gram product: pinned f32 regardless of
            # the hot-loop policy (the factor must be accurate).
            Sinv = mxu_einsum("bkm,bkn->bmn", Linv, Linv, precision="f32")
        return Dinv, Sinv.astype(store_dtype), S

    def stale_factor(rho_b):
        """Reuse the carried factor as a preconditioner: Dinv and S are
        exact for the current problem; only the factor (explicit inverse or
        band Cholesky) is stale — the wh-mix band drifted since it was
        computed — which iterative refinement in ``s_solve`` corrects."""
        Dinv = diag_inv(rho_b)
        if backend == "band":
            Sb = scatter_fn(schur_contrib(schur, vals_s, Dinv))
            return Dinv, carry_in.Sinv, Sb
        return Dinv, carry_in.Sinv, form_S(Dinv)

    def s_solve(F, r, refine: int = 1):
        """S⁻¹ r with ``refine`` iterative-refinement steps (absorbing
        bf16-storage rounding and stale-factor drift)."""
        if backend == "band":
            _, Lb, Sb = F
            v = band_solve_fn(Lb, Sb, r[:, perm_ix], refine)
            return v[:, invp_ix]
        _, Sinv, S = F
        # The dominant per-iteration matmul — runs at the configured
        # hot-loop policy; the refinement residual against the exact S
        # stays pinned f32 (it is what corrects the low-precision apply).
        pinv = lambda rr: mxu_einsum(
            "bmn,bn->bm", Sinv, rr.astype(Sinv.dtype),
            precision=precision, out_dtype=dtype,
        )
        v = pinv(r)
        for _ in range(refine):
            resid = r - mxu_einsum("bmn,bn->bm", S, v, precision="f32")
            v = v + pinv(resid)
        return v

    def kkt_solve(F, rhs):
        """x-update KKT solve: x = D⁻¹(rhs − Âᵀν), ν = S⁻¹(Â D⁻¹ rhs − b̂).
        Equalities hold to solver accuracy at EVERY iterate."""
        Dinv = F[0]
        nu = s_solve(F, mv(Dinv * rhs) - bs, refine=refine)
        return Dinv * (rhs - mvt(nu)), nu

    rho_b = jnp.full((B,), rho, dtype=dtype) if rho0 is None else rho0.astype(dtype)
    x = jnp.zeros((B, n), dtype=dtype) if x0 is None else (x0.astype(dtype) / d)
    nu = jnp.zeros((B, m_eq), dtype=dtype)
    y_box = jnp.zeros((B, n), dtype=dtype) if y_box0 is None else (c * y_box0.astype(dtype) / e_box)
    z_box = jnp.clip(w * x, ls, us)

    def residuals(x, z_box, nu, y_box):
        """Unscaled residuals + relative scalings (OSQP sec. 3.4, 5.1).
        ALWAYS f32 — trace-time guarded (ops/precision.py): the sparse
        matvecs and every reduction below decide convergence and may
        never inherit the hot loop's reduced precision."""
        x = f32_guard(x, "admm residual iterate x")
        y_box = f32_guard(y_box, "admm residual dual y_box")
        Ax = mv(x)
        wx = w * x
        r_p_eq = jnp.max(jnp.abs((Ax - bs) / e_eq), axis=1)
        r_p_box = jnp.max(jnp.abs((wx - z_box) / e_box), axis=1)
        r_prim = jnp.maximum(r_p_eq, r_p_box)
        dual = (p_diag * x + qs + mvt(nu) + w * y_box) / (c * d)
        r_dual = jnp.max(jnp.abs(dual), axis=1)
        p_sc = jnp.maximum(
            jnp.maximum(jnp.max(jnp.abs(Ax / e_eq), axis=1), jnp.max(jnp.abs(bs / e_eq), axis=1)),
            jnp.maximum(jnp.max(jnp.abs(wx / e_box), axis=1), jnp.max(jnp.abs(z_box / e_box), axis=1)),
        )
        d_sc = jnp.maximum(
            jnp.max(jnp.abs(mvt(nu) / (c * d)), axis=1),
            jnp.maximum(
                jnp.max(jnp.abs(w * y_box / (c * d)), axis=1),
                jnp.max(jnp.abs(qs / (c * d)), axis=1),
            ),
        )
        ok = (r_prim <= eps_abs + eps_rel * p_sc) & (r_dual <= eps_abs + eps_rel * d_sc)
        return r_prim, r_dual, p_sc, d_sc, ok

    def one_iter(F, rho_b, carry):
        x, z_box, nu, y_box = carry
        rhs = sigma * x - qs + w * (rho_b[:, None] * z_box - y_box)
        x_t, nu_t = kkt_solve(F, rhs)
        z_t_box = w * x_t
        x_new = alpha * x_t + (1.0 - alpha) * x
        v = alpha * z_t_box + (1.0 - alpha) * z_box + y_box / rho_b[:, None]
        z_box_new = jnp.clip(v, ls, us)
        y_box_new = y_box + rho_b[:, None] * (alpha * z_t_box + (1.0 - alpha) * z_box - z_box_new)
        return x_new, z_box_new, nu_t, y_box_new

    def primal_infeasible(dnu, dy_box):
        """OSQP primal-infeasibility certificate (Stellato et al. §3.4) on
        the dual-change direction accumulated over one check window."""
        dnu_u = e_eq * dnu / c              # unscale: y = E ŷ / c
        dy_box_u = e_box * dy_box / c
        At_dy = mvt_raw(dnu_u) + dy_box_u
        norm_dy = jnp.maximum(
            jnp.max(jnp.abs(dnu_u), axis=1), jnp.max(jnp.abs(dy_box_u), axis=1)
        )
        eps_inf = 1e-4 * jnp.maximum(norm_dy, 1e-12)
        cond1 = jnp.max(jnp.abs(At_dy), axis=1) <= eps_inf
        dy_pos = jnp.maximum(dy_box_u, 0.0)
        dy_neg = jnp.minimum(dy_box_u, 0.0)
        # inf bounds: a nonzero δy component against an infinite bound makes
        # the support value +inf, correctly blocking the certificate.
        sup = (
            jnp.sum(b_eq * dnu_u, axis=1)
            + jnp.sum(jnp.where(dy_pos > 0, u_box * dy_pos, 0.0), axis=1)
            + jnp.sum(jnp.where(dy_neg < 0, l_box * dy_neg, 0.0), axis=1)
        )
        cond2 = sup <= -eps_inf
        return cond1 & cond2 & (norm_dy > 1e-10)

    # ---- Anderson acceleration state (see the ``anderson`` parameter).
    K_aa = int(anderson)
    D_aa = 2 * n

    def aa_init():
        return (
            jnp.zeros((K_aa, B, D_aa), dtype=dtype),   # hist_s: window entries
            jnp.zeros((K_aa, B, D_aa), dtype=dtype),   # hist_t: their T-images
            jnp.zeros((B,), jnp.int32),                # cnt: valid history len
            jnp.full((B,), jnp.inf, dtype=dtype),      # prev_r: safeguard ref
            jnp.zeros((B,), bool),                     # applied last window
            jnp.zeros((B, D_aa), dtype=dtype),         # plain fallback iterate
        )

    def aa_step(aa, widx, s_entry, s_plain, r_tot, done, rho_changed):
        """One AA update at a window boundary.  Returns (aa', s_next,
        use_mask); ``s_next`` seeds the next window where ``use_mask``."""
        hist_s, hist_t, cnt, prev_r, applied, s_plain_prev = aa
        # Safeguard: a window that started from an accelerated point and
        # regressed reverts to the last plain iterate and restarts history.
        revert = applied & (r_tot > 2.0 * prev_r) & ~done
        base = jnp.where(revert[:, None], s_plain_prev, s_plain)
        cnt = jnp.where(revert | rho_changed, 0, cnt)
        slot = jnp.mod(widx, K_aa)
        # The stored pair is ALWAYS the true map application (s_entry →
        # s_plain) — even on a revert, where the continuation state differs
        # from the observed image (storing ``base`` would corrupt the first
        # post-restart extrapolation).
        hist_s = lax.dynamic_update_index_in_dim(hist_s, s_entry, slot, 0)
        hist_t = lax.dynamic_update_index_in_dim(hist_t, s_plain, slot, 0)
        cnt = jnp.minimum(cnt + 1, K_aa)
        # Per-home slot validity: the c most recent circular slots.
        ages = jnp.mod(widx - jnp.arange(K_aa), K_aa)        # (K,)
        valid = ages[None, :] < cnt[:, None]                 # (B, K)
        G = jnp.transpose(hist_s - hist_t, (1, 0, 2)) * valid[..., None]  # (B, K, D)
        M = mxu_einsum("bkd,bjd->bkj", G, G, precision="f32")
        gnorm = jnp.maximum(jnp.einsum("bkk->b", M), 1e-12)  # dragg: disable=DT008, diagonal trace, not a matmul
        M = M + (1e-8 * gnorm)[:, None, None] * jnp.eye(K_aa, dtype=dtype)
        # Invalid slots: unit diagonal, excluded from the sum-to-one row.
        inv = ~valid
        M = jnp.where((inv[:, :, None] | inv[:, None, :]),
                      jnp.eye(K_aa, dtype=dtype)[None], M)
        o = valid.astype(dtype)                              # (B, K)
        kkt = jnp.concatenate([
            jnp.concatenate([M, o[:, :, None]], axis=2),
            jnp.concatenate([o[:, None, :], jnp.zeros((B, 1, 1), dtype)], axis=2),
        ], axis=1)                                           # (B, K+1, K+1)
        rhs = jnp.zeros((B, K_aa + 1), dtype).at[:, -1].set(1.0)
        gamma = jnp.linalg.solve(kkt, rhs[..., None])[..., 0][:, :K_aa]  # (B, K)
        gamma = gamma * o
        s_acc = jnp.einsum("bk,kbd->bd", gamma, hist_t)  # dragg: disable=DT008, AA extrapolation weights (check-window work, historical default precision kept bit-exact)
        finite = jnp.all(jnp.isfinite(s_acc), axis=1)
        use = (cnt >= 2) & ~done & ~revert & finite
        s_next = jnp.where(use[:, None], s_acc, base)
        # ``applied`` marks every synthetic jump — AA extrapolations AND
        # safeguard reverts — so the next window suppresses both its
        # infeasibility certificate and a cascading re-revert.
        aa = (hist_s, hist_t, cnt, r_tot, use | revert, base)
        return aa, s_next, use | revert

    def chunk(carry):
        if K_aa > 0:
            (state, rho_b, F, it, _, pinf, best_done, best_r, last_improve,
             conv_it, aa) = carry
        else:
            (state, rho_b, F, it, _, pinf, best_done, best_r, last_improve,
             conv_it) = carry
        x0_, z0_, nu_prev, y_box_prev = state
        aa_entry = jnp.concatenate([state[1], state[3]], axis=1) if K_aa > 0 else None
        applied_entry = aa[4] if K_aa > 0 else None
        state = lax.fori_loop(0, check_every, lambda _, cc: one_iter(F, rho_b, cc), state)
        x, z_box, nu, y_box = state
        r_prim, r_dual, p_sc, d_sc, ok = residuals(x, z_box, nu, y_box)
        new_pinf = primal_infeasible(nu - nu_prev, y_box - y_box_prev)
        if K_aa > 0:
            # A window seeded by an AA jump has a synthetic dual direction —
            # don't let it mint an infeasibility certificate.
            new_pinf = new_pinf & ~applied_entry
        pinf = pinf | new_pinf
        done = ok | pinf
        it = it + check_every
        # Per-home attribution: the check-window iteration at which each
        # home FIRST read done (−1 = not yet; resolved to the final budget
        # after the loop).  Residual checks run per window, so this has
        # check_every granularity — same resolution the loop itself has.
        conv_it = jnp.where((conv_it < 0) & done, it, conv_it)
        # Progress = another home finished OR ANY unfinished home's residual
        # is still descending (per-home best tracking: a single straggler
        # making steady progress at large B must keep the loop alive, and
        # the cold-start phase — where the first convergence can take
        # hundreds of iterations — registers as residual descent).
        n_done = jnp.sum(done)
        r_tot = r_prim + r_dual
        descending = (r_tot < 0.99 * best_r) & ~done
        improved = (n_done > best_done) | jnp.any(descending)
        best_done = jnp.maximum(best_done, n_done)
        best_r = jnp.minimum(best_r, r_tot)
        last_improve = jnp.where(improved, it, last_improve)
        rho_changed = jnp.zeros((B,), bool)
        if adaptive_rho:
            ratio = jnp.sqrt(
                (r_prim / jnp.maximum(p_sc, 1e-10)) / jnp.maximum(r_dual / jnp.maximum(d_sc, 1e-10), 1e-10)
            )
            rho_new = jnp.clip(rho_b * ratio, RHO_MIN, RHO_MAX)
            win_due = (it // check_every) % max(1, rho_update_every) == 0
            update = ((ratio > 5.0) | (ratio < 0.2)) & win_due
            rho_next = jnp.where(update & ~done, rho_new, rho_b)
            F = lax.cond(jnp.any(rho_next != rho_b), factor, lambda _: F, rho_next)
            rho_changed = rho_next != rho_b
            rho_b = rho_next
        if K_aa > 0:
            widx = it // check_every - 1
            s_plain = jnp.concatenate([z_box, y_box], axis=1)
            aa, s_next, _ = aa_step(aa, widx, aa_entry, s_plain,
                                    r_tot, done, rho_changed)
            state = (x, s_next[:, :n], nu, s_next[:, n:])
            return (state, rho_b, F, it, jnp.all(done), pinf, best_done,
                    best_r, last_improve, conv_it, aa)
        return (state, rho_b, F, it, jnp.all(done), pinf, best_done, best_r,
                last_improve, conv_it)

    def cond(carry):
        it, all_done, last_improve = carry[3], carry[4], carry[8]
        keep = (it < iters) & (~all_done)
        if patience > 0:
            keep = keep & (it - last_improve < patience * check_every)
        return keep

    if carry_in is None:
        F = factor(rho_b)
    else:
        F = lax.cond(refresh, factor, stale_factor, rho_b)
    state = (x, z_box, nu, y_box)
    pinf0 = jnp.zeros((B,), dtype=bool)
    carry0 = (state, rho_b, F, jnp.asarray(0), jnp.asarray(False), pinf0,
              jnp.asarray(-1), jnp.full((B,), jnp.inf, dtype=dtype), jnp.asarray(0),
              jnp.full((B,), -1, dtype=jnp.int32))
    if K_aa > 0:
        carry0 = (*carry0, aa_init())
    out = lax.while_loop(cond, chunk, carry0)
    state, rho_b, F, it, _, pinf = out[0], out[1], out[2], out[3], out[4], out[5]
    conv_it = out[9]
    x, z_box, nu, y_box = state
    r_prim, r_dual, _, _, ok = residuals(x, z_box, nu, y_box)

    # Final polish: D-weighted projection of the iterate onto the equality
    # manifold (one extra Schur solve) — drives the dynamics-row violation to
    # solve accuracy so downstream physics sees consistent trajectories.
    # Two refinement passes: with a stale carried factor the extra pass
    # squares the drift term, keeping the projection at solve accuracy.
    Dinv = F[0]
    x = x - Dinv * mvt(s_solve(F, mv(x) - bs, refine=2))

    # Unscale and box-project the primal so downstream physics sees in-bound
    # values even at loose tolerance.
    x_out = jnp.clip(d * x, l_box, u_box)
    sol = ADMMSolution(
        x=x_out, y_eq=e_eq * nu / c, y_box=e_box * y_box / c,
        r_prim=r_prim, r_dual=r_dual, solved=ok & ~pinf, infeasible=pinf,
        iters=it, rho=rho_b,
        conv_iters=jnp.where(conv_it < 0, it, conv_it).astype(jnp.int32),
        diverged=pinf,
    )
    return sol, FactorCarry(d=d, e_eq=e_eq, e_box=e_box, c=c, Sinv=F[1])


_STATIC = ("pat", "iters", "check_every", "ruiz_iters", "adaptive_rho",
           "rho_update_every", "patience", "matvec_dtype", "precision",
           "refine", "anderson",
           "banded_factor", "solve_backend", "band_kernel", "mesh", "mesh_axis")


@partial(jax.jit, static_argnames=_STATIC)
def admm_solve_qp(pat, vals, b_eq, l_box, u_box, q, **kwargs) -> ADMMSolution:
    """One-shot solve (scalings + factor computed in-call).  See
    :func:`_admm_impl` for parameters."""
    sol, _ = _admm_impl(pat, vals, b_eq, l_box, u_box, q, **kwargs)
    return sol


@partial(jax.jit, static_argnames=_STATIC)
def admm_solve_qp_cached(pat, vals, b_eq, l_box, u_box, q, carry_in, refresh,
                         **kwargs) -> tuple[ADMMSolution, FactorCarry]:
    """MPC-mode solve with the cross-timestep factor cache: reuses
    ``carry_in``'s Ruiz scalings and Schur inverse unless the traced
    ``refresh`` flag fires (periodic exact refactorization).  Returns the
    solution plus the carry for the next timestep."""
    return _admm_impl(pat, vals, b_eq, l_box, u_box, q, carry_in=carry_in,
                      refresh=refresh, **kwargs)


def init_factor_carry(B: int, pat: SparsePattern, dtype=jnp.float32,
                      matvec_dtype: str = "f32",
                      solve_backend: str = "auto",
                      banded_factor: bool = True,
                      band_kernel: str = "xla") -> FactorCarry:
    """Zero-filled carry for t=0 (the first step must pass refresh=True).
    In band mode the ``Sinv`` field holds the (B, m, bw+1) band Cholesky
    factor instead of a dense inverse — or its (m, bw+1, B) transpose under
    the Pallas kernels."""
    plan = plan_for(_schur_structure_for(pat), pat.m) if banded_factor else None
    backend = resolve_backend(solve_backend, B, pat.m, plan is not None,
                              elem_bytes=2 if matvec_dtype == "bf16" else 4)
    if backend == "band" and band_kernel == "pallas":
        factor0 = jnp.zeros((pat.m, plan.bw + 1, B), dtype=dtype)
    elif backend == "band":
        factor0 = jnp.zeros((B, pat.m, plan.bw + 1), dtype=dtype)
    else:
        sinv_dtype = jnp.bfloat16 if matvec_dtype == "bf16" else dtype
        factor0 = jnp.zeros((B, pat.m, pat.m), dtype=sinv_dtype)
    return FactorCarry(
        d=jnp.ones((B, pat.n), dtype=dtype),
        e_eq=jnp.ones((B, pat.m), dtype=dtype),
        e_box=jnp.ones((B, pat.n), dtype=dtype),
        c=jnp.ones((B, 1), dtype=dtype),
        Sinv=factor0,
    )


@lru_cache(maxsize=32)
def dense_pattern(m: int, n: int) -> SparsePattern:
    """A fully-dense SparsePattern (for generic LPs and tests; the MPC path
    uses the banded pattern from build_qp_static)."""
    from dragg_tpu.ops.qp import _build_pattern

    rows = np.repeat(np.arange(m), n)
    cols = np.tile(np.arange(n), m)
    return _build_pattern(rows, cols, m, n)


def admm_solve(A_eq, b_eq, l_box, u_box, q, **kwargs) -> ADMMSolution:
    """Dense-matrix API: wraps :func:`admm_solve_qp` with a dense pattern.
    Prefer the sparse API for the MPC path.

    The proximal regularization defaults to a near-zero 1e-8 here: arbitrary
    LP callers should not inherit the MPC-tuned 1e-3 (which Tikhonov-biases
    their objectives); the engine passes its tuned reg explicitly."""
    kwargs.setdefault("reg", 1e-8)
    B, m_eq, n = A_eq.shape
    pat = dense_pattern(m_eq, n)
    return admm_solve_qp(pat, A_eq.reshape(B, m_eq * n), b_eq, l_box, u_box, q, **kwargs)
