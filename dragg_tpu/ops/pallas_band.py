"""Pallas TPU kernels for the banded Schur machinery.

The XLA implementation (dragg_tpu/ops/banded.py) runs each band operation
as a ``lax.scan`` over the m matrix rows with only (B, bw+1) elementwise
work per step — on chip every one of those m sequential steps pays loop
dispatch overhead, and one IPM iteration runs ~9 such scans (factor + four
forward/backward solves).  At 10k homes that overhead IS the solve phase
(docs/perf_notes.md, on-chip phase timers).

These kernels invert the layout — the HOME axis maps onto the TPU lanes,
the row recurrence runs as a ``fori_loop`` INSIDE one kernel over
VMEM-resident band storage — so the m-step chain costs VPU latency per
step instead of an XLA loop iteration, and a whole factor/refined-solve is
one kernel launch.

Band storage here is "transposed": ``(m, bw+1, B)`` with
``Sb_t[i, k, b] = S_perm[i, i-k]`` for home b (the XLA path uses
``(B, m, bw+1)``).  Blocks of ``LANE_BLOCK`` homes are mapped over the
grid; B is padded to a multiple (identity rows — benign for the factor).

Numerics are identical to banded.py's recurrences (same operation order),
verified element-wise in tests/test_pallas_band.py via interpret mode.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

# Homes per kernel program (lane tiles of 128).  Env-tunable for on-chip
# block-size experiments without code edits; 512 measured as the default,
# and block sizes now AUTO-shrink from a scoped-VMEM model when the env
# var is unset (round 5 — see _auto_blocks).
def _lane_block_from_env() -> int | None:
    """Parse DRAGG_LANE_BLOCK defensively: a bad value must not make every
    dragg_tpu import raise, and a non-multiple of 128 (the TPU lane width)
    would break Mosaic lowering in a way the self-test only catches on
    TPU — round it up and warn instead.  Returns None when UNSET: block
    sizes are then chosen per call shape by _auto_blocks."""
    import logging
    import os

    raw = os.environ.get("DRAGG_LANE_BLOCK", "")
    if not raw:
        return None
    try:
        v = int(raw)
    except ValueError:
        logging.getLogger("dragg_tpu.pallas").warning(
            "DRAGG_LANE_BLOCK=%r is not an integer; using auto policy", raw)
        return None
    if v <= 0:
        logging.getLogger("dragg_tpu.pallas").warning(
            "DRAGG_LANE_BLOCK=%d must be positive; using auto policy", v)
        return None
    rounded = -(-v // 128) * 128
    if rounded != v:
        logging.getLogger("dragg_tpu.pallas").warning(
            "DRAGG_LANE_BLOCK=%d is not a multiple of the TPU lane width "
            "(128); rounding up to %d", v, rounded)
    return rounded


_ENV_LANE_BLOCK = _lane_block_from_env()
# Back-compat constant (self-test block size, tools' sweeps): the measured
# default when no override/auto applies.
LANE_BLOCK = _ENV_LANE_BLOCK or 512

# Scoped-VMEM budget for the auto policy.  v5e/v4 cores have 16 MiB of
# VMEM; Mosaic double-buffers pipelined blocks and (observed round 4,
# docs/onchip_r4/) the FULL (m, B) kernel output participates in the
# scoped budget — so we model both and keep headroom.  Tunable for
# on-chip A/B without code edits.
def _vmem_budget_from_env() -> int:
    """Defensive like the sibling parsers: a malformed value must not
    make every dragg_tpu import raise — fall back to the 10 MiB default."""
    import logging

    raw = os.environ.get("DRAGG_VMEM_BUDGET_MB", "")
    try:
        mb = float(raw) if raw else 10.0
    except ValueError:
        logging.getLogger("dragg_tpu.pallas").warning(
            "DRAGG_VMEM_BUDGET_MB=%r is not a number; using 10", raw)
        mb = 10.0
    if mb <= 0:
        logging.getLogger("dragg_tpu.pallas").warning(
            "DRAGG_VMEM_BUDGET_MB=%r must be positive; using 10", raw)
        mb = 10.0
    return int(mb * (1 << 20))


_VMEM_BUDGET = _vmem_budget_from_env()


def _auto_blocks(m: int, bwp1: int, n_band_bufs: int, n_vec_bufs: int,
                 itemsize: int, B: int,
                 lane_block: int | None = None) -> tuple[int, int]:
    """Choose (lane_block, b_chunk) from the call shape so the kernel fits
    the scoped-VMEM budget with no env overrides (VERDICT r4 next-3: the
    flagship H=48 shape must not OOM out of the box).

    Model (per kernel program, double-buffered for grid pipelining):
    ``2·(n_band_bufs·m·bwp1 + n_vec_bufs·m)·lane_block·itemsize`` — plus
    the full ``(m, B_call)`` output, which the round-4 OOM showed lives
    in the SAME scoped budget and which only chunking the home axis
    (b_chunk) can shrink.  Each half gets half the budget.

    Measured anchors: m=77 (H=24) fits at lane_block=512 (band kernels
    15-38 us, docs/onchip_r4/band_kernel_24h.json); m=149 (H=48) OOMs at
    512 and was staged at 256 (CLAUDE.md) — this policy reproduces both
    with the default 10 MiB budget.
    """
    half = _VMEM_BUDGET // 2
    per_home = 2 * (n_band_bufs * m * bwp1 + n_vec_bufs * m) * itemsize
    if lane_block is not None:
        # An explicit lane-block override (arg or DRAGG_LANE_BLOCK): the
        # chunk below must align to THIS block, not the auto one —
        # chunks pad up to lane-block multiples, so a chunk sized against
        # a smaller auto block breaks the scoped-VMEM model it was
        # derived from (ADVICE r5 #1: LANE_BLOCK=512 at m=149 yielded a
        # 256-multiple chunk padded to 512 multiples).
        lb = lane_block
    else:
        lb = 512
        while lb > 128 and per_home * lb > half:
            lb -= 128
    # Full-output half: bound homes per pallas_call to a lane_block
    # multiple; 0 = no chunking needed.  When even lb homes' output
    # exceeds the half-budget (tiny DRAGG_VMEM_BUDGET_MB A/Bs), chunk at
    # the minimum possible (lb) rather than not at all — disabling the
    # guard exactly when pressure is worst would guarantee the OOM the
    # policy exists to prevent (round-5 review finding).
    cap = half // max(m * itemsize, 1)
    cap = (cap // lb) * lb
    if cap >= B:
        b_chunk = 0
    else:
        b_chunk = max(cap, lb)
    return lb, b_chunk


def _blocks_for(m: int, bwp1: int, n_band_bufs: int, n_vec_bufs: int,
                itemsize: int, B: int,
                lane_block: int | None, b_chunk: int | None) -> tuple[int, int]:
    """Resolve (lane_block, b_chunk): explicit args win, then env
    overrides, then the auto policy for whichever remains unset.  An
    auto-policy b_chunk is always computed AGAINST the resolved lane
    block — an overridden lane block with an auto chunk must not size the
    chunk from the auto block it replaced (ADVICE r5 #1: the chunk pads
    up to lane-block multiples, so misalignment silently re-inflates the
    scoped-VMEM footprint the chunk was chosen to bound)."""
    lb_override = lane_block or _ENV_LANE_BLOCK or None
    auto_lb, auto_ck = _auto_blocks(m, bwp1, n_band_bufs, n_vec_bufs,
                                    itemsize, B, lane_block=lb_override)
    lb = lb_override or auto_lb
    if b_chunk is None:
        ck = auto_ck if _ENV_B_CHUNK is None else _ENV_B_CHUNK
    else:
        ck = b_chunk
    return lb, ck


def _bchunk_from_env() -> int | None:
    """DRAGG_PALLAS_BCHUNK: split the home axis into slices of this size,
    one pallas_call per slice (an explicit 0 = chunking OFF — the round-4
    OOM repro configuration).  Prepared for the m=149 scoped-VMEM OOM
    seen on the axon AOT compiler (docs/onchip_r4/): the OOM'd
    allocation was the FULL (m, B) kernel output, which a smaller
    LANE_BLOCK cannot shrink — bounding B per call can.  Parity: each
    home is independent, so chunked == unchunked bitwise (pinned in
    tests/test_pallas_band.py).  Returns None when UNSET or malformed —
    the auto policy then chooses (a typo must not silently disable the
    OOM guard; round-5 review finding)."""
    import logging
    import os

    raw = os.environ.get("DRAGG_PALLAS_BCHUNK", "")
    if not raw:
        return None
    try:
        v = int(raw)
    except ValueError:
        logging.getLogger("dragg_tpu.pallas").warning(
            "DRAGG_PALLAS_BCHUNK=%r is not an integer; using auto policy",
            raw)
        return None
    if v < 0:
        logging.getLogger("dragg_tpu.pallas").warning(
            "DRAGG_PALLAS_BCHUNK=%d must be >= 0; using auto policy", v)
        return None
    return v


_ENV_B_CHUNK = _bchunk_from_env()
# Back-compat constant for tools' sweeps: 0 when unset (no forced chunk).
B_CHUNK = _ENV_B_CHUNK or 0


def _chunked(fn, n_out: int, ck: int, *arrays):
    """Apply ``fn(*arrays)`` in ``ck``-sized slices of the trailing (home)
    axis and concatenate."""
    B = arrays[0].shape[-1]
    outs = [fn(*(a[..., i:i + ck] for a in arrays))
            for i in range(0, B, ck)]
    if n_out == 1:
        return jnp.concatenate(outs, axis=-1)
    return tuple(jnp.concatenate([o[j] for o in outs], axis=-1)
                 for j in range(n_out))


_SELFTEST: bool | None = None


def available() -> bool:
    """True when the runtime can execute Pallas TPU kernels compiled (not
    interpreted) — i.e. the default backend is a TPU AND a small
    representative kernel actually compiles and runs.

    The self-test exercises the same primitives as the real kernels
    (dynamic row slicing on refs, in-kernel fori_loop, concat shifts,
    VMEM scratch) on tiny shapes, once per process.  A Mosaic lowering
    regression then degrades 'auto' to the XLA scan path instead of
    sinking every engine build — the kernels are a fast path, not a
    correctness dependency."""
    global _SELFTEST
    try:
        # Sanctioned backend query (resilience.devices): the kernel
        # availability check only runs on device-committed paths (engine
        # builds inside supervised children), never a jax-free parent.
        from dragg_tpu.resilience.devices import default_platform

        if default_platform() != "tpu":
            return False
    except Exception:
        return False
    if _SELFTEST is None:
        _SELFTEST = _run_self_test()
    return _SELFTEST


def _run_self_test() -> bool:
    """Compile + run the kernels on a tiny genuinely-banded SPD system
    (nonzero off-band entries, so the shift/alignment machinery is
    actually exercised) and compare against the XLA scan implementation;
    see :func:`available`."""
    try:
        from dragg_tpu.ops import banded as bd

        m, bw, B = 6, 2, LANE_BLOCK
        Sb_b = jnp.zeros((B, m, bw + 1), jnp.float32)
        Sb_b = Sb_b.at[:, :, 0].set(4.0 + jnp.arange(m, dtype=jnp.float32) * 0.1)
        Sb_b = Sb_b.at[:, 1:, 1].set(0.7)
        Sb_b = Sb_b.at[:, 2:, 2].set(-0.3)
        r = jnp.tile(jnp.arange(1.0, m + 1.0, dtype=jnp.float32)[None], (B, 1))
        L_ref = bd.banded_cholesky(Sb_b, bw)
        x_ref = x0 = bd.banded_solve(L_ref, r, bw)
        x_ref = x0 + bd.banded_solve(L_ref, r - bd.band_matvec(Sb_b, x0, bw), bw)

        Sb = jnp.transpose(Sb_b, (1, 2, 0))
        Lb = banded_cholesky_t(Sb, bw)
        x = refined_banded_solve_t(Lb, Sb, jnp.swapaxes(r, 0, 1), bw,
                                   refine=1)
        Lb2, x2 = factor_refined_solve_t(Sb, jnp.swapaxes(r, 0, 1), bw,
                                         refine=1)
        ok = bool(
            jnp.all(jnp.isfinite(x))
            & jnp.all(jnp.abs(jnp.transpose(Lb, (2, 0, 1)) - L_ref) < 1e-5)
            & jnp.all(jnp.abs(jnp.swapaxes(x, 0, 1) - x_ref) < 1e-4)
            & jnp.all(jnp.abs(Lb2 - Lb) < 1e-6)
            & jnp.all(jnp.abs(x2 - x) < 1e-5)
        )
        if not ok:
            import logging

            logging.getLogger("dragg_tpu.pallas").warning(
                "pallas band kernel self-test produced wrong values — "
                "falling back to the XLA scan path")
        return ok
    except Exception as e:
        import logging

        logging.getLogger("dragg_tpu.pallas").warning(
            "pallas band kernel self-test failed (%r) — falling back "
            "to the XLA scan path", e)
        return False


def _interpret() -> bool:
    from dragg_tpu.resilience.devices import default_platform

    return default_platform() != "tpu"


def _unit_row(bwp1: int, Bt: int, dtype) -> jnp.ndarray:
    """(bw+1, Bt) tile of a virtual identity L row: diag 1, off-band 0."""
    is_diag = lax.broadcasted_iota(jnp.int32, (bwp1, Bt), 0) == 0
    return jnp.where(is_diag, jnp.ones((), dtype), jnp.zeros((), dtype))


# ----------------------------------------------------------------- cholesky
def _chol_body(s_ref, l_ref, *, m: int, bw: int):
    """In-kernel band Cholesky: l_ref ← factor(s_ref), row by row.  Shared
    by the standalone factor kernel and the fused factor+solve kernel."""
    from jax.experimental import pallas as pl

    bwp1 = bw + 1
    Bt = s_ref.shape[-1]
    dtype = s_ref.dtype
    unit = _unit_row(bwp1, Bt, dtype)

    def row_step(i, _):
        srow = s_ref[pl.ds(i, 1)][0]                        # (bw+1, Bt)
        # prevs[d-1] = L row (i-d), virtual unit rows above the top.
        prevs = []
        for d in range(1, bw + 1):
            jj = jnp.maximum(i - d, 0)
            lrow = l_ref[pl.ds(jj, 1)][0]
            prevs.append(jnp.where(i - d >= 0, lrow, unit))
        # Same recurrence/operation order as banded.banded_cholesky.
        row = [None] * bwp1
        for k in range(bw, 0, -1):
            s = srow[k]
            for j in range(1, bw - k + 1):
                s = s - row[k + j] * prevs[k - 1][j]
            row[k] = s / prevs[k - 1][0]
        diag = srow[0]
        for j in range(1, bw + 1):
            diag = diag - row[j] * row[j]
        row[0] = jnp.sqrt(jnp.maximum(diag, 1e-20))
        l_ref[pl.ds(i, 1)] = jnp.stack(row)[None]
        return 0

    lax.fori_loop(0, m, row_step, 0)


def _chol_kernel(s_ref, l_ref, *, m: int, bw: int):
    _chol_body(s_ref, l_ref, m=m, bw=bw)


@functools.partial(jax.jit, static_argnames=("bw", "lane_block", "b_chunk"))
def banded_cholesky_t(Sb_t: jnp.ndarray, bw: int,
                      lane_block: int | None = None,
                      b_chunk: int | None = None) -> jnp.ndarray:
    """Batched band Cholesky in transposed storage: (m, bw+1, B) → L same
    layout, one kernel per ``lane_block`` (default LANE_BLOCK) homes.
    ``b_chunk`` (default: $DRAGG_PALLAS_BCHUNK) bounds homes per
    pallas_call — see _bchunk_from_env."""
    from jax.experimental import pallas as pl

    m, bwp1, B = Sb_t.shape
    # S in + L out = 2 band buffers, no vector buffers.
    lb, ck = _blocks_for(m, bwp1, 2, 0, Sb_t.dtype.itemsize, B,
                         lane_block, b_chunk)
    if ck and B > ck:
        # b_chunk=0 in the recursion: the outer level did the chunking —
        # letting the default re-apply would silently re-chunk every
        # slice and corrupt explicit chunk-size sweeps.  lane_block is
        # pinned so every slice uses the block the policy chose here.
        return _chunked(lambda s: banded_cholesky_t(s, bw, lb, b_chunk=0),
                        1, ck, Sb_t)
    Bp = -(-B // lb) * lb
    if Bp != B:
        pad = jnp.zeros((m, bwp1, Bp - B), Sb_t.dtype).at[:, 0, :].set(1.0)
        Sb_t = jnp.concatenate([Sb_t, pad], axis=-1)
    out = pl.pallas_call(
        functools.partial(_chol_kernel, m=m, bw=bw),
        out_shape=jax.ShapeDtypeStruct((m, bwp1, Bp), Sb_t.dtype),
        grid=(Bp // lb,),
        in_specs=[pl.BlockSpec((m, bwp1, lb), lambda b: (0, 0, b))],
        out_specs=pl.BlockSpec((m, bwp1, lb), lambda b: (0, 0, b)),
        interpret=_interpret(),
    )(Sb_t)
    return out[:, :, :B]


# ------------------------------------------------------------ refined solve
def _solve_into(l_ref, rhs_ref, y_ref, x_ref, *, m: int, bw: int):
    """In-kernel forward+backward substitution: x_ref ← (L Lᵀ)⁻¹ rhs_ref.
    ``y_ref`` is scratch for the forward pass; ``x_ref`` may alias
    ``rhs_ref`` (the backward pass never re-reads the rhs)."""
    from jax.experimental import pallas as pl

    Bt = l_ref.shape[-1]
    dtype = l_ref.dtype
    zero = jnp.zeros((1, Bt), dtype)

    def fwd(i, _):
        lrow = l_ref[pl.ds(i, 1)][0]                        # (bw+1, Bt)
        acc = rhs_ref[pl.ds(i, 1)]                          # (1, Bt)
        for k in range(1, bw + 1):
            jj = jnp.maximum(i - k, 0)
            yk = y_ref[pl.ds(jj, 1)]
            acc = acc - jnp.where(i - k >= 0, lrow[k][None] * yk, zero)
        y_ref[pl.ds(i, 1)] = acc / lrow[0][None]
        return 0

    lax.fori_loop(0, m, fwd, 0)

    def bwd(t, _):
        i = m - 1 - t
        lrow = l_ref[pl.ds(i, 1)][0]
        acc = y_ref[pl.ds(i, 1)]
        for k in range(1, bw + 1):
            jj = jnp.minimum(i + k, m - 1)
            lbelow = l_ref[pl.ds(jj, 1)][0]
            xk = x_ref[pl.ds(jj, 1)]
            acc = acc - jnp.where(i + k < m, lbelow[k][None] * xk, zero)
        x_ref[pl.ds(i, 1)] = acc / lrow[0][None]
        return 0

    lax.fori_loop(0, m, bwd, 0)


def _band_matvec_body(s_ref, v, *, m: int, bw: int):
    """(S v) for band-stored symmetric S against an (m, Bt) value."""
    from jax.experimental import pallas as pl

    S = s_ref[:]                                            # (m, bw+1, Bt)
    Bt = v.shape[-1]
    zk = lambda k: jnp.zeros((k, Bt), v.dtype)
    out = S[:, 0, :] * v
    for k in range(1, bw + 1):
        lo = S[k:, k, :]                                    # S[i, i-k], i>=k
        # row i (i>=k) += lo[i-k]·v[i-k]; row j (j<m-k) += lo[j]·v[j+k].
        out = out + jnp.concatenate([zk(k), lo * v[:-k]], axis=0)
        out = out + jnp.concatenate([lo * v[k:], zk(k)], axis=0)
    return out


def _refined_solve_kernel(l_ref, s_ref, r_ref, out_ref, y_ref, t_ref, *,
                          m: int, bw: int, refine: int):
    _solve_into(l_ref, r_ref, y_ref, out_ref, m=m, bw=bw)
    for _ in range(refine):
        t_ref[:] = r_ref[:] - _band_matvec_body(s_ref, out_ref[:], m=m, bw=bw)
        _solve_into(l_ref, t_ref, y_ref, t_ref, m=m, bw=bw)
        out_ref[:] = out_ref[:] + t_ref[:]


@functools.partial(jax.jit, static_argnames=("bw", "refine", "lane_block",
                                             "b_chunk"))
def refined_banded_solve_t(Lb_t: jnp.ndarray, Sb_t: jnp.ndarray,
                           r_t: jnp.ndarray, bw: int,
                           refine: int = 1,
                           lane_block: int | None = None,
                           b_chunk: int | None = None) -> jnp.ndarray:
    """x ≈ S⁻¹ r via band factor + ``refine`` iterative-refinement passes,
    fused into ONE kernel (the XLA path runs 2(1+refine) scans + a matvec).

    Lb_t/Sb_t: (m, bw+1, B) transposed band storage; r_t: (m, B).
    """
    from jax.experimental import pallas as pl

    m, bwp1, B = Lb_t.shape
    # L + S band inputs = 2 band buffers; r/out/y/t = 4 vector buffers.
    lb, ck = _blocks_for(m, bwp1, 2, 4, Lb_t.dtype.itemsize, B,
                         lane_block, b_chunk)
    if ck and B > ck:
        return _chunked(
            lambda L, S, r: refined_banded_solve_t(L, S, r, bw,
                                                   refine=refine,
                                                   lane_block=lb,
                                                   b_chunk=0),
            1, ck, Lb_t, Sb_t, r_t)
    Bp = -(-B // lb) * lb
    if Bp != B:
        padL = jnp.zeros((m, bwp1, Bp - B), Lb_t.dtype).at[:, 0, :].set(1.0)
        Lb_t = jnp.concatenate([Lb_t, padL], axis=-1)
        Sb_t = jnp.concatenate([Sb_t, padL], axis=-1)
        r_t = jnp.concatenate([r_t, jnp.zeros((m, Bp - B), r_t.dtype)], axis=-1)
    from jax.experimental.pallas import tpu as pltpu

    out = pl.pallas_call(
        functools.partial(_refined_solve_kernel, m=m, bw=bw, refine=refine),
        out_shape=jax.ShapeDtypeStruct((m, Bp), r_t.dtype),
        grid=(Bp // lb,),
        in_specs=[
            pl.BlockSpec((m, bwp1, lb), lambda b: (0, 0, b)),
            pl.BlockSpec((m, bwp1, lb), lambda b: (0, 0, b)),
            pl.BlockSpec((m, lb), lambda b: (0, b)),
        ],
        out_specs=pl.BlockSpec((m, lb), lambda b: (0, b)),
        scratch_shapes=[
            pltpu.VMEM((m, lb), r_t.dtype),
            pltpu.VMEM((m, lb), r_t.dtype),
        ],
        interpret=_interpret(),
    )(Lb_t, Sb_t, r_t)
    return out[:, :B]


# ----------------------------------------------- fused factor + first solve
def _factor_solve_kernel(s_ref, r_ref, l_ref, out_ref, y_ref, t_ref, *,
                         m: int, bw: int, refine: int):
    """Band Cholesky AND the first refined solve in one kernel: the factor
    stays VMEM-resident for the solve instead of round-tripping through HBM
    between two launches.  The IPM consumes this for the predictor step
    (whose rhs is factor-independent); the corrector re-reads the emitted
    ``l_ref`` through the plain solve kernel."""
    _chol_body(s_ref, l_ref, m=m, bw=bw)
    _solve_into(l_ref, r_ref, y_ref, out_ref, m=m, bw=bw)
    for _ in range(refine):
        t_ref[:] = r_ref[:] - _band_matvec_body(s_ref, out_ref[:], m=m, bw=bw)
        _solve_into(l_ref, t_ref, y_ref, t_ref, m=m, bw=bw)
        out_ref[:] = out_ref[:] + t_ref[:]


@functools.partial(jax.jit, static_argnames=("bw", "refine", "lane_block",
                                             "b_chunk"))
def factor_refined_solve_t(Sb_t: jnp.ndarray, r_t: jnp.ndarray, bw: int,
                           refine: int = 0, lane_block: int | None = None,
                           b_chunk: int | None = None,
                           ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(L, x) with x ≈ S⁻¹ r — factor + first solve fused into ONE kernel.

    Identical recurrences and operation order to ``banded_cholesky_t``
    followed by ``refined_banded_solve_t`` (parity pinned in
    tests/test_pallas_band.py), one fewer launch and one fewer HBM pass
    over the (m, bw+1, B) factor per call.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, bwp1, B = Sb_t.shape
    # S in + L out = 2 band buffers; r/x/y/t = 4 vector buffers.
    lb, ck = _blocks_for(m, bwp1, 2, 4, Sb_t.dtype.itemsize, B,
                         lane_block, b_chunk)
    if ck and B > ck:
        return _chunked(
            lambda S, r: factor_refined_solve_t(S, r, bw, refine=refine,
                                                lane_block=lb,
                                                b_chunk=0),
            2, ck, Sb_t, r_t)
    Bp = -(-B // lb) * lb
    if Bp != B:
        pad = jnp.zeros((m, bwp1, Bp - B), Sb_t.dtype).at[:, 0, :].set(1.0)
        Sb_t = jnp.concatenate([Sb_t, pad], axis=-1)
        r_t = jnp.concatenate([r_t, jnp.zeros((m, Bp - B), r_t.dtype)], axis=-1)
    L, x = pl.pallas_call(
        functools.partial(_factor_solve_kernel, m=m, bw=bw, refine=refine),
        out_shape=(jax.ShapeDtypeStruct((m, bwp1, Bp), Sb_t.dtype),
                   jax.ShapeDtypeStruct((m, Bp), r_t.dtype)),
        grid=(Bp // lb,),
        in_specs=[
            pl.BlockSpec((m, bwp1, lb), lambda b: (0, 0, b)),
            pl.BlockSpec((m, lb), lambda b: (0, b)),
        ],
        out_specs=(pl.BlockSpec((m, bwp1, lb), lambda b: (0, 0, b)),
                   pl.BlockSpec((m, lb), lambda b: (0, b))),
        scratch_shapes=[
            pltpu.VMEM((m, lb), r_t.dtype),
            pltpu.VMEM((m, lb), r_t.dtype),
        ],
        interpret=_interpret(),
    )(Sb_t, r_t)
    return L[:, :, :B], x[:, :B]


# ------------------------------------------------------- shared dispatch
def make_band_ops(plan, band_kernel: str, mesh=None, mesh_axis: str = "homes"):
    """One source of truth for the pallas/xla band-kernel dispatch, shared
    by the ADMM and IPM solvers.

    With ``mesh`` set (the sharded engine), the pallas kernels are wrapped
    in ``shard_map`` over the home axis: each device runs the kernel on its
    local shard — the band operations are embarrassingly parallel over
    homes, so no collectives are needed.  The XLA scan path needs no
    wrapping (it partitions under SPMD propagation).

    Returns ``(scatter_fn, chol_fn, solve_fn, add_diag_fn, factor_solve_fn)``:
      scatter_fn(contrib)            → band storage
      chol_fn(Sb)                    → band Cholesky factor (same layout)
      solve_fn(Lb, Sb, rp, refine)   → S⁻¹ rp with ``refine`` iterative-
                                       refinement passes; rp is (B, m) in
                                       PERMUTED row order for both kernels
      add_diag_fn(Sb, rel)           → Sb with ``rel × max-diag`` Tikhonov
                                       added per home (layout-aware)
      factor_solve_fn(Sb, rp, refine) → (Lb, S⁻¹ rp): factor + first solve
                                       in ONE fused kernel on the pallas
                                       path (the factor never leaves VMEM
                                       between the two), plain chol+solve
                                       composition on the XLA path
    Under ``"pallas"`` the storage layout is the transposed (m, bw+1, B)
    and the whole refined solve is one fused kernel; under ``"xla"`` it is
    (B, m, bw+1) and the scan path runs 2(1+refine) scans + matvecs.
    """
    from dragg_tpu.ops import banded as bd

    bw = plan.bw
    if band_kernel == "pallas":
        def chol_fn(Sb):
            return banded_cholesky_t(Sb, bw)

        def solve_fn(Lb, Sb, rp, refine):
            return jnp.swapaxes(refined_banded_solve_t(
                Lb, Sb, jnp.swapaxes(rp, 0, 1), bw, refine=refine), 0, 1)

        def add_diag_fn(Sb, rel):
            return Sb.at[:, 0, :].add(
                rel * jnp.max(Sb[:, 0, :], axis=0, keepdims=True))

        # Fused factor+solve vs split chol→solve: MEASURED opposite ways
        # on the two backends (docs/perf_notes.md round 4) — real Mosaic
        # runs the fused kernel 0.73× (larger VMEM residency hurts
        # pipelining), interpret/CPU runs it 1.38×.  "auto" follows the
        # measurement; DRAGG_PALLAS_FUSED=0/1 overrides for on-chip A/Bs
        # without code edits.
        fused_env = os.environ.get("DRAGG_PALLAS_FUSED", "auto")
        use_fused = (_interpret() if fused_env == "auto"
                     else fused_env not in ("0", "false"))

        if use_fused:
            def factor_solve_fn(Sb, rp, refine):
                Lb, x = factor_refined_solve_t(
                    Sb, jnp.swapaxes(rp, 0, 1), bw, refine=refine)
                return Lb, jnp.swapaxes(x, 0, 1)
        else:
            def factor_solve_fn(Sb, rp, refine):
                Lb = banded_cholesky_t(Sb, bw)
                return Lb, jnp.swapaxes(refined_banded_solve_t(
                    Lb, Sb, jnp.swapaxes(rp, 0, 1), bw, refine=refine), 0, 1)

        if mesh is not None:
            from functools import partial

            from jax.sharding import PartitionSpec as P

            from dragg_tpu.utils.compat import shard_map_partial

            shard_map = shard_map_partial(mesh)

            band_s = P(None, None, mesh_axis)   # (m, bw+1, B) — homes last
            vec_s = P(mesh_axis, None)          # (B, m)
            # Replication check off (compat.shard_map_partial): pallas_call
            # outputs carry no varying-mesh-axes annotation; the maps are
            # per-shard elementwise over homes, so it has nothing to verify.
            chol_fn = shard_map(chol_fn, in_specs=(band_s,),
                                out_specs=band_s)
            _solve = solve_fn
            _fsolve = factor_solve_fn

            def solve_fn(Lb, Sb, rp, refine):  # refine is Python-static
                return shard_map(
                    partial(_solve, refine=refine),
                    in_specs=(band_s, band_s, vec_s), out_specs=vec_s,
                )(Lb, Sb, rp)

            def factor_solve_fn(Sb, rp, refine):
                return shard_map(
                    partial(_fsolve, refine=refine),
                    in_specs=(band_s, vec_s), out_specs=(band_s, vec_s),
                )(Sb, rp)

        return (lambda c: band_scatter_t(plan, c),
                chol_fn, solve_fn, add_diag_fn, factor_solve_fn)

    # xla and cr share the (B, m, bw+1) storage layout — only the
    # factor/solve pair differs (cr's "factor" is an opaque pytree with
    # serial depth log2(m/bw); pure-jax ops, so sharding propagates under
    # SPMD with no shard_map wrapping).
    if band_kernel == "cr":
        from dragg_tpu.ops import block_cr

        chol_x = lambda Sb: block_cr.cr_factor(Sb, bw)
        base_solve = block_cr.cr_solve
    else:
        chol_x = lambda Sb: bd.banded_cholesky(Sb, bw)
        base_solve = lambda Lb, rp: bd.banded_solve(Lb, rp, bw)

    def solve_fn(Lb, Sb, rp, refine):
        v = base_solve(Lb, rp)
        for _ in range(refine):
            resid = rp - bd.band_matvec(Sb, v, bw)
            v = v + base_solve(Lb, resid)
        return v

    def add_diag_fn(Sb, rel):
        return Sb.at[:, :, 0].add(
            rel * jnp.max(Sb[:, :, 0], axis=1, keepdims=True))

    def factor_solve_fn(Sb, rp, refine):
        Lb = chol_x(Sb)
        return Lb, solve_fn(Lb, Sb, rp, refine)

    return (lambda c: bd.band_scatter(plan, c),
            chol_x, solve_fn, add_diag_fn, factor_solve_fn)


# ----------------------------------------------------- transposed scatter
def band_scatter_t(plan, contrib: jnp.ndarray) -> jnp.ndarray:
    """Schur entry values (B, n_s) → TRANSPOSED band storage (m, bw+1, B)
    (banded.band_scatter builds the (B, m, bw+1) layout)."""
    B = contrib.shape[0]
    Sb_t = jnp.zeros((plan.m, plan.bw + 1, B), dtype=contrib.dtype)
    return Sb_t.at[plan.ent_row, plan.ent_off, :].set(
        jnp.swapaxes(contrib[:, plan.ent_src], 0, 1)
    )
