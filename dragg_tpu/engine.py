"""The community engine — one jitted, scan-able step for the whole community.

This is the TPU-native replacement for the reference's per-timestep fan-out
(``Aggregator.run_iteration`` → pathos pool → ``MPCCalc.run_home`` → CVXPY →
GLPK_MI → Redis, dragg/aggregator.py:711-755, dragg/mpc_calc.py:649-672):
the community is a batched tensor program.  Each step

1. slices the environment windows (OAT/GHI/TOU) on device with
   ``lax.dynamic_slice`` — the series are placed on device once, the analog
   of the reference pushing them into Redis up front
   (dragg/aggregator.py:653-662);
2. computes water-draw windows and the draw-mixed initial WH temperature
   (dragg/mpc_calc.py:193-204,281);
3. gates each home's HVAC season (heat-only vs cool-only) on the *noisy*
   OAT forecast — in the reference the "expected-value" forecast noise is
   used only for this seasonal switch; the MPC constraints themselves use
   the true OAT/GHI windows (dragg/mpc_calc.py:206-231 builds
   ``oat_current_ev`` but :229 passes the un-noised ``oat_current`` into the
   constraints; the EV array is read only by the season check :303);
4. assembles the fixed-shape batched QP and solves it with the ADMM kernel;
5. routes homes whose solve failed tolerance through the vectorized
   fallback controller (dragg/mpc_calc.py:527-596);
6. emits the per-home observables of the reference's Redis result hash
   (dragg/mpc_calc.py:482-524) as stacked arrays.

``make_engine`` builds the step and a ``lax.scan`` chunk runner over
timesteps; the host loop only crosses the device boundary at checkpoint
intervals.  Everything batches over the home axis, which is the axis the
parallel layer shards over the TPU mesh.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dragg_tpu.models.fallback import fallback_control
from dragg_tpu.ops.admm import FactorCarry, admm_solve_qp_cached, init_factor_carry
from dragg_tpu.ops.qp import (
    QPLayout,
    TAP_TEMP,
    TYPE_SPECS,
    assemble_qp_step,
    build_qp_static,
    ev_charge_bounds,
    hp_cops,
    recover_solution,
    shift_warm_start,
    superset_spec_for,
)

WINTER_MAX_OAT = 30.0  # season switch threshold, degC (dragg/mpc_calc.py:303)

# ``tpu.bucketed = "auto"`` enables type-bucketed solving when BOTH hold
# (thresholds set from the 512-home CPU A/B, docs/perf_notes.md round 8:
# the per-bucket compile multiplication only pays for itself once enough
# homes shed their dead battery/PV blocks):
BUCKETED_MIN_HOMES = 32   # below this the extra compiles dominate any win
BUCKETED_MIN_FRAC = 0.25  # min fraction of homes with a non-superset shape

# --- Observatory layer (round 9): fixed histogram binning for the per-home
# solver attribution folded ON DEVICE inside the scan (engine._per_home_obs)
# and piggybacked on the StepOutputs host transfer.  The bins are FIXED
# LITERALS (not config) so chunk histograms are summable across runs and
# rounds without bin-edge bookkeeping; docs/telemetry.md documents them.
#
# Residual bins: index 0 = r_prim < 1e-7, then half-decade log10 bins over
# [1e-7, 10) (values >= 10 clip into the last log bin), and a final bin for
# certified-diverged / non-finite homes.
OBS_RES_LOG_LO = -7.0
OBS_RES_LOG_STEP = 0.5
OBS_RES_BINS = 18  # 1 underflow + 16 half-decade bins + 1 diverged
# Iteration bins: per-home convergence iterations (solver conv_iters),
# bin i = (edge[i-1], edge[i]]-ish via searchsorted; last bin = > 512.
OBS_ITER_EDGES = (2, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256,
                  384, 512)
OBS_ITER_BINS = len(OBS_ITER_EDGES) + 1

# StepOutputs fields carrying the per-bucket observatory fold — shaped
# (n_buckets, bins) / (n_buckets * k,) per step, NOT per home, so the
# aggregator's real_home_cols slicing must skip them (aggregator._collect_chunk).
OBS_FIELDS = frozenset({
    "conv_hist", "iters_hist", "iters_sum", "diverged_count",
    "worst_idx", "worst_rp", "worst_rd", "worst_iters", "worst_bucket",
})


def resolve_bucket_plan(bucketed: str, type_code) -> list[tuple[str, int, int]] | None:
    """Resolve the ``tpu.bucketed`` tri-state against a community's type
    codes: the list of contiguous ``(type_name, start, stop)`` buckets to
    solve at type-specialized shapes, or ``None`` for the one-batch
    superset path.

    ``"auto"`` buckets only when the community is big enough and enough
    homes are non-superset (see ``BUCKETED_MIN_*``); ``"true"`` forces
    bucketing (raising if the homes are not grouped by type — slicing
    needs the materialization order of ``homes.create_homes``);
    ``"false"`` forces the superset batch."""
    from dragg_tpu.homes import TYPE_CODES, type_bucket_ranges

    if bucketed == "false":
        return None
    ranges = type_bucket_ranges(type_code)
    if bucketed == "true":
        if ranges is None:
            raise ValueError(
                "tpu.bucketed=true needs homes grouped by type (the "
                "create_homes materialization order); this batch "
                "interleaves types")
        return ranges
    if ranges is None:
        return None
    codes = np.asarray(type_code)
    n = codes.size
    non_superset = int(np.sum(codes != TYPE_CODES["pv_battery"]))
    if n < BUCKETED_MIN_HOMES or non_superset < BUCKETED_MIN_FRAC * n:
        return None
    return ranges


class _TypeBucket:
    """One home-type bucket's compiled-shape context: the type-specialized
    layout/static/pattern plus the bucket's slice of every per-home device
    constant.  Array attributes are swapped for traced values while the
    jitted entry points trace (:meth:`Engine._bound`), exactly like the
    engine-level constants."""

    ARRAY_ATTRS = ("draws", "tank", "check_mask", "home_idx", "noise_idx",
                   "home_key", "env_off", "comm_idx")

    def __init__(self, *, name, spec, lay, comm_start, n_real, start_slot,
                 n, static, batch, draws, tank, check_mask, home_idx,
                 noise_idx, home_key, env_off, comm_idx,
                 band_plan, solve_backend, ordinal=0):
        self.ordinal = ordinal      # position in engine._buckets (= the
                                    # bucket_info() row the observatory's
                                    # worst_bucket codes index)
        self.name = name            # home type ("pv_battery" … "base")
        self.spec = spec
        self.lay = lay
        self.comm_start = comm_start  # first home in community order
        self.n_real = n_real          # real homes in the bucket
        self.start_slot = start_slot  # first slot in merged output order
        self.n = n                    # slot count (shard-padded)
        self.static = static
        self.batch = batch
        self.draws = draws
        self.tank = tank
        self.check_mask = check_mask
        self.home_idx = home_idx      # global fleet index per slot
                                      # (community-major — the all_homes /
                                      # real_home_cols order)
        self.noise_idx = noise_idx    # WITHIN-community index per slot —
                                      # the forecast-noise stream id, so
                                      # fleet batching reproduces each
                                      # community's standalone noise
        self.home_key = home_key      # (n, 2) uint32 per-home base PRNG
                                      # key (the home's community seed)
        self.env_off = env_off        # (n,) int32 env-series offset
        self.comm_idx = comm_idx      # (n,) int32 community index — the
                                      # event-timeline row each home reads
        self.band_plan = band_plan
        self.solve_backend = solve_backend


class _SupersetView:
    """Bucket-interface view of the whole superset-shaped engine, so the
    per-bucket step phases are the only implementation — the unbucketed
    path is the single-bucket special case.  Array reads delegate to the
    live engine attributes so the :meth:`Engine._bound` tracing swap flows
    through unchanged."""

    name = "superset"
    comm_start = 0
    start_slot = 0
    ordinal = 0

    def __init__(self, eng):
        self._eng = eng
        # The union spec of the types present (superset_spec_for) — equals
        # the historical SUPERSET_SPEC for legacy populations.
        self.spec = eng.layout.spec

    lay = property(lambda s: s._eng.layout)
    comm_idx = property(lambda s: s._eng._comm_idx)
    static = property(lambda s: s._eng.static)
    batch = property(lambda s: s._eng.batch)
    draws = property(lambda s: s._eng._draws)
    tank = property(lambda s: s._eng._tank)
    check_mask = property(lambda s: s._eng._check_mask)
    home_idx = property(lambda s: s._eng._home_idx)
    noise_idx = property(lambda s: s._eng._noise_idx)
    home_key = property(lambda s: s._eng._home_key)
    env_off = property(lambda s: s._eng._env_off)
    n = property(lambda s: s._eng.n_homes)
    n_real = property(lambda s: s._eng.true_n_homes)
    band_plan = property(lambda s: s._eng._band_plan)
    solve_backend = property(lambda s: s._eng._solve_backend)


class CommunityState(NamedTuple):
    """Per-home simulation state carried between timesteps.

    The reference persists these in each home's Redis hash
    (``temp_in_opt``/``temp_wh_opt``/``e_batt_opt``/``solve_counter`` and the
    ``{key}_{j}`` horizon plans, dragg/mpc_calc.py:100-115,482-524); here
    they are device arrays threaded through ``lax.scan``.
    """

    temp_in: jnp.ndarray     # (n,) one-step deterministic indoor temp
    temp_wh: jnp.ndarray     # (n,) WH temp BEFORE next step's draw mixing
    e_batt: jnp.ndarray      # (n,) battery SoC (kWh)
    e_ev: jnp.ndarray        # (n,) EV SOC (kWh; zeros for non-EV homes —
                             # the return-trip drain lands here, engine
                             # §15 scenario types)
    counter: jnp.ndarray     # (n,) int32 solve_counter
    plan_cool: jnp.ndarray   # (n, H) last feasible raw-duty plans (replay source)
    plan_heat: jnp.ndarray   # (n, H)
    plan_wh: jnp.ndarray     # (n, H)
    warm_x: jnp.ndarray      # (n, nvar) ADMM warm-start primal
    warm_y_box: jnp.ndarray  # (n, nvar) ADMM warm-start box duals
    warm_rho: jnp.ndarray    # (n,) ADMM warm-start rho
    key: jnp.ndarray         # PRNG key (legacy carry — since round 12 the
                             # forecast noise is keyed from the per-home
                             # ctx.home_key/noise_idx constants so fleet
                             # batching can't perturb it; the leaf stays so
                             # checkpoints keep their structure)


class StepOutputs(NamedTuple):
    """Per-home observables for one timestep — the reference's Redis result
    hash fields (dragg/mpc_calc.py:482-524), same units:

    * ``p_grid`` / ``p_load`` / ``forecast_p_grid`` are physical kW
      (reference stores ``value / sub_subhourly_steps``);
    * duty cycles are fractions in [0, 1] (reference stores count / s);
    * ``cost`` follows the reference's per-path convention: s * price *
      p_grid on optimal steps (dragg/mpc_calc.py:500 — the raw QP variable),
      price * p_grid on fallback steps (dragg/mpc_calc.py:594).

    Known bounded inconsistency (ADVICE r5 #2, documented rather than
    adjusted): under ``integer_repair="project"`` the projection pins the
    k=0 duty counts and moves the k=1 temperatures by the closed-form
    affine deltas, but the k=1 DUTY plan — which ``forecast_p_grid``
    (``mpc.p_grid[:, 1]``) is affine in — stays the relaxed optimum, so
    the reported forecast reflects the relaxed plan where "resolve" mode's
    second solve would re-optimize it against the pinned k=0 state.  The
    drift is bounded by the one-count-per-appliance pin delta propagated
    one step through the thermal dynamics (≲ kin·a_in⁻¹·|ΔT₁| plus the WH
    analog — fractions of a kW at the shipped parameters), is telemetry-
    only (nothing applied to the plant reads it; the k=1 plan is
    re-optimized from scratch next step), and re-deriving the k=1 duties
    in closed form is underdetermined (heat vs cool vs WH split).  The
    observatory's forensic dumps record the relaxed-plan provenance.
    """

    p_grid: jnp.ndarray           # (n,)
    forecast_p_grid: jnp.ndarray  # (n,)
    p_load: jnp.ndarray           # (n,)
    temp_in: jnp.ndarray          # (n,)
    temp_wh: jnp.ndarray          # (n,)
    hvac_cool_on: jnp.ndarray     # (n,) duty fraction
    hvac_heat_on: jnp.ndarray     # (n,)
    wh_heat_on: jnp.ndarray       # (n,)
    cost: jnp.ndarray             # (n,)
    waterdraws: jnp.ndarray       # (n,) liters
    correct_solve: jnp.ndarray    # (n,) 1.0 / 0.0
    p_pv: jnp.ndarray             # (n,) kW
    u_pv_curt: jnp.ndarray        # (n,)
    e_batt: jnp.ndarray           # (n,) kWh
    p_batt_ch: jnp.ndarray        # (n,) kW
    p_batt_disch: jnp.ndarray     # (n,) kW (non-positive)
    p_ev_ch: jnp.ndarray          # (n,) kW EV charging (0 for non-EV homes)
    e_ev: jnp.ndarray             # (n,) kWh EV SOC after this step's
                                  # action + any return-trip drain
    agg_load: jnp.ndarray         # () sum of p_grid over homes (the one
                                  # reduction in the system; psum-able)
    forecast_load: jnp.ndarray    # ()
    agg_cost: jnp.ndarray         # ()
    admm_iters: jnp.ndarray       # () iterations the solver ran this step
    repair_failed: jnp.ndarray    # () homes whose integer_first_action
                                  # pinned re-solve failed and kept the
                                  # relaxed action (0 when repair is off);
                                  # surfaces the measured-99.9% coverage
                                  # regressing on chip (ADVICE round 4)
    r_prim_max: jnp.ndarray       # () max final primal residual over the
                                  # check-mask homes — device-side solver
                                  # telemetry piggybacked on the chunk
                                  # outputs (no extra device→host sync);
                                  # non-finite residuals of diverged homes
                                  # are clamped to an f32-max sentinel so
                                  # divergence is visible, not NaN
    r_dual_max: jnp.ndarray       # () max final dual residual (same
                                  # masking/sentinel convention)
    bank_fallback_count: jnp.ndarray  # () homes that entered the reluqp
                                  # rho bank's fallback exact-
                                  # refactorization tail this step
                                  # (masked count; always 0.0 for the
                                  # families without a bank) — bench.py
                                  # reports whether the pre-factorized
                                  # path sufficed from this
    # --- Observatory fold (round 9; see OBS_* constants).  Per-BUCKET
    # shapes, not per-home — merged by concatenation on axis 0, so a
    # bucketed engine reports (n_buckets, bins) / (n_buckets · k,) and the
    # unbucketed engine the single-bucket special case.  All computed on
    # device inside the scan from the solver's per-home residual /
    # conv_iters / diverged vectors BEFORE the masked reductions above
    # discard them — zero extra device→host syncs (they ride the same
    # StepOutputs transfer _collect_chunk already makes).  With
    # ``telemetry.per_home = false`` every leaf is zero-width and the
    # traced program is unchanged from the pre-observatory engine.
    conv_hist: jnp.ndarray        # (n_buckets, OBS_RES_BINS) r_prim counts
    iters_hist: jnp.ndarray       # (n_buckets, OBS_ITER_BINS) conv_iters
    iters_sum: jnp.ndarray        # (n_buckets,) masked sum of conv_iters
    diverged_count: jnp.ndarray   # (n_buckets,) certified-diverged homes
    worst_idx: jnp.ndarray        # (n_buckets·k,) community home index of
                                  # the bucket's worst-k by r_prim (−1 =
                                  # empty slot: k exceeded the real homes)
    worst_rp: jnp.ndarray         # (n_buckets·k,) their r_prim
    worst_rd: jnp.ndarray         # (n_buckets·k,) their r_dual
    worst_iters: jnp.ndarray      # (n_buckets·k,) their conv_iters
    worst_bucket: jnp.ndarray     # (n_buckets·k,) bucket ordinal (the
                                  # bucket_info() row naming the type)


class StepAux(NamedTuple):
    """Intermediates produced by the assemble phase and consumed by the
    merge/collect phase (kept explicit so the phases can be timed and jitted
    separately by the benchmark harness)."""

    draw0: jnp.ndarray        # (n,) liters drawn this step
    temp_wh_init: jnp.ndarray # (n,) draw-mixed initial WH temp
    oat1: jnp.ndarray         # () OAT at t+1 (fallback simulation forcing);
                              # (n,) under fleet weather offsets
    ghi_w: jnp.ndarray        # (H+1,); (n, H+1) under fleet weather offsets
    price_total: jnp.ndarray  # (n, H)
    cool_cap: jnp.ndarray     # (n,)
    heat_cap: jnp.ndarray     # (n,)


class EngineParams(NamedTuple):
    """Static (Python-side) engine configuration."""

    solver: str         # "admm" | "ipm" | "reluqp" (home.hems.solver — the
                        # reference's solver field, dragg/mpc_calc.py:141-145
                        # analog; registry: config.SOLVER_FAMILIES)
    horizon: int        # H — decision steps (hems horizon * dt)
    dt: int             # steps per hour
    s: float            # sub_subhourly_steps (duty-cycle denominator)
    discount: float
    start_index: int    # index of sim t=0 in the environment series
    admm_iters: int
    admm_rho: float
    admm_eps: float
    admm_sigma: float
    admm_alpha: float
    admm_reg: float
    admm_refactor_every: int  # exact refactorization cadence (sim steps)
    admm_patience: int  # solver stagnation-exit patience (0 disables; tests
                        # pin it with eps=0 to force a fixed iteration count)
    admm_rho_update_every: int  # in-loop rho-update cadence (check windows)
    admm_matvec_dtype: str  # "f32" | "bf16" Sinv storage for the hot matvec
    admm_refine: int    # refinement passes per in-loop KKT solve
    admm_anderson: int  # Anderson-acceleration history depth (0 = off)
    admm_banded_factor: bool  # banded-Cholesky Schur factorization
    admm_solve_backend: str  # "auto" | "dense_inv" | "band" in-loop solve
    ipm_iters: int      # Mehrotra iteration cap (solver="ipm")
    ipm_tail_frac: float  # straggler sub-batch fraction (0 disables)
    ipm_tail_iters: int   # tail-phase iteration cap (0 = ipm_iters)
    ipm_warm: bool      # seed the IPM from the receding-horizon shift
    ipm_eps: float      # IPM stopping tolerance (decoupled from admm_eps)
    ipm_freeze_zmax: float  # divergence-freeze dual threshold (scaled space)
    integer_first_action: bool  # MILP repair: pin rounded k=0 duty counts
    integer_repair: str  # "project" (closed-form k=1 update, no 2nd solve)
                         # | "resolve" (pinned-box re-solve)
    repair_eps: float    # IPM tolerance for the "resolve" re-solve (loose:
                         # its applied outputs are the pins themselves —
                         # measured 8-9 iters at 1e-3 vs 25-39 at 2e-4 with
                         # 1.5e-4 cost drift, perf notes round 5)
    band_kernel: str    # "auto" | "pallas" | "xla" | "cr" band factor/solve
    forecast_noise_cap: float  # max forecast-noise std, degC (see _prepare)
    bucketed: str       # "auto" | "true" | "false" — type-bucketed shape
                        # specialization (see resolve_bucket_plan)
    seed: int
    # Observatory (round 9; trailing defaults keep direct constructions
    # valid).  obs_per_home is STATIC: false compiles the per-home fold
    # out of the program entirely (zero-width StepOutputs leaves), so the
    # disabled-mode device cost is bit-identical to the pre-observatory
    # engine ([telemetry] per_home / worst_k — docs/config.md).
    obs_per_home: bool = True
    obs_worst_k: int = 8
    # ReLU-QP family (round 10; trailing defaults keep direct
    # constructions valid).  The shared ADMM knobs (sigma/alpha/eps/reg/
    # patience) are reused — the iteration is the same OSQP splitting;
    # only the operator representation and rho handling differ.
    reluqp_rho: float = 0.1        # rho-bank center
    reluqp_rho_factor: float = 6.0  # geometric bank spacing
    reluqp_bank: int = 5           # bank size R
    reluqp_iters: int = 2000       # banked-loop iteration cap
    reluqp_tail_iters: int = 300   # fallback exact-refactor tail budget
    # Mixed-precision MXU policy (ISSUE 11; trailing defaults keep direct
    # constructions valid).  ``precision`` applies to the DENSE families'
    # hot-loop matmuls only (reluqp x-update, admm dense_inv apply) —
    # residual/check/warm-start tensors stay f32 by construction
    # (ops/precision.py; docs/architecture.md §16).  ``iter_kernel``
    # selects the fused Pallas check-window kernel for reluqp
    # (ops/pallas_iter.py): "auto" resolves to "lax" until the on-chip
    # A/B (tools/bench_engine_kernels.py --iter-kernels) records a
    # verdict — the perf_notes rule: no default without a measurement.
    precision: str = "f32"         # "f32" | "bf16x3"
    iter_kernel: str = "auto"      # "auto" | "pallas" | "lax"


class Engine:
    """Holds the compiled step/scan functions for one (community, config).

    Build via :func:`make_engine`.  The home batch and environment series
    are closed over as device constants; state flows through explicitly.
    """

    def __init__(self, params: EngineParams, batch, env_oat, env_ghi, env_tou,
                 check_mask=None, fleet=None, events=None, hour0: int = 0):
        self.params = params
        self.batch = batch
        # Scenario event timeline (docs/architecture.md §15): an inert /
        # absent timeline keeps the pre-scenario program byte-for-byte
        # (no gathers, no grid block, no extra device constants).
        self._events = (None if events is None or events.inert else events)
        if self._events is not None:
            want_c = 1 if fleet is None else fleet.n_communities
            if self._events.n_communities != want_c:
                raise ValueError(
                    f"event timeline covers {self._events.n_communities} "
                    f"communities but the engine runs {want_c}")
        self._grid_events = (self._events is not None
                             and self._events.has_grid)
        self._hour0 = int(hour0)  # hour of day at environment-series index
                                  # 0 (EV away windows are wall-clock hours)
        # The one-batch layout pads every home to the UNION of the specs
        # of the types present (superset_spec_for) — identical to the
        # historical pv_battery superset for legacy populations; an
        # active grid-event schedule additionally compiles the explicit
        # p_grid block into every shape.
        spec0 = superset_spec_for(batch.type_code)
        if self._grid_events:
            spec0 = spec0._replace(has_grid=True)
        lay = QPLayout(params.horizon, spec0)
        self.layout = lay
        self.n_homes = batch.n_homes
        # ShardedEngine sets true_n_homes to the pre-padding population
        # before super().__init__; unsharded engines carry no padding.
        if not hasattr(self, "true_n_homes"):
            self.true_n_homes = batch.n_homes
        # Fleet identity per batch row (ROADMAP item 3): community-major
        # fleet index, within-community noise index, per-home base PRNG
        # key (the community's seed), and env-series offset.  A
        # single-community engine is the C=1 special case — identical
        # values to the pre-fleet engine, so its noise streams (and the
        # compiled numbers) are unchanged.  A padded batch (ShardedEngine
        # pads before super().__init__) edge-extends the fleet rows like
        # every other per-home array.
        self._fleet = fleet
        n_now = batch.n_homes
        if fleet is None:
            g_idx = np.arange(n_now)
            n_idx = np.arange(n_now)
            e_off = np.zeros(n_now, np.int32)
            c_idx = np.zeros(n_now, np.int32)
            keys = np.broadcast_to(
                np.asarray(jax.random.PRNGKey(params.seed), np.uint32),
                (n_now, 2)).copy()
        else:
            pad = n_now - len(fleet.global_idx)

            def _padded(a):
                return np.pad(np.asarray(a), (0, pad), mode="edge")

            g_idx = _padded(fleet.global_idx)
            n_idx = _padded(fleet.local_idx)
            e_off = _padded(fleet.env_offset).astype(np.int32)
            c_idx = _padded(fleet.community).astype(np.int32)
            seed_keys = np.stack(
                [np.asarray(jax.random.PRNGKey(int(s)), np.uint32)
                 for s in fleet.seeds])
            keys = seed_keys[_padded(fleet.community)]
        self._fleet_rows = {
            "home_idx": g_idx.astype(np.int64),
            "noise_idx": n_idx.astype(np.int32),
            "home_key": keys, "env_off": e_off,
            "comm_idx": c_idx,
        }
        # Static trace-time switch: all-zero offsets keep the scalar
        # shared-window slice (byte-identical program to the pre-fleet
        # engine); any non-zero offset compiles the per-home gather path.
        self._per_home_env = bool(np.any(e_off))
        # Type-bucketed shape specialization (tpu.bucketed) resolves FIRST:
        # a bucketed engine's per-home constants live in the bucket
        # contexts, and building the superset copies too would double the
        # device-resident per-home memory for the engine's lifetime
        # (ShardedEngine resolves the plan BEFORE padding — buckets are
        # shard-padded independently — and stashes it; unsharded engines
        # resolve here).
        if not hasattr(self, "_bucket_ranges"):
            self._bucket_ranges = resolve_bucket_plan(
                params.bucketed, batch.type_code)
        self._bucketed = self._bucket_ranges is not None
        # Device-resident environment series (float32) — shared by every
        # bucket (replicated under a mesh).
        self._oat = jnp.asarray(np.asarray(env_oat), dtype=jnp.float32)
        self._ghi = jnp.asarray(np.asarray(env_ghi), dtype=jnp.float32)
        self._tou = jnp.asarray(np.asarray(env_tou), dtype=jnp.float32)
        # Device-resident event timeline (C, T) series — shared by every
        # bucket like the environment series; only the ACTIVE families are
        # committed (and traced), so e.g. a pure tariff-shock schedule
        # compiles no grid block and no relax gather.
        self._evt: dict = {}
        if self._events is not None:
            ev = self._events
            if ev.has_price:
                self._evt["price"] = jnp.asarray(ev.price, jnp.float32)
            if ev.has_grid:
                self._evt["cap"] = jnp.asarray(ev.cap, jnp.float32)
                self._evt["floor"] = jnp.asarray(ev.floor, jnp.float32)
            if ev.has_relax:
                self._evt["relax"] = jnp.asarray(ev.relax, jnp.float32)
        # check_type mask: aggregate reductions include only selected homes
        # (the reference only simulates matching homes, dragg/aggregator.py:
        # 767-770; homes are independent, so simulating all and masking the
        # sums is behaviorally identical for the selected homes).
        if check_mask is None:
            check_mask = np.ones(batch.n_homes)
        from dragg_tpu.ops.admm import _schur_structure_for, resolve_backend
        from dragg_tpu.ops.banded import plan_for

        if not self._bucketed:
            # Superset-shaped per-home device constants (the union spec of
            # the types present — see layout above).
            self.static = build_qp_static(batch, params.horizon, params.dt,
                                          lay.spec)
            self._draws = jnp.asarray(np.asarray(batch.draws_hourly),
                                      dtype=jnp.float32)
            self._tank = jnp.asarray(np.asarray(batch.tank_size),
                                     dtype=jnp.float32)
            self._home_idx = jnp.asarray(self._fleet_rows["home_idx"])
            self._noise_idx = jnp.asarray(self._fleet_rows["noise_idx"])
            self._home_key = jnp.asarray(self._fleet_rows["home_key"])
            self._env_off = jnp.asarray(self._fleet_rows["env_off"])
            self._comm_idx = jnp.asarray(self._fleet_rows["comm_idx"])
            self._check_mask = jnp.asarray(np.asarray(check_mask),
                                           dtype=jnp.float32)
            # Resolve the "auto" solve backend HERE, where the mesh is
            # known: the 1 GB Sinv budget is per device shard (ShardedEngine
            # sets _mesh_shards before this runs), and bf16 storage halves
            # the bytes.
            plan = (plan_for(_schur_structure_for(self.static.pattern),
                             lay.m_eq)
                    if params.admm_banded_factor else None)
            self._band_plan = plan
            self._solve_backend = resolve_backend(
                params.admm_solve_backend, batch.n_homes, lay.m_eq,
                plan is not None,
                elem_bytes=2 if params.admm_matvec_dtype == "bf16" else 4,
                n_shards=getattr(self, "_mesh_shards", 1),
            )
        else:
            # Bucket contexts carry their own static/plan/backend; the
            # superset equivalents stay unbuilt (no dead HBM).
            self.static = None
            self._band_plan = None
            self._solve_backend = None
        # Resolve the "auto" band kernel HERE too: Pallas only when it
        # compiles natively (TPU backend).  On a sharded engine the pallas
        # kernels run under shard_map over the homes axis (make_band_ops),
        # so the mesh is no obstacle — it is threaded to the solvers below.
        from dragg_tpu.ops import pallas_band

        kern = params.band_kernel
        if kern not in ("auto", "pallas", "xla", "cr"):
            raise ValueError(
                f"tpu.band_kernel must be auto|pallas|xla|cr, got {kern!r}")
        if kern == "auto":
            kern = "pallas" if pallas_band.available() else "xla"
        self._band_kernel = kern
        # The ADMM factor cache stores the band factor as an ARRAY inside
        # FactorCarry; the CR "factor" is a pytree, so the ADMM path keeps
        # the scan kernels when cr is selected (the IPM uses cr fully).
        self._admm_band_kernel = "xla" if kern == "cr" else kern
        # Resolve the fused iteration kernel (ISSUE 11): "auto" stays on
        # the lax path EVERYWHERE until the engine-level on-chip A/B
        # (tools/bench_engine_kernels.py --iter-kernels) records a
        # verdict in docs/perf_notes.md — unlike band_kernel's auto,
        # there is no measured pallas win to encode yet.  An explicit
        # "pallas" is honored (interpret mode off-TPU, same contract as
        # the band kernels) except under a multi-device mesh, where the
        # kernel is not shard_map-wired — degrade to lax rather than
        # miscompile.
        ik = params.iter_kernel
        if ik not in ("auto", "pallas", "lax"):
            raise ValueError(
                f"tpu.iter_kernel must be auto|pallas|lax, got {ik!r}")
        if ik == "auto":
            ik = "lax"
        if ik == "pallas" and (params.precision != "f32"
                               or getattr(self, "_mesh_shards", 1) > 1):
            ik = "lax"
        self._iter_kernel = ik
        # Whether CommunityState carries the receding-horizon warm start:
        # only the ADMM solver and the (measured-pessimal, opt-in)
        # ipm_warm_start consume it — see init_state / warm_cols.
        self._carry_warm = params.solver != "ipm" or params.ipm_warm
        # ShardedEngine sets these before super().__init__; the base engine
        # runs unsharded.
        self._solver_mesh = getattr(self, "mesh", None) \
            if getattr(self, "_mesh_shards", 1) > 1 else None
        self._solver_mesh_axis = getattr(self, "axis_name", "homes")
        # The superset view makes the bucket-parameterized step phases the
        # only implementation — the unbucketed engine is its single bucket.
        self._ctx0 = _SupersetView(self)
        self._buckets: list[_TypeBucket] = []
        if self._bucketed:
            self._build_buckets(batch, check_mask)
            self.n_homes = sum(c.n for c in self._buckets)
        else:
            # Commit every per-home constant to the device once, so passing
            # them into the jitted step as ARGUMENTS is pointer-cheap.  They
            # must be arguments, not closure captures: XLA refuses to bake
            # in constants that span processes (multi-host mesh), and
            # argument passing keeps their NamedShardings first-class
            # either way.  (ShardedEngine re-commits these with explicit
            # global shardings right after this constructor.  Bucketed
            # engines keep the HOST batch here — their device copies are
            # the bucket slices.)
            self.batch = type(batch)(*[jnp.asarray(np.asarray(f))
                                       for f in batch])
        self._step_fn = jax.jit(self._step_entry)  # dragg: disable=DT013, single-step API contract — callers reuse the passed state (tests/tools replay it)
        self._chunk_fn = jax.jit(self._chunk_entry)  # dragg: disable=DT013, the deliberately NON-donating twin — XLA:CPU executes donated computations synchronously (round-12 caveat, run_chunk docstring); run_chunk builds _chunk_fn_donate for accelerator paths

    def _build_buckets(self, batch, check_mask) -> None:
        """Materialize the per-type bucket contexts: slice the community
        (contiguous by construction — resolve_bucket_plan), shard-pad each
        bucket independently, and build the type-specialized layout /
        static / pattern / solver backend per bucket.  Buckets keep the
        community order, so concatenating their outputs reproduces the
        superset ordering exactly (plus per-bucket pad slots, dropped via
        :attr:`real_home_cols`)."""
        from dragg_tpu.homes import pad_batch, slice_batch
        from dragg_tpu.ops.admm import _schur_structure_for, resolve_backend
        from dragg_tpu.ops.banded import plan_for

        p = self.params
        shards = getattr(self, "_mesh_shards", 1)
        cmask = np.asarray(check_mask, dtype=np.float64)
        rows = self._fleet_rows

        def _row_pad(key, a, b, n_slots):
            v = np.asarray(rows[key])[a:b]
            widths = [(0, n_slots - (b - a))] + [(0, 0)] * (v.ndim - 1)
            return jnp.asarray(np.pad(v, widths, mode="edge"))

        slot = 0
        for ordinal, (tname, a, b) in enumerate(self._bucket_ranges):
            spec = TYPE_SPECS[tname]
            if self._grid_events:
                # Active grid events compile the explicit p_grid block
                # into EVERY bucket's shape (events key per community,
                # never per type).
                spec = spec._replace(has_grid=True)
            blay = QPLayout(p.horizon, spec)
            sub = slice_batch(batch, a, b)
            sub, pmask = pad_batch(sub, shards)
            n_slots = sub.n_homes
            bstatic = build_qp_static(sub, p.horizon, p.dt, spec)
            plan = (plan_for(_schur_structure_for(bstatic.pattern), blay.m_eq)
                    if p.admm_banded_factor else None)
            backend = resolve_backend(
                p.admm_solve_backend, n_slots, blay.m_eq, plan is not None,
                elem_bytes=2 if p.admm_matvec_dtype == "bf16" else 4,
                n_shards=shards)
            self._buckets.append(_TypeBucket(
                name=tname, spec=spec, lay=blay,
                comm_start=a, n_real=b - a, start_slot=slot, n=n_slots,
                static=bstatic,
                batch=type(sub)(*[jnp.asarray(np.asarray(f)) for f in sub]),
                draws=jnp.asarray(np.asarray(sub.draws_hourly), dtype=jnp.float32),
                tank=jnp.asarray(np.asarray(sub.tank_size), dtype=jnp.float32),
                check_mask=jnp.asarray(
                    np.pad(cmask[a:b], (0, n_slots - (b - a))) * pmask,
                    dtype=jnp.float32),
                home_idx=_row_pad("home_idx", a, b, n_slots),
                noise_idx=_row_pad("noise_idx", a, b, n_slots),
                home_key=_row_pad("home_key", a, b, n_slots),
                env_off=_row_pad("env_off", a, b, n_slots),
                comm_idx=_row_pad("comm_idx", a, b, n_slots),
                band_plan=plan, solve_backend=backend, ordinal=ordinal,
            ))
            slot += n_slots

    # ------------------------------------------------- traced constant tree
    _CONST_ATTRS = ("_oat", "_ghi", "_tou", "_draws", "_tank", "_check_mask",
                    "_home_idx", "_noise_idx", "_home_key", "_env_off",
                    "_comm_idx")
    _STATIC_ARRAYS = ("vals", "a_in", "a_wh", "kin", "kwh", "awr")

    def _consts(self):
        """Every device-resident constant the traced step reads, gathered
        into one pytree that is passed INTO the jitted entry points.
        Bucketed engines carry only the shared environment series plus the
        per-bucket trees — the superset per-home constants are never built
        for them (see __init__)."""
        if self._bucketed:
            attrs = {k: getattr(self, k) for k in ("_oat", "_ghi", "_tou")}
            static_t: dict = {}
            batch_t: tuple = ()
        else:
            attrs = {k: getattr(self, k) for k in self._CONST_ATTRS}
            static_t = {k: getattr(self.static, k)
                        for k in self._STATIC_ARRAYS}
            batch_t = tuple(self.batch)
        return {
            "attrs": attrs,
            "events": dict(self._evt),
            "static": static_t,
            "batch": batch_t,
            "buckets": tuple(
                {"static": {k: getattr(c.static, k)
                            for k in self._STATIC_ARRAYS},
                 "batch": tuple(c.batch),
                 "arrs": {k: getattr(c, k) for k in _TypeBucket.ARRAY_ATTRS}}
                for c in self._buckets),
        }

    def _bound(self, consts):
        """Context manager that swaps the constant attributes for the traced
        values while the step functions trace, restoring the real arrays
        after.  This keeps the step-code bodies reading ``self._oat`` etc.
        (and the bucket contexts their slices) while the compiled program
        receives those arrays as inputs."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            saved = (self.static, self.batch,
                     {k: getattr(self, k) for k in consts["attrs"]},
                     [(c.static, c.batch,
                       {k: getattr(c, k) for k in _TypeBucket.ARRAY_ATTRS})
                      for c in self._buckets],
                     self._evt)
            try:
                for k, v in consts["attrs"].items():
                    setattr(self, k, v)
                self._evt = consts.get("events", self._evt)
                if consts["static"]:
                    self.static = self.static._replace(**consts["static"])
                if consts["batch"]:
                    self.batch = type(self.batch)(*consts["batch"])
                for c, bc in zip(self._buckets, consts["buckets"]):
                    c.static = c.static._replace(**bc["static"])
                    c.batch = type(c.batch)(*bc["batch"])
                    for k, v in bc["arrs"].items():
                        setattr(c, k, v)
                yield
            finally:
                self.static, self.batch = saved[0], saved[1]
                for k, v in saved[2].items():
                    setattr(self, k, v)
                for c, (cst, cb, carrs) in zip(self._buckets, saved[3]):
                    c.static, c.batch = cst, cb
                    for k, v in carrs.items():
                        setattr(c, k, v)
                self._evt = saved[4]

        return cm()

    def _step_entry(self, consts, state, t, rp, refresh, factor):
        with self._bound(consts):
            return self._step(state, t, rp, refresh, factor)

    def _chunk_entry(self, consts, state, t0, rps):
        with self._bound(consts):
            return self._chunk(state, t0, rps)

    @property
    def band_bw(self) -> int | None:
        """Bandwidth of the RCM band plan the solvers factor with (None when
        the banded factorization is disabled) — the authoritative input to
        bench.py's HBM-bandwidth model.  A bucketed engine reports the
        widest bucket's bandwidth (per-bucket values ride bucket_info)."""
        if self._bucketed:
            bws = [c.band_plan.bw for c in self._buckets
                   if c.band_plan is not None]
            return max(bws) if bws else None
        return self._band_plan.bw if self._band_plan is not None else None

    @property
    def band_kernel(self) -> str:
        """The RESOLVED band kernel ("pallas" | "xla" | "cr") the IPM path
        compiled with — "auto" has already been settled against the
        backend + the Pallas compile self-test, so benchmark artifacts can
        record which implementation actually ran (a silent self-test
        fallback would otherwise be indistinguishable from 'pallas didn't
        help').  The ADMM path may differ (see :attr:`admm_band_kernel`)."""
        return self._band_kernel

    @property
    def admm_band_kernel(self) -> str:
        """The band kernel the ADMM factor cache compiled with — "cr" is
        demoted to "xla" there (the cache stores the factor as an array,
        and cr's factor is a pytree).  Bench artifacts must report THIS
        when the ADMM solver ran, or a cr-configured ADMM run would look
        like a cr measurement."""
        return self._admm_band_kernel

    @property
    def iter_kernel(self) -> str:
        """The RESOLVED fused-iteration kernel for the reluqp family
        ("pallas" | "lax") — "auto" has been settled (to "lax", pending
        the on-chip A/B verdict), and a forced "pallas" has been degraded
        to "lax" under a multi-device mesh or a non-f32 precision, so
        A/B artifacts record which window implementation actually ran."""
        return self._iter_kernel

    @property
    def warm_cols(self):
        """Width of the warm-start carry columns in CommunityState — the
        ONE place this is decided (init_state sizes the leaves by it and
        aggregator._run_shape keys checkpoint invalidation on it; deriving
        it twice is how the two silently disagree).  Bucketed engines
        return a per-bucket list (each bucket's layout has its own
        variable count)."""
        if self._bucketed:
            return [c.lay.n if self._carry_warm else 0 for c in self._buckets]
        return self.layout.n if self._carry_warm else 0

    @property
    def bucketed(self) -> bool:
        """Whether the community solves as per-type buckets (resolved from
        ``tpu.bucketed`` against the population — see resolve_bucket_plan)."""
        return self._bucketed

    def bucket_info(self) -> list[dict]:
        """Static bucket descriptors for benchmarks/telemetry: one dict per
        bucket with its type, community/slot ranges and compiled shape.
        Unbucketed engines report the single superset batch."""
        if not self._bucketed:
            return [dict(name="superset", comm_start=0,
                         n_real=self.true_n_homes, start_slot=0,
                         n_slots=self.n_homes, m_eq=self.layout.m_eq,
                         n_var=self.layout.n,
                         nnz=self.static.pattern.nnz,
                         band_bw=self.band_bw)]
        return [dict(name=c.name, comm_start=c.comm_start, n_real=c.n_real,
                     start_slot=c.start_slot, n_slots=c.n,
                     m_eq=c.lay.m_eq, n_var=c.lay.n,
                     nnz=c.static.pattern.nnz,
                     band_bw=c.band_plan.bw if c.band_plan is not None
                     else None)
                for c in self._buckets]

    @property
    def real_home_cols(self) -> np.ndarray:
        """Column indices of the TRUE homes in the merged per-home output
        axis, in COMMUNITY-MAJOR fleet order (``all_homes`` order; for a
        single community that is just community order).  Superset engines
        pad (if at all) only at the end; bucketed engines shard-pad each
        bucket independently, interleaving pad slots at bucket boundaries;
        fleet engines additionally interleave communities within each type
        bucket (the batch is type-major), so the mapping is the inverse of
        the rows' ``global_idx``.  ``real_home_pairs`` carries the same
        mapping as explicit (community, col) pairs."""
        if self._fleet is None and not self._bucketed:
            return np.arange(self.true_n_homes)
        cols = np.empty(self.true_n_homes, dtype=np.int64)
        g = self._fleet_rows["home_idx"]
        if self._bucketed:
            for c in self._buckets:
                cols[g[c.comm_start:c.comm_start + c.n_real]] = \
                    c.start_slot + np.arange(c.n_real)
        else:
            cols[g[:self.true_n_homes]] = np.arange(self.true_n_homes)
        return cols

    @property
    def real_home_pairs(self) -> np.ndarray:
        """(true_n_homes, 2) int array of ``(community, output column)``
        per home, in community-major fleet order — row ``j`` is home
        ``j % B`` of community ``j // B`` and names the merged-output
        column carrying it.  Single-community engines report community 0
        everywhere (B = the community size)."""
        cols = self.real_home_cols
        if self._fleet is None:
            comm = np.zeros(len(cols), dtype=np.int64)
        else:
            comm = np.arange(len(cols)) // self._fleet.homes_per_community
        return np.stack([comm, cols], axis=1)

    @property
    def fleet(self):
        """The :class:`~dragg_tpu.homes.FleetSpec` this engine was built
        with (``None`` for a single community)."""
        return self._fleet

    def community_fold_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """``(comm_idx, mask)`` aligned with the MERGED per-home
        StepOutputs columns (bucket-concatenation order), for on-device
        per-community aggregate folds: ``segment_sum(vec * mask,
        comm_idx, C)`` reproduces each community's ``agg_load``-style sum
        exactly as the fleet-total scalar does (same check mask, pad
        slots zeroed).  The fleet RL scans (dragg_tpu/rl/fleet) thread
        these through their jitted chunk as arguments — host numpy here,
        never traced closures (multi-host discipline)."""
        if self._bucketed:
            comm = np.concatenate(
                [np.asarray(c.comm_idx) for c in self._buckets])
            mask = np.concatenate(
                [np.asarray(c.check_mask) for c in self._buckets])
        else:
            comm = np.asarray(self._fleet_rows["comm_idx"])
            mask = np.asarray(self._check_mask)
        return comm.astype(np.int32), mask.astype(np.float32)

    @property
    def n_communities(self) -> int:
        return 1 if self._fleet is None else self._fleet.n_communities

    @property
    def obs_enabled(self) -> bool:
        """Whether the per-home observatory fold compiled into the step
        (``telemetry.per_home``) — the aggregator's emit gate."""
        return self.params.obs_per_home

    def state_slice(self, state, home_idx: int) -> dict:
        """ONE home's scalar carried state as host floats — the forensic
        dump's chunk-start snapshot (aggregator._write_forensics).  Pulls
        only the (n,) leaves (temp_in/temp_wh/e_batt/counter), never the
        (n, H) plans or warm starts, so an opt-in dump at 10k homes moves
        kilobytes, not the full carry."""
        if not 0 <= home_idx < self.true_n_homes:
            return {}
        # ``home_idx`` is the community-major fleet index (all_homes
        # order); map it to its TYPE-MAJOR batch row first (identity for
        # single communities).
        row = home_idx
        if self._fleet is not None:
            inv = getattr(self, "_fleet_inv", None)
            if inv is None:
                inv = np.empty(self.true_n_homes, dtype=np.int64)
                inv[self._fleet_rows["home_idx"][:self.true_n_homes]] = \
                    np.arange(self.true_n_homes)
                self._fleet_inv = inv
            row = int(inv[home_idx])
        if self._bucketed:
            for ctx, st in zip(self._buckets, state):
                if ctx.comm_start <= row < ctx.comm_start + ctx.n_real:
                    local = row - ctx.comm_start
                    break
            else:
                return {}
        else:
            st, local = state, row
        return {f: float(np.asarray(getattr(st, f))[local])
                for f in ("temp_in", "temp_wh", "e_batt", "counter")}

    # ---------------------------------------------------------------- state
    def init_state(self):
        """t=0 initial conditions (dragg/mpc_calc.py:267-277).  Bucketed
        engines carry one CommunityState per bucket (a tuple pytree — the
        scan, checkpoints, and shard placement all treat it leaf-wise)."""
        if self._bucketed:
            return tuple(self._init_state_bucket(c) for c in self._buckets)
        return self._init_state_bucket(self._ctx0)

    def _init_state_bucket(self, ctx) -> CommunityState:
        b = ctx.batch
        n = ctx.n
        H = self.params.horizon
        f32 = jnp.float32
        # Warm-start carry is dead weight on the default IPM path
        # (ipm_warm=False — a measured +55 % iteration PESSIMIZATION,
        # docs/perf_notes.md round 3): two (n, nvar) f32 arrays threaded
        # through every scan step, checkpoint, and resume (~35 MB at
        # 10k×48h, ~350 MB at the 100k target).  Zero-width columns keep
        # the pytree STRUCTURE (scan carries and shardings see the same
        # leaves) while dropping the bytes (round-3 verdict, weak #4);
        # leaf SHAPES do change with the solver config, which
        # aggregator._run_shape records so a mismatched checkpoint is
        # invalidated instead of crashing resume.
        nw = ctx.lay.n if self._carry_warm else 0
        return CommunityState(
            temp_in=jnp.asarray(b.temp_in_init, dtype=f32),
            temp_wh=jnp.asarray(b.temp_wh_init, dtype=f32),
            e_batt=jnp.asarray(b.e_batt_init_frac * b.batt_capacity, dtype=f32),
            e_ev=(jnp.asarray(b.is_ev, dtype=f32)
                  * jnp.asarray(b.ev_init_frac, dtype=f32)
                  * jnp.asarray(b.ev_cap, dtype=f32)),
            counter=jnp.zeros((n,), dtype=jnp.int32),
            plan_cool=jnp.zeros((n, H), dtype=f32),
            plan_heat=jnp.zeros((n, H), dtype=f32),
            plan_wh=jnp.zeros((n, H), dtype=f32),
            warm_x=jnp.zeros((n, nw), dtype=f32),
            warm_y_box=jnp.zeros((n, nw), dtype=f32),
            warm_rho=jnp.full((n,), self.params.admm_rho, dtype=f32),
            key=jax.random.PRNGKey(self.params.seed),
        )

    def init_factor(self):
        """Zero factor cache.  The cache lives only in chunk-local scan
        carries — NOT in CommunityState — so checkpoints never pay for the
        (n, m, m) Schur inverse (237 MB at 10k homes, ~9 GB at the
        100k-home/H=48 target); every chunk's first step refreshes it.
        Bucketed engines thread one carry per bucket (each at its own
        (n_b, m_b) shape)."""
        if self._bucketed:
            return tuple(self._init_factor_bucket(c) for c in self._buckets)
        return self._init_factor_bucket(self._ctx0)

    def _init_factor_bucket(self, ctx) -> FactorCarry:
        if self.params.solver == "ipm":
            # The IPM has no cross-step factor cache — thread a token-sized
            # carry instead of the ADMM's (B, m, m) dead weight.
            f32 = jnp.float32
            one = jnp.ones((ctx.n, 1), f32)
            return FactorCarry(d=one, e_eq=one, e_box=one, c=one,
                               Sinv=jnp.zeros((ctx.n, 1, 1), f32))
        if self.params.solver == "reluqp":
            # The reluqp carry holds the full pre-inverted rho bank
            # (B, R, m, m) — refreshed on the same admm_refactor_every
            # cadence as the ADMM's FactorCarry (ops/reluqp.py).
            from dragg_tpu.ops.reluqp import init_reluqp_carry

            return init_reluqp_carry(ctx.n, ctx.static.pattern,
                                     bank=self.params.reluqp_bank)
        return init_factor_carry(ctx.n, ctx.static.pattern,
                                 matvec_dtype=self.params.admm_matvec_dtype,
                                 solve_backend=ctx.solve_backend,
                                 banded_factor=self.params.admm_banded_factor,
                                 band_kernel=self._admm_band_kernel)

    # ----------------------------------------------------------------- step
    def _prepare(self, ctx, state: CommunityState, t, rp):
        """Assemble phase: environment windows, water draws, seasonal gate,
        and the batched QP for one timestep of ONE bucket (``ctx`` — the
        superset view when unbucketed).  ``t`` is the sim timestep
        (traced), ``rp`` the reward-price vector (H,) for this step — or
        (C, H) PER-COMMUNITY reward prices (the fleet RL aggregator,
        dragg_tpu/rl/fleet: each community's agent announces its own
        price), routed per home through ``ctx.comm_idx`` exactly like the
        scenario event windows.  The shape is a trace-time switch, so the
        (H,) baseline/single-agent program is byte-identical to the
        pre-fleet-RL engine."""
        p = self.params
        lay = ctx.lay
        b = ctx.batch
        H, dt, s = p.horizon, p.dt, p.s
        n = ctx.n
        f32 = jnp.float32

        # --- Water draws (dragg/mpc_calc.py:193-204).
        hour = t // dt
        win_hourly = lax.dynamic_slice(ctx.draws, (0, hour), (n, H // dt + 1))
        raw = jnp.repeat(win_hourly, dt, axis=-1) / dt
        n_raw = raw.shape[-1]
        idx = jnp.arange(H + 1)
        prev_ok = (idx - 1 >= 0).astype(f32)
        next_ok = (idx + 1 < n_raw).astype(f32)
        take = lambda off: jnp.take(raw, jnp.clip(idx + off, 0, n_raw - 1), axis=-1)
        rolled = (take(-1) * prev_ok + take(0) + take(1) * next_ok) / (prev_ok + 1.0 + next_ok)
        direct = jnp.take(raw, jnp.minimum(idx, n_raw - 1), axis=-1)
        draw_size = jnp.where(idx < dt, direct, rolled)        # (n, H+1) liters
        draw_frac = draw_size / ctx.tank[:, None]

        # Draw-mixed initial WH temperature (dragg/mpc_calc.py:271,281).
        temp_wh_init = (
            state.temp_wh * (ctx.tank - draw_size[:, 0]) + TAP_TEMP * draw_size[:, 0]
        ) / ctx.tank

        # --- Environment windows (true values; dragg/mpc_calc.py:211-230).
        # Fleet weather offsets (fleet.weather_offset_hours) shift each
        # home's window by its community's offset: a per-home gather from
        # the shared series.  The trace-time switch keeps the scalar
        # dynamic_slice path — byte-identical to the pre-fleet program —
        # whenever every offset is zero (single communities, and fleets
        # running synchronized weather).
        start = p.start_index + t
        rp_rows = (rp[ctx.comm_idx, :].astype(f32) if rp.ndim == 2
                   else rp[None, :].astype(f32))
        if self._per_home_env:
            row0 = start + ctx.env_off[:, None]                  # (n, 1)
            oat_w = self._oat[row0 + jnp.arange(H + 1)[None, :]]  # (n, H+1)
            ghi_w = self._ghi[row0 + jnp.arange(H + 1)[None, :]]
            tou_w = self._tou[row0 + jnp.arange(H)[None, :]]      # (n, H)
            price_total = rp_rows + tou_w
            oat0, oat1 = oat_w[:, 0], oat_w[:, 1]
            oat_fore = oat_w[:, 1:]
        else:
            oat_w = lax.dynamic_slice(self._oat, (start,), (H + 1,))
            ghi_w = lax.dynamic_slice(self._ghi, (start,), (H + 1,))
            tou_w = lax.dynamic_slice(self._tou, (start,), (H,))
            price_total = rp_rows + tou_w[None, :]
            oat0, oat1 = oat_w[0], oat_w[1]
            oat_fore = oat_w[None, 1:]

        # --- Community event windows (docs/architecture.md §15): per-step
        # gathers from the (C, T) timeline series, routed per home through
        # its community index — the fleet axis runs heterogeneous event
        # schedules under one compiled pattern set.  Events are scheduled
        # in SIM time (never weather-offset), so the window anchor is the
        # scalar ``start`` even under fleet weather offsets.
        def _evt_window(name, offset=0):
            series = self._evt[name]                      # (C, T)
            win = lax.dynamic_slice(
                series, (0, start + offset), (series.shape[0], H))
            return win[ctx.comm_idx]                      # (n, H)

        if "price" in self._evt:
            price_total = price_total + _evt_window("price")
        grid_cap = _evt_window("cap") if "cap" in self._evt else None
        grid_floor = _evt_window("floor") if "floor" in self._evt else None
        # Comfort relief aligns with the BOUNDED T_in entries, which live
        # at t+k+1 — one step ahead of the control window.
        relax_w = _evt_window("relax", 1) if "relax" in self._evt else None
        price_total = jnp.broadcast_to(price_total, (n, H))

        # --- EV availability / departure-deadline bounds (data, not
        # structure — ops/qp.ev_charge_bounds; hour-of-day is wall clock:
        # environment index → hour via the series' start hour).
        if lay.has_ev:
            ks_h = jnp.arange(H)
            hod_ctrl = ((p.start_index + t + ks_h) // dt
                        + self._hour0) % 24
            hod_state = ((p.start_index + t + 1 + ks_h) // dt
                         + self._hour0) % 24
            ev_avail, ev_floor = ev_charge_bounds(
                hod_ctrl, hod_state, b, state.e_ev, dt)
            e_ev_init = state.e_ev
        else:
            ev_avail = ev_floor = e_ev_init = None

        # --- Seasonal gate on the noisy forecast (dragg/mpc_calc.py:217-223,302-309).
        # Per-home keys (not one (n, H) draw): each home's noise stream is
        # a function of (its COMMUNITY's seed — ctx.home_key, t, its
        # WITHIN-community index — ctx.noise_idx) alone, so it is
        # invariant to the batch size, the bucket partition, AND the fleet
        # composition — shard-padding, bucketing, or fleet-batching a
        # community must not perturb the real homes' forecasts
        # (sharded/bucketed/fleet-vs-single equivalence).  For a
        # single-community engine home_key is the tiled PRNGKey(seed) and
        # noise_idx the global index, reproducing the pre-fleet stream
        # bit-for-bit.
        #
        # Documented deviation: the reference's 1.1^k noise growth is
        # unbounded — at the H=48 BASELINE horizon step 47 carries ±88 degC
        # of "forecast error", which flips the 30 degC season gate to
        # cooling-only in January and makes EVERY home infeasible (verified
        # vs HiGHS).  The reference never ran horizons >16 h.  We cap the
        # std at ``forecast_noise_cap`` (default 3 degC ~ 1.1^12, identical
        # to the reference for the first 12 horizon steps).
        keys_t = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
            ctx.home_key, t)
        home_keys = jax.vmap(jax.random.fold_in)(keys_t, ctx.noise_idx)
        noise_std = jnp.minimum(
            jnp.power(jnp.asarray(1.1, f32), jnp.arange(H, dtype=f32)),
            jnp.asarray(p.forecast_noise_cap, f32),
        )
        noise = jax.vmap(lambda k: jax.random.normal(k, (H,), dtype=f32))(home_keys) * noise_std
        oat_ev_max = jnp.maximum(oat0, jnp.max(oat_fore + noise, axis=1))
        winter = (oat_ev_max <= WINTER_MAX_OAT).astype(f32)
        heat_cap = winter * s
        cool_cap = (1.0 - winter) * s

        # --- Assemble + solve the batched QP.
        qp = assemble_qp_step(
            ctx.static, lay, b,
            oat_window=oat_w, ghi_window=ghi_w, price_total=price_total,
            draw_frac=draw_frac,
            temp_in_init=state.temp_in, temp_wh_init=temp_wh_init,
            e_batt_init=state.e_batt,
            cool_cap=cool_cap, heat_cap=heat_cap, wh_cap=s,
            discount=p.discount,
            e_ev_init=e_ev_init, ev_avail=ev_avail, ev_floor=ev_floor,
            grid_cap=grid_cap, grid_floor=grid_floor,
            comfort_relax=relax_w,
        )
        aux = StepAux(
            draw0=draw_size[:, 0], temp_wh_init=temp_wh_init, oat1=oat1,
            ghi_w=ghi_w, price_total=price_total,
            cool_cap=cool_cap, heat_cap=heat_cap,
        )
        return qp, aux

    def _solve(self, ctx, state: CommunityState, qp, factor: FactorCarry,
               refresh):
        """Solve phase: one bucket's batched QP solve (``ctx`` is the
        superset view when unbucketed).

        ``solver="admm"``: warm-started from state; ``refresh`` (traced
        bool) forces an exact re-equilibration + refactorization; between
        refreshes the carried Schur factor is reused with iterative
        refinement (SURVEY.md §7 step 3).

        ``solver="ipm"``: the Mehrotra interior point (ops/ipm.py) —
        converges in ~15-30 iterations with an all-frozen early exit; no
        cross-step factor cache (the carry passes through untouched).
        Warm starts are opt-in (``tpu.ipm_warm_start`` → x0 from the
        receding-horizon shift) and measured neutral — docs/perf_notes.md.

        ``solver="reluqp"``: the pre-factorized dense-matmul ADMM
        (ops/reluqp.py) — the carry is a :class:`ReLUQPCarry` holding the
        full pre-inverted rho bank; warm-start/refresh contract matches
        the ADMM's.
        """
        p = self.params
        if p.solver == "ipm":
            from dragg_tpu.ops.ipm import ipm_solve_qp

            # Tail compaction (1.5-1.6x solver wall-clock at equal-or-
            # better solve counts, docs/perf_notes.md): the budget split
            # and its eligibility conditions live inside ipm_solve_qp —
            # the engine just forwards the cap and the knobs.
            def run_ipm(l_box, u_box, eps=p.ipm_eps):
                return ipm_solve_qp(
                    ctx.static.pattern, qp.vals, qp.b_eq, l_box, u_box,
                    qp.q, reg=p.admm_reg, iters=p.ipm_iters,
                    tail_frac=p.ipm_tail_frac, tail_iters=p.ipm_tail_iters,
                    eps_abs=eps, eps_rel=eps,
                    band_kernel=self._band_kernel,
                    mesh=self._solver_mesh, mesh_axis=self._solver_mesh_axis,
                    x0=state.warm_x if p.ipm_warm else None,
                    freeze_zmax=p.ipm_freeze_zmax,
                )

            relaxed = run_ipm(qp.l_box, qp.u_box)
            sol, repair_failed = relaxed, jnp.float32(0.0)
            if p.integer_first_action:
                # The "resolve" re-solve runs at the LOOSE repair_eps: its
                # applied outputs are the pinned counts themselves, and
                # 1e-3 measured 8-9 iterations vs 25-39 at the production
                # 2e-4 with 1.5e-4 cost drift (perf notes round 5).  COLD
                # start — x0 from the relaxed iterate measured SLOWER
                # (20-29 iters, warm-start jamming; same measurement).
                sol, repair_failed = self._integerize_first_action(
                    ctx, qp, relaxed,
                    lambda l2, u2: run_ipm(l2, u2, eps=p.repair_eps))
            # Warm starts always shift the RELAXED solution: the repaired
            # iterate sits on pinned boxes that move every step, and
            # seeding the next solve from it measurably jams warm-start-
            # dependent solvers (ADMM: downstream solve rate 0.755→0.44
            # before this split — docs/perf_notes.md round 4).
            return sol, factor, relaxed, repair_failed

        if p.solver == "reluqp":
            # The pre-factorized dense-matmul family (ops/reluqp.py): the
            # carry holds the rho BANK; ``refresh`` re-equilibrates and
            # rebuilds every bank inverse, between refreshes the in-loop
            # rho adaptation is a bank-index gather and the final polish
            # refines against the exact current S.  Warm-start contract
            # is the ADMM's (relaxed solution shifts — see below).
            from dragg_tpu.ops.reluqp import reluqp_solve_qp_cached

            def run_reluqp(l_box, u_box, fac, ref, x0, y0, rho_w):
                return reluqp_solve_qp_cached(
                    ctx.static.pattern, qp.vals, qp.b_eq, l_box, u_box,
                    qp.q, fac, ref,
                    rho0=p.reluqp_rho, rho_factor=p.reluqp_rho_factor,
                    bank=p.reluqp_bank,
                    sigma=p.admm_sigma, alpha=p.admm_alpha,
                    eps_abs=p.admm_eps, eps_rel=p.admm_eps,
                    reg=p.admm_reg,
                    iters=p.reluqp_iters,
                    patience=p.admm_patience,
                    tail_iters=p.reluqp_tail_iters,
                    precision=p.precision,
                    iter_kernel=self._iter_kernel,
                    x0=x0, y_box0=y0, rho_warm=rho_w,
                )

            relaxed, fcarry = run_reluqp(qp.l_box, qp.u_box, factor,
                                         refresh, state.warm_x,
                                         state.warm_y_box, state.warm_rho)
            sol, repair_failed = relaxed, jnp.float32(0.0)
            if p.integer_first_action:
                # Pinned re-solve warm-starts from the relaxed solution
                # and reuses the just-built bank; the NEXT step's warm
                # start comes from `relaxed` (same contract as the ADMM —
                # this family is warm-start-dependent too).
                sol, repair_failed = self._integerize_first_action(
                    ctx, qp, relaxed,
                    lambda l2, u2: run_reluqp(l2, u2, fcarry, False,
                                              relaxed.x, relaxed.y_box,
                                              relaxed.rho)[0])
            return sol, fcarry, relaxed, repair_failed

        def run_admm(l_box, u_box, fac, ref, x0, y0, rho0):
            return admm_solve_qp_cached(
                ctx.static.pattern, qp.vals, qp.b_eq, l_box, u_box, qp.q,
                fac, ref,
                rho=p.admm_rho, sigma=p.admm_sigma, alpha=p.admm_alpha,
                eps_abs=p.admm_eps, eps_rel=p.admm_eps,
                reg=p.admm_reg,
                iters=p.admm_iters,
                patience=p.admm_patience,
                rho_update_every=p.admm_rho_update_every,
                matvec_dtype=p.admm_matvec_dtype,
                precision=p.precision,
                refine=p.admm_refine,
                anderson=p.admm_anderson,
                banded_factor=p.admm_banded_factor,
                solve_backend=ctx.solve_backend,
                band_kernel=self._admm_band_kernel,
                mesh=self._solver_mesh, mesh_axis=self._solver_mesh_axis,
                x0=x0, y_box0=y0, rho0=rho0,
            )

        relaxed, fcarry = run_admm(qp.l_box, qp.u_box, factor, refresh,
                                   state.warm_x, state.warm_y_box,
                                   state.warm_rho)
        sol, repair_failed = relaxed, jnp.float32(0.0)
        if p.integer_first_action:
            # Pinned re-solve warm-starts from the relaxed solution and
            # reuses the just-built factor; the NEXT step's warm start
            # comes from `relaxed` (third return), which is what makes
            # the repair safe on this warm-start-dependent family.
            sol, repair_failed = self._integerize_first_action(
                ctx, qp, relaxed,
                lambda l2, u2: run_admm(l2, u2, fcarry, False,
                                        relaxed.x, relaxed.y_box,
                                        relaxed.rho)[0])
        return sol, fcarry, relaxed, repair_failed

    def _integerize_first_action(self, ctx, qp, sol, run_solver):
        """Default-on MILP repair (``tpu.integer_first_action``): pin the three
        k=0 duty counts to their rounded values and re-solve, so the
        APPLIED action matches the reference's integer duty-cycle
        discretization (dragg/mpc_calc.py:171-173 — integer counts in
        [0, s]; only k=0 ever reaches the plant in the receding horizon).

        Measured basis (tools/milp_gap.py, docs/perf_notes.md round 4):
        the shipped relaxation sits 2.7-3.6 % below the true integer
        optimum; full-horizon rounding is comfort-infeasible for 15/20
        homes, while first-action pinning (with a rounding-direction
        retry) is feasible for 20/20.  NEAREST rounding alone is not
        enough — rounding the active duty DOWN can push the k=1
        temperature out of its comfort band (measured: 4/8 homes
        infeasible at H=6) — so the pin is bumped one count in the
        comfort-safe direction using the QP's own row arithmetic: the
        k=1 temperatures are affine in the k=0 duty counts
        (rows r_tind+0 / r_twhd+0, build_qp_static), so the band check
        is closed-form and costs no extra solve.  Homes whose pinned
        re-solve nevertheless fails KEEP the relaxed solution (graceful
        degradation — no new fallback routes).  Cost: one extra batched
        solve per step (``run_solver`` is either family's pinned-box
        re-solve; the ADMM one warm-starts from the relaxed solution and
        reuses the factor).  The NEXT step's warm start must come from
        the RELAXED solution, not the merged one — see _solve/_finish
        (measured: repaired warm shifts collapse ADMM's downstream solve
        rate 0.755 → 0.44, perf notes round 4).
        """
        lay = ctx.lay
        st, b = ctx.static, ctx.batch
        f32 = jnp.float32
        a_in_eff = jnp.asarray(st.a_in, f32)
        if len(st.hp_cool_pos):
            # Heat-pump buckets: the k=0 THERMAL coefficients are the
            # COP-scaled per-step values assemble wrote into the matrix —
            # read them back from qp.vals (rows r_tind+0 / r_tin1 share
            # them), so the closed-form k=1 band arithmetic below stays
            # exact for COP != 1 homes (and bit-identical for COP == 1).
            pc = qp.vals[:, int(st.hp_cool_pos[0])].astype(f32) / a_in_eff
            ph = -qp.vals[:, int(st.hp_heat_pos[0])].astype(f32) / a_in_eff
        else:
            pc = jnp.asarray(b.hvac_p_c, f32)
            ph = jnp.asarray(b.hvac_p_h, f32)
        pwh = jnp.asarray(b.wh_p, f32)
        a_in = jnp.asarray(st.a_in, f32)
        awr = jnp.asarray(st.awr, f32)
        a_wh = jnp.asarray(st.a_wh, f32)

        def col(a, c):
            return a[:, c]

        lo = lambda c: col(qp.l_box, c)
        hi = lambda c: col(qp.u_box, c)
        cool_r, heat_r, wh_r = (col(sol.x, lay.i_cool), col(sol.x, lay.i_heat),
                                col(sol.x, lay.i_wh))
        pin_c = jnp.clip(jnp.round(cool_r), lo(lay.i_cool), hi(lay.i_cool))
        pin_h = jnp.clip(jnp.round(heat_r), lo(lay.i_heat), hi(lay.i_heat))
        pin_w = jnp.clip(jnp.round(wh_r), lo(lay.i_wh), hi(lay.i_wh))

        # k=1 indoor temp under the pin (row r_tind+0: T1 = b + kin*T0
        # - a_in*pc*cool0 + a_in*ph*heat0, T0 pinned -> affine delta).
        def t1_of(pc_pin, ph_pin):
            return col(sol.x, lay.i_tin + 1) + a_in * (
                ph * (ph_pin - heat_r) - pc * (pc_pin - cool_r))

        heat_active = hi(lay.i_heat) > 0.5  # season gate (cool_cap/heat_cap)
        t1 = t1_of(pin_c, pin_h)
        need_up = t1 < lo(lay.i_tin + 1)    # too cold: +heat / -cool
        need_dn = t1 > hi(lay.i_tin + 1)    # too hot: -heat / +cool
        pin_h = jnp.where(need_up & heat_active,
                          jnp.minimum(pin_h + 1, hi(lay.i_heat)), pin_h)
        pin_c = jnp.where(need_up & ~heat_active,
                          jnp.maximum(pin_c - 1, lo(lay.i_cool)), pin_c)
        pin_h = jnp.where(need_dn & heat_active,
                          jnp.maximum(pin_h - 1, lo(lay.i_heat)), pin_h)
        pin_c = jnp.where(need_dn & ~heat_active,
                          jnp.minimum(pin_c + 1, hi(lay.i_cool)), pin_c)
        # k=1 WH temp under the pin — BOTH rows: the EV entry (r_twhd+0,
        # draw-mixed) and the APPLIED entry (r_twh1, no mixing — this is
        # the value _finish propagates as temp_wh_next).  The two differ
        # in constants, so a pin can leave one in band and not the other
        # (measured: 0.124 degC applied-row excursion at 1000 homes when
        # only the EV row was checked — round-5 fix).  The duty/indoor
        # deltas are identical for both rows; bump toward whichever bound
        # the WORSE row violates.
        dt1 = t1_of(pin_c, pin_h) - col(sol.x, lay.i_tin + 1)
        dwh = lambda w: awr * dt1 + a_wh * pwh * (w - wh_r)
        twh_rows = lambda w: (col(sol.x, lay.i_twh + 1) + dwh(w),
                              col(sol.x, lay.i_twh1) + dwh(w))
        ev0, ap0 = twh_rows(pin_w)
        low = jnp.minimum(ev0 - lo(lay.i_twh + 1), ap0 - lo(lay.i_twh1))
        high = jnp.maximum(ev0 - hi(lay.i_twh + 1), ap0 - hi(lay.i_twh1))
        pin_w = jnp.where(low < 0,
                          jnp.minimum(pin_w + 1, hi(lay.i_wh)),
                          jnp.where(high > 0,
                                    jnp.maximum(pin_w - 1, lo(lay.i_wh)),
                                    pin_w))

        cols = jnp.asarray([lay.i_cool, lay.i_heat, lay.i_wh])
        pinned = jnp.stack([pin_c, pin_h, pin_w], axis=1)

        if self.params.integer_repair == "project":
            # PROJECT mode (round 5): no second solve.  Everything the
            # receding-horizon loop actually APPLIES from the repaired
            # solution is affine in the pinned k=0 counts — the applied
            # duties are the pins themselves, and the k=1 temperatures /
            # battery energy are pinned by equality rows (build_qp_static
            # r_tind+0 / r_twhd+0 / r_tin1 / r_twh1 share the same duty
            # coefficients, and e_batt[1] depends only on the untouched
            # k=0 battery action).  The plan BEYOND k=1 is discarded next
            # step, so re-optimizing it (the "resolve" mode's 2nd solve,
            # measured 25-39 IPM iterations vs the relaxation's 8-10 —
            # docs/perf_notes.md round 5) buys nothing the plant ever
            # sees.  Repair-failed = the bump could not restore the k=1
            # comfort bands (closed form), same graceful degradation.
            dwh1 = dwh(pin_w)
            t1f = col(sol.x, lay.i_tin + 1) + dt1
            t1a = col(sol.x, lay.i_tin1) + dt1
            twh1f, twh1a = twh_rows(pin_w)
            tol = jnp.asarray(1e-3, f32)  # fp32 row-arithmetic slack
            # Check BOTH the EV and the APPLIED entries of each k=1
            # temperature: the applied ones are what _finish propagates
            # (the resolve re-solve enforces all four bounds; the
            # projection must too — round-5 fix, 0.124 degC excursion).
            in_band = (
                (t1f >= lo(lay.i_tin + 1) - tol)
                & (t1f <= hi(lay.i_tin + 1) + tol)
                & (t1a >= lo(lay.i_tin1) - tol)
                & (t1a <= hi(lay.i_tin1) + tol)
                & (twh1f >= lo(lay.i_twh + 1) - tol)
                & (twh1f <= hi(lay.i_twh + 1) + tol)
                & (twh1a >= lo(lay.i_twh1) - tol)
                & (twh1a <= hi(lay.i_twh1) + tol)
            )
            keep = in_band & sol.solved
            repair_failed = jnp.sum(
                jnp.where(sol.solved & ~in_band, ctx.check_mask, 0.0))
            x2 = sol.x.at[:, cols].set(pinned)
            # k=1 entries move by the same affine delta in the EV and the
            # applied (true-OAT) rows — the duty coefficients coincide;
            # the windows differ only in the constant term.
            x2 = x2.at[:, lay.i_tin + 1].add(dt1)
            x2 = x2.at[:, lay.i_tin1].add(dt1)
            x2 = x2.at[:, lay.i_twh + 1].add(dwh1)
            x2 = x2.at[:, lay.i_twh1].add(dwh1)
            k2 = keep[:, None]
            return type(sol)(
                x=jnp.where(k2, x2, sol.x),
                y_eq=sol.y_eq, y_box=sol.y_box,
                r_prim=sol.r_prim, r_dual=sol.r_dual,
                solved=sol.solved, infeasible=sol.infeasible,
                iters=sol.iters, rho=sol.rho,
                # Attribution stays the RELAXED solve's: the projection is
                # closed-form (no iterations) and divergence is a property
                # of the relaxation (the rho-bank fallback verdict too).
                conv_iters=sol.conv_iters, diverged=sol.diverged,
                bank_fallback=sol.bank_fallback,
            ), repair_failed

        l2 = qp.l_box.at[:, cols].set(pinned)
        u2 = qp.u_box.at[:, cols].set(pinned)
        sol2 = run_solver(l2, u2)
        # Adopt the repaired iterate only where BOTH solves succeeded;
        # solvedness itself stays the relaxation's verdict.
        keep = sol2.solved & sol.solved
        # Homes whose pinned re-solve failed keep the relaxed (fractional)
        # action; count them (masked — padded replica homes excluded) so
        # chunk telemetry can detect repair coverage regressing below the
        # measured 99.9 % (ADVICE round 4).
        repair_failed = jnp.sum(
            jnp.where(sol.solved & ~sol2.solved, ctx.check_mask, 0.0))

        def pick(b, a):
            k = keep.reshape(keep.shape + (1,) * (a.ndim - 1)) \
                if a.ndim else keep  # iters is a scalar — handled below
            return jnp.where(k, b, a)

        return type(sol)(
            x=pick(sol2.x, sol.x),
            y_eq=pick(sol2.y_eq, sol.y_eq),
            y_box=pick(sol2.y_box, sol.y_box),
            r_prim=pick(sol2.r_prim, sol.r_prim),
            r_dual=pick(sol2.r_dual, sol.r_dual),
            solved=sol.solved,
            infeasible=sol.infeasible,
            iters=sol.iters + sol2.iters,
            rho=pick(sol2.rho, sol.rho),
            # Per-home attribution keeps the RELAXED solve's verdicts (the
            # pinned re-solve runs at the loose repair_eps and its counts
            # would conflate repair cost with convergence behavior; the
            # rho-bank fallback verdict likewise stays the relaxation's).
            conv_iters=sol.conv_iters, diverged=sol.diverged,
            bank_fallback=sol.bank_fallback,
        ), repair_failed

    def _per_home_obs(self, ctx, sol) -> dict:
        """Observatory fold for one bucket: the solver's per-home residual
        / conv_iters / diverged vectors → fixed-bin histograms + the
        bucket's worst-k capture, all on device (O(bins + k) extra bytes
        on the existing StepOutputs transfer; see the OBS_* constants).
        Disabled (``telemetry.per_home = false``): zero-width leaves, so
        the compiled program carries no observatory work at all."""
        f32 = jnp.float32
        if not self.params.obs_per_home:
            z = jnp.zeros((0,), f32)
            return dict(conv_hist=jnp.zeros((1, 0), f32),
                        iters_hist=jnp.zeros((1, 0), f32),
                        iters_sum=z, diverged_count=z,
                        worst_idx=jnp.zeros((0,), jnp.int32),
                        worst_rp=z, worst_rd=z, worst_iters=z,
                        worst_bucket=jnp.zeros((0,), jnp.int32))
        mask = ctx.check_mask > 0
        rp, rd = sol.r_prim, sol.r_dual
        # Solvers built by this repo always attach the per-home extras;
        # the fallbacks keep hand-constructed ADMMSolutions (tests) legal.
        cit = (sol.conv_iters if sol.conv_iters is not None
               else jnp.broadcast_to(sol.iters, rp.shape)).astype(jnp.int32)
        div = (sol.diverged if sol.diverged is not None else sol.infeasible)
        fin = jnp.isfinite(rp)
        w = jnp.where(mask, 1.0, 0.0).astype(f32)
        logr = jnp.log10(jnp.clip(jnp.where(fin, rp, 1.0), 1e-30, 1e30))
        rbin = jnp.clip(
            jnp.floor((logr - OBS_RES_LOG_LO) / OBS_RES_LOG_STEP)
            .astype(jnp.int32) + 1, 0, OBS_RES_BINS - 2)
        rbin = jnp.where(div | ~fin, OBS_RES_BINS - 1, rbin)
        rhist = jnp.zeros((OBS_RES_BINS,), f32).at[rbin].add(w)
        ibin = jnp.searchsorted(jnp.asarray(OBS_ITER_EDGES, jnp.int32), cit,
                                side="left").astype(jnp.int32)
        ihist = jnp.zeros((OBS_ITER_BINS,), f32).at[ibin].add(w)
        iters_sum = jnp.sum(jnp.where(mask, cit.astype(f32), 0.0))
        div_count = jnp.sum(jnp.where(mask, div.astype(f32), 0.0))
        # Worst-k by final primal residual: non-finite residuals rank as —
        # AND are reported as — the f32-max sentinel (same convention as
        # r_prim_max: divergence stays visible and finite, never a NaN
        # that would poison downstream isfinite checks / strict-JSON
        # event streams); masked / pad slots score −1 so they fill slots
        # only when the bucket has fewer than k real homes — marked
        # idx = −1 for the host to drop.
        k = min(self.params.obs_worst_k, ctx.n)
        big = jnp.asarray(3.4e38, f32)
        rp_s = jnp.where(fin, rp, big)
        rd_s = jnp.where(jnp.isfinite(rd), rd, big)
        score = jnp.where(mask, rp_s, -1.0)
        top_s, top_ix = lax.top_k(score, k)
        return dict(
            conv_hist=rhist[None, :],
            iters_hist=ihist[None, :],
            iters_sum=iters_sum[None],
            diverged_count=div_count[None],
            worst_idx=jnp.where(top_s >= 0, ctx.home_idx[top_ix],
                                -1).astype(jnp.int32),
            worst_rp=rp_s[top_ix].astype(f32),
            worst_rd=rd_s[top_ix].astype(f32),
            worst_iters=cit[top_ix].astype(f32),
            worst_bucket=jnp.full((k,), ctx.ordinal, jnp.int32),
        )

    def _finish(self, ctx, state: CommunityState, t, sol, aux: StepAux,
                warm_sol, repair_failed=0.0):
        """Merge/collect phase for one bucket: recover physical series,
        route unsolved homes through the fallback controller, emit
        observables, advance state."""
        p = self.params
        lay = ctx.lay
        b = ctx.batch
        H, dt, s = p.horizon, p.dt, p.s
        n = ctx.n
        f32 = jnp.float32
        temp_wh_init = aux.temp_wh_init
        price_total = aux.price_total
        cool_cap, heat_cap = aux.cool_cap, aux.heat_cap

        mpc = recover_solution(sol.x, lay, b, aux.ghi_w, price_total, s)
        solved = sol.solved
        # Warm-start source: the RELAXED solution (never the repaired one
        # — see _solve; the parameter is required so an omitted argument
        # fails loudly instead of silently regressing the measured ADMM
        # collapse).
        wsol = warm_sol

        # --- Fallback for unsolved homes (dragg/mpc_calc.py:527-596).
        # Heat-pump homes deliver COP(OAT)× thermal watts per electrical
        # watt, so the fallback's bang-bang thermal simulation runs on the
        # COP-scaled rates (the ELECTRICAL p_load below keeps the raw
        # powers — only heat delivery scales).
        pc_fb = jnp.asarray(b.hvac_p_c, f32)
        ph_fb = jnp.asarray(b.hvac_p_h, f32)
        if lay.has_hp:
            oat1v = jnp.broadcast_to(jnp.asarray(aux.oat1, f32), (n,))
            cop_c1, cop_h1 = hp_cops(oat1v[:, None], b.hp_cop_base,
                                     b.hp_cop_slope)
            is_hp_f = jnp.asarray(b.is_hp, f32)
            pc_fb = pc_fb * (1.0 + is_hp_f * (cop_c1[:, 0].astype(f32) - 1.0))
            ph_fb = ph_fb * (1.0 + is_hp_f * (cop_h1[:, 0].astype(f32) - 1.0))
        counter_inc = jnp.where(solved, 0, state.counter + 1)
        ridx = jnp.clip(counter_inc, 0, H - 1)[:, None]
        fb = fallback_control(
            counter_inc, t, H,
            jnp.take_along_axis(state.plan_cool, ridx, axis=1)[:, 0],
            jnp.take_along_axis(state.plan_heat, ridx, axis=1)[:, 0],
            jnp.take_along_axis(state.plan_wh, ridx, axis=1)[:, 0],
            state.temp_in, temp_wh_init, aux.oat1,
            jnp.asarray(b.hvac_r, f32), jnp.asarray(b.hvac_c, f32),
            pc_fb, ph_fb,
            jnp.asarray(b.wh_r, f32), jnp.asarray(b.wh_c, f32), jnp.asarray(b.wh_p, f32),
            jnp.asarray(b.temp_in_min, f32), jnp.asarray(b.temp_in_max, f32),
            jnp.asarray(b.temp_wh_min, f32), jnp.asarray(b.temp_wh_max, f32),
            cool_cap, heat_cap, jnp.full((n,), s, dtype=f32),
            dt,
        )

        # --- Merge optimal / fallback per home.
        pick = lambda a, fbv: jnp.where(solved, a, fbv)
        cool0 = pick(mpc.cool[:, 0], fb.cool_on)
        heat0 = pick(mpc.heat[:, 0], fb.heat_on)
        wh0 = pick(mpc.wh[:, 0], fb.wh_on)
        # Fallback: battery idles, PV drops out of p_grid — the reference's
        # fallback path likewise excludes battery/PV from p_grid
        # (dragg/mpc_calc.py:590-593).
        p_ch0 = pick(mpc.p_ch[:, 0], jnp.zeros((n,), f32))
        p_d0 = pick(mpc.p_disch[:, 0], jnp.zeros((n,), f32))
        p_pv0 = pick(mpc.p_pv[:, 0], jnp.zeros((n,), f32))
        u_curt0 = pick(mpc.u_curt[:, 0], jnp.zeros((n,), f32))
        # EV: applied k=0 charge + SOC advance; a vehicle returning between
        # t and t+1 lands with the trip energy drained (the plant-side
        # disturbance the receding horizon recovers from, like water
        # draws — docs/architecture.md §15).
        if lay.has_ev:
            p_ev0 = pick(mpc.p_ev_ch[:, 0], jnp.zeros((n,), f32))
            hod_t = ((p.start_index + t) // dt + self._hour0) % 24
            hod_t1 = ((p.start_index + t + 1) // dt + self._hour0) % 24
            a_s = jnp.asarray(b.ev_away_start, f32)
            a_e = jnp.asarray(b.ev_away_end, f32)
            away_now = (hod_t >= a_s) & (hod_t < a_e)
            away_next = (hod_t1 >= a_s) & (hod_t1 < a_e)
            returning = away_now & ~away_next
            e_ev_next = pick(mpc.e_ev[:, 1], state.e_ev)
            e_ev_next = jnp.where(
                (jnp.asarray(b.is_ev, f32) > 0) & returning,
                jnp.maximum(
                    e_ev_next - jnp.asarray(b.ev_trip_kwh, f32), 0.0),
                e_ev_next)
        else:
            p_ev0 = jnp.zeros((n,), f32)
            e_ev_next = state.e_ev
        p_load0 = (
            jnp.asarray(b.hvac_p_c, f32) * cool0
            + jnp.asarray(b.hvac_p_h, f32) * heat0
            + jnp.asarray(b.wh_p, f32) * wh0
        )
        p_grid0 = p_load0 + (p_ch0 + p_d0 + p_ev0) - p_pv0
        price0 = price_total[:, 0]
        # Optimal path records cost on the raw (s-scaled) grid variable,
        # fallback on the physical one (dragg/mpc_calc.py:500 vs :594).
        cost0 = jnp.where(solved, price0 * s * p_grid0, price0 * p_grid0)
        temp_in_next = pick(mpc.temp_in1, fb.temp_in)
        temp_wh_next = pick(mpc.temp_wh1, fb.temp_wh)
        e_batt_next = pick(mpc.e_batt[:, 1], state.e_batt)
        # forecast_p_grid_opt = plan's step-1 grid power (0 at the horizon
        # end; dragg/mpc_calc.py:491), fallback falls back to p_load (:591).
        fore = mpc.p_grid[:, 1] / s if H > 1 else jnp.zeros((n,), f32)
        fore = jnp.where(solved, fore, p_load0)

        # Residual maxima over the check-mask homes: the per-step solver
        # telemetry the unified stream records (dragg_tpu/telemetry).  A
        # diverged home's non-finite residual becomes an f32-max sentinel
        # (visible in chunk telemetry) instead of NaN-poisoning the max.
        _big = jnp.asarray(3.4e38, f32)

        def _res_max(r):
            r = jnp.where(ctx.check_mask > 0, r, 0.0)
            return jnp.max(jnp.where(jnp.isfinite(r), r, _big))

        sel2 = solved[:, None]
        new_state = CommunityState(
            temp_in=temp_in_next,
            temp_wh=temp_wh_next,
            e_batt=e_batt_next,
            e_ev=e_ev_next,
            counter=jnp.where(solved, 0, fb.counter).astype(jnp.int32),
            plan_cool=jnp.where(sel2, mpc.cool, state.plan_cool),
            plan_heat=jnp.where(sel2, mpc.heat, state.plan_heat),
            plan_wh=jnp.where(sel2, mpc.wh, state.plan_wh),
            warm_x=(shift_warm_start(wsol.x, lay) if self._carry_warm
                    else state.warm_x),
            warm_y_box=(shift_warm_start(wsol.y_box, lay) if self._carry_warm
                        else state.warm_y_box),
            warm_rho=wsol.rho,
            key=state.key,
        )
        out = StepOutputs(
            p_grid=p_grid0,
            forecast_p_grid=fore,
            p_load=p_load0,
            temp_in=temp_in_next,
            temp_wh=temp_wh_next,
            hvac_cool_on=cool0 / s,
            hvac_heat_on=heat0 / s,
            wh_heat_on=wh0 / s,
            cost=cost0,
            waterdraws=aux.draw0,
            correct_solve=solved.astype(f32),
            p_pv=p_pv0,
            u_pv_curt=u_curt0,
            e_batt=e_batt_next,
            p_batt_ch=p_ch0,
            p_batt_disch=p_d0,
            p_ev_ch=p_ev0,
            e_ev=e_ev_next,
            agg_load=jnp.sum(p_grid0 * ctx.check_mask),
            forecast_load=jnp.sum(fore * ctx.check_mask),
            agg_cost=jnp.sum(cost0 * ctx.check_mask),
            admm_iters=sol.iters,
            repair_failed=jnp.asarray(repair_failed, f32),
            r_prim_max=_res_max(sol.r_prim),
            r_dual_max=_res_max(sol.r_dual),
            bank_fallback_count=(
                jnp.sum(jnp.where(sol.bank_fallback, ctx.check_mask, 0.0))
                if sol.bank_fallback is not None else jnp.float32(0.0)),
            **self._per_home_obs(ctx, sol),
        )
        return new_state, out

    # Merge policy for per-bucket StepOutputs: per-home leaves concatenate
    # in bucket (= community) order; the scalar reductions are sums of
    # already-masked partial sums, and the solver telemetry scalars take
    # the binding (max) bucket.
    _SUM_OUTPUTS = frozenset(
        {"agg_load", "forecast_load", "agg_cost", "repair_failed",
         "bank_fallback_count"})
    _MAX_OUTPUTS = frozenset({"admm_iters", "r_prim_max", "r_dual_max"})

    def _merge_outputs(self, outs: list) -> StepOutputs:
        from functools import reduce

        merged = {}
        for f in StepOutputs._fields:
            leaves = [getattr(o, f) for o in outs]
            if f in self._SUM_OUTPUTS:
                merged[f] = reduce(jnp.add, leaves)
            elif f in self._MAX_OUTPUTS:
                merged[f] = reduce(jnp.maximum, leaves)
            else:
                merged[f] = jnp.concatenate(leaves, axis=0)
        return StepOutputs(**merged)

    def _step_bucket(self, ctx, state_b, t, rp, refresh, factor_b):
        """assemble → solve → merge/collect for one bucket."""
        qp, aux = self._prepare(ctx, state_b, t, rp)
        sol, fcarry, warm_sol, repair_failed = self._solve(
            ctx, state_b, qp, factor_b, refresh)
        new_state, out = self._finish(ctx, state_b, t, sol, aux, warm_sol,
                                      repair_failed)
        return new_state, fcarry, out

    def _step(self, state, t, rp, refresh, factor):
        """One community timestep: assemble → solve → merge/collect.
        Returns (new_state, new_factor, outputs) — the factor cache is
        threaded separately from CommunityState so it never reaches
        checkpoints (see :meth:`init_factor`).  Bucketed engines step each
        type bucket at its own shape (state/factor are per-bucket tuples)
        and merge the outputs back into community order."""
        if not self._bucketed:
            return self._step_bucket(self._ctx0, state, t, rp, refresh,
                                     factor)
        parts = [self._step_bucket(c, s, t, rp, refresh, f)
                 for c, s, f in zip(self._buckets, state, factor)]
        new_states, fcarries, outs = zip(*parts)
        return tuple(new_states), tuple(fcarries), self._merge_outputs(
            list(outs))

    def _chunk(self, state: CommunityState, t0, rps):
        """Scan ``rps.shape[0]`` timesteps on device (the sim hot loop —
        replaces dragg/aggregator.py:771-778's per-step pool fan-out).

        The solver's factor cache is chunk-local: it refreshes on the
        chunk's first step (so chunks never depend on a stale carried
        factor — resume stays bit-exact), then every
        ``admm_refactor_every`` sim steps, and is dropped at chunk end."""
        K = max(1, self.params.admm_refactor_every)

        def body(carry, inp):
            cstate, factor = carry
            i, rp = inp
            t = t0 + i
            refresh = (i == 0) | ((t % K) == 0)
            new_state, new_factor, out = self._step(cstate, t, rp, refresh, factor)
            return (new_state, new_factor), out

        n_steps = rps.shape[0]
        (state, _), outs = lax.scan(
            body, (state, self.init_factor()), (jnp.arange(n_steps), rps)
        )
        return state, outs

    # ------------------------------------------------------------------ api
    def step(self, state: CommunityState, t: int, rp) -> tuple[CommunityState, StepOutputs]:
        """Run a single timestep (jitted).  Single-step calls always refresh
        the factor cache — exact scalings + factorization every call.  The
        (never-read) zero carry is cached: at 10k homes its Sinv alone is
        ~237 MB, too much to allocate per call."""
        if getattr(self, "_factor0", None) is None:
            self._factor0 = self.init_factor()
        state, _, out = self._step_fn(
            self._consts(),
            state, jnp.asarray(t), jnp.asarray(rp, dtype=jnp.float32),
            jnp.asarray(True), self._factor0,
        )
        return state, out

    def run_chunk(self, state: CommunityState, t0: int, rps,
                  donate: bool = False) -> tuple[CommunityState, StepOutputs]:
        """Run a chunk of timesteps with a device-side scan.  ``rps`` is
        (n_steps, H) reward prices (zeros for the baseline case).  Returns
        (final_state, outputs stacked along time).

        ``donate=True`` donates the incoming carry's buffers to the
        output state (XLA aliases them, halving the carry HBM at the
        100k-home target) — the caller MUST NOT touch ``state`` after the
        call.  The aggregator's double-buffered pipeline host-snapshots
        the carry before the next dispatch for exactly this reason
        (aggregator.run_baseline); plain callers (tests, tools that reuse
        a state) keep the default non-donating entry.  Caveat measured
        round 12: XLA:CPU executes donated computations SYNCHRONOUSLY
        inside the dispatch call (async dispatch is lost), so the
        aggregator only donates on accelerator backends — donate here on
        CPU only when you don't care about dispatch asynchrony."""
        if donate:
            if getattr(self, "_chunk_fn_donate", None) is None:
                self._chunk_fn_donate = jax.jit(self._chunk_entry,
                                                donate_argnums=(1,))
            fn = self._chunk_fn_donate
        else:
            fn = self._chunk_fn
        return fn(self._consts(), state, jnp.asarray(t0),
                  jnp.asarray(rps, dtype=jnp.float32))

    # ----------------------------------------------------------- profiling
    def phase_fns(self):
        """Separately-jitted (prepare, solve, finish) phase functions for
        the benchmark's per-phase timers.  Splitting loses cross-phase XLA
        fusion, so the phase-time sum slightly over-estimates the fused
        step — use for attribution, not as the headline rate.

        On a bucketed engine each phase maps over the buckets (qp/aux/
        sol/factor/warm become per-bucket tuples between phases, merged
        outputs at the end), so the benchmark's phase flow is unchanged."""
        consts = self._consts()

        def entry(fn):
            def wrapped(c, *a):
                with self._bound(c):
                    return fn(*a)

            jitted = jax.jit(wrapped)
            return lambda *a: jitted(consts, *a)

        if not self._bucketed:
            ctx = self._ctx0
            return (entry(lambda *a: self._prepare(ctx, *a)),
                    entry(lambda *a: self._solve(ctx, *a)),
                    entry(lambda *a: self._finish(ctx, *a)))

        from functools import reduce

        def prep(state, t, rp):
            pairs = [self._prepare(c, s, t, rp)
                     for c, s in zip(self._buckets, state)]
            qps, auxs = zip(*pairs)
            return tuple(qps), tuple(auxs)

        def solve(state, qps, factors, refresh):
            res = [self._solve(c, s, qp, f, refresh)
                   for c, s, qp, f in zip(self._buckets, state, qps, factors)]
            sols, fcs, warms, rfs = zip(*res)
            return (tuple(sols), tuple(fcs), tuple(warms),
                    reduce(jnp.add, rfs))

        def fin(state, t, sols, auxs, warms):
            parts = [self._finish(c, s, t, so, au, w)
                     for c, s, so, au, w in zip(self._buckets, state, sols,
                                                auxs, warms)]
            new_states, outs = zip(*parts)
            return tuple(new_states), self._merge_outputs(list(outs))

        return entry(prep), entry(solve), entry(fin)

    def bucket_solve_fns(self):
        """``[(type_name, fn)]`` — separately-jitted single-bucket
        assemble+solve closures for the benchmark's per-bucket phase
        attribution (``[]`` on an unbucketed engine).  Each fn takes the
        full per-bucket state/factor tuples and runs ONLY its bucket, so
        timing it isolates that bucket's share of the solve phase (the
        bucket's assemble rides along — measured ~0.5 % of solve)."""
        if not self._bucketed:
            return []
        consts = self._consts()
        fns = []
        for i, ctx in enumerate(self._buckets):
            def make(i=i, ctx=ctx):
                def wrapped(c, state, t, rp, refresh, factor):
                    with self._bound(c):
                        qp, _aux = self._prepare(ctx, state[i], t, rp)
                        return self._solve(ctx, state[i], qp, factor[i],
                                           refresh)[0]

                jitted = jax.jit(wrapped)  # dragg: disable=DT013, per-bucket attribution fns — the bench times each bucket against the SAME state/factor tuples; donation would invalidate them across buckets
                return lambda state, t, rp, refresh, factor: jitted(
                    consts, state, t, rp, refresh, factor)

            fns.append((ctx.name, make()))
        return fns


def engine_params(config, start_index: int) -> EngineParams:
    """Derive the static engine configuration from a validated config dict."""
    hems = config["home"]["hems"]
    dt = int(config["agg"]["subhourly_steps"])
    tpu_cfg = config.get("tpu", {})
    horizon = max(1, int(hems["prediction_horizon"]) * dt)
    # Solver-family resolution (registry + reference-name mapping) lives in
    # config.resolve_solver_family so the engine, the compile cache's
    # solver scoping, and checkpoint invalidation agree on the family.
    from dragg_tpu.config import resolve_solver_family

    solver = resolve_solver_family(config)
    repair_mode = str(tpu_cfg.get("integer_repair", "project"))
    if repair_mode not in ("project", "resolve"):
        raise ValueError(
            f"tpu.integer_repair must be project|resolve, got {repair_mode!r}")
    # TOML booleans arrive as Python bools; normalize the tri-state to the
    # canonical lowercase strings.
    bucketed = str(tpu_cfg.get("bucketed", "auto")).lower()
    if bucketed not in ("auto", "true", "false"):
        raise ValueError(
            f"tpu.bucketed must be auto|true|false, got "
            f"{tpu_cfg.get('bucketed')!r}")
    # Mixed-precision policy + fused iteration kernel (ISSUE 11):
    # validated against the ops/precision registry so a typo'd policy
    # fails the build, not the first solve.
    from dragg_tpu.ops.precision import validate_precision

    precision = validate_precision(str(tpu_cfg.get("precision", "f32")))
    iter_kernel = str(tpu_cfg.get("iter_kernel", "auto"))
    if iter_kernel not in ("auto", "pallas", "lax"):
        raise ValueError(
            f"tpu.iter_kernel must be auto|pallas|lax, got {iter_kernel!r}")
    if iter_kernel == "pallas" and precision != "f32":
        raise ValueError(
            "tpu.iter_kernel='pallas' requires tpu.precision='f32' — the "
            "fused window computes its residual reduction in-kernel and "
            "is f32 end-to-end (ops/pallas_iter.py)")
    return EngineParams(
        solver=solver,
        horizon=horizon,
        dt=dt,
        s=float(max(1, int(hems["sub_subhourly_steps"]))),
        discount=float(hems["discount_factor"]),
        start_index=int(start_index),
        admm_iters=int(tpu_cfg.get("admm_iters", 1500)),
        admm_rho=float(tpu_cfg.get("admm_rho", 0.1)),
        admm_eps=float(tpu_cfg.get("admm_eps", 1e-4)),
        admm_sigma=float(tpu_cfg.get("admm_sigma", 1e-6)),
        admm_alpha=float(tpu_cfg.get("admm_alpha", 1.6)),
        admm_reg=float(tpu_cfg.get("admm_reg", 1e-3)),
        admm_refactor_every=int(tpu_cfg.get("admm_refactor_every", 8)),
        admm_patience=int(tpu_cfg.get("admm_patience", 4)),
        admm_rho_update_every=int(tpu_cfg.get("admm_rho_update_every", 4)),
        admm_matvec_dtype=str(tpu_cfg.get("admm_matvec_dtype", "f32")),
        admm_refine=int(tpu_cfg.get("admm_refine", 0)),
        admm_anderson=int(tpu_cfg.get("admm_anderson", 0)),
        admm_banded_factor=bool(tpu_cfg.get("admm_banded_factor", True)),
        admm_solve_backend=str(tpu_cfg.get("admm_solve_backend", "auto")),
        # Mehrotra iterations needed grow with the horizon (measured at
        # H=48: 25 iters → 95.3% solve rate, 35 → 97.9%, 45 → 99.0%);
        # 0 = horizon-aware default, explicit values override.
        ipm_iters=int(tpu_cfg.get("ipm_iters", 0)) or 16 + horizon // 2,
        ipm_tail_frac=float(tpu_cfg.get("ipm_tail_frac", 0.25)),
        ipm_tail_iters=int(tpu_cfg.get("ipm_tail_iters", 0)),
        ipm_warm=bool(tpu_cfg.get("ipm_warm_start", False)),
        ipm_eps=float(tpu_cfg.get("ipm_eps", 2e-4)),
        ipm_freeze_zmax=float(tpu_cfg.get("ipm_freeze_zmax", 300.0)),
        integer_first_action=bool(tpu_cfg.get("integer_first_action", True)),
        integer_repair=repair_mode,
        repair_eps=float(tpu_cfg.get("repair_eps", 1e-3)),
        band_kernel=str(tpu_cfg.get("band_kernel", "auto")),
        forecast_noise_cap=float(tpu_cfg.get("forecast_noise_cap", 3.0)),
        bucketed=bucketed,
        seed=int(config["simulation"]["random_seed"]),
        obs_per_home=bool(
            config.get("telemetry", {}).get("per_home", True)),
        obs_worst_k=max(1, int(
            config.get("telemetry", {}).get("worst_k", 8))),
        reluqp_rho=float(tpu_cfg.get("reluqp_rho", 0.1)),
        reluqp_rho_factor=float(tpu_cfg.get("reluqp_rho_factor", 6.0)),
        reluqp_bank=max(1, int(tpu_cfg.get("reluqp_bank", 5))),
        reluqp_iters=int(tpu_cfg.get("reluqp_iters", 2000)),
        reluqp_tail_iters=int(tpu_cfg.get("reluqp_tail_iters", 300)),
        precision=precision,
        iter_kernel=iter_kernel,
    )


def check_mask_for(batch, config) -> np.ndarray:
    """check_type → aggregate-reduction mask (dragg/aggregator.py:767-770)."""
    check_type = config["simulation"].get("check_type", "all")
    if check_type == "all":
        return np.ones(batch.n_homes)
    from dragg_tpu.homes import TYPE_CODES

    return (np.asarray(batch.type_code) == TYPE_CODES[check_type]).astype(np.float64)


def resolve_engine_events(config, env, params, fleet=None, data_dir=None):
    """The scenario event timeline an engine should close over — the
    ``[scenarios]`` table resolved against the fleet size and environment
    span (None when the config schedules nothing).  Shared by
    :func:`make_engine` and the sharded constructor so the two cannot
    disagree about what an event schedule means."""
    from dragg_tpu.scenarios import timeline_for

    n_comm = 1 if fleet is None else fleet.n_communities
    return timeline_for(config, n_comm, len(np.asarray(env.oat)), params.dt,
                        params.start_index, data_dir=data_dir)


def env_hour0(env) -> int:
    """Hour of day at environment-series index 0 (EV away windows are
    wall-clock hours; the series starts at ``env.data_start``)."""
    ds = getattr(env, "data_start", None)
    return int(ds.hour) if ds is not None else 0


def make_engine(batch, env, config, start_index: int, fleet=None,
                events=None, data_dir=None) -> Engine:
    """Construct an :class:`Engine` from a HomeBatch + EnvironmentData +
    validated config dict.  ``fleet`` (a :class:`~dragg_tpu.homes.FleetSpec`
    from :func:`~dragg_tpu.homes.build_fleet_batch`) folds C independent
    communities into the home axis.  ``events`` overrides the scenario
    event timeline (default: resolved from the config's ``[scenarios]``
    table — :func:`resolve_engine_events`)."""
    params = engine_params(config, start_index)
    mask = check_mask_for(batch, config)
    if events is None:
        events = resolve_engine_events(config, env, params, fleet=fleet,
                                       data_dir=data_dir)
    return Engine(params, batch, env.oat, env.ghi, env.tou, check_mask=mask,
                  fleet=fleet, events=events, hour0=env_hour0(env))
