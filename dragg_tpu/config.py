"""Config loading + schema validation.

Mirrors the reference's TOML schema and required-key validation
(dragg/aggregator.py:38-50,88-109 and dragg/data/config.toml:1-71).  The same
TOML files the reference ships are loadable unchanged.  Differences:

* reading uses the stdlib ``tomllib`` (the reference used the ``toml``
  package);
* validation raises ``ConfigError`` instead of calling ``sys.exit(1)``;
* ``default_config()`` provides the full default configuration as a dict so
  the framework runs standalone without a data directory.
"""

from __future__ import annotations

import copy
import os
try:
    import tomllib
except ImportError:  # Python < 3.11: same API from the tomli backport
    import tomli as tomllib
from typing import Any

# Required-key schema — parity with dragg/aggregator.py:38-50.  The reference
# requires home.wh.c_dist but never uses it (WH capacitance is derived from
# tank size, dragg/mpc_calc.py:183-184); we therefore do NOT require it.
REQUIRED_KEYS: dict[str, Any] = {
    "community": {"total_number_homes"},
    "home": {
        "hvac": {"r_dist", "c_dist", "p_cool_dist", "p_heat_dist", "temp_sp_dist", "temp_deadband_dist"},
        "wh": {"r_dist", "p_dist", "sp_dist", "deadband_dist", "size_dist", "waterdraw_file"},
        "battery": {"max_rate", "capacity", "lower_bound", "upper_bound", "charge_eff", "discharge_eff"},
        "pv": {"area", "efficiency"},
        "hems": {"prediction_horizon", "sub_subhourly_steps", "discount_factor"},
    },
    "simulation": {"start_datetime", "end_datetime", "random_seed", "check_type", "run_rbo_mpc"},
    "agg": {"base_price", "subhourly_steps"},
}


class ConfigError(ValueError):
    """Raised when a config file fails schema validation."""


def _validate(data: dict, required: dict, path: str = "") -> None:
    for key, sub in required.items():
        if key not in data:
            raise ConfigError(f"Missing required config key: {path}{key}")
        if not isinstance(data[key], dict):
            raise ConfigError(f"Config section {path}{key} must be a table, got {type(data[key]).__name__}")
        if isinstance(sub, dict):
            _validate(data[key], sub, path=f"{path}{key}.")
        elif isinstance(sub, set):
            missing = sub - set(data[key].keys())
            if missing:
                raise ConfigError(f"Parameters for {path}{key}: {sorted(missing)} must be specified")


def validate_config(data: dict) -> dict:
    _validate(data, REQUIRED_KEYS)
    return data


def configured_solver(config: dict) -> str:
    """The raw configured solver name, with the framework default applied.

    Single source of truth for every consumer of ``home.hems.solver`` —
    run-directory naming (utils.layout), Reformat discovery, home metadata,
    and the engine (which additionally maps reference solver names onto the
    batched families) — so a config that omits the key gets ONE consistent
    identity everywhere."""
    return str(config["home"]["hems"].get("solver", "ipm"))


# Batched solver families this framework implements (round 10 adds the
# pre-factorized dense-matmul "reluqp" — ops/reluqp.py), plus the mapping
# from the reference's solver names (the GLPK_MI/ECOS/GUROBI table,
# dragg/mpc_calc.py:141-145, and the shipped config.toml default
# "GLPK_MI") onto them, so an unmodified reference config runs: the MILP
# semantics are covered by the relaxation + integer_first_action contract
# (ops/qp.py), and ECOS — itself an interior-point code — maps to the IPM.
SOLVER_FAMILIES = ("ipm", "admm", "reluqp")
REFERENCE_SOLVER_MAP = {
    "glpk_mi": "ipm", "glpk": "ipm", "gurobi": "ipm", "ecos": "ipm",
}


def resolve_solver_family(config: dict) -> str:
    """The batched solver family the config selects — ``configured_solver``
    lowered and mapped through :data:`REFERENCE_SOLVER_MAP`.  Raises
    ``ConfigError`` for names in neither table.  The engine, the compile
    cache's solver scoping (utils/compile_cache.py), and checkpoint
    invalidation (aggregator._run_shape) all resolve through here so the
    three can never disagree about which family a config runs."""
    name = configured_solver(config).lower()
    name = REFERENCE_SOLVER_MAP.get(name, name)
    if name not in SOLVER_FAMILIES:
        raise ConfigError(
            f"home.hems.solver must be one of {'|'.join(SOLVER_FAMILIES)} "
            f"(or a reference solver name "
            f"{'|'.join(sorted(REFERENCE_SOLVER_MAP))}), got "
            f"{config['home']['hems'].get('solver')!r}")
    return name


def load_config(path: str | None = None) -> dict:
    """Load and validate a TOML config.

    Resolution mirrors the reference (dragg/aggregator.py:31-35): if ``path``
    is None, use ``$DATA_DIR/$CONFIG_FILE`` (defaults ``data/config.toml``).
    Falls back to :func:`default_config` if no file exists at the default
    location and none was explicitly requested.
    """
    explicit = path is not None
    if path is None:
        data_dir = os.path.expanduser(os.environ.get("DATA_DIR", "data"))
        path = os.path.join(data_dir, os.environ.get("CONFIG_FILE", "config.toml"))
    if not os.path.exists(path):
        if explicit:
            raise ConfigError(f"Configuration file does not exist: {path}")
        return default_config()
    with open(path, "rb") as f:
        data = tomllib.load(f)
    return validate_config(data)


# Default configuration — same parameter distributions and simulation window
# as the reference's shipped config (dragg/data/config.toml:1-71).
_DEFAULT: dict[str, Any] = {
    "community": {
        "total_number_homes": 10,
        "homes_battery": 0,
        "homes_pv": 4,
        "homes_pv_battery": 0,
        "homes_ev": 0,           # scenario types (ROADMAP item 4,
        "homes_heat_pump": 0,    # docs/architecture.md §15) — 0 keeps the
                                 # reference's four-type population
        "overwrite_existing": True,
        "house_p_avg": 1.2,
    },
    "simulation": {
        "start_datetime": "2015-01-01 00",
        "end_datetime": "2015-01-04 00",
        "random_seed": 12,
        "n_nodes": 4,
        "load_zone": "LZ_HOUSTON",
        "check_type": "all",
        "run_rbo_mpc": True,
        "run_rl_agg": False,
        "run_rl_simplified": False,
        "checkpoint_interval": "daily",
        "named_version": "test",
    },
    "agg": {
        "base_price": 0.07,
        "subhourly_steps": 1,
        "tou_enabled": True,
        "spp_enabled": False,
        "rl": {
            "action_horizon": 1,
            "forecast_horizon": 1,
            "prev_timesteps": 12,
            "max_rp": 0.02,
        },
        "tou": {
            "shoulder_times": [9, 21],
            "shoulder_price": 0.09,
            "peak_times": [14, 18],
            "peak_price": 0.13,
        },
        "simplified": {"response_rate": 0.3, "offset": 0.0},
    },
    "home": {
        "hvac": {
            "r_dist": [6.8, 9.2],
            "c_dist": [4.25, 5.75],
            "p_cool_dist": [3.5, 3.5],
            "p_heat_dist": [3.5, 3.5],
            "temp_sp_dist": [18, 22],
            "temp_deadband_dist": [2, 3],
        },
        "wh": {
            "r_dist": [18.7, 25.3],
            "p_dist": [2.5, 2.5],
            "sp_dist": [45.5, 48.5],
            "deadband_dist": [9, 12],
            "size_dist": [200, 300],
            "waterdraw_file": "waterdraw_profiles.csv",
        },
        "battery": {
            "max_rate": [3, 5],
            "capacity": [9.0, 13.5],
            "lower_bound": [0.01, 0.15],
            "upper_bound": [0.85, 0.99],
            "charge_eff": [0.85, 0.95],
            "discharge_eff": [0.97, 0.99],
        },
        "pv": {"area": [20, 32], "efficiency": [0.15, 0.2]},
        # Scenario-type parameter distributions (uniform bounds, like every
        # other [home.*] table; homes.EV_PARAM_DEFAULTS mirrors these so an
        # unmodified reference TOML — which lacks the tables — still runs).
        "ev": {
            "capacity": [40.0, 80.0],
            "max_rate": [3.3, 9.6],
            "charge_eff": [0.88, 0.95],
            "target_soc": [0.7, 0.9],
            "init_soc": [0.3, 0.6],
            "away_start": [7.0, 9.0],
            "away_duration": [7.0, 10.0],
            "trip_kwh": [6.0, 14.0],
        },
        "heat_pump": {
            "cop_base": [2.4, 3.2],
            "cop_slope": [0.04, 0.08],
        },
        "hems": {
            "prediction_horizon": 6,
            "sub_subhourly_steps": 6,
            "discount_factor": 0.92,
            # Default solver family (reference analog: the GLPK_MI/ECOS/
            # GUROBI table, dragg/mpc_calc.py:141-145).  "ipm" — the batched
            # Mehrotra predictor-corrector — is the measured winner at every
            # batch size on both CPU (1.2-3.5x at 16-128 homes, ~4x at
            # 256-1024, 7.1x at 2048) and TPU (21.7x at 10k homes; all
            # measurements in docs/perf_notes.md); "admm" (warm-started
            # splitting) remains available (docs/perf_notes.md).
            "solver": "ipm",
        },
    },
    "rl": {
        "utility": {"action_space": [-0.02, 0.02]},
        "parameters": {
            "agent": "linear",  # "linear" (reference parity) | "ddpg" (Flax neural)
            "alpha": 0.0625,
            "beta": 1.0,
            "epsilon": 0.05,
            "batch_size": 32,
            "twin_q": True,
        },
        # Fleet-scale vectorized RL training (dragg_tpu/rl/fleet —
        # ROADMAP item 1, architecture.md §17; no reference analog: the
        # reference trains one agent against one community).  Active only
        # when fleet.communities > 1; C = 1 keeps the single-community
        # RL paths byte-for-byte (test-pinned).
        "fleet": {
            "policy": "shared",     # "shared": ONE actor-critic trained
                                    # IMPALA-style from C parallel rollout
                                    # streams feeding a common replay +
                                    # batched learner update per step;
                                    # "per_community": C independent
                                    # agents (vmapped reference cores)
            "learner_batch": 0,     # learner minibatch for the shared
                                    # policy's batched update (0 =
                                    # rl.parameters.batch_size)
            "gradient": "score",    # "score": stochastic policy gradient
                                    # (reference semantics); "mpc": add a
                                    # deterministic actor term through the
                                    # branch-free relaxed MPC solve
                                    # (jvp d agg_load/d rp — CA-AC-MPC,
                                    # PAPERS.md; shared policy only)
            "mpc_weight": 0.25,     # weight of the "mpc" actor term
            "event_features": True,  # fold the scenario event timeline
                                     # (round 13) into the shared policy's
                                     # observation as per-community
                                     # features (price shock / DR cap /
                                     # outage / comfort relax intensity)
        },
    },
    # Supervised device execution (dragg_tpu/resilience — no reference
    # analog; the reference has no accelerator to lose).
    "resilience": {
        "deadline_s": 3600.0,   # hard wall-clock limit per supervised child
        "stall_s": 900.0,       # kill a child whose heartbeat goes older
                                # than this (round-4 hung-compile window:
                                # the 10k engine build stalled 900 s before
                                # wedging the tunnel); 0 disables
        "retries": 1,           # TPU attempts after the first failure
        "backoff_s": 30.0,      # base of probe-gated exponential backoff
        "probe_timeout_s": 60.0,  # jax-level tunnel probe hard timeout
        "degrade_to_cpu": True,  # on device loss mid-run, resume the SAME
                                 # run on CPU from the latest atomic
                                 # checkpoint (platform transition recorded
                                 # in the provenance JSON)
    },
    # MPC serving daemon (dragg_tpu/serve — no reference analog; replaces
    # the pathos+Redis aggregator's dies-with-its-process lifetime,
    # dragg/aggregator.py:723-724).
    "serve": {
        "host": "127.0.0.1",
        "port": 8070,         # HTTP surface (0 = ephemeral, for tests)
        "workers": 1,          # supervised worker slots (each holds one
                               # warm compiled engine child)
        "queue_max": 256,      # pending+assigned cap; beyond it POST
                               # /solve answers 429 + Retry-After
        "batch_max": 0,        # requests per coalesced group (0 = the
                               # serving community size — the compiled
                               # engine's per-slot batch shape)
        "fleet_slots": 1,      # community slots C per worker engine: the
                               # worker compiles a C-community fleet of
                               # IDENTICAL copies of the serving community
                               # (seed_stride 0), so one warm solve
                               # coalesces up to C request groups (round
                               # 12: compile flat in C).  1 = the round-11
                               # single-shape engine, byte-identical
        "batch_window_ms": 25.0,  # latency-aware coalescing window: a
                                  # dispatchable group waits up to this
                                  # long for more same-timestep groups to
                                  # arrive before the batch goes out;
                                  # dispatch fires early the moment all C
                                  # slots fill (granularity = poll_s)
        "max_streams": 32,     # concurrent /result?stream=1 consumers;
                               # each stream pins an HTTP thread + an
                               # events-tail follower for up to its
                               # whole budget, so past the cap streams
                               # answer 429 + Retry-After (poll /result
                               # instead)
        "max_steps": 96,       # cap on a request's multi-chunk `steps`
                               # (each step re-runs the warm compiled
                               # one-step program; incremental results
                               # stream over /result?stream=1)
        "patterns": [],        # extra pattern lanes warmed at boot — each
                               # entry {name, horizon_hours?, homes?,
                               # fleet_slots?, workers?} compiles its own
                               # bucket-pattern signature (serve/patterns)
        "spill_patterns": 1,   # bounded compile-on-demand lanes for
                               # requests carrying an inline pattern spec
                               # no existing lane serves; beyond it such
                               # requests answer 429 (pattern_capacity)
        "request_deadline_s": 120.0,  # default per-request deadline;
                                      # expired-unserved requests fail
                                      # (a request's own deadline_s wins)
        "request_retries": 2,  # re-dispatches after worker deaths before
                               # a request fails terminally
        "batch_deadline_s": 120.0,  # wall-clock limit per dispatched
                                    # batch; expiry kills the worker
                                    # (DEADLINE if still beating,
                                    # COMPILE_HANG if stalled)
        "worker_stall_s": 900.0,  # heartbeat-stall kill for workers
                                  # (hung compile / hung solve — the
                                  # round-4 wedge chain); 0 disables.
                                  # Default matches resilience.stall_s:
                                  # staged_compile beats only BETWEEN
                                  # stages, and a single cold compile
                                  # stage runs 59-123 s at the 10k
                                  # target shape — a tighter default
                                  # would stall-kill honest cold
                                  # compiles into an unrecoverable
                                  # relaunch loop (nothing persisted
                                  # mid-compile, so every relaunch is
                                  # equally cold)
        "backoff_s": 2.0,      # base of exponential relaunch backoff
                               # after consecutive worker failures
        "probe_timeout_s": 60.0,  # classified liveness probe budget for
                                  # probe-gated admission / degradation
        "retry_after_s": 2.0,  # Retry-After hint on queue-full 429s
        "poll_s": 0.05,        # dispatch/worker spool poll cadence
        "drain_s": 30.0,       # graceful-drain budget on SIGTERM (the
                               # journal carries whatever didn't finish)
        "journal_fsync": True,  # fsync every journal append (the
                                # durability point; false only for
                                # throwaway benchmarking)
        "results_cache": 4096,  # terminal answers held in memory for
                                # /result + duplicate-POST lookup; the
                                # journal keeps the unbounded history
                                # (evicted ids answer their verdict of
                                # record with an `evicted` marker)
        "degrade_to_cpu": True,  # dead/wedged tunnel flips to degraded-
                                 # CPU serving (transition journaled,
                                 # provenance on every response); false
                                 # + --platform tpu = strict 429s
    },
    # Scenario packs + community event timelines (dragg_tpu/scenarios —
    # ROADMAP item 4, docs/architecture.md §15, docs/scenarios.md; no
    # reference analog: the reference knows one static tariff and four
    # home types).
    "scenarios": {
        "pack": "",    # scenario-pack name (resolves data/packs/<name>.toml
                       # or a literal .toml path): [mix] fractions expand
                       # into community.homes_* counts, [[events]] merge
                       # after the inline list below
        "events": [],  # inline [[scenarios.events]] entries — kind =
                       # tariff_shock|dr|outage with start_hour (sim-
                       # relative), duration_hours, repeat_hours,
                       # communities, price_delta / p_cap_kw /
                       # comfort_relax_degc (schema: docs/scenarios.md)
    },
    # Multi-community fleet engine (round 12 — ROADMAP item 3,
    # architecture.md §14; no reference analog: the reference runs one
    # community per process).
    "fleet": {
        "communities": 1,   # C independent communities folded into the
                            # home axis as one batched fleet (each drawn
                            # with its own seed; type buckets hold
                            # C·B_type homes under the SAME compiled
                            # patterns — compile cost flat in C)
        "seed_stride": 1,   # community c's population seed =
                            # random_seed + c * seed_stride
        "community_base": 0,  # GLOBAL index of this engine's first
                              # community (cross-process sharding,
                              # architecture.md §19): a shard worker
                              # running communities [base, base+C) of a
                              # larger fleet keeps every community's
                              # global seed / name prefix / weather
                              # offset, so its per-community outputs are
                              # bit-identical to the in-process fleet's.
                              # 0 = the whole fleet in one engine
        "weather_offset_hours": 0,  # community c's environment windows are
                                    # shifted by c * this many hours
                                    # (decorrelates fleet weather; 0 keeps
                                    # the shared-window fast path)
        "pipeline": True,   # double-buffered host pipeline: dispatch chunk
                            # N+1 before materializing chunk N's outputs so
                            # collect/observatory/checkpoint/telemetry run
                            # while the device solves; false restores the
                            # synchronous loop (for overlap A/Bs)
    },
    # Cross-process fleet sharding (dragg_tpu/shard — ROADMAP item 4,
    # architecture.md §19; no reference analog: the reference's
    # pathos+Redis fan-out died with its central store).  A jax-free
    # COORDINATOR partitions fleet.communities into shard.workers
    # contiguous community ranges, each run by its own supervised worker
    # process (own mesh/backend, own chunk-boundary checkpoints); only
    # per-chunk per-community aggregate series cross process boundaries.
    "shard": {
        "workers": 1,       # shard worker processes N (1 = the in-process
                            # fleet engine, byte-identical legacy path);
                            # communities split into N contiguous ranges
        "chunk_steps": 8,   # sim timesteps per shard chunk — the unit of
                            # outbox exchange, checkpointing, and crash
                            # re-work (a killed shard replays at most one)
        "deadline_s": 0.0,  # PROGRESS deadline per shard — re-armed on
                            # every merged chunk and on relaunch, so it
                            # bounds time WITHOUT progress, not a whole
                            # multi-hour run (0 = resilience.deadline_s)
        "stall_s": 0.0,     # kill a worker whose heartbeat goes older
                            # than this (0 = disabled — a big CPU chunk
                            # legitimately computes longer than any beat
                            # cadence; set ~900 for on-chip runs)
        "restarts": 3,      # relaunches per shard before the run fails
        "degrade_after": 1,  # consecutive failures of one shard before
                             # it degrades TPU→CPU INDEPENDENTLY of the
                             # others (resilience.degrade_to_cpu gates;
                             # transition journaled with the taxonomy
                             # kind)
        "poll_s": 0.05,     # coordinator spool/liveness poll cadence
        "transport": "spool",  # chunk exchange: "spool" = shared-disk
                               # outbox files (round 18, byte-identical);
                               # "tcp" = workers push checksummed frames
                               # to the coordinator's chunk-ingest server
                               # (at-least-once, epoch-fenced, journal-
                               # before-ack — architecture.md §20)
        "transport_retry_s": 10.0,  # wire-down budget per chunk push
                                    # before a tcp worker degrades
                                    # (sticky) to the shared spool
        "listen": "127.0.0.1:0",  # chunk-ingest bind address for
                                  # transport="tcp" (port 0 = ephemeral;
                                  # workers get the bound endpoint via
                                  # their spec)
    },
    # Unified run telemetry (dragg_tpu/telemetry — round-7 tentpole).
    "telemetry": {
        "enabled": True,  # run-scoped event bus: <run_dir>/events.jsonl +
                          # final metrics.json snapshot; false = metrics
                          # and events both no-op (near-zero overhead)
        "dir": "",        # events/metrics destination ("" = resolve
                          # $DRAGG_TELEMETRY_DIR, else the run directory —
                          # supervised runs export the env var so parent
                          # and child share one stream)
        # Observatory layer (round 9 — docs/telemetry.md "Observatory").
        "per_home": True,  # fold per-home solver attribution on device
                           # (fixed-bin residual/iteration histograms +
                           # worst-k capture riding the StepOutputs
                           # transfer); false compiles the fold out —
                           # device program identical to pre-round-9
        "worst_k": 8,      # worst-homes captured per bucket per step
        "forensics": False,  # per-chunk worst-k forensic dumps to
                             # <run_dir>/forensics/ (home config + chunk-
                             # start state — offline QP reconstruction
                             # without a full re-run)
        # Trace plane (ISSUE 20 — docs/telemetry.md "Tracing").
        "trace": False,    # causal trace context on every record (trace/
                           # span/parent ids), propagated to supervised
                           # children, serve requests, and shard chunk
                           # pushes; false = no trace fields at all —
                           # streams byte-identical to round 19
        "flush_interval_s": 0.0,  # live metrics rollup cadence: >0
                                  # flushes in-progress metric deltas to
                                  # metrics.json every this-many seconds
                                  # (crash no longer loses the snapshot);
                                  # 0 = final-snapshot-only (round-19
                                  # behavior)
    },
    # dragg_tpu-specific knobs (no reference analog).
    "tpu": {
        "admm_iters": 1500,
        "admm_refactor_every": 8,
        "admm_patience": 4,   # stagnation-exit patience in check windows (0 disables)
        "admm_rho_update_every": 4,  # in-loop rho-update cadence (check windows)
        "admm_matvec_dtype": "f32",  # "bf16": half-traffic Sinv matvec (opt-in;
                                     # measured unhelpful — costs iterations)
        "admm_refine": 0,  # refinement passes per in-loop KKT solve: 0 reads
                           # 1 (B,m,m) matrix/iter instead of 3 for ~19% more
                           # iterations on the stale-factor path — ~2.5x less
                           # HBM traffic net (final polish still refines)
        "admm_anderson": 0,  # Anderson-acceleration depth (opt-in: measured
                             # -16% warm iterations, slight solve-rate dip)
        "admm_banded_factor": True,  # RCM + banded-Cholesky Schur factor
                                     # (O(Bm·bw²) vs dense O(Bm³); bw=4)
        "admm_solve_backend": "auto",  # in-loop KKT solve: "dense_inv" |
                                       # "band" (no (B,m,m) array — the
                                       # 100k-home memory regime) | "auto"
        # ReLU-QP family (hems.solver="reluqp", round 10 — ops/reluqp.py):
        # per-type pre-factorized dense-matmul ADMM.  The rho schedule is a
        # geometric bank centered on reluqp_rho with ratio
        # reluqp_rho_factor; in-loop rho adaptation is an index switch into
        # the bank (never a refactorization).
        "reluqp_rho": 0.1,        # bank center rho (matches admm_rho)
        "reluqp_rho_factor": 6.0,  # geometric spacing between bank entries
        "reluqp_bank": 5,         # bank size R — (B, R, m, m) pre-inverted
                                  # Schur operators per refresh
        "reluqp_iters": 2000,     # banked-loop iteration cap
        "reluqp_tail_iters": 300,  # fallback exact-refactorization tail
                                   # budget for homes the banked loop left
                                   # unconverged (0 disables; 300 = the
                                   # measured rescue depth for warm steps
                                   # jammed by a stale bank — see
                                   # ops/reluqp.py tail_iters)
        # Mixed-precision MXU policy (ISSUE 11 — ops/precision.py,
        # docs/architecture.md §16): "bf16x3" runs the dense families'
        # hot-loop matmuls (reluqp x-update, admm dense_inv apply) as
        # 3-pass bf16 with f32 accumulation; residual/check/warm-start
        # tensors stay f32 ALWAYS (rounds 2/9 measured bf16 storage
        # diverging — the policy is compute-only by construction).
        # "f32" (default) is bit-identical to the pre-policy engine.
        "precision": "f32",
        # Fused reluqp check-window kernel (ops/pallas_iter.py): one
        # Pallas launch per check window (matmuls + clamp + residual-max
        # reduction, VMEM-resident).  "auto" resolves to "lax" until the
        # on-chip A/B (tools/bench_engine_kernels.py --iter-kernels)
        # records a verdict; "pallas" forces it (f32-only, unsharded).
        "iter_kernel": "auto",
        "ipm_warm_start": False,  # seed the IPM from the receding-horizon
                                  # shift — measured PESSIMIZATION (+55%
                                  # steady-state iterations, warm-start
                                  # jamming; docs/perf_notes.md round 3)
        "ipm_iters": 0,  # Mehrotra iteration cap (hems.solver="ipm");
                         # 0 = horizon-aware default: 16 + (decision steps)/2
        "ipm_tail_frac": 0.25,  # tail compaction: after a short full-batch
                                # phase, gather the worst 25% of homes and
                                # finish them alone (1.5-1.6x solver time,
                                # equal-or-better solve rates); 0 disables
        "ipm_tail_iters": 0,  # tail-phase iteration cap (0 = ipm_iters)
        "integer_first_action": True,  # MILP repair ON by default (round-5:
                                       # integer parity is the SHIPPED story
                                       # — the reference's GLPK_MI applies
                                       # integer duty counts,
                                       # dragg/mpc_calc.py:171-173): pin the
                                       # three k=0 duty counts to rounded
                                       # values and re-solve so the APPLIED
                                       # action is integer (measured: the
                                       # bare relaxation sits 2.7-3.6% below
                                       # the integer optimum; pinning k=0 is
                                       # 20/20 feasible — perf notes round
                                       # 4).  Costs a 2nd (warm) solve/step;
                                       # set false for relaxation-only runs.
        "integer_repair": "project",  # how the repair lands the pin:
                                      # "project" = closed-form k=1 state
                                      # update, NO second solve (everything
                                      # the plant applies is affine in the
                                      # pinned counts; measured drift vs
                                      # re-solving: see perf notes round 5);
                                      # "resolve" = pinned-box re-solve.
        "repair_eps": 1e-3,  # IPM tolerance for the "resolve" re-solve —
                             # loose on purpose: 8-9 iters vs 25-39 at the
                             # production 2e-4, cost drift 1.5e-4 (perf
                             # notes round 5).  Unused under "project".
        "ipm_freeze_zmax": 300.0,  # divergence-freeze dual threshold (scaled
                                   # space): freeze a home when rp stalls AND
                                   # its box duals exceed this.  Feasible
                                   # homes measure O(1) duals (CPU) so 300
                                   # keeps ~2.5 orders of margin; vs 1e3 it
                                   # cuts hard-day iterations 15.7/19.7 →
                                   # 10.9/13.2 with BIT-IDENTICAL outcomes
                                   # (solved flags, cost, agg load — 512
                                   # homes × 3 days, perf notes round 4).
                                   # Exposed for on-chip re-tuning.
        "ipm_eps": 2e-4,  # IPM stopping tolerance: halves iterations vs
                          # 1e-4 at equal-or-better solve rate, 0 comfort
                          # violations, identical ≤0.36% objective gap vs
                          # HiGHS (docs/perf_notes.md round 3); the ADMM
                          # keeps admm_eps — its certificates are tuned
                          # at 1e-4
        "band_kernel": "auto",  # band factor/solve impl: "pallas" (fused TPU
                                # kernels, ops/pallas_band.py) | "xla" (scan
                                # path) | "auto" = pallas on TPU, xla elsewhere
        "bucketed": "auto",  # type-bucketed shape specialization: solve each
                             # home-type bucket at its own (n, m) shape
                             # instead of padding every home to the superset
                             # pv_battery layout (base homes carry ~33%
                             # smaller band factors).  "auto" buckets when
                             # the community is >=32 homes and >=25% of
                             # them are non-superset (engine.BUCKETED_MIN_*;
                             # thresholds from the 512-home A/B, perf notes
                             # round 8); true/false force either path
        "forecast_noise_cap": 3.0,  # max forecast-noise std (degC): the reference's
                                    # unbounded 1.1^k growth breaks the season gate
                                    # beyond ~16h horizons (see engine._prepare)
        "compile_cache": True,  # persistent XLA compilation cache: re-runs of
                                # the same config skip the cold compile
        "compile_cache_dir": "",  # cache location ("" = $DRAGG_COMPILE_CACHE_DIR
                                  # or ~/.cache/dragg_tpu/xla)
        "admm_rho": 0.1,
        "admm_sigma": 1e-6,
        "admm_reg": 1e-3,
        "admm_alpha": 1.6,
        "admm_eps": 1e-4,
        "fix_tou_peak": False,  # reference bug parity: peak price is overwritten by shoulder (dragg/aggregator.py:214-215)
        "mesh_axis": "homes",
        "sharded": "auto",  # Aggregator engine: "auto" = shard the home axis
                            # when >1 device is visible; true/false force
        "profile_dir": "",  # non-empty: jax.profiler trace of one device chunk
                            # (JAX_PROFILE_DIR env overrides)
        # Flax DDPG agent knobs (rl.parameters.agent = "ddpg").
        "ddpg_actor_lr": 1e-3,
        "ddpg_critic_lr": 1e-3,
        "ddpg_tau": 0.01,
        "ddpg_policy_delay": 2,
        "ddpg_hidden": 64,
    },
}


def default_config() -> dict:
    """Return a deep copy of the default configuration."""
    return copy.deepcopy(_DEFAULT)
