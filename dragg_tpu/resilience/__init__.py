"""Supervised device execution — the survival logic five rounds of TPU
outages taught this repo (CLAUDE.md gotchas; docs/perf_notes.md rounds
2-5), promoted from bash into one tested layer.

Every long-running entry point routes device work through here:

* :mod:`supervisor` — run any device workload in a CHILD process with a
  hard deadline, a progress-heartbeat file, and stdout/stderr capture,
  so a hung compile kills the child instead of wedging the parent (the
  parent never initializes a jax backend — asserted in tests);
* :mod:`liveness` — the jax-level tunnel probe + the round-4 wedge
  signature as a structured, tested API with probe-gated exponential
  backoff;
* :mod:`taxonomy` — the failure vocabulary (``TUNNEL_DOWN``, ``WEDGED``,
  ``COMPILE_HANG``, ``VMEM_OOM``, ``CHILD_CRASH``, ``DEADLINE``) and the
  classifiers that map child outcomes / probe verdicts onto it;
* :mod:`runner` — retry ladders and the degradation policy: on device
  loss mid-run, resume the SAME run on CPU from the latest atomic
  checkpoint and record the platform transition in the output JSON;
* :mod:`faults` — deterministic fault injection (``$DRAGG_FAULT_INJECT``)
  so chaos tests exercise every recovery path on the CPU mesh in CI;
* :mod:`heartbeat` — the child-side progress beats the supervisor's
  stall detector reads;
* :mod:`net` — socket deadline helpers (every raw socket op in the
  framework carries an explicit timeout — dragglint DT005; the shard
  wire's per-connection deadlines ride these).

Import rule: nothing in this package imports jax at module level, and
the parent-side paths (supervisor, liveness, runner, taxonomy, faults)
never import it at all — probes and workloads run in subprocesses.
"""

from dragg_tpu.resilience.taxonomy import (  # noqa: F401
    CHILD_CRASH,
    COMPILE_HANG,
    DEADLINE,
    FAILURE_KINDS,
    TUNNEL_DOWN,
    VMEM_OOM,
    WEDGED,
    classify_child,
    classify_liveness,
)
from dragg_tpu.resilience.liveness import (  # noqa: F401
    LivenessReport,
    backoff_delays,
    check_liveness,
)
from dragg_tpu.resilience.net import (  # noqa: F401
    connect_deadline,
    parse_endpoint,
    recv_exact,
)
from dragg_tpu.resilience.supervisor import (  # noqa: F401
    SupervisedResult,
    assert_parent_has_no_jax,
    run_supervised,
)
