"""Deterministic fault injection — stdlib only, zero cost when unarmed.

Chaos tests must exercise every recovery path of the supervised
execution layer on the 8-device virtual CPU mesh, with no chip and no
flaky timing: the faults are INJECTED at named sites, armed through one
environment variable so supervised children inherit them.

``$DRAGG_FAULT_INJECT`` is a comma-separated list of specs:

    <action>@<site>[:<nth>][:once]
                                 fire at the <nth> hit of <site> (1-based,
                                 default 1) in THIS process; ``once``
                                 fires at most once ACROSS processes (a
                                 marker file under ``$DRAGG_FAULT_STATE``
                                 records the firing), so "die once, then
                                 the relaunch succeeds" resume tests need
                                 no other shared state
    probe_down[:<n>]             the first <n> liveness checks report
                                 TUNNEL_DOWN (default 1), then real
    probe_wedge[:<n>]            ... report the full round-4 WEDGE
                                 signature (hung probe + proxy http-403 +
                                 compile helper not listening)
    probe_live[:<n>]             liveness reports a live TPU — opens the
                                 probe gate so CPU-only chaos tests can
                                 drive the TPU-attempt paths.  Bare =
                                 every check; ``:n`` = only the next <n>
                                 checks (then the real probe resumes)

Actions for ``fault_hook(site)`` call sites:

    hang        stop beating and sleep past any deadline (the supervisor
                must kill us — COMPILE_HANG when the stall detector
                fires first, DEADLINE otherwise)
    sigkill     SIGKILL our own process (abrupt device-loss analog)
    vmem_oom    raise RuntimeError with the scoped-VMEM OOM signature
    exit        sys.exit(17) (plain child failure)
    torn        raise :class:`WireFault` ("torn") — the shard wire
                client sends a deliberately TRUNCATED frame (the server
                must discard it as torn and the retry must succeed)
    drop        raise :class:`WireFault` ("drop") — the chunk-ingest
                server drops the connection AFTER merge+journal, before
                the 200 (ack lost after merge; the retry must dedup)
    cut         raise :class:`WireFault` ("cut") — the wire client
                severs the connection MID-FRAME (network partition
                mid-chunk; at-least-once delivery must re-send)

The wire actions are only meaningful at the ``wire_*`` sites
(shard/transport.py interprets the raised :class:`WireFault`); generic
actions (``sigkill@wire_send``, ...) still work everywhere.

Sites are plain strings; the instrumented code names them
(``sim_chunk``, ``bench_chunk``, ``bench_build``, ...).  Every site
compiled into the repo is registered in :data:`SITES` (one catalog —
docs/architecture.md §8 table; a test asserts both stay in sync).
Counters are per-process: a spec like ``sigkill@sim_chunk:3`` kills the
child at its 3rd chunk, and the RELAUNCHED child starts counting from
zero — which is exactly what lets a resume test inject "die once, then
succeed" without any shared state.
"""

from __future__ import annotations

import os
import signal
import sys
import time

ENV = "DRAGG_FAULT_INJECT"

_ACTIONS = ("hang", "sigkill", "vmem_oom", "exit", "torn", "drop", "cut")
_WIRE_ACTIONS = ("torn", "drop", "cut")

# Every fault_hook site compiled into the repo, with where it lives —
# THE catalog (docs/architecture.md §8 renders it as a table; a test
# asserts every entry appears there and every fault_hook("...") literal
# in the source is an entry here).  The staged-compile family is one
# parameterized site per stage (telemetry/compile_obs.py).
SITES = {
    "sim_chunk": "aggregator baseline loop, before each device chunk",
    "bench_build": "bench.py measured child, before the engine build",
    "bench_chunk": "bench.py measured child, before each timed chunk",
    "scale_chunk": "tools/validate_scale.py child, before each chunk",
    "compile_lower": "staged compile (telemetry/compile_obs), before "
                     "the jit lowering stage",
    "compile_compile": "staged compile, before the AOT compile stage",
    "compile_first_execute": "staged compile, before the first execution",
    "serve_boot": "serve worker, before its engine build / warm report",
    "serve_batch": "serve worker, before solving each batch",
    "shard_build": "shard worker, before its fleet engine build",
    "shard_chunk": "shard worker, before each chunk (the kill -9 "
                   "≤1-chunk re-work site)",
    "wire_send": "shard wire client, before pushing a chunk frame "
                 "(torn = truncated frame on the wire)",
    "wire_ack": "shard chunk-ingest server, AFTER merge+journal, before "
                "the 200 (drop = ack lost after merge)",
    "wire_partition": "shard wire client, mid-chunk push (cut = "
                      "connection severed mid-frame)",
}

# The injected scoped-VMEM OOM must trip taxonomy.looks_like_vmem_oom —
# same wording family as the real axon AOT compiler error (round 4).
VMEM_OOM_MESSAGE = ("RESOURCE_EXHAUSTED: injected fault: scoped vmem limit "
                    "exceeded while allocating output (m, B) block")


class WireFault(RuntimeError):
    """An armed wire action fired at a ``wire_*`` site.  The shard
    transport (shard/transport.py) catches this and performs the named
    network misbehavior deterministically — a torn frame, a dropped ack,
    a mid-frame partition — instead of dying."""

    def __init__(self, action: str, site: str):
        super().__init__(f"injected wire fault {action!r} at {site!r}")
        self.action = action
        self.site = site


class FaultPlan:
    """Parsed ``$DRAGG_FAULT_INJECT`` for this process."""

    def __init__(self, spec: str = ""):
        # (action, site, nth, once)
        self.site_faults: list[tuple[str, str, int, bool]] = []
        self.probe_seq: list[str] = []   # "down"/"wedge" prefix, consumed FIFO
        self.probe_live = False
        self._hits: dict[str, int] = {}
        self._probe_calls = 0
        for raw in (spec or "").split(","):
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("probe_live") and ":" not in raw:
                self.probe_live = True
                continue
            if raw.startswith(("probe_down", "probe_wedge", "probe_live")):
                kind = raw.split(":", 1)[0].removeprefix("probe_")
                n = int(raw.split(":", 1)[1]) if ":" in raw else 1
                self.probe_seq.extend([kind] * n)
                continue
            action, _, rest = raw.partition("@")
            if action not in _ACTIONS or not rest:
                raise ValueError(f"bad {ENV} spec {raw!r}")
            parts = rest.split(":")
            site = parts[0]
            once = "once" in parts[1:]
            nums = [p for p in parts[1:] if p and p != "once"]
            self.site_faults.append((action, site,
                                     int(nums[0]) if nums else 1, once))

    @property
    def armed(self) -> bool:
        return bool(self.site_faults or self.probe_seq or self.probe_live)

    # ---------------------------------------------------------- site hooks
    def fire(self, site: str) -> None:
        """Called by instrumented code at a named site; executes any armed
        fault whose (site, nth) matches this hit."""
        hit = self._hits[site] = self._hits.get(site, 0) + 1
        for action, s, nth, once in self.site_faults:
            if s != site or nth != hit:
                continue
            if once:
                # Cross-process at-most-once: O_EXCL marker creation is
                # the atomic claim; written BEFORE acting (sigkill never
                # returns).
                marker = os.path.join(
                    os.environ.get("DRAGG_FAULT_STATE", "/tmp"),
                    f"dragg_fault_{action}_{s}_{nth}.fired")
                try:
                    fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.close(fd)
                except FileExistsError:
                    continue
            if action in _WIRE_ACTIONS:
                raise WireFault(action, s)
            if action == "hang":
                # Unbounded from the child's view; the supervisor's stall
                # detector / deadline is what ends it.
                while True:
                    time.sleep(3600)
            if action == "sigkill":
                os.kill(os.getpid(), signal.SIGKILL)
            if action == "vmem_oom":
                raise RuntimeError(VMEM_OOM_MESSAGE)
            if action == "exit":
                sys.exit(17)

    # -------------------------------------------------------- probe faults
    def probe_override(self) -> str | None:
        """None = no injection (real probe runs); else "down" | "wedge" |
        "live" for this liveness check."""
        self._probe_calls += 1
        if self._probe_calls <= len(self.probe_seq):
            return self.probe_seq[self._probe_calls - 1]
        if self.probe_live:
            return "live"
        return None


_PLAN: FaultPlan | None = None


def active_plan() -> FaultPlan:
    """The process-wide plan, parsed once from the environment."""
    global _PLAN
    if _PLAN is None:
        _PLAN = FaultPlan(os.environ.get(ENV, ""))
    return _PLAN


def reset_plan() -> None:
    """Re-read ``$DRAGG_FAULT_INJECT`` on the next hook — for tests that
    change the spec within one process."""
    global _PLAN
    _PLAN = None


def fault_hook(site: str) -> None:
    """Zero-cost no-op unless ``$DRAGG_FAULT_INJECT`` is armed."""
    plan = active_plan()
    if plan.armed:
        plan.fire(site)
