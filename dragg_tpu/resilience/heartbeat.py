"""Child-side progress heartbeat — stdlib only.

The supervisor exports ``$DRAGG_HEARTBEAT_FILE`` into every child it
runs; instrumented child code calls :func:`beat` at real progress
boundaries (a build stage finished, a scan chunk returned).  The
supervisor reads the file's age: no beat within ``stall_s`` means the
child stopped making progress — the round-4 hung-compile signature —
and it is killed BEFORE the abandoned compile can wedge the tunnel for
every other process.

Beats are deliberately EXPLICIT, not a background thread: a hung C call
(the wedge) releases the GIL, so a thread would keep beating through
exactly the hang this machinery exists to catch.
"""

from __future__ import annotations

import json
import os
import time

ENV = "DRAGG_HEARTBEAT_FILE"


def heartbeat_path() -> str | None:
    return os.environ.get(ENV) or None


def beat(progress: dict | None = None) -> None:
    """Record one progress beat (atomic write; no-op when unsupervised).
    ``progress`` is a small JSON-able payload the supervisor surfaces in
    its diagnostics (e.g. ``{"timestep": 120}``)."""
    path = heartbeat_path()
    if path is None:
        return
    # Beats mirror onto the unified telemetry stream (no-op without a
    # bus; never raises) so an after-the-fact wedge forensic can see the
    # child's last progress inline with the supervisor's verdicts.
    try:
        from dragg_tpu import telemetry

        telemetry.emit("heartbeat.beat",
                       **({"progress": progress} if progress else {}))
    except Exception:
        pass
    payload = {"t": time.time(), **({"progress": progress} if progress else {})}  # dragg: disable=DT014, heartbeat protocol IS wall-clock — cross-process stall age
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        # A heartbeat must never kill the workload it instruments.
        try:
            os.remove(tmp)
        except OSError:
            pass


def read(path: str) -> tuple[float | None, dict | None]:
    """(age_seconds, last progress payload) of a heartbeat file, or
    (None, None) when it does not exist / is mid-write garbage."""
    try:
        with open(path) as f:
            payload = json.load(f)
        age = max(0.0, time.time() - float(payload["t"]))  # dragg: disable=DT014, heartbeat protocol IS wall-clock — cross-process stall age
        return age, payload.get("progress")
    except (OSError, ValueError, KeyError):
        return None, None
