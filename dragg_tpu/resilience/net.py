"""Socket deadline helpers — every raw socket op in the framework runs
under an explicit deadline.

The round-4 wedge taught that NOTHING may block unboundedly (CLAUDE.md
gotchas), and the shard wire (shard/transport.py, architecture.md §20)
extends that discipline to the network: dragglint DT005 rejects a
socket created without a deadline in scope, and these helpers are the
sanctioned way to open one — ``settimeout`` is applied at creation so
every later ``connect``/``send``/``recv`` on the object inherits the
per-operation deadline.  Stdlib only; never imports jax.
"""

from __future__ import annotations

import socket


def connect_deadline(host: str, port: int, deadline_s: float) -> socket.socket:
    """A connected TCP socket whose EVERY operation (the connect itself
    included) times out after ``deadline_s`` seconds."""
    sock = socket.create_connection((host, port), timeout=deadline_s)
    sock.settimeout(deadline_s)  # per-op deadline for later send/recv too
    return sock


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Exactly ``n`` bytes from ``sock`` (whose deadline was set at
    creation — :func:`connect_deadline`); ``ConnectionError`` when the
    peer closes early, ``TimeoutError`` when an op exceeds the
    deadline."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(f"peer closed after {len(buf)}/{n} bytes")
        buf.extend(chunk)
    return bytes(buf)


def parse_endpoint(endpoint: str) -> tuple[str, int]:
    """``"host:port"`` -> ``(host, port)``; raises ValueError loudly on
    anything else (a mistyped listen address must not bind a surprise)."""
    host, sep, port = endpoint.rpartition(":")
    if not sep or not host:
        raise ValueError(f"endpoint must be host:port, got {endpoint!r}")
    return host, int(port)
