"""The failure taxonomy and its classifiers — stdlib only, no jax.

Five rounds of outage forensics (CLAUDE.md "Environment gotchas",
docs/perf_notes.md rounds 2-5) produced a small, stable vocabulary of
ways device work dies here.  This module pins that vocabulary and the
rules that map raw observations (child exit status, heartbeat age,
stderr text, probe verdicts) onto it, so every entry point names
failures the same way and chaos tests can assert on the names.

Kinds
-----
``TUNNEL_DOWN``   the axon tunnel is unreachable: the jax-level probe
                  fails fast or hangs WITHOUT the wedge signature, or
                  the default backend resolves to CPU when a TPU was
                  requested (rounds 2-5: the tunnel flaps for hours).
``WEDGED``        the round-4 wedge signature: the jax probe hangs
                  while the local proxy still answers plain HTTP
                  (403 in ~20 ms) and the remote-compile helper port
                  (8093) stops listening.  A hung big compile causes
                  this; every later backend init then hangs too.
``COMPILE_HANG``  a supervised child stopped making progress (stale
                  heartbeat) and had to be killed — the round-4
                  10k-engine-build failure mode, caught before it can
                  wedge the tunnel for other processes.
``VMEM_OOM``      the child died with the scoped-VMEM OOM signature
                  (m=149 Pallas kernels at LANE_BLOCK=512 on the axon
                  AOT compiler — CLAUDE.md).
``CHILD_CRASH``   the child died abnormally for any other reason
                  (signal, nonzero exit) — including a SIGKILL'd or
                  OOM-killed process.
``DEADLINE``      the child was still beating its heartbeat but ran
                  past its hard deadline — slow, not stuck.
"""

from __future__ import annotations

import re

TUNNEL_DOWN = "TUNNEL_DOWN"
WEDGED = "WEDGED"
COMPILE_HANG = "COMPILE_HANG"
VMEM_OOM = "VMEM_OOM"
CHILD_CRASH = "CHILD_CRASH"
DEADLINE = "DEADLINE"

FAILURE_KINDS = (TUNNEL_DOWN, WEDGED, COMPILE_HANG, VMEM_OOM, CHILD_CRASH,
                 DEADLINE)

# The scoped-VMEM OOM as the axon AOT compiler reports it (round-4 logs:
# RESOURCE_EXHAUSTED with a scoped-vmem allocation trace; the full (m, B)
# output appears in the scoped budget — CLAUDE.md).  Matched on stderr
# tails, case-insensitive; fault injection raises the same signature.
_VMEM_OOM_RE = re.compile(
    r"(?i)(scoped\s*vmem|vmem\s*(limit|budget|capacity)|"
    r"resource_exhausted[^\n]*vmem|vmem[^\n]*exceed)")


def looks_like_vmem_oom(text: str | None) -> bool:
    return bool(text) and _VMEM_OOM_RE.search(text) is not None


def classify_child(rc: int | None, timed_out: bool, stalled: bool,
                   stderr_tail: str | None = "") -> str | None:
    """Name the failure of one supervised child, or None on success.

    ``stalled`` — the supervisor killed the child because its heartbeat
    went stale (no progress beat within ``stall_s``); with ``timed_out``
    it distinguishes a hang (COMPILE_HANG — no progress) from honest
    slowness (DEADLINE — still beating when the deadline landed).
    """
    if rc == 0 and not timed_out and not stalled:
        return None
    if stalled:
        return COMPILE_HANG
    if timed_out:
        return DEADLINE
    if looks_like_vmem_oom(stderr_tail):
        return VMEM_OOM
    return CHILD_CRASH


def classify_liveness(probe_ok: bool, backend: str | None, probe_hung: bool,
                      proxy: str | None, compile_helper: str | None
                      ) -> str | None:
    """Name the tunnel state from one probe + wedge-signature read, or
    None when a TPU backend is actually up.

    The wedge signature (round 4, CLAUDE.md): the jax probe HANGS while
    the proxy answers plain HTTP (``http-403``/any ``http-*``) and the
    compile-helper port is not listening.  A hung probe without that
    corroboration is an ordinary outage — the signature upgrades it to
    WEDGED, which operators treat differently (restart the tunnel; do
    not retry compiles into it).
    """
    if probe_ok and backend == "tpu":
        return None
    if probe_hung and proxy is not None and proxy.startswith("http-") \
            and compile_helper == "no-listen":
        return WEDGED
    return TUNNEL_DOWN
