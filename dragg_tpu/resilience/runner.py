"""Retry ladders + the degradation policy — stdlib only, parent never
imports jax.

Two shapes of supervised execution:

* :func:`run_device_job` — one-shot measurement jobs (bench attempts,
  runbook stages): probe-gate the TPU attempt, retry with probe-gated
  exponential backoff, then fall back to a CPU run of the SAME config.
* :func:`supervised_sim_run` — long simulation runs with checkpoints
  (``python -m dragg_tpu run --supervised``): the child writes atomic
  checkpoints at chunk boundaries (dragg_tpu/checkpoint.py); if the
  child dies mid-run (hang, crash, device loss), the run RESUMES on CPU
  from the latest checkpoint instead of restarting from t=0, and the
  platform transition is recorded in the emitted provenance JSON.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import tempfile
import time

from dragg_tpu import telemetry
from dragg_tpu.resilience import liveness
from dragg_tpu.resilience.supervisor import run_supervised
from dragg_tpu.resilience.taxonomy import TUNNEL_DOWN


def cpu_env(base: dict | None = None) -> dict:
    """Child environment pinned to the CPU backend: a wedged tunnel hangs
    ANY backend init because the plugin registers at interpreter start
    via $PALLAS_AXON_POOL_IPS (CLAUDE.md) — so CPU children must both
    request cpu AND drop the plugin registration."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def resilience_config(config: dict | None) -> dict:
    """The ``[resilience]`` config section with defaults applied."""
    from dragg_tpu.config import default_config

    merged = dict(default_config()["resilience"])
    merged.update((config or {}).get("resilience", {}))
    return merged


def run_device_job(build_argv, *, platform: str = "auto",
                   tpu_deadline_s: float, cpu_deadline_s: float,
                   retries: int = 1, backoff_s: float = 30.0,
                   probe_timeout_s: float = 60.0,
                   probe_log: str | None = None,
                   stall_s: float | None = None,
                   base_env: dict | None = None, cwd: str | None = None,
                   log=None, sleep=time.sleep):
    """Probe-gated TPU→CPU ladder for one supervised job.

    ``build_argv(platform, attempt)`` returns the child argv for "tpu" or
    "cpu"; ``attempt`` counts TPU retries (bench shrinks its chunk length
    on retry — long single device executions are the known axon-runtime
    failure mode).  Returns ``(json_result_or_None, attempts)`` where
    each attempt dict carries the platform, the classified failure, and
    the supervisor diagnostics — the artifact trail bench.py publishes.
    """
    attempts: list[dict] = []

    def _tpu_gate() -> bool:
        report = liveness.check_liveness(probe_timeout_s, probe_log)
        if log:
            log(f"probe: {'LIVE' if report.alive else report.kind} "
                f"{report.detail}")
        if not report.alive:
            attempts.append({"platform": "tpu", "skipped": "probe_down",
                             "failure": report.kind or TUNNEL_DOWN,
                             "detail": report.detail})
        return report.alive

    if platform in ("auto", "tpu") and _tpu_gate():
        delays = [0.0] + liveness.backoff_delays(retries, backoff_s)
        for i, delay in enumerate(delays):
            if delay:
                sleep(delay)
                # Probe-gated retry: a timed-out attempt is known to
                # WEDGE the tunnel for subsequent backend inits
                # (round 4) — never retry into a dead tunnel.
                if not _tpu_gate():
                    break
            # Retries run at HALF the deadline: the first attempt already
            # burned the full budget, and callers (the runbook) size
            # their outer stage timeouts assuming probe + attempt +
            # retry/2 + CPU fallback fit inside them.
            res = run_supervised(build_argv("tpu", i),
                                 tpu_deadline_s / (2 if i else 1),
                                 label=f"tpu attempt {i}", env=base_env,
                                 cwd=cwd, stall_s=stall_s, log=log)
            attempts.append({"platform": "tpu", **res.diagnostic()})
            if res.ok and res.json is not None:
                return res.json, attempts

    if platform in ("auto", "cpu"):
        if platform == "auto" and attempts:
            # The ladder is degrading: every TPU avenue (probe gate or
            # executed attempts) failed and the same config re-runs on
            # CPU — record the transition on the unified stream with the
            # classified reason, like supervised_sim_run's provenance.
            telemetry.emit(
                "degrade.transition", from_platform="tpu",
                to_platform="cpu",
                failure=next((a.get("failure") for a in reversed(attempts)
                              if a.get("failure")), None))
        # No stall detector on the CPU attempt: stall-kill exists to stop
        # a hung TPU compile from wedging the tunnel; a big CPU run
        # legitimately computes for longer than any beat cadence (a 10k
        # admm chunk is ~2000 s between beats) and is already bounded by
        # its hard deadline.
        res = run_supervised(build_argv("cpu", 0), cpu_deadline_s,
                             label="cpu attempt", env=cpu_env(base_env),
                             cwd=cwd, stall_s=None, log=log)
        attempts.append({"platform": "cpu", **res.diagnostic()})
        if res.ok and res.json is not None:
            return res.json, attempts
    return None, attempts


# ------------------------------------------------------------ sim runs


def run_dir_for(config: dict, outputs_dir: str) -> str:
    """THIS config's run directory, computed jax-free via the same shared
    name builders the Aggregator uses (aggregator.set_run_dir /
    utils.layout) — so the parent's checkpoint lookups are scoped to the
    run it is supervising, never a neighbor run under the same outputs
    root."""
    from dragg_tpu.config import configured_solver
    from dragg_tpu.data import parse_dt
    from dragg_tpu.utils import date_folder_name, run_dir_name

    sim = config["simulation"]
    return os.path.join(
        outputs_dir,
        date_folder_name(parse_dt(sim["start_datetime"]),
                         parse_dt(sim["end_datetime"])),
        run_dir_name(
            sim["check_type"],
            config["community"]["total_number_homes"],
            config["home"]["hems"]["prediction_horizon"],
            int(config["agg"]["subhourly_steps"]),
            int(config["home"]["hems"]["sub_subhourly_steps"]),
            configured_solver(config),
        ),
        f"version-{sim.get('named_version', 'test')}",
    )


def latest_checkpoint_timestep(outputs_dir: str) -> int | None:
    """Newest checkpointed timestep under ``outputs_dir`` — pass a RUN
    directory (:func:`run_dir_for`), not the whole outputs root, or an
    unrelated run's checkpoint can masquerade as this run's progress.
    Read WITHOUT importing the aggregator (parent stays jax-free): the
    checkpoint layout is ``<case>/checkpoint/LATEST`` → progress.json."""
    best = None
    for pointer in glob.glob(os.path.join(outputs_dir, "**", "checkpoint",
                                          "LATEST"), recursive=True):
        try:
            with open(pointer) as f:
                name = f.read().strip()
            with open(os.path.join(os.path.dirname(pointer), name,
                                   "progress.json")) as f:
                t = int(json.load(f)["timestep"])
        except (OSError, ValueError, KeyError):
            continue
        best = t if best is None else max(best, t)
    return best


def supervised_sim_run(config: dict, outputs_dir: str = "outputs", *,
                       platform: str = "auto", deadline_s: float | None = None,
                       base_env: dict | None = None, cwd: str | None = None,
                       log=None, sleep=time.sleep) -> dict:
    """Run an Aggregator simulation under supervision with checkpointed
    degradation: device loss mid-run resumes the SAME run on CPU from
    the latest atomic checkpoint.

    Returns the provenance dict (also what ``--supervised`` prints as
    one JSON line): per-attempt diagnostics, the ``platform_transitions``
    record, and whether the run completed.  The config's ``[resilience]``
    section supplies deadlines/backoff; ``simulation.resume`` is forced
    true so relaunches continue instead of restarting.
    """
    rcfg = resilience_config(config)
    deadline = float(deadline_s if deadline_s is not None
                     else rcfg["deadline_s"])
    stall = float(rcfg["stall_s"]) or None
    retries = int(rcfg["retries"])
    backoff = float(rcfg["backoff_s"])
    probe_timeout = float(rcfg["probe_timeout_s"])
    degrade = bool(rcfg["degrade_to_cpu"])

    cfg = json.loads(json.dumps(config))  # deep copy, JSON-able by contract
    cfg.setdefault("simulation", {})["resume"] = True
    fd, cfg_path = tempfile.mkstemp(prefix="dragg_simrun_", suffix=".json")
    with os.fdopen(fd, "w") as f:
        json.dump(cfg, f)

    def child_argv() -> list[str]:
        return [sys.executable, "-m", "dragg_tpu.resilience.simchild",
                "--config", cfg_path, "--outputs-dir", outputs_dir]

    attempts: list[dict] = []
    transitions: list[dict] = []
    provenance = {"completed": False, "attempts": attempts,
                  "platform_transitions": transitions,
                  "outputs_dir": outputs_dir}

    def attempt(plat: str, env: dict | None) -> bool:
        # Stall detection only on the TPU attempt (wedge prevention); a
        # CPU chunk may legitimately compute longer than any beat cadence
        # and is bounded by the deadline alone.
        res = run_supervised(child_argv(), deadline, label=f"sim on {plat}",
                             env=env, cwd=cwd,
                             stall_s=stall if plat == "tpu" else None,
                             log=log)
        attempts.append({"platform": plat, **res.diagnostic()})
        return res.ok

    try:
        want_tpu = platform in ("auto", "tpu")
        ran_tpu = False
        if want_tpu:
            report = liveness.wait_for_liveness(
                retries, backoff, probe_timeout, sleep=sleep)
            if log:
                log(f"probe: {'LIVE' if report.alive else report.kind} "
                    f"{report.detail}")
            if report.alive:
                ran_tpu = True
                if attempt("tpu", base_env):
                    provenance.update(completed=True, final_platform="tpu")
                    return provenance
            else:
                attempts.append({"platform": "tpu", "skipped": "probe_down",
                                 "failure": report.kind or TUNNEL_DOWN,
                                 "detail": report.detail})
        if platform == "tpu" and not (degrade and ran_tpu):
            # An explicit TPU-only request either disabled degradation or
            # never acquired a device at all (probe down) — a CPU run
            # here would be a CPU artifact masquerading as the requested
            # TPU measurement.  degrade_to_cpu covers device loss
            # MID-RUN, not a run that never started (docs/config.md).
            return provenance
        # Degradation: resume the SAME run on CPU from the latest atomic
        # checkpoint (the child forces simulation.resume, so a fresh
        # start only happens when no checkpoint was ever written).  The
        # lookup is scoped to THIS config's run directory — a neighbor
        # run's checkpoint under the same outputs root must not
        # masquerade as this run's progress.
        root = (os.path.join(cwd, outputs_dir)
                if cwd and not os.path.isabs(outputs_dir) else outputs_dir)
        resume_t = latest_checkpoint_timestep(run_dir_for(cfg, root))
        if want_tpu:
            transitions.append({
                "from": "tpu",
                "to": "cpu",
                "resumed_from_timestep": resume_t,
                "failure": next((a.get("failure") for a in reversed(attempts)
                                 if a.get("failure")), None),
            })
            telemetry.emit("degrade.transition", from_platform="tpu",
                           to_platform="cpu",
                           resumed_from_timestep=resume_t,
                           failure=transitions[-1]["failure"])
        if attempt("cpu", cpu_env(base_env)):
            provenance.update(completed=True, final_platform="cpu")
        return provenance
    finally:
        try:
            os.remove(cfg_path)
        except OSError:
            pass
