"""Tunnel liveness + wedge classification — stdlib only, probe runs in a
subprocess (the parent NEVER initializes a jax backend).

Promotes the bash-era survival logic into one tested API:

* the jax-level probe with a hard timeout stays the ONLY authoritative
  liveness test (the proxy accepting TCP is not liveness — round 3);
* the round-4 wedge signature (proxy answers plain HTTP 403 in ~20 ms
  while the remote-compile helper port 8093 stops listening, jax probe
  hung) is read as structured fields and classified as ``WEDGED``;
* retries between device attempts use probe-gated exponential backoff:
  sleep, re-probe, and only retry into a tunnel that answers.

Fault injection (``$DRAGG_FAULT_INJECT`` — see :mod:`faults`) can force
any verdict deterministically for chaos tests.
"""

from __future__ import annotations

import socket
import time
import urllib.error
import urllib.request
from typing import NamedTuple

from dragg_tpu import telemetry
from dragg_tpu.resilience import faults
from dragg_tpu.resilience.taxonomy import TUNNEL_DOWN, WEDGED, classify_liveness

PROXY_PORT = 48271          # local axon proxy (CLAUDE.md)
COMPILE_HELPER_PORT = 8093  # remote-compile helper (round-4 OOM logs)


class LivenessReport(NamedTuple):
    alive: bool              # a TPU backend initialized within the timeout
    kind: str | None         # None | TUNNEL_DOWN | WEDGED
    detail: str              # one human line
    backend: str | None      # backend the probe resolved ("tpu"/"cpu"/None)
    proxy: str | None        # wedge-signature field ("http-403"/"hang"/...)
    compile_helper: str | None
    elapsed_s: float


def _peek_http(port: int, timeout_s: float = 1.5) -> str:
    """One-word verdict for a local HTTP endpoint: "http-<code>" /
    "http-ok" / "hang" (accepted, never answered) / "no-listen"."""
    # Direct connection: urlopen honors $http_proxy by default, which in
    # a tunneled environment would peek at the WRONG endpoint.
    opener = urllib.request.build_opener(urllib.request.ProxyHandler({}))
    try:
        opener.open(f"http://127.0.0.1:{port}/", timeout=timeout_s)
        return "http-ok"
    except urllib.error.HTTPError as e:
        return f"http-{e.code}"
    except (TimeoutError, socket.timeout):
        return "hang"
    except urllib.error.URLError as e:
        if isinstance(e.reason, (TimeoutError, socket.timeout)):
            return "hang"
        return "no-listen"
    except Exception:
        return "no-listen"


def read_wedge_signature() -> tuple[str, str]:
    """(proxy, compile_helper) one-word verdicts.  Diagnostic color for a
    HUNG probe; the jax-level probe stays authoritative."""
    return _peek_http(PROXY_PORT), _peek_http(COMPILE_HELPER_PORT)


def check_liveness(timeout_s: float = 60.0,
                   log_path: str | None = None) -> LivenessReport:
    """One classified liveness verdict.  ``log_path`` appends the verdict
    to the committed probe transcript (tools/tpu_probe.py format)."""
    override = faults.active_plan().probe_override()
    if override == "live":
        report = LivenessReport(True, None, "injected: live tpu", "tpu",
                                None, None, 0.0)
    elif override == "down":
        report = LivenessReport(False, TUNNEL_DOWN, "injected: tunnel down",
                                None, None, None, 0.0)
    elif override == "wedge":
        report = LivenessReport(False, WEDGED,
                                "injected: wedged (proxy http-403, compile "
                                "helper gone, probe hung)",
                                None, "http-403", "no-listen", 0.0)
    else:
        from dragg_tpu.utils.probe import probe_backend

        try:
            r = probe_backend(timeout_s)
        except Exception as e:  # belt-and-braces on top of the probe's
            # own guard: liveness feeds one-JSON-line harness contracts.
            r = {"ok": False, "timeout": False, "elapsed_s": 0.0,
                 "error": f"probe plumbing failed: {e!r}"}
        backend = r.get("backend")
        hung = bool(r.get("timeout"))
        proxy = helper = None
        if hung:
            proxy, helper = read_wedge_signature()
        kind = classify_liveness(r.get("ok", False), backend, hung,
                                 proxy, helper)
        if kind is None:
            detail = f"tpu {r.get('kind', '')} ({r['elapsed_s']}s)".strip()
        elif kind == WEDGED:
            detail = (f"wedged: probe hung >{timeout_s:.0f}s, proxy {proxy}, "
                      f"compile helper {helper}")
        elif r.get("ok"):
            detail = f"backend resolved to {backend}, not tpu ({r['elapsed_s']}s)"
        else:
            sig = f" [proxy:{proxy} compile:{helper}]" if hung else ""
            detail = (f"{r.get('error', '')[:160]} "
                      f"({r['elapsed_s']}s){sig}").replace("\n", " ").strip()
        report = LivenessReport(kind is None, kind, detail, backend,
                                proxy, helper, float(r.get("elapsed_s", 0.0)))
    if log_path:
        try:
            from dragg_tpu.utils.probe import append_probe_log

            append_probe_log(log_path, report.alive, report.detail)
        except OSError:
            pass
    # Every verdict lands on the unified stream too (no-op when no bus
    # is open) — the watcher (tools/tpu_probe.py --watch), bench's
    # ladder, doctor --classify, and the runbook all share this one
    # forensic format instead of per-tool transcripts.
    telemetry.emit("probe.verdict", alive=report.alive, kind=report.kind,
                   detail=report.detail, backend=report.backend,
                   proxy=report.proxy, compile_helper=report.compile_helper,
                   elapsed_s=report.elapsed_s)
    telemetry.observe("probe.elapsed_s", report.elapsed_s)
    if report.kind is not None:
        telemetry.emit("failure." + report.kind,  # dragg: disable=DT007, kind from taxonomy.FAILURE_KINDS, each registered literally
                       source="probe", detail=report.detail)
    return report


def backoff_delays(retries: int, base_s: float = 30.0,
                   cap_s: float = 600.0) -> list[float]:
    """Exponential backoff schedule (base, 2*base, 4*base, ... capped)."""
    return [min(cap_s, base_s * (2 ** i)) for i in range(max(0, retries))]


def wait_for_liveness(retries: int, base_s: float = 30.0,
                      probe_timeout_s: float = 60.0,
                      log_path: str | None = None,
                      sleep=time.sleep) -> LivenessReport:
    """Probe-gated backoff: re-probe after each delay, return the first
    LIVE report (or the last failed one).  ``sleep`` is injectable so
    tests run the schedule without wall-clock cost."""
    report = check_liveness(probe_timeout_s, log_path)
    for delay in backoff_delays(retries, base_s):
        if report.alive:
            return report
        sleep(delay)
        report = check_liveness(probe_timeout_s, log_path)
    return report
