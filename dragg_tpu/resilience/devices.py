"""Sanctioned in-process device enumeration (round 12, ISSUE 8
satellite).

``jax.devices()`` initializes the backend, and on this repo's hardware a
wedged axon tunnel makes that initialization HANG — which is why
``tools/lint.py`` rejects bare device calls in entry-point scope
(CLAUDE.md gotchas).  Code that genuinely needs the device count from
inside a process that is *already committed* to touching the backend
(the aggregator's sharding auto-resolution, the engine build — both of
which commit device arrays moments later, and both of which run inside
supervised children on every shipped path: ``run --supervised``, bench,
validate_scale, the serve worker pool) routes through
:func:`device_count` instead, so the discipline has exactly one
documented escape hatch and the lint scope can keep widening.

Import rule: this module imports jax lazily inside the function — the
jax-free resilience parents can import the package without pulling in a
backend.
"""

from __future__ import annotations


def default_platform() -> str:
    """The initialized backend's platform name ("cpu" / "tpu" / …), via
    the same sanctioned in-process site as :func:`device_count` — same
    contract: callers are already device-committed."""
    import jax

    return jax.default_backend()  # dragg: disable=DT004, the sanctioned helper — see module docstring


def device_count() -> int:
    """Number of visible devices, via the one sanctioned in-process
    backend-init site.

    Callers must already be on a device-committed path (a supervised
    child, or a process about to build an engine): this call can hang on
    a wedged tunnel exactly like the engine build that follows it would,
    so it adds no NEW hang risk there — but it must never appear in a
    jax-free supervising parent (use ``liveness.check_liveness`` to probe
    from those).
    """
    import jax

    return len(jax.devices())  # dragg: disable=DT004, the sanctioned helper — see module docstring


def device_list() -> list:
    """The visible device objects themselves (mesh construction needs
    the list, not just the count) — same sanctioned site, same
    device-committed caller contract as :func:`device_count`."""
    import jax

    return list(jax.devices())  # dragg: disable=DT004, the sanctioned helper — see module docstring
