"""Subprocess supervisor — stdlib only; the parent NEVER imports jax.

Every device workload this repo runs can hang (a wedged tunnel hangs
backend init at interpreter start), stall (the 10k engine compile hung
between build and first step for 900 s, round 4), OOM, or disappear.
The supervisor runs the workload in a CHILD process with:

* a hard **deadline** — on expiry the child's whole process group gets
  SIGTERM, then SIGKILL after a grace period, so a hung compile dies in
  the child instead of wedging the parent;
* a **heartbeat file** (``$DRAGG_HEARTBEAT_FILE``, written by
  :mod:`heartbeat` at the child's real progress boundaries) — with
  ``stall_s`` set, a child that stops beating is killed EARLY, before
  the abandoned compile can wedge the tunnel for every later process
  (the round-4 failure chain this layer exists to break);
* **stdout/stderr capture** to temp files (no pipe-buffer deadlock on
  chatty children), returned as bounded tails;
* a classified verdict from :mod:`taxonomy`.

The parent-side guarantee — no jax backend init in this process — is
what keeps the supervisor itself un-wedgeable; :func:`assert_parent_has_no_jax`
enforces it and a chaos test proves it end-to-end.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import NamedTuple

from dragg_tpu import telemetry
from dragg_tpu.resilience import heartbeat as hb
from dragg_tpu.resilience.taxonomy import classify_child


class SupervisedResult(NamedTuple):
    ok: bool
    rc: int | None           # child return code (negative = killed by signal)
    timed_out: bool          # hard deadline expired
    stalled: bool            # heartbeat went stale (killed early)
    failure: str | None      # taxonomy kind, None on success
    elapsed_s: float
    stdout_tail: str
    stderr_tail: str
    heartbeat_age_s: float | None  # age at verdict time (None = no file)
    progress: dict | None    # last progress payload the child beat
    json: dict | None        # last JSON-parseable stdout line, if any

    def diagnostic(self) -> dict:
        """Compact attempt record for artifacts (bench ``attempts`` etc.)."""
        d = {"ok": self.ok, "rc": self.rc, "elapsed_s": round(self.elapsed_s, 1)}
        if self.failure:
            d["failure"] = self.failure
        if self.timed_out:
            d["timed_out"] = True
        if self.stalled:
            d["stalled"] = True
        if self.heartbeat_age_s is not None:
            d["heartbeat_age_s"] = round(self.heartbeat_age_s, 1)
        if self.progress:
            d["progress"] = self.progress
        if not self.ok and self.stderr_tail:
            d["stderr_tail"] = self.stderr_tail[-2000:]
        return d


def assert_parent_has_no_jax() -> None:
    """The supervising process must never have initialized jax: a wedged
    tunnel hangs ANY backend init (the plugin registers at interpreter
    start), and the supervisor is the one component that must stay alive
    through that.  Raises RuntimeError if jax is already imported."""
    if "jax" in sys.modules:
        raise RuntimeError(
            "supervisor parent has imported jax — a wedged tunnel could hang "
            "this process; run device work only in supervised children")


def _read_tail(path: str, limit: int) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - limit))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""


def _last_json_line(path: str) -> dict | None:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 1_000_000))
            lines = f.read().decode("utf-8", "replace").splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _kill_group(proc: subprocess.Popen, grace_s: float) -> None:
    """SIGTERM the child's process group, escalate to SIGKILL.  The group
    matters: device children spawn their own subprocesses (probes, nested
    stages) and an orphaned grandchild holding a hung compile is exactly
    the wedge this layer prevents."""
    def _signal_group(sig):
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    _signal_group(signal.SIGTERM)
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    if proc.poll() is None:
        _signal_group(signal.SIGKILL)
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            pass


# Public handles for parents that supervise LONG-LIVED children with
# their own poll loops (the serving daemon's worker pool — serve/pool.py)
# instead of the blocking run_supervised shape: same group-kill escalation
# and bounded-tail reads, one implementation.
kill_group = _kill_group
read_tail = _read_tail


def run_supervised(argv: list[str], deadline_s: float, *,
                   label: str = "", env: dict | None = None,
                   cwd: str | None = None, stall_s: float | None = None,
                   poll_s: float = 0.25, grace_s: float = 5.0,
                   tail_bytes: int = 4000,
                   stdout_path: str | None = None,
                   stderr_path: str | None = None,
                   telemetry_dir: str | None = None,
                   log=None) -> SupervisedResult:
    """Run ``argv`` in a supervised child process.

    ``deadline_s`` — hard wall-clock limit; ``stall_s`` — kill earlier if
    the child's heartbeat file goes older than this (None disables; the
    file is seeded at launch, so a child that never beats is stalled
    ``stall_s`` after start).  ``env`` replaces the child environment
    when given (otherwise inherits); ``$DRAGG_HEARTBEAT_FILE`` is always
    exported.  ``log`` is an optional ``callable(str)`` for progress
    lines (the runbook's transcript).  ``stdout_path``/``stderr_path``
    persist the FULL captures as artifacts (the runbook's per-stage
    .json/.log files) instead of supervisor-private temp files.

    Entry-point parents (bench.py, the runbook, ``run --supervised``)
    call :func:`assert_parent_has_no_jax` before supervising — not
    enforced here, because test processes legitimately drive the
    supervisor with jax already imported for OTHER purposes.
    """
    child_env = dict(os.environ if env is None else env)
    hb_fd, hb_path = tempfile.mkstemp(prefix="dragg_hb_")
    os.close(hb_fd)
    child_env[hb.ENV] = hb_path
    # When this (jax-free) parent has an on-disk telemetry stream, the
    # child joins it: its events (heartbeats, engine chunks, bench
    # results) land in the SAME events.jsonl as the supervisor's own
    # lifecycle records — one correlated forensic file per run.
    # ``telemetry_dir`` overrides the destination for parents running
    # CONCURRENT children (the shard coordinator gives each worker
    # ``<stream>/shard<k>`` so N shards never interleave into one bus
    # file; telemetry.tail_events_dir merges the sub-streams back).
    if telemetry_dir:
        child_env[telemetry.ENV_DIR] = telemetry_dir
    elif telemetry.run_dir():
        child_env.setdefault(telemetry.ENV_DIR, telemetry.run_dir())
    # Same contract for the causal trace context (ISSUE 20): a tracing
    # parent exports $DRAGG_TRACE_CTX so the child's records land in
    # the same trace, its process root span parented on ours.  Nothing
    # is exported when tracing is off.
    trace_ctx = telemetry.trace.env_value()
    if trace_ctx:
        child_env.setdefault(telemetry.trace.ENV_CTX, trace_ctx)
    flush_s = os.environ.get(telemetry.ENV_FLUSH)
    if flush_s:
        child_env.setdefault(telemetry.ENV_FLUSH, flush_s)
    out_f = (open(stdout_path, "wb") if stdout_path else
             tempfile.NamedTemporaryFile(prefix="dragg_sup_out_", delete=False))
    err_f = (open(stderr_path, "wb") if stderr_path else
             tempfile.NamedTemporaryFile(prefix="dragg_sup_err_", delete=False))
    t0 = time.monotonic()
    # Seed the heartbeat at launch so stall time is measured from start.
    with open(hb_path, "w") as f:
        json.dump({"t": time.time()}, f)  # dragg: disable=DT014, heartbeat seed file — the stall-kill protocol is wall-clock
    timed_out = stalled = False
    try:
        proc = subprocess.Popen(argv, env=child_env, cwd=cwd,
                                stdout=out_f, stderr=err_f,
                                start_new_session=True)
        if log:
            log(f">>> {label or argv[0]} pid={proc.pid} "
                f"deadline={deadline_s:.0f}s"
                + (f" stall={stall_s:.0f}s" if stall_s else ""))
        telemetry.emit("supervisor.launch", label=label or argv[0],
                       pid=proc.pid, deadline_s=deadline_s,
                       stall_s=stall_s)
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            elapsed = time.monotonic() - t0
            if elapsed >= deadline_s:
                timed_out = True
                # The deadline verdict (COMPILE_HANG vs DEADLINE) hinges
                # on whether the child was still making progress when the
                # limit landed.
                age, _ = hb.read(hb_path)
                stalled = (stall_s is not None and age is not None
                           and age > stall_s)
                _kill_group(proc, grace_s)
                break
            if stall_s is not None:
                age, _ = hb.read(hb_path)
                if age is not None and age > stall_s:
                    stalled = True
                    _kill_group(proc, grace_s)
                    break
            time.sleep(poll_s)
        rc = proc.poll()
    finally:
        out_f.close()
        err_f.close()
    elapsed = time.monotonic() - t0
    age, progress = hb.read(hb_path)
    stderr_tail = _read_tail(err_f.name, tail_bytes)
    failure = classify_child(rc, timed_out, stalled, stderr_tail)
    result = SupervisedResult(
        ok=failure is None,
        rc=rc, timed_out=timed_out, stalled=stalled, failure=failure,
        elapsed_s=elapsed,
        stdout_tail=_read_tail(out_f.name, tail_bytes),
        stderr_tail=stderr_tail,
        heartbeat_age_s=age, progress=progress,
        json=_last_json_line(out_f.name),
    )
    keep = {stdout_path, stderr_path}
    for p in (hb_path, out_f.name, err_f.name):
        if p in keep:
            continue
        try:
            os.remove(p)
        except OSError:
            pass
    telemetry.observe("supervisor.child_s", elapsed)
    # The child's LAST heartbeat payload rides the verdict events: an
    # instrumented child beats a stage name before each risky phase
    # (telemetry/compile_obs stages, bench's build stages), so a
    # stall-killed compile is attributed to "compile:compile at pattern X"
    # in the stream, not just COMPILE_HANG (round-9 observatory).
    telemetry.emit("supervisor.exit", label=label or argv[0], rc=rc,
                   ok=result.ok, failure=failure, timed_out=timed_out,
                   stalled=stalled, elapsed_s=round(elapsed, 3),
                   progress=progress)
    if failure is not None:
        # The taxonomy kind IS the event type — wedge forensics grep one
        # stream for "failure." instead of three ad-hoc transcripts.
        telemetry.emit("failure." + failure,  # dragg: disable=DT007, kind from taxonomy.FAILURE_KINDS, each registered literally
                       source="supervisor", label=label or argv[0],
                       rc=rc, elapsed_s=round(elapsed, 3),
                       progress=progress)
    if log:
        log(f"<<< {label or argv[0]} rc={rc} "
            f"{'ok' if result.ok else result.failure} "
            f"({elapsed:.1f}s)")
    return result
