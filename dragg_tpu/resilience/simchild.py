"""Supervised simulation child: ``python -m dragg_tpu.resilience.simchild``.

The ONLY process in a supervised sim run that initializes a jax backend.
Loads the JSON config the parent staged (``runner.supervised_sim_run``),
runs the Aggregator (which beats the heartbeat and writes atomic
checkpoints at chunk boundaries), and exits 0 on completion.  A relaunch
after a mid-run death resumes from the newest checkpoint because the
parent forces ``simulation.resume`` true.
"""

from __future__ import annotations

import argparse
import json


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True, help="JSON config path")
    ap.add_argument("--outputs-dir", default="outputs")
    args = ap.parse_args()
    with open(args.config) as f:
        config = json.load(f)

    from dragg_tpu.aggregator import Aggregator
    from dragg_tpu.resilience.heartbeat import beat

    beat({"stage": "aggregator_init"})
    agg = Aggregator(config=config, outputs_dir=args.outputs_dir)
    agg.run()
    beat({"stage": "done", "timestep": agg.timestep})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
