"""CLI entry point — the reference's L0 (dragg/main.py:1-19) plus the
post-processing step it ships commented out.

    python -m dragg_tpu run        # Aggregator().run() (dragg/main.py:4-9)
    python -m dragg_tpu reformat   # Reformat().main()  (dragg/main.py:11-17)
    python -m dragg_tpu bench      # the repo-root bench harness
    python -m dragg_tpu dashboard  # results webapp (dragg/plotter.py's TODO)
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dragg_tpu",
                                description="TPU-native community energy MPC simulator")
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="run the simulation cases enabled in the config")
    run.add_argument("--config", default=None, help="TOML config path (default: $DATA_DIR/$CONFIG_FILE)")
    run.add_argument("--data-dir", default=None, help="directory with nsrdb.csv / waterdraw profiles")
    run.add_argument("--outputs-dir", default="outputs")
    run.add_argument("--supervised", action="store_true",
                     help="run the simulation in a supervised child process "
                          "(hard deadline, heartbeat-stall detection, "
                          "checkpointed TPU→CPU degradation on device loss; "
                          "prints one provenance JSON line — "
                          "dragg_tpu/resilience)")
    run.add_argument("--platform", choices=["auto", "tpu", "cpu"],
                     default="auto", help="supervised mode only: which "
                          "backends the ladder may try")
    run.add_argument("--deadline", type=float, default=None,
                     help="supervised mode only: per-attempt hard deadline "
                          "seconds (default: resilience.deadline_s)")

    ref = sub.add_parser("reformat", help="discover finished runs and build comparison figures")
    ref.add_argument("--config", default=None)
    ref.add_argument("--outputs-dir", default=None, help="default: $OUTPUT_DIR or ./outputs")
    ref.add_argument("--home", default=None, help="sample home name for per-home plots")
    ref.add_argument("--no-save", action="store_true", help="don't write PNGs")

    sweep = sub.add_parser(
        "sweep",
        help="run a prediction-horizon sweep and compare the runs "
             "(the reference paper's horizon study and main.py's "
             "commented-out parametric workflow)")
    sweep.add_argument("--horizons", default="2,4,8",
                       help="comma-separated prediction horizons (hours)")
    sweep.add_argument("--config", default=None)
    sweep.add_argument("--data-dir", default=None)
    sweep.add_argument("--outputs-dir", default="outputs")
    sweep.add_argument("--no-figures", action="store_true")

    doc = sub.add_parser("doctor", help="diagnose the environment (backend, "
                                        "native runtime, data files, outputs)")
    doc.add_argument("--outputs-dir", default="outputs")
    doc.add_argument("--backend-timeout", type=float, default=60.0)
    doc.add_argument("--classify", action="store_true",
                     help="one classified liveness verdict as a JSON line "
                          "(names the failure: TUNNEL_DOWN / WEDGED) "
                          "instead of the full check table")
    doc.add_argument("--compile-check", action="store_true",
                     help="additionally run a tiny STAGED engine compile "
                          "(lower/compile/first-execute stage timings + "
                          "persistent-cache verdict) in a hard-timeouted "
                          "subprocess — the observatory's compile-path "
                          "self-test")
    doc.add_argument("--shard-check", action="store_true",
                     help="additionally self-test the shard coordinator's "
                          "crash-safety substrate (dragg_tpu/shard): "
                          "journal torn-tail truncation at every byte "
                          "boundary + duplicate-epoch refusal, mirroring "
                          "the serve_journal check")
    doc.add_argument("--telemetry", action="store_true",
                     help="additionally self-test the trace plane "
                          "(dragg_tpu/telemetry): a traced run in a "
                          "subprocess must assemble to one complete "
                          "causal tree, live-flush metrics.json "
                          "mid-run, and fold a rollup with Prometheus "
                          "exposition")

    srv = sub.add_parser(
        "serve",
        help="run the fault-tolerant MPC serving daemon (crash-safe "
             "request journal, supervised warm-engine worker pool, "
             "probe-gated admission with TPU→CPU degradation — "
             "dragg_tpu/serve, docs/serving.md)")
    srv.add_argument("--config", default=None, help="TOML config path")
    srv.add_argument("--serve-dir", default=os.path.join("outputs", "serve"),
                     help="journal + spool + telemetry directory (the "
                          "daemon's durable state; survives restarts)")
    srv.add_argument("--host", default=None,
                     help="bind host (default: serve.host)")
    srv.add_argument("--port", type=int, default=None,
                     help="bind port (default: serve.port; 0 = ephemeral)")
    srv.add_argument("--platform", choices=["auto", "tpu", "cpu"],
                     default="auto",
                     help="auto probes and degrades to CPU on a dead "
                          "tunnel; tpu is strict (429s while the probe "
                          "says no, unless serve.degrade_to_cpu); cpu "
                          "skips probing entirely")
    srv.add_argument("--stub", action="store_true", help=argparse.SUPPRESS)

    sub.add_parser("bench", help="run the benchmark harness (prints one JSON line)")

    dash = sub.add_parser("dashboard", help="serve the results dashboard over HTTP")
    dash.add_argument("--config", default=None)
    dash.add_argument("--outputs-dir", default=None, help="default: $OUTPUT_DIR or ./outputs")
    dash.add_argument("--port", type=int, default=8050)
    dash.add_argument("--host", default="127.0.0.1")
    return p


def run_sweep(args) -> int:
    """Prediction-horizon sweep: one full run per horizon, then the
    parametric comparison over all of them.

    Reproduces the reference paper's horizon study (horizons 1-16 h,
    solve-time-vs-cost tradeoff — BASELINE.md) through the workflow the
    reference ships commented out in main.py:9-19 (parameter dicts fed to
    Reformat).  Prints a per-horizon summary table and, unless
    --no-figures, saves the parametric comparison figures.
    """
    import copy

    from dragg_tpu.aggregator import Aggregator
    from dragg_tpu.config import load_config
    from dragg_tpu.reformat import Reformat

    try:
        horizons = sorted({int(h) for h in str(args.horizons).split(",") if h.strip()})
    except ValueError:
        print(f"sweep: --horizons must be comma-separated integers, got "
              f"{args.horizons!r}", file=sys.stderr)
        return 1
    if not horizons or min(horizons) < 1:
        print("sweep: need at least one horizon >= 1", file=sys.stderr)
        return 1
    base_cfg = load_config(args.config)
    for h in horizons:
        cfg = copy.deepcopy(base_cfg)
        cfg["home"]["hems"]["prediction_horizon"] = h
        Aggregator(cfg, data_dir=args.data_dir,
                   outputs_dir=args.outputs_dir).run()

    # Reformat discovery permutes over value SETS — extend the horizon axis
    # to cover the sweep and re-discover (dragg/reformat.py:86-99 pattern).
    r = Reformat(config=base_cfg, outputs_dir=args.outputs_dir)
    r.mpc_params["mpc_prediction_horizons"] = set(horizons)
    r.mpc_folders = r.set_mpc_folders()
    r.files = r.set_files()

    rows = []
    for file in r.files:
        s = r._load(file["results"])["Summary"]  # warms the figure cache too
        rows.append((s.get("horizon"), s.get("solve_time"),
                     s.get("p_max_aggregate"), file["case"]))
    print(f"{'horizon':>8} {'solve_time_s':>13} {'p_max_kW':>10}  case")
    for h, st, pmax, case in sorted(rows, key=lambda x: (x[0] or 0)):
        print(f"{h!s:>8} {st:13.2f} {pmax:10.2f}  {case}")

    if not args.no_figures:
        r.save_images([("parametric", r.plot_parametric()),
                       ("typical_day", r.plot_typ_day()),
                       ("max_and_12hravg", r.plot_max_and_12hravg())])
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # CLI processes filter XLA:CPU's spurious warm-cache AOT mismatch
    # ERROR lines (tuning prefs only; real ISA mismatches pass through —
    # see utils/stderr_filter.py).  Never installed under pytest.
    from dragg_tpu.utils.stderr_filter import install_aot_mismatch_filter

    install_aot_mismatch_filter()
    if args.cmd == "run" and args.supervised:
        # Supervised mode: THIS process stays jax-free (a wedged tunnel
        # hangs any backend init — the supervisor must outlive it); all
        # device work happens in supervised children with deadlines,
        # heartbeat-stall detection, and checkpointed CPU degradation.
        import json

        from dragg_tpu.config import load_config
        from dragg_tpu.resilience.runner import supervised_sim_run
        from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax

        assert_parent_has_no_jax()
        config = load_config(args.config)
        if args.data_dir is not None:
            os.environ["DATA_DIR"] = args.data_dir
        # One telemetry stream for the whole supervised run: the parent
        # opens the bus ON THE RUN DIRECTORY (computed jax-free), so its
        # probe/supervisor/degradation events interleave with the
        # child's run.start/chunk.done records in one events.jsonl.  The
        # child owns the final metrics.json (the parent never snapshots
        # — it would overwrite the run's metrics with supervisor-only
        # numbers).
        from dragg_tpu import telemetry
        from dragg_tpu.resilience.runner import run_dir_for

        if config.get("telemetry", {}).get("enabled", True):
            telemetry.init_run(
                config.get("telemetry", {}).get("dir")
                or os.environ.get(telemetry.ENV_DIR)
                or run_dir_for(config, args.outputs_dir))
        provenance = supervised_sim_run(
            config, args.outputs_dir, platform=args.platform,
            deadline_s=args.deadline,
            log=lambda m: print(f"[supervised] {m}", file=sys.stderr,
                                flush=True))
        print(json.dumps(provenance))
        return 0 if provenance["completed"] else 1
    if args.cmd == "run":
        # Multi-host pod slices: every worker runs this same command and the
        # coordinator handshake merges them into ONE JAX program whose
        # jax.devices() spans all hosts (deploy/launch_tpu_pod.sh sets the
        # env var).  No-op on a single host.
        if os.environ.get("DRAGG_DISTRIBUTED") == "1":
            import jax

            # CPU backends need an explicit cross-process collectives
            # implementation (TPU rides ICI natively).  This makes the
            # multi-host code path testable as N local processes —
            # tests/test_distributed.py runs exactly this entry.
            if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            # On TPU pods initialize() auto-detects the topology from the
            # runtime; for N-local-process testing (and any cluster without
            # auto-detection) the coordinator is passed explicitly.
            kw = {}
            if os.environ.get("DRAGG_COORDINATOR_ADDRESS"):
                missing = [v for v in ("DRAGG_NUM_PROCESSES", "DRAGG_PROCESS_ID")
                           if not os.environ.get(v)]
                if missing:
                    print("DRAGG_COORDINATOR_ADDRESS is set but "
                          f"{' and '.join(missing)} "
                          "is missing; all three are required for explicit "
                          "multi-process init.", file=sys.stderr)
                    return 2
                kw = dict(
                    coordinator_address=os.environ["DRAGG_COORDINATOR_ADDRESS"],
                    num_processes=int(os.environ["DRAGG_NUM_PROCESSES"]),
                    process_id=int(os.environ["DRAGG_PROCESS_ID"]),
                )
            jax.distributed.initialize(**kw)

        from dragg_tpu.aggregator import Aggregator

        Aggregator(config=args.config, data_dir=args.data_dir,
                   outputs_dir=args.outputs_dir).run()
        return 0
    if args.cmd == "reformat":
        from dragg_tpu.reformat import Reformat

        r = Reformat(config=args.config, outputs_dir=args.outputs_dir)
        if args.home:
            r.sample_home = args.home
        r.main(save=not args.no_save)
        return 0
    if args.cmd == "serve":
        # Serving parent stays jax-free for its whole lifetime: all
        # device work runs in the supervised worker pool's children
        # (dragg_tpu/serve/pool.py), so a wedged tunnel can never hang
        # the daemon that must classify and survive it.
        from dragg_tpu.config import load_config
        from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax
        from dragg_tpu.serve import run_serve

        assert_parent_has_no_jax()
        return run_serve(
            load_config(args.config), args.serve_dir,
            platform=args.platform, host=args.host, port=args.port,
            stub=args.stub,
            log=lambda m: print(f"[serve] {m}", file=sys.stderr, flush=True))
    if args.cmd == "doctor":
        if args.classify:
            from dragg_tpu.doctor import run_classify

            return run_classify(backend_timeout=args.backend_timeout)
        from dragg_tpu.doctor import run_doctor

        return run_doctor(outputs_dir=args.outputs_dir,
                          backend_timeout=args.backend_timeout,
                          compile_check=args.compile_check,
                          shard_check=args.shard_check,
                          telemetry_check=args.telemetry)
    if args.cmd == "sweep":
        return run_sweep(args)
    if args.cmd == "dashboard":
        from dragg_tpu.dashboard import serve

        serve(config=args.config, outputs_dir=args.outputs_dir,
              port=args.port, host=args.host)
        return 0
    if args.cmd == "bench":
        import runpy

        # bench.py lives at the repo root next to the package, not inside it;
        # resolve it by path so the command works from any CWD.
        import dragg_tpu

        bench = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(dragg_tpu.__file__))), "bench.py")
        if not os.path.isfile(bench):
            print(f"bench.py not found at {bench}", file=sys.stderr)
            return 1
        runpy.run_path(bench, run_name="__main__")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
