"""CLI entry point — the reference's L0 (dragg/main.py:1-19) plus the
post-processing step it ships commented out.

    python -m dragg_tpu run        # Aggregator().run() (dragg/main.py:4-9)
    python -m dragg_tpu reformat   # Reformat().main()  (dragg/main.py:11-17)
    python -m dragg_tpu bench      # the repo-root bench harness
    python -m dragg_tpu dashboard  # results webapp (dragg/plotter.py's TODO)
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dragg_tpu",
                                description="TPU-native community energy MPC simulator")
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="run the simulation cases enabled in the config")
    run.add_argument("--config", default=None, help="TOML config path (default: $DATA_DIR/$CONFIG_FILE)")
    run.add_argument("--data-dir", default=None, help="directory with nsrdb.csv / waterdraw profiles")
    run.add_argument("--outputs-dir", default="outputs")

    ref = sub.add_parser("reformat", help="discover finished runs and build comparison figures")
    ref.add_argument("--config", default=None)
    ref.add_argument("--outputs-dir", default=None, help="default: $OUTPUT_DIR or ./outputs")
    ref.add_argument("--home", default=None, help="sample home name for per-home plots")
    ref.add_argument("--no-save", action="store_true", help="don't write PNGs")

    sub.add_parser("bench", help="run the benchmark harness (prints one JSON line)")

    dash = sub.add_parser("dashboard", help="serve the results dashboard over HTTP")
    dash.add_argument("--config", default=None)
    dash.add_argument("--outputs-dir", default=None, help="default: $OUTPUT_DIR or ./outputs")
    dash.add_argument("--port", type=int, default=8050)
    dash.add_argument("--host", default="127.0.0.1")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "run":
        # Multi-host pod slices: every worker runs this same command and the
        # coordinator handshake merges them into ONE JAX program whose
        # jax.devices() spans all hosts (deploy/launch_tpu_pod.sh sets the
        # env var).  No-op on a single host.
        if os.environ.get("DRAGG_DISTRIBUTED") == "1":
            import jax

            jax.distributed.initialize()

        from dragg_tpu.aggregator import Aggregator

        Aggregator(config=args.config, data_dir=args.data_dir,
                   outputs_dir=args.outputs_dir).run()
        return 0
    if args.cmd == "reformat":
        from dragg_tpu.reformat import Reformat

        r = Reformat(config=args.config, outputs_dir=args.outputs_dir)
        if args.home:
            r.sample_home = args.home
        r.main(save=not args.no_save)
        return 0
    if args.cmd == "dashboard":
        from dragg_tpu.dashboard import serve

        serve(config=args.config, outputs_dir=args.outputs_dir,
              port=args.port, host=args.host)
        return 0
    if args.cmd == "bench":
        import runpy

        # bench.py lives at the repo root next to the package, not inside it;
        # resolve it by path so the command works from any CWD.
        import dragg_tpu

        bench = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(dragg_tpu.__file__))), "bench.py")
        if not os.path.isfile(bench):
            print(f"bench.py not found at {bench}", file=sys.stderr)
            return 1
        runpy.run_path(bench, run_name="__main__")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
