"""Hand-built polynomial × Fourier feature bases for the linear RL agent.

Same feature construction as the reference (dragg/agent.py:88-111): quadratic
bases in each state scalar, outer products flattened with the constant term
dropped, crossed with a Fourier time-of-day basis.  Dimensions: state basis
23, state-action basis 71.  Pure ``jnp`` so they trace, ``vmap`` and ``grad``.
"""

from __future__ import annotations

import jax.numpy as jnp

STATE_DIM = 23
STATE_ACTION_DIM = 71


def _quad(x):
    """(1, x, x²) quadratic basis in a scalar."""
    return jnp.stack([jnp.ones_like(x), x, x * x])


def _time_fourier(time_of_day):
    """(1, sin 2πt, cos 2πt) Fourier basis (dragg/agent.py:91)."""
    ang = 2.0 * jnp.pi * time_of_day
    return jnp.stack([jnp.ones_like(time_of_day), jnp.sin(ang), jnp.cos(ang)])


def state_basis(fcst_error, forecast_trend, time_of_day):
    """φ(s) ∈ R^23 (dragg/agent.py:88-96)."""
    fe = _quad(fcst_error)
    ft = _quad(forecast_trend)
    tb = _time_fourier(time_of_day)
    phi = jnp.outer(fe, ft).flatten()[1:]
    return jnp.outer(phi, tb).flatten()[1:]


def state_action_basis(fcst_error, forecast_trend, time_of_day, delta_action, action):
    """φ(s, a) ∈ R^71 (dragg/agent.py:98-111)."""
    ab = _quad(action)
    dab = _quad(delta_action)
    tb = _time_fourier(time_of_day)
    fe = _quad(fcst_error)
    ft = _quad(forecast_trend)
    v = jnp.outer(ft, ab).flatten()[1:]
    w = jnp.outer(fe, ab).flatten()[1:]
    z = jnp.outer(fe, dab).flatten()[1:]
    phi = jnp.concatenate([v, w, z])
    return jnp.outer(phi, tb).flatten()[1:]
